"""Own-compositor mode: bring up a headless Wayland compositor when no
external one is offered.

The reference can attach to an existing compositor OR start its own
headless session (reference stream_server.py:420-447
``ensure_wayland_display``). This is the TPU framework's equivalent
supervisor: prefer the configured external socket when it is alive,
otherwise spawn the first available wlroots-style compositor with the
headless backend, wait for its socket, and keep it running (restart with
backoff) until torn down. The capture/input plane
(:mod:`selkies_tpu.wayland.client`) then attaches by screencopy exactly
as it does to an external compositor — the two modes differ only in who
owns the process.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
import time
from typing import Optional, Sequence

logger = logging.getLogger("selkies_tpu.wayland.compositor")

#: candidate commands, first-found wins; each must understand the
#: wlroots headless env. ``weston --backend=headless`` speaks its own
#: flag so it is handled specially.
CANDIDATES: Sequence[str] = ("labwc", "sway", "cage", "weston")

SOCKET_WAIT_S = 10.0
RESTART_BACKOFF_S = (0.5, 1.0, 2.0, 5.0)


def _runtime_dir() -> str:
    d = os.environ.get("XDG_RUNTIME_DIR")
    if not d:
        d = f"/tmp/selkies-runtime-{os.getuid()}"
        os.makedirs(d, mode=0o700, exist_ok=True)
        os.environ["XDG_RUNTIME_DIR"] = d
    return d


def socket_alive(display: str) -> bool:
    """A Wayland socket counts as alive when something accepts on it."""
    import socket as _socket
    path = display if os.path.isabs(display) else \
        os.path.join(_runtime_dir(), display)
    if not os.path.exists(path):
        return False
    s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    s.settimeout(1.0)
    try:
        s.connect(path)
        return True
    except OSError:
        return False
    finally:
        s.close()


class HeadlessCompositor:
    """Supervise one owned headless compositor process."""

    def __init__(self, command: str = "", display: str = "selkies-wl-0",
                 width: int = 1920, height: int = 1080):
        self.command = command            # explicit override from settings
        self.display = display
        self.width = width
        self.height = height
        self.proc: Optional[asyncio.subprocess.Process] = None
        self._watch: Optional[asyncio.Task] = None
        self._closed = False

    def _pick(self) -> Optional[list[str]]:
        if self.command:
            argv = self.command.split()
            return argv if shutil.which(argv[0]) else None
        for cand in CANDIDATES:
            if shutil.which(cand):
                if cand == "weston":
                    return ["weston", "--backend=headless",
                            f"--width={self.width}",
                            f"--height={self.height}",
                            f"--socket={self.display}"]
                return [cand]
        return None

    def _env(self) -> dict[str, str]:
        env = dict(os.environ)
        env.update({
            "WLR_BACKENDS": "headless",
            "WLR_LIBINPUT_NO_DEVICES": "1",
            "WLR_RENDERER": "pixman",      # no GPU in the TPU container
            "WAYLAND_DISPLAY": self.display,
            "XDG_RUNTIME_DIR": _runtime_dir(),
            # size of the headless output wlroots creates
            "WLR_HEADLESS_OUTPUTS": "1",
        })
        return env

    async def start(self) -> bool:
        argv = self._pick()
        if argv is None:
            logger.warning(
                "no headless compositor found (tried %s); wayland "
                "own-compositor mode unavailable",
                self.command or ",".join(CANDIDATES))
            return False
        if not await self._spawn(argv):
            return False
        self._watch = asyncio.create_task(self._watchdog(argv))
        return True

    async def _spawn(self, argv: list[str]) -> bool:
        logger.info("starting headless compositor: %s (socket %s)",
                    " ".join(argv), self.display)
        try:
            self.proc = await asyncio.create_subprocess_exec(
                *argv, env=self._env(),
                stdout=asyncio.subprocess.DEVNULL,
                stderr=asyncio.subprocess.DEVNULL)
        except OSError as e:
            logger.warning("compositor spawn failed: %s", e)
            return False
        deadline = time.monotonic() + SOCKET_WAIT_S
        while time.monotonic() < deadline:
            if socket_alive(self.display):
                logger.info("compositor socket %s is up", self.display)
                return True
            if self.proc.returncode is not None:
                logger.warning("compositor exited rc=%s before its "
                               "socket appeared", self.proc.returncode)
                return False
            await asyncio.sleep(0.2)
        logger.warning("compositor socket %s never appeared", self.display)
        return False

    async def _watchdog(self, argv: list[str]) -> None:
        """Restart the compositor if it dies (capture clients reconnect
        through their own retry loops); bounded backoff so a broken
        install can't spin."""
        attempt = 0
        while not self._closed:
            assert self.proc is not None
            await self.proc.wait()
            if self._closed:
                return
            delay = RESTART_BACKOFF_S[min(attempt,
                                          len(RESTART_BACKOFF_S) - 1)]
            attempt += 1
            logger.warning("compositor died (rc=%s); restart %d in %.1fs",
                           self.proc.returncode, attempt, delay)
            await asyncio.sleep(delay)
            if not await self._spawn(argv):
                logger.error("compositor restart failed; giving up")
                return
            attempt = 0 if socket_alive(self.display) else attempt

    async def stop(self) -> None:
        self._closed = True
        if self._watch is not None:
            self._watch.cancel()
        if self.proc is not None and self.proc.returncode is None:
            self.proc.terminate()
            try:
                await asyncio.wait_for(self.proc.wait(), timeout=5)
            except asyncio.TimeoutError:
                self.proc.kill()
                await self.proc.wait()


async def ensure_wayland_display(settings) -> tuple[Optional[str],
                                                    Optional[HeadlessCompositor]]:
    """The reference's ``ensure_wayland_display`` contract: return a
    usable WAYLAND_DISPLAY, starting an owned headless compositor when
    the configured/ambient one is missing or dead. Returns
    ``(display_name, owned_compositor_or_None)``; ``(None, None)`` when
    nothing can be brought up."""
    for cand in (settings.wayland_host_display,
                 os.environ.get("WAYLAND_DISPLAY", "")):
        if cand and socket_alive(cand):
            logger.info("using external wayland compositor %s", cand)
            return cand, None
    comp = HeadlessCompositor(
        command=getattr(settings, "wayland_compositor", ""),
        width=settings.initial_width, height=settings.initial_height)
    if await comp.start():
        return comp.display, comp
    return None, None
