"""Dynamic xkb keymap for the virtual keyboard.

The input plane hands us X11 KEYSYMS (the client's wire grammar,
``kd,<keysym>``); Wayland's virtual-keyboard protocol wants evdev KEY
CODES interpreted through an xkb keymap. Instead of carrying a static
layout and hunting for spare keycodes (the X11 backend's approach,
input/backends.py:115-162 — necessary there because the X server owns
the map), we OWN the keymap here: every keysym that appears is assigned
the next free keycode and the whole map is re-uploaded (virtual-keyboard
allows re-keymapping at any time; compositors apply it to subsequent
events). One level per key — shifted glyphs are distinct keysyms on
their own keycodes, so no modifier state machine is needed for text.

Keysyms are emitted as hexadecimal literals (``0x100041``), which
xkbcommon's keysym parser accepts for any value — no name table needed.
"""

from __future__ import annotations

# evdev code = xkb keycode - 8; usable xkb keycodes 9..255 leave
# 247 simultaneous distinct keysyms, re-assignable LRU when exhausted
_MIN_KEYCODE = 9
_MAX_KEYCODE = 255


class DynamicKeymap:
    def __init__(self):
        self._by_keysym: dict[int, int] = {}
        self._order: list[int] = []            # keysyms, LRU first
        self._dirty = True

    def keycode_for(self, keysym: int) -> tuple[int, bool]:
        """-> (xkb keycode, keymap_changed)."""
        kc = self._by_keysym.get(keysym)
        if kc is not None:
            self._order.remove(keysym)
            self._order.append(keysym)
            return kc, self._consume_dirty()
        if len(self._by_keysym) >= _MAX_KEYCODE - _MIN_KEYCODE + 1:
            victim = self._order.pop(0)
            kc = self._by_keysym.pop(victim)
        else:
            kc = _MIN_KEYCODE + len(self._by_keysym)
        self._by_keysym[keysym] = kc
        self._order.append(keysym)
        self._dirty = True
        return kc, self._consume_dirty()

    def _consume_dirty(self) -> bool:
        d, self._dirty = self._dirty, False
        return d

    def text(self) -> str:
        codes = [f"        <K{kc}> = {kc};"
                 for kc in sorted(self._by_keysym.values())]
        syms = [f"        key <K{kc}> {{ [ {hex(ks)} ] }};"
                for ks, kc in sorted(self._by_keysym.items(),
                                     key=lambda kv: kv[1])]
        return "\n".join([
            "xkb_keymap {",
            '    xkb_keycodes "selkies" {',
            f"        minimum = {_MIN_KEYCODE - 1};",
            f"        maximum = {_MAX_KEYCODE};",
            *codes,
            "    };",
            '    xkb_types "selkies" { };',
            '    xkb_compatibility "selkies" { };',
            '    xkb_symbols "selkies" {',
            *syms,
            "    };",
            "};",
        ]) + "\n"
