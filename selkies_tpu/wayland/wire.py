"""Wayland wire-protocol codec + connection (client side).

The reference's Wayland roles live inside the closed pixelflux wheel: it
either composits its own headless output or attaches to an external
compositor as a screencopy/virtual-input client (reference
src/selkies/settings.py:615-638, stream_server.py:420-447). This package
implements the latter role from the wire up — no libwayland, no
python-wayland: messages are marshalled by hand and fds ride SCM_RIGHTS —
so the capture/input planes work against any wlroots-style compositor
(labwc, sway --headless, ...) and are testable against the in-tree fake
compositor (tests/test_wayland.py).

Wire format (stable since Wayland 1.0):

    message := header payload
    header  := object_id:u32  (size<<16 | opcode):u32      # LE, size incl hdr
    args    := i32 | u32 | fixed(24.8) | string (u32 len incl NUL, pad 4)
               | object (u32 id) | new_id (u32 id) | array (u32 len, pad 4)
               | fd (no bytes in payload; one fd in the ancillary queue)

Client object IDs allocate upward from 2 (1 is wl_display); IDs freed by
``wl_display.delete_id`` are recycled.
"""

from __future__ import annotations

import array
import os
import socket
import struct
import threading
from typing import Callable, Optional

MAX_FDS_PER_RECV = 28


class WireError(RuntimeError):
    pass


# ----------------------------------------------------------------- marshal
def arg_u32(v: int) -> bytes:
    return struct.pack("<I", v & 0xFFFFFFFF)


def arg_i32(v: int) -> bytes:
    return struct.pack("<i", v)


def arg_fixed(v: float) -> bytes:
    """Wayland 'fixed' is signed 24.8."""
    return struct.pack("<i", int(round(v * 256.0)))


def arg_string(s: str) -> bytes:
    raw = s.encode() + b"\x00"
    pad = (-len(raw)) % 4
    return struct.pack("<I", len(raw)) + raw + b"\x00" * pad


def arg_array(b: bytes) -> bytes:
    pad = (-len(b)) % 4
    return struct.pack("<I", len(b)) + b + b"\x00" * pad


class ArgReader:
    """Sequential unmarshal of one event's payload; fds pop from the
    connection-level ancillary queue in arrival order (the protocol
    guarantees fd args are queued in message order)."""

    def __init__(self, payload: bytes, fd_pop: Callable[[], int]):
        self.b = payload
        self.off = 0
        self._fd_pop = fd_pop

    def u32(self) -> int:
        v, = struct.unpack_from("<I", self.b, self.off)
        self.off += 4
        return v

    def i32(self) -> int:
        v, = struct.unpack_from("<i", self.b, self.off)
        self.off += 4
        return v

    def fixed(self) -> float:
        return self.i32() / 256.0

    def string(self) -> str:
        n = self.u32()
        raw = self.b[self.off:self.off + n]
        self.off += n + ((-n) % 4)
        return raw.split(b"\x00", 1)[0].decode()

    def array(self) -> bytes:
        n = self.u32()
        raw = self.b[self.off:self.off + n]
        self.off += n + ((-n) % 4)
        return bytes(raw)

    def fd(self) -> int:
        return self._fd_pop()


# -------------------------------------------------------------- connection
class WaylandConnection:
    """One client connection: socket IO, object-id allocation, event
    dispatch. Thread-safety: sends are locked; dispatch runs on whichever
    thread calls :meth:`dispatch`/:meth:`roundtrip` (one at a time)."""

    DISPLAY_ID = 1

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.sock.setblocking(True)
        self._send_lock = threading.Lock()
        self._next_id = 2
        self._free_ids: list[int] = []
        self._rbuf = b""
        self._fds: list[int] = []
        #: object_id -> handler(opcode, ArgReader); unhandled events are
        #: legal (a client may ignore any event)
        self.handlers: dict[int, Callable[[int, ArgReader], None]] = {
            self.DISPLAY_ID: self._on_display_event,
        }
        self.dead: Optional[str] = None

    # -- lifecycle ----------------------------------------------------------
    @classmethod
    def connect(cls, display: Optional[str] = None) -> "WaylandConnection":
        name = display or os.environ.get("WAYLAND_DISPLAY", "wayland-0")
        if not name.startswith("/"):
            run = os.environ.get("XDG_RUNTIME_DIR")
            if not run:
                raise WireError("XDG_RUNTIME_DIR unset; no Wayland socket")
            name = os.path.join(run, name)
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.connect(name)
        except OSError as e:
            s.close()
            raise WireError(f"cannot connect to compositor at {name}: {e}")
        return cls(s)

    def close(self) -> None:
        for fd in self._fds:
            try:
                os.close(fd)
            except OSError:
                pass
        self._fds.clear()
        try:
            self.sock.close()
        except OSError:
            pass

    # -- ids ----------------------------------------------------------------
    def new_id(self) -> int:
        if self._free_ids:
            return self._free_ids.pop()
        nid = self._next_id
        self._next_id += 1
        return nid

    # -- send ---------------------------------------------------------------
    def send(self, obj_id: int, opcode: int, payload: bytes = b"",
             fds: tuple[int, ...] = ()) -> None:
        size = 8 + len(payload)
        if size > 0xFFFF:
            raise WireError(f"message too large ({size})")
        msg = struct.pack("<II", obj_id, (size << 16) | opcode) + payload
        with self._send_lock:
            if fds:
                anc = [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                        array.array("i", fds).tobytes())]
                # a short write would desync the whole stream: loop until
                # the full message is out (fds ride the FIRST segment only)
                sent = self.sock.sendmsg([msg], anc)
                while sent < len(msg):
                    sent += self.sock.send(msg[sent:])
            else:
                self.sock.sendall(msg)

    # -- receive / dispatch -------------------------------------------------
    def _pop_fd(self) -> int:
        if not self._fds:
            raise WireError("event consumed an fd but none arrived")
        return self._fds.pop(0)

    def _fill(self, timeout: Optional[float]) -> bool:
        """Read once from the socket (with ancillary fds); False on
        timeout, raises on EOF."""
        self.sock.settimeout(timeout)
        try:
            data, anc, _flags, _addr = self.sock.recvmsg(
                65536, socket.CMSG_SPACE(MAX_FDS_PER_RECV * 4))
        except (socket.timeout, BlockingIOError):
            return False
        finally:
            self.sock.settimeout(None)
        if not data:
            raise WireError("compositor closed the connection"
                            + (f" (error: {self.dead})" if self.dead else ""))
        for level, ctype, cdata in anc:
            if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
                n = len(cdata) // 4
                self._fds.extend(array.array("i", cdata[:n * 4]).tolist())
        self._rbuf += data
        return True

    def dispatch(self, timeout: Optional[float] = None) -> int:
        """Dispatch every buffered event, reading once if the buffer is
        empty. Returns events dispatched."""
        n = 0
        if len(self._rbuf) < 8:
            if not self._fill(timeout):
                return 0
        while len(self._rbuf) >= 8:
            obj_id, sz_op = struct.unpack_from("<II", self._rbuf)
            size, opcode = sz_op >> 16, sz_op & 0xFFFF
            if size < 8:
                raise WireError(f"bad message size {size}")
            if len(self._rbuf) < size:
                if not self._fill(timeout):
                    break
                continue
            payload = self._rbuf[8:size]
            self._rbuf = self._rbuf[size:]
            handler = self.handlers.get(obj_id)
            if handler is not None:
                handler(opcode, ArgReader(payload, self._pop_fd))
            n += 1
        return n

    def roundtrip(self, timeout: float = 5.0) -> None:
        """wl_display.sync barrier: the compositor has processed every
        prior request once the callback fires."""
        done = threading.Event()
        cb_id = self.new_id()

        def _cb(opcode: int, r: ArgReader) -> None:
            if opcode == 0:                         # wl_callback.done
                done.set()
                self.handlers.pop(cb_id, None)
                # the id is recycled by wl_display.delete_id, NOT here —
                # freeing twice would hand one id to two live objects

        self.handlers[cb_id] = _cb
        self.send(self.DISPLAY_ID, 0, arg_u32(cb_id))      # sync
        deadline = _now() + timeout
        while not done.is_set():
            left = deadline - _now()
            if left <= 0:
                raise WireError("roundtrip timed out")
            self.dispatch(timeout=left)
            if self.dead:
                raise WireError(f"compositor error: {self.dead}")

    # -- wl_display events --------------------------------------------------
    def _on_display_event(self, opcode: int, r: ArgReader) -> None:
        if opcode == 0:                              # error
            oid, code, msg = r.u32(), r.u32(), r.string()
            self.dead = f"object {oid} code {code}: {msg}"
            raise WireError(f"compositor error: {self.dead}")
        elif opcode == 1:                            # delete_id
            did = r.u32()
            self.handlers.pop(did, None)
            self._free_ids.append(did)


def _now() -> float:
    import time
    return time.monotonic()
