/* Audio plane: playback (0x01 Opus+RED -> AudioDecoder -> WebAudio) and
 * microphone capture (getUserMedia -> AudioWorklet -> 0x02 s16le PCM).
 * Reference client extractOpusFrames (selkies-ws-core.js:36-38) and mic
 * sender (selkies-ws-core.js:5685). */

import { OP_MIC } from "./protocol.js";

/* Opus over 0x01 frames -> WebCodecs AudioDecoder -> WebAudio graph.
 * RED (RFC 2198) redundancy is de-framed; only the primary block is
 * decoded (redundant blocks cover WS message loss, which TCP prevents —
 * they matter on the datagram transports). */
export class AudioPlayer {
  constructor(serverSettings) {
    const st = serverSettings.settings || {};
    this.sampleRate = 48000;
    this.channels = (st.audio_channels && st.audio_channels.value) || 2;
    this.frameMs = (st.audio_frame_ms && st.audio_frame_ms.value) || 10;
    // surround (>2ch): the server ships an RFC 7845 OpusHead whose
    // channel-mapping table the decoder needs as `description`
    this.head = serverSettings.audio_head
      ? Uint8Array.from(atob(serverSettings.audio_head),
                        (c) => c.charCodeAt(0))
      : null;
    this.ctx = new AudioContext({ sampleRate: this.sampleRate });
    this.playhead = 0;
    this.tsUs = 0;
    this.queueTarget = 5 * this.frameMs / 1000;  // ≤5 frames client buffer
    this.dec = null;
    this._initDecoder();
  }

  _initDecoder() {
    if (typeof AudioDecoder === "undefined") return;
    this.dec = new AudioDecoder({
      output: (ad) => this._play(ad),
      error: (e) => console.warn("audio decode", e),
    });
    const cfg = {
      codec: "opus", sampleRate: this.sampleRate,
      numberOfChannels: this.channels,
    };
    if (this.head && this.channels > 2) cfg.description = this.head;
    this.dec.configure(cfg);
  }

  push(buf) {
    if (!this.dec || this.dec.state !== "configured") return;
    const nRed = buf[1];
    let payload = buf.subarray(2);
    if (nRed > 0) {
      // RED: u32 pts + nRed*4-byte block hdrs + 1-byte primary hdr + blocks
      let off = 4 + nRed * 4 + 1;
      const dv = new DataView(buf.buffer, buf.byteOffset + 2);
      let skip = 0;
      for (let i = 0; i < nRed; i++)
        skip += dv.getUint32(4 + i * 4) & 0x3FF;   // 10-bit block length
      payload = payload.subarray(off + skip);       // primary block only
    }
    if (!payload.length) return;
    this.dec.decode(new EncodedAudioChunk({
      type: "key", timestamp: this.tsUs, data: payload,
    }));
    this.tsUs += this.frameMs * 1000;
  }

  _play(ad) {
    const n = ad.numberOfFrames, ch = ad.numberOfChannels;
    const buf = this.ctx.createBuffer(ch, n, ad.sampleRate);
    for (let c = 0; c < ch; c++) {
      const dst = buf.getChannelData(c);
      ad.copyTo(dst, { planeIndex: c, format: "f32-planar" });
    }
    ad.close();
    const now = this.ctx.currentTime;
    if (this.playhead < now) this.playhead = now + 0.01;
    if (this.playhead - now > this.queueTarget * 3) {
      this.playhead = now + this.queueTarget;  // queue ran away: resync
    }
    const src = this.ctx.createBufferSource();
    src.buffer = buf;
    src.connect(this.ctx.destination);
    src.start(this.playhead);
    this.playhead += buf.duration;
  }

  close() {
    if (this.dec) try { this.dec.close(); } catch { /* already closed */ }
    this.ctx.close();
  }
}

/* Capture path: the AudioContext resamples the getUserMedia track to
 * 24 kHz; an AudioWorklet batches ~20 ms (480-sample) mono chunks that
 * are sent as [0x02][s16le PCM] frames — the exact format
 * audio/pipeline.play_mic_pcm feeds pacat. */
export class MicSender {
  constructor(sendBytes) {
    this.sendBytes = sendBytes;
    this.ctx = null;
    this.node = null;
    this.stream = null;
  }

  async start() {
    this.stream = await navigator.mediaDevices.getUserMedia({
      audio: { channelCount: 1, echoCancellation: true,
               noiseSuppression: true },
    });
    this.ctx = new AudioContext({ sampleRate: 24000 });
    const src = `registerProcessor("selkies-mic",
      class extends AudioWorkletProcessor {
        process(inputs) {
          const ch = inputs[0] && inputs[0][0];
          if (ch && ch.length) this.port.postMessage(ch.slice(0));
          return true;
        }
      });`;
    const url = URL.createObjectURL(
      new Blob([src], { type: "application/javascript" }));
    try {
      await this.ctx.audioWorklet.addModule(url);
    } finally {
      URL.revokeObjectURL(url);
    }
    const input = this.ctx.createMediaStreamSource(this.stream);
    this.node = new AudioWorkletNode(this.ctx, "selkies-mic");
    this._chunks = [];
    this._n = 0;
    this.node.port.onmessage = (e) => this._onChunk(e.data);
    input.connect(this.node);
    /* no destination connection: capture-only graph */
  }

  _onChunk(f32) {
    this._chunks.push(f32);
    this._n += f32.length;
    if (this._n < 480) return;                    // ~20 ms at 24 kHz
    const all = new Float32Array(this._n);
    let o = 0;
    for (const c of this._chunks) { all.set(c, o); o += c.length; }
    this._chunks = [];
    this._n = 0;
    const frame = new Uint8Array(1 + all.length * 2);
    frame[0] = OP_MIC;
    const dv = new DataView(frame.buffer);
    for (let i = 0; i < all.length; i++) {
      const s = Math.max(-1, Math.min(1, all[i]));
      dv.setInt16(1 + i * 2, s < 0 ? s * 0x8000 : s * 0x7FFF, true);
    }
    this.sendBytes(frame);
  }

  stop() {
    if (this.node) { try { this.node.disconnect(); } catch { /* gone */ } }
    if (this.ctx) this.ctx.close();
    if (this.stream)
      for (const t of this.stream.getTracks()) t.stop();
    this.node = this.ctx = this.stream = null;
  }
}
