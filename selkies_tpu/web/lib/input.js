/* Input capture: keyboard (X11 keysyms), mouse (absolute + pointer-lock
 * relative), touch (direct + trackpad modes), on-screen keyboard,
 * gamepad polling, clipboard. All events become the text-verb grammar
 * both transports speak (reference lib/input.js, lib/gamepad.js;
 * SURVEY.md §2.3).
 *
 * `io` contract: io.send(text), io.size() -> [w, h] (stream geometry —
 * the canvas element may be an offscreen-transferred placeholder whose
 * width attribute is stale, so coordinates always scale against the
 * authoritative stream size). */

import { keysymOf } from "./keysyms.js";

export class InputManager {
  constructor(canvas, io) {
    this.cv = canvas;
    this.io = io;
    this.held = new Set();            // held keysyms
    this.touchMode = "direct";        // or "trackpad" (postMessage API)
    this.pointerLocked = false;
    this._bind();
  }

  heartbeat() {
    if (this.held.size)
      this.io.send(`kh,${Array.from(this.held).join(",")}`);
  }

  _scaleClient(clientX, clientY) {
    const r = this.cv.getBoundingClientRect();
    const [w, h] = this.io.size();
    const x = Math.round((clientX - r.left) * (w / r.width));
    const y = Math.round((clientY - r.top) * (h / r.height));
    return [Math.max(0, Math.min(w - 1, x)),
            Math.max(0, Math.min(h - 1, y))];
  }

  _bind() {
    const cv = this.cv;
    cv.addEventListener("contextmenu", (e) => e.preventDefault());

    cv.addEventListener("keydown", (e) => {
      const ks = keysymOf(e);
      if (ks === null) return;
      e.preventDefault();
      if (!e.repeat) { this.held.add(ks); this.io.send(`kd,${ks}`); }
    });
    cv.addEventListener("keyup", (e) => {
      const ks = keysymOf(e);
      if (ks === null) return;
      e.preventDefault();
      this.held.delete(ks);
      this.io.send(`ku,${ks}`);
    });
    cv.addEventListener("blur", () => {
      if (this.held.size) { this.held.clear(); this.io.send("kr,"); }
    });

    cv.addEventListener("mousemove", (e) => {
      if (this.pointerLocked)
        this.io.send(`m2,${e.movementX},${e.movementY}`);
      else {
        const [x, y] = this._scaleClient(e.clientX, e.clientY);
        this.io.send(`m,${x},${y}`);
      }
    });
    const btnMap = { 0: 1, 1: 2, 2: 3, 3: 8, 4: 9 };  // DOM -> X11
    cv.addEventListener("mousedown", (e) => {
      cv.focus();
      const [x, y] = this._scaleClient(e.clientX, e.clientY);
      this.io.send(`m,${x},${y}`);
      this.io.send(`mb,${btnMap[e.button] ?? 1},1`);
      e.preventDefault();
    });
    cv.addEventListener("mouseup", (e) => {
      this.io.send(`mb,${btnMap[e.button] ?? 1},0`);
      e.preventDefault();
    });
    cv.addEventListener("wheel", (e) => {
      const dy = Math.sign(e.deltaY), dx = Math.sign(e.deltaX);
      if (dx || dy) this.io.send(`ms,${dx},${dy}`);
      e.preventDefault();
    }, { passive: false });

    document.addEventListener("pointerlockchange", () => {
      this.pointerLocked = document.pointerLockElement === cv;
    });
    cv.addEventListener("dblclick", () => {
      // double-click toggles pointer lock for games needing relative mouse
      if (!this.pointerLocked && cv.requestPointerLock)
        cv.requestPointerLock();
    });

    document.addEventListener("paste", (e) => {
      const text = e.clipboardData && e.clipboardData.getData("text");
      if (text)
        this.io.send(`cw,${btoa(unescape(encodeURIComponent(text)))}`);
    });
    document.addEventListener("copy", () => {
      // fetch the REMOTE clipboard; delayed so the forwarded Ctrl+C
      // keystroke reaches the remote app BEFORE the server reads its
      // selection (otherwise the reply is the previous clipboard)
      setTimeout(() => this.io.send("REQUEST_CLIPBOARD"), 150);
    });

    this._bindGamepad();
    this._bindTouch(cv);
  }

  /* ------------------------------------------------------------- gamepad
   * navigator.getGamepads() polling -> js,c/d/b/a verbs (the server half
   * feeds the C interposer sockets; reference lib/gamepad.js:1-229). */
  _bindGamepad() {
    this.padState = new Map();          // index -> {buttons:[], axes:[]}
    window.addEventListener("gamepadconnected", (e) => {
      const p = e.gamepad;
      if (p.index > 3) return;
      this.padState.set(p.index, { buttons: [], axes: [] });
      this.io.send(`js,c,${p.index},${p.id.slice(0, 64)}`);
      if (!this._padTimer) this._padTimer = setInterval(
        () => this._pollGamepads(), 16);
    });
    window.addEventListener("gamepaddisconnected", (e) => {
      if (!this.padState.delete(e.gamepad.index)) return;
      this.io.send(`js,d,${e.gamepad.index}`);
      if (this.padState.size === 0 && this._padTimer) {
        clearInterval(this._padTimer);
        this._padTimer = null;
      }
    });
  }

  _pollGamepads() {
    const pads = navigator.getGamepads ? navigator.getGamepads() : [];
    for (const p of pads) {
      if (!p || !this.padState.has(p.index)) continue;
      const st = this.padState.get(p.index);
      p.buttons.forEach((b, i) => {
        const v = b.pressed ? 1 : 0;
        if (st.buttons[i] !== v) {
          st.buttons[i] = v;
          this.io.send(`js,b,${p.index},${i},${v}`);
        }
      });
      p.axes.forEach((a, i) => {
        const v = Math.round(a * 1000) / 1000;
        if (Math.abs((st.axes[i] ?? 0) - v) > 0.009) {
          st.axes[i] = v;
          this.io.send(`js,a,${p.index},${i},${v}`);
        }
      });
    }
  }

  /* --------------------------------------------------------------- touch
   * Touch-to-mouse: one finger = absolute move + left button; two-finger
   * vertical pan = wheel; two-finger tap = right click (reference
   * lib/input.js touch mode). */
  _bindTouch(cv) {
    const scaleT = (t) => this._scaleClient(t.clientX, t.clientY);
    // tap-vs-gesture disambiguation: the left press is DEFERRED 60 ms
    // so a second finger (scroll/right-click gesture) can cancel it —
    // otherwise every two-finger gesture starts with a phantom click
    let twoFinger = null;               // {y, moved, t0}
    let pendingPress = null;            // timer id
    let pressed = false;
    const commitPress = () => {
      if (pendingPress !== null) {
        clearTimeout(pendingPress);
        pendingPress = null;
        this.io.send("mb,1,1");
        pressed = true;
      }
    };
    cv.addEventListener("touchstart", (e) => {
      e.preventDefault();
      if (this.touchMode === "trackpad") {
        this._trackpadStart(e);
        return;
      }
      if (e.touches.length === 1) {
        const [x, y] = scaleT(e.touches[0]);
        this.io.send(`m,${x},${y}`);
        pendingPress = setTimeout(commitPress, 60);
      } else if (e.touches.length === 2) {
        if (pendingPress !== null) {    // gesture: cancel the tap press
          clearTimeout(pendingPress);
          pendingPress = null;
        } else if (pressed) {
          this.io.send("mb,1,0");
          pressed = false;
        }
        twoFinger = { y: e.touches[0].clientY, moved: false,
                      t0: performance.now() };
      }
    }, { passive: false });
    cv.addEventListener("touchmove", (e) => {
      e.preventDefault();
      if (this.touchMode === "trackpad") {
        this._trackpadMove(e);
        return;
      }
      if (e.touches.length === 1 && !twoFinger) {
        commitPress();                  // moving finger = drag, press now
        const [x, y] = scaleT(e.touches[0]);
        this.io.send(`m,${x},${y}`);
      } else if (e.touches.length === 2 && twoFinger) {
        const dy = e.touches[0].clientY - twoFinger.y;
        if (Math.abs(dy) > 12) {
          this.io.send(`ms,0,${dy > 0 ? -1 : 1}`);
          twoFinger.y = e.touches[0].clientY;
          twoFinger.moved = true;
        }
      }
    }, { passive: false });
    cv.addEventListener("touchend", (e) => {
      e.preventDefault();
      if (this.touchMode === "trackpad") {
        this._trackpadEnd(e);
        return;
      }
      if (twoFinger) {
        if (!twoFinger.moved && performance.now() - twoFinger.t0 < 350) {
          this.io.send("mb,3,1");       // two-finger tap = right click
          this.io.send("mb,3,0");
          twoFinger.moved = true;       // fire once, not per lifted finger
        }
        if (e.touches.length === 0) twoFinger = null;
      } else if (e.touches.length === 0) {
        if (pendingPress !== null) {    // quick tap: full click now
          commitPress();
        }
        if (pressed) {
          this.io.send("mb,1,0");
          pressed = false;
        }
      }
    }, { passive: false });
  }

  /* trackpad touch mode (reference lib/input.js trackpad mode): the
   * canvas is a laptop touchpad — one finger moves the cursor
   * RELATIVELY (m2 verbs), a quick tap left-clicks, a one-finger
   * tap-then-drag drags, two-finger pan scrolls, two-finger tap
   * right-clicks. Switch via postMessage {type:"touchMode"}. */
  _trackpadStart(e) {
    const t = e.touches;
    const now = performance.now();
    if (t.length === 1) {
      const tapTap = this._tpLastTap && now - this._tpLastTap < 280;
      this._tp = { x: t[0].clientX, y: t[0].clientY, t0: now,
                   moved: false, drag: !!tapTap };
      if (tapTap) this.io.send("mb,1,1");    // tap-drag: hold the button
    } else if (t.length === 2) {
      // both fingers may land in ONE touchstart (fast two-finger tap):
      // synthesize the missing one-finger state so the gesture works
      if (!this._tp)
        this._tp = { x: t[0].clientX, y: t[0].clientY, t0: now,
                     moved: false, drag: false };
      if (this._tp.drag) { this.io.send("mb,1,0"); this._tp.drag = false; }
      this._tp.two = { y: t[0].clientY, t0: now, moved: this._tp.moved };
    }
  }

  _trackpadMove(e) {
    const t = e.touches;
    if (!this._tp) return;
    if (t.length === 1 && !this._tp.two) {
      const dx = Math.round((t[0].clientX - this._tp.x) * 1.4);
      const dy = Math.round((t[0].clientY - this._tp.y) * 1.4);
      if (dx || dy) {
        this.io.send(`m2,${dx},${dy}`);
        this._tp.x = t[0].clientX;
        this._tp.y = t[0].clientY;
        this._tp.moved = true;
      }
    } else if (t.length === 2 && this._tp.two) {
      const dy = t[0].clientY - this._tp.two.y;
      if (Math.abs(dy) > 12) {
        this.io.send(`ms,0,${dy > 0 ? -1 : 1}`);
        this._tp.two.y = t[0].clientY;
        this._tp.two.moved = true;
      }
    }
  }

  _trackpadEnd(e) {
    if (!this._tp) return;
    const now = performance.now();
    if (this._tp.two) {
      if (!this._tp.two.moved && now - this._tp.two.t0 < 350) {
        this.io.send("mb,3,1");
        this.io.send("mb,3,0");
        this._tp.two.moved = true;
      }
      if (e.touches.length === 0) this._tp = null;
      return;
    }
    if (e.touches.length === 0) {
      if (this._tp.drag) this.io.send("mb,1,0");
      else if (!this._tp.moved && now - this._tp.t0 < 250) {
        this.io.send("mb,1,1");
        this.io.send("mb,1,0");
        this._tpLastTap = now;
      }
      this._tp = null;
    }
  }

  /* --------------------------------------------------- on-screen keyboard
   * Minimal OSK for touch devices (reference lib/input.js OSK): a
   * toggleable overlay whose buttons fire the same kd/ku verbs. */
  toggleOnScreenKeyboard() {
    if (this._osk) {
      this._osk.remove();
      this._osk = null;
      return;
    }
    const rows = [
      ["Esc:65307", "1", "2", "3", "4", "5", "6", "7", "8", "9", "0",
       "⌫:65288"],
      ["q", "w", "e", "r", "t", "y", "u", "i", "o", "p"],
      ["a", "s", "d", "f", "g", "h", "j", "k", "l", "⏎:65293"],
      ["⇧:65505", "z", "x", "c", "v", "b", "n", "m", ",", "."],
      ["Ctrl:65507", "Alt:65513", "␣:32", "←:65361", "↓:65364",
       "↑:65362", "→:65363"],
    ];
    const osk = document.createElement("div");
    osk.style.cssText =
      "position:fixed;bottom:0;left:0;right:0;background:#222d;" +
      "padding:6px;z-index:1000;display:flex;flex-direction:column;" +
      "gap:4px;touch-action:none";
    for (const row of rows) {
      const line = document.createElement("div");
      line.style.cssText = "display:flex;gap:4px;justify-content:center";
      for (const keydef of row) {
        const [label, ksStr] = keydef.includes(":")
          ? keydef.split(":") : [keydef, null];
        const ks = ksStr ? parseInt(ksStr, 10)
          : label.codePointAt(0);
        const b = document.createElement("button");
        b.textContent = label;
        b.style.cssText =
          "flex:1;max-width:72px;padding:10px 4px;font-size:16px;" +
          "background:#444;color:#eee;border:1px solid #666;" +
          "border-radius:4px";
        const down = (e) => { e.preventDefault(); this.io.send(`kd,${ks}`); };
        const up = (e) => { e.preventDefault(); this.io.send(`ku,${ks}`); };
        b.addEventListener("pointerdown", down);
        b.addEventListener("pointerup", up);
        b.addEventListener("pointerleave", up);
        line.appendChild(b);
      }
      osk.appendChild(line);
    }
    document.body.appendChild(osk);
    this._osk = osk;
  }
}
