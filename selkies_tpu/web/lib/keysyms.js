/* X11 keysym mapping + keyboard layout detection.
 *
 * Printable ASCII/Latin-1 map to their codepoint; other Unicode maps to
 * 0x01000000 + codepoint (X11 convention); special keys use the table
 * below (keysymdef.h values, same table the reference client carries in
 * lib/input.js KeyTable). */

export const KEYSYM_SPECIAL = {
  Backspace: 0xFF08, Tab: 0xFF09, Enter: 0xFF0D, Pause: 0xFF13,
  ScrollLock: 0xFF14, Escape: 0xFF1B, Home: 0xFF50, ArrowLeft: 0xFF51,
  ArrowUp: 0xFF52, ArrowRight: 0xFF53, ArrowDown: 0xFF54, PageUp: 0xFF55,
  PageDown: 0xFF56, End: 0xFF57, Insert: 0xFF63, Menu: 0xFF67,
  ContextMenu: 0xFF67, NumLock: 0xFF7F, F1: 0xFFBE, F2: 0xFFBF, F3: 0xFFC0,
  F4: 0xFFC1, F5: 0xFFC2, F6: 0xFFC3, F7: 0xFFC4, F8: 0xFFC5, F9: 0xFFC6,
  F10: 0xFFC7, F11: 0xFFC8, F12: 0xFFC9, Delete: 0xFFFF,
  CapsLock: 0xFFE5, PrintScreen: 0xFF61,
};

export const KEYSYM_BY_CODE = {    // location-dependent keys need e.code
  ShiftLeft: 0xFFE1, ShiftRight: 0xFFE2, ControlLeft: 0xFFE3,
  ControlRight: 0xFFE4, AltLeft: 0xFFE9, AltRight: 0xFFEA,
  MetaLeft: 0xFFEB, MetaRight: 0xFFEC,
  NumpadEnter: 0xFF8D, NumpadMultiply: 0xFFAA, NumpadAdd: 0xFFAB,
  NumpadSubtract: 0xFFAD, NumpadDecimal: 0xFFAE, NumpadDivide: 0xFFAF,
  Numpad0: 0xFFB0, Numpad1: 0xFFB1, Numpad2: 0xFFB2, Numpad3: 0xFFB3,
  Numpad4: 0xFFB4, Numpad5: 0xFFB5, Numpad6: 0xFFB6, Numpad7: 0xFFB7,
  Numpad8: 0xFFB8, Numpad9: 0xFFB9,
};

export function keysymOf(e) {
  if (KEYSYM_BY_CODE[e.code] !== undefined) return KEYSYM_BY_CODE[e.code];
  const k = e.key;
  if (k.length === 1) {
    const cp = k.codePointAt(0);
    if (cp >= 0x20 && cp <= 0x7E) return cp;          // ASCII printable
    if (cp >= 0xA0 && cp <= 0xFF) return cp;          // Latin-1
    return 0x01000000 + cp;                            // Unicode keysym
  }
  if (KEYSYM_SPECIAL[k] !== undefined) return KEYSYM_SPECIAL[k];
  return null;
}

/* Best-effort layout detection (reference lib/keyboard-layout.js): probe
 * the physical-key layout map, fall back to the UI language. The server
 * aligns the X keymap for scancode-reading apps (character input is
 * already layout-independent via keysyms). */
export async function detectKeyboardLayout() {
  let layout = "";
  try {
    if (navigator.keyboard && navigator.keyboard.getLayoutMap) {
      const map = await navigator.keyboard.getLayoutMap();
      const probe = [map.get("KeyQ"), map.get("KeyW"), map.get("KeyZ")]
        .join("");
      layout = { qwz: "us", azw: "fr", qwy: "de" }[probe] || "";
    }
  } catch (_e) { /* permissions / unsupported */ }
  if (!layout) {
    const lang = (navigator.language || "en-US").toLowerCase();
    layout = { fr: "fr", de: "de", es: "es", it: "it", pt: "pt",
               ru: "ru", gb: "gb" }[lang.split("-")[0]] || "us";
  }
  return layout;
}
