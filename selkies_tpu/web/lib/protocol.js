/* Wire constants shared across modules (protocol.py opcode bytes). */

export const OP_AUDIO = 0x01;
export const OP_MIC = 0x02;
export const OP_JPEG = 0x03;
export const OP_H264 = 0x04;
export const OP_GZ = 0x05;

/* uint16 circular frame-id comparison (matches the server's ACK rule). */
export const fidNewer = (a, b) =>
  ((a - b + 0x10000) & 0xFFFF) < 0x8000 && a !== b;
