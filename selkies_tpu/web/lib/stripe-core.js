/* Shared stripe-decode core — ONE copy of the wire-format logic
 * (headers, frame-id dedup, overload drop, decoder-per-y, fullcolor
 * codec select) used by BOTH rendering paths:
 *   - lib/video-worker.js  (classic worker: importScripts this file)
 *   - lib/video.js CanvasVideoSink (main thread: index.html loads this
 *     with a plain <script> before the module entry)
 * Classic script on purpose: ES modules can't be importScripts'd and
 * classic workers can't import modules, so the shared core speaks the
 * one dialect both sides can load. Exposes `SelkiesStripeCore` on the
 * global scope (window or worker self). */

"use strict";

(function (global) {
  const OP_JPEG = 0x03, OP_H264 = 0x04;
  const fidNewer = (a, b) =>
    ((a - b + 0x10000) & 0xFFFF) < 0x8000 && a !== b;

  /* hooks: draw(imageLike, y)  — blit one decoded stripe,
   *        onAck(fid), onDrawn(), onKeyframeNeeded(), onStatus(msg),
   *        fullcolor() -> bool  — read at decoder creation time. */
  function makeStripeDecoder(hooks) {
    const stripeLastFid = new Map();   // y -> last drawn frame id
    const h264Decoders = new Map();    // y -> VideoDecoder
    let jpegQueue = 0;                 // in-flight createImageBitmap
    let h264warned = false;
    let droppedDecodes = 0;            // overload drops (CLIENT_STATS)

    /* 6-byte header: [0x03, flags, u16 frame_id, u16 stripe_y] + JFIF */
    async function pushJpeg(buf) {
      const dv = new DataView(buf.buffer, buf.byteOffset, 6);
      const fid = dv.getUint16(2), y = dv.getUint16(4);
      const last = stripeLastFid.get(y);
      if (last !== undefined && !fidNewer(fid, last)) return; // stale
      if (jpegQueue > 48) {         // overload: drop, keyframe recovers
        droppedDecodes++;
        return;
      }
      jpegQueue++;
      try {
        const blob = new Blob([buf.subarray(6)], { type: "image/jpeg" });
        const bmp = await createImageBitmap(blob);
        const l2 = stripeLastFid.get(y);
        if (l2 === undefined || fidNewer(fid, l2) || fid === l2) {
          stripeLastFid.set(y, fid);
          hooks.draw(bmp, y);       // canvas crops right/bottom padding
          hooks.onDrawn();
          hooks.onAck(fid);
        }
        bmp.close();
      } catch (e) {
        console.warn("jpeg stripe decode failed", e);
      } finally {
        jpegQueue--;
      }
    }

    /* 10-byte header: [0x04, frame_type, u16 fid, u16 y, u16 w, u16 h]
     * + Annex-B. Every stripe row is an independent H.264 stream with
     * its own decoder keyed by y_start (reference
     * selkies-ws-core.js:4424-4460). */
    function pushH264(buf) {
      if (typeof VideoDecoder === "undefined") {
        if (!h264warned) {
          h264warned = true;
          hooks.onStatus("WebCodecs H.264 unsupported in this browser");
        }
        return;
      }
      const dv = new DataView(buf.buffer, buf.byteOffset, 10);
      const fid = dv.getUint16(2), y = dv.getUint16(4);
      let dec = h264Decoders.get(y);
      if (!dec || dec.state === "closed") {
        const yTop = y;
        dec = new VideoDecoder({
          output: (frame) => {
            hooks.draw(frame, yTop);
            hooks.onDrawn();
            hooks.onAck(frame.timestamp & 0xFFFF);
            frame.close();
          },
          error: (e) => {
            console.warn("h264 stripe decoder error", e);
            h264Decoders.delete(yTop);
            hooks.onKeyframeNeeded();
          },
        });
        // Annex-B stream (no description): constrained baseline, or
        // Hi444PP when the server streams fullcolor 4:4:4 (the
        // reference's f4001f profile munge)
        dec.configure({
          codec: hooks.fullcolor() ? "avc1.f4002a" : "avc1.42c02a",
          optimizeForLatency: true,
        });
        h264Decoders.set(y, dec);
      }
      if (dec.decodeQueueSize > 16) {
        // overload: drop the stripe but request a refresh (throttled
        // by the client) — the server's damage gating believes it was
        // delivered and would otherwise leave this region stale until
        // the next change
        droppedDecodes++;
        hooks.onKeyframeNeeded();
        return;
      }
      dec.decode(new EncodedVideoChunk({
        type: buf[1] === 1 ? "key" : "delta",  // frame_type from header
        timestamp: fid,
        data: buf.subarray(10),
      }));
    }

    function push(u8) {
      if (u8[0] === OP_JPEG) pushJpeg(u8);
      else if (u8[0] === OP_H264) pushH264(u8);
    }

    function reset() {
      stripeLastFid.clear();
      for (const dec of h264Decoders.values()) {
        try { dec.close(); } catch (_e) { /* already closed */ }
      }
      h264Decoders.clear();
    }

    /* decoder-side load for CLIENT_STATS: current queued work across
     * every stripe decoder plus the cumulative overload-drop count */
    function stats() {
      let queue = jpegQueue;
      for (const dec of h264Decoders.values()) {
        if (dec.state !== "closed") queue += dec.decodeQueueSize || 0;
      }
      return { queue, dropped: droppedDecodes };
    }

    return { push, reset, stats };
  }

  global.SelkiesStripeCore = { makeStripeDecoder, fidNewer,
                               OP_JPEG, OP_H264 };
})(typeof self !== "undefined" ? self : window);
