/* File upload: drag-drop -> chunked POST /api/upload with the
 * X-Upload-* resume protocol the server speaks (reference
 * lib/file-upload.js; server/core.py upload handler). */

export function bindUpload(cv, post) {
  const stop = (e) => { e.preventDefault(); e.stopPropagation(); };
  ["dragenter", "dragover"].forEach((ev) => cv.addEventListener(ev, stop));
  cv.addEventListener("drop", async (e) => {
    stop(e);
    const files = [...(e.dataTransfer ? e.dataTransfer.files : [])];
    for (const f of files) {
      try {
        await uploadFile(f, post);
        post({ type: "uploadDone", name: f.name });
      } catch (err) {
        post({ type: "uploadError", name: f.name, error: String(err) });
      }
    }
  });
}

export async function uploadFile(file, post, chunkBytes = 1 << 20) {
  for (let off = 0; off < file.size || off === 0; off += chunkBytes) {
    const chunk = file.slice(off, off + chunkBytes);
    const r = await fetch("/api/upload", {
      method: "POST",
      headers: {
        // headers are Latin-1 only: percent-encode, server decodes
        "X-Upload-Name": encodeURIComponent(file.name),
        "X-Upload-Offset": String(off),
        "X-Upload-Total": String(file.size),
      },
      body: chunk,
      credentials: "same-origin",
    });
    if (!r.ok) throw new Error(`upload ${file.name}: HTTP ${r.status}`);
    post({ type: "uploadProgress", name: file.name,
           sent: Math.min(off + chunkBytes, file.size),
           total: file.size });
    if (file.size === 0) break;
  }
}
