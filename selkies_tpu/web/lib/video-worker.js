/* Video decode worker: per-stripe decoders + frame composition OFF the
 * main thread (reference addons/selkies-web-core/selkies-ws-core.js
 * :4424-4460 per-stripe decoders + README "Video Rendering" worker /
 * track-generator pipeline; fresh code).
 *
 * Classic worker (module workers don't load everywhere workers do);
 * the wire-format decode logic is shared with the main-thread fallback
 * via lib/stripe-core.js (one copy, importScripts'd here).
 *
 * Modes (set by the 'init' message):
 *  - 'offscreen':  draw stripes straight into the page canvas
 *                  (transferControlToOffscreen); zero extra copies, the
 *                  compositor presents whatever was last drawn.
 *  - 'compose':    draw stripes into a local OffscreenCanvas, then emit
 *                  one VideoFrame per dirty tick into a
 *                  MediaStreamTrackGenerator writable the main thread
 *                  transferred in (Chrome zero-copy path).
 *  - 'composeTrackGen': same, but the worker creates a
 *                  VideoTrackGenerator (worker-native, Safari path) and
 *                  transfers its .track back to the main thread.
 *
 * In->out protocol: see lib/video.js WorkerVideoSink.
 */

"use strict";

importScripts("stripe-core.js");

let mode = null;
let canvas = null;          // OffscreenCanvas (page-linked or local)
let ctx = null;
let writer = null;          // WritableStreamDefaultWriter for VideoFrames
let fullcolor = false;
let width = 0, height = 0;

let drawnBatch = 0;                 // stripes drawn since last stats post
let dirty = false;
let emitScheduled = false;
let lastEmitFid = 0;
let lastAckFid = -1;

function post(msg, transfer) { self.postMessage(msg, transfer || []); }

const decoder = SelkiesStripeCore.makeStripeDecoder({
  draw: (img, y) => { ctx.drawImage(img, 0, y); scheduleEmit(); },
  onDrawn: () => {
    drawnBatch++;
    if (drawnBatch >= 8) { post({ type: "drawn", n: drawnBatch }); drawnBatch = 0; }
  },
  onAck: (fid) => {
    if (fid !== lastAckFid) { lastAckFid = fid; post({ type: "ack", fid }); }
  },
  onKeyframeNeeded: () => post({ type: "kf" }),
  onStatus: (msg) => post({ type: "err", msg }),
  fullcolor: () => fullcolor,
});

setInterval(() => {   // flush the stripe-stats remainder at low rates
  if (drawnBatch) { post({ type: "drawn", n: drawnBatch }); drawnBatch = 0; }
  // decoder load for CLIENT_STATS (queue depth, overload drops)
  post({ type: "cstats", stats: decoder.stats() });
}, 500);

/* ---------------------------------------------------------------- caps */
function caps() {
  return {
    type: "caps",
    videoDecoder: typeof VideoDecoder !== "undefined",
    trackGen: typeof VideoTrackGenerator !== "undefined",
    offscreen: typeof OffscreenCanvas !== "undefined",
  };
}

/* ---------------------------------------------------------------- emit */
function scheduleEmit() {
  dirty = true;
  if (emitScheduled || writer === null) return;
  emitScheduled = true;
  // rAF exists in dedicated workers on Chromium/Firefox; elsewhere a
  // 60 Hz timer gives the same coalescing
  if (typeof requestAnimationFrame === "function")
    requestAnimationFrame(emitFrame);
  else setTimeout(emitFrame, 16);
}

function emitFrame() {
  emitScheduled = false;
  if (!dirty || writer === null || canvas === null) return;
  dirty = false;
  let frame = null;
  try {
    frame = new VideoFrame(canvas, {
      timestamp: (lastEmitFid++) * 16667,
    });
    // drop rather than await when the sink applies backpressure: the
    // next dirty tick carries the newer content anyway. On rejection
    // (track ended, writable errored) the sink never took ownership —
    // close the frame or pool-backed frames leak until GC
    const f = frame;
    writer.write(f).catch(() => {
      try { f.close(); } catch (_e) { /* already closed */ }
    });
  } catch (e) {
    if (frame) try { frame.close(); } catch (_e) { /* closed */ }
  }
}

/* --------------------------------------------------------------- state */
function resize(w, h) {
  width = w; height = h;
  decoder.reset();
  if (canvas) {
    canvas.width = w; canvas.height = h;
    ctx = canvas.getContext("2d", { desynchronized: true });
  }
}

/* ------------------------------------------------------------- message */
self.onmessage = (e) => {
  const m = e.data;
  switch (m.type) {
    case "caps?":
      post(caps());
      break;
    case "init":
      mode = m.mode;
      fullcolor = !!m.fullcolor;
      width = m.width; height = m.height;
      if (m.canvas) canvas = m.canvas;                  // offscreen mode
      else canvas = new OffscreenCanvas(width || 2, height || 2);
      if (width) { canvas.width = width; canvas.height = height; }
      ctx = canvas.getContext("2d", { desynchronized: true });
      if (m.writable) writer = m.writable.getWriter();  // compose mode
      else if (mode === "composeTrackGen") {
        try {
          const gen = new VideoTrackGenerator();
          writer = gen.writable.getWriter();
          post({ type: "track", track: gen.track }, [gen.track]);
        } catch (err) {
          post({ type: "err", msg: "VideoTrackGenerator: " + err });
        }
      }
      break;
    case "stripe":
      decoder.push(new Uint8Array(m.buf));
      break;
    case "config":
      if (m.fullcolor !== undefined && m.fullcolor !== fullcolor) {
        fullcolor = !!m.fullcolor;
        decoder.reset();
      }
      break;
    case "resize":
      resize(m.width, m.height);
      break;
    case "reset":
      decoder.reset();
      break;
    default:
      break;
  }
};
