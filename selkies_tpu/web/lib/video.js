/* Video sinks: where decoded stripes become pixels.
 *
 * Preference order (reference selkies-web-core README "Video Rendering"):
 *  1. WorkerVideoSink — decode + composite in a worker; present through
 *     MediaStreamTrackGenerator (Chrome) or VideoTrackGenerator (Safari)
 *     into a <video>, or draw directly into the page canvas via
 *     transferControlToOffscreen. Main thread never touches pixels.
 *  2. CanvasVideoSink — main-thread WebCodecs/createImageBitmap into a
 *     2d canvas (works everywhere the codecs do).
 *
 * The wire-format decode logic itself lives ONCE in lib/stripe-core.js
 * (classic script: the worker importScripts it, index.html loads it for
 * this module's CanvasVideoSink).
 *
 * Sink interface: push(u8) / resize(w,h) / setFullcolor(b) / reset() /
 * close() / mode (string, for the HUD).
 *
 * hooks: onAck(fid), onKeyframeNeeded(), onStripeDrawn(n),
 *        onStatus(msg, isErr), attachVideo(stream) -> overlay sync.
 */

export function createVideoSink(canvas, hooks) {
  if (typeof Worker !== "undefined" && typeof OffscreenCanvas !== "undefined")
    return new WorkerVideoSink(canvas, hooks);
  return new CanvasVideoSink(canvas, hooks);
}

/* ------------------------------------------------------- worker-backed */
class WorkerVideoSink {
  constructor(canvas, hooks) {
    this.canvas = canvas;
    this.hooks = hooks;
    this.mode = "worker:negotiating";
    this.fullcolor = false;
    this.w = canvas.width; this.h = canvas.height;
    this._queue = [];              // stripes buffered during negotiation
    this._fallback = null;         // CanvasVideoSink if workers punt
    this._ready = false;
    try {
      this.worker = new Worker("lib/video-worker.js");
    } catch (e) {
      this._toFallback(`worker spawn failed: ${e}`);
      return;
    }
    this.worker.onerror = (e) => {
      if (!this._ready) this._toFallback(`worker error: ${e.message || e}`);
    };
    this.worker.onmessage = (e) => this._onMessage(e.data);
    this.worker.postMessage({ type: "caps?" });
    // negotiation deadline: a worker that never answers caps? (CSP, file
    // URL quirks) must not stall video forever
    this._capsTimer = setTimeout(
      () => this._toFallback("worker caps timeout"), 2000);
  }

  _onMessage(m) {
    switch (m.type) {
      case "caps": this._onCaps(m); break;
      case "ack": this.hooks.onAck(m.fid); break;
      case "drawn": this.hooks.onStripeDrawn(m.n); break;
      case "cstats": this._clientStats = m.stats; break;
      case "kf": this.hooks.onKeyframeNeeded(); break;
      case "track":
        this.hooks.attachVideo(new MediaStream([m.track]));
        break;
      case "err":
        this.hooks.onStatus(`video worker: ${m.msg}`, true);
        break;
      default: break;
    }
  }

  _onCaps(caps) {
    clearTimeout(this._capsTimer);
    if (this._fallback) return;                  // timeout already fired
    if (!caps.videoDecoder) {
      // no WebCodecs in the worker: H.264 must decode on main (or the
      // canvas sink surfaces the unsupported warning) — either way the
      // worker can't carry the session
      this._toFallback("no VideoDecoder in worker");
      return;
    }
    const init = { type: "init", width: this.w, height: this.h,
                   fullcolor: this.fullcolor };
    if (typeof MediaStreamTrackGenerator !== "undefined") {
      // Chrome zero-copy: generator on main, writable into the worker
      const gen = new MediaStreamTrackGenerator({ kind: "video" });
      init.mode = "compose";
      init.writable = gen.writable;
      this.worker.postMessage(init, [gen.writable]);
      this.hooks.attachVideo(new MediaStream([gen.track]));
      this.mode = "worker:trackgen";
    } else if (caps.trackGen) {
      // Safari: VideoTrackGenerator lives in the worker; the track
      // comes back in a 'track' message
      init.mode = "composeTrackGen";
      this.worker.postMessage(init);
      this.mode = "worker:trackgen-worker";
    } else if (this.canvas.transferControlToOffscreen) {
      const off = this.canvas.transferControlToOffscreen();
      init.mode = "offscreen";
      init.canvas = off;
      this.worker.postMessage(init, [off]);
      this.mode = "worker:offscreen";
      this._offscreen = true;
    } else {
      this._toFallback("no presentation path in worker");
      return;
    }
    this._ready = true;
    for (const buf of this._queue) this._post(buf);
    this._queue.length = 0;
  }

  _toFallback(why) {
    clearTimeout(this._capsTimer);
    if (this.worker) { try { this.worker.terminate(); } catch (_e) { /* */ } }
    this.worker = null;
    console.warn("video worker unavailable:", why);
    this._fallback = new CanvasVideoSink(this.canvas, this.hooks);
    this._fallback.setFullcolor(this.fullcolor);
    if (this.w && this.h) this._fallback.resize(this.w, this.h);
    this.mode = this._fallback.mode;
    for (const buf of this._queue) this._fallback.push(buf);
    this._queue.length = 0;
  }

  _post(u8) {
    // transfer, don't copy: stripes are fresh ArrayBuffers off the WS
    const buf = (u8.byteOffset === 0 &&
                 u8.byteLength === u8.buffer.byteLength)
      ? u8.buffer : u8.slice().buffer;
    this.worker.postMessage({ type: "stripe", buf }, [buf]);
  }

  push(u8) {
    if (this._fallback) { this._fallback.push(u8); return; }
    if (!this._ready) {
      if (this._queue.length < 128) this._queue.push(u8.slice());
      return;
    }
    this._post(u8);
  }

  resize(w, h) {
    this.w = w; this.h = h;
    if (this._fallback) { this._fallback.resize(w, h); return; }
    // in offscreen mode the worker owns canvas geometry; in compose
    // modes the page canvas is only the input overlay and the client
    // sizes it against the <video>
    if (this.worker) this.worker.postMessage({ type: "resize",
                                               width: w, height: h });
  }

  setFullcolor(b) {
    this.fullcolor = !!b;
    if (this._fallback) { this._fallback.setFullcolor(b); return; }
    if (this.worker) this.worker.postMessage({ type: "config",
                                               fullcolor: this.fullcolor });
  }

  reset() {
    if (this._fallback) { this._fallback.reset(); return; }
    if (this.worker) this.worker.postMessage({ type: "reset" });
  }

  /* last decoder-load report from the worker (pushed every 500 ms);
   * null until the first report lands */
  clientStats() {
    if (this._fallback) return this._fallback.clientStats();
    return this._clientStats || null;
  }

  close() {
    if (this._fallback) { this._fallback.close(); return; }
    if (this.worker) { try { this.worker.terminate(); } catch (_e) { /* */ } }
    this.worker = null;
  }
}

/* ------------------------------------------------------- canvas-backed
 * Main-thread fallback: wraps the same stripe-core decoder the worker
 * uses, drawing into the visible canvas. */
export class CanvasVideoSink {
  constructor(canvas, hooks) {
    this.canvas = canvas;
    this.hooks = hooks;
    this.ctx = canvas.getContext("2d", { desynchronized: true });
    this.mode = "canvas";
    this.fullcolor = false;
    this._core = window.SelkiesStripeCore.makeStripeDecoder({
      draw: (img, y) => this.ctx.drawImage(img, 0, y),
      onDrawn: () => this.hooks.onStripeDrawn(1),
      onAck: (fid) => this.hooks.onAck(fid),
      onKeyframeNeeded: () => this.hooks.onKeyframeNeeded(),
      onStatus: (msg) => this.hooks.onStatus(msg, true),
      fullcolor: () => this.fullcolor,
    });
  }

  push(u8) { this._core.push(u8); }

  resize(w, h) {
    this.canvas.width = w;
    this.canvas.height = h;
    this.ctx = this.canvas.getContext("2d", { desynchronized: true });
    this._core.reset();
  }

  setFullcolor(b) {
    if (this.fullcolor !== !!b) {
      this.fullcolor = !!b;
      this._core.reset();
    }
  }

  reset() { this._core.reset(); }

  clientStats() { return this._core.stats(); }

  close() { this._core.reset(); }
}
