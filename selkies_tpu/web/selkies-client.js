/* Selkies-TPU web client: WS transport + WebRTC transport.
 *
 * Fresh implementation of the reference client's protocol surface
 * (reference addons/selkies-web-core/selkies-ws-core.js:4255-4460 binary
 * demux, selkies-wr-core.js + lib/signaling.js RTC path, lib/input.js
 * keysym capture; SURVEY.md §2.3):
 *
 *   WS:  server -> client binary: 0x01 audio (+RED), 0x03 JPEG stripe,
 *                                 0x04 H.264 stripe, 0x05 gzip'd control
 *        client -> server binary: 0x02 mic PCM, 0x05 gzip'd control text
 *        text verbs: kd/ku/kr/kh, m/m2/mb/ms/p, r, s, vb/ab, SETTINGS,
 *               CLIENT_FRAME_ACK, START/STOP_VIDEO, START/STOP_AUDIO,
 *               REQUEST_KEYFRAME, _gz, _f/_l, cw*
 *   RTC: /api/signaling WS (HELLO / SESSION / JSON SDP relay, reference
 *        signaling_server.py protocol) -> RTCPeerConnection answering the
 *        server's ICE-lite offer; media arrives as real tracks on a
 *        <video> sink; input rides an ordered "input" data channel
 *        speaking the SAME text-verb grammar as the WS transport.
 *
 * Modules: lib/video.js (worker decode + track-generator sinks),
 * lib/audio.js (playback + mic), lib/input.js (all input capture),
 * lib/keysyms.js, lib/upload.js, lib/protocol.js. This file owns the
 * transports, control-verb dispatch, and the dashboard postMessage API. */

"use strict";

import { detectKeyboardLayout } from "./lib/keysyms.js";
import { OP_AUDIO, OP_JPEG, OP_H264, OP_GZ } from "./lib/protocol.js";
import { AudioPlayer, MicSender } from "./lib/audio.js";
import { InputManager } from "./lib/input.js";
import { createVideoSink } from "./lib/video.js";
import { bindUpload } from "./lib/upload.js";

/* ------------------------------------------------------------------ client */
class SelkiesClient {
  constructor(canvas, hud) {
    this.canvas = canvas;
    this.hud = hud;
    this.ws = null;
    this.gz = false;
    this.serverSettings = null;
    this.displayW = 0; this.displayH = 0;
    this.videoActive = false;
    this.lastAckFid = -1;
    this.framesDrawn = 0;
    this.stripesDrawn = 0;
    this.everDrawn = false;
    this.videoStartedAt = 0;
    this.lastStatsT = performance.now();
    this.audio = null;                // AudioPlayer
    this.reconnectDelay = 500;
    this.statusMsg = "connecting…";
    this.killed = false;
    this.rtcMode = false;             // true once the RTC transport owns IO
    this.pc = null;                   // RTCPeerConnection
    this.dc = null;                   // "input" data channel
    this.sigWs = null;                // signaling WebSocket
    this.videoEl = null;              // <video> sink (RTC or track-gen)

    this.input = new InputManager(canvas, {
      send: (t) => this.send(t),
      size: () => [this.displayW || canvas.width || 1,
                   this.displayH || canvas.height || 1],
    });
    this.sink = null;   // built lazily: RTC sessions never need one
    bindUpload(canvas, (m) => this._post(m));
    window.addEventListener("message", (e) => this._onDashboardMessage(e));
    this._bindResize();
    document.addEventListener("visibilitychange", () => {
      if (!this.ws || this.ws.readyState !== WebSocket.OPEN) return;
      if (document.hidden) this.send("STOP_VIDEO");
      else { this.send("START_VIDEO"); this.send("REQUEST_KEYFRAME"); }
    });
    this._statsTimer = setInterval(() => this._reportStats(), 2000);
    this._hbTimer = setInterval(() => this.input.heartbeat(), 500);
    /* glass-to-glass timing plane: NTP-style clock pings (the server
     * runs the offset/drift estimator) + per-frame receive/decode/
     * present timestamps batched into CLIENT_FRAME_TIMING */
    this._clockSeq = 0;
    this._frameTiming = new Map();    // fid -> {recv, decode}
    this._timingQueue = [];
    this._timingLastFlush = 0;
    this._clockTimer = setInterval(() => this._clockPing(), 2000);
    this._sendLayout();
  }

  async _sendLayout() {
    const layout = await detectKeyboardLayout();
    this._kbLayout = layout;
    const sendIt = () => this.send(
      `SETTINGS,${JSON.stringify({ keyboard_layout: layout })}`);
    if (this.ws && this.ws.readyState === WebSocket.OPEN) sendIt();
    else this._pendingLayout = sendIt;
  }

  /* ------------------------------------------------------------ transport */
  async start() {
    // pick the transport the server is actually running (/api/status.mode)
    let mode = "websockets";
    try {
      const r = await fetch("/api/status", { credentials: "same-origin" });
      if (r.ok) mode = (await r.json()).mode || mode;
    } catch (_e) { /* status unreachable: default to WS */ }
    if (mode === "webrtc" && typeof RTCPeerConnection !== "undefined")
      this.connectRTC();
    else this.connect();
  }

  connect() {
    const proto = location.protocol === "https:" ? "wss:" : "ws:";
    // a fleet migration (the migrate,{json} control verb) overrides the
    // target: reconnect to the NEW gateway carrying the fleet_sid
    // affinity key so the session-affine proxy routes us to the
    // re-placed seat
    const url = this._migrateUrl ||
      `${proto}//${location.host}/api/websockets`;
    this.status(`connecting to ${url}`);
    const ws = new WebSocket(url);
    ws.binaryType = "arraybuffer";
    this.ws = ws;
    ws.onopen = () => {
      this.reconnectDelay = 500;
      this.send("_gz,1");
      this.gz = true;
      this._clockPing();      // first sync sample without the 2 s wait
      if (this._pendingLayout) {
        this._pendingLayout();
        this._pendingLayout = null;
      }
      if (this._migrateResync) {
        // the target host answers the fresh START_VIDEO with an IDR
        // anyway; the explicit request covers resync-after-reconnect
        // races (a stripe already in flight from the old GOP)
        this._migrateResync = false;
        this.send("REQUEST_KEYFRAME");
      }
    };
    ws.onmessage = (ev) => {
      if (typeof ev.data === "string") this._onText(ev.data);
      else this._onBinary(new Uint8Array(ev.data));
    };
    ws.onclose = () => {
      this.videoActive = false;
      if (this.killed) return;
      this.status(`disconnected — retrying in ${this.reconnectDelay} ms`, true);
      setTimeout(() => this.connect(), this.reconnectDelay);
      this.reconnectDelay = Math.min(this.reconnectDelay * 2, 10000);
    };
  }

  send(text) {
    if (this.rtcMode) {
      if (this.dc && this.dc.readyState === "open") this.dc.send(text);
      return;
    }
    if (this.ws && this.ws.readyState === WebSocket.OPEN) this.ws.send(text);
  }

  sendBytes(u8) {
    /* binary frames (0x02 mic) ride the WS transport only; the SCTP
     * data channel carries the text verb grammar */
    if (!this.rtcMode && this.ws && this.ws.readyState === WebSocket.OPEN)
      this.ws.send(u8);
  }

  /* --------------------------------------------------------- RTC transport
   * Signaling protocol (server signaling.py): HELLO client {meta} ->
   * "SESSION server" -> SESSION_OK <uid> -> the server peer sends
   * {"sdp":{"type":"offer",...}}; we answer. Media flows on ICE-lite host
   * candidates; the browser opens the "input" data channel (DCEP). */
  connectRTC() {
    this.rtcMode = true;
    const proto = location.protocol === "https:" ? "wss:" : "ws:";
    const url = `${proto}//${location.host}/api/signaling`;
    this.status(`rtc signaling: ${url}`);
    const ws = new WebSocket(url);
    this.sigWs = ws;
    const params = new URLSearchParams(location.search);
    ws.onopen = () => {
      this.reconnectDelay = 500;
      ws.send("HELLO client " + JSON.stringify({
        client_type: params.get("view_only") ? "viewer" : "controller",
        display_id: params.get("display") || "primary",
      }));
    };
    ws.onmessage = (ev) => this._onSignal(String(ev.data));
    ws.onclose = () => {
      if (this.killed) return;
      this._rtcTeardown();
      this.status(`signaling lost — retrying in ${this.reconnectDelay} ms`, true);
      setTimeout(() => this.connectRTC(), this.reconnectDelay);
      this.reconnectDelay = Math.min(this.reconnectDelay * 2, 10000);
    };
  }

  async _onSignal(text) {
    if (text === "HELLO") { this.sigWs.send("SESSION server"); return; }
    if (text.startsWith("SESSION_OK")) { this.status("rtc: waiting for offer"); return; }
    if (text.startsWith("SESSION_END")) { this._rtcTeardown(); return; }
    if (text.startsWith("ERROR")) { this.status(`rtc: ${text}`, true); return; }
    let msg;
    try { msg = JSON.parse(text); } catch { return; }
    if (msg.sdp && msg.sdp.type === "offer") await this._onRtcOffer(msg.sdp);
  }

  async _onRtcOffer(offer) {
    this._rtcTeardown();
    this._lastOfferSdp = offer.sdp;
    let iceServers = (this.rtcConfig && this.rtcConfig.iceServers) || [];
    if (!iceServers.length) {
      try {
        const r = await fetch("/api/turn", { credentials: "same-origin" });
        if (r.ok) iceServers = (await r.json()).iceServers || [];
      } catch (_e) { /* host-candidate-only is fine on a LAN */ }
    }
    const pc = new RTCPeerConnection({ iceServers });
    this.pc = pc;
    pc.ontrack = (e) => {
      if (e.track.kind === "video") this._attachVideo(e.streams[0] ||
        new MediaStream([e.track]));
      else if (this.videoEl) this.videoEl.muted = false;
    };
    pc.onconnectionstatechange = () => {
      if (pc.connectionState === "connected")
        this.status("webrtc connected");
      else if (pc.connectionState === "failed") {
        this.status("webrtc failed — renegotiating", true);
        try { this.sigWs.send("SESSION_END"); } catch (_e) { /* gone */ }
        this._rtcTeardown();
        setTimeout(() => { try { this.sigWs.send("SESSION server"); } catch (_e) { /* retried on reconnect */ } }, 1000);
      }
    };
    const dc = pc.createDataChannel("input", { ordered: true });
    this.dc = dc;
    dc.onopen = () => {
      this.status("webrtc connected · input channel open");
      this._sendPreferredSize();
    };
    dc.onmessage = (ev) => {
      if (typeof ev.data === "string") this._onText(ev.data);
    };
    await pc.setRemoteDescription(offer);
    // sendrecv audio m-line + mic requested: attach the mic track so the
    // answer carries it (server decodes into its virtual-mic graph)
    if (this._micWanted && /m=audio[^]*?a=sendrecv/.test(offer.sdp)) {
      try {
        const ms = await navigator.mediaDevices.getUserMedia({
          audio: { channelCount: 1, echoCancellation: true },
        });
        const tx = pc.getTransceivers().find(
          (t) => t.receiver && t.receiver.track &&
                 t.receiver.track.kind === "audio");
        if (tx) {
          await tx.sender.replaceTrack(ms.getAudioTracks()[0]);
          tx.direction = "sendrecv";
          this._micStream = ms;
          this._postToDashboard({ type: "microphone", active: true });
        } else ms.getTracks().forEach((t) => t.stop());
      } catch (e) {
        this.status(`microphone unavailable: ${e.message || e}`, true);
      }
    }
    const answer = await pc.createAnswer();
    await pc.setLocalDescription(answer);
    // ICE-lite server: no trickle needed; ship the answer as-is (the
    // browser probes the offer's host candidate directly)
    this.sigWs.send(JSON.stringify({ sdp: {
      type: answer.type, sdp: pc.localDescription.sdp } }));
  }

  /* -------------------------------------------------------- <video> sink
   * Shared by the RTC transport (real tracks) and the worker sink's
   * track-generator path: the canvas floats transparently above the
   * video as the input-capture surface. */
  _attachVideo(stream) {
    if (!this.videoEl) {
      const v = document.createElement("video");
      v.autoplay = true; v.playsInline = true; v.muted = true;
      v.style.cssText =
        "max-width:100%;max-height:100%;background:#000;outline:none";
      // canvas stays on top (transparent, input-capturing); video below
      this.canvas.parentElement.insertBefore(v, this.canvas);
      this.canvas.style.position = "absolute";
      this.canvas.style.background = "transparent";
      this.videoEl = v;
      v.addEventListener("resize", () => this._syncOverlay());
    }
    this.videoEl.srcObject = stream;
    this.videoEl.play().catch(() => { /* needs a user gesture; autoplay muted */ });
    this._syncOverlay();
  }

  /* size the input-capturing canvas exactly over the displayed video; in
   * RTC mode the stream size is also the authoritative display size
   * (no server_settings push there) */
  _syncOverlay() {
    const v = this.videoEl;
    if (!v || !v.videoWidth) return;
    if (this.rtcMode) {
      this.displayW = v.videoWidth; this.displayH = v.videoHeight;
      document.title = `Selkies TPU — ${v.videoWidth}x${v.videoHeight}`;
    }
    const r = v.getBoundingClientRect();
    this.canvas.style.left = `${r.left}px`;
    this.canvas.style.top = `${r.top}px`;
    this.canvas.style.width = `${r.width}px`;
    this.canvas.style.height = `${r.height}px`;
  }

  _rtcTeardown() {
    if (this.dc) { try { this.dc.close(); } catch (_e) { /* closed */ } this.dc = null; }
    if (this.pc) { try { this.pc.close(); } catch (_e) { /* closed */ } this.pc = null; }
    if (this.videoEl) this.videoEl.srcObject = null;
  }

  async sendMaybeGz(text) {
    // 0x05-frame large control messages (server inflates, bounded)
    if (this.gz && text.length > 512 && typeof CompressionStream !== "undefined") {
      const stream = new Blob([text]).stream()
        .pipeThrough(new CompressionStream("gzip"));
      const packed = new Uint8Array(await new Response(stream).arrayBuffer());
      const framed = new Uint8Array(packed.length + 1);
      framed[0] = OP_GZ; framed.set(packed, 1);
      this.ws.send(framed);
    } else this.send(text);
  }

  /* -------------------------------------------------------------- binary */
  /* Lazy: stripes only arrive on the WS transport, so RTC sessions never
   * spawn a decode worker whose track-generator attachVideo could race
   * the real RTC stream on the shared <video>. */
  _ensureSink() {
    if (!this.sink) {
      this.sink = createVideoSink(this.canvas, {
        onAck: (fid) => this._ackFrame(fid),
        onStripeDrawn: (n) => { this.stripesDrawn += n; this.everDrawn = true; },
        onKeyframeNeeded: () => this._requestKeyframeThrottled(),
        onStatus: (m, isErr) => this.status(m, isErr),
        attachVideo: (stream) => {
          if (!this.rtcMode) this._attachVideo(stream);
        },
      });
    }
    return this.sink;
  }

  _onBinary(buf) {
    switch (buf[0]) {
      case OP_JPEG:
      case OP_H264:
        if (!this.rtcMode) {
          this._noteFrameReceived(buf);
          this._ensureSink().push(buf);
        }
        break;
      case OP_AUDIO: if (this.audio) this.audio.push(buf); break;
      case OP_GZ: this._onGzControl(buf); break;
    }
  }

  /* --------------------------------------------- glass-to-glass timing
   * Three client-side timestamps per frame, all performance.now():
   * receive (first stripe off the wire), decode-complete (the sink's
   * ack — every stripe decoded+drawn), present (requestVideoFrameCallback
   * when a <video> sink carries the session, else the next rAF).
   * Batched as CLIENT_FRAME_TIMING fid:recv:decode:present;... and
   * mapped onto the server timebase by the CLIENT_CLOCK estimator. */
  _clockPing() {
    if (this.rtcMode || !this.ws || this.ws.readyState !== WebSocket.OPEN)
      return;
    this.send(`CLIENT_CLOCK ping,${++this._clockSeq},` +
              performance.now().toFixed(3));
  }

  _noteFrameReceived(buf) {
    const fid = (buf[2] << 8) | buf[3];   // u16 frame_id, both headers
    if (this._frameTiming.has(fid)) return;   // later stripe, same frame
    if (this._frameTiming.size > 128) {       // never-acked backlog
      this._frameTiming.delete(this._frameTiming.keys().next().value);
    }
    this._frameTiming.set(fid, { recv: performance.now() });
  }

  _noteFrameDecoded(fid) {
    const e = this._frameTiming.get(fid);
    if (!e || e.decode !== undefined) return;
    e.decode = performance.now();
    const finish = (t) => this._noteFramePresented(fid, t);
    const v = this.videoEl;
    if (v && typeof v.requestVideoFrameCallback === "function")
      v.requestVideoFrameCallback((now) => finish(now));
    else if (typeof requestAnimationFrame === "function")
      requestAnimationFrame((t) => finish(t));
    else finish(performance.now());
  }

  _noteFramePresented(fid, t) {
    const e = this._frameTiming.get(fid);
    if (!e || e.decode === undefined) return;
    this._frameTiming.delete(fid);
    const present = Math.max(t || performance.now(), e.decode);
    this._timingQueue.push(`${fid}:${e.recv.toFixed(2)}:` +
                           `${e.decode.toFixed(2)}:${present.toFixed(2)}`);
    const now = performance.now();
    if (this._timingQueue.length >= 16 ||
        now - this._timingLastFlush > 250) this._flushTiming(now);
  }

  _flushTiming(now) {
    if (this.rtcMode) { this._timingQueue.length = 0; return; }
    if (!this._timingQueue.length) return;
    this._timingLastFlush = now;
    this.send(`CLIENT_FRAME_TIMING ${this._timingQueue.join(";")}`);
    this._timingQueue.length = 0;
  }

  async _onGzControl(buf) {
    if (typeof DecompressionStream === "undefined") return;
    const stream = new Blob([buf.subarray(1)]).stream()
      .pipeThrough(new DecompressionStream("gzip"));
    this._onText(await new Response(stream).text());
  }

  _ackFrame(fid) {
    if (fid !== this.lastAckFid) {
      this.lastAckFid = fid;
      this.framesDrawn++;
      this.send(`CLIENT_FRAME_ACK ${fid}`);
      this._noteFrameDecoded(fid);
    }
  }

  _requestKeyframeThrottled() {
    const now = performance.now();
    if (!this._lastKfReq || now - this._lastKfReq > 1000) {
      this._lastKfReq = now;
      this.send("REQUEST_KEYFRAME");
    }
  }

  /* ---------------------------------------------------------------- text */
  _onText(text) {
    const sp = text.indexOf(" "), cm = text.indexOf(",");
    const cut = Math.min(sp < 0 ? text.length : sp, cm < 0 ? text.length : cm);
    const verb = text.slice(0, cut), rest = text.slice(cut + 1);
    switch (verb) {
      case "MODE": break;
      case "server_clock": {
        // echo the 4th timestamp back; the server owns estimation
        this.send(`CLIENT_CLOCK sample,${rest},` +
                  performance.now().toFixed(3));
        break;
      }
      case "server_settings": this._applyServerSettings(rest); break;
      case "system_stats": this._showStats(rest); break;
      case "gpu_stats": this._showGpuStats(rest); break;
      case "cursor": this._applyCursor(rest); break;
      case "VIDEO_STARTED":
        this.videoActive = true;
        this.videoStartedAt = performance.now();
        break;
      case "VIDEO_STOPPED": this.videoActive = false; break;
      case "AUDIO_DISABLED": if (this.audio) { this.audio.close(); this.audio = null; } break;
      case "MICROPHONE_DISABLED":
        this.stopMic();
        this.status("microphone disabled by server", true);
        break;
      case "settings_applied": break;
      case "clipboard": this._applyClipboard(rest); break;
      case "system_msg": this.status(rest); break;
      case "rtc_config":
        // pushed by the server's RTC-config-file watchdog: preferred
        // over /api/turn on the next RTC (re)negotiation
        try { this.rtcConfig = JSON.parse(rest); } catch { /* ignore */ }
        break;
      case "migrate": this._onMigrate(rest); break;
      case "KILL":
        this.killed = true;
        this.status("session terminated by server", true);
        this.ws.close();
        break;
      default: break;
    }
    this._postToDashboard({ type: "serverMessage", verb, payload: rest });
  }

  /* Fleet migration (fleet/protocol.migrate_command): the draining
   * host tells us to reconnect elsewhere. Payload {url, sid, resync}:
   * rebuild the WS URL against the new gateway with ?fleet_sid=<sid>
   * (the affinity key its session-affine proxy routes on), close the
   * socket, and let the normal reconnect loop carry us over — the
   * capture stays warm inside the reconnect grace, and resync asks for
   * an IDR so the decoder never sees a mid-GOP seam. */
  _onMigrate(json) {
    let m;
    try { m = JSON.parse(json); } catch { return; }
    if (!m || typeof m.url !== "string") return;
    let u;
    try {
      u = new URL("/api/websockets", new URL(m.url, location.href));
    } catch { return; }
    u.protocol = (u.protocol === "https:" || u.protocol === "wss:")
      ? "wss:" : "ws:";
    if (m.sid) u.searchParams.set("fleet_sid", String(m.sid));
    this._migrateUrl = u.toString();
    this._migrateResync = m.resync !== false;
    this.status(`migrating to ${u.host}…`, true);
    this.reconnectDelay = 500;
    if (this.ws) {
      try { this.ws.close(); } catch (_e) { /* already closing */ }
    }
  }

  _applyServerSettings(json) {
    let payload;
    try { payload = JSON.parse(json); } catch { return; }
    this.serverSettings = payload;
    const st = payload.settings || {};
    this._ensureSink().setFullcolor(!!(st.fullcolor && st.fullcolor.value));
    const d = (payload.displays && payload.displays[0]) || {};
    if (d.width && (d.width !== this.displayW || d.height !== this.displayH)) {
      this.displayW = d.width; this.displayH = d.height;
      this.sink.resize(d.width, d.height);
      this.send("REQUEST_KEYFRAME");
    }
    document.title = `${payload.app_name || "Selkies TPU"} — ${d.width}x${d.height}`;
    if (!this.videoActive) {
      this.send("START_VIDEO");
      this.videoStartedAt = performance.now();
      if (payload.features && payload.features.audio) {
        if (!this.audio) this.audio = new AudioPlayer(payload);
        this.send("START_AUDIO");
      }
      this._sendPreferredSize();
    }
    this.status(`${d.width}x${d.height} · ` +
      `${(payload.settings?.framerate?.value ?? "?")} fps target`);
    this._postToDashboard({ type: "serverSettings", payload });
  }

  _applyCursor(json) {
    try {
      const c = JSON.parse(json);
      if (c.png_b64) {
        this.canvas.style.cursor =
          `url(data:image/png;base64,${c.png_b64}) ${c.xhot || 0} ${c.yhot || 0}, default`;
      } else if (c.visible === false) this.canvas.style.cursor = "none";
      else this.canvas.style.cursor = "default";
    } catch { /* tolerate malformed cursor payloads */ }
  }

  async _applyClipboard(b64) {
    try {
      const text = atob(b64);
      if (navigator.clipboard && document.hasFocus())
        await navigator.clipboard.writeText(text);
    } catch { /* clipboard permission denied: ignore */ }
  }

  _showStats(json) {
    try {
      const s = JSON.parse(json);
      const enc = Object.entries(s.encoded_fps || {})
        .map(([d, f]) => `${d}:${f.toFixed(0)}`).join(" ");
      this.status(
        `${this.displayW}x${this.displayH} · encode ${enc} fps · ` +
        `draw ${this._drawFps.toFixed(0)} fps · ` +
        `sink ${this.sink ? this.sink.mode : "rtc"} · cpu ${s.cpu_percent}%`);
      this._postToDashboard({ type: "systemStats", payload: s });
    } catch { /* ignore */ }
  }

  _showGpuStats(json) {
    try {
      this._postToDashboard({ type: "gpuStats",
                              payload: JSON.parse(json) });
    } catch { /* ignore */ }
  }

  /* --------------------------------------------------------------- stats */
  get _drawFps() { return this.__drawFps || 0; }

  _reportStats() {
    const now = performance.now();
    const dt = (now - this.lastStatsT) / 1000;
    this.__drawFps = this.framesDrawn / Math.max(dt, 1e-3);
    this.framesDrawn = 0;
    this.lastStatsT = now;
    this._flushTiming(now);       // timing remainder at low frame rates
    if (this.videoActive) {
      this.send(`_f,${this.__drawFps.toFixed(1)}`);
      if (!this.rtcMode && this.sink && this.sink.clientStats) {
        // decoder-side load: the server's client-overload signal
        const cs = this.sink.clientStats();
        if (cs) this.send(`CLIENT_STATS ${JSON.stringify({
          decode_queue: cs.queue | 0,
          dropped_decodes: cs.dropped | 0,
          draw_fps: +this.__drawFps.toFixed(1),
        })}`);
      }
      // cold-start UX: the first TPU compile of a new geometry can take
      // minutes — say so instead of leaving a silent black screen
      if (!this.everDrawn && this.videoStartedAt &&
          now - this.videoStartedAt > 3000 && !this.rtcMode) {
        const s = Math.round((now - this.videoStartedAt) / 1000);
        this.status(`server is compiling the encoder for this geometry ` +
                    `(first run can take minutes)… ${s}s`);
      }
    }
  }

  /* -------------------------------------------------------------- resize */
  _bindResize() {
    let timer = null;
    window.addEventListener("resize", () => {
      if (this.videoEl)                        // keep the overlay aligned
        requestAnimationFrame(() => this._syncOverlay());
      clearTimeout(timer);
      timer = setTimeout(() => this._sendPreferredSize(), 500);
    });
  }

  _sendPreferredSize() {
    const s = this.serverSettings;
    // RTC mode gets no server_settings push; the server gates 'r' on its
    // own enable_resize setting, so always offer the preferred size there
    if (!this.rtcMode && (!s || !s.features || !s.features.resize)) return;
    const dpr = window.devicePixelRatio || 1;
    const w = Math.round(window.innerWidth * dpr / 2) * 2;
    const h = Math.round(window.innerHeight * dpr / 2) * 2;
    if (w !== this.displayW || h !== this.displayH) this.send(`r,${w}x${h}`);
  }

  /* --------------------------------------------- dashboard postMessage API
   * Same-origin embedding surface mirroring the reference dashboard
   * protocol (reference addons/selkies-web-core/README.md:49-200). */
  _postToDashboard(msg) {
    if (window.parent !== window)
      window.parent.postMessage({ selkies: true, ...msg }, location.origin);
  }

  _post(msg) {
    try {
      (window.parent || window).postMessage(
        Object.assign({ scope: "selkies" }, msg), "*");
    } catch (_e) { /* sandboxed parent */ }
  }

  _onDashboardMessage(e) {
    if (e.origin !== location.origin || !e.data || e.data.selkies !== true)
      return;
    const d = e.data;
    switch (d.type) {
      case "settings":
        this.sendMaybeGz(`SETTINGS,${JSON.stringify(d.settings || {})}`);
        break;
      case "pipelineControl":
        if (d.video === false) this.send("STOP_VIDEO");
        if (d.video === true) this.send("START_VIDEO");
        if (d.audio === false) this.send("STOP_AUDIO");
        if (d.audio === true) this.send("START_AUDIO");
        if (d.microphone === true) this.startMic();
        if (d.microphone === false) this.stopMic();
        if (d.keyframe) this.send("REQUEST_KEYFRAME");
        break;
      case "getStats":
        this._postToDashboard({
          type: "stats",
          payload: { drawFps: this._drawFps,
                     sink: this.sink ? this.sink.mode : "rtc",
                     display: [this.displayW, this.displayH] },
        });
        break;
      case "videoBitrate": this.send(`vb,${d.kbps | 0}`); break;
      case "audioBitrate": this.send(`ab,${d.bps | 0}`); break;
      case "toggleOsk": this.input.toggleOnScreenKeyboard(); break;
      case "touchMode":
        this.input.touchMode = d.mode === "trackpad" ? "trackpad" : "direct";
        break;
      case "clipboard":
        if (typeof d.text === "string")
          this.send(`cw,${btoa(unescape(encodeURIComponent(d.text)))}`);
        break;
      default: break;
    }
  }

  /* ------------------------------------------------------------ microphone
   * getUserMedia -> AudioWorklet -> s16 24 kHz mono 0x02 frames (the
   * server plays them into the SelkiesVirtualMic graph so desktop apps
   * can record — reference selkies-ws-core.js:5685 / selkies.py:229). */
  async startMic() {
    if (this.mic) return;
    if (this.rtcMode) {
      /* RTC transport: the mic rides the sendrecv audio m-line, which
       * needs a renegotiation so the answer can carry the track */
      if (this._micStream) return;           // already attached
      if (this._lastOfferSdp &&
          !/m=audio[^]*?a=sendrecv/.test(this._lastOfferSdp)) {
        // server offered sendonly (mic disabled there): restarting the
        // session would interrupt video for nothing, forever
        this.status("microphone disabled by server", true);
        return;
      }
      this._micWanted = true;
      this.status("microphone: renegotiating webrtc session");
      try {
        this.sigWs.send("SESSION_END");
        this._rtcTeardown();
        this.sigWs.send("SESSION server");
      } catch (_e) { /* retried on signaling reconnect */ }
      return;
    }
    const feats = this.serverSettings && this.serverSettings.features;
    if (!feats || !feats.microphone) {
      this.status("microphone disabled by server", true);
      return;
    }
    const mic = new MicSender((u8) => this.sendBytes(u8));
    try {
      await mic.start();
      this.mic = mic;
      this.status("microphone forwarding on");
      this._postToDashboard({ type: "microphone", active: true });
    } catch (e) {
      mic.stop();     // release any tracks/context acquired before the throw
      this.status(`microphone unavailable: ${e.message || e}`, true);
    }
  }

  stopMic() {
    if (this.rtcMode) {
      this._micWanted = false;
      if (this._micStream) {
        this._micStream.getTracks().forEach((t) => t.stop());
        this._micStream = null;
        this._postToDashboard({ type: "microphone", active: false });
      }
      return;
    }
    if (!this.mic) return;
    this.mic.stop();
    this.mic = null;
    this._postToDashboard({ type: "microphone", active: false });
  }

  /* ----------------------------------------------------------------- hud */
  status(msg, isErr = false) {
    this.statusMsg = msg;
    if (this.hud) {
      this.hud.innerHTML = "";
      const span = document.createElement("span");
      span.className = isErr ? "err" : "";
      span.textContent = msg;
      this.hud.appendChild(span);
    }
  }
}

/* ------------------------------------------------------------------ boot */
const canvas = document.getElementById("screen");
const hud = document.getElementById("hud");
const badge = document.getElementById("badge");
const client = new SelkiesClient(canvas, document.getElementById("status"));
badge.addEventListener("click", () => hud.classList.toggle("hidden"));
hud.classList.remove("hidden");
canvas.focus();
client.start();            // picks WS or WebRTC from /api/status
window.selkies = client;   // console / dashboard access
