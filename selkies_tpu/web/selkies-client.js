/* Selkies-TPU web client: WS transport + WebRTC transport.
 *
 * Fresh implementation of the reference client's protocol surface
 * (reference addons/selkies-web-core/selkies-ws-core.js:4255-4460 binary
 * demux, selkies-wr-core.js + lib/signaling.js RTC path, lib/input.js
 * keysym capture; SURVEY.md §2.3):
 *
 *   WS:  server -> client binary: 0x01 audio (+RED), 0x03 JPEG stripe,
 *                                 0x04 H.264 stripe, 0x05 gzip'd control
 *        client -> server binary: 0x02 mic PCM, 0x05 gzip'd control text
 *        text verbs: kd/ku/kr/kh, m/m2/mb/ms/p, r, s, vb/ab, SETTINGS,
 *               CLIENT_FRAME_ACK, START/STOP_VIDEO, START/STOP_AUDIO,
 *               REQUEST_KEYFRAME, _gz, _f/_l, cw*
 *   RTC: /api/signaling WS (HELLO / SESSION / JSON SDP relay, reference
 *        signaling_server.py protocol) -> RTCPeerConnection answering the
 *        server's ICE-lite offer; media arrives as real tracks on a
 *        <video> sink; input rides an ordered "input" data channel
 *        speaking the SAME text-verb grammar as the WS transport. */

"use strict";

/* ------------------------------------------------------------------ keysyms
 * X11 keysym mapping. Printable ASCII/Latin-1 map to their codepoint;
 * other Unicode maps to 0x01000000 + codepoint (X11 convention); special
 * keys use the table below (keysymdef.h values, same table the reference
 * client carries in lib/input.js KeyTable). */
const KEYSYM_SPECIAL = {
  Backspace: 0xFF08, Tab: 0xFF09, Enter: 0xFF0D, Pause: 0xFF13,
  ScrollLock: 0xFF14, Escape: 0xFF1B, Home: 0xFF50, ArrowLeft: 0xFF51,
  ArrowUp: 0xFF52, ArrowRight: 0xFF53, ArrowDown: 0xFF54, PageUp: 0xFF55,
  PageDown: 0xFF56, End: 0xFF57, Insert: 0xFF63, Menu: 0xFF67,
  ContextMenu: 0xFF67, NumLock: 0xFF7F, F1: 0xFFBE, F2: 0xFFBF, F3: 0xFFC0,
  F4: 0xFFC1, F5: 0xFFC2, F6: 0xFFC3, F7: 0xFFC4, F8: 0xFFC5, F9: 0xFFC6,
  F10: 0xFFC7, F11: 0xFFC8, F12: 0xFFC9, Delete: 0xFFFF,
  CapsLock: 0xFFE5, PrintScreen: 0xFF61,
};
const KEYSYM_BY_CODE = {           // location-dependent keys need e.code
  ShiftLeft: 0xFFE1, ShiftRight: 0xFFE2, ControlLeft: 0xFFE3,
  ControlRight: 0xFFE4, AltLeft: 0xFFE9, AltRight: 0xFFEA,
  MetaLeft: 0xFFEB, MetaRight: 0xFFEC,
  NumpadEnter: 0xFF8D, NumpadMultiply: 0xFFAA, NumpadAdd: 0xFFAB,
  NumpadSubtract: 0xFFAD, NumpadDecimal: 0xFFAE, NumpadDivide: 0xFFAF,
  Numpad0: 0xFFB0, Numpad1: 0xFFB1, Numpad2: 0xFFB2, Numpad3: 0xFFB3,
  Numpad4: 0xFFB4, Numpad5: 0xFFB5, Numpad6: 0xFFB6, Numpad7: 0xFFB7,
  Numpad8: 0xFFB8, Numpad9: 0xFFB9,
};

function keysymOf(e) {
  if (KEYSYM_BY_CODE[e.code] !== undefined) return KEYSYM_BY_CODE[e.code];
  const k = e.key;
  if (k.length === 1) {
    const cp = k.codePointAt(0);
    if (cp >= 0x20 && cp <= 0x7E) return cp;          // ASCII printable
    if (cp >= 0xA0 && cp <= 0xFF) return cp;          // Latin-1
    return 0x01000000 + cp;                            // Unicode keysym
  }
  if (KEYSYM_SPECIAL[k] !== undefined) return KEYSYM_SPECIAL[k];
  return null;
}

/* opcode bytes (protocol.py) */
const OP_AUDIO = 0x01, OP_MIC = 0x02, OP_JPEG = 0x03, OP_H264 = 0x04,
      OP_GZ = 0x05;

const fidNewer = (a, b) => ((a - b + 0x10000) & 0xFFFF) < 0x8000 && a !== b;

/* ------------------------------------------------------------------ client */
class SelkiesClient {
  constructor(canvas, hud) {
    this.canvas = canvas;
    this.ctx = canvas.getContext("2d", { desynchronized: true });
    this.hud = hud;
    this.ws = null;
    this.gz = false;
    this.serverSettings = null;
    this.displayW = 0; this.displayH = 0;
    this.videoActive = false;
    this.touchMode = "direct";        // or "trackpad" (postMessage API)
    this.lastAckFid = -1;
    this.stripeLastFid = new Map();   // y -> last drawn frame id
    this.held = new Set();            // held keysyms
    this.decodeQueue = 0;             // in-flight createImageBitmap calls
    this.framesDrawn = 0;
    this.stripesDrawn = 0;
    this.lastStatsT = performance.now();
    this.pointerLocked = false;
    this.audio = null;                // AudioPlayer
    this.reconnectDelay = 500;
    this.statusMsg = "connecting…";
    this.killed = false;
    this.rtcMode = false;             // true once the RTC transport owns IO
    this.pc = null;                   // RTCPeerConnection
    this.dc = null;                   // "input" data channel
    this.sigWs = null;                // signaling WebSocket
    this.videoEl = null;              // RTC <video> sink

    this._bindInput();
    this._bindResize();
    this._statsTimer = setInterval(() => this._reportStats(), 2000);
    this._hbTimer = setInterval(() => this._heartbeat(), 500);
  }

  /* ------------------------------------------------------------ transport */
  async start() {
    // pick the transport the server is actually running (/api/status.mode)
    let mode = "websockets";
    try {
      const r = await fetch("/api/status", { credentials: "same-origin" });
      if (r.ok) mode = (await r.json()).mode || mode;
    } catch (_e) { /* status unreachable: default to WS */ }
    if (mode === "webrtc" && typeof RTCPeerConnection !== "undefined")
      this.connectRTC();
    else this.connect();
  }

  connect() {
    const proto = location.protocol === "https:" ? "wss:" : "ws:";
    const url = `${proto}//${location.host}/api/websockets`;
    this.status(`connecting to ${url}`);
    const ws = new WebSocket(url);
    ws.binaryType = "arraybuffer";
    this.ws = ws;
    ws.onopen = () => {
      this.reconnectDelay = 500;
      this.send("_gz,1");
      this.gz = true;
      if (this._pendingLayout) {
        this._pendingLayout();
        this._pendingLayout = null;
      }
    };
    ws.onmessage = (ev) => {
      if (typeof ev.data === "string") this._onText(ev.data);
      else this._onBinary(new Uint8Array(ev.data));
    };
    ws.onclose = () => {
      this.videoActive = false;
      if (this.killed) return;
      this.status(`disconnected — retrying in ${this.reconnectDelay} ms`, true);
      setTimeout(() => this.connect(), this.reconnectDelay);
      this.reconnectDelay = Math.min(this.reconnectDelay * 2, 10000);
    };
  }

  send(text) {
    if (this.rtcMode) {
      if (this.dc && this.dc.readyState === "open") this.dc.send(text);
      return;
    }
    if (this.ws && this.ws.readyState === WebSocket.OPEN) this.ws.send(text);
  }

  sendBytes(u8) {
    /* binary frames (0x02 mic) ride the WS transport only; the SCTP
     * data channel carries the text verb grammar */
    if (!this.rtcMode && this.ws && this.ws.readyState === WebSocket.OPEN)
      this.ws.send(u8);
  }

  /* --------------------------------------------------------- RTC transport
   * Signaling protocol (server signaling.py): HELLO client {meta} ->
   * "SESSION server" -> SESSION_OK <uid> -> the server peer sends
   * {"sdp":{"type":"offer",...}}; we answer. Media flows on ICE-lite host
   * candidates; the browser opens the "input" data channel (DCEP). */
  connectRTC() {
    this.rtcMode = true;
    const proto = location.protocol === "https:" ? "wss:" : "ws:";
    const url = `${proto}//${location.host}/api/signaling`;
    this.status(`rtc signaling: ${url}`);
    const ws = new WebSocket(url);
    this.sigWs = ws;
    const params = new URLSearchParams(location.search);
    ws.onopen = () => {
      this.reconnectDelay = 500;
      ws.send("HELLO client " + JSON.stringify({
        client_type: params.get("view_only") ? "viewer" : "controller",
        display_id: params.get("display") || "primary",
      }));
    };
    ws.onmessage = (ev) => this._onSignal(String(ev.data));
    ws.onclose = () => {
      if (this.killed) return;
      this._rtcTeardown();
      this.status(`signaling lost — retrying in ${this.reconnectDelay} ms`, true);
      setTimeout(() => this.connectRTC(), this.reconnectDelay);
      this.reconnectDelay = Math.min(this.reconnectDelay * 2, 10000);
    };
  }

  async _onSignal(text) {
    if (text === "HELLO") { this.sigWs.send("SESSION server"); return; }
    if (text.startsWith("SESSION_OK")) { this.status("rtc: waiting for offer"); return; }
    if (text.startsWith("SESSION_END")) { this._rtcTeardown(); return; }
    if (text.startsWith("ERROR")) { this.status(`rtc: ${text}`, true); return; }
    let msg;
    try { msg = JSON.parse(text); } catch { return; }
    if (msg.sdp && msg.sdp.type === "offer") await this._onRtcOffer(msg.sdp);
  }

  async _onRtcOffer(offer) {
    this._rtcTeardown();
    let iceServers = [];
    try {
      const r = await fetch("/api/turn", { credentials: "same-origin" });
      if (r.ok) iceServers = (await r.json()).iceServers || [];
    } catch (_e) { /* host-candidate-only is fine on a LAN */ }
    const pc = new RTCPeerConnection({ iceServers });
    this.pc = pc;
    pc.ontrack = (e) => {
      if (e.track.kind === "video") this._attachRtcVideo(e.streams[0] ||
        new MediaStream([e.track]));
      else if (this.videoEl) this.videoEl.muted = false;
    };
    pc.onconnectionstatechange = () => {
      if (pc.connectionState === "connected")
        this.status("webrtc connected");
      else if (pc.connectionState === "failed") {
        this.status("webrtc failed — renegotiating", true);
        try { this.sigWs.send("SESSION_END"); } catch (_e) { /* gone */ }
        this._rtcTeardown();
        setTimeout(() => { try { this.sigWs.send("SESSION server"); } catch (_e) { /* retried on reconnect */ } }, 1000);
      }
    };
    const dc = pc.createDataChannel("input", { ordered: true });
    this.dc = dc;
    dc.onopen = () => {
      this.status("webrtc connected · input channel open");
      this._sendPreferredSize();
    };
    dc.onmessage = (ev) => {
      if (typeof ev.data === "string") this._onText(ev.data);
    };
    await pc.setRemoteDescription(offer);
    const answer = await pc.createAnswer();
    await pc.setLocalDescription(answer);
    // ICE-lite server: no trickle needed; ship the answer as-is (the
    // browser probes the offer's host candidate directly)
    this.sigWs.send(JSON.stringify({ sdp: {
      type: answer.type, sdp: pc.localDescription.sdp } }));
  }

  _attachRtcVideo(stream) {
    if (!this.videoEl) {
      const v = document.createElement("video");
      v.autoplay = true; v.playsInline = true; v.muted = true;
      v.style.cssText =
        "max-width:100%;max-height:100%;background:#000;outline:none";
      // canvas stays on top (transparent, input-capturing); video below
      this.canvas.parentElement.insertBefore(v, this.canvas);
      this.canvas.style.position = "absolute";
      this.canvas.style.background = "transparent";
      this.videoEl = v;
      v.addEventListener("resize", () => this._syncRtcCanvas());
    }
    this.videoEl.srcObject = stream;
    this.videoEl.play().catch(() => { /* needs a user gesture; autoplay muted */ });
    this._syncRtcCanvas();
  }

  /* size the input-capturing canvas exactly over the displayed video and
   * keep canvas.width/height at the STREAM size so _bindInput's coordinate
   * scaling holds for both transports */
  _syncRtcCanvas() {
    const v = this.videoEl;
    if (!v || !v.videoWidth) return;
    this.displayW = v.videoWidth; this.displayH = v.videoHeight;
    this.canvas.width = v.videoWidth; this.canvas.height = v.videoHeight;
    const r = v.getBoundingClientRect();
    this.canvas.style.left = `${r.left}px`;
    this.canvas.style.top = `${r.top}px`;
    this.canvas.style.width = `${r.width}px`;
    this.canvas.style.height = `${r.height}px`;
    document.title = `Selkies TPU — ${v.videoWidth}x${v.videoHeight}`;
  }

  _rtcTeardown() {
    if (this.dc) { try { this.dc.close(); } catch (_e) { /* closed */ } this.dc = null; }
    if (this.pc) { try { this.pc.close(); } catch (_e) { /* closed */ } this.pc = null; }
    if (this.videoEl) this.videoEl.srcObject = null;
  }

  async sendMaybeGz(text) {
    // 0x05-frame large control messages (server inflates, bounded)
    if (this.gz && text.length > 512 && typeof CompressionStream !== "undefined") {
      const stream = new Blob([text]).stream()
        .pipeThrough(new CompressionStream("gzip"));
      const packed = new Uint8Array(await new Response(stream).arrayBuffer());
      const framed = new Uint8Array(packed.length + 1);
      framed[0] = OP_GZ; framed.set(packed, 1);
      this.ws.send(framed);
    } else this.send(text);
  }

  /* -------------------------------------------------------------- binary */
  _onBinary(buf) {
    switch (buf[0]) {
      case OP_JPEG: this._onJpegStripe(buf); break;
      case OP_H264: this._onH264Stripe(buf); break;
      case OP_AUDIO: if (this.audio) this.audio.push(buf); break;
      case OP_GZ: this._onGzControl(buf); break;
    }
  }

  async _onGzControl(buf) {
    if (typeof DecompressionStream === "undefined") return;
    const stream = new Blob([buf.subarray(1)]).stream()
      .pipeThrough(new DecompressionStream("gzip"));
    this._onText(await new Response(stream).text());
  }

  /* 6-byte header: [0x03, flags, u16 frame_id, u16 stripe_y] + JFIF */
  async _onJpegStripe(buf) {
    const dv = new DataView(buf.buffer, buf.byteOffset, 6);
    const fid = dv.getUint16(2), y = dv.getUint16(4);
    const last = this.stripeLastFid.get(y);
    if (last !== undefined && !fidNewer(fid, last)) return; // stale stripe
    if (this.decodeQueue > 48) return;  // overload: drop, keyframe recovers
    this.decodeQueue++;
    try {
      const blob = new Blob([buf.subarray(6)], { type: "image/jpeg" });
      const bmp = await createImageBitmap(blob);
      const l2 = this.stripeLastFid.get(y);
      if (l2 === undefined || fidNewer(fid, l2) || fid === l2) {
        this.stripeLastFid.set(y, fid);
        this.ctx.drawImage(bmp, 0, y);   // canvas crops right/bottom padding
        this.stripesDrawn++;
        this._ackFrame(fid);
      }
      bmp.close();
    } catch (e) {
      console.warn("jpeg stripe decode failed", e);
    } finally {
      this.decodeQueue--;
    }
  }

  /* 10-byte header: [0x04, frame_type, u16 fid, u16 y, u16 w, u16 h] +
   * Annex-B. Every stripe row is an independent H.264 stream with its own
   * decoder keyed by y_start (reference selkies-ws-core.js:4424-4460). */
  _onH264Stripe(buf) {
    if (typeof VideoDecoder === "undefined") {
      if (!this._h264warned) {
        this._h264warned = true;
        this.status("WebCodecs H.264 unsupported in this browser", true);
      }
      return;
    }
    const dv = new DataView(buf.buffer, buf.byteOffset, 10);
    const fid = dv.getUint16(2), y = dv.getUint16(4);
    if (!this.h264Decoders) this.h264Decoders = new Map();
    let dec = this.h264Decoders.get(y);
    if (!dec || dec.state === "closed") {
      const yTop = y;
      dec = new VideoDecoder({
        output: (frame) => {
          this.ctx.drawImage(frame, 0, yTop);
          this.stripesDrawn++;
          this._ackFrame(frame.timestamp & 0xFFFF);
          frame.close();
        },
        error: (e) => {
          console.warn("h264 stripe decoder error", e);
          this.h264Decoders.delete(yTop);
          this._requestKeyframeThrottled();
        },
      });
      // Annex-B stream (no description): constrained baseline, or
      // Hi444PP when the server streams fullcolor 4:4:4 (the reference's
      // f4001f profile munge)
      const st = (this.serverSettings && this.serverSettings.settings) || {};
      const fullcolor = !!(st.fullcolor && st.fullcolor.value);
      dec.configure({ codec: fullcolor ? "avc1.f4002a" : "avc1.42c02a",
                      optimizeForLatency: true });
      this.h264Decoders.set(y, dec);
    }
    if (dec.decodeQueueSize > 16) {
      // overload: drop the stripe, but ask for a refresh — the server's
      // damage gating believes it was delivered and would otherwise leave
      // this region stale until the next change. THROTTLED: an unthrottled
      // request per dropped stripe re-forces full-frame IDRs every frame
      // and locks the overloaded client into a feedback loop.
      this._requestKeyframeThrottled();
      return;
    }
    dec.decode(new EncodedVideoChunk({
      type: buf[1] === 1 ? "key" : "delta",   // frame_type from the header
      timestamp: fid,
      data: buf.subarray(10),
    }));
  }

  _ackFrame(fid) {
    if (fid !== this.lastAckFid) {
      this.lastAckFid = fid;
      this.framesDrawn++;
      this.send(`CLIENT_FRAME_ACK ${fid}`);
    }
  }

  _requestKeyframeThrottled() {
    const now = performance.now();
    if (!this._lastKfReq || now - this._lastKfReq > 1000) {
      this._lastKfReq = now;
      this.send("REQUEST_KEYFRAME");
    }
  }

  /* ---------------------------------------------------------------- text */
  _onText(text) {
    const sp = text.indexOf(" "), cm = text.indexOf(",");
    const cut = Math.min(sp < 0 ? text.length : sp, cm < 0 ? text.length : cm);
    const verb = text.slice(0, cut), rest = text.slice(cut + 1);
    switch (verb) {
      case "MODE": break;
      case "server_settings": this._applyServerSettings(rest); break;
      case "system_stats": this._showStats(rest); break;
      case "gpu_stats": this._showGpuStats(rest); break;
      case "cursor": this._applyCursor(rest); break;
      case "VIDEO_STARTED": this.videoActive = true; break;
      case "VIDEO_STOPPED": this.videoActive = false; break;
      case "AUDIO_DISABLED": if (this.audio) { this.audio.close(); this.audio = null; } break;
      case "settings_applied": break;
      case "clipboard": this._applyClipboard(rest); break;
      case "KILL":
        this.killed = true;
        this.status("session terminated by server", true);
        this.ws.close();
        break;
      default: break;
    }
    this._postToDashboard({ type: "serverMessage", verb, payload: rest });
  }

  _applyServerSettings(json) {
    let payload;
    try { payload = JSON.parse(json); } catch { return; }
    this.serverSettings = payload;
    const d = (payload.displays && payload.displays[0]) || {};
    if (d.width && (d.width !== this.displayW || d.height !== this.displayH)) {
      this.displayW = d.width; this.displayH = d.height;
      this.canvas.width = d.width; this.canvas.height = d.height;
      this.stripeLastFid.clear();
      if (this.h264Decoders) {   // stripe geometry changed: fresh decoders
        for (const dec of this.h264Decoders.values()) {
          try { dec.close(); } catch { /* already closed */ }
        }
        this.h264Decoders.clear();
      }
      this.send("REQUEST_KEYFRAME");
    }
    document.title = `${payload.app_name || "Selkies TPU"} — ${d.width}x${d.height}`;
    if (!this.videoActive) {
      this.send("START_VIDEO");
      if (payload.features && payload.features.audio) {
        if (!this.audio) this.audio = new AudioPlayer(payload);
        this.send("START_AUDIO");
      }
      this._sendPreferredSize();
    }
    this.status(`${d.width}x${d.height} · ` +
      `${(payload.settings?.framerate?.value ?? "?")} fps target`);
    this._postToDashboard({ type: "serverSettings", payload });
  }

  _applyCursor(json) {
    try {
      const c = JSON.parse(json);
      if (c.png_b64) {
        this.canvas.style.cursor =
          `url(data:image/png;base64,${c.png_b64}) ${c.xhot || 0} ${c.yhot || 0}, default`;
      } else if (c.visible === false) this.canvas.style.cursor = "none";
      else this.canvas.style.cursor = "default";
    } catch { /* tolerate malformed cursor payloads */ }
  }

  async _applyClipboard(b64) {
    try {
      const text = atob(b64);
      if (navigator.clipboard && document.hasFocus())
        await navigator.clipboard.writeText(text);
    } catch { /* clipboard permission denied: ignore */ }
  }

  _showStats(json) {
    try {
      const s = JSON.parse(json);
      const enc = Object.entries(s.encoded_fps || {})
        .map(([d, f]) => `${d}:${f.toFixed(0)}`).join(" ");
      this.status(
        `${this.displayW}x${this.displayH} · encode ${enc} fps · ` +
        `draw ${this._drawFps.toFixed(0)} fps · cpu ${s.cpu_percent}%`);
      this._postToDashboard({ type: "systemStats", payload: s });
    } catch { /* ignore */ }
  }

  _showGpuStats(json) {
    try {
      this._postToDashboard({ type: "gpuStats",
                              payload: JSON.parse(json) });
    } catch { /* ignore */ }
  }

  /* --------------------------------------------------------------- stats */
  get _drawFps() { return this.__drawFps || 0; }

  _reportStats() {
    const now = performance.now();
    const dt = (now - this.lastStatsT) / 1000;
    this.__drawFps = this.framesDrawn / Math.max(dt, 1e-3);
    this.framesDrawn = 0;
    this.lastStatsT = now;
    if (this.videoActive) this.send(`_f,${this.__drawFps.toFixed(1)}`);
  }

  /* --------------------------------------------------------------- input */
  _bindInput() {
    const cv = this.canvas;
    cv.addEventListener("contextmenu", (e) => e.preventDefault());

    cv.addEventListener("keydown", (e) => {
      const ks = keysymOf(e);
      if (ks === null) return;
      e.preventDefault();
      if (!e.repeat) { this.held.add(ks); this.send(`kd,${ks}`); }
    });
    cv.addEventListener("keyup", (e) => {
      const ks = keysymOf(e);
      if (ks === null) return;
      e.preventDefault();
      this.held.delete(ks);
      this.send(`ku,${ks}`);
    });
    cv.addEventListener("blur", () => {
      if (this.held.size) { this.held.clear(); this.send("kr,"); }
    });

    const scale = (e) => {
      const r = cv.getBoundingClientRect();
      const x = Math.round((e.clientX - r.left) * (cv.width / r.width));
      const y = Math.round((e.clientY - r.top) * (cv.height / r.height));
      return [Math.max(0, Math.min(cv.width - 1, x)),
              Math.max(0, Math.min(cv.height - 1, y))];
    };
    cv.addEventListener("mousemove", (e) => {
      if (this.pointerLocked) this.send(`m2,${e.movementX},${e.movementY}`);
      else { const [x, y] = scale(e); this.send(`m,${x},${y}`); }
    });
    const btnMap = { 0: 1, 1: 2, 2: 3, 3: 8, 4: 9 };  // DOM -> X11
    cv.addEventListener("mousedown", (e) => {
      cv.focus();
      const [x, y] = scale(e);
      this.send(`m,${x},${y}`);
      this.send(`mb,${btnMap[e.button] ?? 1},1`);
      e.preventDefault();
    });
    cv.addEventListener("mouseup", (e) => {
      this.send(`mb,${btnMap[e.button] ?? 1},0`);
      e.preventDefault();
    });
    cv.addEventListener("wheel", (e) => {
      const dy = Math.sign(e.deltaY), dx = Math.sign(e.deltaX);
      if (dx || dy) this.send(`ms,${dx},${dy}`);
      e.preventDefault();
    }, { passive: false });

    document.addEventListener("pointerlockchange", () => {
      this.pointerLocked = document.pointerLockElement === cv;
    });
    cv.addEventListener("dblclick", () => {
      // double-click toggles pointer lock for games needing relative mouse
      if (!this.pointerLocked && cv.requestPointerLock) cv.requestPointerLock();
    });

    document.addEventListener("visibilitychange", () => {
      if (!this.ws || this.ws.readyState !== WebSocket.OPEN) return;
      if (document.hidden) this.send("STOP_VIDEO");
      else { this.send("START_VIDEO"); this.send("REQUEST_KEYFRAME"); }
    });

    document.addEventListener("paste", async (e) => {
      const text = e.clipboardData && e.clipboardData.getData("text");
      if (text) this.send(`cw,${btoa(unescape(encodeURIComponent(text)))}`);
    });
    document.addEventListener("copy", () => {
      // fetch the REMOTE clipboard; delayed so the forwarded Ctrl+C
      // keystroke reaches the remote app BEFORE the server reads its
      // selection (otherwise the reply is the previous clipboard)
      setTimeout(() => this.send("REQUEST_CLIPBOARD"), 150);
    });

    window.addEventListener("message", (e) => this._onDashboardMessage(e));
    this._bindGamepad();
    this._bindTouch(cv);
    this._bindUpload(cv);
    this._detectKeyboardLayout();
  }

  /* ------------------------------------------------------ layout detect
   * Best-effort layout detection (reference lib/keyboard-layout.js):
   * probe the physical-key layout map, fall back to the UI language, and
   * tell the server so it can align the X keymap for scancode-reading
   * apps (character input is already layout-independent via keysyms). */
  async _detectKeyboardLayout() {
    let layout = "";
    try {
      if (navigator.keyboard && navigator.keyboard.getLayoutMap) {
        const map = await navigator.keyboard.getLayoutMap();
        const probe = [map.get("KeyQ"), map.get("KeyW"), map.get("KeyZ")]
          .join("");
        layout = { qwz: "us", azw: "fr", qwy: "de" }[probe] || "";
      }
    } catch (_e) { /* permissions / unsupported */ }
    if (!layout) {
      const lang = (navigator.language || "en-US").toLowerCase();
      layout = { fr: "fr", de: "de", es: "es", it: "it", pt: "pt",
                 ru: "ru", gb: "gb" }[lang.split("-")[0]] || "us";
    }
    this._kbLayout = layout;
    const sendIt = () => this.send(
      `SETTINGS,${JSON.stringify({ keyboard_layout: layout })}`);
    if (this.ws && this.ws.readyState === WebSocket.OPEN) sendIt();
    else this._pendingLayout = sendIt;
  }

  /* --------------------------------------------------- on-screen keyboard
   * Minimal OSK for touch devices (reference lib/input.js OSK): a
   * toggleable overlay whose buttons fire the same kd/ku verbs. */
  toggleOnScreenKeyboard() {
    if (this._osk) {
      this._osk.remove();
      this._osk = null;
      return;
    }
    const rows = [
      ["Esc:65307", "1", "2", "3", "4", "5", "6", "7", "8", "9", "0",
       "⌫:65288"],
      ["q", "w", "e", "r", "t", "y", "u", "i", "o", "p"],
      ["a", "s", "d", "f", "g", "h", "j", "k", "l", "⏎:65293"],
      ["⇧:65505", "z", "x", "c", "v", "b", "n", "m", ",", "."],
      ["Ctrl:65507", "Alt:65513", "␣:32", "←:65361", "↓:65364",
       "↑:65362", "→:65363"],
    ];
    const osk = document.createElement("div");
    osk.style.cssText =
      "position:fixed;bottom:0;left:0;right:0;background:#222d;" +
      "padding:6px;z-index:1000;display:flex;flex-direction:column;" +
      "gap:4px;touch-action:none";
    for (const row of rows) {
      const line = document.createElement("div");
      line.style.cssText = "display:flex;gap:4px;justify-content:center";
      for (const keydef of row) {
        const [label, ksStr] = keydef.includes(":")
          ? keydef.split(":") : [keydef, null];
        const ks = ksStr ? parseInt(ksStr, 10)
          : label.codePointAt(0);
        const b = document.createElement("button");
        b.textContent = label;
        b.style.cssText =
          "flex:1;max-width:72px;padding:10px 4px;font-size:16px;" +
          "background:#444;color:#eee;border:1px solid #666;" +
          "border-radius:4px";
        const down = (e) => { e.preventDefault(); this.send(`kd,${ks}`); };
        const up = (e) => { e.preventDefault(); this.send(`ku,${ks}`); };
        b.addEventListener("pointerdown", down);
        b.addEventListener("pointerup", up);
        b.addEventListener("pointerleave", up);
        line.appendChild(b);
      }
      osk.appendChild(line);
    }
    document.body.appendChild(osk);
    this._osk = osk;
  }

  /* ------------------------------------------------------------- gamepad
   * navigator.getGamepads() polling -> js,c/d/b/a verbs (the server half
   * feeds the C interposer sockets; reference lib/gamepad.js:1-229). */
  _bindGamepad() {
    this.padState = new Map();          // index -> {buttons:[], axes:[]}
    window.addEventListener("gamepadconnected", (e) => {
      const p = e.gamepad;
      if (p.index > 3) return;
      this.padState.set(p.index, { buttons: [], axes: [] });
      this.send(`js,c,${p.index},${p.id.slice(0, 64)}`);
      if (!this._padTimer) this._padTimer = setInterval(
        () => this._pollGamepads(), 16);
    });
    window.addEventListener("gamepaddisconnected", (e) => {
      if (!this.padState.delete(e.gamepad.index)) return;
      this.send(`js,d,${e.gamepad.index}`);
      if (this.padState.size === 0 && this._padTimer) {
        clearInterval(this._padTimer);
        this._padTimer = null;
      }
    });
  }

  _pollGamepads() {
    const pads = navigator.getGamepads ? navigator.getGamepads() : [];
    for (const p of pads) {
      if (!p || !this.padState.has(p.index)) continue;
      const st = this.padState.get(p.index);
      p.buttons.forEach((b, i) => {
        const v = b.pressed ? 1 : 0;
        if (st.buttons[i] !== v) {
          st.buttons[i] = v;
          this.send(`js,b,${p.index},${i},${v}`);
        }
      });
      p.axes.forEach((a, i) => {
        const v = Math.round(a * 1000) / 1000;
        if (Math.abs((st.axes[i] ?? 0) - v) > 0.009) {
          st.axes[i] = v;
          this.send(`js,a,${p.index},${i},${v}`);
        }
      });
    }
  }

  /* --------------------------------------------------------------- touch
   * Touch-to-mouse: one finger = absolute move + left button; two-finger
   * vertical pan = wheel; two-finger tap = right click (reference
   * lib/input.js touch mode). */
  _bindTouch(cv) {
    const scaleT = (t) => {
      const r = cv.getBoundingClientRect();
      const x = Math.round((t.clientX - r.left) * (cv.width / r.width));
      const y = Math.round((t.clientY - r.top) * (cv.height / r.height));
      return [Math.max(0, Math.min(cv.width - 1, x)),
              Math.max(0, Math.min(cv.height - 1, y))];
    };
    // tap-vs-gesture disambiguation: the left press is DEFERRED 60 ms
    // so a second finger (scroll/right-click gesture) can cancel it —
    // otherwise every two-finger gesture starts with a phantom click
    let twoFinger = null;               // {y, moved, t0}
    let pendingPress = null;            // timer id
    let pressed = false;
    const commitPress = () => {
      if (pendingPress !== null) {
        clearTimeout(pendingPress);
        pendingPress = null;
        this.send("mb,1,1");
        pressed = true;
      }
    };
    cv.addEventListener("touchstart", (e) => {
      e.preventDefault();
      if (this.touchMode === "trackpad") {
        this._trackpadStart(e);
        return;
      }
      if (e.touches.length === 1) {
        const [x, y] = scaleT(e.touches[0]);
        this.send(`m,${x},${y}`);
        pendingPress = setTimeout(commitPress, 60);
      } else if (e.touches.length === 2) {
        if (pendingPress !== null) {    // gesture: cancel the tap press
          clearTimeout(pendingPress);
          pendingPress = null;
        } else if (pressed) {
          this.send("mb,1,0");
          pressed = false;
        }
        twoFinger = { y: e.touches[0].clientY, moved: false,
                      t0: performance.now() };
      }
    }, { passive: false });
    cv.addEventListener("touchmove", (e) => {
      e.preventDefault();
      if (this.touchMode === "trackpad") {
        this._trackpadMove(e);
        return;
      }
      if (e.touches.length === 1 && !twoFinger) {
        commitPress();                  // moving finger = drag, press now
        const [x, y] = scaleT(e.touches[0]);
        this.send(`m,${x},${y}`);
      } else if (e.touches.length === 2 && twoFinger) {
        const dy = e.touches[0].clientY - twoFinger.y;
        if (Math.abs(dy) > 12) {
          this.send(`ms,0,${dy > 0 ? -1 : 1}`);
          twoFinger.y = e.touches[0].clientY;
          twoFinger.moved = true;
        }
      }
    }, { passive: false });
    cv.addEventListener("touchend", (e) => {
      e.preventDefault();
      if (this.touchMode === "trackpad") {
        this._trackpadEnd(e);
        return;
      }
      if (twoFinger) {
        if (!twoFinger.moved && performance.now() - twoFinger.t0 < 350) {
          this.send("mb,3,1");          // two-finger tap = right click
          this.send("mb,3,0");
          twoFinger.moved = true;       // fire once, not per lifted finger
        }
        if (e.touches.length === 0) twoFinger = null;
      } else if (e.touches.length === 0) {
        if (pendingPress !== null) {    // quick tap: full click now
          commitPress();
        }
        if (pressed) {
          this.send("mb,1,0");
          pressed = false;
        }
      }
    }, { passive: false });
  }

  /* trackpad touch mode (reference lib/input.js trackpad mode): the
   * canvas is a laptop touchpad — one finger moves the cursor
   * RELATIVELY (m2 verbs), a quick tap left-clicks, a one-finger
   * tap-then-drag drags, two-finger pan scrolls, two-finger tap
   * right-clicks. Switch via postMessage {type:"touchMode"}. */
  _trackpadStart(e) {
    const t = e.touches;
    const now = performance.now();
    if (t.length === 1) {
      const tapTap = this._tpLastTap && now - this._tpLastTap < 280;
      this._tp = { x: t[0].clientX, y: t[0].clientY, t0: now,
                   moved: false, drag: !!tapTap };
      if (tapTap) this.send("mb,1,1");       // tap-drag: hold the button
    } else if (t.length === 2) {
      // both fingers may land in ONE touchstart (fast two-finger tap):
      // synthesize the missing one-finger state so the gesture works
      if (!this._tp)
        this._tp = { x: t[0].clientX, y: t[0].clientY, t0: now,
                     moved: false, drag: false };
      if (this._tp.drag) { this.send("mb,1,0"); this._tp.drag = false; }
      this._tp.two = { y: t[0].clientY, t0: now, moved: this._tp.moved };
    }
  }

  _trackpadMove(e) {
    const t = e.touches;
    if (!this._tp) return;
    if (t.length === 1 && !this._tp.two) {
      const dx = Math.round((t[0].clientX - this._tp.x) * 1.4);
      const dy = Math.round((t[0].clientY - this._tp.y) * 1.4);
      if (dx || dy) {
        this.send(`m2,${dx},${dy}`);
        this._tp.x = t[0].clientX;
        this._tp.y = t[0].clientY;
        this._tp.moved = true;
      }
    } else if (t.length === 2 && this._tp.two) {
      const dy = t[0].clientY - this._tp.two.y;
      if (Math.abs(dy) > 12) {
        this.send(`ms,0,${dy > 0 ? -1 : 1}`);
        this._tp.two.y = t[0].clientY;
        this._tp.two.moved = true;
      }
    }
  }

  _trackpadEnd(e) {
    if (!this._tp) return;
    const now = performance.now();
    if (this._tp.two) {
      if (!this._tp.two.moved && now - this._tp.two.t0 < 350) {
        this.send("mb,3,1");
        this.send("mb,3,0");
        this._tp.two.moved = true;
      }
      if (e.touches.length === 0) this._tp = null;
      return;
    }
    if (e.touches.length === 0) {
      if (this._tp.drag) this.send("mb,1,0");
      else if (!this._tp.moved && now - this._tp.t0 < 250) {
        this.send("mb,1,1");
        this.send("mb,1,0");
        this._tpLastTap = now;
      }
      this._tp = null;
    }
  }

  /* -------------------------------------------------------------- upload
   * Drag-drop -> chunked POST /api/upload with the X-Upload-* resume
   * protocol the server already speaks (reference lib/file-upload.js). */
  _bindUpload(cv) {
    const stop = (e) => { e.preventDefault(); e.stopPropagation(); };
    ["dragenter", "dragover"].forEach((ev) =>
      cv.addEventListener(ev, stop));
    cv.addEventListener("drop", async (e) => {
      stop(e);
      const files = [...(e.dataTransfer ? e.dataTransfer.files : [])];
      for (const f of files) {
        try {
          await this.uploadFile(f);
          this._post({ type: "uploadDone", name: f.name });
        } catch (err) {
          this._post({ type: "uploadError", name: f.name,
                       error: String(err) });
        }
      }
    });
  }

  async uploadFile(file, chunkBytes = 1 << 20) {
    for (let off = 0; off < file.size || off === 0; off += chunkBytes) {
      const chunk = file.slice(off, off + chunkBytes);
      const r = await fetch("/api/upload", {
        method: "POST",
        headers: {
          // headers are Latin-1 only: percent-encode, server decodes
          "X-Upload-Name": encodeURIComponent(file.name),
          "X-Upload-Offset": String(off),
          "X-Upload-Total": String(file.size),
        },
        body: chunk,
        credentials: "same-origin",
      });
      if (!r.ok) throw new Error(`upload ${file.name}: HTTP ${r.status}`);
      this._post({ type: "uploadProgress", name: file.name,
                   sent: Math.min(off + chunkBytes, file.size),
                   total: file.size });
      if (file.size === 0) break;
    }
  }

  _post(msg) {
    try {
      (window.parent || window).postMessage(
        Object.assign({ scope: "selkies" }, msg), "*");
    } catch (_e) { /* sandboxed parent */ }
  }

  _heartbeat() {
    if (this.held.size)
      this.send(`kh,${Array.from(this.held).join(",")}`);
  }

  /* -------------------------------------------------------------- resize */
  _bindResize() {
    let timer = null;
    window.addEventListener("resize", () => {
      if (this.rtcMode)                         // keep the overlay aligned
        requestAnimationFrame(() => this._syncRtcCanvas());
      clearTimeout(timer);
      timer = setTimeout(() => this._sendPreferredSize(), 500);
    });
  }

  _sendPreferredSize() {
    const s = this.serverSettings;
    // RTC mode gets no server_settings push; the server gates 'r' on its
    // own enable_resize setting, so always offer the preferred size there
    if (!this.rtcMode && (!s || !s.features || !s.features.resize)) return;
    const dpr = window.devicePixelRatio || 1;
    const w = Math.round(window.innerWidth * dpr / 2) * 2;
    const h = Math.round(window.innerHeight * dpr / 2) * 2;
    if (w !== this.displayW || h !== this.displayH) this.send(`r,${w}x${h}`);
  }

  /* --------------------------------------------- dashboard postMessage API
   * Same-origin embedding surface mirroring the reference dashboard
   * protocol (reference addons/selkies-web-core/README.md:49-200). */
  _postToDashboard(msg) {
    if (window.parent !== window)
      window.parent.postMessage({ selkies: true, ...msg }, location.origin);
  }

  _onDashboardMessage(e) {
    if (e.origin !== location.origin || !e.data || e.data.selkies !== true)
      return;
    const d = e.data;
    switch (d.type) {
      case "settings":
        this.sendMaybeGz(`SETTINGS,${JSON.stringify(d.settings || {})}`);
        break;
      case "pipelineControl":
        if (d.video === false) this.send("STOP_VIDEO");
        if (d.video === true) this.send("START_VIDEO");
        if (d.audio === false) this.send("STOP_AUDIO");
        if (d.audio === true) this.send("START_AUDIO");
        if (d.microphone === true) this.startMic();
        if (d.microphone === false) this.stopMic();
        if (d.keyframe) this.send("REQUEST_KEYFRAME");
        break;
      case "getStats":
        this._postToDashboard({
          type: "stats",
          payload: { drawFps: this._drawFps, display: [this.displayW, this.displayH] },
        });
        break;
      case "videoBitrate": this.send(`vb,${d.kbps | 0}`); break;
      case "audioBitrate": this.send(`ab,${d.bps | 0}`); break;
      case "toggleOsk": this.toggleOnScreenKeyboard(); break;
      case "touchMode":
        this.touchMode = d.mode === "trackpad" ? "trackpad" : "direct";
        break;
      case "clipboard":
        if (typeof d.text === "string")
          this.send(`cw,${btoa(unescape(encodeURIComponent(d.text)))}`);
        break;
      default: break;
    }
  }

  /* ------------------------------------------------------------ microphone
   * getUserMedia -> AudioWorklet -> s16 24 kHz mono 0x02 frames (the
   * server plays them into the SelkiesVirtualMic graph so desktop apps
   * can record — reference selkies-ws-core.js:5685 / selkies.py:229). */
  async startMic() {
    if (this.mic) return;
    if (this.rtcMode) {
      /* 0x02 frames ride the WS transport only (sendBytes no-ops on
       * RTC) — claiming success here would light the mic for nothing */
      this.status("microphone needs the WebSockets transport", true);
      return;
    }
    const feats = this.serverSettings && this.serverSettings.features;
    if (!feats || !feats.microphone) {
      this.status("microphone disabled by server", true);
      return;
    }
    const mic = new MicSender(this);
    try {
      await mic.start();
      this.mic = mic;
      this.status("microphone forwarding on");
      this._postToDashboard({ type: "microphone", active: true });
    } catch (e) {
      mic.stop();     // release any tracks/context acquired before the throw
      this.status(`microphone unavailable: ${e.message || e}`, true);
    }
  }

  stopMic() {
    if (!this.mic) return;
    this.mic.stop();
    this.mic = null;
    this._postToDashboard({ type: "microphone", active: false });
  }

  /* ----------------------------------------------------------------- hud */
  status(msg, isErr = false) {
    this.statusMsg = msg;
    if (this.hud) {
      this.hud.innerHTML = "";
      const span = document.createElement("span");
      span.className = isErr ? "err" : "";
      span.textContent = msg;
      this.hud.appendChild(span);
    }
  }
}

/* ---------------------------------------------------------------- audio
 * Opus over 0x01 frames -> WebCodecs AudioDecoder -> WebAudio graph.
 * RED (RFC 2198) redundancy is de-framed; redundant blocks are only decoded
 * when a gap is detected (reference client extractOpusFrames,
 * selkies-ws-core.js:36-38). */
class AudioPlayer {
  constructor(serverSettings) {
    const st = serverSettings.settings || {};
    this.sampleRate = 48000;
    this.channels = (st.audio_channels && st.audio_channels.value) || 2;
    this.frameMs = (st.audio_frame_ms && st.audio_frame_ms.value) || 10;
    this.ctx = new AudioContext({ sampleRate: this.sampleRate });
    this.playhead = 0;
    this.tsUs = 0;
    this.queueTarget = 5 * this.frameMs / 1000;  // ≤5 frames client buffer
    this.dec = null;
    this._initDecoder();
  }

  _initDecoder() {
    if (typeof AudioDecoder === "undefined") return;
    this.dec = new AudioDecoder({
      output: (ad) => this._play(ad),
      error: (e) => console.warn("audio decode", e),
    });
    this.dec.configure({
      codec: "opus", sampleRate: this.sampleRate,
      numberOfChannels: this.channels,
    });
  }

  push(buf) {
    if (!this.dec || this.dec.state !== "configured") return;
    const nRed = buf[1];
    let payload = buf.subarray(2);
    if (nRed > 0) {
      // RED: u32 pts + nRed*4-byte block hdrs + 1-byte primary hdr + blocks
      let off = 4 + nRed * 4 + 1;
      const dv = new DataView(buf.buffer, buf.byteOffset + 2);
      let skip = 0;
      for (let i = 0; i < nRed; i++)
        skip += dv.getUint32(4 + i * 4) & 0x3FF;   // 10-bit block length
      payload = payload.subarray(off + skip);       // primary block only
    }
    if (!payload.length) return;
    this.dec.decode(new EncodedAudioChunk({
      type: "key", timestamp: this.tsUs, data: payload,
    }));
    this.tsUs += this.frameMs * 1000;
  }

  _play(ad) {
    const n = ad.numberOfFrames, ch = ad.numberOfChannels;
    const buf = this.ctx.createBuffer(ch, n, ad.sampleRate);
    for (let c = 0; c < ch; c++) {
      const dst = buf.getChannelData(c);
      ad.copyTo(dst, { planeIndex: c, format: "f32-planar" });
    }
    ad.close();
    const now = this.ctx.currentTime;
    if (this.playhead < now) this.playhead = now + 0.01;
    if (this.playhead - now > this.queueTarget * 3) {
      this.playhead = now + this.queueTarget;  // queue ran away: resync
    }
    const src = this.ctx.createBufferSource();
    src.buffer = buf;
    src.connect(this.ctx.destination);
    src.start(this.playhead);
    this.playhead += buf.duration;
  }

  close() {
    if (this.dec) try { this.dec.close(); } catch { /* already closed */ }
    this.ctx.close();
  }
}

/* ------------------------------------------------------------------- mic
 * Capture path: the AudioContext resamples the getUserMedia track to
 * 24 kHz; an AudioWorklet batches ~20 ms (480-sample) mono chunks that
 * are sent as [0x02][s16le PCM] frames — the exact format
 * audio/pipeline.play_mic_pcm feeds pacat. */
class MicSender {
  constructor(client) {
    this.client = client;
    this.ctx = null;
    this.node = null;
    this.stream = null;
  }

  async start() {
    this.stream = await navigator.mediaDevices.getUserMedia({
      audio: { channelCount: 1, echoCancellation: true,
               noiseSuppression: true },
    });
    this.ctx = new AudioContext({ sampleRate: 24000 });
    const src = `registerProcessor("selkies-mic",
      class extends AudioWorkletProcessor {
        process(inputs) {
          const ch = inputs[0] && inputs[0][0];
          if (ch && ch.length) this.port.postMessage(ch.slice(0));
          return true;
        }
      });`;
    const url = URL.createObjectURL(
      new Blob([src], { type: "application/javascript" }));
    try {
      await this.ctx.audioWorklet.addModule(url);
    } finally {
      URL.revokeObjectURL(url);
    }
    const input = this.ctx.createMediaStreamSource(this.stream);
    this.node = new AudioWorkletNode(this.ctx, "selkies-mic");
    this._chunks = [];
    this._n = 0;
    this.node.port.onmessage = (e) => this._onChunk(e.data);
    input.connect(this.node);
    /* no destination connection: capture-only graph */
  }

  _onChunk(f32) {
    this._chunks.push(f32);
    this._n += f32.length;
    if (this._n < 480) return;                    // ~20 ms at 24 kHz
    const all = new Float32Array(this._n);
    let o = 0;
    for (const c of this._chunks) { all.set(c, o); o += c.length; }
    this._chunks = [];
    this._n = 0;
    const frame = new Uint8Array(1 + all.length * 2);
    frame[0] = OP_MIC;
    const dv = new DataView(frame.buffer);
    for (let i = 0; i < all.length; i++) {
      const s = Math.max(-1, Math.min(1, all[i]));
      dv.setInt16(1 + i * 2, s < 0 ? s * 0x8000 : s * 0x7FFF, true);
    }
    this.client.sendBytes(frame);
  }

  stop() {
    if (this.node) { try { this.node.disconnect(); } catch { /* gone */ } }
    if (this.ctx) this.ctx.close();
    if (this.stream)
      for (const t of this.stream.getTracks()) t.stop();
    this.node = this.ctx = this.stream = null;
  }
}

/* ------------------------------------------------------------------ boot */
const canvas = document.getElementById("screen");
const hud = document.getElementById("hud");
const badge = document.getElementById("badge");
const client = new SelkiesClient(canvas, document.getElementById("status"));
badge.addEventListener("click", () => hud.classList.toggle("hidden"));
hud.classList.remove("hidden");
canvas.focus();
client.start();            // picks WS or WebRTC from /api/status
window.selkies = client;   // console / dashboard access
