"""TPU-native WebRTC media plane.

The reference vendors a 15.3k-LoC aiortc/aioice fork for its opt-in
WebRTC transport (reference src/selkies/webrtc/, src/selkies/ice/ —
SURVEY.md §2.2). This package is the from-scratch equivalent sized to
what the product actually uses: the server is the media SENDER of
pre-encoded access units (the reference fork's whole point was the
``Encoder.pack()`` passthrough, rtcrtpsender.py:364-393), so it needs

- an ICE-LITE responder (we are always the public, answering agent),
- a DTLS endpoint (system OpenSSL via ctypes) with RFC 5764 SRTP key
  export,
- SRTP/SRTCP packet protection (RFC 3711, AES-CM-128 + HMAC-SHA1-80),
- RFC 6184 H.264 RTP packetization (single NAL + FU-A) and Opus RTP,
- SDP offer/answer for the browser peer,

and NOT a full ICE agent, TURN client, or DTLS-client media stack.
"""

from .dtls import DtlsEndpoint, generate_certificate   # noqa: F401
from .peer import RTCPeer                              # noqa: F401
from .rtp import H264Packetizer, RtpPacket             # noqa: F401
from .srtp import SrtpContext                          # noqa: F401
from .stun import StunMessage                          # noqa: F401
