"""Send-side congestion control (GCC) from transport-wide-cc feedback.

The reference runs Google Congestion Control inside its vendored webrtc
fork — inter-arrival grouping, overuse detection and AIMD in
src/selkies/webrtc/rate.py:56-491, TWCC feedback surfaced as
``twcc_estimate`` (rtcrtpsender.py:336-337) and consumed by the CBR
steering loop (webrtc_mode.py:1652-1716: loss > 10% backs off x0.7,
clean windows recover x1.15 toward the user ceiling). This is a
clean-room implementation of the same published algorithm (trendline
variant) against the same wire format:

- outgoing RTP carries the transport-wide sequence header extension;
- the browser returns RTCP transport-cc feedback (RTPFB FMT 15,
  draft-holmer-rmcat-transport-wide-cc-extensions-01);
- per feedback batch: packets are grouped into 5 ms send bursts, the
  inter-group delay variation feeds a least-squares trendline whose
  slope is compared against an adaptive threshold (overuse/underuse/
  normal), driving an AIMD rate state machine bounded by the acked
  bitrate; a parallel loss controller applies the reference's x0.7 /
  x1.15 policy.

Everything takes explicit ``now`` timestamps — fully deterministic for
tests (tests/test_webrtc_cc.py)."""

from __future__ import annotations

import collections
import dataclasses
import struct

TWCC_EXT_URI = ("http://www.ietf.org/id/"
                "draft-holmer-rmcat-transport-wide-cc-extensions-01")
TWCC_EXT_ID = 3


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def twcc_extension(seq: int, ext_id: int = TWCC_EXT_ID) -> bytes:
    """One-byte-header extension element carrying the transport-wide
    sequence number (2 bytes)."""
    return bytes(((ext_id << 4) | 1,)) + struct.pack("!H", seq & 0xFFFF)


@dataclasses.dataclass
class TwccFeedback:
    base_seq: int
    ref_time_us: int                 # reference time in microseconds
    fb_count: int
    # (seq, rx_time_us or None) — absolute within the 24-bit ref epoch
    packets: list


def parse_rtcp_twcc(data: bytes) -> list[TwccFeedback]:
    """Extract transport-cc feedback messages (RTPFB FMT 15) from a
    (possibly compound) RTCP packet."""
    out = []
    off = 0
    while off + 8 <= len(data):
        b0, pt, length = struct.unpack_from("!BBH", data, off)
        size = 4 * (length + 1)
        if pt == 205 and (b0 & 0x1F) == 15 and off + 16 <= len(data):
            try:
                fb = _parse_one_twcc(data[off + 8:off + size])
                if fb is not None:
                    out.append(fb)
            except (struct.error, IndexError):
                pass
        off += max(size, 4)
    return out


def _parse_one_twcc(body: bytes) -> TwccFeedback | None:
    if len(body) < 12:
        return None
    base_seq, status_count = struct.unpack_from("!HH", body, 4)
    ref_fb = struct.unpack_from("!I", body, 8)[0]
    ref_time = ref_fb >> 8                       # signed 24-bit, 64 ms units
    if ref_time & 0x800000:
        ref_time -= 1 << 24
    fb_count = ref_fb & 0xFF
    ref_us = ref_time * 64000

    # --- status chunks -> per-packet symbols
    symbols = []
    off = 12
    while len(symbols) < status_count and off + 2 <= len(body):
        chunk = struct.unpack_from("!H", body, off)[0]
        off += 2
        if chunk >> 15 == 0:                     # run-length
            sym = (chunk >> 13) & 0x3
            run = chunk & 0x1FFF
            symbols.extend([sym] * run)
        elif (chunk >> 14) & 1 == 0:             # 14 x 1-bit symbols
            for i in range(14):
                symbols.append((chunk >> (13 - i)) & 1)
        else:                                    # 7 x 2-bit symbols
            for i in range(7):
                symbols.append((chunk >> (12 - 2 * i)) & 0x3)
    symbols = symbols[:status_count]

    # --- receive deltas
    t_us = ref_us
    packets = []
    for i, sym in enumerate(symbols):
        seq = (base_seq + i) & 0xFFFF
        if sym == 1:
            if off + 1 > len(body):
                break
            t_us += body[off] * 250
            off += 1
            packets.append((seq, t_us))
        elif sym == 2:
            if off + 2 > len(body):
                break
            d = struct.unpack_from("!h", body, off)[0]
            off += 2
            t_us += d * 250
            packets.append((seq, t_us))
        else:
            packets.append((seq, None))
    return TwccFeedback(base_seq, ref_us, fb_count, packets)


def build_rtcp_twcc(sender_ssrc: int, media_ssrc: int, base_seq: int,
                    rx_times_us: list, fb_count: int = 0,
                    ref_time_us: int | None = None) -> bytes:
    """Feedback builder (the BROWSER's role) — used by loopback tests and
    any receiving peer we drive ourselves. ``rx_times_us[i]`` is the
    arrival time of packet base_seq+i, or None if lost."""
    if ref_time_us is None:
        first = next((t for t in rx_times_us if t is not None), 0)
        ref_time_us = (first // 64000) * 64000
    symbols = []
    deltas = bytearray()
    t = ref_time_us
    for rx in rx_times_us:
        if rx is None:
            symbols.append(0)
            continue
        d = (rx - t) // 250
        t += d * 250
        if 0 <= d <= 0xFF:
            symbols.append(1)
            deltas.append(d)
        else:
            symbols.append(2)
            deltas += struct.pack("!h", max(-32768, min(32767, d)))
    chunks = bytearray()
    for i in range(0, len(symbols), 7):          # 2-bit vector chunks
        word = 0xC000
        for j, s in enumerate(symbols[i:i + 7]):
            word |= s << (12 - 2 * j)
        chunks += struct.pack("!H", word)
    ref_time = (ref_time_us // 64000) & 0xFFFFFF
    body = struct.pack("!IIHHI", sender_ssrc, media_ssrc, base_seq,
                       len(symbols),
                       (ref_time << 8) | (fb_count & 0xFF))
    body += bytes(chunks) + bytes(deltas)
    while len(body) % 4:
        body += b"\x00"
    return struct.pack("!BBH", 0x80 | 15, 205, len(body) // 4 + 1) + body


# ---------------------------------------------------------------------------
# estimators
# ---------------------------------------------------------------------------

_BURST_US = 5000
_TREND_WINDOW = 20
_THRESHOLD_GAIN = 4.0
_K_UP = 0.0087
_K_DOWN = 0.039
_OVERUSE_TIME_MS = 10.0


class TrendlineEstimator:
    """Inter-group delay-variation trendline + adaptive-threshold overuse
    detector. States: 'normal' | 'overuse' | 'underuse'."""

    def __init__(self):
        self._first_group = None
        self._prev_group = None            # (send_us, arrival_us)
        self._cur_send = None
        self._cur_arrival = None
        self._acc_delay_ms = 0.0
        self._smoothed_ms = 0.0
        self._history = collections.deque(maxlen=_TREND_WINDOW)
        self._num_deltas = 0
        self._threshold = 12.5
        self._last_update_ms = None
        self._overuse_ms = 0.0
        self._prev_trend = 0.0
        self.state = "normal"

    @property
    def threshold(self) -> float:
        """Current adaptive overuse threshold (stats surface)."""
        return self._threshold

    def add_packet(self, send_us: int, arrival_us: int) -> None:
        if self._cur_send is None or send_us - self._cur_send > _BURST_US:
            if self._cur_send is not None:
                self._on_group_done()
            self._cur_send = send_us
            self._cur_arrival = arrival_us
        else:
            self._cur_arrival = max(self._cur_arrival, arrival_us)
        self._last_arrival = arrival_us

    def _on_group_done(self) -> None:
        g = (self._cur_send, self._cur_arrival)
        if self._prev_group is not None:
            send_d = (g[0] - self._prev_group[0]) / 1000.0
            arr_d = (g[1] - self._prev_group[1]) / 1000.0
            delta = arr_d - send_d
            self._num_deltas += 1
            self._acc_delay_ms += delta
            self._smoothed_ms = (0.9 * self._smoothed_ms
                                 + 0.1 * self._acc_delay_ms)
            if self._first_group is None:
                self._first_group = g[1]
            x = (g[1] - self._first_group) / 1000.0
            self._history.append((x, self._smoothed_ms))
            trend = self._slope()
            self._detect(trend, arr_d)
        self._prev_group = g

    def flush(self) -> None:
        """Close the open burst (call once per feedback batch)."""
        if self._cur_send is not None:
            self._on_group_done()
            self._cur_send = None

    def _slope(self) -> float:
        n = len(self._history)
        if n < 2:
            return self._prev_trend
        mx = sum(p[0] for p in self._history) / n
        my = sum(p[1] for p in self._history) / n
        num = sum((p[0] - mx) * (p[1] - my) for p in self._history)
        den = sum((p[0] - mx) ** 2 for p in self._history)
        if den == 0:
            return self._prev_trend
        return num / den

    def _detect(self, trend: float, ts_delta_ms: float) -> None:
        modified = (min(self._num_deltas, 60)
                    * trend * _THRESHOLD_GAIN)
        if modified > self._threshold:
            self._overuse_ms += ts_delta_ms
            if (self._overuse_ms > _OVERUSE_TIME_MS
                    and trend >= self._prev_trend):
                self.state = "overuse"
        elif modified < -self._threshold:
            self._overuse_ms = 0.0
            self.state = "underuse"
        else:
            self._overuse_ms = 0.0
            self.state = "normal"
        self._prev_trend = trend
        # adaptive threshold (clamped drift toward |modified|)
        if self._last_update_ms is None:
            self._last_update_ms = 0.0
        k = _K_DOWN if abs(modified) < self._threshold else _K_UP
        self._threshold += k * (abs(modified) - self._threshold) * 30.0
        self._threshold = min(max(self._threshold, 6.0), 600.0)


class AckedBitrate:
    """Acked throughput over a sliding window."""

    def __init__(self, window_us: int = 500_000):
        self._window = window_us
        self._samples = collections.deque()     # (rx_us, size)
        self._bytes = 0

    def add(self, rx_us: int, size: int) -> None:
        self._samples.append((rx_us, size))
        self._bytes += size
        lo = rx_us - self._window
        while self._samples and self._samples[0][0] < lo:
            self._bytes -= self._samples.popleft()[1]

    def bps(self) -> float | None:
        if len(self._samples) < 2:
            return None
        span = self._samples[-1][0] - self._samples[0][0]
        if span <= 0:
            return None
        return self._bytes * 8 * 1e6 / span


class AimdRateControl:
    """Additive-increase / multiplicative-decrease on the detector state."""

    def __init__(self, start_bps: float = 2_000_000.0,
                 min_bps: float = 150_000.0, max_bps: float = 50_000_000.0):
        self.rate = start_bps
        self.min_bps = min_bps
        self.max_bps = max_bps
        self._state = "increase"
        self._last_decrease_bps = None
        self._last_update_us = None

    @property
    def state(self) -> str:
        """'increase' | 'hold' (stats surface)."""
        return self._state

    def update(self, detector_state: str, acked_bps: float | None,
               now_us: int) -> float:
        dt = 0.0
        if self._last_update_us is not None:
            dt = min((now_us - self._last_update_us) / 1e6, 1.0)
        self._last_update_us = now_us

        if detector_state == "overuse":
            if acked_bps is not None:
                self.rate = max(self.min_bps, 0.85 * acked_bps)
                self._last_decrease_bps = acked_bps
            else:
                self.rate = max(self.min_bps, 0.85 * self.rate)
            self._state = "hold"
        elif detector_state == "underuse":
            self._state = "hold"
        else:
            if self._state == "hold":
                self._state = "increase"
            elif self._state == "increase":
                near_max = (self._last_decrease_bps is not None
                            and self.rate > 0.95 * self._last_decrease_bps)
                if near_max:
                    self.rate += max(4000.0, 0.04 * self.rate) * dt
                else:
                    self.rate *= 1.08 ** dt
        if acked_bps is not None:
            self.rate = min(self.rate, 1.5 * acked_bps + 10_000)
        self.rate = min(max(self.rate, self.min_bps), self.max_bps)
        return self.rate


class LossController:
    """The reference loop's loss policy (webrtc_mode.py:1652-1716):
    loss > 10%% over a window backs the cap off x0.7 (at most once per
    backoff interval); loss < 2%% recovers x1.15 toward the ceiling."""

    def __init__(self, ceiling_bps: float, min_bps: float = 150_000.0,
                 backoff_interval_us: int = 300_000):
        self.cap = ceiling_bps
        self.ceiling = ceiling_bps
        self.min_bps = min_bps
        self._interval = backoff_interval_us
        self._last_change_us = None

    def update(self, loss_fraction: float, now_us: int) -> float:
        if (self._last_change_us is not None
                and now_us - self._last_change_us < self._interval):
            return self.cap
        if loss_fraction > 0.10:
            self.cap = max(self.min_bps, self.cap * 0.7)
            self._last_change_us = now_us
        elif loss_fraction < 0.02:
            self.cap = min(self.ceiling, self.cap * 1.15)
            self._last_change_us = now_us
        return self.cap


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------

class SendSideCongestionController:
    """Ties the pieces together for one peer (all media share one
    transport-wide sequence space, RFC 8888 style)."""

    #: how long a "not received" TWCC symbol may stay provisional before
    #: it is finalised as a loss. Browsers routinely report a packet as
    #: missing in one feedback and received in the next (reordering /
    #: delayed delivery); counting it lost on first sight inflates
    #: last_loss_fraction and triggers spurious 0.7x backoffs.
    LOSS_GRACE_US = 300_000

    #: sliding window for the loss fraction — must comfortably cover
    #: LOSS_GRACE_US so a finalised loss is compared against the
    #: receives of its own era rather than one feedback batch's
    LOSS_WINDOW_US = 1_000_000

    def __init__(self, ceiling_bps: float = 20_000_000.0,
                 start_bps: float = 2_000_000.0):
        self._next_seq = 0
        self._sent = collections.OrderedDict()   # seq -> (send_us, size)
        self._missing = {}                       # seq -> first-missing us
        # per-feedback (now_us, received, lost) samples: the loss
        # fraction is computed over a sliding window so grace-delayed
        # loss finalisations are weighed against the receives of THEIR
        # window, not whatever single feedback they land in
        self._loss_window = collections.deque()
        # newest send time already fed to the trendline: a late packet
        # (missing in one feedback, received in a later one) must not be
        # grouped behind newer packets — the out-of-order send time would
        # inject a huge spurious delay-delta and a false overuse signal
        self._max_send_fed = -1
        self._trend = TrendlineEstimator()
        self._acked = AckedBitrate()
        self._aimd = AimdRateControl(start_bps=start_bps,
                                     max_bps=ceiling_bps)
        self._loss = LossController(ceiling_bps)
        self._evicted_lost = 0
        self.target_bps = start_bps
        self.last_loss_fraction = 0.0
        #: TWCC round-trip: feedback arrival minus the newest acked
        #: packet's send time (the standard send-side RTT proxy —
        #: older packets in the batch include feedback batching delay)
        self.last_rtt_ms: float | None = None
        self.srtt_ms: float | None = None     # RFC6298-style 1/8 EWMA

    # -- sender side --------------------------------------------------------
    def alloc_seq(self) -> int:
        s = self._next_seq
        self._next_seq = (self._next_seq + 1) & 0xFFFF
        return s

    def on_packet_sent(self, seq: int, size: int, now_us: int) -> None:
        self._sent[seq] = (now_us, size)
        while len(self._sent) > 4096:
            old_seq, _ = self._sent.popitem(last=False)
            # a packet evicted while still marked missing really was
            # lost; silently dropping it made the sliding-window loss
            # fraction underestimate under sustained heavy loss at high
            # send rates, so the 0.7x backoff could fail to fire
            # (ADVICE r4)
            if self._missing.pop(old_seq, None) is not None:
                self._evicted_lost += 1

    # -- feedback -----------------------------------------------------------
    def on_feedback(self, fb: TwccFeedback, now_us: int) -> float:
        received = 0
        lost = 0
        newest_send_us = None
        for seq, rx_us in fb.packets:
            if rx_us is None:
                # provisional: a later feedback often re-reports the same
                # seq as received — keep it in _sent for a grace window
                if seq in self._sent:
                    self._missing.setdefault(seq, now_us)
                continue
            sent = self._sent.pop(seq, None)
            self._missing.pop(seq, None)
            if sent is None:
                continue
            send_us, size = sent
            received += 1
            if newest_send_us is None or send_us > newest_send_us:
                newest_send_us = send_us
            self._acked.add(rx_us, size)
            if send_us >= self._max_send_fed:
                self._max_send_fed = send_us
                self._trend.add_packet(send_us, rx_us)
        # finalise losses whose grace window has expired
        for seq in [s for s, t in self._missing.items()
                    if now_us - t >= self.LOSS_GRACE_US]:
            del self._missing[seq]
            if self._sent.pop(seq, None) is not None:
                lost += 1
        self._trend.flush()
        lost += self._evicted_lost
        self._evicted_lost = 0
        self._loss_window.append((now_us, received, lost))
        lo = now_us - self.LOSS_WINDOW_US
        while self._loss_window and self._loss_window[0][0] < lo:
            self._loss_window.popleft()
        w_recv = sum(s[1] for s in self._loss_window)
        w_lost = sum(s[2] for s in self._loss_window)
        if w_recv + w_lost:
            self.last_loss_fraction = w_lost / (w_recv + w_lost)
        if newest_send_us is not None and now_us >= newest_send_us:
            rtt = (now_us - newest_send_us) / 1000.0
            self.last_rtt_ms = rtt
            self.srtt_ms = rtt if self.srtt_ms is None \
                else self.srtt_ms + 0.125 * (rtt - self.srtt_ms)
        delay_rate = self._aimd.update(self._trend.state,
                                       self._acked.bps(), now_us)
        loss_cap = self._loss.update(self.last_loss_fraction, now_us)
        self.target_bps = max(self._aimd.min_bps,
                              min(delay_rate, loss_cap))
        return self.target_bps

    def stats(self) -> dict:
        """Coherent snapshot of the controller's internals — the
        ``getStats()`` surface the per-session QoE plane
        (:mod:`...obs.qoe`) and ``GET /api/sessions`` expose. Plain
        data, safe to call from any thread between feedback batches."""
        return {
            "target_bps": round(self.target_bps, 1),
            "acked_bps": (round(b, 1)
                          if (b := self._acked.bps()) is not None else None),
            "detector_state": self._trend.state,
            "trend_threshold": round(self._trend.threshold, 3),
            "aimd_state": self._aimd.state,
            "aimd_rate_bps": round(self._aimd.rate, 1),
            "loss_fraction": round(self.last_loss_fraction, 4),
            "loss_cap_bps": round(self._loss.cap, 1),
            "rtt_ms": (round(self.srtt_ms, 3)
                       if self.srtt_ms is not None else None),
            "last_rtt_ms": (round(self.last_rtt_ms, 3)
                            if self.last_rtt_ms is not None else None),
            "in_flight": len(self._sent),
            "provisional_missing": len(self._missing),
        }

    def on_rtcp(self, rtcp: bytes, now_us: int) -> float | None:
        """Feed a full (decrypted) RTCP packet; returns the new target
        when it carried transport-cc feedback."""
        fbs = parse_rtcp_twcc(rtcp)
        if not fbs:
            return None
        for fb in fbs:
            self.on_feedback(fb, now_us)
        return self.target_bps
