"""DTLS endpoint over the system OpenSSL (libssl.so.3) via ctypes.

Replaces the reference's vendored ``rtcdtlstransport.py`` (869 LoC on
pylibsrtp + pyOpenSSL, reference src/selkies/webrtc/rtcdtlstransport.py)
with a memory-BIO driven endpoint: datagrams in via :meth:`feed`,
outgoing flights out via :meth:`take_outgoing`, SRTP master keys out via
:meth:`export_srtp_keys` (RFC 5764 ``EXTRACTOR-dtls_srtp``).

Both roles are implemented — the server answers browsers as
``a=setup:passive``'s peer, and the client role lets the test suite run
a full loopback handshake without any browser.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import hashlib
import os
import tempfile
import threading

def _load(*names: str) -> ctypes.CDLL:
    last: Exception | None = None
    for n in names:
        if not n:
            continue
        try:
            return ctypes.CDLL(n)
        except OSError as e:
            last = e
    raise ImportError(f"no usable OpenSSL library ({names}): {last}")


_ssl = _load("libssl.so.3", ctypes.util.find_library("ssl"))
_crypto = _load("libcrypto.so.3", ctypes.util.find_library("crypto"))

for _fn, _res, _args in [
    ("DTLS_server_method", ctypes.c_void_p, []),
    ("DTLS_client_method", ctypes.c_void_p, []),
    ("SSL_CTX_new", ctypes.c_void_p, [ctypes.c_void_p]),
    ("SSL_CTX_free", None, [ctypes.c_void_p]),
    ("SSL_CTX_use_certificate_file", ctypes.c_int,
     [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]),
    ("SSL_CTX_use_PrivateKey_file", ctypes.c_int,
     [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]),
    ("SSL_CTX_set_tlsext_use_srtp", ctypes.c_int,
     [ctypes.c_void_p, ctypes.c_char_p]),
    ("SSL_CTX_set_verify", None,
     [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]),
    ("SSL_new", ctypes.c_void_p, [ctypes.c_void_p]),
    ("SSL_free", None, [ctypes.c_void_p]),
    ("SSL_set_bio", None, [ctypes.c_void_p, ctypes.c_void_p,
                           ctypes.c_void_p]),
    ("SSL_set_accept_state", None, [ctypes.c_void_p]),
    ("SSL_set_connect_state", None, [ctypes.c_void_p]),
    ("SSL_do_handshake", ctypes.c_int, [ctypes.c_void_p]),
    ("SSL_get_error", ctypes.c_int, [ctypes.c_void_p, ctypes.c_int]),
    ("SSL_is_init_finished", ctypes.c_int, [ctypes.c_void_p]),
    ("SSL_read", ctypes.c_int,
     [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]),
    ("SSL_write", ctypes.c_int,
     [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]),
    ("SSL_shutdown", ctypes.c_int, [ctypes.c_void_p]),
    ("SSL_export_keying_material", ctypes.c_int,
     [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
      ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int]),
    ("SSL_get1_peer_certificate", ctypes.c_void_p, [ctypes.c_void_p]),
    ("BIO_new", ctypes.c_void_p, [ctypes.c_void_p]),
    ("BIO_s_mem", ctypes.c_void_p, []),
    ("BIO_write", ctypes.c_int,
     [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]),
    ("BIO_read", ctypes.c_int,
     [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]),
    ("BIO_ctrl_pending", ctypes.c_size_t, [ctypes.c_void_p]),
]:
    # a missing symbol (old libcrypto without e.g.
    # SSL_get1_peer_certificate) must be an ImportError, not the
    # AttributeError ctypes raises: importers — including pytest's
    # module-level importorskip in the webrtc tests — treat "this
    # OpenSSL cannot back the module" as an import failure
    f = getattr(_ssl, _fn, None) or getattr(_crypto, _fn, None)
    if f is None:
        raise ImportError(
            f"OpenSSL symbol {_fn} unavailable — a libssl/libcrypto "
            "with the DTLS-SRTP surface (>= 1.1.1/3.x) is required")
    f.restype = _res
    f.argtypes = _args
    globals()["_" + _fn] = f

_crypto.i2d_X509.restype = ctypes.c_int
_crypto.i2d_X509.argtypes = [ctypes.c_void_p,
                             ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte))]
_crypto.X509_free.argtypes = [ctypes.c_void_p]
# OPENSSL_free is a macro over CRYPTO_free(ptr, file, line)
_crypto.CRYPTO_free.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int]

SSL_ERROR_WANT_READ = 2
SSL_FILETYPE_PEM = 1
SSL_VERIFY_PEER = 0x01
SRTP_PROFILE = b"SRTP_AES128_CM_SHA1_80"

# accept any peer cert at the TLS layer; authenticity is the SDP
# fingerprint's job (RFC 8122), enforced in verify_peer_fingerprint()
_VERIFY_CB = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int, ctypes.c_void_p)(
    lambda ok, store_ctx: 1)

_cert_lock = threading.Lock()
_cert_cache: tuple[str, str, str] | None = None


def generate_certificate() -> tuple[str, str, str]:
    """-> (cert_pem_path, key_pem_path, sha256_fingerprint). One
    self-signed ECDSA P-256 certificate per process (like a browser's
    per-session DTLS identity)."""
    global _cert_cache
    with _cert_lock:
        if _cert_cache is not None:
            return _cert_cache
        import datetime

        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID

        key = ec.generate_private_key(ec.SECP256R1())
        name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, "selkies-tpu")])
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - datetime.timedelta(days=1))
                .not_valid_after(now + datetime.timedelta(days=30))
                .sign(key, hashes.SHA256()))
        der = cert.public_bytes(serialization.Encoding.DER)
        fp = hashlib.sha256(der).hexdigest()
        fingerprint = ":".join(fp[i:i + 2].upper()
                               for i in range(0, len(fp), 2))
        d = tempfile.mkdtemp(prefix="selkies-dtls-")
        cert_path = os.path.join(d, "cert.pem")
        key_path = os.path.join(d, "key.pem")
        with open(cert_path, "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))
        with open(key_path, "wb") as f:
            f.write(key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption()))
        os.chmod(key_path, 0o600)
        _cert_cache = (cert_path, key_path, fingerprint)
        return _cert_cache


class DtlsError(Exception):
    pass


class DtlsEndpoint:
    """One DTLS association driven through memory BIOs."""

    def __init__(self, server: bool, cert_path: str | None = None,
                 key_path: str | None = None):
        if cert_path is None:
            cert_path, key_path, _ = generate_certificate()
        method = _DTLS_server_method() if server else _DTLS_client_method()
        self._ctx = _SSL_CTX_new(method)
        if not self._ctx:
            raise DtlsError("SSL_CTX_new failed")
        if _SSL_CTX_use_certificate_file(
                self._ctx, cert_path.encode(), SSL_FILETYPE_PEM) != 1:
            raise DtlsError("certificate load failed")
        if _SSL_CTX_use_PrivateKey_file(
                self._ctx, key_path.encode(), SSL_FILETYPE_PEM) != 1:
            raise DtlsError("private key load failed")
        if _SSL_CTX_set_tlsext_use_srtp(self._ctx, SRTP_PROFILE) != 0:
            raise DtlsError("use_srtp profile rejected")
        # request the peer's cert in both roles (fingerprint auth)
        _SSL_CTX_set_verify(self._ctx, SSL_VERIFY_PEER, _VERIFY_CB)
        self._ssl = _SSL_new(self._ctx)
        self._rbio = _BIO_new(_BIO_s_mem())
        self._wbio = _BIO_new(_BIO_s_mem())
        _SSL_set_bio(self._ssl, self._rbio, self._wbio)
        if server:
            _SSL_set_accept_state(self._ssl)
        else:
            _SSL_set_connect_state(self._ssl)
        self.server = server
        self._complete = False

    # -- datagram pump ------------------------------------------------------
    def feed(self, datagram: bytes) -> list[bytes]:
        """Process one inbound datagram; returns decrypted application
        RECORDS (one list entry per DTLS record — the SCTP layer needs
        packet framing preserved, never concatenated)."""
        _BIO_write(self._rbio, datagram, len(datagram))
        return self._pump()

    def handshake(self) -> None:
        """Kick the handshake state machine (client: emits ClientHello)."""
        self._pump()

    def send_app(self, data: bytes) -> None:
        """Queue one application record (an SCTP packet); drain the wire
        bytes with :meth:`take_outgoing`."""
        if not self._complete:
            raise DtlsError("handshake not complete")
        rc = _SSL_write(self._ssl, data, len(data))
        if rc <= 0:
            raise DtlsError(f"SSL_write failed ({rc})")

    def _pump(self) -> list[bytes]:
        app: list[bytes] = []
        if not self._complete:
            rc = _SSL_do_handshake(self._ssl)
            if rc == 1:
                self._complete = True
            else:
                err = _SSL_get_error(self._ssl, rc)
                if err != SSL_ERROR_WANT_READ:
                    raise DtlsError(f"handshake failed (ssl error {err})")
        if self._complete:
            buf = ctypes.create_string_buffer(8192)
            while True:
                n = _SSL_read(self._ssl, buf, len(buf))
                if n <= 0:
                    break
                app.append(buf.raw[:n])
        return app

    def take_outgoing(self) -> bytes:
        """Drain pending handshake/alert records as one datagram blob
        (DTLS permits multiple records per datagram)."""
        pending = _BIO_ctrl_pending(self._wbio)
        if not pending:
            return b""
        buf = ctypes.create_string_buffer(int(pending))
        n = _BIO_read(self._wbio, buf, int(pending))
        return buf.raw[:n] if n > 0 else b""

    # -- post-handshake -----------------------------------------------------
    @property
    def handshake_complete(self) -> bool:
        return self._complete

    def export_srtp_keys(self) -> tuple[bytes, bytes]:
        """-> (client_master, server_master), each 16-byte key + 14-byte
        salt, per RFC 5764 §4.2."""
        if not self._complete:
            raise DtlsError("handshake not complete")
        out = ctypes.create_string_buffer(60)
        rc = _SSL_export_keying_material(
            self._ssl, out, 60, b"EXTRACTOR-dtls_srtp", 19, None, 0, 0)
        if rc != 1:
            raise DtlsError("SRTP key export failed")
        m = out.raw
        ck, sk, cs, ss = m[0:16], m[16:32], m[32:46], m[46:60]
        return ck + cs, sk + ss

    def peer_fingerprint(self) -> str:
        cert = _SSL_get1_peer_certificate(self._ssl)
        if not cert:
            raise DtlsError("no peer certificate")
        try:
            p = ctypes.POINTER(ctypes.c_ubyte)()
            n = _crypto.i2d_X509(cert, ctypes.byref(p))
            if n <= 0:
                raise DtlsError("i2d_X509 failed")
            der = ctypes.string_at(p, n)
            _crypto.CRYPTO_free(p, b"", 0)
        finally:
            _crypto.X509_free(cert)
        fp = hashlib.sha256(der).hexdigest()
        return ":".join(fp[i:i + 2].upper() for i in range(0, len(fp), 2))

    def verify_peer_fingerprint(self, expected: str) -> bool:
        want = expected.replace(":", "").lower()
        have = self.peer_fingerprint().replace(":", "").lower()
        return want == have

    def close(self):
        if getattr(self, "_ssl", None):
            _SSL_free(self._ssl)    # frees both BIOs
            self._ssl = None
        if getattr(self, "_ctx", None):
            _SSL_CTX_free(self._ctx)
            self._ctx = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
