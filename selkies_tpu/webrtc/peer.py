"""RTCPeer: one browser peer = one UDP socket muxing STUN + DTLS + SRTP.

The reference holds an RTCPeerConnection per peer with per-display media
graphs (reference src/selkies/rtc.py:1171-1302). Here a peer is an
asyncio DatagramProtocol plus three tiny state machines; demux is the
RFC 7983 first-byte rule. Media in is the engine's pre-encoded Annex-B
access units; media out of the peer is SRTP on the wire."""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

from ..taskutil import spawn_retained
from .cc import SendSideCongestionController
from .dtls import DtlsEndpoint, generate_certificate
from .rtp import (H264Packetizer, OpusPacketizer, parse_rtcp_pli,
                  parse_rtcp_remb)
from .sdp import RemoteDescription, build_offer, parse_answer
from .srtp import SrtpContext, SrtpError
from .stun import IceLiteResponder, is_stun, make_ice_credentials

logger = logging.getLogger("selkies_tpu.webrtc.peer")


class RTCPeer(asyncio.DatagramProtocol):
    """Server-side peer: ICE-lite responder + DTLS server + SRTP sender.

    Lifecycle: ``await peer.listen()`` -> ``peer.create_offer()`` ->
    (signaling) -> ``peer.set_remote_answer(sdp)`` -> datagrams drive the
    handshake -> ``peer.connected`` -> ``send_video_au()`` /
    ``send_audio_frame()``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 on_request_keyframe: Optional[Callable] = None,
                 with_audio: bool = True, fullcolor: bool = False,
                 on_datachannel_message: Optional[Callable] = None,
                 on_bitrate_estimate: Optional[Callable] = None,
                 turn_config: Optional[dict] = None,
                 with_mic: bool = False,
                 on_audio_packet: Optional[Callable] = None,
                 audio_params: Optional[dict] = None):
        self.host = host
        self.port = port
        self.ufrag, self.pwd = make_ice_credentials()
        self.ice = IceLiteResponder(self.ufrag, self.pwd)
        self.dtls = DtlsEndpoint(server=True)
        self.srtp: SrtpContext | None = None
        # GCC send-side estimate from browser transport-cc feedback; video
        # and audio share the transport-wide sequence space (reference:
        # twcc_estimate in rtcrtpsender.py:336-337 feeds the CBR loop)
        self.cc = SendSideCongestionController()
        self.video = H264Packetizer(twcc_alloc=self.cc.alloc_seq)
        self.audio = OpusPacketizer(twcc_alloc=self.cc.alloc_seq)
        self.remote: RemoteDescription | None = None
        self.on_request_keyframe = on_request_keyframe
        self.on_datachannel_message = on_datachannel_message
        self.on_bitrate_estimate = on_bitrate_estimate
        self.sctp = None                 # SctpAssociation after DTLS
        self.with_audio = with_audio
        self.fullcolor = fullcolor
        self._transport: asyncio.DatagramTransport | None = None
        self._peer_addr: tuple[str, int] | None = None
        self.connected = asyncio.Event()
        self._t0 = time.monotonic()
        self._last_sr = 0.0
        self._closed = False
        #: TURN relay (webrtc/turn.py): allocated on listen() when
        #: configured. Replies always ride the path a datagram ARRIVED
        #: on (forcing relay replies to direct-path checks would break
        #: the direct candidate pair for NAT'd browsers whose mapped
        #: address shows up on both paths); media follows the path of
        #: the nominating check.
        self.turn_config = turn_config
        self.turn = None
        self.relay_addr: tuple[str, int] | None = None
        self._peer_via_turn = False
        self._turn_bound: set = set()
        # strong refs to fire-and-forget tasks (TURN binds/permissions):
        # the loop only holds weak references, so a bare ensure_future
        # can be collected before it runs
        self._bg_tasks: set = set()
        #: browser mic receive path (reference rtc.py:1303): sendrecv
        #: audio m-line + a compact reorder buffer in front of
        #: ``on_audio_packet(opus_payload, seq, rtp_ts)``
        self.with_mic = with_mic
        self.on_audio_packet = on_audio_packet
        self.audio_params = audio_params   # multiopus surround layout
        self._mic_next: int | None = None
        self._mic_buf: dict[int, object] = {}

    # -- socket -------------------------------------------------------------
    async def listen(self) -> int:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, self.port))
        self.port = self._transport.get_extra_info("sockname")[1]
        if self.turn_config:
            await self._allocate_relay()
        return self.port

    async def _allocate_relay(self) -> None:
        """Best-effort TURN allocation — a dead relay must never block
        the direct host-candidate path."""
        from .turn import TurnClient, TurnError
        cfg = self.turn_config
        try:
            self.turn = TurnClient(
                (cfg["host"], int(cfg.get("port", 3478))),
                cfg.get("username", ""), cfg.get("password", ""),
                on_data=self._on_turn_data)
            await self.turn.connect()
            self.relay_addr = await asyncio.wait_for(
                self.turn.allocate(), 10.0)
        except (TurnError, OSError, asyncio.TimeoutError, KeyError) as e:
            logger.warning("turn allocation failed (%s); direct path only",
                           e)
            if self.turn is not None:
                self.turn.close()
                self.turn = None
            self.relay_addr = None

    def _on_turn_data(self, data: bytes, peer) -> None:
        """Datagram a peer sent to our relayed address: same demux;
        replies ride the relay because that is the arrival path."""
        try:
            self._demux(data, peer, via_turn=True)
        except Exception:
            logger.exception("turn-relayed datagram error")

    def _sendto(self, data: bytes, addr, via_turn: bool = False) -> None:
        if via_turn and self.turn is not None:
            self.turn.send_to_peer(data, addr)
        elif self._transport is not None:
            self._transport.sendto(data, addr)

    def _send_peer(self, data: bytes) -> None:
        """Send to the nominated peer on its selected path."""
        self._sendto(data, self._peer_addr, via_turn=self._peer_via_turn)

    def connection_made(self, transport):
        self._transport = transport

    def datagram_received(self, data: bytes, addr):
        try:
            self._demux(data, addr)
        except Exception:
            logger.exception("peer datagram error")

    # -- demux (RFC 7983) ---------------------------------------------------
    def _demux(self, data: bytes, addr, via_turn: bool = False) -> None:
        if not data:
            return
        b = data[0]
        if is_stun(data):
            resp = self.ice.handle(data, addr)
            if resp:
                self._sendto(resp, addr, via_turn=via_turn)
            if self.ice.nominated_addr:
                # media follows the path the nominating check arrived on
                if self.ice.nominated_addr == addr:
                    self._peer_via_turn = via_turn
                self._peer_addr = self.ice.nominated_addr
                if (self._peer_via_turn and self.turn is not None
                        and self._peer_addr not in self._turn_bound):
                    # nominated via the relay: bind a channel (4-byte
                    # framing instead of 36-byte Send indications)
                    self._turn_bound.add(self._peer_addr)
                    self._spawn_retained(
                        self._bind_channel(self._peer_addr))
        elif 20 <= b <= 63:                       # DTLS
            self._peer_addr = addr
            self._peer_via_turn = via_turn
            records = self.dtls.feed(data)
            self._flush_dtls(addr, via_turn)
            if self.dtls.handshake_complete and self.srtp is None:
                self._on_dtls_complete()
            if self.sctp is not None:
                for rec in records:               # app data = SCTP packets
                    self.sctp.receive(rec)
                self.sctp.poll_timers()
                self._flush_dtls(addr, via_turn)
        elif 128 <= b <= 191 and self.srtp is not None:
            self._on_srtp(data)

    async def _bind_channel(self, peer) -> None:
        from .turn import TurnError
        turn = self.turn
        if turn is None:                   # torn down before we ran
            return
        try:
            await turn.channel_bind(peer)
        except (TurnError, OSError) as e:
            logger.warning("turn channel bind failed: %s", e)
            self._turn_bound.discard(peer)

    def _flush_dtls(self, addr, via_turn: bool = False) -> None:
        out = self.dtls.take_outgoing()
        if out:
            self._sendto(out, addr, via_turn=via_turn)

    def _on_dtls_complete(self) -> None:
        if self.remote and self.remote.fingerprint:
            if not self.dtls.verify_peer_fingerprint(
                    self.remote.fingerprint):
                logger.error("peer fingerprint mismatch; dropping")
                self.close()
                return
        client_master, server_master = self.dtls.export_srtp_keys()
        # we are the DTLS server
        self.srtp = SrtpContext(client_master, server_master,
                                is_client=False)
        from .sctp import SctpAssociation
        self.sctp = SctpAssociation(
            self._send_sctp, server=True,
            on_message=self._on_channel_message)
        self.connected.set()
        logger.info("webrtc peer connected (srtp up, addr=%s)",
                    self._peer_addr)

    def _send_sctp(self, packet: bytes) -> None:
        try:
            self.dtls.send_app(packet)
        except Exception:
            return
        out = self.dtls.take_outgoing()
        if out and self._peer_addr:
            self._send_peer(out)

    def _on_channel_message(self, channel, data: bytes, ppid: int) -> None:
        if self.on_datachannel_message is not None:
            text = data.decode("utf-8", "replace") if ppid != 53 else data
            try:
                self.on_datachannel_message(channel.label, text)
            except Exception:
                logger.exception("datachannel handler failed")

    def send_channel_message(self, text: str, sid: int | None = None
                             ) -> bool:
        """Server -> browser control message on the first open channel."""
        if self.sctp is None or self.sctp.state != "ESTABLISHED":
            return False
        if sid is None:
            if not self.sctp.channels:
                return False
            sid = next(iter(self.sctp.channels))
        self.sctp.send(sid, text.encode())
        return True

    def _on_srtp(self, data: bytes) -> None:
        pt = data[1] & 0x7F
        if 64 <= pt <= 95:                        # RTCP range (RFC 5761)
            try:
                rtcp = self.srtp.unprotect_rtcp(data)
            except SrtpError:
                return
            if parse_rtcp_pli(rtcp) and self.on_request_keyframe:
                self.on_request_keyframe()
            now_us = int(time.monotonic() * 1e6)
            gcc = self.cc.on_rtcp(rtcp, now_us)
            remb = parse_rtcp_remb(rtcp)
            if self.on_bitrate_estimate:
                # send-side GCC is authoritative when feedback flows;
                # REMB is the receiver-computed fallback estimate
                if gcc is not None:
                    self.on_bitrate_estimate(
                        int(min(gcc, remb) if remb else gcc))
                elif remb is not None:
                    self.on_bitrate_estimate(remb)
            return
        # inbound RTP: the browser's microphone track (sendrecv audio)
        if self.on_audio_packet is None:
            return
        try:
            rtp = self.srtp.unprotect_rtp(data)
        except SrtpError:
            return
        from .rtp import RtpPacket
        try:
            pkt = RtpPacket.parse(rtp)
        except ValueError:
            return
        if pkt.payload_type != self.audio.payload_type or not pkt.payload:
            return
        self._deliver_mic(pkt)

    def _deliver_mic(self, pkt) -> None:
        """Tiny reorder buffer (up to 8 packets ≈ 160 ms at 20 ms
        frames): late packets re-sequence, real gaps are skipped so a
        single loss can't dam the stream (the reference's jitterbuffer
        role, fork jitterbuffer.py, scoped to the mic's low rate)."""
        seq = pkt.seq
        if self._mic_next is None:
            self._mic_next = seq
        if (seq - self._mic_next) & 0xFFFF >= 0x8000:
            return                                  # duplicate / too late
        self._mic_buf[seq] = pkt
        while True:
            nxt = self._mic_buf.pop(self._mic_next, None)
            if nxt is not None:
                try:
                    self.on_audio_packet(nxt.payload, nxt.seq,
                                         nxt.timestamp)
                except Exception:
                    logger.exception("mic packet handler failed")
                self._mic_next = (self._mic_next + 1) & 0xFFFF
            elif len(self._mic_buf) > 8:
                # gap won't fill: jump to the oldest buffered packet
                self._mic_next = min(
                    self._mic_buf,
                    key=lambda s: (s - self._mic_next) & 0xFFFF)
            else:
                return

    # -- signaling ----------------------------------------------------------
    def create_offer(self) -> str:
        _, _, fingerprint = generate_certificate()
        return build_offer(self.host, self.port, self.ufrag, self.pwd,
                           fingerprint, video_pt=self.video.payload_type,
                           audio_pt=self.audio.payload_type,
                           with_audio=self.with_audio,
                           fullcolor=self.fullcolor,
                           relay=self.relay_addr,
                           with_mic=self.with_mic,
                           audio_params=self.audio_params)

    def set_remote_answer(self, sdp: str) -> None:
        self.remote = parse_answer(sdp)
        self.ice.set_remote(self.remote.ice_ufrag, self.remote.ice_pwd)
        # relay path: the TURN server only forwards peers we hold
        # permissions for — install one per remote candidate IP
        for cand in self.remote.candidates:
            self.add_remote_candidate(cand)

    def add_remote_candidate(self, candidate: str) -> None:
        """Install a TURN permission for a remote candidate (answer SDP
        or trickled) so its checks can reach our relayed address. Only
        literal IPv4 connection addresses are usable — mDNS ``.local``
        hostnames (Chrome's default host candidates) and IPv6 have no
        relay permission to install."""
        if self.turn is None:
            return
        parts = candidate.split()
        ip = parts[4] if len(parts) >= 5 else ""
        try:
            import socket
            socket.inet_aton(ip)
        except (OSError, UnicodeEncodeError):
            return
        turn = self.turn

        async def _perm():
            from .turn import TurnError
            try:
                await turn.create_permission(ip)
            except (TurnError, OSError) as e:
                logger.warning("turn permission for %s failed: %s", ip, e)
        self._spawn_retained(_perm())

    # -- media --------------------------------------------------------------
    @property
    def can_send(self) -> bool:
        return (self.srtp is not None and self._peer_addr is not None
                and not self._closed)

    def video_timestamp(self) -> int:
        return int((time.monotonic() - self._t0) * 90000) & 0xFFFFFFFF

    def send_video_au(self, annexb: bytes, timestamp: int | None = None
                      ) -> int:
        """Packetize + protect + send one pre-encoded access unit.
        Returns packets sent (0 when not connected — drop, never block:
        the relay/backpressure contract lives upstream)."""
        if not self.can_send:
            return 0
        ts = self.video_timestamp() if timestamp is None else timestamp
        pkts = self.video.packetize(annexb, ts)
        now_us = int(time.monotonic() * 1e6)
        for p in pkts:
            wire = self.srtp.protect_rtp(p.to_bytes())
            self._send_peer(wire)
            if p.twcc_seq is not None:
                self.cc.on_packet_sent(p.twcc_seq, len(wire), now_us)
        now = time.monotonic()
        if now - self._last_sr > 1.0:
            self._last_sr = now
            self._send_peer(
                self.srtp.protect_rtcp(self.video.sender_report(ts)))
        return len(pkts)

    def send_audio_frame(self, opus: bytes, timestamp: int) -> int:
        if not self.can_send:
            return 0
        p = self.audio.packetize(opus, timestamp)
        wire = self.srtp.protect_rtp(p.to_bytes())
        self._send_peer(wire)
        if p.twcc_seq is not None:
            self.cc.on_packet_sent(p.twcc_seq, len(wire),
                                   int(time.monotonic() * 1e6))
        return 1

    def stats(self) -> dict:
        """Wire-side snapshot for the per-session QoE plane: congestion
        controller internals (:meth:`~.cc.SendSideCongestionController.
        stats`) plus packetizer counters and connection state."""
        d = self.cc.stats()
        d["connected"] = self.connected.is_set()
        d["via_turn"] = self._peer_via_turn
        d["video"] = self.video.stats()
        d["audio"] = self.audio.stats()
        return d

    def _spawn_retained(self, coro) -> asyncio.Task:
        """Background task retained on the peer; cancelled on
        close()."""
        return spawn_retained(self._bg_tasks, coro)

    def close(self) -> None:
        self._closed = True
        for task in list(self._bg_tasks):
            task.cancel()
        if self.turn is not None:
            self.turn.close()
            self.turn = None
        if self._transport:
            self._transport.close()
            self._transport = None
        self.dtls.close()
