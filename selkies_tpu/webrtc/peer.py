"""RTCPeer: one browser peer = one UDP socket muxing STUN + DTLS + SRTP.

The reference holds an RTCPeerConnection per peer with per-display media
graphs (reference src/selkies/rtc.py:1171-1302). Here a peer is an
asyncio DatagramProtocol plus three tiny state machines; demux is the
RFC 7983 first-byte rule. Media in is the engine's pre-encoded Annex-B
access units; media out of the peer is SRTP on the wire."""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

from .cc import SendSideCongestionController
from .dtls import DtlsEndpoint, generate_certificate
from .rtp import (H264Packetizer, OpusPacketizer, parse_rtcp_pli,
                  parse_rtcp_remb)
from .sdp import RemoteDescription, build_offer, parse_answer
from .srtp import SrtpContext, SrtpError
from .stun import IceLiteResponder, is_stun, make_ice_credentials

logger = logging.getLogger("selkies_tpu.webrtc.peer")


class RTCPeer(asyncio.DatagramProtocol):
    """Server-side peer: ICE-lite responder + DTLS server + SRTP sender.

    Lifecycle: ``await peer.listen()`` -> ``peer.create_offer()`` ->
    (signaling) -> ``peer.set_remote_answer(sdp)`` -> datagrams drive the
    handshake -> ``peer.connected`` -> ``send_video_au()`` /
    ``send_audio_frame()``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 on_request_keyframe: Optional[Callable] = None,
                 with_audio: bool = True, fullcolor: bool = False,
                 on_datachannel_message: Optional[Callable] = None,
                 on_bitrate_estimate: Optional[Callable] = None):
        self.host = host
        self.port = port
        self.ufrag, self.pwd = make_ice_credentials()
        self.ice = IceLiteResponder(self.ufrag, self.pwd)
        self.dtls = DtlsEndpoint(server=True)
        self.srtp: SrtpContext | None = None
        # GCC send-side estimate from browser transport-cc feedback; video
        # and audio share the transport-wide sequence space (reference:
        # twcc_estimate in rtcrtpsender.py:336-337 feeds the CBR loop)
        self.cc = SendSideCongestionController()
        self.video = H264Packetizer(twcc_alloc=self.cc.alloc_seq)
        self.audio = OpusPacketizer(twcc_alloc=self.cc.alloc_seq)
        self.remote: RemoteDescription | None = None
        self.on_request_keyframe = on_request_keyframe
        self.on_datachannel_message = on_datachannel_message
        self.on_bitrate_estimate = on_bitrate_estimate
        self.sctp = None                 # SctpAssociation after DTLS
        self.with_audio = with_audio
        self.fullcolor = fullcolor
        self._transport: asyncio.DatagramTransport | None = None
        self._peer_addr: tuple[str, int] | None = None
        self.connected = asyncio.Event()
        self._t0 = time.monotonic()
        self._last_sr = 0.0
        self._closed = False

    # -- socket -------------------------------------------------------------
    async def listen(self) -> int:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, self.port))
        self.port = self._transport.get_extra_info("sockname")[1]
        return self.port

    def connection_made(self, transport):
        self._transport = transport

    def datagram_received(self, data: bytes, addr):
        try:
            self._demux(data, addr)
        except Exception:
            logger.exception("peer datagram error")

    # -- demux (RFC 7983) ---------------------------------------------------
    def _demux(self, data: bytes, addr) -> None:
        if not data:
            return
        b = data[0]
        if is_stun(data):
            resp = self.ice.handle(data, addr)
            if resp and self._transport:
                self._transport.sendto(resp, addr)
            if self.ice.nominated_addr:
                self._peer_addr = self.ice.nominated_addr
        elif 20 <= b <= 63:                       # DTLS
            self._peer_addr = addr
            records = self.dtls.feed(data)
            self._flush_dtls(addr)
            if self.dtls.handshake_complete and self.srtp is None:
                self._on_dtls_complete()
            if self.sctp is not None:
                for rec in records:               # app data = SCTP packets
                    self.sctp.receive(rec)
                self.sctp.poll_timers()
                self._flush_dtls(addr)
        elif 128 <= b <= 191 and self.srtp is not None:
            self._on_srtp(data)

    def _flush_dtls(self, addr) -> None:
        out = self.dtls.take_outgoing()
        if out and self._transport:
            self._transport.sendto(out, addr)

    def _on_dtls_complete(self) -> None:
        if self.remote and self.remote.fingerprint:
            if not self.dtls.verify_peer_fingerprint(
                    self.remote.fingerprint):
                logger.error("peer fingerprint mismatch; dropping")
                self.close()
                return
        client_master, server_master = self.dtls.export_srtp_keys()
        # we are the DTLS server
        self.srtp = SrtpContext(client_master, server_master,
                                is_client=False)
        from .sctp import SctpAssociation
        self.sctp = SctpAssociation(
            self._send_sctp, server=True,
            on_message=self._on_channel_message)
        self.connected.set()
        logger.info("webrtc peer connected (srtp up, addr=%s)",
                    self._peer_addr)

    def _send_sctp(self, packet: bytes) -> None:
        try:
            self.dtls.send_app(packet)
        except Exception:
            return
        out = self.dtls.take_outgoing()
        if out and self._transport and self._peer_addr:
            self._transport.sendto(out, self._peer_addr)

    def _on_channel_message(self, channel, data: bytes, ppid: int) -> None:
        if self.on_datachannel_message is not None:
            text = data.decode("utf-8", "replace") if ppid != 53 else data
            try:
                self.on_datachannel_message(channel.label, text)
            except Exception:
                logger.exception("datachannel handler failed")

    def send_channel_message(self, text: str, sid: int | None = None
                             ) -> bool:
        """Server -> browser control message on the first open channel."""
        if self.sctp is None or self.sctp.state != "ESTABLISHED":
            return False
        if sid is None:
            if not self.sctp.channels:
                return False
            sid = next(iter(self.sctp.channels))
        self.sctp.send(sid, text.encode())
        return True

    def _on_srtp(self, data: bytes) -> None:
        pt = data[1] & 0x7F
        if 64 <= pt <= 95:                        # RTCP range (RFC 5761)
            try:
                rtcp = self.srtp.unprotect_rtcp(data)
            except SrtpError:
                return
            if parse_rtcp_pli(rtcp) and self.on_request_keyframe:
                self.on_request_keyframe()
            now_us = int(time.monotonic() * 1e6)
            gcc = self.cc.on_rtcp(rtcp, now_us)
            remb = parse_rtcp_remb(rtcp)
            if self.on_bitrate_estimate:
                # send-side GCC is authoritative when feedback flows;
                # REMB is the receiver-computed fallback estimate
                if gcc is not None:
                    self.on_bitrate_estimate(
                        int(min(gcc, remb) if remb else gcc))
                elif remb is not None:
                    self.on_bitrate_estimate(remb)
        # inbound RTP (browser mic) is handled by the service if wired

    # -- signaling ----------------------------------------------------------
    def create_offer(self) -> str:
        _, _, fingerprint = generate_certificate()
        return build_offer(self.host, self.port, self.ufrag, self.pwd,
                           fingerprint, video_pt=self.video.payload_type,
                           audio_pt=self.audio.payload_type,
                           with_audio=self.with_audio,
                           fullcolor=self.fullcolor)

    def set_remote_answer(self, sdp: str) -> None:
        self.remote = parse_answer(sdp)
        self.ice.set_remote(self.remote.ice_ufrag, self.remote.ice_pwd)

    # -- media --------------------------------------------------------------
    @property
    def can_send(self) -> bool:
        return (self.srtp is not None and self._peer_addr is not None
                and not self._closed)

    def video_timestamp(self) -> int:
        return int((time.monotonic() - self._t0) * 90000) & 0xFFFFFFFF

    def send_video_au(self, annexb: bytes, timestamp: int | None = None
                      ) -> int:
        """Packetize + protect + send one pre-encoded access unit.
        Returns packets sent (0 when not connected — drop, never block:
        the relay/backpressure contract lives upstream)."""
        if not self.can_send:
            return 0
        ts = self.video_timestamp() if timestamp is None else timestamp
        pkts = self.video.packetize(annexb, ts)
        now_us = int(time.monotonic() * 1e6)
        for p in pkts:
            wire = self.srtp.protect_rtp(p.to_bytes())
            self._transport.sendto(wire, self._peer_addr)
            if p.twcc_seq is not None:
                self.cc.on_packet_sent(p.twcc_seq, len(wire), now_us)
        now = time.monotonic()
        if now - self._last_sr > 1.0:
            self._last_sr = now
            self._transport.sendto(
                self.srtp.protect_rtcp(self.video.sender_report(ts)),
                self._peer_addr)
        return len(pkts)

    def send_audio_frame(self, opus: bytes, timestamp: int) -> int:
        if not self.can_send:
            return 0
        p = self.audio.packetize(opus, timestamp)
        wire = self.srtp.protect_rtp(p.to_bytes())
        self._transport.sendto(wire, self._peer_addr)
        if p.twcc_seq is not None:
            self.cc.on_packet_sent(p.twcc_seq, len(wire),
                                   int(time.monotonic() * 1e6))
        return 1

    def close(self) -> None:
        self._closed = True
        if self._transport:
            self._transport.close()
            self._transport = None
        self.dtls.close()
