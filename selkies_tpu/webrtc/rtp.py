"""RTP packetization: RFC 3550 headers, RFC 6184 H.264 (single NAL +
FU-A), RFC 7587 Opus, and the minimal RTCP the product uses (SR out,
PLI/RR in).

The reference's whole fork purpose was feeding PRE-ENCODED access units
straight to the packetizer (``Encoder.pack()``, reference
src/selkies/webrtc/rtcrtpsender.py:364-393 and codecs/h264.py:339-346);
this module is that seam, built TPU-side: the engine's Annex-B output
goes straight to packets, no re-encode, no av dependency."""

from __future__ import annotations

import secrets
import struct
import time


class RtpPacket:
    __slots__ = ("payload_type", "seq", "timestamp", "ssrc", "marker",
                 "payload", "extensions", "twcc_seq")

    def __init__(self, payload_type: int, seq: int, timestamp: int,
                 ssrc: int, marker: bool, payload: bytes,
                 extensions: list | None = None):
        self.payload_type = payload_type
        self.seq = seq
        self.timestamp = timestamp
        self.ssrc = ssrc
        self.marker = marker
        self.payload = payload
        self.extensions = extensions     # [(id, data)] one-byte-header
        self.twcc_seq = None             # transport-wide seq when stamped

    def to_bytes(self) -> bytes:
        b0 = 0x80 | (0x10 if self.extensions else 0)
        b1 = (0x80 if self.marker else 0) | self.payload_type
        head = struct.pack("!BBHII", b0, b1, self.seq & 0xFFFF,
                           self.timestamp & 0xFFFFFFFF, self.ssrc)
        if self.extensions:
            body = b"".join(
                bytes(((eid << 4) | (len(data) - 1),)) + data
                for eid, data in self.extensions)
            while len(body) % 4:
                body += b"\x00"
            head += struct.pack("!HH", 0xBEDE, len(body) // 4) + body
        return head + self.payload

    @classmethod
    def parse(cls, data: bytes) -> "RtpPacket":
        if len(data) < 12:
            raise ValueError("short RTP packet")
        v_p_x_cc, m_pt, seq, ts, ssrc = struct.unpack_from("!BBHII", data, 0)
        if v_p_x_cc >> 6 != 2:
            raise ValueError("not RTP v2")
        off = 12 + 4 * (v_p_x_cc & 0x0F)
        if v_p_x_cc & 0x10:                      # extension header
            if len(data) < off + 4:
                raise ValueError("short RTP extension")
            ext_len = struct.unpack_from("!H", data, off + 2)[0]
            off += 4 + 4 * ext_len
        payload = data[off:]
        if v_p_x_cc & 0x20 and payload:          # padding
            payload = payload[:-payload[-1]]
        return cls(m_pt & 0x7F, seq, ts, ssrc, bool(m_pt & 0x80), payload)


def split_annexb(annexb: bytes) -> list[bytes]:
    """Annex-B byte stream -> raw NAL units (no start codes)."""
    nals = []
    i = 0
    n = len(annexb)
    while i < n:
        if annexb[i:i + 3] == b"\x00\x00\x01":
            start = i + 3
        elif annexb[i:i + 4] == b"\x00\x00\x00\x01":
            start = i + 4
        else:
            i += 1
            continue
        j = annexb.find(b"\x00\x00\x01", start)
        end = n if j < 0 else (j - 1 if annexb[j - 1] == 0 else j)
        nals.append(annexb[start:end])
        i = end
    return nals


class H264Packetizer:
    """RFC 6184 packetization-mode 1 (non-interleaved): single NAL units
    when they fit, FU-A fragmentation otherwise. One call per access
    unit; marker set on the AU's last packet."""

    #: wire overhead counted against ``mtu`` (which budgets the whole
    #: SRTP datagram, not just the H.264 payload): 12-byte RTP header +
    #: 8-byte one-byte-header extension block when TWCC is on (4 BEDE
    #: header + 3 element + 1 pad) + the SRTP auth tag.
    RTP_HEADER = 12
    TWCC_EXT_OVERHEAD = 8

    def __init__(self, payload_type: int = 102, ssrc: int | None = None,
                 mtu: int = 1200, twcc_alloc=None):
        self.payload_type = payload_type
        self.ssrc = ssrc if ssrc is not None else secrets.randbits(32)
        self.mtu = mtu
        self.seq = secrets.randbits(16)
        self.twcc_alloc = twcc_alloc     # () -> transport-wide seq
        self._octets = 0
        self._packets = 0

    @property
    def _max_payload(self) -> int:
        from .srtp import SrtpContext
        over = self.RTP_HEADER + SrtpContext.AUTH_TAG
        if self.twcc_alloc is not None:
            over += self.TWCC_EXT_OVERHEAD
        return max(64, self.mtu - over)

    def packetize(self, annexb: bytes, timestamp: int) -> list[RtpPacket]:
        packets: list[RtpPacket] = []
        nals = [n for n in split_annexb(annexb) if n]
        budget = self._max_payload
        for nal in nals:
            if len(nal) <= budget:
                packets.append(self._pkt(nal, timestamp))
            else:
                indicator = (nal[0] & 0xE0) | 28          # FU-A
                header = nal[0] & 0x1F
                rest = nal[1:]
                first = True
                while rest:
                    chunk, rest = rest[:budget - 2], rest[budget - 2:]
                    fu = 0x80 if first else (0x40 if not rest else 0x00)
                    packets.append(self._pkt(
                        bytes((indicator, fu | header)) + chunk, timestamp))
                    first = False
        if packets:
            packets[-1].marker = True
        return packets

    def _pkt(self, payload: bytes, ts: int) -> RtpPacket:
        p = RtpPacket(self.payload_type, self.seq, ts, self.ssrc, False,
                      payload)
        if self.twcc_alloc is not None:
            from .cc import TWCC_EXT_ID
            p.twcc_seq = self.twcc_alloc()
            p.extensions = [(TWCC_EXT_ID,
                             struct.pack("!H", p.twcc_seq & 0xFFFF))]
        self.seq = (self.seq + 1) & 0xFFFF
        self._octets += len(payload)
        self._packets += 1
        return p

    def stats(self) -> dict:
        """Lifetime packet/octet counters (the QoE snapshot surface)."""
        return {"packets": self._packets, "octets": self._octets}

    def sender_report(self, timestamp: int) -> bytes:
        """Minimal RTCP SR for lipsync/stat baselines."""
        now = time.time() + 2208988800            # NTP epoch
        ntp_hi = int(now)
        ntp_lo = int((now - ntp_hi) * (1 << 32))
        return struct.pack("!BBHIIIIII", 0x80, 200, 6, self.ssrc,
                           ntp_hi, ntp_lo, timestamp & 0xFFFFFFFF,
                           self._packets, self._octets)


class OpusPacketizer:
    """RFC 7587: one Opus frame per packet, 48 kHz RTP clock."""

    def __init__(self, payload_type: int = 111, ssrc: int | None = None,
                 twcc_alloc=None):
        self.payload_type = payload_type
        self.ssrc = ssrc if ssrc is not None else secrets.randbits(32)
        self.seq = secrets.randbits(16)
        self.twcc_alloc = twcc_alloc
        self._octets = 0
        self._packets = 0

    def packetize(self, opus_frame: bytes, timestamp: int) -> RtpPacket:
        p = RtpPacket(self.payload_type, self.seq, timestamp, self.ssrc,
                      True, opus_frame)
        if self.twcc_alloc is not None:
            from .cc import TWCC_EXT_ID
            p.twcc_seq = self.twcc_alloc()
            p.extensions = [(TWCC_EXT_ID,
                             struct.pack("!H", p.twcc_seq & 0xFFFF))]
        self.seq = (self.seq + 1) & 0xFFFF
        self._octets += len(opus_frame)
        self._packets += 1
        return p

    def stats(self) -> dict:
        """Lifetime packet/octet counters (the QoE snapshot surface)."""
        return {"packets": self._packets, "octets": self._octets}


def depacketize_h264(packets: list[RtpPacket]) -> bytes:
    """Client-side inverse for the loopback tests: RTP payloads of one
    access unit -> Annex-B."""
    out = bytearray()
    fu: bytearray | None = None
    for p in sorted(packets, key=lambda p: p.seq):
        pl = p.payload
        if not pl:
            continue
        ntype = pl[0] & 0x1F
        if ntype == 28:                           # FU-A
            start, end = pl[1] & 0x80, pl[1] & 0x40
            if start:
                fu = bytearray(
                    bytes(((pl[0] & 0xE0) | (pl[1] & 0x1F),)))
            if fu is not None:
                fu += pl[2:]
                if end:
                    out += b"\x00\x00\x00\x01" + fu
                    fu = None
        elif ntype == 24:                         # STAP-A
            off = 1
            while off + 2 <= len(pl):
                ln = struct.unpack_from("!H", pl, off)[0]
                off += 2
                out += b"\x00\x00\x00\x01" + pl[off:off + ln]
                off += ln
        else:
            out += b"\x00\x00\x00\x01" + pl
    return bytes(out)


def parse_rtcp_remb(data: bytes) -> int | None:
    """Receiver Estimated Max Bitrate (draft-alvestrand-rmcat-remb,
    PSFB FMT 15): -> bits/s, or None. The receiver-side half of the
    congestion loop (reference webrtc_mode.py:1652-1716 steers CBR off
    the send-side TWCC estimate; REMB is the receiver-computed analog
    Chrome still emits when offered goog-remb)."""
    off = 0
    while off + 8 <= len(data):
        b0, pt, length = struct.unpack_from("!BBH", data, off)
        size = 4 * (length + 1)
        if pt == 206 and (b0 & 0x1F) == 15 and off + 20 <= len(data) \
                and data[off + 12:off + 16] == b"REMB":
            word = struct.unpack_from("!I", data, off + 16)[0]
            exp = (word >> 18) & 0x3F
            mantissa = word & 0x3FFFF
            return mantissa << exp
        off += max(size, 4)
    return None


def parse_rtcp_pli(data: bytes) -> list[int]:
    """-> media SSRCs for which the receiver asked a keyframe (PSFB/PLI,
    RFC 4585 §6.3.1); also treats FIR (RFC 5104) as a PLI."""
    ssrcs = []
    off = 0
    while off + 8 <= len(data):
        b0, pt, length = struct.unpack_from("!BBH", data, off)
        size = 4 * (length + 1)
        if pt == 206:                             # PSFB
            fmt = b0 & 0x1F
            if fmt == 1 and off + 12 <= len(data):        # PLI
                ssrcs.append(struct.unpack_from("!I", data, off + 8)[0])
            elif fmt == 4 and off + 16 <= len(data):      # FIR
                ssrcs.append(struct.unpack_from("!I", data, off + 12)[0])
        off += max(size, 4)
    return ssrcs
