"""SCTP over DTLS for WebRTC data channels (RFC 9260 subset + RFC
8831/8832 DCEP).

The reference vendors aiortc's 2.1k-line ``rtcsctptransport``; the
product needs far less: ONE association carrying a handful of ordered
data channels whose hot direction is browser -> server input verbs.
Implemented: INIT/INIT-ACK with state cookie, COOKIE-ECHO/ACK, DATA
with fragment reassembly, SACK with gap reports, DCEP open/ack,
HEARTBEAT, ABORT/SHUTDOWN-on-close, go-back-N retransmission with a T3
timer for the (low-rate) server -> browser direction, CRC32c framing.
Not implemented (and not needed for input/control): multi-homing,
unordered/partial-reliability, stream reconfig, cookie-jar hardening
beyond HMAC.
"""

from __future__ import annotations

import hmac
import logging
import os
import struct
import time
from hashlib import sha256
from typing import Callable, Optional

logger = logging.getLogger("selkies_tpu.webrtc.sctp")

# chunk types (RFC 9260 §3.2)
DATA = 0
INIT = 1
INIT_ACK = 2
SACK = 3
HEARTBEAT = 4
HEARTBEAT_ACK = 5
ABORT = 6
SHUTDOWN = 7
ERROR = 9
COOKIE_ECHO = 10
COOKIE_ACK = 11

PPID_DCEP = 50
PPID_STRING = 51
PPID_BINARY = 53

DCEP_OPEN = 0x03
DCEP_ACK = 0x02

_CRC_TBL = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC_TBL.append(_c)


def crc32c(data: bytes) -> int:
    c = 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ _CRC_TBL[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


def _tsn_gt(a: int, b: int) -> bool:
    return ((a - b) & 0xFFFFFFFF) < 0x80000000 and a != b


class Channel:
    def __init__(self, stream_id: int, label: str, protocol: str = ""):
        self.stream_id = stream_id
        self.label = label
        self.protocol = protocol
        self.open = True


class SctpAssociation:
    """One association over a datagram transport (DTLS app data).

    ``send_datagram(bytes)`` ships an SCTP packet; :meth:`receive` takes
    inbound packets. ``on_message(channel, data, ppid)`` fires per
    reassembled user message; ``on_channel(channel)`` on DCEP open."""

    SECRET = os.urandom(32)

    def __init__(self, send_datagram: Callable[[bytes], None],
                 server: bool = True, port: int = 5000,
                 on_message=None, on_channel=None,
                 now: Callable[[], float] = time.monotonic):
        self.send_datagram = send_datagram
        self.server = server
        self.local_port = port
        self.remote_port = port
        self.on_message = on_message
        self.on_channel = on_channel
        self.now = now
        self.state = "CLOSED"
        self.local_tag = struct.unpack("!I", os.urandom(4))[0] or 1
        self.remote_tag = 0
        self.next_tsn = struct.unpack("!I", os.urandom(4))[0]
        self.cum_ack: Optional[int] = None       # highest in-order TSN seen
        self.received: dict[int, tuple] = {}     # out-of-order buffer
        self.reasm: dict[int, list] = {}         # stream -> fragments
        self.next_ssn: dict[int, int] = {}
        self.channels: dict[int, Channel] = {}
        self.a_rwnd = 1 << 20
        self._outstanding: dict[int, bytes] = {}  # tsn -> full chunk bytes
        self._t3_deadline: Optional[float] = None
        self._rto = 1.0

    # ------------------------------------------------------------- packets
    def _packet(self, chunks: bytes, tag: Optional[int] = None) -> bytes:
        hdr = struct.pack("!HHII", self.local_port, self.remote_port,
                          self.remote_tag if tag is None else tag, 0)
        pkt = hdr + chunks
        return pkt[:8] + struct.pack("<I", crc32c(pkt)) + pkt[12:]

    def _send_chunk(self, ctype: int, flags: int, value: bytes,
                    tag: Optional[int] = None) -> None:
        chunk = struct.pack("!BBH", ctype, flags, 4 + len(value)) + value
        chunk += b"\x00" * (-len(chunk) % 4)
        self.send_datagram(self._packet(chunk, tag))

    # ------------------------------------------------------------ lifecycle
    def connect(self) -> None:
        """Client role (tests / loopback): initiate."""
        self.state = "COOKIE_WAIT"
        v = struct.pack("!IIHHI", self.local_tag, self.a_rwnd, 4, 4,
                        self.next_tsn)
        self._send_chunk(INIT, 0, v, tag=0)

    def close(self) -> None:
        if self.state == "ESTABLISHED":
            try:
                self._send_chunk(SHUTDOWN, 0,
                                 struct.pack("!I", self.cum_ack or 0))
            except Exception:
                pass
        self.state = "CLOSED"

    # -------------------------------------------------------------- receive
    def receive(self, packet: bytes) -> None:
        if len(packet) < 12:
            return
        src, dst, tag, _crc = struct.unpack_from("!HHII", packet, 0)
        body = packet[:8] + b"\x00\x00\x00\x00" + packet[12:]
        if struct.unpack_from("<I", packet, 8)[0] != crc32c(body):
            logger.debug("sctp: bad crc32c; dropped")
            return
        off = 12
        sacked = False
        while off + 4 <= len(packet):
            ctype, flags, length = struct.unpack_from("!BBH", packet, off)
            if length < 4:
                break
            value = packet[off + 4: off + length]
            off += length + (-length % 4)
            sacked |= self._on_chunk(ctype, flags, value)
        if sacked:
            self._send_sack()

    def _on_chunk(self, ctype: int, flags: int, value: bytes) -> bool:
        if ctype == INIT and self.server:
            (itag, rwnd, nos, nis, itsn) = struct.unpack_from("!IIHHI",
                                                              value, 0)
            self.remote_tag = itag
            self.cum_ack = (itsn - 1) & 0xFFFFFFFF
            cookie = self._make_cookie(itag, itsn)
            v = struct.pack("!IIHHI", self.local_tag, self.a_rwnd, 16, 16,
                            self.next_tsn)
            v += struct.pack("!HH", 7, 4 + len(cookie)) + cookie
            self._send_chunk(INIT_ACK, 0, v, tag=itag)
        elif ctype == INIT_ACK and not self.server:
            (itag, rwnd, nos, nis, itsn) = struct.unpack_from("!IIHHI",
                                                              value, 0)
            self.remote_tag = itag
            self.cum_ack = (itsn - 1) & 0xFFFFFFFF
            poff = 16
            while poff + 4 <= len(value):
                pt, plen = struct.unpack_from("!HH", value, poff)
                if pt == 7:
                    cookie = value[poff + 4: poff + plen]
                    self._send_chunk(COOKIE_ECHO, 0, cookie)
                    self.state = "COOKIE_ECHOED"
                    break
                poff += plen + (-plen % 4)
        elif ctype == COOKIE_ECHO and self.server:
            if self._check_cookie(value):
                self.state = "ESTABLISHED"
                self._send_chunk(COOKIE_ACK, 0, b"")
        elif ctype == COOKIE_ACK and not self.server:
            self.state = "ESTABLISHED"
        elif ctype == DATA:
            return self._on_data(flags, value)
        elif ctype == SACK:
            self._on_sack(value)
        elif ctype == HEARTBEAT:
            self._send_chunk(HEARTBEAT_ACK, 0, value)
        elif ctype in (ABORT, SHUTDOWN):
            self.state = "CLOSED"
        return False

    # --------------------------------------------------------------- cookie
    def _make_cookie(self, peer_tag: int, peer_tsn: int) -> bytes:
        body = struct.pack("!IIII", peer_tag, peer_tsn, self.local_tag,
                           int(self.now()))
        return body + hmac.new(self.SECRET, body, sha256).digest()[:16]

    def _check_cookie(self, cookie: bytes) -> bool:
        if len(cookie) < 32:
            return False
        body, mac = cookie[:-16], cookie[-16:]
        want = hmac.new(self.SECRET, body, sha256).digest()[:16]
        if not hmac.compare_digest(want, mac):
            return False
        peer_tag, peer_tsn, _, _ = struct.unpack_from("!IIII", body, 0)
        self.remote_tag = peer_tag
        self.cum_ack = (peer_tsn - 1) & 0xFFFFFFFF
        return True

    # ----------------------------------------------------------------- data
    def _on_data(self, flags: int, value: bytes) -> bool:
        tsn, sid, ssn, ppid = struct.unpack_from("!IHHI", value, 0)
        payload = value[12:]
        if self.cum_ack is not None and not _tsn_gt(tsn, self.cum_ack):
            return True                     # duplicate
        self.received[tsn] = (flags, sid, ssn, ppid, payload)
        # advance the cumulative ack through contiguous TSNs
        while self.cum_ack is not None and \
                ((self.cum_ack + 1) & 0xFFFFFFFF) in self.received:
            nxt = (self.cum_ack + 1) & 0xFFFFFFFF
            self._deliver(*self.received.pop(nxt))
            self.cum_ack = nxt
        return True

    def _deliver(self, flags: int, sid: int, ssn: int, ppid: int,
                 payload: bytes) -> None:
        begin, end = flags & 0x02, flags & 0x01
        frags = self.reasm.setdefault(sid, [])
        if begin:
            frags.clear()
        frags.append(payload)
        if not end:
            return
        data = b"".join(frags)
        frags.clear()
        if ppid == PPID_DCEP:
            self._on_dcep(sid, data)
        else:
            ch = self.channels.get(sid)
            if ch is not None and self.on_message is not None:
                try:
                    self.on_message(ch, data, ppid)
                except Exception:
                    logger.exception("sctp message handler failed")

    def _on_dcep(self, sid: int, data: bytes) -> None:
        if not data:
            return
        if data[0] == DCEP_OPEN and len(data) >= 12:
            (_t, _cht, _prio, _rel, llen, plen) = struct.unpack_from(
                "!BBHIHH", data, 0)
            label = data[12:12 + llen].decode("utf-8", "replace")
            proto = data[12 + llen:12 + llen + plen].decode(
                "utf-8", "replace")
            ch = Channel(sid, label, proto)
            self.channels[sid] = ch
            self._send_data(sid, bytes((DCEP_ACK,)), PPID_DCEP)
            if self.on_channel is not None:
                try:
                    self.on_channel(ch)
                except Exception:
                    logger.exception("sctp channel handler failed")
        elif data[0] == DCEP_ACK:
            pass                            # our open confirmed

    # ----------------------------------------------------------------- send
    def open_channel(self, sid: int, label: str) -> Channel:
        """Negotiate a channel from our side (DCEP OPEN)."""
        lb = label.encode()
        msg = struct.pack("!BBHIHH", DCEP_OPEN, 0x00, 0, 0, len(lb), 0) + lb
        self._send_data(sid, msg, PPID_DCEP)
        ch = Channel(sid, label)
        self.channels[sid] = ch
        return ch

    def send(self, sid: int, data: bytes, ppid: int = PPID_STRING) -> None:
        if self.state != "ESTABLISHED":
            raise RuntimeError("association not established")
        self._send_data(sid, data, ppid)

    def _send_data(self, sid: int, data: bytes, ppid: int,
                   mtu: int = 1100) -> None:
        ssn = self.next_ssn.get(sid, 0)
        self.next_ssn[sid] = (ssn + 1) & 0xFFFF
        chunks = [data[i:i + mtu] for i in range(0, len(data), mtu)] or [b""]
        for i, frag in enumerate(chunks):
            flags = (0x02 if i == 0 else 0) | \
                    (0x01 if i == len(chunks) - 1 else 0)
            tsn = self.next_tsn
            self.next_tsn = (self.next_tsn + 1) & 0xFFFFFFFF
            v = struct.pack("!IHHI", tsn, sid, ssn, ppid) + frag
            chunk = struct.pack("!BBH", DATA, flags, 4 + len(v)) + v
            chunk += b"\x00" * (-len(chunk) % 4)
            self._outstanding[tsn] = chunk
            self.send_datagram(self._packet(chunk))
        if self._t3_deadline is None:
            self._t3_deadline = self.now() + self._rto

    def _send_sack(self) -> None:
        if self.cum_ack is None:
            return
        # gap ack blocks for whatever is parked out of order
        gaps = []
        if self.received:
            offs = sorted(((t - self.cum_ack) & 0xFFFFFFFF)
                          for t in self.received)
            start = prev = offs[0]
            for o in offs[1:]:
                if o != prev + 1:
                    gaps.append((start, prev))
                    start = o
                prev = o
            gaps.append((start, prev))
        v = struct.pack("!IIHH", self.cum_ack, self.a_rwnd, len(gaps), 0)
        for s, e in gaps[:100]:
            v += struct.pack("!HH", s, e)
        self._send_chunk(SACK, 0, v)

    def _on_sack(self, value: bytes) -> None:
        cum, _rwnd, ngaps, _ndups = struct.unpack_from("!IIHH", value, 0)
        for tsn in [t for t in self._outstanding
                    if not _tsn_gt(t, cum)]:
            del self._outstanding[tsn]
        acked = set()
        for i in range(ngaps):
            s, e = struct.unpack_from("!HH", value, 12 + 4 * i)
            for off in range(s, e + 1):
                acked.add((cum + off) & 0xFFFFFFFF)
        for tsn in list(self._outstanding):
            if tsn in acked:
                del self._outstanding[tsn]
        self._t3_deadline = None if not self._outstanding \
            else self.now() + self._rto

    def poll_timers(self) -> None:
        """Call periodically (peer's heartbeat loop): go-back-N
        retransmit of anything still outstanding past the T3 deadline."""
        if self._t3_deadline is not None and self.now() >= self._t3_deadline:
            for tsn in sorted(self._outstanding,
                              key=lambda t: (t - (self.cum_ack or 0))
                              & 0xFFFFFFFF):
                self.send_datagram(self._packet(self._outstanding[tsn]))
            self._rto = min(self._rto * 2, 8.0)
            self._t3_deadline = self.now() + self._rto
