"""SDP offer generation + answer parsing for the browser peer.

Shapes match what the reference's RTC app negotiates (reference
src/selkies/rtc.py:601-717 munge pass): H.264 packetization-mode 1,
BUNDLE + rtcp-mux on one ICE-lite host candidate, sendonly media from
the server. We always OFFER (the reference server initiates after
signaling SESSION_START) and the browser answers."""

from __future__ import annotations

import dataclasses
import secrets


@dataclasses.dataclass
class RemoteDescription:
    ice_ufrag: str = ""
    ice_pwd: str = ""
    fingerprint: str = ""          # sha-256 hex:hex:...
    setup: str = "active"
    candidates: list = dataclasses.field(default_factory=list)


def build_offer(host: str, port: int, ufrag: str, pwd: str,
                fingerprint: str, video_pt: int = 102,
                audio_pt: int = 111, with_audio: bool = True,
                fullcolor: bool = False, with_data: bool = True,
                relay: "tuple[str, int] | None" = None,
                with_mic: bool = False,
                audio_params: "dict | None" = None) -> str:
    """One-shot SDP offer: sendonly video (+audio) + a data channel
    m-line for input, ICE-lite, DTLS actpass, all bundled on one port.
    ``relay`` adds a TURN ``typ relay`` candidate (webrtc/turn.py
    allocation) after the host candidate for NAT'd servers.
    ``with_mic`` flips the audio m-line to sendrecv so the browser can
    send its microphone track back (reference rtc.py:1303 mic
    receiver). With ``with_mic`` and NOT ``with_audio`` the m-line is
    still emitted, as recvonly — a mic-only configuration
    (enable_microphone without enable_audio) must not silently lose the
    browser's track for want of an m-line (ADVICE r5)."""
    sid = secrets.randbits(62)
    audio_mline = with_audio or with_mic
    mids = ["0"] + (["1"] if audio_mline else [])
    if with_data:
        mids.append(str(len(mids)))
    lines = [
        "v=0",
        f"o=- {sid} 2 IN IP4 127.0.0.1",
        "s=-",
        "t=0 0",
        "a=ice-lite",
        f"a=group:BUNDLE {' '.join(mids)}",
        "a=msid-semantic: WMS selkies",
    ]
    cand_lines = [
        f"a=candidate:1 1 udp 2130706431 {host} {port} typ host"]
    if relay is not None:
        cand_lines.append(
            f"a=candidate:2 1 udp 16777215 {relay[0]} {relay[1]} "
            f"typ relay raddr {host} rport {port}")
    cand_lines.append("a=end-of-candidates")
    # profile f4001f enables Hi444PP for 4:4:4 streams (the reference's
    # fullcolor munge, rtc.py:649-717); 42e01f is constrained baseline
    profile = "f4001f" if fullcolor else "42e01f"
    from .cc import TWCC_EXT_ID, TWCC_EXT_URI
    extmap = f"a=extmap:{TWCC_EXT_ID} {TWCC_EXT_URI}"
    media = [
        (f"m=video {port} UDP/TLS/RTP/SAVPF {video_pt}", [
            f"a=rtpmap:{video_pt} H264/90000",
            f"a=fmtp:{video_pt} level-asymmetry-allowed=1;"
            f"packetization-mode=1;profile-level-id={profile}",
            f"a=rtcp-fb:{video_pt} nack pli",
            f"a=rtcp-fb:{video_pt} ccm fir",
            f"a=rtcp-fb:{video_pt} goog-remb",
            f"a=rtcp-fb:{video_pt} transport-cc",
            extmap,
        ]),
    ]
    if audio_mline:
        if audio_params and int(audio_params.get("channels", 2)) > 2:
            # surround: Chrome's multiopus (multistream Opus whose
            # stream layout rides the fmtp — reference
            # webrtc_mode.py:252-254); the packets are exactly what
            # audio/opus.MultistreamEncoder emits
            ch = int(audio_params["channels"])
            mapping = ",".join(
                str(int(v)) for v in audio_params["channel_mapping"])
            audio_lines = [
                f"a=rtpmap:{audio_pt} multiopus/48000/{ch}",
                f"a=fmtp:{audio_pt} minptime=10;useinbandfec=1;"
                f"channel_mapping={mapping};"
                f"num_streams={int(audio_params['num_streams'])};"
                f"coupled_streams={int(audio_params['coupled_streams'])}",
                f"a=rtcp-fb:{audio_pt} transport-cc",
                extmap,
            ]
        else:
            audio_lines = [
                f"a=rtpmap:{audio_pt} opus/48000/2",
                f"a=fmtp:{audio_pt} minptime=10;useinbandfec=1",
                f"a=rtcp-fb:{audio_pt} transport-cc",
                extmap,
            ]
        media.append(
            (f"m=audio {port} UDP/TLS/RTP/SAVPF {audio_pt}",
             audio_lines))
    for i, (mline, extra) in enumerate(media):
        lines.append(mline)
        lines.append(f"c=IN IP4 {host}")
        lines += [
            f"a=mid:{mids[i]}",
            ("a=sendonly" if i == 0 or not with_mic
             else ("a=sendrecv" if with_audio else "a=recvonly")),
            f"a=ice-ufrag:{ufrag}",
            f"a=ice-pwd:{pwd}",
            f"a=fingerprint:sha-256 {fingerprint}",
            "a=setup:actpass",
            "a=rtcp-mux",
            f"a=msid:selkies selkies-{'video' if i == 0 else 'audio'}",
        ]
        lines += extra
        lines += cand_lines
    if with_data:
        lines += [
            f"m=application {port} UDP/DTLS/SCTP webrtc-datachannel",
            f"c=IN IP4 {host}",
            f"a=mid:{mids[-1]}",
            f"a=ice-ufrag:{ufrag}",
            f"a=ice-pwd:{pwd}",
            f"a=fingerprint:sha-256 {fingerprint}",
            "a=setup:actpass",
            "a=sctp-port:5000",
            "a=max-message-size:262144",
        ]
        lines += cand_lines
    return "\r\n".join(lines) + "\r\n"


def parse_answer(sdp: str) -> RemoteDescription:
    r = RemoteDescription()
    for raw in sdp.replace("\r\n", "\n").split("\n"):
        line = raw.strip()
        if line.startswith("a=ice-ufrag:") and not r.ice_ufrag:
            r.ice_ufrag = line.split(":", 1)[1]
        elif line.startswith("a=ice-pwd:") and not r.ice_pwd:
            r.ice_pwd = line.split(":", 1)[1]
        elif line.startswith("a=fingerprint:sha-256") and not r.fingerprint:
            r.fingerprint = line.split()[-1]
        elif line.startswith("a=setup:"):
            r.setup = line.split(":", 1)[1]
        elif line.startswith("a=candidate:"):
            r.candidates.append(line[len("a=candidate:"):])
    return r
