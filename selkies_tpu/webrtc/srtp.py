"""SRTP / SRTCP packet protection — RFC 3711, AES-CM-128 + HMAC-SHA1-80.

The reference gets this from pylibsrtp inside its aiortc fork; here it is
~150 lines on the ``cryptography`` AES-CTR primitive. Only the profile
DTLS negotiates (``SRTP_AES128_CM_SHA1_80``) is implemented. Packet rate
on this path is a few thousand per second — comfortably Python-speed.
"""

from __future__ import annotations

import hmac
import struct
from hashlib import sha1

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes


def _aes_ctr(key: bytes, iv16: bytes, data: bytes) -> bytes:
    c = Cipher(algorithms.AES(key), modes.CTR(iv16)).encryptor()
    return c.update(data) + c.finalize()


def _kdf(master_key: bytes, master_salt: bytes, label: int,
         n: int) -> bytes:
    """RFC 3711 §4.3.1 AES-CM key derivation (kdr = 0).

    key_id = label || r is 56 bits with the 8-bit label ABOVE the 48-bit
    r term, XORed into the low bits of the 112-bit master salt — i.e. the
    label lands at bit 48 (validated against the RFC 3711 B.3 vectors in
    tests/test_webrtc_media.py)."""
    x = int.from_bytes(master_salt, "big") ^ (label << 48)
    iv = (x << 16).to_bytes(16, "big")
    return _aes_ctr(master_key, iv, b"\x00" * ((n + 15) // 16 * 16))[:n]


class ReplayWindow:
    """RFC 3711 §3.3.2 64-entry sliding window."""

    def __init__(self):
        self._latest = -1
        self._mask = 0

    def check_and_update(self, index: int) -> bool:
        if index > self._latest:
            shift = index - self._latest
            self._mask = (self._mask << shift) | 1
            self._mask &= (1 << 64) - 1
            self._latest = index
            return True
        delta = self._latest - index
        if delta >= 64 or (self._mask >> delta) & 1:
            return False
        self._mask |= 1 << delta
        return True


class _Stream:
    """Per-direction derived keys + rollover/replay state."""

    def __init__(self, master: bytes):
        key, salt = master[:16], master[16:30]
        self.enc_key = _kdf(key, salt, 0, 16)
        self.auth_key = _kdf(key, salt, 1, 20)
        self.salt = _kdf(key, salt, 2, 14)
        self.rtcp_enc_key = _kdf(key, salt, 3, 16)
        self.rtcp_auth_key = _kdf(key, salt, 4, 20)
        self.rtcp_salt = _kdf(key, salt, 5, 14)
        self.roc: dict[int, int] = {}           # ssrc -> rollover counter
        self.last_seq: dict[int, int] = {}
        self.replay: dict[int, ReplayWindow] = {}
        self.rtcp_index: dict[int, int] = {}


def _rtp_iv(salt: bytes, ssrc: int, index: int) -> bytes:
    x = int.from_bytes(salt, "big") ^ (ssrc << 48) ^ index
    return (x << 16).to_bytes(16, "big")


def _rtp_header_len(pkt: bytes) -> int:
    """RTP header length incl. CSRCs and header extension — SRTP encrypts
    the payload ONLY (RFC 3711 §3.1: the extension stays in the clear;
    the browser reads our transport-cc seq out of it)."""
    off = 12 + 4 * (pkt[0] & 0x0F)
    if pkt[0] & 0x10:
        if len(pkt) < off + 4:
            raise SrtpError("short RTP extension")
        off += 4 + 4 * struct.unpack_from("!H", pkt, off + 2)[0]
    if off > len(pkt):
        raise SrtpError("bad RTP header length")
    return off


class SrtpError(Exception):
    pass


class SrtpContext:
    """Bidirectional SRTP context from the two DTLS-exported masters.

    ``is_client`` is the DTLS role: a client protects with the client
    master and expects the server master inbound (RFC 5764 §4.2)."""

    AUTH_TAG = 10

    def __init__(self, client_master: bytes, server_master: bytes,
                 is_client: bool):
        self._tx = _Stream(client_master if is_client else server_master)
        self._rx = _Stream(server_master if is_client else client_master)

    # -- RTP ---------------------------------------------------------------
    def protect_rtp(self, packet: bytes) -> bytes:
        if len(packet) < 12:
            raise SrtpError("short RTP packet")
        seq = struct.unpack_from("!H", packet, 2)[0]
        ssrc = struct.unpack_from("!I", packet, 8)[0]
        st = self._tx
        last = st.last_seq.get(ssrc)
        roc = st.roc.get(ssrc, 0)
        if last is not None and seq < 0x1000 and last > 0xF000:
            roc += 1                    # sender-side wrap
        st.roc[ssrc] = roc
        st.last_seq[ssrc] = seq
        index = (roc << 16) | seq
        hdr = _rtp_header_len(packet)
        payload = _aes_ctr(st.enc_key, _rtp_iv(st.salt, ssrc, index),
                           packet[hdr:])
        authed = packet[:hdr] + payload
        tag = hmac.new(st.auth_key,
                       authed + struct.pack("!I", roc), sha1).digest()
        return authed + tag[:self.AUTH_TAG]

    def unprotect_rtp(self, packet: bytes) -> bytes:
        if len(packet) < 12 + self.AUTH_TAG:
            raise SrtpError("short SRTP packet")
        body, tag = packet[:-self.AUTH_TAG], packet[-self.AUTH_TAG:]
        seq = struct.unpack_from("!H", body, 2)[0]
        ssrc = struct.unpack_from("!I", body, 8)[0]
        st = self._rx
        # index estimate (RFC 3711 §3.3.1)
        roc = st.roc.get(ssrc, 0)
        last = st.last_seq.get(ssrc)
        guess = roc
        if last is not None:
            if last > 0xF000 and seq < 0x1000:
                guess = roc + 1
            elif last < 0x1000 and seq > 0xF000 and roc > 0:
                guess = roc - 1
        want = hmac.new(st.auth_key,
                        body + struct.pack("!I", guess), sha1).digest()
        if not hmac.compare_digest(want[:self.AUTH_TAG], tag):
            raise SrtpError("SRTP auth failure")
        index = (guess << 16) | seq
        rw = st.replay.setdefault(ssrc, ReplayWindow())
        if not rw.check_and_update(index):
            raise SrtpError("SRTP replay")
        if guess > roc or (last is not None and seq > last) or last is None:
            st.roc[ssrc] = guess
            st.last_seq[ssrc] = seq
        hdr = _rtp_header_len(body)
        return body[:hdr] + _aes_ctr(st.enc_key,
                                     _rtp_iv(st.salt, ssrc, index),
                                     body[hdr:])

    # -- RTCP (always E-bit encrypted) -------------------------------------
    def protect_rtcp(self, packet: bytes) -> bytes:
        if len(packet) < 8:
            raise SrtpError("short RTCP packet")
        ssrc = struct.unpack_from("!I", packet, 4)[0]
        st = self._tx
        index = st.rtcp_index.get(ssrc, 0) + 1
        st.rtcp_index[ssrc] = index
        iv = _rtp_iv(st.rtcp_salt, ssrc, index)
        enc = packet[:8] + _aes_ctr(st.rtcp_enc_key, iv, packet[8:])
        trailer = struct.pack("!I", index | 0x80000000)       # E-bit set
        tag = hmac.new(st.rtcp_auth_key, enc + trailer, sha1).digest()
        return enc + trailer + tag[:self.AUTH_TAG]

    def unprotect_rtcp(self, packet: bytes) -> bytes:
        if len(packet) < 8 + 4 + self.AUTH_TAG:
            raise SrtpError("short SRTCP packet")
        tag = packet[-self.AUTH_TAG:]
        trailer = packet[-self.AUTH_TAG - 4:-self.AUTH_TAG]
        body = packet[:-self.AUTH_TAG - 4]
        st = self._rx
        want = hmac.new(st.rtcp_auth_key, body + trailer, sha1).digest()
        if not hmac.compare_digest(want[:self.AUTH_TAG], tag):
            raise SrtpError("SRTCP auth failure")
        word = struct.unpack("!I", trailer)[0]
        if not word & 0x80000000:
            return body                 # unencrypted SRTCP
        index = word & 0x7FFFFFFF
        ssrc = struct.unpack_from("!I", body, 4)[0]
        iv = _rtp_iv(st.rtcp_salt, ssrc, index)
        return body[:8] + _aes_ctr(st.rtcp_enc_key, iv, body[8:])
