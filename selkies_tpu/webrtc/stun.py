"""STUN message codec + ICE-lite responder logic (RFC 5389 / RFC 8445).

The reference vendors aioice (2.7k LoC: full agent, TURN/mDNS, check
lists). An ICE-LITE server needs none of that — it answers Binding
Requests on its single host candidate with MESSAGE-INTEGRITY +
XOR-MAPPED-ADDRESS + FINGERPRINT, and notices USE-CANDIDATE nominations
(reference src/selkies/ice/stun.py is the behavioural model for the
codec)."""

from __future__ import annotations

import hmac
import os
import secrets
import struct
import zlib
from hashlib import sha1

MAGIC_COOKIE = 0x2112A442
BINDING_REQUEST = 0x0001
BINDING_RESPONSE = 0x0101
BINDING_ERROR = 0x0111

ATTR_USERNAME = 0x0006
ATTR_MESSAGE_INTEGRITY = 0x0008
ATTR_ERROR_CODE = 0x0009
ATTR_XOR_MAPPED_ADDRESS = 0x0020
ATTR_PRIORITY = 0x0024
ATTR_USE_CANDIDATE = 0x0025
ATTR_FINGERPRINT = 0x8028
ATTR_ICE_CONTROLLING = 0x802A
ATTR_ICE_CONTROLLED = 0x8029


def is_stun(datagram: bytes) -> bool:
    return (len(datagram) >= 20 and datagram[0] < 4
            and struct.unpack_from("!I", datagram, 4)[0] == MAGIC_COOKIE)


class StunError(Exception):
    pass


class StunMessage:
    def __init__(self, msg_type: int, txid: bytes | None = None):
        self.type = msg_type
        self.txid = txid if txid is not None else os.urandom(12)
        self.attrs: list[tuple[int, bytes]] = []

    # -- build --------------------------------------------------------------
    def add(self, attr: int, value: bytes) -> "StunMessage":
        self.attrs.append((attr, value))
        return self

    def add_xor_mapped_address(self, host: str, port: int):
        xport = port ^ (MAGIC_COOKIE >> 16)
        ip = bytes(int(p) for p in host.split("."))
        xip = bytes(b ^ m for b, m in
                    zip(ip, struct.pack("!I", MAGIC_COOKIE)))
        return self.add(ATTR_XOR_MAPPED_ADDRESS,
                        struct.pack("!BBH", 0, 0x01, xport) + xip)

    def _encode(self, attrs: list[tuple[int, bytes]],
                length_override: int | None = None) -> bytes:
        body = b""
        for a, v in attrs:
            body += struct.pack("!HH", a, len(v)) + v + b"\x00" * (-len(v) % 4)
        length = length_override if length_override is not None else len(body)
        return struct.pack("!HHI", self.type, length,
                           MAGIC_COOKIE) + self.txid + body

    def to_bytes(self, integrity_key: bytes | None = None,
                 fingerprint: bool = True) -> bytes:
        attrs = list(self.attrs)
        if integrity_key is not None:
            # MI covers the header with length up to and including MI
            mi_len = sum(4 + len(v) + (-len(v) % 4) for _, v in attrs) + 24
            data = self._encode(attrs, length_override=mi_len)
            mac = hmac.new(integrity_key, data, sha1).digest()
            attrs.append((ATTR_MESSAGE_INTEGRITY, mac))
        if fingerprint:
            fp_len = sum(4 + len(v) + (-len(v) % 4) for _, v in attrs) + 8
            data = self._encode(attrs, length_override=fp_len)
            crc = (zlib.crc32(data) & 0xFFFFFFFF) ^ 0x5354554E
            attrs.append((ATTR_FINGERPRINT, struct.pack("!I", crc)))
        return self._encode(attrs)

    # -- parse --------------------------------------------------------------
    @classmethod
    def parse(cls, data: bytes) -> "StunMessage":
        if len(data) < 20:
            raise StunError("short STUN message")
        msg_type, length, cookie = struct.unpack_from("!HHI", data, 0)
        if cookie != MAGIC_COOKIE or len(data) < 20 + length:
            raise StunError("bad STUN header")
        m = cls(msg_type, data[4 + 4:20])
        off = 20
        end = 20 + length
        while off + 4 <= end:
            a, alen = struct.unpack_from("!HH", data, off)
            off += 4
            m.attrs.append((a, data[off:off + alen]))
            off += alen + (-alen % 4)
        m._raw = data
        return m

    def attr(self, attr: int) -> bytes | None:
        for a, v in self.attrs:
            if a == attr:
                return v
        return None

    def check_integrity(self, key: bytes) -> bool:
        """Validate MESSAGE-INTEGRITY over the received raw bytes."""
        raw = getattr(self, "_raw", None)
        mi = self.attr(ATTR_MESSAGE_INTEGRITY)
        if raw is None or mi is None:
            return False
        off = 20
        while off + 4 <= len(raw):
            a, alen = struct.unpack_from("!HH", raw, off)
            if a == ATTR_MESSAGE_INTEGRITY:
                hdr = struct.pack("!HHI", self.type, off - 20 + 24,
                                  MAGIC_COOKIE) + self.txid
                covered = hdr + raw[20:off]
                want = hmac.new(key, covered, sha1).digest()
                return hmac.compare_digest(want, mi)
            off += 4 + alen + (-alen % 4)
        return False

    def xor_mapped_address(self) -> tuple[str, int] | None:
        v = self.attr(ATTR_XOR_MAPPED_ADDRESS)
        if v is None or len(v) < 8 or v[1] != 0x01:
            return None
        port = struct.unpack_from("!H", v, 2)[0] ^ (MAGIC_COOKIE >> 16)
        ip = bytes(b ^ m for b, m in
                   zip(v[4:8], struct.pack("!I", MAGIC_COOKIE)))
        return ".".join(str(b) for b in ip), port


_ICE_CHARS = ("ABCDEFGHIJKLMNOPQRSTUVWXYZ"
              "abcdefghijklmnopqrstuvwxyz0123456789")


def make_ice_credentials() -> tuple[str, str]:
    """-> (ufrag, pwd) in the ice-char grammar (ALPHA / DIGIT / '+' /
    '/'; base64url's '-'/'_' are NOT valid and trip spec-strict parsers).
    8 alphanumeric ufrag chars ≈ 47 bits, comfortably over RFC 8445's
    24-bit minimum; 22 pwd chars ≈ 131 bits over the required 128."""
    return ("".join(secrets.choice(_ICE_CHARS) for _ in range(8)),
            "".join(secrets.choice(_ICE_CHARS) for _ in range(22)))


class IceLiteResponder:
    """Answers authenticated Binding Requests on one host candidate;
    reports the peer's (address, nominated) as it learns them."""

    def __init__(self, local_ufrag: str, local_pwd: str):
        self.ufrag = local_ufrag
        self.pwd = local_pwd
        self.remote_ufrag: str | None = None
        self.remote_pwd: str | None = None
        self.nominated_addr: tuple[str, int] | None = None

    def set_remote(self, ufrag: str, pwd: str) -> None:
        self.remote_ufrag, self.remote_pwd = ufrag, pwd

    def handle(self, datagram: bytes, addr: tuple[str, int]
               ) -> bytes | None:
        """-> response datagram (or None to drop)."""
        try:
            msg = StunMessage.parse(datagram)
        except StunError:
            return None
        if msg.type != BINDING_REQUEST:
            return None                    # lite agents never get responses
        if not msg.check_integrity(self.pwd.encode()):
            err = StunMessage(BINDING_ERROR, msg.txid)
            err.add(ATTR_ERROR_CODE, b"\x00\x00\x04\x01Unauthorized")
            return err.to_bytes()
        if msg.attr(ATTR_USE_CANDIDATE) is not None:
            self.nominated_addr = addr
        elif self.nominated_addr is None:
            self.nominated_addr = addr     # lite: first valid pair wins
        resp = StunMessage(BINDING_RESPONSE, msg.txid)
        resp.add_xor_mapped_address(*addr)
        return resp.to_bytes(integrity_key=self.pwd.encode())

    def binding_request(self, dest_note: tuple[str, int] | None = None
                        ) -> bytes:
        """Client-side helper (tests): an authenticated Binding Request
        toward a remote ICE-lite agent."""
        if self.remote_pwd is None:
            raise StunError("remote credentials not set")
        req = StunMessage(BINDING_REQUEST)
        req.add(ATTR_USERNAME,
                f"{self.remote_ufrag}:{self.ufrag}".encode())
        req.add(ATTR_ICE_CONTROLLING, os.urandom(8))
        req.add(ATTR_USE_CANDIDATE, b"")
        req.add(ATTR_PRIORITY, struct.pack("!I", 0x7E0000FF))
        return req.to_bytes(integrity_key=self.remote_pwd.encode())
