"""TURN client (RFC 5766/8656 subset, UDP): relay allocation for the
media plane.

The server is ICE-lite with one host candidate; when the browser cannot
reach it directly (server behind NAT / firewalled), the reference relays
via its vendored TURN client (reference src/selkies/ice/turn.py,
consumed at webrtc_mode.py:256-296). This is the TPU framework's
equivalent: allocate a relayed transport address on the in-tree coturn
(addons/coturn, addons/turn-rest), advertise it as an additional
``typ relay`` candidate, and shuttle datagrams through ChannelData
framing (Send/Data indications until the channel binds).

Scope: UDP transport, long-term credentials (401 realm/nonce dance, key
= MD5(user:realm:pass)), Allocate / Refresh / CreatePermission /
ChannelBind / Send+Data indications, ChannelData. TCP/TLS transports
are out of scope (the direct path plus UDP relay covers the product's
NAT matrix; coturn terminates TLS in front of the same allocation API).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import struct
import time
from typing import Callable, Optional

from .stun import MAGIC_COOKIE, StunError, StunMessage

logger = logging.getLogger("selkies_tpu.webrtc.turn")

# methods (request class; success response = | 0x0100, error = | 0x0110)
M_ALLOCATE = 0x0003
M_REFRESH = 0x0004
M_SEND_IND = 0x0016
M_DATA_IND = 0x0017
M_CREATE_PERMISSION = 0x0008
M_CHANNEL_BIND = 0x0009

ATTR_CHANNEL_NUMBER = 0x000C
ATTR_LIFETIME = 0x000D
ATTR_XOR_PEER_ADDRESS = 0x0012
ATTR_DATA = 0x0013
ATTR_REALM = 0x0014
ATTR_NONCE = 0x0015
ATTR_XOR_RELAYED_ADDRESS = 0x0016
ATTR_REQUESTED_TRANSPORT = 0x0019
ATTR_USERNAME = 0x0006
ATTR_MESSAGE_INTEGRITY = 0x0008
ATTR_ERROR_CODE = 0x0009

UDP_TRANSPORT = 17


def xor_address(host: str, port: int) -> bytes:
    xport = port ^ (MAGIC_COOKIE >> 16)
    ip = bytes(int(p) for p in host.split("."))
    xip = bytes(b ^ m for b, m in zip(ip, struct.pack("!I", MAGIC_COOKIE)))
    return struct.pack("!BBH", 0, 0x01, xport) + xip


def unxor_address(v: bytes) -> Optional[tuple[str, int]]:
    if len(v) < 8 or v[1] != 0x01:
        return None
    port = struct.unpack_from("!H", v, 2)[0] ^ (MAGIC_COOKIE >> 16)
    ip = bytes(b ^ m for b, m in
               zip(v[4:8], struct.pack("!I", MAGIC_COOKIE)))
    return ".".join(str(b) for b in ip), port


def is_channel_data(datagram: bytes) -> bool:
    return len(datagram) >= 4 and 0x40 <= datagram[0] <= 0x7F


def _error_code(msg: StunMessage) -> int:
    v = msg.attr(ATTR_ERROR_CODE)
    if v is None or len(v) < 4:
        return 0
    return (v[2] & 0x7) * 100 + v[3]


class TurnError(Exception):
    pass


class TurnClient(asyncio.DatagramProtocol):
    """One UDP socket to one TURN server; one allocation.

    ``on_data(data, peer_addr)`` fires for every datagram a remote peer
    sent to the relayed address (via Data indication or ChannelData).
    """

    def __init__(self, server: tuple[str, int], username: str,
                 password: str,
                 on_data: Optional[Callable] = None):
        self.server = server
        self.username = username
        self.password = password
        self.on_data = on_data
        self.realm = ""
        self.nonce = b""
        self.relayed_addr: Optional[tuple[str, int]] = None
        self.lifetime = 600
        self._transport: Optional[asyncio.DatagramTransport] = None
        self._pending: dict[bytes, asyncio.Future] = {}
        self._channels: dict[tuple[str, int], int] = {}
        self._channel_rev: dict[int, tuple[str, int]] = {}
        self._next_channel = 0x4000
        self._permissions: set[str] = set()
        self._maint_task: Optional[asyncio.Task] = None
        self._closed = False

    # -- socket -------------------------------------------------------------
    async def connect(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: self, remote_addr=self.server)

    def connection_made(self, transport):
        self._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            self._on_datagram(data)
        except Exception:
            logger.exception("turn datagram error")

    def _on_datagram(self, data: bytes) -> None:
        if is_channel_data(data):
            ch, length = struct.unpack_from("!HH", data, 0)
            peer = self._channel_rev.get(ch)
            if peer is not None and self.on_data is not None:
                self.on_data(data[4:4 + length], peer)
            return
        try:
            msg = StunMessage.parse(data)
        except StunError:
            return
        if msg.type == M_DATA_IND:
            peer = unxor_address(msg.attr(ATTR_XOR_PEER_ADDRESS) or b"")
            payload = msg.attr(ATTR_DATA)
            if peer and payload is not None and self.on_data is not None:
                self.on_data(payload, peer)
            return
        fut = self._pending.get(msg.txid)
        if fut is None or fut.done():
            return
        # Once the realm is known every request we send is integrity-
        # protected, so success responses MUST carry a verifying
        # MESSAGE-INTEGRITY (RFC 5389 §10.2.3) — validating MI only when
        # the attribute happens to be present lets an off-path attacker
        # who observed the txid inject an MI-less success carrying a
        # bogus relayed address (ADVICE r5). Error responses are the
        # exception: 401/438 are sent BEFORE auth to (re)issue
        # realm/nonce and legitimately lack MI; any other MI-less error
        # is dropped too (forged errors only cost a retransmit).
        if self.realm:
            has_mi = msg.attr(ATTR_MESSAGE_INTEGRITY) is not None
            is_success = (msg.type & 0x0110) == 0x0100
            if has_mi:
                if not msg.check_integrity(self._lt_key()):
                    logger.warning(
                        "turn response failed integrity check; dropped")
                    return
            elif is_success or _error_code(msg) not in (401, 438):
                logger.warning(
                    "turn %s response lacks MESSAGE-INTEGRITY; dropped",
                    "success" if is_success else "error")
                return
        self._pending.pop(msg.txid, None)
        fut.set_result(msg)

    # -- auth ---------------------------------------------------------------
    def _lt_key(self) -> bytes:
        return hashlib.md5(
            f"{self.username}:{self.realm}:{self.password}"
            .encode()).digest()

    def _auth_attrs(self, msg: StunMessage) -> StunMessage:
        msg.add(ATTR_USERNAME, self.username.encode())
        msg.add(ATTR_REALM, self.realm.encode())
        msg.add(ATTR_NONCE, self.nonce)
        return msg

    async def _request(self, msg: StunMessage, authed: bool,
                       timeout: float = 5.0) -> StunMessage:
        """Send a request, retransmitting with a doubling RTO (RFC 5389
        §7.2.1) so a single lost datagram doesn't downgrade the session
        to direct-path-only (ADVICE r4)."""
        if self._transport is None:
            raise TurnError("not connected")
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg.txid] = fut
        key = self._lt_key() if authed else None
        wire = msg.to_bytes(integrity_key=key)
        rto = 0.5
        remaining = timeout
        try:
            while remaining > 0:
                self._transport.sendto(wire)
                wait = min(rto, remaining)
                try:
                    return await asyncio.wait_for(
                        asyncio.shield(fut), wait)
                except asyncio.TimeoutError:
                    remaining -= wait
                    rto *= 2
            raise TurnError("turn request timed out")
        finally:
            self._pending.pop(msg.txid, None)

    async def _authed_request(self, method: int,
                              attrs: list[tuple[int, bytes]]
                              ) -> StunMessage:
        """Request with the long-term-credential retry dance: 401 to
        learn realm/nonce, 438 to refresh a stale nonce."""
        for _ in range(3):
            msg = StunMessage(method)
            for a, v in attrs:
                msg.add(a, v)
            if self.realm:
                resp = await self._request(self._auth_attrs(msg),
                                           authed=True)
            else:
                resp = await self._request(msg, authed=False)
            if resp.type == method | 0x0100:
                return resp
            code = _error_code(resp)
            if code in (401, 438):
                realm = resp.attr(ATTR_REALM)
                nonce = resp.attr(ATTR_NONCE)
                if realm is None or nonce is None:
                    raise TurnError(f"turn {code} without realm/nonce")
                self.realm = realm.decode()
                self.nonce = nonce
                continue
            raise TurnError(f"turn error {code} on method {method:#x}")
        raise TurnError("turn auth retries exhausted")

    # -- allocation lifecycle ----------------------------------------------
    async def allocate(self, lifetime: int = 600) -> tuple[str, int]:
        resp = await self._authed_request(M_ALLOCATE, [
            (ATTR_REQUESTED_TRANSPORT,
             struct.pack("!BBH", UDP_TRANSPORT, 0, 0)),
            (ATTR_LIFETIME, struct.pack("!I", lifetime)),
        ])
        relayed = unxor_address(
            resp.attr(ATTR_XOR_RELAYED_ADDRESS) or b"")
        if relayed is None:
            raise TurnError("allocate response lacks relayed address")
        lt = resp.attr(ATTR_LIFETIME)
        if lt is not None and len(lt) == 4:
            self.lifetime = struct.unpack("!I", lt)[0]
        self.relayed_addr = relayed
        self._maint_task = asyncio.create_task(self._maintain())
        logger.info("turn allocation: relay %s:%d (lifetime %ds)",
                    relayed[0], relayed[1], self.lifetime)
        return relayed

    async def refresh(self, lifetime: Optional[int] = None) -> None:
        await self._authed_request(M_REFRESH, [
            (ATTR_LIFETIME,
             struct.pack("!I", self.lifetime
                         if lifetime is None else lifetime)),
        ])

    async def create_permission(self, peer_ip: str) -> None:
        await self._authed_request(M_CREATE_PERMISSION, [
            (ATTR_XOR_PEER_ADDRESS, xor_address(peer_ip, 0)),
        ])
        self._permissions.add(peer_ip)

    async def channel_bind(self, peer: tuple[str, int]) -> int:
        ch = self._channels.get(peer)
        if ch is None:
            ch = self._next_channel
            self._next_channel += 1
        await self._authed_request(M_CHANNEL_BIND, [
            (ATTR_CHANNEL_NUMBER, struct.pack("!HH", ch, 0)),
            (ATTR_XOR_PEER_ADDRESS, xor_address(*peer)),
        ])
        self._channels[peer] = ch
        self._channel_rev[ch] = peer
        self._permissions.add(peer[0])
        return ch

    async def _maintain(self) -> None:
        """Keep the relay alive on a short poll so nothing expires:
        allocation at 5/6 of its lifetime, permissions every 4 min (they
        expire at 5, RFC 5766 §9), channel binds every 8 min (10-minute
        lifetime). A single long sleep would let permissions lapse
        mid-session — the poll must be shorter than every deadline."""
        start = time.monotonic()
        alloc_next = start + self.lifetime * 5 / 6
        perm_next = start + 240
        chan_next = start + 480
        while not self._closed:
            try:
                await asyncio.sleep(30.0)
                now = time.monotonic()
                if now >= alloc_next:
                    await self.refresh()
                    alloc_next = time.monotonic() + self.lifetime * 5 / 6
                if now >= perm_next:
                    perm_next = now + 240
                    for ip in list(self._permissions):
                        await self.create_permission(ip)
                if now >= chan_next:
                    chan_next = now + 480
                    for peer in list(self._channels):
                        await self.channel_bind(peer)
            except asyncio.CancelledError:
                raise
            except TurnError as e:
                logger.warning("turn maintenance failed: %s", e)

    # -- data plane ---------------------------------------------------------
    def send_to_peer(self, data: bytes, peer: tuple[str, int]) -> None:
        """ChannelData when bound, Send indication otherwise (the
        indication path needs only a permission)."""
        if self._transport is None or self._closed:
            return
        ch = self._channels.get(peer)
        if ch is not None:
            frame = struct.pack("!HH", ch, len(data)) + data
            frame += b"\x00" * (-len(data) % 4)
            self._transport.sendto(frame)
            return
        ind = StunMessage(M_SEND_IND)
        ind.add(ATTR_XOR_PEER_ADDRESS, xor_address(*peer))
        ind.add(ATTR_DATA, data)
        self._transport.sendto(ind.to_bytes())

    def close(self) -> None:
        self._closed = True
        if self._maint_task is not None:
            self._maint_task.cancel()
            self._maint_task = None
        if self._transport is not None:
            self._transport.close()
            self._transport = None
