"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; all sharding tests run against
``xla_force_host_platform_device_count=8`` as SURVEY.md §4 prescribes.
Must run before jax is imported anywhere.
"""

import asyncio
import inspect
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # host may pre-set axon; tests are CPU-only
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests without pytest-asyncio (absent from this
    image). Sync fixtures still resolve; async fixtures are not supported —
    use async context managers inside the test instead."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {n: pyfuncitem.funcargs[n]
                  for n in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(fn(**kwargs))
        return True
    return None
