"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; all sharding tests run against
``xla_force_host_platform_device_count=8`` as SURVEY.md §4 prescribes.
Must run before jax is imported anywhere.
"""

import asyncio
import inspect
import os
import sys

import pytest

def pytest_configure(config):
    """Axon escape hatch. The TPU relay is single-client; when
    ``PALLAS_AXON_POOL_IPS`` is set, sitecustomize dials it at INTERPRETER
    startup — before any conftest runs — and a busy/dead relay then hangs
    every jax init, even under ``JAX_PLATFORMS=cpu``. Tests never touch the
    TPU, so re-exec the whole pytest process with a cleaned environment.
    Done here (not at import) so pytest's fd capture can be released first
    — otherwise the child's output lands in the dead parent's tmpfiles."""
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    args = list(config.invocation_params.args)
    os.execvpe(sys.executable, [sys.executable, "-m", "pytest"] + args, env)


os.environ["JAX_PLATFORMS"] = "cpu"  # host may pre-set axon; tests are CPU-only
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests without pytest-asyncio (absent from this
    image). Sync fixtures still resolve; async fixtures are not supported —
    fixtures that need loop-bound teardown (client_factory) register
    cleanups that run inside the same event loop, after the test body."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {n: pyfuncitem.funcargs[n]
                  for n in pyfuncitem._fixtureinfo.argnames}

        async def _run():
            try:
                await fn(**kwargs)
            finally:
                cf = kwargs.get("client_factory")
                if cf is not None:
                    await cf.cleanup()

        asyncio.run(_run())
        return True
    return None


class ClientFactory:
    """``c = await client_factory(server)``: switch the server to a mode,
    start an in-process aiohttp TestClient against its app, and register
    teardown to run in the test's event loop."""

    def __init__(self):
        self._cleanups = []

    async def __call__(self, server, mode: str = "websockets"):
        from aiohttp.test_utils import TestClient, TestServer
        await server.switch_to_mode(mode)
        await asyncio.sleep(0)  # let the service start() task run
        client = TestClient(TestServer(server.app))
        await client.start_server()

        async def _cleanup():
            await server.shutdown()
            await client.close()

        self._cleanups.append(_cleanup)
        return client

    async def cleanup(self):
        for fn in reversed(self._cleanups):
            try:
                await fn()
            except Exception:
                pass
        self._cleanups.clear()


@pytest.fixture
def client_factory():
    return ClientFactory()
