"""HostPoolActuator contracts (ISSUE 20): the reconcile state machine
on injected clocks — settle hysteresis and per-direction cooldowns,
min/max clamps, the panic-brake matrix (queue non-empty / burning host
/ stale input), spawn-fail backoff→park→unpark, boot-deadline miss,
the drain-deadline force path (teardown only after seats evacuate,
abort at the horizon), broadcast-source victim exclusion and the
single-inflight invariant.  No sleeps, no sockets, no subprocesses:
the provider, scheduler and advisor are all fakes."""

import pytest

from selkies_tpu.fleet.actuator import (DRAIN_ABORT_FACTOR,
                                        ActuatorParams,
                                        HostPoolActuator,
                                        SubprocessHostProvider)
from selkies_tpu.obs.health import FlightRecorder
from selkies_tpu.resilience import faults as _faults


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeHeartbeat:
    def __init__(self, warm=0):
        self.warm_geometries = [(640, 360)] * warm


class FakeHost:
    def __init__(self, host_id, *, ready=True, lost=False,
                 draining=False, burn_streak=0, warm=0,
                 url="http://x"):
        self.host_id = host_id
        self.ready = ready
        self.lost = lost
        self.draining = draining
        self.burn_streak = burn_streak
        self.heartbeat = FakeHeartbeat(warm)
        self.url = url


class FakeSpec:
    def __init__(self, is_relay=False):
        self.is_relay = is_relay


class FakePlacement:
    def __init__(self, host_id, is_relay=False):
        self.host_id = host_id
        self.spec = FakeSpec(is_relay)


class FakeScheduler:
    def __init__(self):
        self.hosts = {}
        self.placements = {}
        self.pending = []
        self.forgotten = []

    def forget(self, host_id):
        if any(p.host_id == host_id
               for p in self.placements.values()):
            return False
        self.forgotten.append(host_id)
        return self.hosts.pop(host_id, None) is not None


class FakeAdvisor:
    def __init__(self):
        self.last_decision = None

    def want(self, desired, *, stale=False):
        self.last_decision = {"desired_hosts": desired,
                              "stale": stale}


class FakeProvider:
    """Spawned hosts appear in the scheduler as ready after
    ``boot_after`` ticks of the shared clock (0 = next reconcile)."""

    def __init__(self, sched, *, fail=0, boot=True):
        self.sched = sched
        self.fail = fail            # next N spawns raise
        self.boot = boot            # register the host as ready
        self.spawned = []
        self.torn_down = []         # (host_id, force)
        self._owned = set()

    def spawn(self, host_id):
        if self.fail > 0:
            self.fail -= 1
            raise RuntimeError("cloud says no")
        self.spawned.append(host_id)
        self._owned.add(host_id)
        if self.boot:
            self.sched.hosts[host_id] = FakeHost(host_id)

    def teardown(self, host_id, *, force=False):
        self.torn_down.append((host_id, force))
        self._owned.discard(host_id)
        self.sched.hosts.pop(host_id, None)

    def owns(self, host_id):
        return host_id in self._owned

    def hosts(self):
        return list(self._owned)

    def describe(self):
        return {"kind": "fake"}

    def teardown_all(self, *, force=True):
        for hid in list(self._owned):
            self.teardown(hid, force=force)


class FakeControl:
    def __init__(self, done=False):
        self._done = done
        self.stopped = 0

    def done(self):
        return self._done

    def stop(self):
        self.stopped += 1


PARAMS = ActuatorParams(min_hosts=1, max_hosts=3, boot_deadline_s=60.0,
                        drain_deadline_s=20.0, up_cooldown_s=10.0,
                        down_cooldown_s=30.0, up_settle=2,
                        down_settle=2, spawn_max_restarts=1,
                        spawn_window_s=300.0, spawn_base_backoff_s=1.0,
                        spawn_max_backoff_s=8.0)


def rig(*, params=PARAMS, hosts=(), fail=0, boot=True,
        drain_starter=None):
    clock = Clock()
    sched = FakeScheduler()
    for h in hosts:
        sched.hosts[h.host_id] = h
    advisor = FakeAdvisor()
    provider = FakeProvider(sched, fail=fail, boot=boot)
    recorder = FlightRecorder()
    act = HostPoolActuator(advisor, sched, provider, params=params,
                           drain_starter=drain_starter,
                           recorder=recorder, clock=clock)
    return act, advisor, sched, provider, clock, recorder


def kinds(recorder):
    return [i["kind"] for i in recorder.snapshot()]


def settle_up(act, clock, n):
    """Burn the settle hysteresis; returns the last report."""
    rep = None
    for _ in range(n):
        rep = act.reconcile()
        clock.advance(1.0)
    return rep


# ----------------------------------------------------------- holds

class TestHolds:
    def test_no_decision_holds(self):
        act, *_ = rig()
        rep = act.reconcile()
        assert rep["action"] == "hold"
        assert rep["reason"] == "no_decision"

    def test_steady_holds(self):
        act, advisor, *_ = rig(hosts=[FakeHost("h1")])
        advisor.want(1)
        rep = act.reconcile()
        assert rep["reason"] == "steady"

    def test_stale_input_holds_both_directions(self):
        # desired > actual AND desired < actual both refuse on stale —
        # no heartbeats is an emergency, not a resize signal
        act, advisor, sched, provider, clock, _ = rig(
            hosts=[FakeHost("h1"), FakeHost("h2")])
        provider._owned.update(("h1", "h2"))
        for desired in (5, 1):
            advisor.want(desired, stale=True)
            for _ in range(10):
                rep = act.reconcile()
                clock.advance(1.0)
                assert rep["action"] == "hold"
                assert rep["reason"] == "stale_input"
        assert provider.spawned == [] and provider.torn_down == []
        # staleness also resets the settle pressure: one fresh
        # reconcile after a long stale stretch must NOT actuate
        advisor.want(5)
        assert act.reconcile()["reason"] == "settling"

    def test_never_ready_hosts_do_not_count(self):
        # a synthetic-heartbeat host that was never ready must not
        # inflate actual (it can't serve, only mislead the books)
        act, advisor, sched, *_ = rig(
            hosts=[FakeHost("h1"),
                   FakeHost("ghost", ready=False)])
        advisor.want(1)
        assert act.reconcile()["actual"] == 1


# -------------------------------------------------------- scale-up

class TestScaleUp:
    def test_settle_then_spawn_then_ready_counts(self):
        act, advisor, sched, provider, clock, rec = rig()
        sched.hosts["h1"] = FakeHost("h1")
        advisor.want(2)
        assert act.reconcile()["reason"] == "settling"
        clock.advance(1.0)
        rep = act.reconcile()
        assert rep["action"] == "up" and rep["reason"] == "spawn"
        assert provider.spawned == ["act-1"]
        clock.advance(1.0)
        rep = act.reconcile()          # booted host seen ready
        assert rep["reason"] == "ready"
        assert act.counts == {"up_ok": 1}
        assert "actuation_started" in kinds(rec)
        assert "actuation_done" in kinds(rec)

    def test_single_inflight_no_second_spawn(self):
        act, advisor, sched, provider, clock, _ = rig(boot=False)
        sched.hosts["h1"] = FakeHost("h1")
        advisor.want(3)
        settle_up(act, clock, 2)
        assert provider.spawned == ["act-1"]
        for _ in range(5):             # still booting: hold, no spawn
            rep = act.reconcile()
            clock.advance(1.0)
            assert rep["reason"] == "in_flight"
        assert provider.spawned == ["act-1"]

    def test_max_hosts_clamp(self):
        hosts = [FakeHost(f"h{i}") for i in range(3)]
        act, advisor, sched, provider, clock, _ = rig(hosts=hosts)
        advisor.want(99)
        rep = settle_up(act, clock, 5)
        assert rep["desired"] == PARAMS.max_hosts == rep["actual"]
        assert provider.spawned == []

    def test_min_hosts_clamp(self):
        act, advisor, sched, provider, clock, _ = rig(
            hosts=[FakeHost("h1")])
        provider._owned.add("h1")
        advisor.want(0)
        rep = settle_up(act, clock, 5)
        assert rep["desired"] == PARAMS.min_hosts
        assert rep["reason"] == "steady"
        assert provider.torn_down == []

    def test_up_cooldown_between_spawns(self):
        act, advisor, sched, provider, clock, _ = rig()
        sched.hosts["h1"] = FakeHost("h1")
        advisor.want(3)
        settle_up(act, clock, 3)       # settle + spawn + ready
        assert act.counts == {"up_ok": 1}
        rep = settle_up(act, clock, 2)  # settle burned again, but...
        assert rep["reason"] == "cooldown"
        clock.advance(PARAMS.up_cooldown_s)
        assert act.reconcile()["action"] == "up"

    def test_boot_deadline_miss_tears_down_and_backs_off(self):
        act, advisor, sched, provider, clock, rec = rig(boot=False)
        sched.hosts["h1"] = FakeHost("h1")
        advisor.want(2)
        settle_up(act, clock, 2)
        assert provider.spawned == ["act-1"]
        clock.advance(PARAMS.boot_deadline_s + 1)
        rep = act.reconcile()
        assert ("act-1", True) in provider.torn_down
        assert rep["reason"] == "spawn_failed"
        assert rep["backoff_s"] > 0
        assert act.counts == {"up_boot_timeout": 1}

    def test_spawn_fail_backoff_then_park_then_unpark(self):
        act, advisor, sched, provider, clock, rec = rig(fail=99)
        sched.hosts["h1"] = FakeHost("h1")
        advisor.want(2)
        clock.advance(1.0)
        act.reconcile()
        rep = act.reconcile()          # first spawn attempt fails
        assert rep["reason"] == "spawn_failed"
        backoff = rep["backoff_s"]
        assert backoff == PARAMS.spawn_base_backoff_s
        rep = act.reconcile()
        assert rep["reason"] == "backing_off"
        clock.advance(backoff + 0.1)
        rep = act.reconcile()          # second failure: budget spent
        assert rep["reason"] == "parked"
        assert act.parked
        assert "actuator_parked" in kinds(rec)
        for _ in range(5):             # parked is sticky
            clock.advance(60.0)
            assert act.reconcile()["reason"] == "parked"
        provider.fail = 0
        act.unpark()
        assert "actuator_unparked" in kinds(rec)
        rep = act.reconcile()
        assert rep["action"] == "up"
        assert act.counts["up_spawn_failed"] == 2


# ------------------------------------------------------ scale-down

def down_rig(*, control=None, seats=None, extra_hosts=(),
             params=PARAMS):
    """Two owned hosts + optional seats; desired 1 => drain pressure."""
    control = control if control is not None else FakeControl()
    starter_calls = []

    def starter(host_id, url):
        starter_calls.append(host_id)
        return control

    act, advisor, sched, provider, clock, rec = rig(
        params=params, drain_starter=starter,
        hosts=[FakeHost("h1"), FakeHost("h2", warm=2)]
        + list(extra_hosts))
    provider._owned.update(("h1", "h2"))
    for sid, (host_id, is_relay) in (seats or {}).items():
        sched.placements[sid] = FakePlacement(host_id, is_relay)
    advisor.want(1)
    return (act, advisor, sched, provider, clock, rec, control,
            starter_calls)


class TestScaleDown:
    def test_settle_then_drain_then_teardown(self):
        act, advisor, sched, provider, clock, rec, control, calls = \
            down_rig()
        assert act.reconcile()["reason"] == "settling"
        clock.advance(1.0)
        rep = act.reconcile()
        assert rep["action"] == "down" and rep["reason"] == "drain"
        assert calls == ["h1"]         # fewest warm geometries wins
        control._done = True
        clock.advance(1.0)
        rep = act.reconcile()
        assert rep["reason"] == "drained"
        assert provider.torn_down == [("h1", False)]
        assert act.counts == {"down_ok": 1}
        assert control.stopped == 1
        # torn-down host is dropped from the capacity books so its
        # dead slots stop inflating the occupancy denominator
        assert sched.forgotten == ["h1"]
        assert "h1" not in sched.hosts

    def test_drain_report_merged_into_history(self):
        control = FakeControl()
        control.report = {"migrated": 2, "dropped": 0,
                          "correlation_id": "mig-7", "ignored": "x"}
        act, advisor, sched, provider, clock, rec, control, calls = \
            down_rig(control=control)
        act.reconcile()
        clock.advance(1.0)
        act.reconcile()
        control._done = True
        clock.advance(1.0)
        act.reconcile()
        entry = act.history[-1]
        assert entry["outcome"] == "ok"
        assert entry["migrated"] == 2 and entry["dropped"] == 0
        assert entry["correlation_id"] == "mig-7"
        assert "ignored" not in entry

    def test_panic_brake_queue_pending(self):
        act, advisor, sched, *_ = down_rig()
        sched.pending.append(object())
        act.reconcile()
        rep = act.reconcile()
        assert rep["reason"] == "queue_pending"

    def test_panic_brake_burning_host(self):
        act, advisor, sched, *_ = down_rig()
        sched.hosts["h2"].burn_streak = 3
        act.reconcile()
        rep = act.reconcile()
        assert rep["reason"] == "host_burning"
        assert rep["burning"] == ["h2"]

    def test_victim_fewest_seats_first(self):
        act, advisor, sched, provider, clock, rec, control, calls = \
            down_rig(seats={"s1": ("h1", False), "s2": ("h1", False),
                            "s3": ("h2", False)})
        act.reconcile()
        clock.advance(1.0)
        act.reconcile()
        assert calls == ["h2"]

    def test_broadcast_source_never_victim(self):
        # h2 has fewer seats but carries a relay (broadcast source):
        # draining it would drop every viewer — h1 must be picked
        act, advisor, sched, provider, clock, rec, control, calls = \
            down_rig(seats={"s1": ("h1", False),
                            "src": ("h2", False),
                            "viewer": ("h2", True)})
        act.reconcile()
        clock.advance(1.0)
        act.reconcile()
        assert calls == ["h1"]

    def test_unowned_hosts_never_victims(self):
        act, advisor, sched, provider, clock, rec, control, calls = \
            down_rig()
        provider._owned.clear()        # actuator created neither host
        act.reconcile()
        clock.advance(1.0)
        rep = act.reconcile()
        assert rep["reason"] == "no_victim"
        assert calls == []

    def test_drain_wedged_forces_only_after_evacuation(self):
        act, advisor, sched, provider, clock, rec, control, calls = \
            down_rig(seats={"s1": ("h1", False), "s2": ("h2", False),
                            "s3": ("h2", False)})
        act.reconcile()
        clock.advance(1.0)
        act.reconcile()                # drain h1 started (never done)
        clock.advance(PARAMS.drain_deadline_s + 1)
        rep = act.reconcile()
        assert rep["reason"] == "in_flight" and rep["wedged"]
        assert kinds(rec).count("drain_wedged") == 1
        assert provider.torn_down == []     # seat still placed!
        clock.advance(1.0)
        rep = act.reconcile()               # wedged incident is one-shot
        assert kinds(rec).count("drain_wedged") == 1
        del sched.placements["s1"]          # failover evacuated it
        clock.advance(1.0)
        rep = act.reconcile()
        assert rep["reason"] == "forced"
        assert provider.torn_down == [("h1", True)]
        assert act.counts == {"down_forced": 1}

    def test_drain_abort_horizon_when_seats_never_evacuate(self):
        act, advisor, sched, provider, clock, rec, control, calls = \
            down_rig(seats={"s1": ("h1", False), "s2": ("h2", False),
                            "s3": ("h2", False)})
        act.reconcile()
        clock.advance(1.0)
        act.reconcile()
        clock.advance(DRAIN_ABORT_FACTOR * PARAMS.drain_deadline_s + 1)
        rep = act.reconcile()
        assert rep["reason"] == "aborted"
        assert provider.torn_down == []     # never tear a seated host
        assert act.counts == {"down_aborted": 1}
        assert control.stopped == 1
        assert act._inflight is None        # slot freed for later work

    def test_down_cooldown(self):
        act, advisor, sched, provider, clock, rec, control, calls = \
            down_rig(extra_hosts=[FakeHost("h3")])
        act.provider._owned.add("h3")
        control._done = True
        act.reconcile()
        clock.advance(1.0)
        act.reconcile()
        clock.advance(1.0)
        act.reconcile()                # h1 drained+down
        assert act.counts == {"down_ok": 1}
        rep = settle_up(act, clock, 3)
        assert rep["reason"] == "cooldown"
        clock.advance(PARAMS.down_cooldown_s)
        assert act.reconcile()["action"] == "down"


# ------------------------------------------------- faults & surface

class TestFaultPointAndSnapshot:
    def test_fleet_spawn_fault_point_fails_spawn(self):
        act, advisor, sched, provider, clock, rec = rig()
        sched.hosts["h1"] = FakeHost("h1")
        _faults.registry.arm("fleet.spawn:fail:count=1")
        try:
            advisor.want(2)
            clock.advance(1.0)
            act.reconcile()
            rep = act.reconcile()
            assert rep["reason"] == "spawn_failed"
            assert provider.spawned == []
        finally:
            _faults.registry.disarm("fleet.spawn")

    def test_snapshot_shape(self):
        act, advisor, sched, provider, clock, rec = rig()
        sched.hosts["h1"] = FakeHost("h1")
        advisor.want(2)
        settle_up(act, clock, 3)
        doc = act.snapshot()
        assert doc["enabled"] and not doc["parked"]
        assert doc["counts"] == {"up_ok": 1}
        assert doc["reconciles"] == 3
        assert doc["history"][-1]["outcome"] == "ok"
        assert doc["params"]["max_hosts"] == PARAMS.max_hosts
        assert doc["provider"] == {"kind": "fake"}

    def test_shutdown_reaps_everything(self):
        control = FakeControl()
        act, advisor, sched, provider, clock, rec, control, calls = \
            down_rig(control=control)
        act.reconcile()
        clock.advance(1.0)
        act.reconcile()                # drain in flight
        act.shutdown()
        assert control.stopped == 1
        assert provider._owned == set()


class TestSubprocessProviderShape:
    def test_argv_template_formatting(self):
        p = SubprocessHostProvider(["engine", "--port", "{port}",
                                    "--id", "{host_id}"])
        assert p.owns("nope") is False
        assert p.hosts() == []
        port = p._free_port()
        assert 0 < port < 65536
        argv = [a.format(host_id="act-1", port=port)
                for a in p.argv_template]
        assert argv == ["engine", "--port", str(port),
                        "--id", "act-1"]
