"""Audio pipeline tests: Opus roundtrip, 0x01/RED framing, listener
backpressure, bitrate control. All against real libopus via ctypes."""

import asyncio
import pathlib
import struct

import numpy as np
import pytest

from selkies_tpu import protocol as P
from selkies_tpu.audio import opus
from selkies_tpu.audio.pipeline import AudioPipeline, SyntheticToneSource
from selkies_tpu.settings import AppSettings

pytestmark = pytest.mark.skipif(not opus.available(),
                                reason="libopus not present")


def test_opus_encode_decode_roundtrip():
    enc = opus.Encoder(48000, 2, 96000)
    dec = opus.Decoder(48000, 2)
    t = np.arange(480) / 48000.0
    tone = (np.sin(2 * np.pi * 440 * t) * 8000).astype(np.int16)
    pcm = np.repeat(tone[:, None], 2, axis=1)
    for _ in range(8):            # let the codec converge
        pkt = enc.encode(pcm)
    out = dec.decode(pkt)
    for _ in range(4):
        out = dec.decode(pkt)
    assert out.shape == (480, 2)
    # decoded energy is in the right ballpark of the source tone
    assert 1000 < np.abs(out.astype(np.int64)).mean() < 12000


class FakeWs:
    def __init__(self):
        self.sent = []

    async def send_bytes(self, b):
        self.sent.append(bytes(b))


class FakeClient:
    _n = 1000

    def __init__(self):
        FakeClient._n += 1
        self.id = FakeClient._n
        self.ws = FakeWs()


def _settings(**kw):
    s = AppSettings.parse([], {})
    for k, v in kw.items():
        s.set_server(k, v)
    return s


async def _pump(pipe, client, n_frames=6, timeout=5.0):
    await pipe.start()
    pipe.add_listener(client)
    deadline = asyncio.get_event_loop().time() + timeout
    while len(client.ws.sent) < n_frames \
            and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.02)
    await pipe.stop()


def test_pipeline_delivers_decodable_opus():
    s = _settings(audio_red_distance=0)
    pipe = AudioPipeline(s, source=SyntheticToneSource(48000, 2, 480))
    client = FakeClient()
    asyncio.run(_pump(pipe, client))
    assert len(client.ws.sent) >= 6
    dec = opus.Decoder(48000, 2)
    for frame in client.ws.sent[:6]:
        assert frame[0] == P.OP_AUDIO and frame[1] == 0
        out = dec.decode(frame[2:])
        assert out.shape[0] == 480


def test_pipeline_red_framing_parses():
    s = _settings(audio_red_distance=2)
    pipe = AudioPipeline(s, source=SyntheticToneSource(48000, 2, 480))
    client = FakeClient()
    asyncio.run(_pump(pipe, client, n_frames=8))
    framed = [f for f in client.ws.sent if f[1] > 0]
    assert framed, "RED frames expected after history warms up"
    f = framed[-1]
    n_red = f[1]
    body = f[2:]
    (pts,) = struct.unpack(">I", body[:4])
    # block headers: F=1 + PT + 14-bit offset + 10-bit length
    sizes = []
    off = 4
    for _ in range(n_red):
        (word,) = struct.unpack(">I", body[off:off + 4])
        assert word >> 31 == 1
        sizes.append(word & 0x3FF)
        off += 4
    assert body[off] == 111          # primary header, F=0
    off += 1
    blocks_end = off + sum(sizes)
    primary = body[blocks_end:]
    dec = opus.Decoder(48000, 2)
    assert dec.decode(primary).shape[0] == 480
    # redundant blocks decode too
    dec2 = opus.Decoder(48000, 2)
    assert dec2.decode(bytes(body[off:off + sizes[0]])).shape[0] == 480


def test_listener_queue_drops_oldest_never_blocks():
    s = _settings(audio_backpressure_queue=4, audio_red_distance=0)
    pipe = AudioPipeline(s, source=SyntheticToneSource(48000, 2, 480))

    class StalledWs:
        def __init__(self):
            self.sent = []

        async def send_bytes(self, b):
            await asyncio.sleep(3600)     # never completes

    client = FakeClient()
    client.ws = StalledWs()

    async def run():
        await pipe.start()
        pipe.add_listener(client)
        await asyncio.sleep(0.3)          # ~30 frames at 10 ms
        q = pipe._listeners[client.id][1]
        assert q.qsize() <= 4             # bounded despite the stall
        assert pipe.frames_encoded > 10   # capture never paused
        await pipe.stop()

    asyncio.run(run())


def test_update_bitrate_changes_packet_size():
    enc = opus.Encoder(48000, 2, 320000, lowdelay=False)
    rng = np.random.default_rng(0)
    pcm = rng.integers(-20000, 20000, (480, 2), dtype=np.int16)
    for _ in range(8):
        big = len(enc.encode(pcm))
    enc.set_bitrate(16000)
    for _ in range(8):
        small = len(enc.encode(pcm))
    assert small < big


def test_virtual_mic_provisioning_pactl_sequence(tmp_path, monkeypatch):
    """VirtualMicrophone drives pactl correctly: creates the 'input'
    null sink + SelkiesVirtualMic virtual source, sets the default
    source, and tears down ONLY the modules it loaded (reference
    provision_virtual_microphone semantics, selkies.py:229-380).
    Validated against a scripted fake pactl on PATH."""
    import os
    import stat

    log = tmp_path / "calls.log"
    state = tmp_path / "state"
    state.mkdir()
    fake = tmp_path / "pactl"
    fake.write_text(f"""#!/bin/bash
echo "$@" >> {log}
case "$1 $2 $3" in
  "list short sinks")
    [ -f {state}/sink ] && printf '1\\tinput\\tmodule-null-sink\\n'
    printf '0\\tdefault\\tmodule-alsa\\n' ;;
  "list short sources")
    [ -f {state}/src ] && printf '2\\tSelkiesVirtualMic\\tmodule-virtual-source\\n'
    printf '0\\tdefault.monitor\\tmodule-alsa\\n' ;;
  "load-module module-null-sink"*) touch {state}/sink; echo 41 ;;
  "load-module module-virtual-source"*) touch {state}/src; echo 42 ;;
  "unload-module 41") rm -f {state}/sink ;;
  "unload-module 42") rm -f {state}/src ;;
esac
exit 0
""")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")

    from selkies_tpu.audio.virtual_mic import VirtualMicrophone

    async def run():
        vm = VirtualMicrophone()
        assert await vm.provision() is True
        assert vm.available and vm.source_name == "SelkiesVirtualMic"
        assert vm.sink_name == "input"
        # idempotency: a second instance REUSES, owns nothing new
        vm2 = VirtualMicrophone()
        assert await vm2.provision() is True
        assert vm2._owned_modules == []
        await vm2.teardown()                 # must not unload vm's modules
        calls = log.read_text()
        assert "unload-module" not in calls
        await vm.teardown()
        calls = log.read_text().splitlines()
        assert "unload-module 42" in calls and "unload-module 41" in calls
    asyncio.run(run())


def test_mic_pcm_routed_into_virtual_sink(tmp_path, monkeypatch):
    """play_mic_pcm must target the provisioned 'input' sink (-d) so the
    virtual source actually carries the client mic."""
    import os
    import stat

    log = tmp_path / "pacat.log"
    fake = tmp_path / "pacat"
    fake.write_text(f"#!/bin/bash\necho \"$@\" > {log}\ncat > /dev/null\n")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")

    s = AppSettings.parse([], {})
    s.set_server("enable_microphone", True)

    async def run():
        p = AudioPipeline(s, source=SyntheticToneSource(48000, 2, 480))
        from selkies_tpu.audio.virtual_mic import VirtualMicrophone
        p.virtual_mic = VirtualMicrophone()
        p.virtual_mic.available = True       # as if provisioned
        p.play_mic_pcm(b"\x00\x01" * 240)
        for _ in range(50):
            if log.exists():
                break
            await asyncio.sleep(0.05)
        assert log.exists()
        args = log.read_text()
        assert "-d input" in args and "--rate=24000" in args
        if p._mic_proc:
            p._mic_proc.kill()
    asyncio.run(run())


def _pa_daemon_alive() -> bool:
    import shutil as _sh
    import subprocess as _sp
    if not _sh.which("pactl"):
        return False
    try:
        return _sp.run(["pactl", "info"], capture_output=True,
                       timeout=5).returncode == 0
    except Exception:
        return False


@pytest.mark.x11
def test_virtual_mic_records_injected_tone():
    """End-to-end in the example container (live PulseAudio): client 0x02
    PCM played through the provisioned graph must be RECORDABLE from the
    SelkiesVirtualMic source — the property desktop apps depend on."""
    if not _pa_daemon_alive():
        pytest.skip("no live PulseAudio daemon")
    import subprocess

    from selkies_tpu.audio.virtual_mic import VirtualMicrophone

    async def run():
        vm = VirtualMicrophone()
        assert await vm.provision() is True
        try:
            # 1 s of 440 Hz at 24 kHz mono s16 through the data plane
            t = np.arange(24000) / 24000.0
            tone = (np.sin(2 * np.pi * 440.0 * t) * 12000).astype(np.int16)
            pacat = await asyncio.create_subprocess_exec(
                "pacat", "--format=s16le", "--rate=24000", "--channels=1",
                "-d", vm.sink_name, stdin=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL)
            rec = await asyncio.create_subprocess_exec(
                "parec", "--format=s16le", "--rate=24000", "--channels=1",
                "-d", vm.source_name, stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL)
            pacat.stdin.write(tone.tobytes())
            await pacat.stdin.drain()
            data = await asyncio.wait_for(
                rec.stdout.readexactly(24000), timeout=10)
            pacat.kill()
            rec.kill()
            got = np.frombuffer(data, np.int16).astype(np.float64)
            rms = np.sqrt((got ** 2).mean())
            assert rms > 500, f"virtual mic silent (rms {rms:.0f})"
        finally:
            await vm.teardown()
    asyncio.run(run())


# ----------------------------------------------------------- surround
def test_multistream_surround_roundtrip():
    """>2ch capture encodes through the multistream surround API and the
    matching multistream decoder recovers every channel (reference
    pcmflux surround surface, SURVEY §2.2); the OpusHead carries the
    mapping table browsers need as AudioDecoder description."""
    from selkies_tpu.audio import opus
    if not opus.available():
        pytest.skip("libopus missing")
    try:
        enc = opus.MultistreamEncoder(48000, 6, 320000)
    except opus.OpusError as e:
        pytest.skip(str(e))
    assert enc.streams >= 1 and enc.coupled >= 0
    assert len(enc.mapping) == 6

    # distinct CONTINUOUS tone per channel (phase must not restart at
    # packet boundaries or the spectrum smears). Family-1 order for 6ch
    # is FL C FR RL RR LFE — the LFE stream is lowpassed, so it gets a
    # 60 Hz tone while the full-band channels step 300..900 Hz.
    n_pkts, frame = 8, 480
    t = np.arange(n_pkts * frame) / 48000.0
    freqs = [300, 450, 600, 750, 900, 60]
    pcm = np.stack([
        np.sin(2 * np.pi * f * t) * 12000 for f in freqs],
        axis=1).astype(np.int16)
    packets = [enc.encode(pcm[i * frame:(i + 1) * frame])
               for i in range(n_pkts)]
    assert all(len(p) > 0 for p in packets)

    dec = opus.MultistreamDecoder(48000, 6, enc.streams, enc.coupled,
                                  enc.mapping)
    outs = [dec.decode(p) for p in packets]
    out = np.concatenate(outs[2:])       # skip codec warmup frames
    assert out.shape[1] == 6
    # every channel must carry ITS tone (bin resolution = 48000/len)
    seg = out.astype(np.float64)
    res = 48000 / len(seg)
    peaks = []
    for ch in range(6):
        spec = np.abs(np.fft.rfft(seg[:, ch] * np.hanning(len(seg))))
        spec[:2] = 0                     # ignore DC leakage
        peaks.append(np.argmax(spec) * res)
    for ch in range(6):
        assert abs(peaks[ch] - freqs[ch]) < 40, (ch, peaks)


def test_opus_head_format():
    from selkies_tpu.audio import opus
    head = opus.opus_head(6, 4, 2, bytes(range(6)))
    assert head[:8] == b"OpusHead"
    assert head[8] == 1                  # version
    assert head[9] == 6                  # channels
    assert head[18] == 1                 # mapping family 1
    assert head[19] == 4 and head[20] == 2
    assert head[21:27] == bytes(range(6))
    stereo = opus.opus_head(2, 1, 1, b"")
    assert stereo[18] == 0 and len(stereo) == 19


async def test_pipeline_surround_head_in_settings():
    """A 6-channel pipeline exposes opus_head; the WS hello advertises it
    (audio_head) so AudioDecoder can be configured."""
    from selkies_tpu.audio import opus
    from selkies_tpu.audio.pipeline import AudioPipeline
    from selkies_tpu.settings import AppSettings
    if not opus.available():
        pytest.skip("libopus missing")
    s = AppSettings.parse([], {})
    s.set_server("audio_channels", 6)
    try:
        p = AudioPipeline(s)
    except (RuntimeError, opus.OpusError) as e:
        pytest.skip(str(e))
    assert p.opus_head is not None and p.opus_head[:8] == b"OpusHead"
    # client module consumes it
    js = (pathlib.Path(__file__).parent.parent / "selkies_tpu" / "web"
          / "lib" / "audio.js").read_text()
    assert "audio_head" in js and "description" in js


async def test_red_distance_client_regate():
    """A RED-incapable client zeroes audio_red_distance live: the next
    frames carry n_red=0 (reference all-clients-capable regate,
    selkies.py:949-973)."""
    from selkies_tpu.audio.pipeline import AudioPipeline
    if not opus.available():
        pytest.skip("libopus missing")
    s = AppSettings.parse([], {})
    p = AudioPipeline(s, source=SyntheticToneSource(48000, 2, 480))
    assert p.red_distance == 2
    val = s.apply_client_setting("audio_red_distance", 0)
    p.red_distance = int(val)     # ws_service._apply_live_settings path
    assert p.red_distance == 0
