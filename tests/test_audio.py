"""Audio pipeline tests: Opus roundtrip, 0x01/RED framing, listener
backpressure, bitrate control. All against real libopus via ctypes."""

import asyncio
import struct

import numpy as np
import pytest

from selkies_tpu import protocol as P
from selkies_tpu.audio import opus
from selkies_tpu.audio.pipeline import AudioPipeline, SyntheticToneSource
from selkies_tpu.settings import AppSettings

pytestmark = pytest.mark.skipif(not opus.available(),
                                reason="libopus not present")


def test_opus_encode_decode_roundtrip():
    enc = opus.Encoder(48000, 2, 96000)
    dec = opus.Decoder(48000, 2)
    t = np.arange(480) / 48000.0
    tone = (np.sin(2 * np.pi * 440 * t) * 8000).astype(np.int16)
    pcm = np.repeat(tone[:, None], 2, axis=1)
    for _ in range(8):            # let the codec converge
        pkt = enc.encode(pcm)
    out = dec.decode(pkt)
    for _ in range(4):
        out = dec.decode(pkt)
    assert out.shape == (480, 2)
    # decoded energy is in the right ballpark of the source tone
    assert 1000 < np.abs(out.astype(np.int64)).mean() < 12000


class FakeWs:
    def __init__(self):
        self.sent = []

    async def send_bytes(self, b):
        self.sent.append(bytes(b))


class FakeClient:
    _n = 1000

    def __init__(self):
        FakeClient._n += 1
        self.id = FakeClient._n
        self.ws = FakeWs()


def _settings(**kw):
    s = AppSettings.parse([], {})
    for k, v in kw.items():
        s.set_server(k, v)
    return s


async def _pump(pipe, client, n_frames=6, timeout=5.0):
    await pipe.start()
    pipe.add_listener(client)
    deadline = asyncio.get_event_loop().time() + timeout
    while len(client.ws.sent) < n_frames \
            and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.02)
    await pipe.stop()


def test_pipeline_delivers_decodable_opus():
    s = _settings(audio_red_distance=0)
    pipe = AudioPipeline(s, source=SyntheticToneSource(48000, 2, 480))
    client = FakeClient()
    asyncio.run(_pump(pipe, client))
    assert len(client.ws.sent) >= 6
    dec = opus.Decoder(48000, 2)
    for frame in client.ws.sent[:6]:
        assert frame[0] == P.OP_AUDIO and frame[1] == 0
        out = dec.decode(frame[2:])
        assert out.shape[0] == 480


def test_pipeline_red_framing_parses():
    s = _settings(audio_red_distance=2)
    pipe = AudioPipeline(s, source=SyntheticToneSource(48000, 2, 480))
    client = FakeClient()
    asyncio.run(_pump(pipe, client, n_frames=8))
    framed = [f for f in client.ws.sent if f[1] > 0]
    assert framed, "RED frames expected after history warms up"
    f = framed[-1]
    n_red = f[1]
    body = f[2:]
    (pts,) = struct.unpack(">I", body[:4])
    # block headers: F=1 + PT + 14-bit offset + 10-bit length
    sizes = []
    off = 4
    for _ in range(n_red):
        (word,) = struct.unpack(">I", body[off:off + 4])
        assert word >> 31 == 1
        sizes.append(word & 0x3FF)
        off += 4
    assert body[off] == 111          # primary header, F=0
    off += 1
    blocks_end = off + sum(sizes)
    primary = body[blocks_end:]
    dec = opus.Decoder(48000, 2)
    assert dec.decode(primary).shape[0] == 480
    # redundant blocks decode too
    dec2 = opus.Decoder(48000, 2)
    assert dec2.decode(bytes(body[off:off + sizes[0]])).shape[0] == 480


def test_listener_queue_drops_oldest_never_blocks():
    s = _settings(audio_backpressure_queue=4, audio_red_distance=0)
    pipe = AudioPipeline(s, source=SyntheticToneSource(48000, 2, 480))

    class StalledWs:
        def __init__(self):
            self.sent = []

        async def send_bytes(self, b):
            await asyncio.sleep(3600)     # never completes

    client = FakeClient()
    client.ws = StalledWs()

    async def run():
        await pipe.start()
        pipe.add_listener(client)
        await asyncio.sleep(0.3)          # ~30 frames at 10 ms
        q = pipe._listeners[client.id][1]
        assert q.qsize() <= 4             # bounded despite the stall
        assert pipe.frames_encoded > 10   # capture never paused
        await pipe.stop()

    asyncio.run(run())


def test_update_bitrate_changes_packet_size():
    enc = opus.Encoder(48000, 2, 320000, lowdelay=False)
    rng = np.random.default_rng(0)
    pcm = rng.integers(-20000, 20000, (480, 2), dtype=np.int16)
    for _ in range(8):
        big = len(enc.encode(pcm))
    enc.set_bitrate(16000)
    for _ in range(8):
        small = len(enc.encode(pcm))
    assert small < big
