"""Scaling advisor contracts (ISSUE 19): the pure ``decide`` hysteresis
walk on injected time, the stale-input fail-safe (absent heartbeats
never shrink a fleet), bound pinning, windowed signal summarisation,
and ``advisor_flip`` incidents through the real recorder — no sleeps,
no sockets."""

import dataclasses

import pytest

from selkies_tpu.fleet.autoscale import (REASONS, AdvisorParams,
                                         AdvisorState, ScalingAdvisor,
                                         decide, signals_from_observer)
from selkies_tpu.obs.health import FlightRecorder

PARAMS = AdvisorParams(min_hosts=1, max_hosts=5, up_confirm=2,
                       down_confirm=3, hold_s=30.0, window_s=30.0)


def sig(ts, *, hosts=3, occ=0.5, burn=0.0, queue=0, slo_failed=False,
        stale=False):
    return {"ts": ts, "hosts_ready": hosts, "occupancy": occ,
            "queue_depth": queue, "burn_fast_max": burn,
            "slo_failed": slo_failed, "stale": stale,
            "input_age_s": 0.5}


def walk(signals, params=PARAMS, state=None):
    """Run decide() over a signal sequence; return every decision."""
    st = state if state is not None else AdvisorState()
    out = []
    for s in signals:
        d, st = decide(s, st, params)
        out.append(d)
    return out, st


# ------------------------------------------------------------- decide()

class TestDecideCore:
    def test_first_evaluation_anchors_on_current_fleet(self):
        d, st = decide(sig(0.0, hosts=3), AdvisorState(), PARAMS)
        assert st.desired == 3
        assert d["desired_hosts"] == 3
        assert d["action"] == "hold"
        # with no hosts at all the anchor is min_hosts, never zero
        d, st = decide(sig(0.0, hosts=0), AdvisorState(), PARAMS)
        assert st.desired == PARAMS.min_hosts

    def test_up_needs_confirm_streak_then_flips_on_burn(self):
        ds, st = walk([sig(0.0, burn=20.0), sig(1.0, burn=20.0)])
        assert [d["action"] for d in ds] == ["hold", "up"]
        assert ds[0]["reason"] == "confirming"
        assert ds[1]["reason"] == "slo_burn"
        assert ds[1]["flipped"] and st.desired == 4 and st.flips == 1

    def test_pressure_reason_severity_order(self):
        # burn outranks queue outranks occupancy — the FIRST matching
        # reason names the flip
        ds, _ = walk([sig(0.0, burn=20.0, queue=2, occ=0.99)] * 2)
        assert ds[1]["reason"] == "slo_burn"
        ds, _ = walk([sig(0.0, queue=2, occ=0.99)] * 2)
        assert ds[1]["reason"] == "queue_depth"
        ds, _ = walk([sig(0.0, occ=0.99)] * 2)
        assert ds[1]["reason"] == "occupancy_high"

    def test_mixed_pressure_resets_the_streak(self):
        ds, st = walk([sig(0.0, burn=20.0),          # confirming (1/2)
                       sig(1.0),                     # steady: resets
                       sig(2.0, burn=20.0)])         # confirming again
        assert [d["action"] for d in ds] == ["hold"] * 3
        assert ds[2]["reason"] == "confirming"
        assert st.flips == 0

    def test_down_needs_streak_and_dwell(self):
        # flip up at t=1 (hold_s dwell starts), then go slack: the
        # down-confirm streak completes INSIDE the dwell (holding) and
        # only flips once the dwell expires
        seq = [sig(0.0, burn=20.0), sig(1.0, burn=20.0)]
        seq += [sig(2.0 + i, occ=0.1) for i in range(3)]   # confirming x2, holding
        seq += [sig(40.0, occ=0.1)]                        # dwell expired
        ds, st = walk(seq)
        assert [d["reason"] for d in ds[2:]] == \
            ["confirming", "confirming", "holding", "occupancy_low"]
        assert ds[-1]["action"] == "down" and ds[-1]["flipped"]
        assert st.desired == 3 and st.flips == 2

    def test_pinned_at_max_still_names_the_pressure(self):
        st = AdvisorState(desired=PARAMS.max_hosts)
        ds, st = walk([sig(0.0, burn=20.0)] * 3, state=st)
        assert all(d["action"] == "hold" for d in ds)
        assert ds[-1]["reason"] == "slo_burn"
        assert st.desired == PARAMS.max_hosts and st.flips == 0

    def test_pinned_at_min_never_goes_below(self):
        st = AdvisorState(desired=PARAMS.min_hosts)
        ds, st = walk([sig(float(i), hosts=1, occ=0.05)
                       for i in range(10)], state=st)
        assert st.desired == PARAMS.min_hosts and st.flips == 0
        assert ds[-1]["reason"] == "occupancy_low"

    def test_reasons_stay_in_the_bounded_vocabulary(self):
        seq = [sig(0.0, burn=20.0), sig(1.0, burn=20.0),
               sig(2.0, stale=True), sig(3.0, occ=0.1),
               sig(4.0, queue=1), sig(5.0)]
        ds, _ = walk(seq)
        assert all(d["reason"] in REASONS for d in ds)


class TestStaleFailSafe:
    def test_stale_holds_and_names_it(self):
        ds, st = walk([sig(0.0, stale=True, occ=0.05)] * 6)
        assert all(d["action"] == "hold" for d in ds)
        assert all(d["reason"] == "stale_input" for d in ds)
        assert st.flips == 0

    def test_stale_resets_a_down_streak_mid_confirm(self):
        # 2 calm evaluations, then the observer goes stale, then calm
        # again: the streak must restart from zero — stale gaps never
        # count toward shrinking the fleet
        st = AdvisorState(desired=3)
        seq = [sig(0.0, occ=0.1), sig(1.0, occ=0.1),
               sig(2.0, occ=0.1, stale=True),
               sig(3.0, occ=0.1), sig(4.0, occ=0.1)]
        ds, st = walk(seq, state=st)
        assert st.flips == 0
        assert ds[-1]["reason"] == "confirming"     # 2/3, not done

    def test_stale_does_not_block_later_scale_up(self):
        # recovery from staleness with real pressure still scales up
        seq = [sig(0.0, stale=True), sig(1.0, burn=20.0),
               sig(2.0, burn=20.0)]
        ds, _ = walk(seq)
        assert ds[-1]["action"] == "up"


# ------------------------------------------- signals + stateful wrapper

class FakeObserver:
    """Duck-typed observer: bounded rings + staleness, injected clock."""

    def __init__(self, now=100.0, *, stale=False, age=0.5):
        self.now = now
        self.stale = stale
        self.age = age
        self.rings = {}
        self.recorder = FlightRecorder()

    def _clock(self):
        return self.now

    def series(self, name, window_s=30.0, now=None):
        now = self.now if now is None else now
        return [(ts, v) for ts, v in self.rings.get(name, [])
                if now - ts <= window_s]

    def series_age(self, now=None):
        return self.age

    def is_stale(self, now=None):
        return self.stale


class TestSignalsFromObserver:
    def test_windowed_mean_for_occupancy_max_for_burn(self):
        obs = FakeObserver(now=100.0)
        obs.rings["seat_occupancy"] = [(98.0, 0.4), (99.0, 0.6)]
        obs.rings["burn_fast_max"] = [(98.0, 2.0), (99.0, 16.0)]
        obs.rings["queue_depth"] = [(98.0, 0), (99.0, 3)]
        obs.rings["slo_verdict"] = [(99.0, 2)]
        obs.rings["hosts_ready"] = [(99.0, 4)]
        s = signals_from_observer(obs, window_s=30.0)
        assert s["seat_occupancy"] == pytest.approx(0.5)
        assert s["occupancy"] == pytest.approx(0.5)   # max of axis means
        assert s["burn_fast_max"] == 16.0             # max, not mean
        assert s["queue_depth"] == 3
        assert s["slo_failed"] is True
        assert s["hosts_ready"] == 4

    def test_samples_outside_the_window_are_dropped(self):
        obs = FakeObserver(now=100.0)
        obs.rings["seat_occupancy"] = [(10.0, 1.0), (99.0, 0.2)]
        s = signals_from_observer(obs, window_s=30.0)
        assert s["seat_occupancy"] == pytest.approx(0.2)

    def test_empty_rings_mean_zero_not_crash(self):
        s = signals_from_observer(FakeObserver())
        assert s["occupancy"] == 0.0
        assert s["burn_fast_max"] == 0.0
        assert s["hosts_ready"] == 0


class TestScalingAdvisor:
    def burn_obs(self, now=100.0):
        obs = FakeObserver(now=now)
        obs.rings["hosts_ready"] = [(now - 1, 2)]
        obs.rings["burn_fast_max"] = [(now - 1, 20.0)]
        return obs

    def test_flip_records_incident_and_snapshot_carries_decision(self):
        obs = self.burn_obs()
        adv = ScalingAdvisor(obs, params=PARAMS)
        adv.evaluate(now=100.0)
        obs.now = 101.0
        obs.rings["burn_fast_max"].append((100.5, 20.0))
        d = adv.evaluate(now=101.0)
        assert d["flipped"] and d["reason"] == "slo_burn"
        kinds = [i["kind"] for i in obs.recorder.snapshot()]
        assert kinds.count("advisor_flip") == 1
        snap = adv.snapshot()
        assert snap["flips"] == 1
        assert snap["decision"]["desired_hosts"] == 3
        assert snap["params"]["up_confirm"] == PARAMS.up_confirm

    def test_stale_observer_never_flips(self):
        obs = FakeObserver(stale=True, age=60.0)
        obs.rings["hosts_ready"] = [(99.0, 3)]
        obs.rings["seat_occupancy"] = [(99.0, 0.05)]
        adv = ScalingAdvisor(obs, params=PARAMS)
        for i in range(8):
            d = adv.evaluate(now=100.0 + i)
        assert d["action"] == "hold"
        assert d["reason"] == "stale_input"
        assert adv.state.flips == 0
        assert not [i for i in obs.recorder.snapshot()
                    if i["kind"] == "advisor_flip"]

    def test_params_are_frozen(self):
        with pytest.raises(dataclasses.FrozenInstanceError):
            PARAMS.max_hosts = 10
