"""bench.py driver contract: prints exactly ONE JSON line on stdout with
the keys the driver records (BENCH_r{N}.json). Runs the real bench at a
tiny geometry so the whole thing stays inside the CI budget.

ONE subprocess serves every assertion (a bench run costs ~1 min of jax
import + warm-cache compile; two runs would push tier-1 over its
timeout). The run simulates the dead-relay fallback exactly as
``probe_backend`` records it (JAX_PLATFORMS=cpu +
BENCH_CPU_REASON=relay-dead) — deterministic even on machines where a
REAL relay is alive — which makes it double as the ISSUE 3 acceptance
bar: a dead-relay run must carry a ``failed`` backend verdict, never a
plausible-looking fps number. The healthy-backend verdict branches are
unit-tested in tests/test_obs.py::test_backend_verdict_modes."""

import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent

_cache = {}

#: the run's auto-appended ledger (ISSUE 6) — redirected to a tempdir
#: so the contract run never pollutes the repo's committed trajectory
_LEDGER = os.path.join(tempfile.mkdtemp(prefix="selkies-bench-contract-"),
                       "ledger.jsonl")


def _bench_doc() -> dict:
    if "doc" in _cache:
        return _cache["doc"]
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", BENCH_CPU_REASON="relay-dead",
               BENCH_WIDTH="256", BENCH_HEIGHT="128",
               BENCH_FRAMES="6", BENCH_LAT_BUDGET_S="10",
               BENCH_TP_BUDGET_S="10", BENCH_PIPE_BUDGET_S="15",
               BENCH_PROBE_BUDGET_S="1",
               PERF_LEDGER_PATH=_LEDGER)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, str(ROOT / "bench.py")],
                       capture_output=True, text=True, timeout=900,
                       env=env, cwd=ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE json line: {lines}"
    _cache["doc"] = json.loads(lines[0])
    return _cache["doc"]


def test_bench_emits_single_json_line():
    doc = _bench_doc()
    for key in ("metric", "value", "unit", "vs_baseline", "backend"):
        assert key in doc, key
    assert doc["unit"] == "fps"
    assert isinstance(doc["value"], (int, float))
    # explicit fallback labelling (VERDICT r3 weak 5): never a silent
    # CPU number
    assert doc["backend"].startswith(("cpu-fallback", "cpu", "tpu",
                                      "axon"))
    # per-stage latency attribution (ISSUE 2, re-scoped by ISSUE 10):
    # every stage key present. The ±20% stage-sum-vs-e2e coverage
    # contract is only meaningful FRAME-SERIALLY — and bench.py's stage
    # table + latency_mean_ms come from the ALWAYS-serial IDR loop
    # regardless of BENCH_PIPELINE_DEPTH, so the contract holds (and is
    # asserted) at every depth. The PIPELINED phase is covered by the
    # occupancy identity instead (test_bench_occupancy_block), where
    # stage sum exceeding e2e is the point.
    from selkies_tpu.trace import STAGES
    assert set(doc["stages_ms"]) == set(STAGES)
    stage_sum = doc["stage_sum_ms"]
    e2e = doc["latency_mean_ms"]
    assert stage_sum == round(sum(doc["stages_ms"].values()), 3)
    assert abs(stage_sum - e2e) <= 0.2 * e2e, \
        f"stage sum {stage_sum}ms vs e2e {e2e}ms: uninstrumented stall"


def test_bench_device_telemetry_keys():
    """ISSUE 3: HBM peak, compile accounting, and a backend verdict
    accompany every fps line."""
    doc = _bench_doc()
    assert isinstance(doc["hbm_peak_mb"], (int, float))
    assert isinstance(doc["compile_count"], int)
    assert isinstance(doc["compile_total_s"], (int, float))
    assert isinstance(doc["compile_cache_hits"], int)
    assert isinstance(doc["compile_cache_misses"], int)
    assert doc["backend_health"]["status"] in ("ok", "degraded", "failed")


def test_bench_qoe_block():
    """ISSUE 4: a qoe block (ack RTT percentiles, drop rate, composite
    score) rides next to the fps line, computed with the same formula
    /api/sessions documents."""
    from selkies_tpu.obs.qoe import qoe_score
    doc = _bench_doc()
    q = doc["qoe"]
    assert isinstance(q["ack_rtt_p50_ms"], (int, float))
    assert isinstance(q["ack_rtt_p99_ms"], (int, float))
    assert q["ack_rtt_p99_ms"] >= q["ack_rtt_p50_ms"] > 0
    assert q["drop_rate"] == 0.0
    assert 0.0 <= q["score"] <= 100.0
    assert q["score"] == qoe_score(doc["value"], 60.0,
                                   q["ack_rtt_p50_ms"], 0.0)


def test_bench_perf_block():
    """ISSUE 6: static per-step cost analysis (flops, HBM bytes,
    roofline-ms) recorded at compile time rides the JSON line."""
    doc = _bench_doc()
    p = doc["perf"]
    assert p["hbm_gbps"] == 800.0
    good = [s for s in p["steps"] if not s.get("error")]
    assert good, f"no analysable steps: {p['steps']}"
    names = {s["name"] for s in good}
    assert any(n.startswith(("h264.", "jpeg.")) for n in names), names
    for s in good:
        assert s["flops"] > 0 and s["bytes_accessed"] > 0
        assert s["roofline_ms"] >= 0
        assert s["compile_s"] is None or s["compile_s"] >= 0


def test_bench_occupancy_block():
    """ISSUE 6 + 10: the occupancy block now measures the PIPELINE
    phase. The occupancy identity must hold at every depth: per-frame
    critical-path shares + bubble account for the whole frame window
    (stages + bubble == e2e), i.e. the critical path never exceeds the
    stage sum; overlap is the cross-frame window fraction."""
    from selkies_tpu.trace import STAGES
    from selkies_tpu.trace.summary import BUBBLE
    doc = _bench_doc()
    occ = doc["occupancy"]
    assert occ["frames"] > 0
    assert 0.0 <= occ["overlap_fraction"] < 1.0
    shares = occ["critical_path_share"]
    assert set(shares) <= set(STAGES) | {BUBBLE}
    assert abs(sum(shares.values()) + occ["bubble_share"] - 1.0) < 0.05


def test_bench_pipeline_block():
    """ISSUE 10: the deep-pipeline phase documents its configuration —
    depth, pacing period, streaming — so a serial and a depth-2 run at
    the same geometry compare honestly in the ledger."""
    doc = _bench_doc()
    assert doc["pipeline_depth"] == 2          # the default
    p = doc["pipeline"]
    assert p["depth"] == 2 and p["stripe_streaming"] is True
    assert p["period_ms"] > 0 and p["frames"] >= 12
    assert p["sustained_fps"] > 0


def test_bench_ledger_autorecord():
    """ISSUE 6: every run auto-appends to the perf ledger, and a
    dead-relay fallback records as NOT baseline-eligible — the r4/r5
    silent number can never become the number to beat."""
    _bench_doc()
    sys.path.insert(0, str(ROOT))
    from tools import perf_ledger
    entries = perf_ledger.read_ledger(_LEDGER)
    assert len(entries) == 1, entries
    e = entries[0]
    assert e["backend"] == "cpu-fallback-relay-dead"
    assert e["backend_class"] == "cpu"
    assert e["backend_health"] == "failed"
    assert e["baseline_eligible"] is False
    assert e["resolution"] == "256x128"
    # ISSUE 10: the depth/overlap acceptance pair rides every entry
    assert e["pipeline_depth"] == 2
    assert isinstance(e["overlap_fraction"], (int, float))
    # and check refuses to gate on it: rc 3 = "no gateable number"
    # (0 under --warn-only), so a hard gate can't go green on it
    assert perf_ledger.main(["--ledger", _LEDGER, "check"]) == 3
    assert perf_ledger.main(
        ["--ledger", _LEDGER, "check", "--warn-only"]) == 0


def test_bench_energy_block():
    """ISSUE 14: an energy block (joules/frame, watts over the
    throughput loop, fps/W, honest source label) rides the JSON line,
    with fps_per_w == fps / watts_mean by construction, and the ledger
    entry carries both energy columns non-null."""
    doc = _bench_doc()
    e = doc["energy"]
    assert e["source"] in ("proxy", "rapl", "device")
    assert e["watts_mean"] > 0
    # the idle floor: watts never read zero, whatever the fps
    assert e["watts_mean"] >= e["idle_floor_w"] > 0 \
        or e["source"] != "proxy"
    assert e["joules_frame"] is not None and e["joules_frame"] > 0
    # the pinned identity (fps_per_w is rounded to 4 places)
    assert abs(e["fps_per_w"] - doc["value"] / e["watts_mean"]) < 1e-4
    assert abs(e["joules_frame"] * doc["value"] - e["watts_mean"]) \
        < 0.01 * e["watts_mean"]
    # ledger columns (the pareto subcommand's feed)
    sys.path.insert(0, str(ROOT))
    from tools import perf_ledger
    entry = perf_ledger.read_ledger(_LEDGER)[0]
    assert entry["joules_frame"] == e["joules_frame"]
    assert entry["fps_per_w"] == e["fps_per_w"]
    assert entry["energy_source"] == e["source"]


def test_bench_glass_to_glass_block():
    """ISSUE 7 acceptance: a glass_to_glass block (p50/p99, clock-sync
    quality) rides the JSON line, and g2g >= server-side e2e for EVERY
    frame — min_margin_ms is the per-frame floor of (g2g - e2e), so one
    assertion pins the whole run."""
    doc = _bench_doc()
    g = doc["glass_to_glass"]
    assert g["frames"] > 0
    assert g["p99_ms"] >= g["p50_ms"] > 0
    assert g["mean_ms"] > 0
    # the pin: glass-to-glass can never read better than the server
    # path it contains
    assert g["min_margin_ms"] >= 0.0, g
    # clock-sync quality from the REAL estimator, not a constant
    clock = g["clock"]
    assert clock["synced"] is True
    assert clock["samples"] >= 3 and clock["rejected"] == 0
    assert clock["error_bound_ms"] is not None \
        and clock["error_bound_ms"] < 5.0
    # and the ledger entry carries the g2g trajectory column
    sys.path.insert(0, str(ROOT))
    from tools import perf_ledger
    e = perf_ledger.read_ledger(_LEDGER)[0]
    assert e["g2g_p99_ms"] == g["p99_ms"]
    assert e["g2g_p50_ms"] == g["p50_ms"]


def test_bench_dead_relay_reports_failed_backend_verdict():
    """The ISSUE 3 acceptance bar (the r04/r05 silent-failure mode):
    a run that fell back from a dead relay is loudly labelled AND
    carries a failed backend health verdict."""
    doc = _bench_doc()
    assert doc["backend"] == "cpu-fallback-relay-dead"
    assert doc["backend_health"]["status"] == "failed"
    assert "relay-dead" in doc["backend_health"]["reason"]


def test_bench_prewarm_block():
    """ISSUE 8: the compile-plane view rides the JSON line — the
    ladder-reachable lattice for the bench operating point, with the
    programs this run compiled adopted as warm."""
    doc = _bench_doc()
    p = doc["prewarm"]
    assert p["lattice_size"] >= 2          # base + downscale target
    assert 1 <= p["warmed"] <= p["lattice_size"]
    assert p["deferred_transitions"] == 0  # no ladder runs in main()


def _chaos_doc() -> dict:
    if "chaos" in _cache:
        return _cache["chaos"]
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", BENCH_PROBE_BUDGET_S="1",
               BENCH_CHAOS_WIDTH="128", BENCH_CHAOS_HEIGHT="64",
               BENCH_CHAOS_BUDGET_S="90",
               BENCH_CHAOS_COMPILE_DELAY_S="2",
               BENCH_CHAOS_STORM_BUDGET_S="240",
               PERF_LEDGER_PATH=_LEDGER)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, str(ROOT / "bench.py"),
                        "--chaos"],
                       capture_output=True, text=True, timeout=800,
                       env=env, cwd=ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE json line: {lines}"
    _cache["chaos"] = json.loads(lines[0])
    return _cache["chaos"]


def test_chaos_compile_storm_transitions_stay_compile_free():
    """ISSUE 8 acceptance: with an injected slow compiler
    (encoder.compile:slow), a ladder downscale transition never blocks
    the frame loop on a compile — it defers with a transition_deferred
    incident while the pre-warm worker eats the build in the
    background, then lands with ZERO foreground compiles, and the
    chaos run as a whole still recovers."""
    doc = _chaos_doc()
    assert doc["chaos"]["recovered"] is True
    storm = doc["chaos"]["compile_storm"]
    assert storm["deferred_transitions"] >= 1
    assert storm["landed"] is True and storm["ladder_level"] == 1
    assert storm["foreground_compiles"] == 0
    # the switch itself is session rebuild cost, never a compile: far
    # below the injected compile delay
    assert storm["switch_ms"] < storm["delay_s"] * 1000
    # the background warm demonstrably ate the injected delay
    assert storm["background_compile_s"] >= storm["delay_s"]
    assert storm["prewarm"]["failed"] == 0


def _fleet_doc() -> dict:
    """bench --fleet is pure simulated-host math (no jax): its own
    subprocess costs well under a second, so no caching gymnastics."""
    if "fleet" in _cache:
        return _cache["fleet"]
    env = dict(os.environ, PERF_LEDGER_PATH=_LEDGER)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, str(ROOT / "bench.py"),
                        "--fleet"],
                       capture_output=True, text=True, timeout=120,
                       env=env, cwd=ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE json line: {lines}"
    _cache["fleet"] = json.loads(lines[0])
    return _cache["fleet"]


def test_bench_fleet_contract_block():
    """ISSUE 11 acceptance: bench --fleet (3 simulated in-process
    hosts, seeded, injected clock) emits a ``fleet`` block proving the
    serving-architecture contracts: bin-packing stays within per-host
    HBM/pixel budgets, the cold host receives nothing before its
    readiness probe passes, draining a host migrates every seat with
    an IDR resync and zero wedged or dropped sessions, and a killed
    host's seats re-place within the reconnect grace."""
    doc = _fleet_doc()
    assert doc["metric"] == "fleet_contract"
    assert doc["value"] == 1.0
    assert doc["backend_health"]["status"] == "ok"
    f = doc["fleet"]
    assert f["contract_ok"] is True
    assert f["hosts"] == 3
    p = f["placement"]
    assert p["bin_pack_ok"] is True
    assert p["cold_host_placements_before_ready"] == 0
    assert p["placed"] == p["sessions"] and p["pending"] == 0
    d = f["drain"]
    assert d["dropped"] == 0 and d["wedged"] == 0
    assert d["still_on_source"] == 0
    assert d["migrated"] == d["seats"]
    assert d["idr_resyncs"] >= d["migrated"]
    assert d["drained"] is True
    fo = f["failover"]
    assert fo["replaced"] == fo["seats"]
    assert fo["within_grace"] == fo["seats"]
    # every simulated heartbeat crossed the strict wire parser
    assert f["heartbeats"]["rejected"] == 0
    assert f["heartbeats"]["sent"] > 0


@pytest.mark.slow
def test_bench_adaptive_contract_block():
    """ISSUE 15 acceptance shape: bench --adaptive emits an ``adaptive``
    block whose own clauses already gated the exit code (the run exits 1
    on any break), plus the top-level dirty_fraction/content_class
    ledger columns. Slow-marked like the stripe session contract — the
    ``adaptive-bench`` CI job re-proves the full clauses every push;
    this pins the JSON surface the driver and the ledger consume."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", BENCH_PROBE_BUDGET_S="1",
               BENCH_ADAPT_WIDTH="128", BENCH_ADAPT_HEIGHT="128",
               BENCH_ADAPT_FRAMES="3", BENCH_ADAPT_REPS="1",
               PERF_LEDGER_PATH=_LEDGER)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, str(ROOT / "bench.py"),
                        "--adaptive"],
                       capture_output=True, text=True, timeout=900,
                       env=env, cwd=ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE json line: {lines}"
    doc = json.loads(lines[0])
    assert doc["unit"] == "speedup_10pct_vs_full"
    assert "dirty_fraction" in doc and "content_class" in doc
    a = doc["adaptive"]
    assert a["monotonic"] is True
    assert a["byte_identical_full"] is True
    assert a["decode_valid"] is True
    assert a["content_classes_ok"] is True
    points = a["points"]
    assert [p["dirty_fraction"] for p in points] == \
        sorted(p["dirty_fraction"] for p in points)
    for p in points:
        assert p["encode_ms"] > 0 and p["band_rows"] >= 1
    # the ledger row carries the new columns (entry_from_bench)
    rows = [json.loads(ln) for ln in
            Path(_LEDGER).read_text().splitlines()]
    row = [e for e in rows
           if e["metric"].startswith("adaptive_encode_")][-1]
    assert row["dirty_fraction"] == points[0]["dirty_fraction"]
    assert row["adaptive"]["speedup_10pct"] == a["speedup_10pct"]
