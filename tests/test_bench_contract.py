"""bench.py driver contract: prints exactly ONE JSON line on stdout with
the keys the driver records (BENCH_r{N}.json). Runs the real bench at a
tiny geometry so the whole thing stays inside the CI budget."""

import json
import os
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_bench_emits_single_json_line():
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               BENCH_WIDTH="256", BENCH_HEIGHT="128",
               BENCH_FRAMES="6", BENCH_LAT_BUDGET_S="10",
               BENCH_TP_BUDGET_S="10", BENCH_PROBE_BUDGET_S="1")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, str(ROOT / "bench.py")],
                       capture_output=True, text=True, timeout=900,
                       env=env, cwd=ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE json line: {lines}"
    doc = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "backend"):
        assert key in doc, key
    assert doc["unit"] == "fps"
    assert isinstance(doc["value"], (int, float))
    # explicit fallback labelling (VERDICT r3 weak 5): never a silent
    # CPU number
    assert doc["backend"].startswith(("cpu-fallback", "cpu", "tpu",
                                      "axon"))
    # per-stage latency attribution (ISSUE 2): every stage key present,
    # and the stage sum within 20% of the measured e2e frame latency
    from selkies_tpu.trace import STAGES
    assert set(doc["stages_ms"]) == set(STAGES)
    stage_sum = doc["stage_sum_ms"]
    e2e = doc["latency_mean_ms"]
    assert stage_sum == round(sum(doc["stages_ms"].values()), 3)
    assert abs(stage_sum - e2e) <= 0.2 * e2e, \
        f"stage sum {stage_sum}ms vs e2e {e2e}ms: uninstrumented stall"
