"""Broadcast plane contracts (ISSUE 17): rendition ladder enumeration
and content pruning, viewer-registry rung routing with dwell hysteresis
and IDR resync, bounded viewer metric cardinality, the fan-out hub's
refcounted grace release (reconnect cancels, shutdown leaks nothing),
relay-only seats on the scheduler's bandwidth axis, and the gateway's
1-to-N viewer endpoint — all on injected clocks and fake timers."""

import asyncio

import pytest

from selkies_tpu.broadcast.fanout import RenditionHub
from selkies_tpu.broadcast.ladder import RenditionLadder
from selkies_tpu.broadcast.registry import ViewerRegistry
from selkies_tpu.fleet.migrate import MigrationCoordinator
from selkies_tpu.fleet.protocol import (DeviceCapacity,
                                        FleetProtocolError, Heartbeat,
                                        estimate_relay_mbps,
                                        parse_session_spec)
from selkies_tpu.fleet.scheduler import SeatScheduler
from selkies_tpu.fleet.sim import SimFleet, SimHost
from selkies_tpu.obs.health import FlightRecorder
from selkies_tpu.prewarm.lattice import (Signature,
                                         broadcast_rung_signatures,
                                         lattice_from_settings)


def _ladder(width=1920, height=1080, codec="h264", **kw):
    return RenditionLadder(Signature(width=width, height=height,
                                     codec=codec), **kw)


# ----------------------------------------------------------------- ladder

def test_ladder_enumeration_and_content_pruning():
    ladder = _ladder()
    assert ladder.names() == ["src", "mid", "low"]
    assert [r.width for r in ladder.rungs] == [1920, 960, 480]
    assert ladder.rungs[2].fps_divisor == 2
    # cheaper down the ladder: the relay economics must be monotone
    ks = [r.kbps_est for r in ladder.rungs]
    assert ks[0] > ks[1] > ks[2] > 0
    # PR-15 content classes prune pointless rungs; the top rung and
    # therefore at least ONE rung always survives
    assert [r.name for r in ladder.active("static")] == ["src"]
    assert ladder.device_dispatches_per_frame("static") == 1
    assert ladder.device_dispatches_per_frame("scroll") == 2
    assert ladder.device_dispatches_per_frame("video") == 3
    assert ladder.device_dispatches_per_frame(None) == 3


def test_ladder_rung_selection():
    ladder = _ladder()
    # ladder-per-session (WS): QoE score verdict
    assert ladder.rung_for_score(90.0) == 0
    assert ladder.rung_for_score(55.0) == 1
    assert ladder.rung_for_score(10.0) == 2
    # simulcast (WebRTC): congestion-controller target bitrate picks
    # the best rung that fits under it
    assert ladder.rung_for_bitrate(10_000.0) == 0
    assert ladder.rung_for_bitrate(2_000.0) == 1
    assert ladder.rung_for_bitrate(100.0) == 2


def test_ladder_dedups_at_geometry_floor():
    # a tiny desktop collapses the ladder: /2 and /4 floor to the same
    # program, so only one downscaled rung is enumerated
    ladder = _ladder(width=128, height=96, codec="jpeg")
    assert len(ladder) == 2
    assert ladder.rungs[1].width == 64


def test_broadcast_rungs_ride_the_prewarm_lattice():
    # the ladder's signatures ARE lattice points: the prewarm worker
    # warms them through the same step factories as any seat
    base = Signature(width=1920, height=1080, codec="h264")
    sigs = broadcast_rung_signatures(base)
    assert [s.width for s in sigs] == [1920, 960, 480]
    assert [r.signature.program_key for r in _ladder().rungs] == \
        [s.program_key for s in sigs]

    class NS:
        pass

    ns = NS()
    ns.enable_broadcast = True
    plan = lattice_from_settings(ns)
    assert any(s.width == 480 for s in plan.signatures)
    off = lattice_from_settings(NS())   # gated: default stays put
    assert not any(s.width == 480 for s in off.signatures)


# --------------------------------------------------------------- registry

def test_registry_hysteresis_switch_and_idr_hook():
    switches = []
    reg = ViewerRegistry(_ladder(), source="d", clock=lambda: 0.0,
                         switch_dwell=3,
                         on_switch=lambda st, old, new:
                         switches.append((st.sid, old, new)))
    reg.attach("v1", rung=0)
    # two bad verdicts hold; the third lands the switch
    assert reg.route("v1", score=20.0) == 0
    assert reg.route("v1", score=20.0) == 0
    assert reg.route("v1", score=20.0) == 2
    assert switches == [("v1", 0, 2)]
    st = reg.get("v1")
    assert st.rung_switches == 1 and st.idr_resyncs == 1
    # one healthy blip doesn't flap back up
    reg.route("v1", score=90.0)
    assert reg.get("v1").rung == 2
    # a changed desire resets the dwell streak
    reg.route("v1", score=55.0)
    reg.route("v1", score=90.0)
    reg.route("v1", score=90.0)
    assert reg.get("v1").rung == 2
    reg.route("v1", score=90.0)
    assert reg.get("v1").rung == 0
    assert reg.total_switches == 2


def test_registry_clamps_routing_to_active_rungs():
    # static content prunes every rung but the source: a terrible
    # score must never route a viewer onto a pruned rung
    reg = ViewerRegistry(_ladder(), source="d", switch_dwell=1)
    reg.attach("v1")
    assert reg.route("v1", score=5.0, content_class="static") == 0
    assert reg.get("v1").rung_switches == 0
    # scroll keeps the downscale rung: the same score lands there
    assert reg.route("v1", score=5.0, content_class="scroll") == 1


def test_registry_snapshot_and_g2g():
    reg = ViewerRegistry(_ladder(), source="d", clock=lambda: 7.0)
    reg.attach("v1")
    for ms in (40.0, 42.0, 55.0):
        reg.note_frame("v1", g2g_ms=ms, size_bytes=1000)
    snap = reg.snapshot()
    assert snap["viewers"] == 1 and snap["per_rung"]["src"] == 1
    sess = snap["sessions"][0]
    assert sess["frames"] == 3 and sess["bytes"] == 3000
    assert sess["g2g_p99_ms"] == 55.0
    reg.detach("v1")
    assert len(reg) == 0


def test_registry_metric_cardinality_capped():
    # satellite: viewer series bounded like qoe_seat_label_cap — the
    # first label_cap viewers get series, everyone else rolls into
    # seat="_overflow"; a 10k-viewer webinar cannot mint 10k series
    from selkies_tpu.server import metrics
    metrics.clear()
    reg = ViewerRegistry(_ladder(), source="d", label_cap=4)
    for i in range(10):
        reg.attach(f"v{i}")
        reg.note_frame(f"v{i}", g2g_ms=50.0, size_bytes=100)
    reg.export_metrics()
    seats = set()
    for line in metrics.render_prometheus().splitlines():
        if line.startswith("selkies_broadcast_viewer_bytes{"):
            for part in line[line.index("{") + 1:
                             line.index("}")].split(","):
                if part.startswith("seat="):
                    seats.add(part.split("=", 1)[1].strip('"'))
    assert len(seats) == 5 and "_overflow" in seats
    assert sum(1 for s in seats if s != "_overflow") == 4


# -------------------------------------------------------------------- hub

class FakeSchedule:
    """Manual grace-timer seam: fire_all() is 'the grace elapsed'."""

    def __init__(self):
        self.timers = []
        self.cancelled = 0

    def __call__(self, delay, cb):
        outer = self

        class T:
            def cancel(self):
                outer.cancelled += 1
                if self in outer.timers:
                    outer.timers.remove(self)

            def fire(self):
                if self in outer.timers:
                    outer.timers.remove(self)
                    cb()

        t = T()
        self.timers.append(t)
        return t

    def fire_all(self):
        for t in list(self.timers):
            t.fire()


def test_hub_refcount_grace_and_reconnect_cancel():
    sched = FakeSchedule()
    opens, closes = [], []
    hub = RenditionHub(schedule=sched, grace_s=1.0,
                       on_open=lambda s, r: opens.append((s, r)),
                       on_close=lambda s, r: closes.append((s, r)))
    assert hub.subscribe("d", "src", "v1") == 1
    assert hub.subscribe("d", "src", "v2") == 2
    assert opens == [("d", "src")]        # refcounted: opened ONCE
    assert hub.publish("d", "src", b"f") == 2
    hub.unsubscribe("d", "src", "v1")
    assert not sched.timers               # not last-out: no timer
    hub.unsubscribe("d", "src", "v2")
    assert len(sched.timers) == 1         # last-out arms the grace
    # reconnect inside the grace cancels the release: never flaps
    hub.subscribe("d", "src", "v2")
    assert not sched.timers and closes == [] and sched.cancelled == 1
    hub.unsubscribe("d", "src", "v2")
    sched.fire_all()
    assert closes == [("d", "src")]
    assert hub.open_rungs() == [] and hub.upstream_closes == 1


def test_hub_move_never_dips_and_shutdown_cancels():
    sched = FakeSchedule()
    closes = []
    hub = RenditionHub(schedule=sched, grace_s=1.0,
                       on_close=lambda s, r: closes.append((s, r)))
    hub.subscribe("d", "src", "v1")
    hub.move("d", "src", "low", "v1")
    # new rung opened BEFORE the old one's grace even starts
    assert ("d", "low") in hub.open_rungs()
    assert len(sched.timers) == 1         # old rung pending release
    # gateway shutdown: every pending timer cancelled, every open
    # upstream closed, later subscribes refused
    hub.shutdown()
    assert sched.cancelled == 1 and not sched.timers
    assert hub.pending_releases() == 0 and hub.open_rungs() == []
    assert ("d", "low") in closes
    assert hub.subscribe("d", "src", "v9") == 0


def test_hub_failing_sink_is_isolated():
    hub = RenditionHub()
    got = []
    hub.subscribe("d", "src", "bad", lambda f: 1 / 0)
    hub.subscribe("d", "src", "good", got.append)
    assert hub.publish("d", "src", b"x") == 1
    assert got == [b"x"]
    assert hub.publish("d", "nope", b"x") == 0


# -------------------------------------------- scheduler: relay-only seats

def _rig(**sched_kw):
    clock_box = [0.0]
    rec = FlightRecorder()
    sched = SeatScheduler(clock=lambda: clock_box[0], recorder=rec,
                          host_timeout_s=3.0, **sched_kw)
    coord = MigrationCoordinator(sched, clock=lambda: clock_box[0],
                                 recorder=rec, grace_s=3.0)
    fleet = SimFleet(sched, coord, clock_box=clock_box)
    fleet.add_host(SimHost("h0", clock=fleet.clock, devices=1,
                           seat_slots=4, hbm_limit_mb=4096.0,
                           pixel_budget=3 * 1920 * 1080,
                           warm_after_s=0.0, grace_s=3.0, recorder=rec))
    fleet.tick(0.5)
    return fleet, sched, coord, rec


def _relay_doc(sid, source="desk", w=480, h=270, rung="low"):
    return {"v": 1, "kind": "place", "sid": sid, "seat_class": "relay",
            "source_sid": source, "rung": rung, "width": w, "height": h,
            "codec": "h264"}


def test_relay_spec_budgets_bandwidth_not_hbm():
    spec = parse_session_spec(_relay_doc("v1"))
    assert spec.is_relay and spec.source_sid == "desk"
    # the relay-only fix: zero HBM, zero pixels, zero watts — the seat
    # is billed on the gateway's bandwidth axis instead
    assert spec.budget_mb() == 0.0 and spec.pixels == 0
    assert spec.budget_w() == 0.0
    assert spec.budget_mbps() == estimate_relay_mbps(480, 270, "h264")
    assert spec.budget_mbps() > 0.0
    # a relay without its source is meaningless: strict-parse rejects
    with pytest.raises(FleetProtocolError):
        parse_session_spec({"v": 1, "kind": "place", "sid": "v1",
                            "seat_class": "relay", "width": 640,
                            "height": 360})
    with pytest.raises(FleetProtocolError):
        parse_session_spec({"v": 1, "kind": "place", "sid": "v1",
                            "seat_class": "weird", "width": 640,
                            "height": 360})


def test_relay_placement_pinned_and_bandwidth_refused():
    fleet, sched, coord, rec = _rig(gateway_mbps_budget=2.0)
    desk = parse_session_spec({"v": 1, "kind": "place", "sid": "desk",
                               "width": 1920, "height": 1080,
                               "codec": "h264"})
    assert sched.place(desk) is not None
    # each low rung viewer is ~0.5 Mbps: budget 2.0 admits four
    placed = []
    for i in range(6):
        p = sched.place(parse_session_spec(_relay_doc(f"v{i}")))
        if p is not None:
            placed.append(p)
    assert len(placed) == 4
    assert all(p.host_id == "h0" for p in placed)     # pinned to source
    assert len(sched.pending) == 2                    # refusal queues
    # relays never appear in host seat work: one encode session only
    assert len(fleet.hosts["h0"].sessions) == 1
    assert len(sched.placements_on("h0")) == 1
    bw = sched.snapshot()["bandwidth"]
    assert bw["relay_viewers"] == 4 and bw["budget_mbps"] == 2.0
    assert bw["fleet_mbps_est"] >= 4 * 0.5


def test_relay_released_with_its_source():
    fleet, sched, coord, rec = _rig(gateway_mbps_budget=100.0)
    desk = parse_session_spec({"v": 1, "kind": "place", "sid": "desk",
                               "width": 640, "height": 360,
                               "codec": "h264"})
    assert sched.place(desk) is not None
    for i in range(3):
        assert sched.place(
            parse_session_spec(_relay_doc(f"v{i}"))) is not None
    sched.release("desk")
    # the cascade: a released source takes its viewers with it
    assert all(sched.get(f"v{i}") is None for i in range(3))
    kinds = [e["kind"] for e in rec.snapshot()]
    assert kinds.count("viewer_released") >= 3


def test_relay_viewer_in_sim_heartbeat_round_trip():
    fleet, sched, coord, rec = _rig(gateway_mbps_budget=100.0)
    desk = parse_session_spec({"v": 1, "kind": "place", "sid": "desk",
                               "width": 1920, "height": 1080,
                               "codec": "h264"})
    sched.place(desk)
    sched.place(parse_session_spec(_relay_doc("v0")))
    fleet.tick(1.0)
    # the new heartbeat fields (egress estimate, seat class, rung)
    # round-trip the strict wire parser with zero rejections
    assert fleet.heartbeats_sent > 0
    assert fleet.heartbeats_rejected == 0
    host = sched.hosts.get("h0")
    assert host is not None
    assert (host.heartbeat.egress_mbps_est or 0.0) > 0.0


# ----------------------------------------------------- gateway fan-out WS

async def _gw_client(gw):
    from aiohttp.test_utils import TestClient, TestServer
    client = TestClient(TestServer(gw.make_app()))
    await client.start_server()
    return client


async def _gw_with_source():
    from selkies_tpu.fleet.gateway import FleetGateway
    gw = FleetGateway(sweep_interval_s=3600.0)
    c = await _gw_client(gw)
    hb = Heartbeat(host_id="h0", url="http://127.0.0.1:9", ready=True)
    hb.devices.append(DeviceCapacity(id=0, hbm_limit_mb=8192.0,
                                     seat_slots=4))
    r = await c.post("/fleet/heartbeat", data=hb.to_json())
    assert r.status == 200
    r = await c.post("/fleet/place", json={
        "v": 1, "kind": "place", "sid": "desk",
        "width": 1920, "height": 1080, "codec": "h264"})
    assert r.status == 200
    return gw, c


async def test_gateway_broadcast_viewer_lifecycle_and_grace():
    """Satellite: reconnect-grace under broadcast fan-out — reconnect
    cancels the seat timer, last-viewer-close frees the rendition
    subscription after the grace."""
    gw, c = await _gw_with_source()
    gw.release_grace_s = 0.05
    gw.hub.grace_s = 0.05
    try:
        r = await c.get("/fleet/broadcast/ws?source=ghost")
        assert r.status == 404
        ws = await c.ws_connect("/fleet/broadcast/ws?source=desk&vid=v1")
        await asyncio.sleep(0.05)
        p = gw.scheduler.get("v1")
        assert p is not None and p.spec.is_relay and p.host_id == "h0"
        assert p.spec.budget_mb() == 0.0 and p.spec.pixels == 0
        reg = gw._registries["desk"]
        assert gw.hub.viewer_count("desk") == 1
        # three bad QoE verdicts: dwell-hysteresed switch, IDR resync
        for _ in range(3):
            await ws.send_str("qoe,10")
        await ws.send_str("g2g,48.5")
        await asyncio.sleep(0.1)
        st = reg.get("v1")
        assert st.rung == len(reg.ladder) - 1
        assert st.rung_switches == 1 and st.idr_resyncs == 1
        low = reg.ladder.rung(st.rung).name
        assert ("desk", low) in gw.hub.open_rungs()
        assert st.g2g_p99_ms() == 48.5
        info = await c.get("/fleet/broadcast/desk")
        body = await info.json()
        assert body["found"] and body["rung_switches"] == 1
        await ws.close()
        await asyncio.sleep(0.02)
        # inside the grace: seat survives; reconnect cancels the timer
        assert gw.scheduler.get("v1") is not None
        ws = await c.ws_connect("/fleet/broadcast/ws?source=desk&vid=v1")
        await asyncio.sleep(0.02)
        assert "v1" not in gw._release_timers
        assert gw.scheduler.get("v1") is not None
        await ws.close()
        await asyncio.sleep(0.2)
        # grace expired with nobody back: seat released, rendition
        # subscriptions freed, upstreams balanced
        assert gw.scheduler.get("v1") is None
        assert gw.hub.open_rungs() == []
        assert gw.hub.upstream_closes == gw.hub.upstream_opens
    finally:
        await c.close()


async def test_gateway_shutdown_cancels_broadcast_timers():
    """Satellite: gateway shutdown cancels pending grace timers and
    upstream pumps — nothing leaks past cleanup."""
    gw, c = await _gw_with_source()
    gw.release_grace_s = 30.0
    gw.hub.grace_s = 30.0
    closed = False
    try:
        ws = await c.ws_connect("/fleet/broadcast/ws?source=desk&vid=v1")
        await asyncio.sleep(0.05)
        await ws.close()
        await asyncio.sleep(0.02)
        assert gw.hub.pending_releases() == 1
        assert "v1" in gw._release_timers
        await c.close()       # app cleanup runs _stop_sweep
        closed = True
        assert gw.hub.pending_releases() == 0
        assert gw._release_timers == {}
        assert gw._upstream_tasks == {}
        assert gw._registries == {} and gw._viewer_sinks == {}
    finally:
        if not closed:
            await c.close()


async def test_gateway_broadcast_egress_budget_refusal():
    gw, c = await _gw_with_source()
    gw.scheduler.gateway_mbps_budget = 0.25   # below one viewer's cost
    try:
        r = await c.get("/fleet/broadcast/ws?source=desk&vid=v9",
                        headers={"Connection": "Upgrade",
                                 "Upgrade": "websocket",
                                 "Sec-WebSocket-Version": "13",
                                 "Sec-WebSocket-Key": "x3JJHMbDL1EzLkh9GBhXDw=="})
        assert r.status == 503
        # the refused spec must not linger in the queue
        assert all(s.sid != "v9" for s, _ in gw.scheduler.pending)
    finally:
        await c.close()


# ------------------------------------------------- ws_service viewer verbs

class _NullCapture:
    def is_capturing(self):
        return False

    def request_idr_frame(self):
        pass

    def stop_capture(self):
        pass

    def set_cursor_callback(self, cb):
        pass


def _make_ws_server(**fields):
    from selkies_tpu.input.backends import NullBackend
    from selkies_tpu.input.handler import InputHandler
    from selkies_tpu.server.core import CentralizedStreamServer
    from selkies_tpu.server.ws_service import WebSocketsService
    from selkies_tpu.settings import AppSettings
    s = AppSettings.parse([], {})
    for k, v in fields.items():
        s.set_server(k, v)
    svc = WebSocketsService(s, input_handler=InputHandler(
        backend=NullBackend()), capture_factory=lambda: _NullCapture())
    server = CentralizedStreamServer(s)
    server.register_service("websockets", svc)
    return server, svc


async def test_ws_broadcast_disabled_by_default(client_factory):
    server, svc = _make_ws_server()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str()
    await ws.receive_str()
    await ws.send_str("BROADCAST_VIEW")
    assert (await ws.receive_str()) == "BROADCAST_DISABLED"
    await ws.close()


async def test_ws_broadcast_view_and_qoe_routing(client_factory):
    server, svc = _make_ws_server(enable_broadcast=True)
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str()
    await ws.receive_str()
    await ws.send_str("BROADCAST_VIEW")
    assert (await ws.receive_str()) == "BROADCAST_RUNG,src"
    st = svc._bcast_state
    assert len(st["registry"]) == 1
    (sid, client), = st["clients"].items()
    assert client.qoe.rung == "src"
    # three bad verdicts land the hysteresed switch; the relay re-keys
    # onto the low rung's derived display and QoE carries the rung
    for _ in range(3):
        await ws.send_str("BROADCAST_QOE,15")
    await asyncio.sleep(0.2)
    vs = st["registry"].get(sid)
    low = st["ladder"].rung(len(st["ladder"]) - 1)
    assert vs.rung == len(st["ladder"]) - 1
    assert vs.idr_resyncs == 1
    assert client.display.endswith(f"@{low.name}")
    assert client.qoe.rung == low.name
    assert client.display in client.relays
    # rung attribution reaches the QoE snapshot (obs satellite)
    assert client.qoe.snapshot()["rung"] == low.name
    await ws.close()
    await asyncio.sleep(0.05)
    assert len(st["registry"]) == 0      # disconnect detaches


async def test_ws_broadcast_rung_query_pin(client_factory):
    # the gateway's rendition upstream dials ?rung=<name>: the client
    # is attached on that rung before its first START_VIDEO
    server, svc = _make_ws_server(enable_broadcast=True)
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets?rung=mid")
    await ws.receive_str()
    await ws.receive_str()
    assert (await ws.receive_str()) == "BROADCAST_RUNG,mid"
    st = svc._bcast_state
    (sid, client), = st["clients"].items()
    assert st["registry"].get(sid).rung == st["ladder"].index_of("mid")
    assert client.display.endswith("@mid")
    await ws.close()
