"""Clock-sync estimator (obs.clocksync) under injected timelines.

Every test constructs the 4-timestamp exchanges itself — no wall clock,
no sleeps. The ground truth is an explicit client↔server mapping
``client_of(server)``; the estimator only ever sees the exchange
tuples, and the assertions check what it recovered against the truth.
"""

import json

import pytest

from selkies_tpu.obs.clocksync import (MIN_FIT_SPAN_MS,
                                       ClockSyncEstimator)


def run_exchanges(cs, client_of, t_start=1000.0, n=20, spacing_ms=500.0,
                  wire_ms=2.0, server_turn_ms=0.1):
    """Feed n clean pings: client sends at server instant s, the server
    stamps s+wire and s+wire+turn, the reply lands wire later."""
    for i in range(n):
        s = t_start + i * spacing_ms
        cs.add_sample(client_of(s), s + wire_ms, s + wire_ms + server_turn_ms,
                      client_of(s + 2 * wire_ms + server_turn_ms))


def test_constant_offset_recovered():
    cs = ClockSyncEstimator()
    run_exchanges(cs, lambda s: s + 7_500.0)
    assert cs.synced
    # offset = server - client = -7500, symmetric wire => near-exact
    probe = 42_000.0
    assert cs.to_server_ms(probe + 7_500.0) == pytest.approx(probe, abs=0.1)
    assert cs.offset_at(probe) == pytest.approx(-7_500.0, abs=0.1)
    assert cs.drift_ppm == pytest.approx(0.0, abs=5.0)


def test_drift_recovered_and_extrapolated():
    drift = 50e-6      # client crystal runs 50 ppm fast

    def client_of(s):
        return (s - 1000.0) * (1 + drift) + 3_000.0

    cs = ClockSyncEstimator()
    run_exchanges(cs, client_of, n=40)
    # offset(client) slope == -drift/(1+drift) ~ -50 ppm
    assert cs.drift_ppm == pytest.approx(-50.0, abs=10.0)
    # extrapolate 10 s past the last sample: a slope-less estimator
    # would be ~0.5 ms off by now; the fit must stay tight
    s_future = 1000.0 + 40 * 500.0 + 10_000.0
    mapped = cs.to_server_ms(client_of(s_future))
    assert mapped == pytest.approx(s_future, abs=1.0)


def test_short_burst_never_invents_drift():
    """A connection-open burst of pings spans milliseconds; the fit must
    run slope-0 there instead of amplifying read jitter into phantom
    ppm (the failure mode that broke the bench margin)."""
    cs = ClockSyncEstimator()
    for i in range(8):
        s = 1000.0 + i * 0.01          # 10 us apart
        jitter = 0.001 * (-1) ** i
        cs.add_sample(s + 500.0 + jitter, s + 0.001, s + 0.002,
                      s + 500.0 + 0.003)
    assert cs.synced
    assert cs.drift_ppm == 0.0         # slope-0 below MIN_FIT_SPAN_MS
    assert 8 * 0.01 < MIN_FIT_SPAN_MS  # the premise of this test
    mapped = cs.to_server_ms(1000.0 + 60_000.0 + 500.0)
    assert mapped == pytest.approx(1000.0 + 60_000.0, abs=0.1)


def test_min_rtt_filter_rejects_congested_samples():
    """Congested exchanges carry large, asymmetric RTTs whose offsets
    are wrong by up to rtt/2; only near-min-RTT samples may vote."""
    cs = ClockSyncEstimator()
    run_exchanges(cs, lambda s: s + 100.0, n=10)
    clean = cs.offset_at(6_000.0)
    # now a burst of congested samples: 80 ms extra on the return path
    # only, which skews each sample's offset by -40 ms
    for i in range(10):
        s = 20_000.0 + i * 500.0
        cs.add_sample(s + 100.0, s + 2.0, s + 2.1, s + 100.0 + 84.1)
    skewed = cs.offset_at(26_000.0)
    assert skewed == pytest.approx(clean, abs=1.0), \
        "high-RTT samples must not drag the fit"
    assert cs.rtt_min_ms == pytest.approx(4.1, abs=0.2)


def test_clock_step_resets_window():
    """Suspend/resume: a credible-RTT sample violently off the fit is a
    step — history is discarded and the mapping re-converges on the new
    timebase instead of averaging two incompatible clocks."""
    cs = ClockSyncEstimator()
    run_exchanges(cs, lambda s: s + 1_000.0, n=10)
    assert cs.steps == 0
    jumped = lambda s: s + 1_000.0 + 30_000.0    # noqa: E731
    run_exchanges(cs, jumped, t_start=20_000.0, n=5)
    assert cs.steps == 1
    probe = 30_000.0
    assert cs.to_server_ms(jumped(probe)) == pytest.approx(probe, abs=0.5)


def test_small_residual_is_not_a_step():
    cs = ClockSyncEstimator()
    run_exchanges(cs, lambda s: s + 1_000.0, n=10)
    s = 20_000.0
    cs.add_sample(s + 1_000.0 + 5.0, s + 2.0, s + 2.1, s + 1_000.0 + 9.1)
    assert cs.steps == 0               # 5 ms residual < step_ms


def test_negative_rtt_rejected():
    cs = ClockSyncEstimator()
    assert cs.add_sample(100.0, 50.0, 60.0, 101.0) is None  # rtt < 0
    assert cs.add_sample(100.0, 50.0, 50.1, 99.0) is None   # t3 < t0
    assert cs.rejected == 2
    assert not cs.synced
    assert cs.to_server_ms(123.0) is None


def test_error_bound_and_quality_export():
    cs = ClockSyncEstimator()
    assert cs.error_bound_ms() is None
    run_exchanges(cs, lambda s: s + 250.0, wire_ms=3.0)
    b = cs.error_bound_ms()
    # bound >= rtt_min/2: 6 ms symmetric exchange -> ~3 ms
    assert b == pytest.approx(3.0, abs=0.01)
    q = cs.quality()
    assert q["synced"] is True and q["samples"] == 20
    assert q["rejected"] == 0 and q["steps"] == 0
    json.loads(json.dumps(q))          # /api/sessions must round-trip


def test_window_is_bounded():
    cs = ClockSyncEstimator(window=16)
    run_exchanges(cs, lambda s: s + 10.0, n=100)
    assert cs.samples_total == 100
    assert cs.quality()["samples"] == 16
