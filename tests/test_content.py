"""Content classification (ROADMAP 4, engine/content.py): damage-signal
EWMAs -> class -> rate-control profile, plus the ladder and /api/sessions
integrations. Stdlib-only (no jax) like the other control-plane suites."""

import numpy as np
import pytest

from selkies_tpu.engine.content import (CONTENT_CLASSES,
                                        CONTENT_LADDER_SKIPS,
                                        CONTENT_PROFILES,
                                        ContentClassifier)


def drive(ctl, fractions):
    last = None
    for f in fractions:
        last = ctl.update(f)
    return last


# ------------------------------------------------------------- classifier
def test_classifier_idle_and_typing_stay_static():
    assert drive(ContentClassifier(), [0.0] * 200) == "static"
    typing = [1 / 16 if t % 6 == 0 else 0.0 for t in range(200)]
    assert drive(ContentClassifier(), typing) == "static"


def test_classifier_scroll_video_gaming():
    assert drive(ContentClassifier(), [0.4] * 200) == "scroll"
    assert drive(ContentClassifier(), [1.0] * 200) == "video"
    # volatile full-raster damage reads as gaming
    rng = np.random.default_rng(3)
    chaotic = [float(rng.choice([0.4, 1.0]))
               for _ in range(400)]
    assert drive(ContentClassifier(), chaotic) == "gaming"


def test_classifier_dwell_hysteresis():
    ctl = ContentClassifier(dwell=30)
    drive(ctl, [0.4] * 200)
    assert ctl.current == "scroll"
    # a brief burst must NOT flip the class before the dwell
    for _ in range(29):
        ctl.update(1.0)
    assert ctl.current == "scroll"
    drive(ctl, [1.0] * 200)
    assert ctl.current == "video"
    assert ctl.transitions >= 2


def test_classifier_recovers_to_static():
    ctl = ContentClassifier()
    drive(ctl, [1.0] * 200)
    assert ctl.current == "video"
    drive(ctl, [0.0] * 400)
    assert ctl.current == "static"


def test_profiles_and_snapshot():
    assert set(CONTENT_PROFILES) == set(CONTENT_CLASSES)
    assert CONTENT_PROFILES["static"].qp_bias < 0       # text sharpens
    assert CONTENT_PROFILES["gaming"].qp_bias > 0
    assert not CONTENT_PROFILES["video"].partial_encode
    assert CONTENT_PROFILES["scroll"].band_floor_rows > 1
    ctl = ContentClassifier()
    drive(ctl, [0.4] * 200)
    snap = ctl.snapshot()
    assert snap["class"] == "scroll"
    assert snap["profile"]["band_floor_rows"] == \
        CONTENT_PROFILES["scroll"].band_floor_rows
    assert 0.3 < snap["area_ewma"] < 0.5
    assert ctl.class_index == CONTENT_CLASSES.index("scroll")


def test_gauge_class_mapping_pinned_against_qoe():
    # obs/qoe keeps a literal copy (the obs package is stdlib-only by
    # contract); drift between the two would silently re-number the
    # selkies_session_content_class gauge
    from selkies_tpu.obs.qoe import _CONTENT_CLASSES
    assert _CONTENT_CLASSES == CONTENT_CLASSES


# ------------------------------------------------------------------ ladder
def _mk_ladder(**kw):
    from selkies_tpu.obs import health as _health
    from selkies_tpu.resilience.ladder import DegradationLadder
    return DegradationLadder(recorder=_health.FlightRecorder(16),
                             down_after_s=1.0, hold_s=0.0,
                             ok_window_s=5.0, clock=lambda: 0.0, **kw)


def test_ladder_content_profile_skips_pointless_rungs():
    lad = _mk_ladder()
    lad.set_content_profile("static", CONTENT_LADDER_SKIPS["static"])
    bad = {"qoe": "failed"}
    lad.observe(bad, now=0.0)
    lad.observe(bad, now=2.0)          # past down_after: -> pipeline
    assert lad.level == 1
    lad.observe(bad, now=4.0)          # next rung is fps -> SKIPPED
    assert lad.level == 3              # lands on quality
    snap = lad.snapshot()
    assert snap["content_class"] == "static"
    assert snap["content_skips"] == ["fps"]
    # recorded with the skipped rung named
    kinds = [e["kind"] for e in lad.recorder.snapshot()]
    assert "ladder_content_profile" in kinds
    steps = [e for e in lad.recorder.snapshot()
             if e["kind"] == "degradation_step"]
    assert steps[-1]["step"] == "quality"
    assert steps[-1].get("skipped") == ["fps"]
    assert any("content-skip:static" in r for r in steps[-1]["reasons"])


def test_ladder_content_profile_clear_restores_stock_walk():
    lad = _mk_ladder()
    lad.set_content_profile("static", ("fps",))
    lad.set_content_profile(None)
    bad = {"qoe": "failed"}
    lad.observe(bad, now=0.0)
    lad.observe(bad, now=2.0)
    lad.observe(bad, now=4.0)
    assert lad.level == 2              # stock: pipeline then fps
    assert lad.snapshot()["content_class"] is None


def test_ladder_all_remaining_rungs_skipped_holds():
    lad = _mk_ladder()
    lad.set_content_profile(
        "weird", ("pipeline", "fps", "quality", "downscale"))
    bad = {"qoe": "failed"}
    lad.observe(bad, now=0.0)
    lad.observe(bad, now=2.0)
    assert lad.level == 0              # nothing sheddable: hold, no crash


# ----------------------------------------------------------- qoe snapshot
def test_session_snapshot_carries_content_block():
    from selkies_tpu.obs.qoe import QoERegistry
    reg = QoERegistry()
    st = reg.register("ws", "primary", 1)
    st.content_provider = lambda: {
        "class": "scroll", "dirty_fraction": 0.31,
        "area_ewma": 0.3}
    doc = st.snapshot()
    assert doc["content_class"] == "scroll"
    assert doc["dirty_fraction"] == 0.31
    assert "content" not in doc                    # verbose-only detail
    vdoc = st.snapshot(verbose=True)
    assert vdoc["content"]["area_ewma"] == 0.3
    # absent/broken provider: no content keys, no crash
    st2 = reg.register("ws", "primary", 2)
    assert "content_class" not in st2.snapshot()
    st2.content_provider = lambda: (_ for _ in ()).throw(RuntimeError())
    assert "content_class" not in st2.snapshot()


# --------------------------------------------------- capture-loop wiring
def test_capture_content_tick_applies_profile():
    from selkies_tpu.engine.capture import ScreenCapture
    from selkies_tpu.engine.types import CaptureSettings

    class FakeSession:
        def __init__(self):
            self.dirty_fraction = 1.0
            self.qp = 28
            self.profiles = []
            self.n_rows = 16

        def set_content_profile(self, p):
            self.profiles.append(p)

        def set_qp(self, qp, paint=None):
            self.qp = qp

    cap = ScreenCapture(source_kind="synthetic")
    ctl = ContentClassifier(dwell=5)
    cap._content = ctl          # as the capture loop: ctl IS _content
    sess = FakeSession()
    s = CaptureSettings(output_mode="h264", video_crf=28, use_cbr=False)
    for _ in range(60):
        cap._content_tick(ctl, sess, s)
    assert ctl.current == "video"
    assert sess.profiles and sess.profiles[-1].name == "video"
    assert sess.qp == 28 + CONTENT_PROFILES["video"].qp_bias
    # the bias is RELATIVE and rebases on external writes: a
    # client-chosen quality level set between class changes becomes the
    # new bias-free base (the write overwrote the embedded bias), so
    # the next transition applies the new class's bias against IT —
    # never a reset to video_crf, never a stale-bias double-count
    sess.qp = 20                       # client raised quality meanwhile
    sess.dirty_fraction = 0.0
    for _ in range(120):
        cap._content_tick(ctl, sess, s)
    assert ctl.current == "static"
    assert sess.qp == 20 + CONTENT_PROFILES["static"].qp_bias
    # content_state surfaces the classifier + live dirty fraction
    cap._content = ctl
    cap._session = sess
    state = cap.content_state()
    assert state["class"] == "static"
    assert state["dirty_fraction"] == 0.0


def test_set_content_profile_floors_band_bucket():
    pytest.importorskip("jax")
    from selkies_tpu.engine.h264_encoder import H264EncoderSession
    from selkies_tpu.engine.types import CaptureSettings
    sess = H264EncoderSession(CaptureSettings(
        capture_width=64, capture_height=64, stripe_height=32,
        output_mode="h264", h264_partial_encode=True,
        h264_motion_vrange=0))
    sess.set_content_profile(CONTENT_PROFILES["scroll"])
    assert sess._band_floor == CONTENT_PROFILES["scroll"].band_floor_rows
    # "full-frame" profiles floor at the whole frame, keeping the probe
    # (and the dirty signal) alive instead of leaving the partial path
    sess.set_content_profile(CONTENT_PROFILES["video"])
    assert sess._band_floor == sess.n_rows
