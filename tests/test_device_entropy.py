"""Device entropy coder vs the independent numpy implementation.

The strongest test in the codec suite: two implementations written against
the spec from different angles (slot-event reframing on device vs
event-list construction in numpy) must produce byte-identical scans.
"""

import io

import numpy as np
import pytest
from PIL import Image

from selkies_tpu.codecs import jpeg as J
from selkies_tpu.ops import bitpack as B
from selkies_tpu.ops.jpeg_entropy import finalize_scan_bytes
from selkies_tpu.ops.jpeg_pipeline import jitted_jpeg_encode, jpeg_forward_420


def _img(h, w, seed=0, mode="mixed"):
    rng = np.random.default_rng(seed)
    if mode == "noise":
        return rng.integers(0, 255, (h, w, 3)).astype(np.uint8)
    if mode == "flat":
        return np.full((h, w, 3), 130, dtype=np.uint8)
    yy, xx = np.mgrid[0:h, 0:w]
    img = np.stack([(xx * 255 // w), (yy * 255 // h), (xx + yy) % 256],
                   -1).astype(np.uint8)
    for _ in range(5):
        y0, x0 = rng.integers(0, h - 16), rng.integers(0, w - 16)
        img[y0:y0 + 12, x0:x0 + 14] = rng.integers(0, 255, 3)
    return img


def test_pack_slot_events_simple():
    import jax.numpy as jnp
    # two rows; events: (5 bits 0b10110), (3 bits 0b011) | (8 bits 0xA5)
    payload = jnp.asarray([[0b10110, 0b011], [0xA5, 0]], dtype=jnp.uint32)
    nbits = jnp.asarray([[5, 3], [8, 0]], dtype=jnp.int32)
    out = B.pack_slot_events(payload, nbits, e_cap=8, w_cap=4)
    assert int(out.total_bits) == 16
    assert int(out.n_events) == 3
    assert not bool(out.overflow)
    by = B.words_to_bytes(np.asarray(out.words), int(out.total_bits),
                          pad_ones=False)
    # 10110 011 10100101 -> 0xB3 0xA5
    assert by == bytes([0b10110011, 0xA5])


def test_pack_spanning_word_boundary():
    import jax.numpy as jnp
    # 20 events x 3 bits = 60 bits -> events straddle the 32-bit boundary
    payload = jnp.asarray([[0b101] * 20], dtype=jnp.uint32)
    nbits = jnp.asarray([[3] * 20], dtype=jnp.int32)
    out = B.pack_slot_events(payload, nbits, e_cap=32, w_cap=4)
    by = B.words_to_bytes(np.asarray(out.words), int(out.total_bits),
                          pad_ones=False)
    expect = int("101" * 20, 2) << (64 - 60)
    assert by == expect.to_bytes(8, "big")


def test_pack_overflow_flags():
    import jax.numpy as jnp
    payload = jnp.ones((4, 4), dtype=jnp.uint32)
    nbits = jnp.full((4, 4), 20, dtype=jnp.int32)
    out = B.pack_slot_events(payload, nbits, e_cap=8, w_cap=64)
    assert bool(out.overflow)  # 16 events > e_cap 8
    out = B.pack_slot_events(payload, nbits, e_cap=64, w_cap=2)
    assert bool(out.overflow)  # 320 bits > 64


@pytest.mark.parametrize("mode,quality", [
    ("mixed", 80), ("mixed", 95), ("noise", 85), ("flat", 75),
])
def test_device_scan_matches_numpy(mode, quality):
    import jax.numpy as jnp
    h, w = 64, 96
    img = _img(h, w, seed=3, mode=mode)
    qy = J.scale_qtable(J.STD_LUMA_QUANT, quality)
    qc = J.scale_qtable(J.STD_CHROMA_QUANT, quality)

    # independent numpy path
    y, cb, cr = jpeg_forward_420(jnp.asarray(img), jnp.asarray(qy),
                                 jnp.asarray(qc))
    ref_scan = J.encode_scan(np.asarray(y), np.asarray(cb), np.asarray(cr),
                             h // 8, w // 8, "420")

    # device path; e_cap must cover total slots (1.5*h*w for 4:2:0)
    enc = jitted_jpeg_encode("420", e_cap=2 * h * w, w_cap=h * w // 2)
    out = enc(jnp.asarray(img), jnp.asarray(qy), jnp.asarray(qc))
    assert not bool(out.overflow)
    dev_scan = finalize_scan_bytes(np.asarray(out.words), int(out.total_bits))

    assert dev_scan == ref_scan


def test_device_scan_decodes_in_pil():
    import jax.numpy as jnp
    h, w = 48, 64
    img = _img(h, w, seed=9)
    qy = J.scale_qtable(J.STD_LUMA_QUANT, 85)
    qc = J.scale_qtable(J.STD_CHROMA_QUANT, 85)
    enc = jitted_jpeg_encode("420", e_cap=h * w, w_cap=h * w // 8)
    out = enc(jnp.asarray(img), jnp.asarray(qy), jnp.asarray(qc))
    scan = finalize_scan_bytes(np.asarray(out.words), int(out.total_bits))
    jfif = J.assemble_jfif(h, w, scan, qy, qc, "420")
    dec = Image.open(io.BytesIO(jfif))
    dec.load()
    assert dec.size == (w, h)


def test_scatter_packer_matches_gather_packer():
    """The two pack formulations (argsort+per-word-gather vs cumsum+
    scatter-or) must agree bit-for-bit on adversarial event sets."""
    rng = np.random.default_rng(7)
    for trial in range(8):
        m = int(rng.integers(1, 5))
        s = int(rng.integers(1, 400))
        nbits = rng.integers(0, 28, (m, s)).astype(np.int32)
        nbits[rng.random((m, s)) < 0.5] = 0          # sparse
        if trial == 0:
            nbits[:] = 1                              # all 1-bit events
        if trial == 1:
            nbits[:] = 0                              # empty stream
        payload = rng.integers(0, 1 << 28, (m, s)).astype(np.uint32)
        payload &= (((1 << np.maximum(nbits, 1)) - 1)
            .astype(np.uint32))
        e_cap = int(nbits.astype(bool).sum()) + 4
        w_cap = int(nbits.sum()) // 32 + 4
        a = B.pack_slot_events(payload, nbits,
                               e_cap=e_cap, w_cap=w_cap,
                               max_events_per_word=33)
        b = B.pack_slot_events_scatter(payload, nbits,
                                       e_cap=e_cap, w_cap=w_cap)
        assert int(a.total_bits) == int(b.total_bits)
        assert int(a.n_events) == int(b.n_events)
        assert bool(a.overflow) == bool(b.overflow)
        assert np.array_equal(np.asarray(a.words), np.asarray(b.words)), \
            f"trial {trial}: word mismatch"
