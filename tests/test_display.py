"""Display management tests: CVT-RB modeline math against known-good
``cvt -r`` outputs (pure functions — no X server needed)."""

import asyncio

from selkies_tpu.display import DisplayManager, cvt_rb_modeline


def test_cvt_rb_1080p60_matches_cvt():
    # $ cvt -r 1920 1080 60
    # Modeline "1920x1080R" 138.50 1920 1968 2000 2080 1080 1083 1088 1111
    m = cvt_rb_modeline(1920, 1080, 60)
    assert (m.clock_mhz, m.width, m.hsync_start, m.hsync_end, m.htotal) == \
        (138.50, 1920, 1968, 2000, 2080)
    assert (m.height, m.vsync_start, m.vsync_end, m.vtotal) == \
        (1080, 1083, 1088, 1111)


def test_cvt_rb_1440p60_matches_cvt():
    # Modeline "2560x1440R" 241.50 2560 2608 2640 2720 1440 1443 1448 1481
    m = cvt_rb_modeline(2560, 1440, 60)
    assert m.clock_mhz == 241.50
    assert (m.htotal, m.vtotal) == (2720, 1481)


def test_cvt_rb_odd_width_rounded_even():
    m = cvt_rb_modeline(1365, 768, 60)
    assert m.width == 1364


def test_cvt_rb_4k30():
    m = cvt_rb_modeline(3840, 2160, 30)
    assert m.htotal == 4000
    assert m.vtotal > 2160
    # pixel clock sanity: htotal*vtotal*30 within one step of clock
    assert abs(m.clock_mhz - m.htotal * m.vtotal * 30 / 1e6) <= 0.25


def test_xrandr_args_shape():
    m = cvt_rb_modeline(1280, 720, 60)
    args = m.xrandr_args()
    assert args[0] == "1280x720_60.00"
    assert args[-2:] == ["+hsync", "-vsync"]
    assert len(args) == 12


def test_manager_headless_is_inert():
    dm = DisplayManager(":99")
    # no xrandr or no display -> available() False on this CI image is
    # fine either way; the contract is just "no crash"
    assert dm.available() in (True, False)


# ------------------------------------------------- extended desktop


def test_compute_dual_layout_positions():
    from selkies_tpu.display import compute_dual_layout
    assert compute_dual_layout(1920, 1080, 1280, 720, "right") == \
        (3200, 1080, (0, 0), (1920, 0))
    assert compute_dual_layout(1920, 1080, 1280, 720, "left") == \
        (3200, 1080, (1280, 0), (0, 0))
    assert compute_dual_layout(1920, 1080, 1280, 720, "below") == \
        (1920, 1800, (0, 0), (0, 1080))
    assert compute_dual_layout(1920, 1080, 1280, 720, "above") == \
        (1920, 1800, (0, 720), (0, 0))


async def test_extended_desktop_xrandr_commands():
    """ExtendedDesktop must grow the framebuffer to the union and carve
    one selkies-N logical monitor per display, first bound to the real
    output (reference replace_selkies_monitors)."""
    from selkies_tpu.display import DisplayManager, ExtendedDesktop

    calls = []

    class FakeDM(DisplayManager):
        def available(self):
            return True

        async def _run(self, *args):
            calls.append(args)
            if "--query" in args:
                return 0, "HDMI-1 connected 1920x1080+0+0\n"
            return 0, ""

    ext = ExtendedDesktop(FakeDM(":77"))
    ok = await ext.apply([(0, 0, 1920, 1080), (1920, 0, 1280, 720)])
    assert ok
    flat = ["|".join(c) for c in calls]
    assert any("--newmode" in f and "3200x1080" in f for f in flat), flat
    mon = [c for c in calls if "--setmonitor" in c]
    assert len(mon) == 2
    assert mon[0][2] == "selkies-0" and mon[0][4] == "HDMI-1"
    assert mon[1][2] == "selkies-1" and mon[1][4] == "none"
    assert "+1920+0" in mon[1][3]
    # re-apply drops the stale monitors first
    ok = await ext.apply([(0, 0, 800, 600)])
    assert ok
    dels = [c for c in calls if "--delmonitor" in c]
    assert len(dels) == 2


async def test_two_displays_stream_independently(client_factory):
    """VERDICT round-2 item 7 done bar: two clients on two displays of
    one seat stream independently (per-display captures + routing)."""
    from aiohttp import WSMsgType

    from selkies_tpu.server.core import CentralizedStreamServer
    from selkies_tpu.server.ws_service import WebSocketsService
    from selkies_tpu.settings import AppSettings
    from tests.test_server import FakeCapture

    s = AppSettings.parse([], {})
    s.set_server("max_displays", 2)
    fakes = []

    def factory():
        f = FakeCapture()
        fakes.append(f)
        return f

    svc = WebSocketsService(s, capture_factory=factory,
                            display_manager=None)
    svc.display_manager = None          # headless: offsets only
    server = CentralizedStreamServer(s)
    server.register_service("websockets", svc)
    c = await client_factory(server)

    async def open_display(q):
        ws = await c.ws_connect(f"/api/websockets?display={q}")
        while True:
            msg = await ws.receive(timeout=2)
            if msg.type != WSMsgType.TEXT:
                break
            if msg.data.startswith("server_settings"):
                break
        await ws.send_str("START_VIDEO")
        return ws

    ws1 = await open_display(":0")
    await asyncio.sleep(0.6)            # reconnect debounce
    ws2 = await open_display("display2")
    await asyncio.sleep(0.3)

    assert set(svc.display_geometry) == {":0", "display2"}
    assert svc.display_offsets["display2"][0] > 0      # extended right
    assert len(fakes) == 2
    dids = sorted(f._settings.display_id for f in fakes)
    assert dids == [":0", "display2"]
    # offsets reach the capture settings (sub-rect of the framebuffer)
    d2 = next(f for f in fakes if f._settings.display_id == "display2")
    assert (d2._settings.capture_x, d2._settings.capture_y) == \
        svc.display_offsets["display2"]

    async def collect(ws):
        got = []
        for _ in range(6):
            try:
                msg = await ws.receive(timeout=1.5)
            except (asyncio.TimeoutError, TimeoutError):
                break
            if msg.type == WSMsgType.BINARY:
                got.append(msg.data)
        return got

    for f in fakes:
        f.emit(2)
    b1, b2 = await collect(ws1), await collect(ws2)
    assert b1 and b2, "both displays must stream"

    # resizing the PRIMARY must retarget display2's capture to its moved
    # origin (its sub-rect shifts right when the primary grows)
    await ws1.send_str("r,1280x800")
    await asyncio.sleep(0.3)
    assert svc.display_geometry[":0"] == (1280, 800)
    assert svc.display_offsets["display2"] == (1280, 0)
    await ws1.close()
    await ws2.close()
