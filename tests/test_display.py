"""Display management tests: CVT-RB modeline math against known-good
``cvt -r`` outputs (pure functions — no X server needed)."""

import asyncio
import os
import time

from selkies_tpu.display import DisplayManager, cvt_rb_modeline


def test_cvt_rb_1080p60_matches_cvt():
    # $ cvt -r 1920 1080 60
    # Modeline "1920x1080R" 138.50 1920 1968 2000 2080 1080 1083 1088 1111
    m = cvt_rb_modeline(1920, 1080, 60)
    assert (m.clock_mhz, m.width, m.hsync_start, m.hsync_end, m.htotal) == \
        (138.50, 1920, 1968, 2000, 2080)
    assert (m.height, m.vsync_start, m.vsync_end, m.vtotal) == \
        (1080, 1083, 1088, 1111)


def test_cvt_rb_1440p60_matches_cvt():
    # Modeline "2560x1440R" 241.50 2560 2608 2640 2720 1440 1443 1448 1481
    m = cvt_rb_modeline(2560, 1440, 60)
    assert m.clock_mhz == 241.50
    assert (m.htotal, m.vtotal) == (2720, 1481)


def test_cvt_rb_odd_width_rounded_even():
    m = cvt_rb_modeline(1365, 768, 60)
    assert m.width == 1364


def test_cvt_rb_4k30():
    m = cvt_rb_modeline(3840, 2160, 30)
    assert m.htotal == 4000
    assert m.vtotal > 2160
    # pixel clock sanity: htotal*vtotal*30 within one step of clock
    assert abs(m.clock_mhz - m.htotal * m.vtotal * 30 / 1e6) <= 0.25


def test_xrandr_args_shape():
    m = cvt_rb_modeline(1280, 720, 60)
    args = m.xrandr_args()
    assert args[0] == "1280x720_60.00"
    assert args[-2:] == ["+hsync", "-vsync"]
    assert len(args) == 12


def test_manager_headless_is_inert():
    dm = DisplayManager(":99")
    # no xrandr or no display -> available() False on this CI image is
    # fine either way; the contract is just "no crash"
    assert dm.available() in (True, False)


# ------------------------------------------------- extended desktop


def test_compute_dual_layout_positions():
    from selkies_tpu.display import compute_dual_layout
    assert compute_dual_layout(1920, 1080, 1280, 720, "right") == \
        (3200, 1080, (0, 0), (1920, 0))
    assert compute_dual_layout(1920, 1080, 1280, 720, "left") == \
        (3200, 1080, (1280, 0), (0, 0))
    assert compute_dual_layout(1920, 1080, 1280, 720, "below") == \
        (1920, 1800, (0, 0), (0, 1080))
    assert compute_dual_layout(1920, 1080, 1280, 720, "above") == \
        (1920, 1800, (0, 720), (0, 0))


async def test_extended_desktop_xrandr_commands():
    """ExtendedDesktop must grow the framebuffer to the union and carve
    one selkies-N logical monitor per display, first bound to the real
    output (reference replace_selkies_monitors)."""
    from selkies_tpu.display import DisplayManager, ExtendedDesktop

    calls = []

    class FakeDM(DisplayManager):
        def available(self):
            return True

        async def _run(self, *args):
            calls.append(args)
            if "--query" in args:
                return 0, "HDMI-1 connected 1920x1080+0+0\n"
            return 0, ""

    ext = ExtendedDesktop(FakeDM(":77"))
    ok = await ext.apply([(0, 0, 1920, 1080), (1920, 0, 1280, 720)])
    assert ok
    flat = ["|".join(c) for c in calls]
    assert any("--newmode" in f and "3200x1080" in f for f in flat), flat
    mon = [c for c in calls if "--setmonitor" in c]
    assert len(mon) == 2
    assert mon[0][2] == "selkies-0" and mon[0][4] == "HDMI-1"
    assert mon[1][2] == "selkies-1" and mon[1][4] == "none"
    assert "+1920+0" in mon[1][3]
    # re-apply drops the stale monitors first
    ok = await ext.apply([(0, 0, 800, 600)])
    assert ok
    dels = [c for c in calls if "--delmonitor" in c]
    assert len(dels) == 2


async def test_two_displays_stream_independently(client_factory):
    """VERDICT round-2 item 7 done bar: two clients on two displays of
    one seat stream independently (per-display captures + routing)."""
    from aiohttp import WSMsgType

    from selkies_tpu.server.core import CentralizedStreamServer
    from selkies_tpu.server.ws_service import WebSocketsService
    from selkies_tpu.settings import AppSettings
    from tests.test_server import FakeCapture

    s = AppSettings.parse([], {})
    s.set_server("max_displays", 2)
    fakes = []

    def factory():
        f = FakeCapture()
        fakes.append(f)
        return f

    svc = WebSocketsService(s, capture_factory=factory,
                            display_manager=None)
    svc.display_manager = None          # headless: offsets only
    server = CentralizedStreamServer(s)
    server.register_service("websockets", svc)
    c = await client_factory(server)

    async def open_display(q):
        ws = await c.ws_connect(f"/api/websockets?display={q}")
        while True:
            msg = await ws.receive(timeout=2)
            if msg.type != WSMsgType.TEXT:
                break
            if msg.data.startswith("server_settings"):
                break
        await ws.send_str("START_VIDEO")
        return ws

    ws1 = await open_display(":0")
    await asyncio.sleep(0.6)            # reconnect debounce
    ws2 = await open_display("display2")
    await asyncio.sleep(0.3)

    assert set(svc.display_geometry) == {":0", "display2"}
    assert svc.display_offsets["display2"][0] > 0      # extended right
    assert len(fakes) == 2
    dids = sorted(f._settings.display_id for f in fakes)
    assert dids == [":0", "display2"]
    # offsets reach the capture settings (sub-rect of the framebuffer)
    d2 = next(f for f in fakes if f._settings.display_id == "display2")
    assert (d2._settings.capture_x, d2._settings.capture_y) == \
        svc.display_offsets["display2"]

    async def collect(ws):
        got = []
        for _ in range(6):
            try:
                msg = await ws.receive(timeout=1.5)
            except (asyncio.TimeoutError, TimeoutError):
                break
            if msg.type == WSMsgType.BINARY:
                got.append(msg.data)
        return got

    for f in fakes:
        f.emit(2)
    b1, b2 = await collect(ws1), await collect(ws2)
    assert b1 and b2, "both displays must stream"

    # resizing the PRIMARY must retarget display2's capture to its moved
    # origin (its sub-rect shifts right when the primary grows)
    await ws1.send_str("r,1280x800")
    await asyncio.sleep(0.3)
    assert svc.display_geometry[":0"] == (1280, 800)
    assert svc.display_offsets["display2"] == (1280, 0)
    await ws1.close()
    await ws2.close()


# ----------------------------------------------------- WM / DE chain
def _script(bin_dir, name, body):
    p = bin_dir / name
    p.write_text("#!/bin/sh\n" + body)
    p.chmod(0o755)
    return p


async def test_wm_detection_via_ewmh(tmp_path, monkeypatch):
    """EWMH detection: root _NET_SUPPORTING_WM_CHECK -> check window's
    _NET_WM_NAME (reference display_utils.py WM detect)."""
    from selkies_tpu.display import DisplayManager
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    _script(bin_dir, "xprop", """
case "$*" in
  *_NET_SUPPORTING_WM_CHECK*) echo '_NET_SUPPORTING_WM_CHECK(WINDOW): window id # 0x60000a' ;;
  *_NET_WM_NAME*) echo '_NET_WM_NAME(UTF8_STRING) = "Xfwm4"' ;;
esac
""")
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    dm = DisplayManager(":77")
    assert await dm.detect_window_manager() == "Xfwm4"
    # cached: a second call must not re-probe (remove the script)
    (bin_dir / "xprop").unlink()
    assert await dm.detect_window_manager() == "Xfwm4"


async def test_dpi_chain_hits_xfconf_for_xfce(tmp_path, monkeypatch):
    """set_dpi applies xrdb AND the matching DE tool: under Xfwm4 the
    xfconf xsettings property is written; gsettings is NOT called
    (reference display_utils.py:1391 DPI chain)."""
    from selkies_tpu.display import DisplayManager
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    log = tmp_path / "calls.log"
    _script(bin_dir, "xprop", """
case "$*" in
  *_NET_SUPPORTING_WM_CHECK*) echo 'window id # 0x1' ;;
  *_NET_WM_NAME*) echo '= "Xfwm4"' ;;
esac
""")
    _script(bin_dir, "xrdb", f"cat >> {log}.xrdb\n")
    _script(bin_dir, "xfconf-query", f'echo "$@" >> {log}.xfconf\n')
    _script(bin_dir, "gsettings", f'echo "$@" >> {log}.gsettings\n')
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    dm = DisplayManager(":77")
    await dm.set_dpi(144)
    assert "Xft.dpi: 144" in (tmp_path / "calls.log.xrdb").read_text()
    xf = (tmp_path / "calls.log.xfconf").read_text()
    assert "/Xft/DPI" in xf and "-s 144" in xf
    assert not (tmp_path / "calls.log.gsettings").exists()
    await dm.set_cursor_size(48)
    assert "/Gtk/CursorThemeSize" in \
        (tmp_path / "calls.log.xfconf").read_text()


async def test_wm_swap_spawns_replacement(tmp_path, monkeypatch):
    from selkies_tpu.display import DisplayManager
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    log = tmp_path / "wm.log"
    # the fake WM must outlive the swap grace period: a WM that exits
    # immediately now counts as a failed swap
    _script(bin_dir, "openbox", f'echo "$@" > {log}\nsleep 5\n')
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    dm = DisplayManager(":77")
    dm.wm_grace_s = 0.2
    dm._wm_name = "Xfwm4"
    assert await dm.swap_window_manager("openbox")
    deadline = time.time() + 5
    while time.time() < deadline and not log.exists():
        await asyncio.sleep(0.05)
    assert "--replace" in log.read_text()
    assert dm._wm_name is None           # re-detect after swap
    assert not await dm.swap_window_manager("missing-wm")


async def test_wm_swap_no_replace_for_unknown_wm(tmp_path, monkeypatch):
    """--replace is only passed to WMs on the allowlist; i3 and friends
    treat it as an unknown flag and die."""
    from selkies_tpu.display import DisplayManager
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    log = tmp_path / "wm.log"
    _script(bin_dir, "i3", f'echo "args:$@" > {log}\nsleep 5\n')
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    dm = DisplayManager(":77")
    dm.wm_grace_s = 0.2
    assert await dm.swap_window_manager("i3")
    deadline = time.time() + 5
    while time.time() < deadline and not log.exists():
        await asyncio.sleep(0.05)
    assert "--replace" not in log.read_text()


async def test_wm_swap_fluxbox_single_dash_replace(tmp_path, monkeypatch):
    """fluxbox spells the takeover flag -replace (single dash)."""
    from selkies_tpu.display import DisplayManager
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    log = tmp_path / "wm.log"
    _script(bin_dir, "fluxbox", f'echo "args:$@" > {log}\nsleep 5\n')
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    dm = DisplayManager(":77")
    dm.wm_grace_s = 0.2
    assert await dm.swap_window_manager("fluxbox")
    deadline = time.time() + 5
    while time.time() < deadline and not log.exists():
        await asyncio.sleep(0.05)
    text = log.read_text()
    assert "-replace" in text and "--replace" not in text


async def test_wm_swap_detects_instant_death(tmp_path, monkeypatch):
    """A WM that exits within the grace period is a failed swap, and
    the cached WM name is kept (nothing actually changed)."""
    from selkies_tpu.display import DisplayManager
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    _script(bin_dir, "openbox", "exit 1\n")
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    dm = DisplayManager(":77")
    dm.wm_grace_s = 0.2
    dm._wm_name = "Xfwm4"
    assert not await dm.swap_window_manager("openbox")
    assert dm._wm_name == "Xfwm4"
