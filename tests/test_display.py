"""Display management tests: CVT-RB modeline math against known-good
``cvt -r`` outputs (pure functions — no X server needed)."""

from selkies_tpu.display import DisplayManager, cvt_rb_modeline


def test_cvt_rb_1080p60_matches_cvt():
    # $ cvt -r 1920 1080 60
    # Modeline "1920x1080R" 138.50 1920 1968 2000 2080 1080 1083 1088 1111
    m = cvt_rb_modeline(1920, 1080, 60)
    assert (m.clock_mhz, m.width, m.hsync_start, m.hsync_end, m.htotal) == \
        (138.50, 1920, 1968, 2000, 2080)
    assert (m.height, m.vsync_start, m.vsync_end, m.vtotal) == \
        (1080, 1083, 1088, 1111)


def test_cvt_rb_1440p60_matches_cvt():
    # Modeline "2560x1440R" 241.50 2560 2608 2640 2720 1440 1443 1448 1481
    m = cvt_rb_modeline(2560, 1440, 60)
    assert m.clock_mhz == 241.50
    assert (m.htotal, m.vtotal) == (2720, 1481)


def test_cvt_rb_odd_width_rounded_even():
    m = cvt_rb_modeline(1365, 768, 60)
    assert m.width == 1364


def test_cvt_rb_4k30():
    m = cvt_rb_modeline(3840, 2160, 30)
    assert m.htotal == 4000
    assert m.vtotal > 2160
    # pixel clock sanity: htotal*vtotal*30 within one step of clock
    assert abs(m.clock_mhz - m.htotal * m.vtotal * 30 / 1e6) <= 0.25


def test_xrandr_args_shape():
    m = cvt_rb_modeline(1280, 720, 60)
    args = m.xrandr_args()
    assert args[0] == "1280x720_60.00"
    assert args[-2:] == ["+hsync", "-vsync"]
    assert len(args) == 12


def test_manager_headless_is_inert():
    dm = DisplayManager(":99")
    # no xrandr or no display -> available() False on this CI image is
    # fine either way; the contract is just "no crash"
    assert dm.available() in (True, False)
