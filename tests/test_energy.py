"""Energy observability plane (ISSUE 14): coefficient math, the RAPL
reader, meter estimation (proxy / measured, idle floor, fps/W
identity), per-frame/per-session attribution through the trace
summarizer, the ladder's EnergyBudgetPolicy selection rules, and the
perf-ledger energy columns + pareto front. Stdlib-only by design —
injected clocks, synthetic RAPL sysfs trees, synthetic perf
registries; no jax."""

import json
import sys
from pathlib import Path

from selkies_tpu.obs import energy as E
from selkies_tpu.obs.perf import PerfRegistry

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))
from tools import perf_ledger  # noqa: E402


def make_registry(flops=1e9, nbytes=8e8, name="h264.i_step[t]",
                  backend="cpu"):
    reg = PerfRegistry()
    reg.record_analysis(name,
                        cost=[{"flops": flops, "bytes accessed": nbytes}],
                        backend=backend)
    return reg


def make_rapl_tree(tmp_path, uj=1_000_000, rng=2 ** 32):
    dom = tmp_path / "intel-rapl:0"
    dom.mkdir()
    (dom / "name").write_text("package-0\n")
    (dom / "energy_uj").write_text(f"{uj}\n")
    (dom / "max_energy_range_uj").write_text(f"{rng}\n")
    return dom


# ------------------------------------------------------------ coefficients

def test_step_energy_matches_coefficients():
    c = E.coeffs_for("cpu")
    want = (1e9 * c.pj_per_flop + 8e8 * c.pj_per_byte) * 1e-12
    assert abs(E.step_energy_j(1e9, 8e8, "cpu") - want) < 1e-15
    # negative/garbage inputs clamp instead of going negative
    assert E.step_energy_j(-1, -1, "cpu") == 0.0


def test_coeffs_backend_class_normalisation():
    assert E.coeffs_for("cpu-fallback-relay-dead") is E.coeffs_for("cpu")
    assert E.coeffs_for("tpu") is E.COEFFS["tpu"]
    assert E.coeffs_for(None) is E.COEFFS["cpu"]
    assert E.coeffs_for("riscv-weird") is E.COEFFS["cpu"]  # unknown class
    # accelerator work is cheaper per unit than host work, idle dearer
    assert E.COEFFS["tpu"].pj_per_flop < E.COEFFS["cpu"].pj_per_flop
    assert E.COEFFS["tpu"].idle_w > E.COEFFS["cpu"].idle_w


def test_perf_registry_records_energy_j():
    reg = make_registry()
    entry = reg.report()["steps"][0]
    assert entry["energy_j"] == round(E.step_energy_j(1e9, 8e8, "cpu"), 6)


# ----------------------------------------------------------------- meter

def test_proxy_estimate_identities(tmp_path):
    m = E.EnergyMeter(perf_registry=make_registry(),
                      rapl=E.RaplReader(root=str(tmp_path)))
    est = m.estimate(30.0, backend="cpu")
    c = E.coeffs_for("cpu")
    dyn = E.step_energy_j(1e9, 8e8, "cpu")
    assert est["source"] == "proxy"
    assert est["watts"] == round(c.idle_w + dyn * 30.0, 3)
    assert est["fps_per_w"] == round(30.0 / est["watts"], 4)
    assert abs(est["joules_frame"] * 30.0 - est["watts"]) < 1e-3
    assert est["dynamic_step"] == "h264.i_step[t]"


def test_dynamic_uses_heaviest_step_not_sum(tmp_path):
    """A frame executes ONE engine step: the i/p pair (and stale ladder
    geometries) coexist in the registry but must not sum."""
    reg = make_registry(flops=1e9, nbytes=8e8, name="h264.i_step[t]")
    reg.record_analysis("h264.p_step[t]",
                        cost=[{"flops": 4e8, "bytes accessed": 2e8}],
                        backend="cpu")
    m = E.EnergyMeter(perf_registry=reg,
                      rapl=E.RaplReader(root=str(tmp_path)))
    dyn, step = m.dynamic_j_frame("cpu")
    assert step == "h264.i_step[t]"
    assert abs(dyn - E.step_energy_j(1e9, 8e8, "cpu")) < 1e-15


def test_idle_floor_on_stalled_pipeline(tmp_path):
    m = E.EnergyMeter(perf_registry=make_registry(),
                      rapl=E.RaplReader(root=str(tmp_path)))
    est = m.estimate(0.0, backend="cpu")
    assert est["watts"] == E.coeffs_for("cpu").idle_w   # never zero
    assert est["joules_frame"] is None                  # no frames: no j/f
    assert est["fps_per_w"] == 0.0


def test_rapl_reader_measures_and_wraps(tmp_path):
    dom = make_rapl_tree(tmp_path, uj=1_000_000)
    # a SUBdomain must not double-count the package counter
    sub = tmp_path / "intel-rapl:0:0"
    sub.mkdir()
    (sub / "energy_uj").write_text("999999999\n")
    clock = [100.0]
    m = E.EnergyMeter(perf_registry=make_registry(),
                      rapl=E.RaplReader(root=str(tmp_path)),
                      clock=lambda: clock[0])
    assert m.sample_power() is None            # first read: baseline only
    (dom / "energy_uj").write_text("5000000\n")
    clock[0] += 2.0
    s = m.sample_power()
    assert s == {"watts": 2.0, "source": "rapl"}
    est = m.estimate(10.0, backend="cpu")
    assert est["source"] == "rapl" and est["watts"] == 2.0
    assert est["fps_per_w"] == round(10.0 / 2.0, 4)
    # wraparound: the counter resets below the last read
    (dom / "energy_uj").write_text("1000000\n")
    clock[0] += 2.0
    s = m.sample_power()
    # delta = 1e6 - 5e6 + 2^32 uJ over 2 s
    assert s["source"] == "rapl"
    assert abs(s["watts"] - ((2 ** 32 - 4_000_000) / 1e6 / 2.0)) < 1e-6


def test_rapl_multi_package_wrap_corrects_per_domain(tmp_path):
    """One socket's counter wrapping must be corrected by ITS range,
    not the sum of every package's — the summed correction over-adds a
    whole counter range per extra socket (a phantom ~430 W spike)."""
    dom0 = make_rapl_tree(tmp_path, uj=4_000_000)
    dom1 = tmp_path / "intel-rapl:1"
    dom1.mkdir()
    (dom1 / "energy_uj").write_text("1000000\n")
    (dom1 / "max_energy_range_uj").write_text(f"{2 ** 32}\n")
    clock = [0.0]
    m = E.EnergyMeter(perf_registry=make_registry(),
                      rapl=E.RaplReader(root=str(tmp_path)),
                      clock=lambda: clock[0])
    m.sample_power()
    # dom0 wraps (4e6 -> 1e6); dom1 advances by 2e6 uJ
    (dom0 / "energy_uj").write_text("1000000\n")
    (dom1 / "energy_uj").write_text("3000000\n")
    clock[0] += 2.0
    s = m.sample_power()
    want = ((2 ** 32 - 3_000_000) + 2_000_000) / 1e6 / 2.0
    assert s["source"] == "rapl" and abs(s["watts"] - want) < 1e-6


def test_rapl_frozen_counter_is_not_a_measured_zero(tmp_path):
    """A powercap tree whose counters never advance (VM stubs) must
    degrade to 'unavailable' — a 'measured' 0 W would beat the honest
    proxy and report absurd fps/W to the ledger and the heartbeat."""
    make_rapl_tree(tmp_path, uj=1_000_000)
    clock = [0.0]
    m = E.EnergyMeter(perf_registry=make_registry(),
                      rapl=E.RaplReader(root=str(tmp_path)),
                      clock=lambda: clock[0])
    m.sample_power()                           # baseline
    clock[0] += 5.0
    assert m.sample_power() is None            # 0 delta: unavailable
    est = m.estimate(10.0, backend="cpu")
    assert est["source"] == "proxy" and est["watts"] >= 10.0


def test_device_power_explicit_none_checks(monkeypatch):
    """A 0.0 W reading on one device is a real number, not 'absent'
    (the falsy-or trap); an ALL-zero total is degenerate for fps/W
    and degrades to the next source."""
    import types

    class Dev:
        def __init__(self, w):
            self._w = w

        def power_stats(self):
            return {"power_w": self._w}

    m = E.EnergyMeter(perf_registry=make_registry(),
                      rapl=E.RaplReader(root="/nonexistent"))
    monkeypatch.setitem(sys.modules, "jax", types.SimpleNamespace(
        local_devices=lambda: [Dev(0.0), Dev(7.5)]))
    s = m.sample_power()
    assert s == {"watts": 7.5, "source": "device"}
    monkeypatch.setitem(sys.modules, "jax", types.SimpleNamespace(
        local_devices=lambda: [Dev(0.0), Dev(0.0)]))
    assert m._device_power_w() is None


def test_rapl_absent_falls_back_to_proxy(tmp_path):
    m = E.EnergyMeter(perf_registry=make_registry(),
                      rapl=E.RaplReader(root=str(tmp_path / "nope")))
    assert m.rapl.available() is False
    assert m.sample_power() is None
    assert m.estimate(5.0, backend="cpu")["source"] == "proxy"


def test_measured_sample_goes_stale(tmp_path):
    dom = make_rapl_tree(tmp_path)
    clock = [0.0]
    m = E.EnergyMeter(perf_registry=make_registry(),
                      rapl=E.RaplReader(root=str(tmp_path)),
                      clock=lambda: clock[0])
    m.sample_power()
    (dom / "energy_uj").write_text("3000000\n")
    clock[0] += 1.0
    assert m.sample_power()["source"] == "rapl"
    clock[0] += E.MEASURED_TTL_S + 1.0
    # a reading from before the workload changed must not linger
    assert m.estimate(5.0, backend="cpu")["source"] == "proxy"


def test_live_fps_estimate_from_frame_notes():
    clock = [0.0]
    m = E.EnergyMeter(perf_registry=PerfRegistry(),
                      rapl=E.RaplReader(root="/nonexistent"),
                      clock=lambda: clock[0])
    assert m.fps_estimate() == 0.0
    for _ in range(10):
        clock[0] += 0.1
        m.note_frame()
    assert abs(m.fps_estimate(window_s=1.0) - 10.0) < 1e-9
    assert m.watts_estimate() > 0.0            # idle floor at least


def test_fps_estimate_survives_ring_saturation():
    """A busy multi-seat host delivering more frames than the stamp
    ring holds inside the window must not cap at maxlen/window: the
    fleet would under-report exactly its hottest hosts."""
    clock = [0.0]
    m = E.EnergyMeter(perf_registry=PerfRegistry(),
                      rapl=E.RaplReader(root="/nonexistent"),
                      clock=lambda: clock[0])
    for _ in range(3 * E._FRAME_RING):         # 1000 fps offered
        clock[0] += 0.001
        m.note_frame()
    est = m.fps_estimate(window_s=5.0)
    assert est > 900.0, est                    # not maxlen/5 ≈ 205


# ------------------------------------------------------------ attribution

def _tl(display, fid, t0_ms, spans):
    return {"display_id": display, "frame_id": fid,
            "t0_ns": int(t0_ms * 1e6),
            "t1_ns": int((t0_ms + 12.0) * 1e6),
            "spans": [{"name": n, "lane": "l", "t0_ns": int(a * 1e6),
                       "dur_ns": int(d * 1e6)} for n, a, d in spans]}


def test_attribution_round_trips_per_frame_and_session():
    tls = [
        _tl("s0", 1, 0.0, [("enc", 0.0, 10.0), ("pack", 2.0, 10.0)]),
        _tl("s0", 2, 20.0, [("enc", 20.0, 8.0)]),     # 4 ms bubble
        _tl("s1", 1, 40.0, [("enc", 40.0, 12.0)]),
    ]
    att = E.attribute_timelines(tls, watts=10.0)
    assert att["frames"] == 3
    # 3 frames x 12 ms x 10 W = 0.36 J total
    assert abs(att["joules"] - 0.36) < 1e-9
    assert abs(sum(att["per_stage_j"].values()) - att["joules"]) < 1e-9
    assert abs(att["per_stage_j"]["bubble"] - 10.0 * 0.004) < 1e-9
    per = att["per_session"]
    assert set(per) == {"s0", "s1"}
    assert per["s0"]["frames"] == 2 and per["s1"]["frames"] == 1
    assert abs(per["s0"]["joules"] + per["s1"]["joules"]
               - att["joules"]) < 1e-9
    assert per["s1"]["joules_per_frame"] == 0.12


def test_report_derives_fps_from_timeline_window(tmp_path):
    m = E.EnergyMeter(perf_registry=make_registry(),
                      rapl=E.RaplReader(root=str(tmp_path)))
    tls = [_tl("s0", i, i * 100.0, [("enc", i * 100.0, 10.0)])
           for i in range(5)]
    rep = m.report(timelines=tls, backend="cpu")
    # 5 frames over the 412 ms window
    assert abs(rep["fps"] - round(5 / 0.412, 2)) < 0.02
    assert rep["attribution"]["frames"] == 5
    json.loads(json.dumps(rep))


# ---------------------------------------------------------- ladder policy

def test_policy_over_budget_is_nan_and_failure_safe():
    pol = E.EnergyBudgetPolicy(100.0, lambda: 120.0)
    assert pol.over_budget() is True and pol.last_watts == 120.0
    assert E.EnergyBudgetPolicy(100.0, lambda: 90.0).over_budget() is False
    assert E.EnergyBudgetPolicy(
        100.0, lambda: float("nan")).over_budget() is False

    def boom():
        raise RuntimeError("watts feed died")
    assert E.EnergyBudgetPolicy(100.0, boom).over_budget() is False


def test_policy_selection_rules():
    pol = E.EnergyBudgetPolicy(100.0, lambda: 120.0, rung_table={
        "fps": {"fps_per_w": 1.0},
        "quality": {"fps_per_w": 5.0, "meets_slo": False},
        "downscale": {"fps_per_w": 3.0},
    })
    steps = ("pipeline", "fps", "quality", "downscale")
    # everything warm: downscale (3.0) wins; quality (5.0) is skipped
    # for violating the SLO, pipeline for being unpriced
    assert pol.select_rung(steps, 0, lambda s: True) == 3
    # downscale cold: fps is the best warm SLO-meeting rung
    assert pol.select_rung(steps, 0, lambda s: s != "downscale") == 1
    # only rungs at/below the current level are candidates
    assert pol.select_rung(steps, 2, lambda s: s != "downscale") is None
    # callable SLO predicate is honoured (and a crashing one rejects)
    pol2 = E.EnergyBudgetPolicy(100.0, lambda: 120.0, rung_table={
        "fps": {"fps_per_w": 1.0, "meets_slo": lambda: True},
        "downscale": {"fps_per_w": 3.0,
                      "meets_slo": lambda: 1 / 0},
    })
    assert pol2.select_rung(steps, 0, lambda s: True) == 1


def test_ladder_policy_from_settings():
    import types
    assert E.ladder_policy_from_settings(
        types.SimpleNamespace(power_budget_w=0.0)) is None
    assert E.ladder_policy_from_settings(types.SimpleNamespace()) is None
    pol = E.ladder_policy_from_settings(
        types.SimpleNamespace(power_budget_w=250.0))
    assert pol is not None and pol.budget_w == 250.0


# ------------------------------------------------------------ perf ledger

def _ledger_entry(**over):
    e = {
        "v": 1, "ts": "2026-08-04T00:00:00+00:00", "git_rev": "a" * 40,
        "host": "h", "host_id": "h-1", "metric":
        "encode_fps_256x128_jpeg_tpu", "backend": "cpu",
        "backend_class": "cpu", "resolution": "256x128", "codec": "jpeg",
        "backend_health": "ok", "baseline_eligible": True, "fps": 10.0,
        "latency_p50_ms": 50.0, "latency_p99_ms": 60.0,
        "g2g_p99_ms": 80.0, "qoe_score": 90.0, "pipeline_depth": 2,
        "stripe_devices": 1, "joules_frame": 1.0, "fps_per_w": 0.9,
        "watts_mean": 11.1, "energy_source": "proxy",
    }
    e.update(over)
    return e


def test_entry_from_bench_carries_energy_columns():
    doc = {"metric": "encode_fps_256x128_jpeg_tpu", "value": 10.0,
           "backend": "cpu", "backend_health": {"status": "ok"},
           "energy": {"joules_frame": 1.25, "watts_mean": 12.5,
                      "fps_per_w": 0.8, "source": "rapl"}}
    e = perf_ledger.entry_from_bench(doc)
    assert e["joules_frame"] == 1.25
    assert e["fps_per_w"] == 0.8
    assert e["watts_mean"] == 12.5
    assert e["energy_source"] == "rapl"
    # energy-less docs stay None, never 0 (the columns are honest)
    e2 = perf_ledger.entry_from_bench(
        {"metric": "encode_fps_256x128_jpeg_tpu", "value": 10.0,
         "backend": "cpu", "backend_health": {"status": "ok"}})
    assert e2["joules_frame"] is None and e2["fps_per_w"] is None


def test_wild_joules_swing_cannot_fail_the_gate(tmp_path, capsys):
    """ISSUE 14 satellite: energy columns are informational-only in
    check until a real-TPU baseline exists — a 100x joules swing with
    healthy fps/p99 must exit 0."""
    ledger = tmp_path / "ledger.jsonl"
    perf_ledger.append_entry(str(ledger), _ledger_entry())
    cand = _ledger_entry(git_rev="b" * 40, joules_frame=100.0,
                         fps_per_w=0.009, watts_mean=1000.0)
    cand_file = tmp_path / "cand.json"
    cand_file.write_text(json.dumps(cand))
    rc = perf_ledger.main(["--ledger", str(ledger), "check",
                           "--candidate", str(cand_file)])
    assert rc == 0
    err = capsys.readouterr().err
    assert "informational only" in err and "never gated" in err
    # sanity: the SAME candidate with an fps regression still fails
    cand2 = _ledger_entry(git_rev="c" * 40, fps=5.0)
    cand_file.write_text(json.dumps(cand2))
    assert perf_ledger.main(["--ledger", str(ledger), "check",
                             "--candidate", str(cand_file)]) == 1


def test_report_renders_energy_columns(tmp_path, capsys):
    ledger = tmp_path / "ledger.jsonl"
    perf_ledger.append_entry(str(ledger), _ledger_entry())
    assert perf_ledger.main(["--ledger", str(ledger), "report"]) == 0
    out = capsys.readouterr().out
    assert "j/f" in out and "fps/W" in out
    assert "1.000" in out and "0.900" in out


def test_pareto_front_over_operating_points(tmp_path, capsys):
    ledger = tmp_path / "ledger.jsonl"
    # A: best quality (slow, hungry) — on the front
    perf_ledger.append_entry(str(ledger), _ledger_entry(
        metric="encode_fps_1920x1080_h264_tpu", resolution="1920x1080",
        codec="h264", qoe_score=99.0, g2g_p99_ms=100.0,
        joules_frame=3.0, fps_per_w=0.3))
    # B: efficient and fast — on the front
    perf_ledger.append_entry(str(ledger), _ledger_entry(
        metric="encode_fps_1280x720_h264_tpu", resolution="1280x720",
        codec="h264", qoe_score=95.0, g2g_p99_ms=40.0,
        joules_frame=0.5, fps_per_w=2.0))
    # C: dominated by B on all three axes
    perf_ledger.append_entry(str(ledger), _ledger_entry(
        metric="encode_fps_256x128_jpeg_tpu", resolution="256x128",
        codec="jpeg", qoe_score=90.0, g2g_p99_ms=60.0,
        joules_frame=2.0, fps_per_w=0.45))
    assert perf_ledger.main(["--ledger", str(ledger), "pareto",
                             "--json"]) == 0
    out = capsys.readouterr().out
    assert "2 on the" in out
    doc = json.loads(out.strip().splitlines()[-1])
    # content_class joined the operating-point key in PR 15 ("any"
    # when the entry predates the column or carries null)
    assert sorted(doc["front"]) == sorted([
        "cpu/1920x1080/h264/1/2/any", "cpu/1280x720/h264/1/2/any"])
    assert "dominated" in out and "256x128" in out


def test_pareto_latest_entry_per_point_wins(tmp_path, capsys):
    ledger = tmp_path / "ledger.jsonl"
    perf_ledger.append_entry(str(ledger), _ledger_entry(
        joules_frame=9.0, fps_per_w=0.1))
    perf_ledger.append_entry(str(ledger), _ledger_entry(
        git_rev="b" * 40, joules_frame=1.5, fps_per_w=0.7))
    assert perf_ledger.main(["--ledger", str(ledger), "pareto"]) == 0
    out = capsys.readouterr().out
    assert "1 operating point(s)" in out and "1.5000" in out
