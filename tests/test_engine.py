"""End-to-end engine tests: synthetic source -> ScreenCapture -> chunks.

This is the fake-encoder vertical slice of SURVEY.md §7 step 2, except the
encoder is already the real TPU-shaped one (running on CPU here).
"""

import io
import time

import numpy as np
import pytest
from PIL import Image

from selkies_tpu.engine import CaptureSettings, ScreenCapture
from selkies_tpu.engine.encoder import JpegEncoderSession
from selkies_tpu.engine.sources import SyntheticSource


SMALL = dict(capture_width=64, capture_height=64, stripe_height=32,
             target_fps=120.0, jpeg_quality=75)


def test_encoder_session_roundtrip():
    s = CaptureSettings(**SMALL)
    sess = JpegEncoderSession(s)
    src = SyntheticSource(s.capture_width, s.capture_height)
    out = sess.encode(src.get_frame(0))
    chunks = sess.finalize(out)
    # first frame: everything damaged -> all stripes sent
    assert len(chunks) == sess.grid.n_stripes
    for c in chunks:
        img = Image.open(io.BytesIO(c.payload))
        img.load()
        assert img.size == (sess.grid.width, sess.grid.stripe_h)
        assert c.output_mode == "jpeg" and c.is_idr


def test_damage_gating_skips_static_stripes():
    s = CaptureSettings(**SMALL)
    s.paint_over_delay_frames = 5
    sess = JpegEncoderSession(s)
    src = SyntheticSource(s.capture_width, s.capture_height, static_after=0)
    outs = [sess.finalize(sess.encode(src.get_frame(t))) for t in range(4)]
    assert len(outs[0]) == sess.grid.n_stripes   # first frame full
    assert all(len(o) == 0 for o in outs[1:])    # static -> nothing sent


def test_paint_over_fires_once():
    s = CaptureSettings(**SMALL)
    s.paint_over_delay_frames = 3
    sess = JpegEncoderSession(s)
    src = SyntheticSource(s.capture_width, s.capture_height, static_after=0)
    sent = [len(sess.finalize(sess.encode(src.get_frame(t))))
            for t in range(8)]
    # frame 0 full; then silence; at age==3 one full-quality repaint; silence
    assert sent[0] == sess.grid.n_stripes
    assert sum(sent[1:]) == sess.grid.n_stripes
    assert sent[3] == sess.grid.n_stripes  # age hits the delay on encode 3


def test_force_idr_resends_all():
    s = CaptureSettings(**SMALL)
    sess = JpegEncoderSession(s)
    src = SyntheticSource(s.capture_width, s.capture_height, static_after=0)
    sess.finalize(sess.encode(src.get_frame(0)))
    out = sess.encode(src.get_frame(1))
    chunks = sess.finalize(out, force_all=True)
    assert len(chunks) == sess.grid.n_stripes


def test_screen_capture_thread_delivers_chunks():
    got = []
    cap = ScreenCapture(source_kind="synthetic")
    cap.start_capture(got.append, CaptureSettings(**SMALL))
    deadline = time.time() + 30
    while time.time() < deadline and len(got) < 6:
        time.sleep(0.05)
    assert cap.is_capturing()
    cap.stop_capture()
    assert not cap.is_capturing()
    assert len(got) >= 6
    frame_ids = {c.frame_id for c in got}
    assert len(frame_ids) >= 2          # multiple frames delivered
    for c in got[:4]:
        Image.open(io.BytesIO(c.payload)).load()


def test_damage_gating_disabled_sends_everything():
    s = CaptureSettings(**SMALL)
    s.use_damage_gating = False
    sess = JpegEncoderSession(s)
    src = SyntheticSource(s.capture_width, s.capture_height, static_after=0)
    outs = [sess.finalize(sess.encode(src.get_frame(t))) for t in range(3)]
    assert all(len(o) == sess.grid.n_stripes for o in outs)


def test_paint_over_disabled_never_repaints():
    s = CaptureSettings(**SMALL)
    s.use_paint_over = False
    s.paint_over_delay_frames = 2
    sess = JpegEncoderSession(s)
    src = SyntheticSource(s.capture_width, s.capture_height, static_after=0)
    sent = [len(sess.finalize(sess.encode(src.get_frame(t)))) for t in range(6)]
    assert sent[0] == sess.grid.n_stripes and sum(sent[1:]) == 0


def test_reencoding_same_frame_array_is_safe():
    """Sources may hand back the same device buffer repeatedly (ArraySource
    cycling); the session must not donate/invalidate caller frames."""
    s = CaptureSettings(**SMALL)
    sess = JpegEncoderSession(s)
    src = SyntheticSource(s.capture_width, s.capture_height)
    frame = src.get_frame(0)
    for _ in range(3):
        sess.finalize(sess.encode(frame), force_all=True)
    assert frame.shape == (64, 64, 3)  # still alive and readable


def test_live_quality_update():
    s = CaptureSettings(**SMALL)
    s.jpeg_quality = 90
    sess = JpegEncoderSession(s)
    src = SyntheticSource(s.capture_width, s.capture_height)
    frame = src.get_frame(7)
    big = sum(len(c.payload) for c in sess.finalize(sess.encode(frame), force_all=True))
    sess.update_quality(10)
    small = sum(len(c.payload) for c in
                sess.finalize(sess.encode(frame), force_all=True))
    assert small < big


def test_quality_change_between_encode_and_finalize_uses_snapshot():
    """finalize runs pipeline-depth frames after encode; a live quality
    change in between must not desync the JFIF DQT from the tables the
    device quantized with (round-1 advisor finding)."""
    s1, s2 = CaptureSettings(**SMALL), CaptureSettings(**SMALL)
    a, b = JpegEncoderSession(s1), JpegEncoderSession(s2)
    src = SyntheticSource(s1.capture_width, s1.capture_height)
    frame = src.get_frame(3)
    out = a.encode(frame)
    a.update_quality(10)            # live change while frame is in flight
    chunks_a = a.finalize(out, force_all=True)
    chunks_b = b.finalize(b.encode(frame), force_all=True)
    assert [c.payload for c in chunks_a] == [c.payload for c in chunks_b]


def test_overflow_drop_forces_full_resend():
    """A dropped (overflowed) frame advanced the damage baseline past what
    the client saw; the next delivered frame must resend every stripe."""
    s = CaptureSettings(**SMALL)
    sess = JpegEncoderSession(s)
    src = SyntheticSource(s.capture_width, s.capture_height, static_after=1)
    sess.finalize(sess.encode(src.get_frame(0)))
    out = sess.encode(src.get_frame(1))          # content changed here...
    out["overflow"] = np.array(True)             # ...but the frame dropped
    assert sess.finalize(out) == []
    out2 = sess.encode(src.get_frame(2))         # static vs dropped frame
    chunks = sess.finalize(out2)
    assert len(chunks) == sess.grid.n_stripes    # forced full refresh


def test_keyframe_interval_forces_periodic_refresh():
    """keyframe_interval_s must re-send everything even for a static scene
    (round-1 verdict: the setting was plumbed but never used)."""
    got = []
    s = CaptureSettings(**SMALL)
    s.use_paint_over = False
    s.keyframe_interval_s = 0.25
    cap = ScreenCapture(source_kind="synthetic-static")
    cap.start_capture(got.append, s)
    # two-phase deadline (PERF.md rules): phase 1 absorbs the XLA
    # compile (this box has ONE core — a cold jit under suite load can
    # eat most of a flat 30 s window and flake the cadence assertion);
    # phase 2 times only the refresh cadence from the first delivery
    deadline = time.time() + 120
    while time.time() < deadline and not got:
        time.sleep(0.05)
    assert got, "no first frame within the compile window"
    n = 2 * (s.capture_height // s.stripe_height)  # two full refreshes
    deadline = time.time() + 30
    while time.time() < deadline and len(got) < n + 1:
        time.sleep(0.05)
    cap.stop_capture()
    fids = {c.frame_id for c in got}
    assert len(fids) >= 2, f"no periodic refresh: frame ids {fids}"


def test_watermark_burned_into_stream(tmp_path):
    """watermark_path burns a PNG into the encoded frames on device
    (reference pixelflux watermark, display_utils.py:1674-1679)."""
    from PIL import Image as PILImage
    wm = np.zeros((16, 16, 4), np.uint8)
    wm[..., 0] = 255          # solid red
    wm[..., 3] = 255
    p = tmp_path / "wm.png"
    PILImage.fromarray(wm, "RGBA").save(p)

    base = CaptureSettings(**SMALL)
    marked = CaptureSettings(**SMALL)
    marked.watermark_path = str(p)
    marked.watermark_location = 0     # top-left
    a = JpegEncoderSession(base)
    b = JpegEncoderSession(marked)
    src = SyntheticSource(a.grid.width, a.grid.height)
    frame = src.get_frame(0)
    plain = a.finalize(a.encode(frame), force_all=True)
    stamped = b.finalize(b.encode(frame), force_all=True)
    img_p = Image.open(io.BytesIO(plain[0].payload)); img_p.load()
    img_s = Image.open(io.BytesIO(stamped[0].payload)); img_s.load()
    # the anchored region must turn red-dominant in the stamped stream
    rp = np.asarray(img_p)[16:32, 16:32]
    rs = np.asarray(img_s)[16:32, 16:32]
    assert not np.array_equal(rp, rs)
    assert rs[..., 0].mean() > 200 and rs[..., 1].mean() < 80


# -- cross-thread state discipline (graftlint THREAD-SHARED-MUTATION) --------

class _RecordingLock:
    """threading.Lock stand-in that counts acquisitions — the regression
    contract for the rate-control/clamp lock fixes is 'these paths hold
    the tunables lock', not a timing-dependent race reproduction."""

    def __init__(self):
        self.entered = 0
        self.held = False

    def __enter__(self):
        self.entered += 1
        self.held = True
        return self

    def __exit__(self, *exc):
        self.held = False
        return False


class _QpSession:
    def __init__(self):
        self.qp = 30
        self.qp_sets = []

    def set_qp(self, qp):
        self.qp_sets.append(qp)
        self.qp = qp


def _rc_capture():
    cap = ScreenCapture()
    cap._lock = _RecordingLock()
    s = CaptureSettings(**SMALL)
    s.output_mode = "h264"
    s.use_cbr = True
    s.video_bitrate_kbps = 1000
    cap._settings = s
    cap._session = _QpSession()
    cap._rc_fullness = 0.0
    cap._rc_qp0 = 30
    return cap


def test_rate_control_state_is_locked():
    """An ABANDONED capture thread (timed-out join) can still be inside
    the rate controller when start_capture resets the bucket for the
    replacement run — every _rc_* mutation must hold the tunables lock
    (the race graftlint's THREAD-SHARED-MUTATION rule flagged)."""
    cap = _rc_capture()
    cap._rate_control_frame(50_000)
    assert cap._lock.entered == 1
    assert not cap._lock.held            # released before sess.set_qp
    cap._rate_control(5_000_000, 1.0)    # way over rate: re-centres qp0
    assert cap._lock.entered == 2


def test_rate_control_still_steers_qp_under_lock():
    """The lock fix must not change controller behaviour: a flood of
    bytes fills the bucket and pushes qp up; idle frames drain it."""
    cap = _rc_capture()
    for _ in range(10):
        cap._rate_control_frame(200_000)
    assert cap._session.qp > 30
    for _ in range(300):                 # bucket drains ~rate/fps per
        cap._rate_control_frame(0)       # tick: give it room to empty
    assert cap._session.qp < 30


def test_pipeline_clamp_is_locked():
    """set_pipeline_clamp (loop side) and effective_pipeline_depth
    (capture-thread side) both take the lock around the shared clamp."""
    cap = ScreenCapture()
    cap._lock = _RecordingLock()
    s = CaptureSettings(**SMALL)
    s.pipeline_depth = 4
    cap._settings = s
    cap.set_pipeline_clamp(2)
    assert cap._lock.entered == 1
    assert cap.effective_pipeline_depth() == 2
    assert cap._lock.entered == 2
    cap.set_pipeline_clamp(None)
    assert cap.effective_pipeline_depth() == 4


def test_multiseat_pipeline_clamp_is_locked():
    from selkies_tpu.parallel.capture import MultiSeatCapture
    cap = MultiSeatCapture(2)
    cap._lock = _RecordingLock()
    s = CaptureSettings(**SMALL)
    s.pipeline_depth = 3
    cap._settings = s
    cap.set_pipeline_clamp(1)
    assert cap.effective_pipeline_depth() == 1
    assert cap._lock.entered == 2
