"""fake-udev addon: build + run the C protocol test (enumeration of the
virtual gamepads and inotify-backed hotplug monitor)."""

import pathlib
import shutil
import subprocess

import pytest

ADDON = pathlib.Path(__file__).resolve().parent.parent / "addons" / "fake-udev"


@pytest.mark.skipif(shutil.which("gcc") is None, reason="no gcc")
def test_fake_udev_enumeration_and_monitor(tmp_path):
    subprocess.run(["make", "-C", str(ADDON), "libudev.so.1",
                    "test_fake_udev"], check=True, capture_output=True)
    out = subprocess.run(
        [str(ADDON / "test_fake_udev")],
        env={"SELKIES_JS_SOCKET_PATH": str(tmp_path), "PATH": "/usr/bin"},
        capture_output=True, timeout=30)
    assert out.returncode == 0, out.stderr.decode()
    assert b"EMPTY_OK" in out.stdout
    assert b"ENUM_OK" in out.stdout
    assert b"MONITOR_OK" in out.stdout
