"""Fleet plane contracts (ISSUE 11): protocol hardening, bin-packing
refusal/queueing, warm/cold scoring, evict hysteresis, drain/failover
migration with IDR resync, the cross-host dead-relay re-offer, the
supervisor drain awaitable, and the prewarm readiness gate — all on
injected clocks, no sleeps."""

import asyncio
import json
import threading

import pytest

from selkies_tpu.fleet.migrate import MigrationCoordinator
from selkies_tpu.fleet.protocol import (DeviceCapacity,
                                        FleetProtocolError, Heartbeat,
                                        SessionSpec, estimate_hbm_mb,
                                        migrate_command, parse_heartbeat,
                                        parse_session_spec)
from selkies_tpu.fleet.scheduler import SeatScheduler
from selkies_tpu.fleet.sim import SimFleet, SimHost
from selkies_tpu.obs.health import FlightRecorder
from selkies_tpu.resilience.supervisor import (RestartPolicy, Supervisor)


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def make_rig(*, host_timeout_s=3.0, evict_confirm=3, evict_hold_s=10.0,
             grace_s=3.0):
    clock_box = [0.0]
    rec = FlightRecorder()
    sched = SeatScheduler(clock=lambda: clock_box[0], recorder=rec,
                          host_timeout_s=host_timeout_s,
                          evict_confirm=evict_confirm,
                          evict_hold_s=evict_hold_s)
    coord = MigrationCoordinator(sched, clock=lambda: clock_box[0],
                                 recorder=rec, grace_s=grace_s)
    fleet = SimFleet(sched, coord, clock_box=clock_box)
    return fleet, sched, coord, rec


def add_host(fleet, name, *, seat_slots=4, hbm_limit_mb=1000.0,
             warm_after_s=0.0, warm_geometries=(), devices=1,
             pixel_budget=3 * 1920 * 1080):
    return fleet.add_host(SimHost(
        name, clock=fleet.clock, devices=devices, seat_slots=seat_slots,
        hbm_limit_mb=hbm_limit_mb, pixel_budget=pixel_budget,
        warm_after_s=warm_after_s, warm_geometries=warm_geometries,
        grace_s=3.0, recorder=fleet.scheduler.recorder))


def incident_kinds(rec):
    return [e["kind"] for e in rec.snapshot()]


# ---------------------------------------------------------------- protocol

def test_heartbeat_round_trips_through_wire_parser():
    fleet, sched, coord, rec = make_rig()
    h = add_host(fleet, "h0", warm_geometries=("640x360",))
    fleet.tick(0.5)
    sched.place(SessionSpec("s1", 640, 360, "jpeg"))
    hb = h.heartbeat()
    back = parse_heartbeat(hb.to_json())
    assert back.host_id == "h0" and back.ready
    assert back.devices[0].seat_slots == 4
    assert back.sessions[0].sid == "s1"
    assert back.warm_geometries == ["640x360"]


@pytest.mark.parametrize("doc", [
    "not json {",
    [],
    {"kind": "heartbeat"},                            # no version
    {"v": 1, "kind": "nope", "host_id": "x"},
    {"v": 99, "kind": "heartbeat", "host_id": "x"},   # future version
    {"v": 1, "kind": "heartbeat", "host_id": ""},
    {"v": 1, "kind": "heartbeat", "host_id": "x", "health": "great"},
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "devices": [{"hbm_limit_mb": float("nan")}]},
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "devices": [{"hbm_limit_mb": -5}]},
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "devices": "lots"},
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "sessions": [{"width": 640}]},                   # session no sid
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "warm_geometries": ["640by360"]},
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "slo": {"status": "ok", "fast_burn": float("inf")}},
    # watts_est (ISSUE 14) is a capacity field — it steers the fleet
    # power budget, so NaN / negative / absurd values reject+count
    # like every other axis
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "watts_est": float("nan")},
    {"v": 1, "kind": "heartbeat", "host_id": "x", "watts_est": -3},
    {"v": 1, "kind": "heartbeat", "host_id": "x", "watts_est": 1e9},
    # egress_mbps_est (ISSUE 17) budgets the gateway fan-out: a NaN /
    # negative / absurd estimate would corrupt relay admission, so the
    # field rejects+counts like every other capacity axis
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "egress_mbps_est": float("nan")},
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "egress_mbps_est": -1},
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "egress_mbps_est": 1e12},
    # seat_class is an enum (encode|relay); rung is a bounded ident
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "sessions": [{"sid": "s1", "width": 640, "height": 360,
                   "seat_class": "mystery"}]},
    {"v": 1, "kind": "heartbeat", "host_id": "x",
     "sessions": [{"sid": "s1", "width": 640, "height": 360,
                   "rung": "r" * 64}]},
])
def test_malformed_heartbeats_rejected(doc):
    with pytest.raises(FleetProtocolError):
        parse_heartbeat(doc)


def test_heartbeat_watts_est_round_trips():
    hb = Heartbeat(host_id="h0", watts_est=41.5)
    back = parse_heartbeat(hb.to_json())
    assert back.watts_est == 41.5
    # absent stays absent (older hosts): never defaulted to a number
    assert parse_heartbeat(Heartbeat(host_id="h0").to_json()) \
        .watts_est is None


def test_heartbeat_egress_and_seat_class_round_trip():
    # ISSUE 17: the egress estimate and relay seat annotations survive
    # the wire parser; absent egress stays absent (older hosts)
    hb = Heartbeat(host_id="h0", egress_mbps_est=7.46)
    back = parse_heartbeat(hb.to_json())
    assert back.egress_mbps_est == 7.46
    assert parse_heartbeat(Heartbeat(host_id="h0").to_json()) \
        .egress_mbps_est is None
    doc = {"v": 1, "kind": "heartbeat", "host_id": "h0",
           "sessions": [{"sid": "v1", "width": 640, "height": 360,
                         "seat_class": "relay", "rung": "low"}]}
    back = parse_heartbeat(doc)
    assert back.sessions[0].seat_class == "relay"
    assert back.sessions[0].rung == "low"


def test_session_spec_and_estimate():
    spec = parse_session_spec(json.dumps(
        {"v": 1, "kind": "place", "sid": "a", "width": 1920,
         "height": 1080, "codec": "h264"}))
    assert spec.budget_mb() == estimate_hbm_mb(1920, 1080, "h264")
    # monotonic in pixels, codec state makes h264 dearer than jpeg
    assert estimate_hbm_mb(1920, 1080) > estimate_hbm_mb(640, 360)
    assert estimate_hbm_mb(640, 360, "h264") > \
        estimate_hbm_mb(640, 360, "jpeg")
    with pytest.raises(FleetProtocolError):
        parse_session_spec({"width": 640})
    with pytest.raises(FleetProtocolError):
        parse_session_spec({"sid": "a", "width": 10 ** 9})


def test_migrate_command_shape():
    cmd = migrate_command("wss://gw.example/fleet/ws", "s7")
    assert cmd.startswith("migrate,")
    body = json.loads(cmd.split(",", 1)[1])
    assert body == {"resync": True, "sid": "s7",
                    "url": "wss://gw.example/fleet/ws"}


# --------------------------------------------------------------- scheduler

def _power_hb(host_id="h0", watts=None):
    """A ready single-device host with effectively-infinite seat/HBM/
    pixel headroom, so only the power axis can refuse."""
    return Heartbeat(host_id=host_id, ready=True, watts_est=watts,
                     devices=[DeviceCapacity(
                         id=0, hbm_limit_mb=1e6, seat_slots=64,
                         pixel_budget=10 ** 12)])


def test_power_budget_refusal_queues_and_frees():
    """ISSUE 14: with a fleet power budget set, a placement that would
    push the projected draw past it refuses-into-the-queue like any
    capacity axis, and releasing a seat frees its watts."""
    rec = FlightRecorder()
    spec_w = SessionSpec("s1", 1920, 1080, "h264").budget_w()
    sched = SeatScheduler(clock=Clock(), recorder=rec,
                          power_budget_w=1.5 * spec_w)
    sched.observe(_power_hb())
    assert sched.place(SessionSpec("s1", 1920, 1080, "h264")) is not None
    spec2 = SessionSpec("s2", 1920, 1080, "h264")
    assert sched.feasible(spec2) is False        # power, not HBM/pixels
    assert sched.place(spec2) is None
    assert "placement_pending" in incident_kinds(rec)
    assert len(sched.pending) == 1
    snap = sched.snapshot()
    assert snap["power"]["budget_w"] == 1.5 * spec_w
    assert snap["power"]["fleet_watts_est"] >= spec_w
    sched.release("s1")                          # watts free with the seat
    assert sched.get("s2") is not None


def test_power_budget_heartbeat_watts_floor():
    """The REPORTED draw (measured RAPL/device watts in the heartbeat)
    floors the projection: a fleet already burning its budget takes
    nothing, whatever the scheduler itself placed."""
    sched = SeatScheduler(clock=Clock(), recorder=FlightRecorder(),
                          power_budget_w=50.0)
    sched.observe(_power_hb(watts=49.9))
    assert sched.place(SessionSpec("s1", 640, 360, "jpeg")) is None
    assert len(sched.pending) == 1
    # draw falls on the next heartbeat: the queued session lands on
    # the observe-triggered retry
    sched.observe(_power_hb(watts=10.0))
    assert sched.get("s1") is not None
    assert not sched.pending


def test_power_budget_migration_probe_is_power_neutral():
    """The evict/migrate path probes feasible() BEFORE releasing the
    source seat: an already-placed session's watts are in the fleet
    projection already, so the probe must not double-charge them — or
    rebalance wedges the moment the fleet runs near its budget."""
    spec = SessionSpec("s1", 1920, 1080, "h264")
    sched = SeatScheduler(clock=Clock(), recorder=FlightRecorder(),
                          power_budget_w=spec.budget_w() + 0.1)
    sched.observe(_power_hb("h0"))
    sched.observe(_power_hb("h1"))
    p = sched.place(spec)
    assert p is not None
    # power-neutral move probe: still feasible on the other host
    assert sched.feasible(spec, exclude_hosts={p.host_id}) is True
    # a genuinely NEW session is honestly refused
    assert sched.feasible(SessionSpec("s2", 1920, 1080, "h264")) is False


def test_power_probe_of_placed_session_survives_over_budget_fleet():
    """With the fleet already OVER its power budget (burning hosts —
    exactly when rebalance matters) a power-neutral move of a placed
    session must still probe feasible; only NEW sessions refuse."""
    spec = SessionSpec("s1", 1920, 1080, "h264")
    sched = SeatScheduler(clock=Clock(), recorder=FlightRecorder(),
                          power_budget_w=50.0)
    sched.observe(_power_hb("h0"))
    sched.observe(_power_hb("h1"))
    p = sched.place(spec)
    assert p is not None
    # heartbeats now report 30 W each: fleet 60 W > 50 W budget
    sched.observe(_power_hb("h0", watts=30.0))
    sched.observe(_power_hb("h1", watts=30.0))
    assert sched.feasible(spec, exclude_hosts={p.host_id}) is True
    assert sched.feasible(SessionSpec("s2", 640, 360, "jpeg")) is False


def test_no_power_budget_means_axis_off():
    """Default (power_budget_w None): watts never refuse, whatever the
    heartbeats report — byte-for-byte the pre-ISSUE-14 scheduler."""
    sched = SeatScheduler(clock=Clock(), recorder=FlightRecorder())
    sched.observe(_power_hb(watts=999_999.0))
    assert sched.place(SessionSpec("s1", 1920, 1080, "h264")) is not None


def test_hbm_refusal_queues_with_incident_not_dropped():
    fleet, sched, coord, rec = make_rig()
    # one host, big seat count, tiny HBM: the SECOND 1080p cannot fit
    add_host(fleet, "h0", seat_slots=8,
             hbm_limit_mb=1.5 * estimate_hbm_mb(1920, 1080))
    fleet.tick(0.5)
    p1 = sched.place(SessionSpec("s1", 1920, 1080))
    assert p1 is not None
    p2 = sched.place(SessionSpec("s2", 1920, 1080))
    assert p2 is None
    assert "placement_pending" in incident_kinds(rec)
    assert len(sched.pending) == 1           # queued, not dropped
    # freeing s1 retries the queue: s2 lands in the freed budget
    sched.release("s1")
    assert sched.get("s2") is not None
    assert not sched.pending


def test_pixel_budget_is_a_real_axis():
    fleet, sched, coord, rec = make_rig()
    # plenty of HBM and seats, pixel budget for ONE 1080p only
    add_host(fleet, "h0", seat_slots=8, hbm_limit_mb=100000.0,
             pixel_budget=1920 * 1080)
    fleet.tick(0.5)
    assert sched.place(SessionSpec("a", 1920, 1080)) is not None
    assert sched.place(SessionSpec("b", 1280, 720)) is None
    assert len(sched.pending) == 1


def test_cold_host_receives_no_placements_until_ready():
    fleet, sched, coord, rec = make_rig()
    add_host(fleet, "cold", warm_after_s=5.0)
    fleet.tick(1.0)
    assert not sched.hosts["cold"].ready
    assert sched.place(SessionSpec("s1", 640, 360)) is None
    assert len(sched.pending) == 1
    # readiness flips after the simulated prewarm completes; the next
    # heartbeat retries the queue
    fleet.tick(5.0)
    assert sched.hosts["cold"].ready
    p = sched.get("s1")
    assert p is not None and p.host_id == "cold"


def test_warm_host_preferred_over_cold_cache():
    fleet, sched, coord, rec = make_rig()
    add_host(fleet, "warmhost", warm_geometries=("1280x720",))
    add_host(fleet, "coldcache")
    fleet.tick(0.5)
    for i in range(4):
        p = sched.place(SessionSpec(f"s{i}", 1280, 720))
        assert p is not None and p.host_id == "warmhost", \
            f"s{i} landed on {p.host_id}"


def test_evict_hysteresis_one_blip_never_moves():
    fleet, sched, coord, rec = make_rig(evict_confirm=3,
                                        evict_hold_s=10.0)
    burner = add_host(fleet, "burner")
    add_host(fleet, "calm")
    fleet.tick(0.5)
    p = sched.place(SessionSpec("s1", 640, 360))
    assert p.host_id in ("burner", "calm")
    victim_host = fleet.hosts[p.host_id]
    # ONE burning heartbeat: no eviction
    victim_host.slo_burning = True
    fleet.tick(0.5)
    assert sched.evictions() == []
    victim_host.slo_burning = False
    fleet.tick(0.5)      # healthy heartbeat resets the streak
    victim_host.slo_burning = True
    fleet.tick(0.5)
    fleet.tick(0.5)
    assert sched.evictions() == []           # streak 2 < confirm 3
    fleet.tick(0.5)
    evs = sched.evictions()
    assert [e.sid for e in evs] == ["s1"]    # sustained burn selects
    assert "seat_evict" not in incident_kinds(rec)  # selection is pure
    moves = coord.rebalance()                # the MOVE records it
    assert moves and moves[0]["moved"]
    assert "seat_evict" in incident_kinds(rec)
    assert sched.total_evictions == 1
    # the move starts the hold: still burning, but no re-evict inside it
    sched.note_migration(p.host_id)
    fleet.tick(0.5)
    fleet.tick(0.5)
    fleet.tick(0.5)
    fleet.tick(0.5)
    assert sched.evictions() == []
    assert burner is not None


def test_pending_queue_is_fifo_and_incidents_dont_inflate():
    """A big session at the head must not be rotated behind smaller
    ones on every heartbeat retry, and retries must not re-emit
    placement_pending per sweep."""
    fleet, sched, coord, rec = make_rig()
    add_host(fleet, "h0", seat_slots=8,
             hbm_limit_mb=1.05 * estimate_hbm_mb(1920, 1080))
    fleet.tick(0.5)
    assert sched.place(SessionSpec("big0", 1920, 1080)) is not None
    assert sched.place(SessionSpec("big1", 1920, 1080)) is None
    assert sched.place(SessionSpec("small", 640, 360)) is None
    assert [s.sid for s, _ in sched.pending] == ["big1", "small"]
    before = incident_kinds(rec).count("placement_pending")
    for _ in range(5):
        fleet.tick(0.5)       # retries with no new capacity
    assert [s.sid for s, _ in sched.pending] == ["big1", "small"]
    assert incident_kinds(rec).count("placement_pending") == before
    # capacity frees (host teardown lands on the next heartbeat): the
    # HEAD places first even though 'small' would fit too
    sched.release("big0")
    fleet.tick(0.5)
    assert sched.get("big1") is not None


def test_evict_with_no_feasible_target_stays_put_untouched():
    fleet, sched, coord, rec = make_rig(evict_confirm=2)
    only = add_host(fleet, "only")
    fleet.tick(0.5)
    sched.place(SessionSpec("s1", 640, 360))
    resyncs = only.idr_resyncs
    only.slo_burning = True
    fleet.tick(0.5)
    fleet.tick(0.5)
    moves = coord.rebalance()
    assert moves and not moves[0]["moved"] and not moves[0]["queued"]
    assert moves[0]["to"] == "only"          # stayed
    p = sched.get("s1")
    assert p is not None and p.host_id == "only"
    # no release/re-accept cycle: no gratuitous IDR storm
    assert only.idr_resyncs == resyncs


def test_drained_host_rejoins_after_restart():
    fleet, sched, coord, rec = make_rig()
    h = add_host(fleet, "h0")
    fleet.tick(0.5)
    fleet.tick(0.5)      # seq advances past the fresh process's first
    sched.mark_draining("h0")
    assert sched.place(SessionSpec("s1", 640, 360)) is None
    # the host process restarts: fresh supervisor, seq counter resets
    fleet.hosts["h0"] = SimHost("h0", clock=fleet.clock, devices=1,
                                seat_slots=4, hbm_limit_mb=1000.0,
                                warm_after_s=0.0, grace_s=3.0,
                                recorder=rec)
    coord.register_host("h0", fleet.hosts["h0"])
    fleet.tick(0.5)
    assert not sched.hosts["h0"].draining
    assert sched.get("s1") is not None       # queued session lands
    assert h is not None


def test_rebalance_moves_burning_hosts_session():
    fleet, sched, coord, rec = make_rig(evict_confirm=2)
    a = add_host(fleet, "a", warm_geometries=("640x360",))
    add_host(fleet, "b")
    fleet.tick(0.5)
    p = sched.place(SessionSpec("s1", 640, 360))
    assert p.host_id == "a"                  # warm bonus
    a.slo_burning = True
    fleet.tick(0.5)
    fleet.tick(0.5)
    moves = coord.rebalance()
    assert len(moves) == 1 and moves[0]["moved"]
    assert sched.get("s1").host_id == "b"
    assert fleet.hosts["b"].idr_resyncs >= 1


def test_evict_off_handleless_host_fires_source_release_callback():
    """An HTTP-only host has no in-process handle, so an evict move
    cannot tell the source engine to end the seat — the coordinator
    must fire ``on_source_release`` so the gateway can kick its own
    proxied client socket with the migrate command. Without it the
    client keeps streaming from the old host forever: the placement
    sits as a ghost on the target while the source's session floor
    blocks its slots (ISSUE 20 chaos soak deadlock)."""
    fleet, sched, coord, rec = make_rig(evict_confirm=2)
    a = add_host(fleet, "a", warm_geometries=("640x360",))
    add_host(fleet, "b")
    coord.handles.pop("a")        # "a" is reachable over HTTP only
    kicked = []
    coord.on_source_release = \
        lambda host, sid: kicked.append((host, sid))
    fleet.tick(0.5)
    p = sched.place(SessionSpec("s1", 640, 360))
    assert p.host_id == "a"
    a.slo_burning = True
    fleet.tick(0.5)
    fleet.tick(0.5)
    moves = coord.rebalance()
    assert len(moves) == 1 and moves[0]["moved"]
    assert sched.get("s1").host_id == "b"
    assert kicked == [("a", "s1")]


def test_host_expiry_marks_lost():
    fleet, sched, coord, rec = make_rig(host_timeout_s=2.0)
    h = add_host(fleet, "h0")
    fleet.tick(0.5)
    h.kill()
    fleet.tick(3.0)
    assert sched.hosts["h0"].lost
    assert "host_lost" in incident_kinds(rec)


def test_forget_drops_host_but_refuses_while_placed():
    fleet, sched, coord, rec = make_rig()
    add_host(fleet, "h0")
    add_host(fleet, "h1")
    fleet.tick(0.5)
    p = sched.place(SessionSpec("s1", 640, 360))
    # seated host refuses to be forgotten (actuator backstop)
    assert sched.forget(p.host_id) is False
    assert p.host_id in sched.hosts
    coord.evacuate(p.host_id)
    assert sched.forget(p.host_id) is True
    assert p.host_id not in sched.hosts
    assert "host_forgotten" in incident_kinds(rec)
    # the other host's capacity keeps serving; a forgotten id could
    # even re-register on a fresh heartbeat — books simply restart
    assert sched.place(SessionSpec("s2", 640, 360)) is not None


# --------------------------------------------------------------- migration

def test_drain_migrates_every_seat_with_idr_resync():
    fleet, sched, coord, rec = make_rig()
    src = add_host(fleet, "src")
    dst = add_host(fleet, "dst")
    fleet.tick(0.5)
    for i in range(3):
        sched.place(SessionSpec(f"s{i}", 640, 360))
    on_src = sched.placements_on("src")
    report = coord.evacuate("src")
    assert report["seats"] == len(on_src)
    assert report["migrated"] == len(on_src)
    assert report["dropped"] == 0 and report["queued"] == 0
    assert report["drained"] is True         # supervisor drain awaited
    assert not sched.placements_on("src")
    assert dst.idr_resyncs >= len(on_src)    # every handoff resynced
    # source kept the handed-off captures warm through the grace
    assert not src.teardowns_seen
    # a drained host takes no NEW placements
    fleet.tick(0.5)
    p = sched.place(SessionSpec("late", 640, 360))
    assert p is not None and p.host_id == "dst"


def test_drain_with_no_capacity_queues_never_drops():
    fleet, sched, coord, rec = make_rig()
    add_host(fleet, "solo", seat_slots=2)
    fleet.tick(0.5)
    sched.place(SessionSpec("s1", 640, 360))
    sched.place(SessionSpec("s2", 640, 360))
    report = coord.evacuate("solo")
    assert report["migrated"] == 0
    assert report["queued"] == 2 and report["dropped"] == 0
    assert len(sched.pending) == 2
    # a fresh host appears: the queue lands on its first heartbeat
    add_host(fleet, "rescue")
    fleet.tick(0.5)
    assert not sched.pending
    assert {p.host_id for p in sched.placements.values()} == {"rescue"}


def test_failover_replaces_within_reconnect_grace():
    fleet, sched, coord, rec = make_rig(host_timeout_s=2.0,
                                        grace_s=3.0)
    doomed = add_host(fleet, "doomed")
    add_host(fleet, "survivor")
    fleet.tick(0.5)
    sids = [f"s{i}" for i in range(3)]
    for sid in sids:
        sched.place(SessionSpec(sid, 640, 360))
    on_doomed = [p.sid for p in sched.placements_on("doomed")]
    doomed.kill()
    # heartbeat silence passes the timeout inside the grace window
    fleet.tick(2.5)
    for sid in on_doomed:
        p = sched.get(sid)
        assert p is not None and p.host_id == "survivor"
    fo = [e for e in rec.snapshot() if e["kind"] == "host_failover"]
    assert fo and fo[0]["replaced"] == len(on_doomed)
    assert fo[0]["within_grace"] == len(on_doomed)


def test_cross_host_dead_relay_reoffer_round_trip():
    """The PR-5 dead-relay re-offer made fleet-wide: local supervision
    exhausts its restart budget against a persistently-dead relay, the
    give-up hook escalates to the coordinator, and the seat re-offers
    on ANOTHER host with an IDR resync."""
    fleet, sched, coord, rec = make_rig()
    a = add_host(fleet, "a", warm_geometries=("640x360",))
    b = add_host(fleet, "b")
    fleet.tick(0.5)
    p = sched.place(SessionSpec("s1", 640, 360))
    assert p.host_id == "a"
    a.kill_relay("s1", unrecoverable=True)
    # pump the injected clock until the local budget parks the relay
    # and the fleet re-offer lands (policy: base 0.1 s, 2 restarts)
    ok = fleet.run_until(
        lambda: sched.get("s1") is not None
        and sched.get("s1").host_id == "b", dt=0.5, budget_s=30.0)
    assert ok, "seat never re-offered cross-host"
    assert b.idr_resyncs >= 1
    kinds = incident_kinds(rec)
    assert "relay_reoffer_cross_host" in kinds
    assert "crash_loop" in kinds             # the local park is visible
    assert "s1" in b.sessions and "s1" not in a.sessions


# ------------------------------------------------------- supervisor drain

def test_supervisor_drain_completes_when_components_drop():
    clock = Clock()
    sched_seam = []
    sup = Supervisor(recorder=FlightRecorder(),
                     policy_factory=lambda: RestartPolicy(clock=clock),
                     schedule=lambda d, cb: sched_seam.append((d, cb))
                     or _Handle(sched_seam))
    sup.adopt("a", lambda: None)
    sup.adopt("b", lambda: None)
    handle = sup.drain()
    assert not handle.done
    sup.drop("a")
    assert not handle.done
    sup.drop("b")
    assert handle.done and handle.wait(0)
    # idempotent: same handle back
    assert sup.drain() is handle


class _Handle:
    def __init__(self, seam):
        self._seam = seam

    def cancel(self):
        pass


def test_supervisor_drain_stops_restarting_and_counts_deaths():
    clock = Clock()
    pending = []

    class H:
        def __init__(self, entry):
            self.entry = entry

        def cancel(self):
            if self.entry in pending:
                pending.remove(self.entry)

    def schedule(delay, cb):
        entry = (delay, cb)
        pending.append(entry)
        return H(entry)

    sup = Supervisor(recorder=FlightRecorder(),
                     policy_factory=lambda: RestartPolicy(clock=clock),
                     schedule=schedule)
    sup.adopt("backing", lambda: None)
    sup.adopt("running", lambda: None)
    sup.report_death("backing", "died pre-drain")
    assert len(pending) == 1                 # restart scheduled
    handle = sup.drain()
    # the pending restart was cancelled and the dead component counts
    # as stopped; only 'running' holds the drain open
    assert not pending
    assert not handle.done
    sup.report_death("running", "died during drain")
    assert handle.done
    # a death during drain never schedules a restart
    assert not pending
    assert sup.get("running").state == "stopped"


def test_supervisor_scoped_drain_ignores_control_plane():
    """ISSUE 19: a host evacuation drains the SEAT-SERVING components
    only — the control plane (service, prewarm, fleet heartbeat push)
    must outlive the drain so the gateway can watch it finish."""
    clock = Clock()
    pending = []

    class H:
        def __init__(self, entry):
            self.entry = entry

        def cancel(self):
            if self.entry in pending:
                pending.remove(self.entry)

    def schedule(delay, cb):
        entry = (delay, cb)
        pending.append(entry)
        return H(entry)

    sup = Supervisor(recorder=FlightRecorder(),
                     policy_factory=lambda: RestartPolicy(clock=clock),
                     schedule=schedule)
    sup.adopt("capture:__seats__", lambda: None)
    sup.adopt("relay:1:seat0", lambda: None)
    sup.adopt("fleet_push", lambda: None)
    sup.adopt("prewarm", lambda: None)
    handle = sup.drain(
        scope=lambda n: n.startswith(("capture:", "relay:")))
    # control-plane components still running do NOT hold the handle
    assert not handle.done
    sup.drop("relay:1:seat0")
    sup.drop("capture:__seats__")
    assert handle.done and handle.wait(0)
    # an out-of-scope death DURING the scoped drain still restarts —
    # a heartbeat-push crash mid-evacuation must not silence the host
    sup.report_death("fleet_push", "push loop died")
    assert len(pending) == 1
    assert sup.get("fleet_push").state == "backing_off"
    # ... and firing the restart is not suppressed by draining
    pending[0][1]()
    assert sup.get("fleet_push").state == "running"


def test_supervisor_scoped_drain_counts_in_scope_deaths_as_stops():
    clock = Clock()
    sup = Supervisor(recorder=FlightRecorder(),
                     policy_factory=lambda: RestartPolicy(clock=clock),
                     schedule=lambda d, cb: _Handle(None))
    sup.adopt("capture:seat0", lambda: None)
    sup.adopt("fleet_push", lambda: None)
    handle = sup.drain(scope=lambda n: n.startswith("capture:"))
    assert not handle.done
    sup.report_death("capture:seat0", "stopped by grace window")
    assert handle.done
    assert sup.get("capture:seat0").state == "stopped"
    assert sup.get("fleet_push").state == "running"


async def test_supervisor_drain_handle_is_awaitable():
    sup = Supervisor(recorder=FlightRecorder(),
                     schedule=lambda d, cb: _Handle(None))
    sup.adopt("x", lambda: None)
    handle = sup.drain()

    async def _finish():
        await asyncio.sleep(0)
        # completion signalled from another thread, like a capture join
        t = threading.Thread(target=lambda: sup.drop("x"))
        t.start()
        t.join()

    waiter = asyncio.ensure_future(asyncio.wait_for(_await(handle), 5.0))
    await _finish()
    await waiter
    assert handle.done


async def _await(handle):
    await handle


# ----------------------------------------------------- prewarm ready gate

def test_worker_current_op_ready_lifecycle():
    import types

    from selkies_tpu.prewarm.lattice import lattice_from_settings
    from selkies_tpu.prewarm.worker import PrewarmWorker
    plan = lattice_from_settings(types.SimpleNamespace(
        encoder="jpeg-tpu", initial_width=640, initial_height=360,
        tpu_seats=1, fullcolor=False, stripe_height=64,
        use_damage_gating=True, use_paint_over=False))
    w = PrewarmWorker(plan, compiler=lambda sig: {"programs": []})
    # cold boot: no operating point recorded yet -> failed
    assert w.current_op_ready().status == "failed"
    w.note_operating_point(640, 360)
    v = w.current_op_ready()
    assert v.status == "failed" and "cold" in v.reason
    assert "640x360" not in w.warm_geometries()
    w.run_pending_sync()
    assert w.current_op_ready().status == "ok"
    assert "640x360" in w.warm_geometries()
    # an operating point outside the lattice fails OPEN
    w.note_operating_point(123, 77)
    assert w.current_op_ready().status == "ok"


def test_empty_worker_gate_opens():
    from selkies_tpu.prewarm.worker import PrewarmWorker
    w = PrewarmWorker()
    assert w.current_op_ready().status == "ok"


# ------------------------------------------------------------ sim heartbeat

def test_sim_heartbeats_flow_through_strict_parse():
    fleet, sched, coord, rec = make_rig()
    add_host(fleet, "h0")
    add_host(fleet, "h1", warm_after_s=1.0)
    for _ in range(5):
        fleet.tick(0.5)
    assert fleet.heartbeats_rejected == 0
    assert fleet.heartbeats_sent >= 9
    assert set(sched.hosts) == {"h0", "h1"}


def test_incidents_carry_host_id():
    rec = FlightRecorder()
    e = rec.record("test_kind", detail=1)
    assert isinstance(e["host"], str) and e["host"]
    from selkies_tpu.compile_cache import host_id
    assert e["host"] == host_id()


# ------------------------------------------------- server contract (HTTP)

def _make_server(**fields):
    from test_server import make_app
    return make_app(**fields)


async def test_probe_ready_gates_on_prewarm(client_factory):
    """ISSUE 11 satellite + acceptance: ?probe=ready answers failed
    until the prewarm worker warmed the CURRENT operating point — a
    load balancer never routes onto a cold host — while the default
    /api/health report stays about process health."""
    server, svc, fake, _ = _make_server()
    c = await client_factory(server)
    # default health: fine (the gate is probe-scope only)
    r = await c.get("/api/health")
    assert r.status == 200 and (await r.json())["ok"] is True
    # readiness probe: cold boot -> failed (no op recorded yet)
    r = await c.get("/api/health?probe=ready")
    body = await r.json()
    assert r.status == 503 and body["ready"] is False
    assert "prewarm_ready" in body["failing"]
    # operating point known but still cold -> still failed
    server.prewarm.note_operating_point(
        server.settings.initial_width, server.settings.initial_height)
    r = await c.get("/api/health?probe=ready")
    assert r.status == 503
    # warm the lattice (fake compiler, synchronously) -> ready
    server.prewarm.compiler = lambda sig: {"programs": []}
    server.prewarm.run_pending_sync()
    r = await c.get("/api/health?probe=ready")
    body = await r.json()
    assert r.status == 200 and body["ready"] is True
    # liveness never saw the gate
    r = await c.get("/api/health?probe=live")
    assert r.status == 200


async def test_probe_ready_without_prewarm_passes(client_factory):
    server, svc, fake, _ = _make_server(enable_prewarm=False)
    c = await client_factory(server)
    r = await c.get("/api/health?probe=ready")
    assert r.status == 200 and (await r.json())["ready"] is True


async def test_api_fleet_emits_parseable_heartbeat(client_factory):
    server, svc, fake, _ = _make_server()
    c = await client_factory(server)
    r = await c.get("/api/fleet")
    assert r.status == 200
    doc = await r.json()
    hb = parse_heartbeat(doc)          # the REAL wire parser
    assert hb.ready is False           # cold host (prewarm not run)
    assert hb.draining is False
    assert hb.fingerprint
    # warming flips the heartbeat's ready bit too
    server.prewarm.note_operating_point(
        server.settings.initial_width, server.settings.initial_height)
    server.prewarm.compiler = lambda sig: {"programs": []}
    server.prewarm.run_pending_sync()
    hb2 = parse_heartbeat(await (await c.get("/api/fleet")).json())
    assert hb2.ready is True
    assert hb2.seq > hb.seq
    geo = f"{server.settings.initial_width}" \
          f"x{server.settings.initial_height}"
    assert geo in hb2.warm_geometries


async def test_drain_flips_readiness_and_notifies_clients(client_factory):
    server, svc, fake, _ = _make_server()
    c = await client_factory(server)
    # a connected viewer that must hear about the migration
    ws = await c.ws_connect("/api/websockets")
    assert (await ws.receive_str()) == "MODE websockets"
    r = await c.post("/api/drain",
                     json={"target_url": "wss://gw.example/fleet/ws"})
    body = await r.json()
    assert r.status == 200 and body["draining"] is True
    assert body["clients_notified"] == 1
    # readiness fails immediately; liveness and default health hold
    r = await c.get("/api/health?probe=ready")
    assert r.status == 503
    assert "draining" in (await r.json())["failing"]
    assert (await c.get("/api/health?probe=live")).status == 200
    # the client got its personal migrate command
    saw = None
    for _ in range(8):
        msg = await asyncio.wait_for(ws.receive_str(), 5.0)
        if msg.startswith("migrate,"):
            saw = json.loads(msg.split(",", 1)[1])
            break
    assert saw is not None
    assert saw["url"] == "wss://gw.example/fleet/ws"
    assert saw["resync"] is True
    # heartbeat now says draining (gateway-side: drops from feasible)
    hb = parse_heartbeat(await (await c.get("/api/fleet")).json())
    assert hb.draining is True and hb.ready is False
    await ws.close()


# ---------------------------------------------------- gateway contract

async def _gateway_client(gw):
    from aiohttp.test_utils import TestClient, TestServer
    client = TestClient(TestServer(gw.make_app()))
    await client.start_server()
    return client


async def test_gateway_cold_host_gets_no_placements():
    """Acceptance: a cold host behind the gateway receives no
    placements until its readiness probe passes."""
    from selkies_tpu.fleet.gateway import FleetGateway
    clock = Clock()
    gw = FleetGateway(clock=clock, sweep_interval_s=3600.0)
    c = await _gateway_client(gw)
    try:
        cold = Heartbeat(host_id="cold-1", url="http://cold:8080",
                         ready=False)
        cold.devices.append(DeviceCapacity(
            id=0, hbm_limit_mb=8192.0, seat_slots=4))
        r = await c.post("/fleet/heartbeat", data=cold.to_json())
        assert r.status == 200
        r = await c.post("/fleet/place", json={
            "v": 1, "kind": "place", "sid": "s1",
            "width": 640, "height": 360})
        assert r.status == 202            # queued: only host is cold
        assert (await r.json())["queued"] is True
        # readiness passes -> the queued session lands on it
        cold.ready = True
        cold.seq = 2
        r = await c.post("/fleet/heartbeat", data=cold.to_json())
        assert r.status == 200
        r = await c.get("/fleet/route/s1")
        assert r.status == 200
        body = await r.json()
        assert body["host_id"] == "cold-1"
        assert body["url"] == "http://cold:8080"
    finally:
        await c.close()


async def test_gateway_auth_and_malformed_heartbeat():
    from selkies_tpu.fleet.gateway import FleetGateway
    gw = FleetGateway(token="sekrit", sweep_interval_s=3600.0)
    c = await _gateway_client(gw)
    try:
        r = await c.post("/fleet/heartbeat", data="{}")
        assert r.status == 401            # no token
        hdr = {"Authorization": "Bearer sekrit"}
        r = await c.post("/fleet/heartbeat", data="not json {",
                         headers=hdr)
        assert r.status == 400
        assert gw.heartbeats_rejected == 1
        r = await c.get("/fleet/hosts", headers=hdr)
        assert r.status == 200
        assert (await r.json())["hosts"] == {}
        assert (await c.get("/fleet/hosts",
                            headers={"Authorization": "Bearer nope"})
                ).status == 401
    finally:
        await c.close()


# ------------------------------------------------------------ perf ledger

def test_perf_ledger_entries_carry_host_id():
    import sys as _sys
    _sys.path.insert(0, str(__import__("pathlib").Path(
        __file__).resolve().parent.parent))
    from tools.perf_ledger import entry_from_bench

    from selkies_tpu.compile_cache import host_id
    e = entry_from_bench({"metric": "encode_fps_640x360_jpeg_tpu",
                          "value": 1.0,
                          "backend_health": {"status": "ok"}})
    assert e["host_id"] == host_id()
