"""Heartbeat intake fuzz through the REAL gateway HTTP handler
(ISSUE 19 satellite): seeded random poison on every heartbeat axis —
incident digests, ``egress_mbps_est``/``watts_est``, the clocksync
echo — POSTed over the aiohttp test transport. The contract: the edge
answers 200 or 400 (never a 5xx), every rejection lands in the bounded
``rejection_kind`` vocabulary, and no poisoned value ever reaches the
scheduler, the observer's series rings, or a clocksync estimator."""

import json
import math
import random

from selkies_tpu.fleet.gateway import FleetGateway

TOKEN = "fuzz-token"
HDR = {"Authorization": f"Bearer {TOKEN}"}

#: every label note_heartbeat_reject may be fed (protocol.py
#: _REJECTION_KINDS + the fallback)
REJECTION_VOCAB = {"bad_json", "bad_kind", "bad_version",
                   "missing_field", "bad_number", "out_of_range",
                   "bad_enum", "bad_ident", "bad_shape", "other"}


class Clock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def base_doc(host="fuzz-h0", seq=1):
    return {
        "v": 1, "kind": "heartbeat", "host_id": host, "seq": seq,
        "ts": 1000.0 + seq, "url": f"http://{host}:8080",
        "ready": True, "health": "ok",
        "slo": {"status": "ok", "fast_burn": 0.5},
        "watts_est": 41.5, "egress_mbps_est": 120.0,
        "devices": [{"id": 0, "hbm_limit_mb": 8192.0,
                     "hbm_used_mb": 512.0, "seat_slots": 4,
                     "seats_used": 1}],
        "incidents": [{"kind": "relay_death", "count": 2}],
        "clock": [999.0, 1000.1, 1000.2, 999.4],
    }


#: poison values thrown at each fuzzed field — type confusion, range
#: escapes, IEEE specials (json.loads accepts NaN/Infinity, so they DO
#: reach the validator), oversize payloads, nested junk
POISONS = [
    None, True, -1, -1e18, 1e18, float("nan"), float("inf"),
    -float("inf"), "", "x" * 4096, "1; DROP TABLE hosts", [],
    [[[[[]]]]], {}, {"k": {"k": {"k": {}}}}, [None] * 64, 3.5j.__repr__(),
]

#: fields fuzzed one at a time on top of a valid document
FUZZ_FIELDS = [
    "v", "kind", "host_id", "seq", "ts", "url", "ready", "health",
    "slo", "watts_est", "egress_mbps_est", "devices", "incidents",
    "clock",
]


async def _client(gw):
    from aiohttp.test_utils import TestClient, TestServer
    c = TestClient(TestServer(gw.make_app()))
    await c.start_server()
    return c


def _poisoned_payloads(rng):
    """One valid doc per (field, poison) pair plus structured near-miss
    mutants for the nested axes (the single-field swaps above cannot
    reach e.g. clock arity or duplicate incident kinds)."""
    out = []
    for field in FUZZ_FIELDS:
        for poison in rng.sample(POISONS, 8):
            doc = base_doc(seq=len(out) + 10)
            doc[field] = poison
            out.append(json.dumps(doc))
    nested = [
        {"slo": {"status": "sideways"}},
        {"slo": {"status": "ok", "fast_burn": float("nan")}},
        {"slo": {"status": "ok", "fast_burn": -3.0}},
        {"watts_est": 2e6},                      # above the 1 MW ceiling
        {"egress_mbps_est": float("inf")},
        {"clock": [1.0, 2.0, 3.0]},              # wrong arity
        {"clock": [1.0, 2.0, 3.0, "four"]},
        {"clock": [1.0, 2.0, 3.0, float("nan")]},
        {"clock": [-5.0, 2.0, 3.0, 4.0]},
        {"incidents": [{"kind": "x", "count": 1},
                       {"kind": "x", "count": 2}]},   # duplicate kind
        {"incidents": [{"kind": "", "count": 1}]},
        {"incidents": [{"kind": "x", "count": -2}]},
        {"incidents": [{"kind": "x", "count": float("nan")}]},
        {"incidents": [{"count": 1}]},           # kind missing
        {"devices": [{"hbm_limit_mb": float("nan")}]},
        {"devices": [{"hbm_limit_mb": -1.0}]},
        {"devices": ["not-an-object"]},
        {"host_id": "", "url": "http://x"},
        {"v": 99},                               # future protocol
    ]
    for i, patch in enumerate(nested):
        doc = base_doc(seq=1000 + i)
        doc.update(patch)
        out.append(json.dumps(doc))
    # frame-level garbage: not even JSON objects
    out += ["", "not json {", "[1,2,3]", '"string"', "null",
            "{" * 2000, json.dumps([base_doc()])]
    return out


async def test_fuzzed_heartbeats_never_crash_or_poison_the_gateway():
    rng = random.Random(0xF1EE7)
    clock = Clock()
    gw = FleetGateway(token=TOKEN, clock=clock,
                      sweep_interval_s=3600.0)
    c = await _client(gw)
    try:
        # a healthy baseline host first, so "poison reached the
        # scheduler" is distinguishable from "scheduler is empty"
        r = await c.post("/fleet/heartbeat",
                         data=json.dumps(base_doc(host="good-h", seq=1)),
                         headers=HDR)
        assert r.status == 200
        accepted, rejected = 1, 0
        for payload in _poisoned_payloads(rng):
            clock.now += 0.1
            r = await c.post("/fleet/heartbeat", data=payload,
                             headers=HDR)
            assert r.status in (200, 400), \
                f"edge must answer 200/400, got {r.status} for " \
                f"{payload[:120]!r}"
            if r.status == 200:
                accepted += 1
            else:
                rejected += 1
        assert gw.heartbeats_rejected == rejected and rejected > 50
        assert gw.heartbeats_ok == accepted

        # every rejection classified onto the bounded vocabulary
        roll = gw.observer.rollup()
        rejects = roll["fleet"]["slo"]["gateway"]["rejects"]
        assert rejects and set(rejects) <= REJECTION_VOCAB
        assert sum(rejects.values()) == rejected

        # nothing poisoned crossed the parse: every scheduler-held
        # host carries finite numbers only
        for hid, host in gw.scheduler.hosts.items():
            hb = host.heartbeat
            for val in (hb.watts_est, hb.egress_mbps_est,
                        hb.slo_fast_burn):
                assert val is None or math.isfinite(val), (hid, val)
            for d in hb.devices:
                assert math.isfinite(d.hbm_limit_mb)
                assert d.hbm_limit_mb >= 0
        # ... and the series rings stay finite (the autoscaler reads
        # these blind; every accepted heartbeat sampled them)
        for name in ("seat_occupancy", "watts_est", "burn_fast_max",
                     "queue_depth"):
            for _, v in gw.observer.series(name, window_s=3600.0):
                assert math.isfinite(v), (name, v)
        # clocksync estimators only exist for hosts whose clock echo
        # validated — and hold finite mappings
        for hid, est in gw._clocksync.items():
            q = est.quality()
            for k in ("offset_ms", "error_bound_ms"):
                if q.get(k) is not None:
                    assert math.isfinite(q[k]), (hid, q)

        # the surfaces behind the intake still answer
        for path in ("/fleet/hosts", "/fleet/obs", "/fleet/metrics"):
            r = await c.get(path, headers=HDR)
            assert r.status == 200, path
    finally:
        await c.close()


async def test_rejected_heartbeat_keeps_the_claimed_host_as_a_lead():
    """A refused document still names its claimed sender in the reject
    note — the operator's first lead on a misbehaving host."""
    gw = FleetGateway(token=TOKEN, sweep_interval_s=3600.0)
    c = await _client(gw)
    try:
        doc = base_doc(host="suspect-h")
        doc["watts_est"] = float("nan")
        r = await c.post("/fleet/heartbeat", data=json.dumps(doc),
                         headers=HDR)
        assert r.status == 400
        roll = gw.observer.rollup()
        last = roll["fleet"]["slo"]["gateway"]["last_reject"]
        assert last["host_id"] == "suspect-h"
        assert last["kind"] in REJECTION_VOCAB
    finally:
        await c.close()
