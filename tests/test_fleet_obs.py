"""Fleet observability plane contracts (ISSUE 18): rollup exact-sum
identities, bounded series rings (the autoscaler signal bus),
incident-digest merging, correlated cross-host migration timelines on
injected clocks, Chrome-trace export round-trips, edge-triggered flood
control, heartbeat-rejection classification, and the Prometheus
host-label cardinality cap — no sleeps anywhere."""

import pytest

from selkies_tpu.fleet.migrate import MigrationCoordinator
from selkies_tpu.fleet.obs import MIGRATION_EVENTS, FleetObserver
from selkies_tpu.fleet.protocol import (FleetProtocolError, SessionSpec,
                                        parse_heartbeat, rejection_kind)
from selkies_tpu.fleet.scheduler import SeatScheduler
from selkies_tpu.fleet.sim import SimFleet, SimHost
from selkies_tpu.obs.health import FlightRecorder
from selkies_tpu.trace.export import timelines_from_events


def make_rig(*, host_timeout_s=3.0, grace_s=6.0, host_label_cap=2,
             failed_hosts=2):
    clock_box = [0.0]
    rec = FlightRecorder()
    sched = SeatScheduler(clock=lambda: clock_box[0], recorder=rec,
                          host_timeout_s=host_timeout_s)
    coord = MigrationCoordinator(sched, clock=lambda: clock_box[0],
                                 recorder=rec, grace_s=grace_s)
    fleet = SimFleet(sched, coord, clock_box=clock_box)
    obs = FleetObserver(sched, coord, clock=lambda: clock_box[0],
                        recorder=rec, host_label_cap=host_label_cap,
                        failed_hosts=failed_hosts)
    fleet.observer = obs
    return fleet, sched, coord, rec, obs


def add_host(fleet, name, *, seat_slots=2, devices=2,
             warm_after_s=0.0, hbm_limit_mb=8192.0):
    return fleet.add_host(SimHost(
        name, clock=fleet.clock, devices=devices, seat_slots=seat_slots,
        hbm_limit_mb=hbm_limit_mb, warm_after_s=warm_after_s,
        warm_geometries=("1280x720",), grace_s=6.0,
        recorder=fleet.scheduler.recorder))


def place_n(sched, n, prefix="s"):
    placed = []
    for i in range(n):
        p = sched.place(SessionSpec(f"{prefix}{i}"))
        assert p is not None
        placed.append(p)
    return placed


# ---------------------------------------------------------------- rollup

class TestRollupIdentities:
    def test_fleet_sums_equal_host_sums(self):
        fleet, sched, _, _, obs = make_rig()
        add_host(fleet, "h0")
        add_host(fleet, "h1")
        add_host(fleet, "h2", warm_after_s=5.0)   # cold
        fleet.tick(0.5)
        place_n(sched, 3)
        fleet.tick(0.5)
        roll = obs.rollup()
        verdict = FleetObserver.check_identities(roll)
        assert verdict["ok"], verdict["clauses"]
        # and the identity check is not vacuous: breaking one host's
        # numbers breaks the re-derivation
        roll["hosts"]["h0"]["seats"]["used"] += 1
        assert not FleetObserver.check_identities(roll)["ok"]

    def test_state_partition_counts_lost_and_draining(self):
        fleet, sched, coord, _, obs = make_rig()
        add_host(fleet, "h0")
        add_host(fleet, "h1")
        add_host(fleet, "h2")
        fleet.tick(0.5)
        coord.evacuate("h0")
        fleet.hosts["h1"].kill()
        fleet.tick(4.0)       # h1 expires
        roll = obs.rollup()
        counts = roll["fleet"]["hosts"]
        assert counts["known"] == 3
        assert counts["lost"] == 1
        assert counts["draining"] == 1
        assert FleetObserver.check_identities(roll)["ok"]
        # unreachable capacity is carved out of the fleet seat slots
        assert roll["fleet"]["capacity"]["unreachable_seat_slots"] > 0


# ---------------------------------------------------------------- series

class TestSeriesRings:
    def test_rings_fill_one_sample_per_tick(self):
        fleet, sched, _, _, obs = make_rig()
        add_host(fleet, "h0")
        add_host(fleet, "h1")
        fleet.tick(0.5)
        place_n(sched, 2)
        for _ in range(4):
            fleet.tick(0.5)
        ring = obs.series("seat_occupancy")
        assert len(ring) == 5          # one per tick, not one per host
        ts = [p[0] for p in ring]
        assert ts == sorted(ts)
        assert "watts_est" in obs.series()
        assert "queue_depth" in obs.series()

    def test_window_trims_to_trailing_seconds(self):
        fleet, sched, _, _, obs = make_rig()
        add_host(fleet, "h0")
        for _ in range(10):
            fleet.tick(1.0)
        full = obs.series("hosts_ready")
        recent = obs.series("hosts_ready", window_s=3.0)
        assert len(full) == 10
        assert len(recent) == 4          # inclusive at now - window
        assert all(ts >= fleet.clock() - 3.0 for ts, _ in recent)

    def test_rings_are_bounded(self):
        fleet, sched, _, _, obs = make_rig()
        obs.series_capacity = 8
        obs._series.clear()
        add_host(fleet, "h0")
        for _ in range(20):
            fleet.tick(0.5)
        assert len(obs.series("seat_occupancy")) == 8


# ------------------------------------------------------- incident digest

class TestIncidentDigest:
    def test_digest_round_trips_the_wire(self):
        fleet, _, _, _, _ = make_rig()
        h = add_host(fleet, "h0")
        h.incident("qoe_collapse", 3)
        h.incident("crash_loop")
        hb = parse_heartbeat(h.heartbeat().to_dict())
        assert {"kind": "qoe_collapse", "count": 3} in hb.incidents
        assert {"kind": "crash_loop", "count": 1} in hb.incidents

    def test_digest_is_strictly_parsed(self):
        fleet, _, _, _, _ = make_rig()
        h = add_host(fleet, "h0")
        doc = h.heartbeat().to_dict()
        doc["incidents"] = [{"kind": "x", "count": -1}]
        with pytest.raises(FleetProtocolError):
            parse_heartbeat(doc)
        doc["incidents"] = [{"kind": "x"}]
        with pytest.raises(FleetProtocolError):
            parse_heartbeat(doc)
        doc["incidents"] = [{"kind": "x", "count": 1}] * 2
        with pytest.raises(FleetProtocolError):
            parse_heartbeat(doc)
        doc["incidents"] = [{"kind": f"k{i}", "count": 1}
                            for i in range(64)]
        with pytest.raises(FleetProtocolError):
            parse_heartbeat(doc)

    def test_merge_is_delta_triggered(self):
        fleet, _, _, rec, _ = make_rig()
        h = add_host(fleet, "h0")
        h.incident("relay_death", 2)
        fleet.tick(0.5)
        fleet.tick(0.5)     # same digest re-beats: silent
        merged = [e for e in rec.snapshot()
                  if e["kind"] == "host_incident"]
        assert len(merged) == 1
        assert merged[0]["incident"] == "relay_death"
        assert merged[0]["delta"] == 2
        h.incident("relay_death")       # count rises -> one more merge
        fleet.tick(0.5)
        merged = [e for e in rec.snapshot()
                  if e["kind"] == "host_incident"]
        assert len(merged) == 2
        assert merged[1]["delta"] == 1


# ----------------------------------------------------- migration tracing

class TestMigrationTimeline:
    def _complete(self, fleet, obs, corr, budget_s=20.0):
        assert fleet.run_until(
            lambda: obs.migration_report(corr)["complete"],
            dt=0.5, budget_s=budget_s)
        return obs.migration_report(corr)

    def test_drain_timeline_round_trip(self):
        fleet, sched, coord, _, obs = make_rig()
        add_host(fleet, "h0")
        add_host(fleet, "h1")
        fleet.tick(0.5)
        place_n(sched, 3)
        fleet.tick(0.5)
        report = coord.evacuate("h0")
        corr = report["correlation_id"]
        assert corr and corr.endswith("-drain")
        mrep = self._complete(fleet, obs, corr)
        assert mrep["ordered"]
        assert len(mrep["seats"]) == 3
        for seat in mrep["seats"]:
            assert seat["events"] == ["drain", "replaced", "reconnect",
                                      "idr_resync", "first_frame"]
            assert seat["to"] == "h1"
        # the Chrome-trace export survives a round trip: the X spans
        # come back on the fleet lane with the correlation id intact
        doc = obs.trace_document(corr)
        rebuilt = timelines_from_events(doc["traceEvents"])
        assert len(rebuilt) == 3
        for tl in rebuilt:
            assert tl["display_id"] == corr
            names = [s["name"] for s in tl["spans"]]
            order = [MIGRATION_EVENTS.index(n) for n in names]
            assert order == sorted(order)
            assert all(s["lane"] == "fleet" for s in tl["spans"])
            assert all(s["dur_ns"] > 0 for s in tl["spans"])

    def test_failover_within_grace_honest_inside_window(self):
        fleet, sched, coord, rec, obs = make_rig(host_timeout_s=2.0,
                                                 grace_s=6.0)
        add_host(fleet, "h0")
        add_host(fleet, "h1")
        fleet.tick(0.5)
        place_n(sched, 2)
        fleet.tick(0.5)
        fleet.hosts["h0"].kill()
        fleet.tick(2.5)       # past timeout, inside grace
        fo = [e for e in rec.snapshot() if e["kind"] == "host_failover"]
        assert fo and fo[-1]["correlation_id"].endswith("-failover")
        mrep = self._complete(fleet, obs, fo[-1]["correlation_id"])
        assert mrep["ordered"]
        for seat in mrep["seats"]:
            assert seat["events"][0] == "lost"
            assert seat["within_grace"] is True

    def test_failover_past_grace_reports_honestly(self):
        # grace BELOW the heartbeat timeout: by the time silence is
        # recognised, the client already saw a teardown — the timeline
        # must say so instead of flattering the fleet
        fleet, sched, coord, rec, obs = make_rig(host_timeout_s=4.0,
                                                 grace_s=1.0)
        add_host(fleet, "h0")
        add_host(fleet, "h1")
        fleet.tick(0.5)
        place_n(sched, 2)
        fleet.tick(0.5)
        fleet.hosts["h0"].kill()
        fleet.tick(5.0)
        fo = [e for e in rec.snapshot() if e["kind"] == "host_failover"]
        assert fo
        mrep = self._complete(fleet, obs, fo[-1]["correlation_id"])
        for seat in mrep["seats"]:
            assert seat["within_grace"] is False

    def test_queued_seat_timeline_advances_on_replacement(self):
        # h1 can't take h0's seats until it warms: the drain queues
        # them, the timeline records the detour, and once capacity
        # appears the heartbeat hook advances queued -> replaced
        fleet, sched, coord, _, obs = make_rig()
        add_host(fleet, "h0")
        add_host(fleet, "h1", warm_after_s=5.0)
        fleet.tick(0.5)
        place_n(sched, 2)
        fleet.tick(0.5)
        report = coord.evacuate("h0")
        assert report["queued"] == 2
        corr = report["correlation_id"]
        events = obs.migration_events_for(report["results"][0]["sid"])
        assert events == ["drain", "queued"]
        mrep = self._complete(fleet, obs, corr)
        for seat in mrep["seats"]:
            assert seat["events"] == ["drain", "queued", "replaced",
                                      "reconnect", "idr_resync",
                                      "first_frame"]
            assert seat["ordered"]

    def test_marks_are_idempotent_and_unknown_sids_ignored(self):
        fleet, sched, coord, _, obs = make_rig()
        add_host(fleet, "h0")
        add_host(fleet, "h1")
        fleet.tick(0.5)
        place_n(sched, 1)
        corr = obs.migration_start("drain", "h0", ["s0"])
        assert obs.migration_mark("s0", "replaced", to_host="h1")
        assert not obs.migration_mark("s0", "replaced", to_host="h1")
        assert not obs.note_reconnect("nobody")
        assert obs.note_reconnect("s0")
        assert obs.note_first_frame("s0")
        assert obs.migration_report(corr)["complete"]
        # completed traces leave the open set
        assert "s0" not in obs.open_migration_sids()

    def test_trace_capacity_bounds_retained_correlations(self):
        fleet, sched, _, _, obs = make_rig()
        obs.trace_capacity = 4
        for i in range(10):
            obs.migration_start("drain", "h0", [f"x{i}"])
        assert len(obs._by_corr) == 4
        assert len(obs.open_migration_sids()) == 4


# -------------------------------------------------------- fleet verdict

class TestFleetSloVerdict:
    def test_verdict_flips_degraded_failed_ok(self):
        fleet, sched, _, _, obs = make_rig(failed_hosts=2)
        add_host(fleet, "h0")
        add_host(fleet, "h1")
        add_host(fleet, "h2")
        fleet.tick(0.5)
        assert obs.rollup()["fleet"]["slo"]["verdict"] == "ok"
        fleet.hosts["h1"].slo_burning = True
        fleet.tick(0.5)
        roll = obs.rollup()
        assert roll["fleet"]["slo"]["verdict"] == "degraded"
        assert roll["fleet"]["slo"]["burning_hosts"] == ["h1"]
        fleet.hosts["h2"].slo_burning = True
        fleet.tick(0.5)
        assert obs.rollup()["fleet"]["slo"]["verdict"] == "failed"
        fleet.hosts["h1"].slo_burning = False
        fleet.hosts["h2"].slo_burning = False
        fleet.tick(0.5)
        assert obs.rollup()["fleet"]["slo"]["verdict"] == "ok"

    def test_lost_hosts_do_not_count_as_burning(self):
        fleet, sched, _, _, obs = make_rig(host_timeout_s=2.0)
        add_host(fleet, "h0")
        h1 = add_host(fleet, "h1")
        fleet.tick(0.5)
        h1.slo_burning = True
        fleet.tick(0.5)
        assert obs.rollup()["fleet"]["slo"]["verdict"] == "degraded"
        h1.kill()
        fleet.tick(3.0)       # h1 expires; its last beat said burning
        roll = obs.rollup()
        assert roll["fleet"]["slo"]["burning_hosts"] == []
        assert roll["fleet"]["slo"]["verdict"] == "ok"

    def test_gateway_own_budget_burns_the_verdict(self):
        fleet, sched, _, _, obs = make_rig()
        add_host(fleet, "h0")
        fleet.tick(0.5)
        assert obs.rollup()["fleet"]["slo"]["verdict"] == "ok"
        # a reject storm at the gateway's intake: ITS budget fails the
        # fleet even with every engine host healthy
        for _ in range(50):
            obs.note_heartbeat_reject("bad_json", "junk", "evil")
            fleet.tick(0.1)
        roll = obs.rollup()
        assert roll["fleet"]["slo"]["gateway"]["status"] == "failed"
        assert roll["fleet"]["slo"]["verdict"] == "failed"
        assert roll["fleet"]["slo"]["gateway"]["rejects"][
            "bad_json"] == 50
        assert roll["fleet"]["slo"]["gateway"]["last_reject"][
            "host_id"] == "evil"


# ------------------------------------------------- rejection classifier

class TestRejectionKind:
    @pytest.mark.parametrize("doc,kind", [
        ("not json at all", "bad_json"),
        ({"kind": "nope"}, "bad_kind"),
        ({"v": 99, "kind": "heartbeat", "host_id": "h"}, "bad_version"),
        ({"v": 1, "kind": "heartbeat"}, "missing_field"),
        ({"v": 1, "kind": "heartbeat", "host_id": "h",
          "watts_est": "hot"}, "bad_number"),
        ({"v": 1, "kind": "heartbeat", "host_id": "h",
          "watts_est": -1}, "out_of_range"),
        ({"v": 1, "kind": "heartbeat", "host_id": "h",
          "health": "meh"}, "bad_enum"),
        ({"v": 1, "kind": "heartbeat", "host_id": ""}, "bad_ident"),
        ({"v": 1, "kind": "heartbeat", "host_id": "h",
          "devices": "x"}, "bad_shape"),
    ])
    def test_bounded_vocabulary(self, doc, kind):
        with pytest.raises(FleetProtocolError) as ei:
            parse_heartbeat(doc)
        assert rejection_kind(ei.value) == kind


# ------------------------------------------------ edge-triggered floods

class TestFloodControl:
    def test_stuck_pending_records_once(self):
        fleet, sched, _, rec, _ = make_rig()
        add_host(fleet, "h0")
        fleet.tick(0.5)
        sched.place(SessionSpec("stuck", 3840, 2160, hbm_mb=1e6))
        for _ in range(6):
            fleet.tick(0.5)   # every heartbeat retries the queue
        records = [e for e in rec.snapshot()
                   if e["kind"] == "placement_pending"
                   and e["sid"] == "stuck"]
        assert len(records) == 1
        # cancel re-arms: a NEW queue episode records again
        assert sched.cancel_pending("stuck")
        sched.place(SessionSpec("stuck", 3840, 2160, hbm_mb=1e6))
        records = [e for e in rec.snapshot()
                   if e["kind"] == "placement_pending"
                   and e["sid"] == "stuck"]
        assert len(records) == 2

    def test_evict_blocked_records_once_per_episode(self):
        # one burning host, nowhere to move: the hysteresis keeps
        # re-selecting the seat every sweep, the incident records once
        fleet, sched, coord, rec, _ = make_rig()
        sched.evict_confirm = 2
        sched.evict_hold_s = 0.0
        add_host(fleet, "h0", seat_slots=1, devices=1)
        fleet.tick(0.5)
        assert sched.place(SessionSpec("s0")) is not None
        fleet.hosts["h0"].slo_burning = True
        for _ in range(6):
            fleet.tick(0.5)
            coord.rebalance()
        blocked = [e for e in rec.snapshot()
                   if e["kind"] == "evict_blocked"]
        assert len(blocked) == 1
        assert blocked[0]["host_id"] == "h0"
        # burn clears -> re-armed -> a fresh episode records again
        fleet.hosts["h0"].slo_burning = False
        fleet.tick(0.5)
        coord.rebalance()
        fleet.hosts["h0"].slo_burning = True
        for _ in range(6):
            fleet.tick(0.5)
            coord.rebalance()
        blocked = [e for e in rec.snapshot()
                   if e["kind"] == "evict_blocked"]
        assert len(blocked) == 2


# ------------------------------------------------- Prometheus export

class TestMetricsCardinality:
    def setup_method(self):
        pytest.importorskip("aiohttp")
        from selkies_tpu.server import metrics
        metrics.clear()
        self.metrics = metrics

    def test_host_labels_capped_with_overflow_rollup(self):
        fleet, sched, _, _, obs = make_rig(host_label_cap=2)
        for i in range(5):
            add_host(fleet, f"h{i}")
        fleet.tick(0.5)
        place_n(sched, 6)
        fleet.tick(0.5)
        obs.export_metrics()
        text = self.metrics.render_prometheus()
        for family in FleetObserver._HOST_FAMILIES:
            lines = [ln for ln in text.splitlines()
                     if ln.startswith(family + "{")]
            labels = {ln.split('host="')[1].split('"')[0]
                      for ln in lines}
            assert len(labels) <= 3, (family, labels)
            assert "_overflow" in labels, (family, labels)
        # the overflow rollup keeps the capacity sums honest: capped
        # series + overflow == the fleet total
        roll = obs.rollup()
        total = 0.0
        for ln in text.splitlines():
            if ln.startswith("selkies_fleet_host_seats_used{"):
                total += float(ln.rsplit(" ", 1)[1])
        assert total == roll["fleet"]["seats"]["used"]

    def test_departed_hosts_do_not_flatline(self):
        fleet, sched, _, _, obs = make_rig(host_label_cap=8)
        add_host(fleet, "h0")
        add_host(fleet, "h1")
        fleet.tick(0.5)
        obs.export_metrics()
        del sched.hosts["h1"]
        obs.export_metrics()
        text = self.metrics.render_prometheus()
        assert 'selkies_fleet_host_up{host="h1"}' not in text

    def test_reject_counter_by_kind(self):
        fleet, _, _, _, obs = make_rig()
        obs.note_heartbeat_reject("bad_json", "junk", "evil")
        obs.note_heartbeat_reject("bad_json", "junk", "evil")
        obs.note_heartbeat_reject("missing_field", "no host_id", "")
        assert self.metrics.counter_value(
            "selkies_fleet_heartbeat_rejects_total",
            {"kind": "bad_json"}) == 2
        assert obs.heartbeat_rejects == {"bad_json": 2,
                                         "missing_field": 1}
        assert obs.last_reject["kind"] == "missing_field"
