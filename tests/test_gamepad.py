"""Gamepad socket-server tests: a fake interposer client (the role the
LD_PRELOAD .so plays for real games) connects to the unix sockets and
validates the config struct and event records — the same check the
reference performs with js-interposer-test.py (SURVEY.md §4.3)."""

import asyncio
import struct

from selkies_tpu.input.backends import NullBackend
from selkies_tpu.input.gamepad import (EV_ABS, EV_KEY, EV_SYN,
                                       GamepadManager, GamepadSocketServer,
                                       JS_EVENT_AXIS, JS_EVENT_BUTTON,
                                       XPAD_AXES, XPAD_BTNS, build_config)
from selkies_tpu.input.handler import InputHandler


def test_config_struct_is_exactly_1360_bytes():
    cfg = build_config()
    assert len(cfg) == 1360
    name = cfg[:255].split(b"\0")[0].decode()
    vendor, product, version, nbtn, naxes = struct.unpack_from("<5H", cfg, 256)
    assert name == "Microsoft X-Box 360 pad"
    assert (vendor, product) == (0x045E, 0x028E)
    assert nbtn == len(XPAD_BTNS) and naxes == len(XPAD_AXES)
    btn_map = struct.unpack_from(f"<{nbtn}H", cfg, 266)
    assert list(btn_map) == XPAD_BTNS


async def _read_exact(reader, n, timeout=5.0):
    return await asyncio.wait_for(reader.readexactly(n), timeout)


def test_js_and_evdev_clients_receive_events(tmp_path):
    async def run():
        srv = GamepadSocketServer(0, str(tmp_path))
        await srv.start()
        jr, _jw = await asyncio.open_unix_connection(srv.js_path)
        er, _ew = await asyncio.open_unix_connection(srv.ev_path)
        assert len(await _read_exact(jr, 1360)) == 1360
        assert len(await _read_exact(er, 1360)) == 1360

        srv.report_button(0, 1.0)        # W3C A -> BTN_A
        t, val, typ, num = struct.unpack("<IhBB", await _read_exact(jr, 8))
        assert (val, typ, num) == (1, JS_EVENT_BUTTON, 0)
        s1 = struct.unpack("<qqHHi", await _read_exact(er, 24))
        syn = struct.unpack("<qqHHi", await _read_exact(er, 24))
        assert s1[2:] == (EV_KEY, XPAD_BTNS[0], 1)
        assert syn[2] == EV_SYN

        srv.report_axis(0, -0.5)         # left stick X
        t, val, typ, num = struct.unpack("<IhBB", await _read_exact(jr, 8))
        assert typ == JS_EVENT_AXIS and num == 0 and -16500 < val < -16000
        ab = struct.unpack("<qqHHi", await _read_exact(er, 24))
        assert ab[2] == EV_ABS and ab[3] == XPAD_AXES[0]

        srv.report_button(12, 1.0)       # dpad up -> HAT0Y = -32767
        t, val, typ, num = struct.unpack("<IhBB", await _read_exact(jr, 8))
        assert typ == JS_EVENT_AXIS and num == 7 and val == -32767

        srv.report_button(6, 0.5)        # LT analog -> ABS_Z ~16383
        t, val, typ, num = struct.unpack("<IhBB", await _read_exact(jr, 8))
        assert typ == JS_EVENT_AXIS and num == 2 and 16000 < val < 16700
        await srv.stop()

    asyncio.run(run())


def test_manager_bridges_input_verbs_to_sockets(tmp_path):
    async def run():
        handler = InputHandler(backend=NullBackend())
        mgr = GamepadManager(handler, str(tmp_path))
        handler.gamepad_manager = mgr
        await handler.on_message("js,c,0,My Pad")
        srv = mgr._servers[0]
        jr, _ = await asyncio.open_unix_connection(srv.js_path)
        cfg = await _read_exact(jr, 1360)
        assert cfg[:255].split(b"\0")[0].decode() == "My Pad"
        await handler.on_message("js,b,0,1,1")       # W3C B pressed
        t, val, typ, num = struct.unpack("<IhBB", await _read_exact(jr, 8))
        assert (val, typ, num) == (1, JS_EVENT_BUTTON, 1)
        await handler.on_message("js,a,0,1,0.25")    # left stick Y
        t, val, typ, num = struct.unpack("<IhBB", await _read_exact(jr, 8))
        assert typ == JS_EVENT_AXIS and num == 1 and 8000 < val < 8300
        await mgr.stop()

    asyncio.run(run())


def test_slow_client_does_not_block_fanout(tmp_path):
    async def run():
        srv = GamepadSocketServer(1, str(tmp_path))
        await srv.start()
        # connect but never read: kernel buffers absorb events; fanout
        # must stay synchronous and non-blocking regardless
        jr, _ = await asyncio.open_unix_connection(srv.js_path)
        await _read_exact(jr, 1360)
        for i in range(5000):
            srv.report_axis(0, (i % 100) / 100.0)
        await srv.stop()

    asyncio.run(run())


async def test_ws_gamepad_verbs_reach_interposer_socket(tmp_path,
                                                        client_factory):
    """End-to-end through the transport: the WS verbs the web client's
    gamepad poller emits (js,c / js,b / js,a) must surface as js-protocol
    events on the interposer unix socket — the path a game's LD_PRELOAD
    shim consumes (VERDICT round-2 item 5's done bar; the server half
    alone was already covered above)."""
    import struct

    from aiohttp import WSMsgType

    from tests.test_server import make_app

    server, svc, fake, handler = make_app()
    handler.gamepad_manager = GamepadManager(handler,
                                             socket_dir=str(tmp_path))
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    # drain whatever preamble the server sends (MODE/cursor/settings)
    while True:
        try:
            msg = await ws.receive(timeout=1.0)
        except (asyncio.TimeoutError, TimeoutError):
            break
        if msg.type != WSMsgType.TEXT:
            break

    # exactly what selkies-client.js sends on gamepadconnected + poll
    await ws.send_str("js,c,0,Probe Pad (Vendor: dead Product: beef)")
    js_path = tmp_path / "selkies_js0.sock"
    for _ in range(100):
        if js_path.exists():
            break
        await asyncio.sleep(0.05)
    assert js_path.exists(), "interposer socket never appeared"

    reader, writer = await asyncio.open_unix_connection(str(js_path))
    cfg = await asyncio.wait_for(reader.readexactly(1360), 5)
    name = cfg.split(b"\0", 1)[0].decode()
    assert "Selkies" in name or "Probe" in name

    await ws.send_str("js,b,0,0,1")          # A pressed
    ev = await asyncio.wait_for(reader.readexactly(8), 5)
    _, value, ev_type, number = struct.unpack("<IhBB", ev)
    assert (value, ev_type) == (1, 0x01)     # JS_EVENT_BUTTON

    await ws.send_str("js,a,0,1,-0.5")       # left stick Y up
    ev = await asyncio.wait_for(reader.readexactly(8), 5)
    _, value, ev_type, number = struct.unpack("<IhBB", ev)
    assert ev_type == 0x02 and value < -10000

    writer.close()
    await ws.close()
    await handler.gamepad_manager.stop()
