"""graftlint (selkies_tpu/analysis/): per-rule firing + non-firing
fixtures, suppression pragmas, the baseline ratchet, CLI contract, and
the repo-wide invariant that current findings ⊆ the checked-in
baseline (i.e. the tree is lint-clean modulo tolerated debt)."""
import json
import textwrap
from pathlib import Path

import pytest

from selkies_tpu.analysis import Analyzer, Severity
from selkies_tpu.analysis.__main__ import main as graftlint_main
from selkies_tpu.analysis.core import make_baseline, new_findings

REPO = Path(__file__).resolve().parent.parent


def run(src: str, path: str = "mod.py", **kw) -> list:
    return Analyzer(**kw).run_source(textwrap.dedent(src), path)


def rule_ids(findings) -> list[str]:
    return [f.rule_id for f in findings]


# -- JAX-HOST-SYNC -----------------------------------------------------------

def test_host_sync_fires_in_jitted_fn():
    f = run("""
        import jax, numpy as np
        @jax.jit
        def step(frame):
            return np.asarray(frame)
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC"]
    assert f[0].line == 5 and "np.asarray" in f[0].message


def test_host_sync_item_and_float_fire():
    f = run("""
        import jax
        @jax.jit
        def step(x):
            a = x.item()
            b = float(x)
            return a + b
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC", "JAX-HOST-SYNC"]


def test_host_sync_int_of_shape_is_fine():
    """int(x.shape[0]) / int(len(x)) are trace-static — no host sync."""
    assert run("""
        import jax
        @jax.jit
        def step(x):
            n = int(x.shape[0])
            m = int(len(x))
            return n + m
        """) == []


def test_host_sync_float_of_static_param_is_fine():
    """float(scale) where scale is in static_argnames is a concrete
    Python value at trace time — no sync, no finding."""
    assert run("""
        import functools, jax
        @functools.partial(jax.jit, static_argnames=("scale",))
        def step(x, scale):
            return x * float(scale)
        """) == []


def test_host_sync_item_on_static_is_fine():
    """static_param.item() and MODULE_CONST.item() are concrete at
    trace time — only tracer .item() syncs."""
    assert run("""
        import functools, jax, numpy as np
        K = np.float32(2.0)
        @functools.partial(jax.jit, static_argnames=("q",))
        def step(x, q):
            return x * q.item() * K.item()
        """) == []


def test_host_sync_trace_time_constants_are_fine():
    """np.array(LITERAL) quant tables, float(math.pi), float(self.k):
    all concrete at trace time — no sync, no finding."""
    assert run("""
        import math
        import jax, numpy as np
        QUANT = [[16, 11], [12, 12]]
        @jax.jit
        def step(x):
            q = np.array([[16, 11], [12, 12]])
            r = np.asarray(QUANT)
            return x * q * r * float(math.pi)
        """) == []


def test_host_sync_static_shape_local_is_fine():
    """Binding a static shape to a local before converting is the same
    as the inline form: n = x.shape[0]; float(n) — no sync."""
    assert run("""
        import jax
        @jax.jit
        def f(x):
            n = x.shape[0]
            m = n * 2
            return x * float(n) * int(m)
        """) == []
    f = run("""
        import jax
        @jax.jit
        def f(x):
            n = x + 1
            return float(n)
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC"]


def test_host_sync_static_loop_vars_are_fine():
    """`for i in range(4)` unrolls at trace time: float(i) syncs
    nothing.  Loops over a traced value stay flagged."""
    assert run("""
        import jax
        @jax.jit
        def f(x):
            acc = 0.0
            for i in range(4):
                acc = acc + float(i)
            ys = [float(i) for i in range(3)]
            return x * acc * sum(ys)
        """) == []
    f = run("""
        import jax
        @jax.jit
        def f(x):
            for v in x:
                y = float(v)
            return y
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC"]


def test_host_sync_silent_outside_hot_code():
    assert run("""
        import numpy as np
        def host_side(frame):
            return np.asarray(frame).item()
        """) == []


def test_host_sync_reaches_module_local_helpers():
    """f called from a jitted body is traced too."""
    f = run("""
        import jax, numpy as np
        def helper(x):
            return np.array(x)
        @jax.jit
        def step(frame):
            return helper(frame)
        """)
    assert "JAX-HOST-SYNC" in rule_ids(f)


def test_host_sync_factory_closure_detected():
    """The repo idiom: jax.jit(build_fn(...)) traces the returned
    closure (engine/encoder.py:121)."""
    f = run("""
        import jax, numpy as np
        def build_fn(w):
            def step(frame):
                return np.asarray(frame)
            return step
        compiled = jax.jit(build_fn(64))
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC"]


# -- JAX-TRACER-BRANCH -------------------------------------------------------

def test_tracer_branch_fires():
    f = run("""
        import jax
        @jax.jit
        def step(x):
            if x > 0:
                return x
            return -x
        """)
    assert rule_ids(f) == ["JAX-TRACER-BRANCH"]


def test_tracer_branch_static_arg_is_fine():
    assert run("""
        import functools, jax
        @functools.partial(jax.jit, static_argnames=("mode",))
        def step(x, mode):
            if mode:
                return x
            return -x
        """) == []


def test_tracer_branch_compound_static_guard_is_fine():
    """`x is not None and x.shape[0] > 4` — both legs are trace-static,
    including inside and/or chains."""
    assert run("""
        import jax
        @jax.jit
        def step(x):
            if x is not None and x.shape[0] > 4:
                return x
            return -x
        """) == []


def test_tracer_branch_shape_and_none_checks_are_fine():
    """x.shape / len(x) / `is None` are static at trace time."""
    assert run("""
        import jax
        @jax.jit
        def step(x, y):
            if x.shape[0] > 8:
                return x
            if y is None:
                return x
            if len(x) > 2:
                return x
            return x
        """) == []


def test_partial_bound_params_are_static():
    """jax.jit(partial(f, mode=...)) binds mode to a concrete value
    (ops/jpeg_pipeline.py idiom) — branching on it is fine."""
    assert run("""
        import functools, jax
        def encode(x, mode):
            if mode == "420":
                return x
            return -x
        def make(mode):
            return jax.jit(functools.partial(encode, mode=mode))
        """) == []


# -- JAX-STATIC-ARG ----------------------------------------------------------

def test_static_arg_fires_on_shape_slot():
    f = run("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def tile(n):
            return jnp.zeros(n)
        """)
    assert rule_ids(f) == ["JAX-STATIC-ARG"]
    assert "'n'" in f[0].message


def test_static_arg_fires_on_range():
    f = run("""
        import jax
        @jax.jit
        def loop(x, n):
            for _ in range(n):
                x = x + 1
            return x
        """)
    assert rule_ids(f) == ["JAX-STATIC-ARG"]


def test_static_arg_declared_static_is_fine():
    assert run("""
        import functools, jax
        import jax.numpy as jnp
        @functools.partial(jax.jit, static_argnums=(0,))
        def tile(n):
            return jnp.zeros(n)
        """) == []


def test_static_arg_functional_reshape_array_arg_is_fine():
    """jnp.reshape(x, shape): arg 0 is the traced array, not a shape —
    only the method form x.reshape(*shape) treats every arg as shape."""
    assert run("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def flat(x):
            return jnp.reshape(x, (4, -1))
        """) == []
    f = run("""
        import jax
        @jax.jit
        def flat(x, n):
            return x.reshape(n, -1)
        """)
    assert rule_ids(f) == ["JAX-STATIC-ARG"]


def test_static_arg_shape_attr_is_fine():
    """jnp.zeros(x.shape[0]) is static — no finding."""
    assert run("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def like(x):
            return jnp.zeros(x.shape[0])
        """) == []


# -- JAX-DONATE-HINT ---------------------------------------------------------

def test_donate_hint_fires_and_is_info():
    f = run("""
        import jax
        @jax.jit
        def step(state, delta):
            return state + delta
        def loop(state, d):
            state = step(state, d)
            return state
        """)
    assert rule_ids(f) == ["JAX-DONATE-HINT"]
    assert f[0].severity == Severity.INFO


def test_donate_hint_silent_with_donation():
    assert run("""
        import functools, jax
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, delta):
            return state + delta
        def loop(state, d):
            state = step(state, d)
            return state
        """) == []


# -- ASYNC-ORPHAN-TASK -------------------------------------------------------

def test_orphan_task_fires():
    f = run("""
        import asyncio
        def kick(coro):
            asyncio.ensure_future(coro)
        """)
    assert rule_ids(f) == ["ASYNC-ORPHAN-TASK"]
    assert f[0].line == 4


def test_orphan_loop_create_task_fires():
    f = run("""
        import asyncio
        def kick(loop, coro):
            loop.create_task(coro)
        """)
    assert rule_ids(f) == ["ASYNC-ORPHAN-TASK"]


def test_taskgroup_create_task_is_fine():
    """asyncio.TaskGroup retains its children — the discard pattern is
    the documented structured-concurrency idiom there."""
    assert run("""
        import asyncio
        async def fan_out(coros):
            async with asyncio.TaskGroup() as tg:
                for c in coros:
                    tg.create_task(c)
        """) == []


def test_retained_task_is_fine():
    assert run("""
        import asyncio
        def kick(tasks, coro):
            t = asyncio.create_task(coro)
            tasks.add(t)
            t.add_done_callback(tasks.discard)
        async def kick2(coro):
            return await asyncio.ensure_future(coro)
        """) == []


# -- ASYNC-BLOCKING-CALL -----------------------------------------------------

def test_blocking_call_fires():
    f = run("""
        import time, subprocess
        async def handler():
            time.sleep(1)
            subprocess.run(["ls"])
            open("/tmp/x").read()
        """)
    assert sorted(rule_ids(f)) == ["ASYNC-BLOCKING-CALL"] * 3


def test_blocking_in_executor_thunk_is_fine():
    """A nested sync def or lambda inside a coroutine is (by
    convention) an executor thunk and runs off-loop —
    ws_service._start pattern."""
    assert run("""
        import asyncio, time
        async def handler(loop):
            def _work():
                time.sleep(1)
            await loop.run_in_executor(None, _work)
            await loop.run_in_executor(None, lambda: time.sleep(1))
            await asyncio.sleep(0.1)
        """) == []


# -- ASYNC-SWALLOWED-EXC -----------------------------------------------------

def test_swallowed_exc_fires_in_server_plane():
    f = run("""
        def teardown(sock):
            try:
                sock.close()
            except Exception:
                pass
        """, path="selkies_tpu/server/x.py")
    assert rule_ids(f) == ["ASYNC-SWALLOWED-EXC"]


def test_swallowed_exc_scoped_to_server_and_webrtc():
    src = """
        def teardown(sock):
            try:
                sock.close()
            except Exception:
                pass
        """
    assert run(src, path="selkies_tpu/engine/x.py") == []
    assert rule_ids(run(src, path="selkies_tpu/webrtc/x.py")) == \
        ["ASYNC-SWALLOWED-EXC"]


def test_logged_or_narrowed_exc_is_fine():
    assert run("""
        import logging
        def teardown(sock):
            try:
                sock.close()
            except OSError:
                pass
            try:
                sock.close()
            except Exception:
                logging.debug("close failed")
        """, path="selkies_tpu/server/x.py") == []


# -- suppression + severity config -------------------------------------------

def test_inline_suppression_same_line_and_line_above():
    assert run("""
        import asyncio
        def kick(a, b):
            asyncio.ensure_future(a)  # graftlint: disable=ASYNC-ORPHAN-TASK
            # graftlint: disable=all
            asyncio.ensure_future(b)
        """) == []


def test_suppression_on_last_line_of_multiline_statement():
    """Formatters keep trailing comments on the closing line — the
    pragma works anywhere on the statement's first or last line."""
    assert run("""
        import asyncio
        def kick(a):
            asyncio.ensure_future(
                a)  # graftlint: disable=ASYNC-ORPHAN-TASK
        """) == []


def test_trailing_pragma_does_not_leak_to_next_line():
    """A pragma trailing statement N must not suppress a fresh
    violation on statement N+1 — only a comment-ONLY line above
    suppresses downward."""
    f = run("""
        import asyncio
        def kick(a, b):
            asyncio.ensure_future(a)  # graftlint: disable=ASYNC-ORPHAN-TASK
            asyncio.ensure_future(b)
        """)
    assert rule_ids(f) == ["ASYNC-ORPHAN-TASK"] and f[0].line == 5


def test_suppression_is_per_rule():
    f = run("""
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)  # graftlint: disable=OTHER-RULE
        """)
    assert rule_ids(f) == ["ASYNC-ORPHAN-TASK"]


def test_severity_override_demotes_to_non_gating():
    from selkies_tpu.analysis.core import gating
    f = run("""
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)
        """, severity_overrides={"ASYNC-ORPHAN-TASK": "info"})
    assert f and f[0].severity == Severity.INFO
    assert gating(f) == []


# -- baseline ratchet --------------------------------------------------------

def test_baseline_absorbs_known_and_catches_new():
    src_v1 = """
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)
        """
    base = make_baseline(run(src_v1))
    assert new_findings(run(src_v1), base) == []
    # same file gains a SECOND identical violation: multiplicity-aware
    src_v2 = src_v1 + "    asyncio.ensure_future(a)\n"
    fresh = new_findings(run(src_v2), base)
    assert len(fresh) == 1 and fresh[0].rule_id == "ASYNC-ORPHAN-TASK"


def test_baseline_survives_line_drift():
    src = """
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)
        """
    base = make_baseline(run(src))
    drifted = "# a new leading comment\n" + textwrap.dedent(src)
    assert new_findings(Analyzer().run_source(drifted, "mod.py"), base) == []


# -- CLI contract -------------------------------------------------------------

def _write_pkg(tmp_path: Path, body: str) -> Path:
    d = tmp_path / "pkg"
    d.mkdir(exist_ok=True)
    (d / "m.py").write_text(textwrap.dedent(body))
    return d


def test_cli_exit_codes_and_json(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    pkg = _write_pkg(tmp_path, """
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)
        """)
    assert graftlint_main([str(pkg), "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["summary"] == {"total": 1, "baselined": 0, "new": 1,
                              "gating": 1}
    (f,) = out["findings"]
    assert f["rule"] == "ASYNC-ORPHAN-TASK" and f["line"] == 4 \
        and f["path"] == "pkg/m.py" and f["severity"] == "error"

    # ratchet: write baseline -> clean; inject a fresh violation -> 1
    base = tmp_path / "base.json"
    assert graftlint_main([str(pkg), "--write-baseline", str(base)]) == 0
    assert graftlint_main([str(pkg), "--baseline", str(base)]) == 0
    with (pkg / "m.py").open("a") as fh:
        fh.write("async def h():\n    import time\n    time.sleep(1)\n")
    capsys.readouterr()
    assert graftlint_main([str(pkg), "--baseline", str(base)]) == 1
    text = capsys.readouterr().out
    assert "pkg/m.py" in text and "ASYNC-BLOCKING-CALL" in text


def test_cli_usage_and_parse_errors(tmp_path, capsys):
    assert graftlint_main([]) == 2
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    assert graftlint_main([str(bad)]) == 2
    assert graftlint_main(["--list-rules"]) == 0
    assert "ASYNC-ORPHAN-TASK" in capsys.readouterr().out
    # a typo'd path must be a usage error (2), NOT a clean exit 0 —
    # otherwise a package rename silently disables the CI gate
    assert graftlint_main([str(tmp_path / "no_such_pkg")]) == 2
    # bad --severity is a usage error (2), not a lint failure (1)
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    assert graftlint_main([str(ok), "--severity", "FOO"]) == 2
    assert graftlint_main([str(ok), "--severity", "FOO=banana"]) == 2
    # a malformed baseline is a usage error too, not a crash
    bad_base = tmp_path / "bad_base.json"
    bad_base.write_text(json.dumps(
        {"version": 1, "entries": [{"path": "x.py"}]}))
    assert graftlint_main([str(ok), "--baseline", str(bad_base)]) == 2


# -- repo-wide invariant ------------------------------------------------------

def test_repo_findings_subset_of_baseline():
    """The tree stays lint-clean modulo the checked-in baseline: any
    new violation must be fixed, suppressed, or consciously
    baselined."""
    baseline = json.loads(
        (REPO / "tools" / "graftlint_baseline.json").read_text())
    findings = Analyzer().run([REPO / "selkies_tpu"], root=REPO)
    fresh = new_findings(findings, baseline)
    assert fresh == [], "new graftlint findings:\n" + "\n".join(
        f.render() for f in fresh)


# -- THREAD-SHARED-MUTATION --------------------------------------------------

RACE_SRC = """
    import threading
    class Cap:
        def __init__(self):
            self._lock = threading.Lock()
            self.qp = 0
        def reconfigure(self, qp):
            with self._lock:
                self.qp = qp
        def _run(self):
            self.qp = self.qp + 1
        def start(self):
            threading.Thread(target=self._run).start()
    """


def test_shared_mutation_fires_on_seeded_race():
    f = run(RACE_SRC)
    assert rule_ids(f) == ["THREAD-SHARED-MUTATION"]
    assert "self.qp" in f[0].message and "thread:_run" in f[0].message


def test_shared_mutation_silent_with_common_lock():
    assert run("""
        import threading
        class Cap:
            def __init__(self):
                self._lock = threading.Lock()
                self.qp = 0
            def reconfigure(self, qp):
                with self._lock:
                    self.qp = qp
            def _run(self):
                with self._lock:
                    self.qp = self.qp + 1
            def start(self):
                threading.Thread(target=self._run).start()
        """) == []


def test_shared_mutation_lock_carries_through_calls():
    """Interprocedural locksets: a mutation inside a helper only ever
    called under the lock carries the lock (entry-lockset fixpoint)."""
    assert run("""
        import threading
        class Cap:
            def __init__(self):
                self._lock = threading.Lock()
                self.qp = 0
            def _set(self, qp):
                self.qp = qp
            def reconfigure(self, qp):
                with self._lock:
                    self._set(qp)
            def _run(self):
                with self._lock:
                    self._set(1)
            def start(self):
                threading.Thread(target=self._run).start()
        """) == []


def test_shared_mutation_init_does_not_count():
    """__init__ runs before the instance is shared — seeding state there
    races nothing."""
    assert run("""
        import threading
        class Cap:
            def __init__(self):
                self.qp = 0
            def _run(self):
                self.qp = 1
            def start(self):
                threading.Thread(target=self._run).start()
        """) == []


def test_shared_mutation_finalizer_vs_thread():
    """PipelineRing finalize-fn context races the capture thread — but a
    shared lock (via a local alias) makes it safe."""
    f = run("""
        import threading
        from .pipeline import PipelineRing
        class Cap:
            def _deliver(self, out):
                self.nbytes = len(out)
            def _run(self):
                ring = PipelineRing(self._deliver, depth=2)
                self.nbytes = 0
                ring.submit({})
            def start(self):
                threading.Thread(target=self._run).start()
        """)
    assert rule_ids(f) == ["THREAD-SHARED-MUTATION"]
    assert "finalizer" in f[0].message


# -- THREAD-LOOP-ONLY-CALL ---------------------------------------------------

def test_loop_only_call_fires_from_thread_context():
    f = run("""
        import asyncio, threading
        class Svc:
            def _worker(self):
                t = self.loop.create_task(self._notify())
                return t
            def start(self):
                threading.Thread(target=self._worker).start()
        """)
    assert rule_ids(f) == ["THREAD-LOOP-ONLY-CALL"]
    assert "call_soon_threadsafe" in f[0].message \
        or "run_coroutine_threadsafe" in f[0].message


def test_threadsafe_hop_is_fine():
    """The sanctioned thread->loop hops never fire; neither do loop-only
    APIs used from loop context."""
    assert run("""
        import asyncio, threading
        class Svc:
            def _worker(self):
                self.loop.call_soon_threadsafe(self._notify)
                asyncio.run_coroutine_threadsafe(self.coro(), self.loop)
            def start(self):
                threading.Thread(target=self._worker).start()
            async def handler(self):
                t = asyncio.create_task(self.coro())
                await t
        """) == []


def test_loop_only_call_reaches_thread_helpers():
    """Context propagates through module-local calls: a helper reached
    only from a Thread target is thread code."""
    f = run("""
        import asyncio, threading
        class Svc:
            def _kick(self):
                t = asyncio.ensure_future(self.coro())
                return t
            def _worker(self):
                self._kick()
            def start(self):
                threading.Thread(target=self._worker).start()
        """)
    assert rule_ids(f) == ["THREAD-LOOP-ONLY-CALL"]


# -- THREAD-LOCK-ORDER -------------------------------------------------------

def test_lock_order_cycle_fires():
    f = run("""
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def submit():
            with A:
                with B:
                    pass
        def drain():
            with B:
                with A:
                    pass
        """)
    assert rule_ids(f) == ["THREAD-LOCK-ORDER"]
    assert "A" in f[0].message and "B" in f[0].message


def test_lock_order_consistent_nesting_is_fine():
    assert run("""
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def submit():
            with A:
                with B:
                    pass
        def drain():
            with A:
                with B:
                    pass
        """) == []


def test_lock_order_cycle_through_call():
    """The acquisition graph follows module-local calls: holding A while
    calling a function that takes B closes the cycle."""
    f = run("""
        import threading
        class S:
            def _take_b(self):
                with self._b:
                    pass
            def fwd(self):
                with self._a:
                    self._take_b()
            def rev(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert rule_ids(f) == ["THREAD-LOCK-ORDER"]


def test_lock_order_alias_resolves():
    """`turn = GLOBAL_LOCK; with turn:` keys on the module lock (the
    engine capture-loop idiom), so aliased nesting still makes edges."""
    f = run("""
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def submit():
            turn = A
            with turn:
                with B:
                    pass
        def drain():
            with B:
                with A:
                    pass
        """)
    assert rule_ids(f) == ["THREAD-LOCK-ORDER"]


# -- JAX-USE-AFTER-DONATE ----------------------------------------------------

def test_use_after_donate_fires():
    f = run("""
        import functools, jax
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, delta):
            return state + delta
        def loop(state, d):
            new = step(state, d)
            return state + new
        """)
    assert rule_ids(f) == ["JAX-USE-AFTER-DONATE"]
    assert "'state'" in f[0].message


def test_use_after_donate_rebind_is_fine():
    """state = step(state, d): the donated binding is rebound from the
    output — the prev_out discipline."""
    assert run("""
        import functools, jax
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, delta):
            return state + delta
        def loop(state, d):
            state = step(state, d)
            return state
        """) == []


def test_use_after_donate_tracks_wrap_step_factories():
    """The engine idiom: a factory returns perf.wrap_step(jax.jit(f,
    donate_argnums=donate_argnums_for_backend(...))), the session binds
    it to self._step, encode() donates self._prev — reading the attr
    after the call without rebinding fires; rebinding from the output
    does not."""
    bad = run("""
        import jax
        from ..obs import perf as _perf
        def donate_argnums_for_backend(nums):
            return nums
        def _jitted(mode):
            def step(frame, prev):
                return frame, prev
            return _perf.wrap_step(
                "s", jax.jit(step,
                             donate_argnums=donate_argnums_for_backend(
                                 (1,))))
        class Sess:
            def _build(self):
                return _jitted("i")
            def setup(self):
                self._step = self._build()
            def encode(self, frame):
                out, prev = self._step(frame, self._prev)
                return self._prev.sum() + out
        """)
    assert rule_ids(bad) == ["JAX-USE-AFTER-DONATE"]
    good = """
        import jax
        from ..obs import perf as _perf
        def _jitted(mode):
            def step(frame, prev):
                return frame, prev
            return _perf.wrap_step(
                "s", jax.jit(step, donate_argnums=(1,)))
        class Sess:
            def setup(self):
                self._step = _jitted("i")
            def encode(self, frame):
                out, prev_out = self._step(frame, self._prev)
                self._prev = prev_out
                return self._prev.sum() + out
        """
    assert run(good) == []


def test_use_after_donate_same_call_args_do_not_count():
    """The donating call's own argument list is not a 'later read'."""
    assert run("""
        import functools, jax
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(a, b):
            return a + b
        def loop(a, b):
            a, b = step(a, b)
            return a, b
        """) == []


# -- JAX-SHARD-CONSISTENCY ---------------------------------------------------

SHARD_PRELUDE = """
    import numpy as np
    import jax.numpy as jnp
    from jax import shard_map, lax
    from jax.sharding import Mesh
    mesh = Mesh(np.array([0]), ("stripe",))
    """


def test_shard_host_sync_fires():
    f = run(SHARD_PRELUDE + """
    def build():
        def local(y):
            return np.asarray(y)
        return shard_map(local, mesh=mesh, in_specs=None, out_specs=None)
    """)
    assert rule_ids(f) == ["JAX-SHARD-CONSISTENCY"]
    assert "host" in f[0].message


def test_shard_item_and_branch_fire():
    f = run(SHARD_PRELUDE + """
    def build():
        def local(y):
            if y.sum() > 0:
                return y
            return y * y.max().item()
        return shard_map(local, mesh=mesh, in_specs=None, out_specs=None)
    """)
    assert sorted(rule_ids(f)) == ["JAX-SHARD-CONSISTENCY"] * 2


def test_shard_unbound_axis_name_fires():
    f = run(SHARD_PRELUDE + """
    def build():
        def local(y):
            row0 = lax.axis_index("stripes")    # typo: mesh binds 'stripe'
            return y + row0
        return shard_map(local, mesh=mesh, in_specs=None, out_specs=None)
    """)
    assert rule_ids(f) == ["JAX-SHARD-CONSISTENCY"]
    assert "'stripes'" in f[0].message and "stripe" in f[0].message


def test_shard_clean_program_is_fine():
    """Bound axis names, branches on closure statics, helper calls with
    static params (the stripes.py candidate-tuple idiom): no findings."""
    assert run(SHARD_PRELUDE + """
    def helper(y, candidates):
        sel = np.asarray(candidates)        # static tuple: NOT per-shard
        return y + sel.shape[0]
    def build(want_recon=False):
        def local(y):
            row0 = lax.axis_index("stripe")
            if want_recon:                   # closure var, not per-shard
                return helper(y, ((0, 0),))
            return y + row0
        return shard_map(local, mesh=mesh, in_specs=None, out_specs=None)
    """) == []


# -- context propagation (contexts.py unit surface) --------------------------

def _contexts(src: str):
    import ast as _ast
    from selkies_tpu.analysis.contexts import contexts_of
    from selkies_tpu.analysis.core import ModuleInfo
    src = textwrap.dedent(src)
    tree = _ast.parse(src)
    m = ModuleInfo(path="m.py", source=src, tree=tree,
                   lines=src.splitlines())
    return {n.name: c for n, c in contexts_of(m).items()}


def test_context_thread_target_and_helpers():
    ctx = _contexts("""
        import threading
        class C:
            def _helper(self):
                pass
            def _run(self):
                self._helper()
            def start(self):
                threading.Thread(target=self._run).start()
        """)
    assert ctx["_run"] == {"thread:_run"}
    assert ctx["_helper"] == {"thread:_run"}
    assert ctx["start"] == set()                 # caller-only


def test_context_finalizer_and_loop_seeds():
    ctx = _contexts("""
        import asyncio
        from .pipeline import PipelineRing, retarget
        class C:
            def _deliver(self, out):
                pass
            def _on_loop(self):
                pass
            def wire(self, loop):
                ring = PipelineRing(self._deliver, depth=2)
                ring2 = retarget(None, 2, self._deliver, "x")
                loop.call_soon_threadsafe(self._on_loop)
            async def handler(self):
                pass
        """)
    assert ctx["_deliver"] == {"finalizer"}
    assert ctx["_on_loop"] == {"loop"}
    assert ctx["handler"] == {"loop"}


def test_context_thread_does_not_enter_async_defs():
    """A thread fn calling an async def cannot run its body — loop
    context stays loop."""
    ctx = _contexts("""
        import threading
        class C:
            async def handler(self):
                pass
            def _run(self):
                c = self.handler()
                return c
            def start(self):
                threading.Thread(target=self._run).start()
        """)
    assert ctx["handler"] == {"loop"}


def test_context_supervisor_adopt_is_loop():
    """Supervisor-adopted restart callables fire from the loop's
    call_later (the default schedule seam)."""
    ctx = _contexts("""
        class C:
            def _restart(self):
                pass
            def wire(self, sup):
                sup.adopt("capture", self._restart)
        """)
    assert ctx["_restart"] == {"loop"}


# -- CLI contract v2: sarif, internal errors, pragma warnings, selftest ------

def test_cli_sarif_output(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    pkg = _write_pkg(tmp_path, """
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)
        """)
    assert graftlint_main([str(pkg), "--format=sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    (res,) = doc["runs"][0]["results"]
    assert res["ruleId"] == "ASYNC-ORPHAN-TASK" and res["level"] == "error"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "pkg/m.py"
    assert loc["region"]["startLine"] == 4
    rule_catalog = {r["id"]
                    for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"THREAD-SHARED-MUTATION", "THREAD-LOOP-ONLY-CALL",
            "THREAD-LOCK-ORDER", "JAX-USE-AFTER-DONATE",
            "JAX-SHARD-CONSISTENCY"} <= rule_catalog
    # baselined findings do not reappear as sarif results
    base = tmp_path / "base.json"
    assert graftlint_main([str(pkg), "--write-baseline", str(base)]) == 0
    capsys.readouterr()
    assert graftlint_main([str(pkg), "--format=sarif",
                           "--baseline", str(base)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"] == []


def test_cli_internal_error_exits_2(tmp_path, capsys, monkeypatch):
    """A crashing rule is an INTERNAL error (exit 2), never a lint
    failure (exit 1) — CI must distinguish 'gate found something' from
    'gate broke'."""
    from selkies_tpu.analysis import core as _core

    class _Broken(_core.Rule):
        rule_id = "BROKEN-RULE"
        description = "always crashes"

        def check(self, module):
            raise RuntimeError("boom")
            yield  # pragma: no cover

    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    a = Analyzer(rules=[_Broken()])
    assert a.run_source("x = 1\n", "ok.py") == []
    assert a.internal_errors and "BROKEN-RULE" in a.internal_errors[0]

    import selkies_tpu.analysis.__main__ as _main
    real = _main.Analyzer
    monkeypatch.setattr(_main, "Analyzer",
                        lambda **kw: real(rules=[_Broken()], **kw))
    assert graftlint_main([str(ok)]) == 2
    assert "internal error" in capsys.readouterr().err


def test_unknown_pragma_id_warns():
    a = Analyzer()
    a.run_source(textwrap.dedent("""
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)  # graftlint: disable=ASYNC-ORPHAN-TASKS
        """), "m.py")
    assert a.pragma_warnings and "ASYNC-ORPHAN-TASKS" in a.pragma_warnings[0]
    assert "m.py:4" in a.pragma_warnings[0]


def test_known_pragma_and_docstring_mentions_do_not_warn():
    a = Analyzer()
    a.run_source(textwrap.dedent('''
        """Docs may quote ``# graftlint: disable=NOT-A-RULE`` freely."""
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)  # graftlint: disable=all
        '''), "m.py")
    assert a.pragma_warnings == []


def test_cli_selftest_subcommand(capsys):
    assert graftlint_main(["selftest"]) == 0
    out = capsys.readouterr().out
    assert "0 failure(s)" in out
    assert graftlint_main(["selftest", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True and doc["checks"] >= 18


def test_list_rules_covers_v2():
    assert graftlint_main(["--list-rules"]) == 0
    import io
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        graftlint_main(["--list-rules"])
    out = buf.getvalue()
    for rid in ("THREAD-SHARED-MUTATION", "THREAD-LOOP-ONLY-CALL",
                "THREAD-LOCK-ORDER", "JAX-USE-AFTER-DONATE",
                "JAX-SHARD-CONSISTENCY", "JAX-HOST-SYNC",
                "ASYNC-ORPHAN-TASK"):
        assert rid in out, rid


def test_repo_invariant_covers_new_rule_ids():
    """The ⊆-baseline invariant gates the NEW rules too: they are in the
    default rule set the repo scan runs."""
    from selkies_tpu.analysis import default_rules
    ids = {r.rule_id for r in default_rules()}
    assert {"THREAD-SHARED-MUTATION", "THREAD-LOOP-ONLY-CALL",
            "THREAD-LOCK-ORDER", "JAX-USE-AFTER-DONATE",
            "JAX-SHARD-CONSISTENCY"} <= ids


def test_lock_order_multi_item_with_fires():
    """`with A, B:` acquires sequentially — the idiomatic multi-item
    form must build the same A->B edge as nested withs (regression:
    the scanner once recorded B's acquisition without A held)."""
    f = run("""
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def submit():
            with A, B:
                pass
        def drain():
            with B, A:
                pass
        """)
    assert rule_ids(f) == ["THREAD-LOCK-ORDER"]
    # mixed nested-vs-multi-item ABBA is the same cycle
    f = run("""
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def submit():
            with A:
                with B:
                    pass
        def drain():
            with B, A:
                pass
        """)
    assert rule_ids(f) == ["THREAD-LOCK-ORDER"]
