"""graftlint (selkies_tpu/analysis/): per-rule firing + non-firing
fixtures, suppression pragmas, the baseline ratchet, CLI contract, and
the repo-wide invariant that current findings ⊆ the checked-in
baseline (i.e. the tree is lint-clean modulo tolerated debt)."""
import json
import textwrap
from pathlib import Path

import pytest

from selkies_tpu.analysis import Analyzer, Severity
from selkies_tpu.analysis.__main__ import main as graftlint_main
from selkies_tpu.analysis.core import make_baseline, new_findings

REPO = Path(__file__).resolve().parent.parent


def run(src: str, path: str = "mod.py", **kw) -> list:
    return Analyzer(**kw).run_source(textwrap.dedent(src), path)


def rule_ids(findings) -> list[str]:
    return [f.rule_id for f in findings]


# -- JAX-HOST-SYNC -----------------------------------------------------------

def test_host_sync_fires_in_jitted_fn():
    f = run("""
        import jax, numpy as np
        @jax.jit
        def step(frame):
            return np.asarray(frame)
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC"]
    assert f[0].line == 5 and "np.asarray" in f[0].message


def test_host_sync_item_and_float_fire():
    f = run("""
        import jax
        @jax.jit
        def step(x):
            a = x.item()
            b = float(x)
            return a + b
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC", "JAX-HOST-SYNC"]


def test_host_sync_int_of_shape_is_fine():
    """int(x.shape[0]) / int(len(x)) are trace-static — no host sync."""
    assert run("""
        import jax
        @jax.jit
        def step(x):
            n = int(x.shape[0])
            m = int(len(x))
            return n + m
        """) == []


def test_host_sync_float_of_static_param_is_fine():
    """float(scale) where scale is in static_argnames is a concrete
    Python value at trace time — no sync, no finding."""
    assert run("""
        import functools, jax
        @functools.partial(jax.jit, static_argnames=("scale",))
        def step(x, scale):
            return x * float(scale)
        """) == []


def test_host_sync_item_on_static_is_fine():
    """static_param.item() and MODULE_CONST.item() are concrete at
    trace time — only tracer .item() syncs."""
    assert run("""
        import functools, jax, numpy as np
        K = np.float32(2.0)
        @functools.partial(jax.jit, static_argnames=("q",))
        def step(x, q):
            return x * q.item() * K.item()
        """) == []


def test_host_sync_trace_time_constants_are_fine():
    """np.array(LITERAL) quant tables, float(math.pi), float(self.k):
    all concrete at trace time — no sync, no finding."""
    assert run("""
        import math
        import jax, numpy as np
        QUANT = [[16, 11], [12, 12]]
        @jax.jit
        def step(x):
            q = np.array([[16, 11], [12, 12]])
            r = np.asarray(QUANT)
            return x * q * r * float(math.pi)
        """) == []


def test_host_sync_static_shape_local_is_fine():
    """Binding a static shape to a local before converting is the same
    as the inline form: n = x.shape[0]; float(n) — no sync."""
    assert run("""
        import jax
        @jax.jit
        def f(x):
            n = x.shape[0]
            m = n * 2
            return x * float(n) * int(m)
        """) == []
    f = run("""
        import jax
        @jax.jit
        def f(x):
            n = x + 1
            return float(n)
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC"]


def test_host_sync_static_loop_vars_are_fine():
    """`for i in range(4)` unrolls at trace time: float(i) syncs
    nothing.  Loops over a traced value stay flagged."""
    assert run("""
        import jax
        @jax.jit
        def f(x):
            acc = 0.0
            for i in range(4):
                acc = acc + float(i)
            ys = [float(i) for i in range(3)]
            return x * acc * sum(ys)
        """) == []
    f = run("""
        import jax
        @jax.jit
        def f(x):
            for v in x:
                y = float(v)
            return y
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC"]


def test_host_sync_silent_outside_hot_code():
    assert run("""
        import numpy as np
        def host_side(frame):
            return np.asarray(frame).item()
        """) == []


def test_host_sync_reaches_module_local_helpers():
    """f called from a jitted body is traced too."""
    f = run("""
        import jax, numpy as np
        def helper(x):
            return np.array(x)
        @jax.jit
        def step(frame):
            return helper(frame)
        """)
    assert "JAX-HOST-SYNC" in rule_ids(f)


def test_host_sync_factory_closure_detected():
    """The repo idiom: jax.jit(build_fn(...)) traces the returned
    closure (engine/encoder.py:121)."""
    f = run("""
        import jax, numpy as np
        def build_fn(w):
            def step(frame):
                return np.asarray(frame)
            return step
        compiled = jax.jit(build_fn(64))
        """)
    assert rule_ids(f) == ["JAX-HOST-SYNC"]


# -- JAX-TRACER-BRANCH -------------------------------------------------------

def test_tracer_branch_fires():
    f = run("""
        import jax
        @jax.jit
        def step(x):
            if x > 0:
                return x
            return -x
        """)
    assert rule_ids(f) == ["JAX-TRACER-BRANCH"]


def test_tracer_branch_static_arg_is_fine():
    assert run("""
        import functools, jax
        @functools.partial(jax.jit, static_argnames=("mode",))
        def step(x, mode):
            if mode:
                return x
            return -x
        """) == []


def test_tracer_branch_compound_static_guard_is_fine():
    """`x is not None and x.shape[0] > 4` — both legs are trace-static,
    including inside and/or chains."""
    assert run("""
        import jax
        @jax.jit
        def step(x):
            if x is not None and x.shape[0] > 4:
                return x
            return -x
        """) == []


def test_tracer_branch_shape_and_none_checks_are_fine():
    """x.shape / len(x) / `is None` are static at trace time."""
    assert run("""
        import jax
        @jax.jit
        def step(x, y):
            if x.shape[0] > 8:
                return x
            if y is None:
                return x
            if len(x) > 2:
                return x
            return x
        """) == []


def test_partial_bound_params_are_static():
    """jax.jit(partial(f, mode=...)) binds mode to a concrete value
    (ops/jpeg_pipeline.py idiom) — branching on it is fine."""
    assert run("""
        import functools, jax
        def encode(x, mode):
            if mode == "420":
                return x
            return -x
        def make(mode):
            return jax.jit(functools.partial(encode, mode=mode))
        """) == []


# -- JAX-STATIC-ARG ----------------------------------------------------------

def test_static_arg_fires_on_shape_slot():
    f = run("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def tile(n):
            return jnp.zeros(n)
        """)
    assert rule_ids(f) == ["JAX-STATIC-ARG"]
    assert "'n'" in f[0].message


def test_static_arg_fires_on_range():
    f = run("""
        import jax
        @jax.jit
        def loop(x, n):
            for _ in range(n):
                x = x + 1
            return x
        """)
    assert rule_ids(f) == ["JAX-STATIC-ARG"]


def test_static_arg_declared_static_is_fine():
    assert run("""
        import functools, jax
        import jax.numpy as jnp
        @functools.partial(jax.jit, static_argnums=(0,))
        def tile(n):
            return jnp.zeros(n)
        """) == []


def test_static_arg_functional_reshape_array_arg_is_fine():
    """jnp.reshape(x, shape): arg 0 is the traced array, not a shape —
    only the method form x.reshape(*shape) treats every arg as shape."""
    assert run("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def flat(x):
            return jnp.reshape(x, (4, -1))
        """) == []
    f = run("""
        import jax
        @jax.jit
        def flat(x, n):
            return x.reshape(n, -1)
        """)
    assert rule_ids(f) == ["JAX-STATIC-ARG"]


def test_static_arg_shape_attr_is_fine():
    """jnp.zeros(x.shape[0]) is static — no finding."""
    assert run("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def like(x):
            return jnp.zeros(x.shape[0])
        """) == []


# -- JAX-DONATE-HINT ---------------------------------------------------------

def test_donate_hint_fires_and_is_info():
    f = run("""
        import jax
        @jax.jit
        def step(state, delta):
            return state + delta
        def loop(state, d):
            state = step(state, d)
            return state
        """)
    assert rule_ids(f) == ["JAX-DONATE-HINT"]
    assert f[0].severity == Severity.INFO


def test_donate_hint_silent_with_donation():
    assert run("""
        import functools, jax
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, delta):
            return state + delta
        def loop(state, d):
            state = step(state, d)
            return state
        """) == []


# -- ASYNC-ORPHAN-TASK -------------------------------------------------------

def test_orphan_task_fires():
    f = run("""
        import asyncio
        def kick(coro):
            asyncio.ensure_future(coro)
        """)
    assert rule_ids(f) == ["ASYNC-ORPHAN-TASK"]
    assert f[0].line == 4


def test_orphan_loop_create_task_fires():
    f = run("""
        import asyncio
        def kick(loop, coro):
            loop.create_task(coro)
        """)
    assert rule_ids(f) == ["ASYNC-ORPHAN-TASK"]


def test_taskgroup_create_task_is_fine():
    """asyncio.TaskGroup retains its children — the discard pattern is
    the documented structured-concurrency idiom there."""
    assert run("""
        import asyncio
        async def fan_out(coros):
            async with asyncio.TaskGroup() as tg:
                for c in coros:
                    tg.create_task(c)
        """) == []


def test_retained_task_is_fine():
    assert run("""
        import asyncio
        def kick(tasks, coro):
            t = asyncio.create_task(coro)
            tasks.add(t)
            t.add_done_callback(tasks.discard)
        async def kick2(coro):
            return await asyncio.ensure_future(coro)
        """) == []


# -- ASYNC-BLOCKING-CALL -----------------------------------------------------

def test_blocking_call_fires():
    f = run("""
        import time, subprocess
        async def handler():
            time.sleep(1)
            subprocess.run(["ls"])
            open("/tmp/x").read()
        """)
    assert sorted(rule_ids(f)) == ["ASYNC-BLOCKING-CALL"] * 3


def test_blocking_in_executor_thunk_is_fine():
    """A nested sync def or lambda inside a coroutine is (by
    convention) an executor thunk and runs off-loop —
    ws_service._start pattern."""
    assert run("""
        import asyncio, time
        async def handler(loop):
            def _work():
                time.sleep(1)
            await loop.run_in_executor(None, _work)
            await loop.run_in_executor(None, lambda: time.sleep(1))
            await asyncio.sleep(0.1)
        """) == []


# -- ASYNC-SWALLOWED-EXC -----------------------------------------------------

def test_swallowed_exc_fires_in_server_plane():
    f = run("""
        def teardown(sock):
            try:
                sock.close()
            except Exception:
                pass
        """, path="selkies_tpu/server/x.py")
    assert rule_ids(f) == ["ASYNC-SWALLOWED-EXC"]


def test_swallowed_exc_scoped_to_server_and_webrtc():
    src = """
        def teardown(sock):
            try:
                sock.close()
            except Exception:
                pass
        """
    assert run(src, path="selkies_tpu/engine/x.py") == []
    assert rule_ids(run(src, path="selkies_tpu/webrtc/x.py")) == \
        ["ASYNC-SWALLOWED-EXC"]


def test_logged_or_narrowed_exc_is_fine():
    assert run("""
        import logging
        def teardown(sock):
            try:
                sock.close()
            except OSError:
                pass
            try:
                sock.close()
            except Exception:
                logging.debug("close failed")
        """, path="selkies_tpu/server/x.py") == []


# -- suppression + severity config -------------------------------------------

def test_inline_suppression_same_line_and_line_above():
    assert run("""
        import asyncio
        def kick(a, b):
            asyncio.ensure_future(a)  # graftlint: disable=ASYNC-ORPHAN-TASK
            # graftlint: disable=all
            asyncio.ensure_future(b)
        """) == []


def test_suppression_on_last_line_of_multiline_statement():
    """Formatters keep trailing comments on the closing line — the
    pragma works anywhere on the statement's first or last line."""
    assert run("""
        import asyncio
        def kick(a):
            asyncio.ensure_future(
                a)  # graftlint: disable=ASYNC-ORPHAN-TASK
        """) == []


def test_trailing_pragma_does_not_leak_to_next_line():
    """A pragma trailing statement N must not suppress a fresh
    violation on statement N+1 — only a comment-ONLY line above
    suppresses downward."""
    f = run("""
        import asyncio
        def kick(a, b):
            asyncio.ensure_future(a)  # graftlint: disable=ASYNC-ORPHAN-TASK
            asyncio.ensure_future(b)
        """)
    assert rule_ids(f) == ["ASYNC-ORPHAN-TASK"] and f[0].line == 5


def test_suppression_is_per_rule():
    f = run("""
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)  # graftlint: disable=OTHER-RULE
        """)
    assert rule_ids(f) == ["ASYNC-ORPHAN-TASK"]


def test_severity_override_demotes_to_non_gating():
    from selkies_tpu.analysis.core import gating
    f = run("""
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)
        """, severity_overrides={"ASYNC-ORPHAN-TASK": "info"})
    assert f and f[0].severity == Severity.INFO
    assert gating(f) == []


# -- baseline ratchet --------------------------------------------------------

def test_baseline_absorbs_known_and_catches_new():
    src_v1 = """
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)
        """
    base = make_baseline(run(src_v1))
    assert new_findings(run(src_v1), base) == []
    # same file gains a SECOND identical violation: multiplicity-aware
    src_v2 = src_v1 + "    asyncio.ensure_future(a)\n"
    fresh = new_findings(run(src_v2), base)
    assert len(fresh) == 1 and fresh[0].rule_id == "ASYNC-ORPHAN-TASK"


def test_baseline_survives_line_drift():
    src = """
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)
        """
    base = make_baseline(run(src))
    drifted = "# a new leading comment\n" + textwrap.dedent(src)
    assert new_findings(Analyzer().run_source(drifted, "mod.py"), base) == []


# -- CLI contract -------------------------------------------------------------

def _write_pkg(tmp_path: Path, body: str) -> Path:
    d = tmp_path / "pkg"
    d.mkdir(exist_ok=True)
    (d / "m.py").write_text(textwrap.dedent(body))
    return d


def test_cli_exit_codes_and_json(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    pkg = _write_pkg(tmp_path, """
        import asyncio
        def kick(a):
            asyncio.ensure_future(a)
        """)
    assert graftlint_main([str(pkg), "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["summary"] == {"total": 1, "baselined": 0, "new": 1,
                              "gating": 1}
    (f,) = out["findings"]
    assert f["rule"] == "ASYNC-ORPHAN-TASK" and f["line"] == 4 \
        and f["path"] == "pkg/m.py" and f["severity"] == "error"

    # ratchet: write baseline -> clean; inject a fresh violation -> 1
    base = tmp_path / "base.json"
    assert graftlint_main([str(pkg), "--write-baseline", str(base)]) == 0
    assert graftlint_main([str(pkg), "--baseline", str(base)]) == 0
    with (pkg / "m.py").open("a") as fh:
        fh.write("async def h():\n    import time\n    time.sleep(1)\n")
    capsys.readouterr()
    assert graftlint_main([str(pkg), "--baseline", str(base)]) == 1
    text = capsys.readouterr().out
    assert "pkg/m.py" in text and "ASYNC-BLOCKING-CALL" in text


def test_cli_usage_and_parse_errors(tmp_path, capsys):
    assert graftlint_main([]) == 2
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    assert graftlint_main([str(bad)]) == 2
    assert graftlint_main(["--list-rules"]) == 0
    assert "ASYNC-ORPHAN-TASK" in capsys.readouterr().out
    # a typo'd path must be a usage error (2), NOT a clean exit 0 —
    # otherwise a package rename silently disables the CI gate
    assert graftlint_main([str(tmp_path / "no_such_pkg")]) == 2
    # bad --severity is a usage error (2), not a lint failure (1)
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    assert graftlint_main([str(ok), "--severity", "FOO"]) == 2
    assert graftlint_main([str(ok), "--severity", "FOO=banana"]) == 2
    # a malformed baseline is a usage error too, not a crash
    bad_base = tmp_path / "bad_base.json"
    bad_base.write_text(json.dumps(
        {"version": 1, "entries": [{"path": "x.py"}]}))
    assert graftlint_main([str(ok), "--baseline", str(bad_base)]) == 2


# -- repo-wide invariant ------------------------------------------------------

def test_repo_findings_subset_of_baseline():
    """The tree stays lint-clean modulo the checked-in baseline: any
    new violation must be fixed, suppressed, or consciously
    baselined."""
    baseline = json.loads(
        (REPO / "tools" / "graftlint_baseline.json").read_text())
    findings = Analyzer().run([REPO / "selkies_tpu"], root=REPO)
    fresh = new_findings(findings, baseline)
    assert fresh == [], "new graftlint findings:\n" + "\n".join(
        f.render() for f in fresh)
