"""4:4:4 (fullcolor / Hi444PP) oracle chain, mirroring the 4:2:0 chain
(test_h264_device + test_h264_planes + test_h264_motion):

1. the golden numpy encoders (codecs/h264.I444Encoder / P444Encoder)
   must decode byte-exactly under libavcodec's independent Hi444PP
   decoder;
2. the device plane encoder (ops/h264_planes444) must be BIT-IDENTICAL
   to the golden encoders (I and zero-MV P), reconstruction included;
3. device streams with per-row QP and with motion search (which the
   golden encoders don't implement) must decode byte-exactly in ffmpeg
   against the device's own reconstruction;
4. the ChromaArrayType-3 coded_block_pattern me(v) table must equal the
   empirical derivation against libavcodec (tools/derive_cbp444.py).
"""

import numpy as np
import pytest

from selkies_tpu.codecs import h264 as H
from selkies_tpu.codecs import h264_ref_decoder as refdec
from selkies_tpu.native import avshim

jnp = pytest.importorskip("jax.numpy")

from selkies_tpu.ops.bitpack import words_to_bytes  # noqa: E402
from selkies_tpu.ops.h264_encode import scroll_candidates  # noqa: E402
from selkies_tpu.ops.h264_planes444 import (P_SLOTS_MB_444,  # noqa: E402
                                            SLOTS_MB_444,
                                            h264_encode_p_yuv444,
                                            h264_encode_yuv444)

needs_av = pytest.mark.skipif(not avshim.available(),
                              reason="libavcodec unavailable")

QP = 28


def _planes(h, w, seed=0):
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    y = ((xx * 5 + yy * 11 + rng.integers(0, 48, (h, w))) % 256
         ).astype(np.uint8)
    u = ((xx * 3 + rng.integers(0, 64, (h, w))) % 256).astype(np.uint8)
    v = rng.integers(0, 256, (h, w), dtype=np.uint8)
    return y, u, v


def _device_i444(y, u, v, qp, idr_pic_id=0, want_recon=False):
    R, M = y.shape[0] // 16, y.shape[1] // 16
    pay, nb = H.slice_header_events(M, R)
    e_cap = 16 + M * SLOTS_MB_444 + 2
    out = h264_encode_yuv444(
        jnp.asarray(y, jnp.int32), jnp.asarray(u, jnp.int32),
        jnp.asarray(v, jnp.int32), qp, jnp.asarray(pay), jnp.asarray(nb),
        e_cap, 32768, idr_pic_id=idr_pic_id, want_recon=want_recon)
    res = out[0] if want_recon else out
    assert not bool(np.asarray(res.overflow))
    w_, b_ = np.asarray(res.words), np.asarray(res.total_bits)
    rows = [words_to_bytes(w_[r], int(b_[r]), pad_ones=False)
            for r in range(R)]
    if want_recon:
        return rows, tuple(np.asarray(p) for p in out[1])
    return rows


def _device_p444(y, u, v, recon, qp, cands=((0, 0),), frame_num=1):
    R, M = y.shape[0] // 16, y.shape[1] // 16
    pay, nb = H.p_slice_header_events(M, R)
    e_cap = 16 + M * P_SLOTS_MB_444 + 2
    out, rec = h264_encode_p_yuv444(
        jnp.asarray(y, jnp.int32), jnp.asarray(u, jnp.int32),
        jnp.asarray(v, jnp.int32), jnp.asarray(recon[0]),
        jnp.asarray(recon[1]), jnp.asarray(recon[2]), qp,
        jnp.asarray(pay), jnp.asarray(nb), frame_num, e_cap, 32768,
        candidates=cands)
    assert not bool(np.asarray(out.overflow))
    w_, b_ = np.asarray(out.words), np.asarray(out.total_bits)
    rows = [words_to_bytes(w_[r], int(b_[r]), pad_ones=False)
            for r in range(R)]
    return rows, tuple(np.asarray(p) for p in rec)


def _golden_rows(frame_bytes):
    """NAL-wrapped golden frame -> per-row RBSPs (emulation stripped)."""
    return [refdec.remove_emulation_prevention(part[1:])
            for part in frame_bytes.split(b"\x00\x00\x00\x01")[1:]]


def _ffmpeg_decode_seq(headers, aus):
    sess = avshim.H264Session()
    got = None
    for i, au in enumerate(aus):
        got = sess.decode(headers + au if i == 0 else au) or got
    got = sess.flush() or got
    sess.close()
    assert got is not None
    return got


# ---------------------------------------------------------------------------
# 1. golden encoders vs ffmpeg
# ---------------------------------------------------------------------------

@needs_av
@pytest.mark.parametrize("qp", [16, 28, 40])
def test_golden_i444_byte_exact_under_ffmpeg(qp):
    y, u, v = _planes(48, 64, seed=qp)
    enc = H.I444Encoder(64, 48, qp)
    au = enc.encode_frame(y, u, v)
    fy, fu, fv = avshim.decode_h264(enc.headers() + au)
    assert fy.shape == (48, 64) and fu.shape == (48, 64)
    assert np.array_equal(fy, enc.recon[0])
    assert np.array_equal(fu, enc.recon[1])
    assert np.array_equal(fv, enc.recon[2])


@needs_av
def test_golden_p444_byte_exact_under_ffmpeg():
    y0, u0, v0 = _planes(48, 64, seed=2)
    enc = H.I444Encoder(64, 48, QP)
    idr = enc.encode_frame(y0, u0, v0)
    # second frame: half the MBs change (exercises skip runs + coded MBs)
    y1 = y0.copy()
    y1[:, 16:48] = np.roll(y0[:, 16:48], 3, axis=0)
    u1 = u0.copy()
    u1[8:40] = 255 - u1[8:40]
    penc = H.P444Encoder(enc)
    pau = penc.encode_frame(y1, u1, v0, frame_num=1)
    got = _ffmpeg_decode_seq(enc.headers(), [idr, pau])
    assert np.array_equal(got[0], enc.recon[0])
    assert np.array_equal(got[1], enc.recon[1])
    assert np.array_equal(got[2], enc.recon[2])


# ---------------------------------------------------------------------------
# 2. device plane encoder vs golden: bit-identical
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("qp", [16, 28, 40])
def test_device_i444_bit_identical_to_golden(qp):
    y, u, v = _planes(48, 64, seed=10 + qp)
    dev, drec = _device_i444(y, u, v, qp, want_recon=True)
    enc = H.I444Encoder(64, 48, qp)
    host = _golden_rows(enc.encode_frame(y, u, v))
    assert len(dev) == len(host) == 3
    for r, (d, g) in enumerate(zip(dev, host)):
        assert d == g, f"row {r}: device != golden"
    for ci in range(3):
        assert np.array_equal(drec[ci], enc.recon[ci]), f"recon comp {ci}"


def test_device_p444_bit_identical_to_golden():
    y0, u0, v0 = _planes(48, 64, seed=20)
    _, drec = _device_i444(y0, u0, v0, QP, want_recon=True)
    enc = H.I444Encoder(64, 48, QP)
    enc.encode_frame(y0, u0, v0)
    # changed frame with static regions -> mix of skip and coded MBs
    y1 = y0.copy()
    y1[16:32] = np.roll(y0[16:32], 2, axis=1)
    v1 = v0.copy()
    v1[:16, :32] = 255 - v1[:16, :32]
    dev, dprec = _device_p444(y1, u0, v1, drec, QP)
    penc = H.P444Encoder(enc)
    host = _golden_rows(penc.encode_frame(y1, u0, v1, frame_num=1))
    assert len(dev) == len(host) == 3
    for r, (d, g) in enumerate(zip(dev, host)):
        assert d == g, f"row {r}: device != golden"
    for ci in range(3):
        assert np.array_equal(dprec[ci], enc.recon[ci]), f"recon comp {ci}"


# ---------------------------------------------------------------------------
# 3. device-only features (per-row QP, motion) vs ffmpeg
# ---------------------------------------------------------------------------

@needs_av
def test_device_i444_per_row_qp_decodes_in_ffmpeg():
    y, u, v = _planes(48, 64, seed=30)
    qp_rows = jnp.asarray([18, 30, 44], jnp.int32)
    dev, drec = _device_i444(y, u, v, qp_rows, want_recon=True)
    headers = H.write_sps(64, 48, chroma_format=3) + H.write_pps()
    annexb = headers + H.assemble_annexb(dev)
    fy, fu, fv = avshim.decode_h264(annexb)
    assert np.array_equal(fy, drec[0])
    assert np.array_equal(fu, drec[1])
    assert np.array_equal(fv, drec[2])


@needs_av
def test_device_p444_motion_decodes_in_ffmpeg():
    h, w = 48, 64
    y0, u0, v0 = _planes(h, w, seed=40)
    idev, irec = _device_i444(y0, u0, v0, QP, want_recon=True)
    # vertical scroll by 5 px on all three full-res components
    rng = np.random.default_rng(41)
    dy = 5
    y1 = np.concatenate([y0[dy:], rng.integers(
        0, 256, (dy, w), dtype=np.uint8)])
    u1 = np.concatenate([u0[dy:], np.full((dy, w), 128, np.uint8)])
    v1 = np.concatenate([v0[dy:], np.full((dy, w), 128, np.uint8)])
    zero_rows, _ = _device_p444(y1, u1, v1, irec, QP)
    mv_rows, prec = _device_p444(y1, u1, v1, irec, QP,
                                 cands=scroll_candidates(8, 4))
    assert sum(map(len, mv_rows)) < 0.6 * sum(map(len, zero_rows)), \
        "motion search must beat zero-MV on scrolled 4:4:4 content"
    headers = H.write_sps(w, h, chroma_format=3) + H.write_pps()
    idr_au = H.assemble_annexb(idev)
    p_au = b"".join(H.nal(1, rb, ref_idc=2) for rb in mv_rows)
    got = _ffmpeg_decode_seq(headers, [idr_au, p_au])
    for ci in range(3):
        assert np.array_equal(got[ci], prec[ci]), f"comp {ci}"


def test_device_p444_all_skip_is_tiny():
    y, u, v = _planes(32, 48, seed=50)
    _, rec = _device_i444(y, u, v, QP, want_recon=True)
    rows, _ = _device_p444(rec[0], rec[1], rec[2], rec, QP,
                           cands=scroll_candidates(4, 2))
    assert sum(map(len, rows)) < 2 * 16, \
        "self-referential P must be all-skip"


# ---------------------------------------------------------------------------
# 4. the CBP444 me(v) table equals its empirical derivation
# ---------------------------------------------------------------------------

@needs_av
def test_cbp444_table_matches_libavcodec_derivation():
    # tools/ is not a package: load the script by path so a bare
    # ``pytest`` under an editable install (repo root off sys.path)
    # still finds it
    import importlib.util
    import pathlib
    path = pathlib.Path(__file__).resolve().parent.parent \
        / "tools" / "derive_cbp444.py"
    spec = importlib.util.spec_from_file_location("derive_cbp444", path)
    derive_cbp444 = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(derive_cbp444)
    from selkies_tpu.codecs import h264_tables as T
    derived = derive_cbp444.derive()
    assert np.array_equal(derived, T.CBP444_INTER_CBP2CODE)
