"""Damage-proportional encoding (ROADMAP 4 / ISSUE 15): dirty-band
partial P encode.

Contracts pinned here:

- band geometry bucketing (ops/bands): pow-2 buckets, coverage, motion
  granularity, floors;
- the all-skip slice builder's bitstream format (codecs.h264
  .p_skip_slice_rbsp) field by field through the reference BitReader;
- **byte identity**: the partial path with a 100%-dirty damage map
  emits chunk-for-chunk identical bytes to the stock P step — zero-MV,
  motion-search, 4:4:4 and single-stream configurations;
- **decode validity**: partially-dirty frames (device band rows
  stitched against host-built skip slices) round-trip through the
  reference decoder to EXACTLY the server-side reconstruction, and the
  partial path's paint-over refines as P frames like the stock path;
- idle frames dispatch nothing (the out dict says so);
- bands x stripes composition: a stripe-sharded session gates the
  partial path OFF (keeping the device-parallel stock steps) and stays
  byte-identical to the unsharded stock session;
- ROI QP (per-MB qp plane + real mb_qp_delta syntax) stays oracle-exact;
- the prewarm lattice grows the bands axis (program_key + plan names).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from selkies_tpu.codecs import h264 as hcodec  # noqa: E402
from selkies_tpu.codecs import h264_ref_decoder as refdec  # noqa: E402
from selkies_tpu.engine.h264_encoder import (  # noqa: E402
    H264EncoderSession, StripeShardedH264Session)
from selkies_tpu.engine.types import CaptureSettings  # noqa: E402
from selkies_tpu.ops.bands import (band_buckets, dirty_fraction,  # noqa: E402
                                   plan_band)

W = H = 64
BASE = dict(capture_width=W, capture_height=H, stripe_height=32,
            output_mode="h264", video_crf=28, use_paint_over=False,
            h264_motion_vrange=0, h264_motion_hrange=0)

rng = np.random.default_rng(1234)


# ---------------------------------------------------------------- geometry
def test_band_buckets():
    assert band_buckets(9) == (1, 2, 4, 8, 9)
    assert band_buckets(8) == (1, 2, 4, 8)
    assert band_buckets(8, granularity=2) == (2, 4, 8)
    assert band_buckets(12, granularity=4) == (4, 8, 12)
    with pytest.raises(ValueError):
        band_buckets(0)


def test_plan_band_covers_needed_rows():
    R = 16
    for _ in range(200):
        rows = np.zeros(R, bool)
        n = rng.integers(1, 5)
        rows[rng.integers(0, R, n)] = True
        for g in (1, 4):
            row0, brows = plan_band(rows, granularity=g)
            assert row0 % g == 0
            assert brows in band_buckets(R, g)
            covered = np.zeros(R, bool)
            covered[row0:row0 + brows] = True
            assert (covered | ~rows).all(), (rows, row0, brows)


def test_plan_band_idle_and_floor():
    assert plan_band(np.zeros(8, bool)) is None
    rows = np.zeros(8, bool)
    rows[3] = True
    assert plan_band(rows)[1] == 1
    assert plan_band(rows, floor_rows=4)[1] == 4
    # floor above R clamps to the full frame
    assert plan_band(rows, floor_rows=99) == (0, 8)
    assert dirty_fraction(rows) == 1 / 8


# ------------------------------------------------------- skip-slice format
def test_p_skip_slice_rbsp_fields():
    mb_w, n_mbs, qp, fn = 4, 4, 31, 5
    rbsp = hcodec.p_skip_slice_rbsp(1 * mb_w, n_mbs, qp, fn)
    r = refdec.BitReader(rbsp)
    assert r.ue() == 1 * mb_w          # first_mb_in_slice
    assert r.ue() == 5                 # slice_type P
    assert r.ue() == 0                 # pps id
    assert r.u(4) == fn & 0xF          # frame_num
    assert r.u(1) == 0                 # num_ref_idx_override
    assert r.u(1) == 0                 # ref_pic_list_modification
    assert r.u(1) == 0                 # adaptive_ref_pic_marking
    assert r.se() == qp - 26           # slice_qp_delta
    assert r.ue() == 1                 # disable_deblocking_filter_idc
    assert r.ue() == n_mbs             # mb_skip_run == every MB skipped
    assert not r.more_rbsp_data()      # stop bit + zero pad only


# ----------------------------------------------------------- byte identity
def _chunks(sess, frames):
    out = []
    for t, f in enumerate(frames):
        out.append([(c.stripe_y, c.is_idr, c.payload) for c in
                    sess.finalize(sess.encode(f, force=(t == 0)))])
    return out


def _full_dirty_frames(n=3):
    f0 = rng.integers(0, 256, (H, W, 3), dtype=np.uint8)
    return [jnp.asarray(np.roll(f0, 5 * t, axis=0)) for t in range(n)]


@pytest.mark.parametrize("cfg", [
    {},                                                   # zero-MV
    {"h264_motion_vrange": 8, "h264_motion_hrange": 2},   # motion bands
    {"fullcolor": True},                                  # 4:4:4
    {"single_stream": True},                              # one stream
], ids=["zeromv", "motion", "444", "single"])
def test_partial_full_dirty_byte_identical_to_stock(cfg):
    frames = _full_dirty_frames()
    kw = dict(BASE, **cfg)
    a = _chunks(H264EncoderSession(
        CaptureSettings(**kw, h264_partial_encode=True)), frames)
    b = _chunks(H264EncoderSession(
        CaptureSettings(**kw, h264_partial_encode=False)), frames)
    assert a == b


# --------------------------------------------------------- decode validity
def _partial_script():
    base = rng.integers(0, 256, (H, W, 3), dtype=np.uint8)
    script = [base.copy()]
    f = base.copy()
    f[16:32, 0:32] = rng.integers(0, 256, (16, 32, 3), dtype=np.uint8)
    script.append(f.copy())
    script.append(f.copy())                        # idle frame
    g = f.copy()
    g[H - 16:H, :] = rng.integers(0, 256, (16, W, 3), dtype=np.uint8)
    script.append(g)
    return [jnp.asarray(x) for x in script]


def _assert_oracle_matches_refs(sess, per_stripe):
    sh = sess.grid.stripe_h
    assert per_stripe, "no chunks delivered"
    for y0, payloads in per_stripe.items():
        y, u, v = refdec.decode(b"".join(payloads))
        assert np.array_equal(y, np.asarray(sess._ref_y)[y0:y0 + sh])
        assert np.array_equal(
            u, np.asarray(sess._ref_u)[y0 // 2:(y0 + sh) // 2])
        assert np.array_equal(
            v, np.asarray(sess._ref_v)[y0 // 2:(y0 + sh) // 2])


def test_partial_frames_decode_valid_and_idle_skips_device():
    sess = H264EncoderSession(
        CaptureSettings(**BASE, h264_partial_encode=True))
    frames = _partial_script()
    per_stripe = {}
    outs = []
    for t, f in enumerate(frames):
        out = sess.encode(f, force=(t == 0))
        outs.append(out)
        for c in sess.finalize(out):
            per_stripe.setdefault(c.stripe_y, []).append(c.payload)
    # t=1 damaged one MB row -> a 1-row band, not a full dispatch
    assert outs[1]["band"] == (1, 1)
    assert outs[1]["dirty_fraction"] == pytest.approx(0.25)
    # t=2 was content-identical -> idle: no device dispatch at all
    assert outs[2].get("idle") is True and "data" not in outs[2]
    # client reconstruction == server reference, bit for bit
    _assert_oracle_matches_refs(sess, per_stripe)


def test_partial_paint_over_refines_as_p_band():
    kw = dict(BASE, use_paint_over=True)
    sess = H264EncoderSession(CaptureSettings(
        **kw, h264_partial_encode=True))
    sess.settings.paint_over_delay_frames = 3
    base = rng.integers(0, 256, (H, W, 3), dtype=np.uint8)
    f = base.copy()
    f[0:16] = rng.integers(0, 256, (16, W, 3), dtype=np.uint8)
    per_stripe = {}
    paint_chunks = None
    frames = [base, f] + [f] * 6
    for t, fr in enumerate(frames):
        out = sess.encode(jnp.asarray(fr), force=(t == 0))
        chunks = sess.finalize(out)
        for c in chunks:
            per_stripe.setdefault(c.stripe_y, []).append(c.payload)
        if t >= 2 and chunks:
            # the settled stripe comes back once, at paint qp, as P
            assert np.any(np.asarray(out["is_paint"]))
            assert all(not c.is_idr for c in chunks)
            paint_chunks = chunks
    assert paint_chunks is not None, "paint-over never fired"
    _assert_oracle_matches_refs(sess, per_stripe)


def test_partial_composes_with_stripe_sharding():
    """A sharded session GATES the partial path off (a single-device
    band step would forfeit the N-way scaling under full motion, and
    the probe would dispatch sharded state the prewarmed program was
    not built for) and keeps the stock device-parallel steps — still
    byte-identical to the unsharded STOCK session (sharding stays a
    pure distribution axis, the PR-12 contract). The stock step
    refines clean rows against the lossy reference, so stock and
    partial only coincide at 100% dirty — the identity tests above."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (forced host) devices")
    frames = _partial_script()
    ref = H264EncoderSession(
        CaptureSettings(**BASE, h264_partial_encode=False))
    shard = StripeShardedH264Session(
        CaptureSettings(**BASE, h264_partial_encode=True,
                        stripe_devices=2))
    assert shard.stripe_devices == 2
    assert not shard._partial
    assert _chunks(ref, frames) == _chunks(shard, frames)


# ------------------------------------------------------------------ ROI QP
def test_roi_qp_oracle_round_trip():
    sess = H264EncoderSession(CaptureSettings(
        **BASE, h264_partial_encode=True, h264_roi_qp=True,
        h264_roi_qp_bias=6))
    frames = _partial_script()
    per_stripe = {}
    for t, f in enumerate(frames):
        for c in sess.finalize(sess.encode(f, force=(t == 0))):
            per_stripe.setdefault(c.stripe_y, []).append(c.payload)
    _assert_oracle_matches_refs(sess, per_stripe)


def test_roi_qp_emits_nonzero_mb_qp_delta():
    """The ROI plane must reach the WIRE as mb_qp_delta syntax, not
    just the quantiser: decode the band slice of a mixed
    damaged/settled row and confirm a non-zero delta was parsed."""
    from selkies_tpu.ops.h264_planes import h264_encode_p_yuv
    Rr, M = 2, 4
    hh, ww = Rr * 16, M * 16
    cur = rng.integers(0, 256, (hh, ww), dtype=np.int32)
    ref_y = cur.copy()
    cur[0:16, 0:16] = rng.integers(0, 256, (16, 16), dtype=np.int32)
    cur[0:16, 32:64] = np.clip(ref_y[0:16, 32:64] + 40, 0, 255)
    ref_u = rng.integers(0, 256, (hh // 2, ww // 2), dtype=np.int32)
    ref_v = rng.integers(0, 256, (hh // 2, ww // 2), dtype=np.int32)
    pay, nb = hcodec.p_slice_header_events(M, Rr)
    qp = 30
    qp_mb = np.full((Rr, M), qp, np.int32)
    qp_mb[0, 0] = qp - 6                  # "damaged" MB sharpens
    out, _ = h264_encode_p_yuv(
        jnp.asarray(cur), jnp.asarray(ref_u), jnp.asarray(ref_v),
        jnp.asarray(ref_y), jnp.asarray(ref_u), jnp.asarray(ref_v),
        qp, jnp.asarray(pay), jnp.asarray(nb), 1, 200, 2048,
        qp_mb=jnp.asarray(qp_mb))
    from selkies_tpu.ops.stripes import words_to_bytes_device
    by, lens = words_to_bytes_device(out.words, out.total_bits,
                                     pad_ones=False)
    row0 = bytes(np.asarray(by[0][:int(lens[0])]))
    r = refdec.BitReader(row0)
    r.ue(); r.ue(); r.ue(); r.u(4); r.u(1); r.u(1); r.u(1)
    assert r.se() == qp - 26
    r.ue()                                 # deblock idc
    assert r.ue() == 0                     # skip run 0 (MB 0 coded)
    assert r.ue() == 0                     # mb_type P_L0_16x16
    r.se(); r.se()                         # mvd
    cbp = refdec.T.CBP_INTER_CODE2CBP[r.ue()]
    assert cbp != 0
    assert r.se() == -6                    # mb_qp_delta reaches the wire


# ------------------------------------------------------------ lattice axis
def test_lattice_gains_bands_axis():
    from selkies_tpu.prewarm.lattice import Signature
    from selkies_tpu.prewarm.plan import program_names
    sig = Signature(width=64, height=64, codec="h264", stripe_height=32,
                    h264_motion_vrange=0, partial_encode=True)
    assert "bands" in sig.program_key
    names = program_names(sig)
    assert any("row_probe" in n for n in names)
    # zero-MV partial: MB-row-granular buckets 1, 2, 4
    assert [n for n in names if ".band" in n] == [
        f"h264.band{b}.p_step[64x64]" for b in (1, 2, 4)]
    # motion partial: stripe-granular buckets only
    sig_m = Signature(width=64, height=64, codec="h264", stripe_height=32,
                      h264_motion_vrange=8, partial_encode=True)
    assert [n for n in program_names(sig_m) if ".band" in n] == [
        f"h264.band{b}.p_step[64x64]" for b in (2, 4)]
    # partial off: no band programs, unchanged key shape
    sig_off = Signature(width=64, height=64, codec="h264",
                        stripe_height=32, partial_encode=False)
    assert "bands" not in sig_off.program_key
    assert not any(".band" in n for n in program_names(sig_off))
