"""Device (jax) H.264 encoder vs the numpy golden encoder: the bitstreams
must be BIT-IDENTICAL, and the assembled Annex-B must decode in the
independent oracles."""

import numpy as np
import pytest

from selkies_tpu.codecs import h264 as H
from selkies_tpu.codecs import h264_ref_decoder as refdec
from selkies_tpu.native import avshim

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from selkies_tpu.ops.bitpack import words_to_bytes  # noqa: E402
from selkies_tpu.ops.h264_encode import (SLOTS_MB,  # noqa: E402
                                         h264_encode_yuv)


def _device_rows(y, u, v, qp):
    """Run the device encoder; return per-row RBSP bytes."""
    R = y.shape[0] // 16
    M = y.shape[1] // 16
    pay, nb = H.slice_header_events(M, R)
    slots = 7 + M * SLOTS_MB + 1
    e_cap = slots
    w_cap = max(4096, (M * 16 * 16 * 4) // 4)   # generous bits/row
    out = h264_encode_yuv(jnp.asarray(y), jnp.asarray(u), jnp.asarray(v),
                          qp, jnp.asarray(pay), jnp.asarray(nb),
                          e_cap, w_cap)
    assert not bool(np.asarray(out.overflow))
    words = np.asarray(out.words)
    bits = np.asarray(out.total_bits)
    return [words_to_bytes(words[r], int(bits[r]), pad_ones=False)
            for r in range(R)]


def _host_rows(y, u, v, qp):
    """Golden encoder per-row slice RBSPs (strip NAL wrapper)."""
    enc = H.I16Encoder(y.shape[1], y.shape[0], qp)
    frame = enc.encode_frame(y, u, v)
    rows = []
    for part in frame.split(b"\x00\x00\x00\x01")[1:]:
        rows.append(refdec.remove_emulation_prevention(part[1:]))
    return rows, enc


@pytest.mark.parametrize("qp", [16, 26, 38])
def test_device_bitstream_matches_golden(qp):
    rng = np.random.default_rng(qp)
    h, w = 48, 64
    y = rng.integers(0, 256, (h, w), dtype=np.uint8)
    u = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)
    v = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)
    dev = _device_rows(y, u, v, qp)
    host, _ = _host_rows(y, u, v, qp)
    assert len(dev) == len(host) == 3
    for r, (d, g) in enumerate(zip(dev, host)):
        assert d == g, (
            f"row {r}: device {len(d)}B != golden {len(g)}B; "
            f"first diff at byte "
            f"{next((i for i in range(min(len(d), len(g))) if d[i] != g[i]), -1)}")


def test_device_stream_decodes_in_reference_decoder():
    rng = np.random.default_rng(0)
    h, w = 32, 48
    yy, xx = np.mgrid[0:h, 0:w]
    y = ((xx * 4 + yy * 2) % 256).astype(np.uint8)
    u = rng.integers(100, 156, (h // 2, w // 2), dtype=np.uint8)
    v = rng.integers(60, 200, (h // 2, w // 2), dtype=np.uint8)
    qp = 24
    dev = _device_rows(y, u, v, qp)
    _, enc = _host_rows(y, u, v, qp)
    annexb = enc.headers() + H.assemble_annexb(dev)
    my, mu, mv = refdec.decode(annexb)
    assert np.array_equal(my, enc.recon_y)
    assert np.array_equal(mu, enc.recon_u)
    assert np.array_equal(mv, enc.recon_v)


@pytest.mark.skipif(not avshim.available(), reason="libavcodec unavailable")
def test_device_stream_decodes_in_ffmpeg():
    rng = np.random.default_rng(3)
    h, w = 48, 64
    y = rng.integers(0, 256, (h, w), dtype=np.uint8)
    u = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)
    v = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)
    qp = 30
    dev = _device_rows(y, u, v, qp)
    _, enc = _host_rows(y, u, v, qp)
    annexb = enc.headers() + H.assemble_annexb(dev)
    ry, ru, rv = avshim.decode_h264(annexb)
    assert np.array_equal(ry, enc.recon_y)
    assert np.array_equal(ru, enc.recon_u)
    assert np.array_equal(rv, enc.recon_v)
