"""Engine-level H.264 session tests: damage gating, paint-over qp,
stripe independence, ScreenCapture integration."""

import time

import numpy as np
import pytest

from selkies_tpu.codecs import h264_ref_decoder as refdec
from selkies_tpu.engine import CaptureSettings, ScreenCapture
from selkies_tpu.engine.h264_encoder import H264EncoderSession
from selkies_tpu.engine.sources import SyntheticSource
from selkies_tpu.native import avshim

SMALL = dict(capture_width=64, capture_height=64, stripe_height=32,
             target_fps=120.0, output_mode="h264", video_crf=26,
             # small candidate set: keeps per-shape jit compiles fast; the
             # full ladder is exercised in test_h264_motion.py
             h264_motion_vrange=2, h264_motion_hrange=1)


def test_h264_session_stripes_decode():
    s = CaptureSettings(**SMALL)
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height)
    chunks = sess.finalize(sess.encode(src.get_frame(0)), force_all=True)
    assert len(chunks) == sess.grid.n_stripes == 2
    for c in chunks:
        assert c.output_mode == "h264" and c.is_idr
        assert c.payload.count(b"\x00\x00\x00\x01") == \
            2 + sess.grid.rows_per_stripe          # SPS+PPS+slices
        y, u, v = refdec.decode(c.payload)
        assert y.shape == (sess.grid.stripe_h, sess.grid.width)


def test_h264_damage_gating_and_refresh():
    s = CaptureSettings(**SMALL)
    s.use_paint_over = False
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height, static_after=0)
    first = sess.finalize(sess.encode(src.get_frame(0)))
    assert len(first) == sess.grid.n_stripes       # everything damaged
    still = sess.finalize(sess.encode(src.get_frame(1)))
    assert still == []                             # static -> silence
    forced = sess.finalize(sess.encode(src.get_frame(2), force=True))
    assert len(forced) == sess.grid.n_stripes      # keyframe refresh


def _parse_idr_pic_id(payload: bytes) -> int:
    """idr_pic_id of the first slice NAL in a stripe access unit."""
    for nal in refdec.split_nals(payload):
        if (nal[0] & 0x1F) == 5:
            r = refdec.BitReader(nal[1:])
            r.ue(); r.ue(); r.ue()      # first_mb, slice_type, pps_id
            r.u(4)                      # frame_num
            return r.ue()
    raise AssertionError("no IDR slice found")


def test_idr_pic_id_alternates_per_stripe_stream():
    """Consecutive IDRs of one stripe stream must differ in idr_pic_id
    (§7.4.3) even under damage gating — the parity counter lives on
    device."""
    s = CaptureSettings(**SMALL)
    s.use_paint_over = False
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height, static_after=0)
    ids = []
    for t in range(4):
        chunks = sess.finalize(sess.encode(src.get_frame(t), force=True))
        ids.append([_parse_idr_pic_id(c.payload) for c in chunks])
    for stripe in range(sess.grid.n_stripes):
        seq = [ids[t][stripe] for t in range(4)]
        assert all(a != b for a, b in zip(seq, seq[1:])), seq
    # gated pattern: IDRs sent on frames 0 and 2 only must still alternate
    anim = SyntheticSource(sess.grid.width, sess.grid.height)
    sess2 = H264EncoderSession(s)
    a = sess2.finalize(sess2.encode(anim.get_frame(0)))         # IDR
    sess2.finalize(sess2.encode(anim.get_frame(0)))             # silent
    b = sess2.finalize(sess2.encode(anim.get_frame(7), force=True))  # IDR
    assert len(a) and len(b)
    assert _parse_idr_pic_id(a[0].payload) != _parse_idr_pic_id(b[0].payload)


def test_h264_paint_over_refines_as_p_frames():
    """Paint-over in the I/P design is SNR refinement: a settled stripe is
    re-sent as a P frame at the better qp, coding only the residual
    between the coarse reconstruction and the source."""
    s = CaptureSettings(**SMALL)
    s.paint_over_delay_frames = 2
    sess = H264EncoderSession(s)
    sess.set_qp(40, paint_qp=12)
    src = SyntheticSource(sess.grid.width, sess.grid.height, static_after=0)
    motion = sess.finalize(sess.encode(src.get_frame(0)))   # frame 0 -> IDR
    assert all(c.is_idr for c in motion)
    sess.finalize(sess.encode(src.get_frame(1)))
    paint = sess.finalize(sess.encode(src.get_frame(2)))   # age hits delay
    assert len(paint) == sess.grid.n_stripes
    assert all(not c.is_idr for c in paint)                # refinement = P
    # the refinement pass visibly improves the on-device reconstruction
    import jax.numpy as jnp
    frame = np.asarray(src.get_frame(0))
    from selkies_tpu.ops.h264_encode import rgb_to_yuv420
    ys = np.asarray(rgb_to_yuv420(jnp.asarray(frame))[0])
    rec = np.asarray(sess._ref_y)
    mse_after = np.mean((rec.astype(float) - ys) ** 2)
    assert mse_after < 12.0, mse_after                     # near-lossless


def test_h264_recon_matches_decoders():
    """The engine's stream must land byte-exact in the reference decoder
    and (when present) ffmpeg."""
    s = CaptureSettings(**SMALL)
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height)
    chunks = sess.finalize(sess.encode(src.get_frame(3)), force_all=True)
    for c in chunks:
        my, mu, mv = refdec.decode(c.payload)
        if avshim.available():
            ry, ru, rv = avshim.decode_h264(c.payload)
            assert np.array_equal(my, ry)
            assert np.array_equal(mu, ru)
            assert np.array_equal(mv, rv)


def test_screen_capture_h264_mode_delivers():
    got = []
    cap = ScreenCapture(source_kind="synthetic")
    cap.start_capture(got.append, CaptureSettings(**SMALL))
    # first chunk pays jit compile (slow on a loaded 1-core CI box);
    # after that, chunks must flow at frame cadence
    first_by = time.time() + 300
    while time.time() < first_by and not got:
        time.sleep(0.05)
    deadline = time.time() + 30
    while time.time() < deadline and len(got) < 4:
        time.sleep(0.05)
    cap.stop_capture()
    assert len(got) >= 4
    assert all(c.output_mode == "h264" for c in got)
    y, _, _ = refdec.decode(got[0].payload)
    assert y.shape[1] == 64


def test_h264_ip_sequence_cross_decoders():
    """The adaptive I/P stream: every stripe's IDR+P sequence must decode
    identically in the spec decoder and (when present) ffmpeg, and P
    deltas must appear alongside the initial IDRs."""
    s = CaptureSettings(**SMALL)
    s.use_paint_over = False
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height)
    per_stripe: dict[int, list[bytes]] = {}
    i_bytes = p_bytes = 0
    for t in range(4):
        for c in sess.finalize(sess.encode(src.get_frame(t * 3))):
            per_stripe.setdefault(c.stripe_y, []).append(c.payload)
            if c.is_idr:
                i_bytes += len(c.payload)
            else:
                p_bytes += len(c.payload)
    assert p_bytes > 0
    for y0, aus in per_stripe.items():
        my, mu, mv = refdec.decode(b"".join(aus))
        assert my.shape == (sess.grid.stripe_h, sess.grid.width)
        if avshim.available():
            ses = avshim.H264Session()
            out = None
            for au in aus:                 # each chunk is one access unit
                got = ses.decode(au)
                if got is not None:
                    out = got
            tail = ses.flush()
            if tail is not None:
                out = tail
            ry, ru, rv = out
            assert np.array_equal(my, ry), f"stripe {y0}"
            assert np.array_equal(mu, ru) and np.array_equal(mv, rv)


def test_cbr_rate_control_converges():
    """Per-frame leaky-bucket CBR (VERDICT round-2 weak 5: the old
    1-second +-2 nudge was unvalidated): fully-animated content must
    settle near the bitrate target."""
    import time as _time

    from selkies_tpu.engine.capture import ScreenCapture

    s = CaptureSettings(**SMALL)
    s.use_cbr = True
    s.video_bitrate_kbps = 200
    s.video_crf = 12                      # far too high a quality: the
    s.video_min_qp = 10                   # controller must pull it down
    s.video_max_qp = 46
    s.target_fps = 60.0
    got = []
    cap = ScreenCapture(source_kind="synthetic")
    cap.start_capture(got.append, s)
    deadline = _time.time() + 240
    while _time.time() < deadline and len(got) < 1400:
        _time.sleep(0.2)
    cap.stop_capture()
    assert len(got) >= 1400, f"only {len(got)} chunks"
    qp_now = cap._session.qp
    # steady state: the final ~100 frames only (the ramp is the
    # controller DOING its job, not steady state)
    tail = got[-200:]
    frames = {c.frame_id for c in tail}
    tail_bytes = sum(len(c.payload) for c in tail)
    kbps = tail_bytes * 8 / 1000 / (len(frames) / 60.0)
    assert qp_now > 12, f"controller never raised qp (qp={qp_now})"
    assert kbps < 200 * 1.5, f"steady-state {kbps:.0f} kbps vs 200 target"


def test_h264_session_fullcolor_stripes_decode():
    """fullcolor=True end-to-end through the engine: Hi444PP SPS
    (chroma_format_idc 3), full-resolution chroma out of ffmpeg, and the
    I -> P sequence decodes byte-exact against the device recon path
    (ops oracle chain: tests/test_h264_444.py)."""
    s = CaptureSettings(**SMALL)
    s.fullcolor = True
    s.use_paint_over = False
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height)
    per_stripe: dict[int, list[bytes]] = {}
    for t in range(3):
        for c in sess.finalize(sess.encode(src.get_frame(t * 4)),
                               force_all=(t == 0)):
            assert c.output_mode == "h264"
            per_stripe.setdefault(c.stripe_y, []).append(c.payload)
    assert len(per_stripe) == sess.grid.n_stripes
    if not avshim.available():
        pytest.skip("libavcodec unavailable")
    for y0, aus in per_stripe.items():
        ses = avshim.H264Session()
        out = None
        for au in aus:
            got = ses.decode(au)
            if got is not None:
                out = got
        tail = ses.flush()
        if tail is not None:
            out = tail
        ry, ru, rv = out
        assert ry.shape == (sess.grid.stripe_h, sess.grid.width)
        # 4:4:4: chroma planes are FULL resolution
        assert ru.shape == ry.shape and rv.shape == ry.shape, \
            f"stripe {y0}: chroma subsampled in a fullcolor stream"
