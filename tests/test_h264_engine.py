"""Engine-level H.264 session tests: damage gating, paint-over qp,
stripe independence, ScreenCapture integration."""

import time

import numpy as np
import pytest

from selkies_tpu.codecs import h264_ref_decoder as refdec
from selkies_tpu.engine import CaptureSettings, ScreenCapture
from selkies_tpu.engine.h264_encoder import H264EncoderSession
from selkies_tpu.engine.sources import SyntheticSource
from selkies_tpu.native import avshim

SMALL = dict(capture_width=64, capture_height=64, stripe_height=32,
             target_fps=120.0, output_mode="h264", video_crf=26)


def test_h264_session_stripes_decode():
    s = CaptureSettings(**SMALL)
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height)
    chunks = sess.finalize(sess.encode(src.get_frame(0)), force_all=True)
    assert len(chunks) == sess.grid.n_stripes == 2
    for c in chunks:
        assert c.output_mode == "h264" and c.is_idr
        assert c.payload.count(b"\x00\x00\x00\x01") == \
            2 + sess.grid.rows_per_stripe          # SPS+PPS+slices
        y, u, v = refdec.decode(c.payload)
        assert y.shape == (sess.grid.stripe_h, sess.grid.width)


def test_h264_damage_gating_and_refresh():
    s = CaptureSettings(**SMALL)
    s.use_paint_over = False
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height, static_after=0)
    first = sess.finalize(sess.encode(src.get_frame(0)))
    assert len(first) == sess.grid.n_stripes       # everything damaged
    still = sess.finalize(sess.encode(src.get_frame(1)))
    assert still == []                             # static -> silence
    forced = sess.finalize(sess.encode(src.get_frame(2), force=True))
    assert len(forced) == sess.grid.n_stripes      # keyframe refresh


def _parse_idr_pic_id(payload: bytes) -> int:
    """idr_pic_id of the first slice NAL in a stripe access unit."""
    for nal in refdec.split_nals(payload):
        if (nal[0] & 0x1F) == 5:
            r = refdec.BitReader(nal[1:])
            r.ue(); r.ue(); r.ue()      # first_mb, slice_type, pps_id
            r.u(4)                      # frame_num
            return r.ue()
    raise AssertionError("no IDR slice found")


def test_idr_pic_id_alternates_per_stripe_stream():
    """Consecutive IDRs of one stripe stream must differ in idr_pic_id
    (§7.4.3) even under damage gating — the parity counter lives on
    device."""
    s = CaptureSettings(**SMALL)
    s.use_paint_over = False
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height, static_after=0)
    ids = []
    for t in range(4):
        chunks = sess.finalize(sess.encode(src.get_frame(t), force=True))
        ids.append([_parse_idr_pic_id(c.payload) for c in chunks])
    for stripe in range(sess.grid.n_stripes):
        seq = [ids[t][stripe] for t in range(4)]
        assert all(a != b for a, b in zip(seq, seq[1:])), seq
    # gated pattern: sent on frames 0 and 2 only must still alternate
    anim = SyntheticSource(sess.grid.width, sess.grid.height)
    sess2 = H264EncoderSession(s)
    a = sess2.finalize(sess2.encode(anim.get_frame(0)))         # sent
    sess2.finalize(sess2.encode(anim.get_frame(0)))             # silent
    b = sess2.finalize(sess2.encode(anim.get_frame(7)))         # damaged
    assert len(a) and len(b)
    assert _parse_idr_pic_id(a[0].payload) != _parse_idr_pic_id(b[0].payload)


def test_h264_paint_over_uses_better_qp():
    s = CaptureSettings(**SMALL)
    s.paint_over_delay_frames = 2
    sess = H264EncoderSession(s)
    sess.set_qp(40, paint_qp=12)
    src = SyntheticSource(sess.grid.width, sess.grid.height, static_after=0)
    motion = sess.finalize(sess.encode(src.get_frame(0)), force_all=True)
    sess.finalize(sess.encode(src.get_frame(1)))
    paint = sess.finalize(sess.encode(src.get_frame(2)))   # age hits delay
    assert len(paint) == sess.grid.n_stripes
    assert all(p.is_idr for p in paint)
    # better qp -> noticeably bigger stripes
    assert sum(len(c.payload) for c in paint) > \
        1.2 * sum(len(c.payload) for c in motion)


def test_h264_recon_matches_decoders():
    """The engine's stream must land byte-exact in the reference decoder
    and (when present) ffmpeg."""
    s = CaptureSettings(**SMALL)
    sess = H264EncoderSession(s)
    src = SyntheticSource(sess.grid.width, sess.grid.height)
    chunks = sess.finalize(sess.encode(src.get_frame(3)), force_all=True)
    for c in chunks:
        my, mu, mv = refdec.decode(c.payload)
        if avshim.available():
            ry, ru, rv = avshim.decode_h264(c.payload)
            assert np.array_equal(my, ry)
            assert np.array_equal(mu, ru)
            assert np.array_equal(mv, rv)


def test_screen_capture_h264_mode_delivers():
    got = []
    cap = ScreenCapture(source_kind="synthetic")
    cap.start_capture(got.append, CaptureSettings(**SMALL))
    deadline = time.time() + 30
    while time.time() < deadline and len(got) < 4:
        time.sleep(0.05)
    cap.stop_capture()
    assert len(got) >= 4
    assert all(c.output_mode == "h264" for c in got)
    y, _, _ = refdec.decode(got[0].payload)
    assert y.shape[1] == 64
