"""Motion-search P frames (reference design.md:33 — the x264/NVENC class
encoders the reference rides all motion-search; this is the TPU analog).

Validation strategy: the device stream must land byte-exact in BOTH
independent decoders (in-tree spec decoder + ffmpeg), the in-tree decoder
must also byte-match ffmpeg on REAL x264 P/MV streams, and the size bar
is measured against libx264 on the same content (VERDICT round 2 item 3:
scrolling desktop at <= 2x x264 bytes)."""

import numpy as np
import pytest

from selkies_tpu.codecs import h264 as H
from selkies_tpu.codecs import h264_ref_decoder as refdec
from selkies_tpu.native import avshim

jnp = pytest.importorskip("jax.numpy")

from selkies_tpu.ops.bitpack import words_to_bytes  # noqa: E402
from selkies_tpu.ops.h264_encode import (P_SLOTS_MB, SLOTS_MB,  # noqa: E402
                                         h264_encode_p_yuv, h264_encode_yuv,
                                         scroll_candidates)

needs_av = pytest.mark.skipif(not avshim.available(),
                              reason="libavcodec unavailable")

QP = 28


def _texture(h, w, seed=1):
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    y = ((xx * 7 + yy * 13 + rng.integers(0, 32, (h, w))) % 256
         ).astype(np.uint8)
    u = rng.integers(90, 170, (h // 2, w // 2), dtype=np.uint8)
    v = rng.integers(90, 170, (h // 2, w // 2), dtype=np.uint8)
    return y, u, v


def _scrolled(y, u, v, dy, seed=9):
    """Content moves up by dy px; fresh rows appear at the bottom."""
    rng = np.random.default_rng(seed)
    h, w = y.shape
    y2 = np.empty_like(y)
    y2[:h - dy] = y[dy:]
    y2[h - dy:] = rng.integers(0, 256, (dy, w), dtype=np.uint8)
    cs = dy // 2
    u2, v2 = np.empty_like(u), np.empty_like(v)
    u2[:h // 2 - cs] = u[cs:]
    u2[h // 2 - cs:] = 128
    v2[:h // 2 - cs] = v[cs:]
    v2[h // 2 - cs:] = 128
    return y2, u2, v2


def _encode_idr(y, u, v):
    R, M = y.shape[0] // 16, y.shape[1] // 16
    pay, nb = H.slice_header_events(M, R)
    out, recon = h264_encode_yuv(
        jnp.asarray(y, jnp.int32), jnp.asarray(u, jnp.int32),
        jnp.asarray(v, jnp.int32), QP, jnp.asarray(pay), jnp.asarray(nb),
        16 + M * SLOTS_MB, 16384, want_recon=True)
    assert not bool(np.asarray(out.overflow))
    w_, b_ = np.asarray(out.words), np.asarray(out.total_bits)
    rows = [words_to_bytes(w_[r], int(b_[r]), pad_ones=False)
            for r in range(R)]
    return H.assemble_annexb(rows), recon


def _encode_p(y, u, v, recon, cands, frame_num=1):
    R, M = y.shape[0] // 16, y.shape[1] // 16
    pay, nb = H.p_slice_header_events(M, R)
    out, rec = h264_encode_p_yuv(
        jnp.asarray(y, jnp.int32), jnp.asarray(u, jnp.int32),
        jnp.asarray(v, jnp.int32), recon[0], recon[1], recon[2], QP,
        jnp.asarray(pay), jnp.asarray(nb), frame_num,
        16 + M * P_SLOTS_MB, 16384, candidates=cands)
    assert not bool(np.asarray(out.overflow))
    w_, b_ = np.asarray(out.words), np.asarray(out.total_bits)
    rows = [words_to_bytes(w_[r], int(b_[r]), pad_ones=False)
            for r in range(R)]
    au = b"".join(H.nal(1, rb, ref_idc=2) for rb in rows)
    return au, tuple(np.asarray(p) for p in rec)


def _check_oracles(headers, aus, final_recon):
    my, mu, mv = refdec.Decoder().decode(headers + b"".join(aus))
    assert np.array_equal(my, final_recon[0]), "spec decoder luma"
    assert np.array_equal(mu, final_recon[1]), "spec decoder U"
    assert np.array_equal(mv, final_recon[2]), "spec decoder V"
    if avshim.available():
        sess = avshim.H264Session()
        got = None
        for au in aus:
            got = sess.decode(headers + au if au is aus[0] else au) or got
        got = sess.flush() or got
        assert got is not None
        assert np.array_equal(got[0], final_recon[0]), "ffmpeg luma"
        assert np.array_equal(got[1], final_recon[1]), "ffmpeg U"
        assert np.array_equal(got[2], final_recon[2]), "ffmpeg V"


def test_vertical_scroll_motion_p():
    """Odd vertical scroll: exercises MV selection, MVD coding and the
    chroma half-pel path; the motion P must be much smaller than the
    zero-MV P and decode byte-exact in both oracles."""
    h, w = 48, 64
    y0, u0, v0 = _texture(h, w)
    idr, recon = _encode_idr(y0, u0, v0)
    y1, u1, v1 = _scrolled(y0, u0, v0, 5)
    au_zero, _ = _encode_p(y1, u1, v1, recon, ((0, 0),))
    au_mv, rec = _encode_p(y1, u1, v1, recon, scroll_candidates(8, 4))
    assert len(au_mv) < 0.5 * len(au_zero), \
        f"motion {len(au_mv)}B vs zero-mv {len(au_zero)}B"
    _check_oracles(H.write_sps(w, h) + H.write_pps(), [idr, au_mv], rec)


def test_horizontal_pan_motion_p():
    h, w = 48, 64
    y0, u0, v0 = _texture(h, w, seed=3)
    idr, recon = _encode_idr(y0, u0, v0)
    # pan right by 4: cur(x) = prev(x-4) -> candidate dx = -4
    y1 = np.roll(y0, 4, axis=1)
    u1 = np.roll(u0, 2, axis=1)
    v1 = np.roll(v0, 2, axis=1)
    au_zero, _ = _encode_p(y1, u1, v1, recon, ((0, 0),))
    au_mv, rec = _encode_p(y1, u1, v1, recon, scroll_candidates(4, 4))
    assert len(au_mv) < 0.6 * len(au_zero)
    _check_oracles(H.write_sps(w, h) + H.write_pps(), [idr, au_mv], rec)


def test_static_content_still_skips():
    """Unchanged content must still produce all-skip P frames (the zero
    candidate wins every tie) — motion search must not break P_Skip."""
    h, w = 32, 48
    y0, u0, v0 = _texture(h, w, seed=5)
    _, recon = _encode_idr(y0, u0, v0)
    ry = np.asarray(recon[0])
    ru = np.asarray(recon[1])
    rv = np.asarray(recon[2])
    au, _ = _encode_p(ry, ru, rv, recon, scroll_candidates(4, 2))
    # every row: header + one trailing skip_run + stop bit -> tiny
    assert len(au) < (h // 16) * 16, f"all-skip P should be tiny: {len(au)}B"


@needs_av
def test_refdec_matches_ffmpeg_on_x264_p_streams():
    """The in-tree decoder's motion path (median MV prediction, skip MV,
    integer-pel luma MC, eighth-pel chroma bilinear) against REAL x264
    P/MV streams: every decoded picture must byte-match ffmpeg."""
    h, w = 48, 64
    y0, u0, v0 = _texture(h, w, seed=11)
    ys, us, vs = [y0], [u0], [v0]
    for t, dy in enumerate((3, 7)):
        y, u, v = _scrolled(ys[-1], us[-1], vs[-1], dy, seed=20 + t)
        ys.append(y)
        us.append(u)
        vs.append(v)
    aus = avshim.encode_x264_seq(ys, us, vs, qp=QP)
    assert len(aus) == 3
    d = refdec.Decoder()
    ff = avshim.H264Session()
    stream = b""
    for i, au in enumerate(aus):
        stream += au
        my, mu, mv = refdec.Decoder().decode(stream)
        got = ff.decode(au) or ff.flush()
        assert got is not None, f"frame {i}: ffmpeg wants more data"
        assert np.array_equal(my, got[0]), f"frame {i} luma"
        assert np.array_equal(mu, got[1]), f"frame {i} U"
        assert np.array_equal(mv, got[2]), f"frame {i} V"
    del d


@needs_av
def test_scrolling_desktop_size_bar_vs_x264():
    """VERDICT round-2 item 3 'done' bar: a synthetic scrolling-desktop
    sequence must encode at <= 2x the bytes of libx264 (same qp, same
    content, P frames compared)."""
    h, w = 64, 96
    y0, u0, v0 = _texture(h, w, seed=13)
    ys, us, vs = [y0], [u0], [v0]
    for t in range(3):
        y, u, v = _scrolled(ys[-1], us[-1], vs[-1], 6, seed=30 + t)
        ys.append(y)
        us.append(u)
        vs.append(v)
    x264_aus = avshim.encode_x264_seq(ys, us, vs, qp=QP)
    x264_p_bytes = sum(len(a) for a in x264_aus[1:])

    _, recon = _encode_idr(y0, u0, v0)
    cands = scroll_candidates(8, 4)
    ours = 0
    for t in range(1, 4):
        au, rec = _encode_p(ys[t], us[t], vs[t], recon, cands, frame_num=t)
        ours += len(au)
        recon = tuple(jnp.asarray(p) for p in rec)
    ratio = ours / x264_p_bytes
    assert ratio <= 2.0, \
        f"ours {ours}B vs x264 {x264_p_bytes}B (ratio {ratio:.2f})"


def test_pure_motion_mb_no_residual_conformance():
    """An exact even-pel scroll at moderate qp yields coded MBs with
    mv != 0 and cbp == 0 (pure motion copy). Spec §7.3.5 forbids
    mb_qp_delta on those — regression for the desync this once caused
    (refdec IndexError, ffmpeg MB concealment in live streams)."""
    h, w = 48, 64
    y0, u0, v0 = _texture(h, w, seed=21)
    idr, recon = _encode_idr(y0, u0, v0)
    # scroll by 4 px: chroma shifts exactly 2 -> zero residual everywhere
    rng = np.random.default_rng(55)
    y1 = np.empty_like(y0)
    y1[:h - 4] = np.asarray(recon[0])[4:]       # recon content: exact match
    y1[h - 4:] = rng.integers(0, 256, (4, w), dtype=np.uint8)
    u1, v1 = np.empty_like(u0), np.empty_like(v0)
    u1[:h // 2 - 2] = np.asarray(recon[1])[2:]
    u1[h // 2 - 2:] = 128
    v1[:h // 2 - 2] = np.asarray(recon[2])[2:]
    v1[h // 2 - 2:] = 128
    au, rec = _encode_p(y1, u1, v1, recon, scroll_candidates(8, 4))
    _check_oracles(H.write_sps(w, h) + H.write_pps(), [idr, au], rec)
