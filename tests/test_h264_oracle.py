"""H.264 conformance tests (SURVEY.md §7 hard-part #3: golden-stream
checks from day one).

Two independent oracles:
- libavcodec (ffmpeg h264 decoder / libx264 encoder) via the native shim —
  shares NOTHING with our code;
- the in-tree numpy reference decoder — shares only the table module,
  whose entries these tests pin against the external oracle.

All pure-numpy (no jax import): safe to run anywhere.
"""

import numpy as np
import pytest

from selkies_tpu.codecs import h264 as H
from selkies_tpu.codecs import h264_ref_decoder as refdec
from selkies_tpu.codecs.h264 import BitWriter, _write_residual_block
from selkies_tpu.native import avshim

needs_av = pytest.mark.skipif(not avshim.available(),
                              reason="libavcodec shim unavailable")


def _content(h=32, w=48, seed=42):
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    return {
        "gradient": ((xx * 255 // w).astype(np.uint8),
                     np.full((h // 2, w // 2), 90, np.uint8),
                     np.full((h // 2, w // 2), 170, np.uint8)),
        "noise": (rng.integers(0, 256, (h, w), dtype=np.uint8),
                  rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8),
                  rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)),
    }


def test_cavlc_writer_reader_roundtrip():
    """Our CAVLC reader must invert our writer bit-exactly on random
    blocks across every nC context, including escape levels."""
    rng = np.random.default_rng(1)
    for _ in range(3000):
        max_coeff = int(rng.choice([4, 15, 16]))
        nc = -1 if max_coeff == 4 else int(rng.choice([0, 1, 2, 3, 4, 7, 9]))
        tc = int(rng.integers(0, max_coeff + 1))
        v = np.zeros(max_coeff, np.int64)
        if tc:
            pos = np.sort(rng.choice(max_coeff, size=tc, replace=False))
            mag = rng.integers(1, 60, size=tc)
            if rng.random() < 0.2:
                mag[0] = int(rng.integers(60, 500))
            v[pos] = mag * rng.choice([-1, 1], size=tc)
        w = BitWriter()
        _write_residual_block(w, v, nc, max_coeff)
        w.rbsp_trailing()
        r = refdec.BitReader(w.to_bytes())
        got = refdec.residual_block(r, nc, max_coeff)
        assert np.array_equal(got.astype(np.int64), v), (nc, max_coeff, v)


def test_encoder_decodes_with_own_reference_decoder():
    """In-tree closure: our encoder's stream through our decoder equals the
    encoder's own reconstruction (works without libavcodec)."""
    for name, (y, u, v) in _content().items():
        for qp in (14, 30):
            enc = H.I16Encoder(y.shape[1], y.shape[0], qp)
            bs = enc.headers() + enc.encode_frame(y, u, v)
            my, mu, mv = refdec.decode(bs)
            assert np.array_equal(my, enc.recon_y), (name, qp)
            assert np.array_equal(mu, enc.recon_u), (name, qp)
            assert np.array_equal(mv, enc.recon_v), (name, qp)


def test_encoder_psnr_reasonable():
    y, u, v = _content()["gradient"]
    enc = H.I16Encoder(y.shape[1], y.shape[0], qp=24)
    enc.headers()
    enc.encode_frame(y, u, v)
    mse = np.mean((enc.recon_y.astype(float) - y) ** 2)
    assert 10 * np.log10(255 ** 2 / max(mse, 1e-9)) > 38


@needs_av
def test_our_streams_decode_exactly_in_ffmpeg():
    """THE conformance gate: ffmpeg must reconstruct our bitstream to the
    byte-identical planes our encoder predicted."""
    for name, (y, u, v) in _content().items():
        for qp in (10, 24, 40):
            enc = H.I16Encoder(y.shape[1], y.shape[0], qp)
            bs = enc.headers() + enc.encode_frame(y, u, v)
            ry, ru, rv = avshim.decode_h264(bs)
            assert np.array_equal(ry, enc.recon_y), (name, qp)
            assert np.array_equal(ru, enc.recon_u), (name, qp)
            assert np.array_equal(rv, enc.recon_v), (name, qp)


@needs_av
def test_reference_decoder_matches_ffmpeg_on_x264_streams():
    """Decode real x264 CAVLC-I16 streams with both decoders: byte-equal
    planes pin every CAVLC table entry the streams exercise."""
    for name, (y, u, v) in _content().items():
        for qp in (12, 30, 44):
            bs = avshim.encode_x264_idr(y, u, v, qp=qp)
            ry, ru, rv = avshim.decode_h264(bs)
            my, mu, mv = refdec.decode(bs)
            assert np.array_equal(my, ry), (name, qp)
            assert np.array_equal(mu, ru), (name, qp)
            assert np.array_equal(mv, rv), (name, qp)


@needs_av
def test_multi_slice_per_row_streams():
    """Our slice-per-MB-row layout (the TPU parallelism contract) is
    conformant: a 64x48 frame = 3 row-slices must decode exactly."""
    y, u, v = _content(48, 64)["noise"]
    enc = H.I16Encoder(64, 48, qp=26)
    bs = enc.headers() + enc.encode_frame(y, u, v)
    assert bs.count(b"\x00\x00\x00\x01") == 5  # SPS + PPS + 3 slices
    ry, ru, rv = avshim.decode_h264(bs)
    assert np.array_equal(ry, enc.recon_y)


@needs_av
def test_non_mb_aligned_size_cropping():
    rng = np.random.default_rng(0)
    y = rng.integers(0, 256, (30, 52), dtype=np.uint8)
    u = rng.integers(0, 256, (15, 26), dtype=np.uint8)
    v = rng.integers(0, 256, (15, 26), dtype=np.uint8)
    enc = H.I16Encoder(52, 30, qp=26)
    bs = enc.headers() + enc.encode_frame(y, u, v)
    ry, ru, rv = avshim.decode_h264(bs)
    assert ry.shape == (30, 52) and ru.shape == (15, 26)
    assert np.array_equal(ry, enc.recon_y[:30, :52])
