"""Equivalence: the TPU-layout plane encoder (ops/h264_planes) must be
bit-identical to the reference-layout encoder (ops/h264_encode), which is
itself pinned to the numpy golden encoder and libavcodec (test_h264_device,
test_h264_oracle). Together these make the plane rewrite a pure layout
change with zero stream drift."""

import numpy as np
import jax.numpy as jnp
import pytest

from selkies_tpu.codecs import h264 as hc
from selkies_tpu.ops import h264_encode as He
from selkies_tpu.ops import h264_planes as Hp
from selkies_tpu.ops.bitpack import words_to_bytes


def _mkyuv(rng, H, W):
    return (jnp.asarray(rng.integers(0, 256, (H, W), np.int32)),
            jnp.asarray(rng.integers(0, 256, (H // 2, W // 2), np.int32)),
            jnp.asarray(rng.integers(0, 256, (H // 2, W // 2), np.int32)))


def _assert_same(ref, out, R):
    rb, ob = np.asarray(ref.total_bits), np.asarray(out.total_bits)
    assert np.array_equal(rb, ob)
    for r in range(R):
        a = words_to_bytes(np.asarray(ref.words)[r], int(rb[r]),
                           pad_ones=False)
        b = words_to_bytes(np.asarray(out.words)[r], int(ob[r]),
                           pad_ones=False)
        assert a == b, f"row {r} differs"
    assert bool(ref.overflow) == bool(out.overflow)


@pytest.mark.parametrize("qp", [10, 26, 42])
def test_i_path_bit_identical(qp):
    rng = np.random.default_rng(qp)
    H, W = 64, 96
    R, M = H // 16, W // 16
    yf, uf, vf = _mkyuv(rng, H, W)
    pay, nb = hc.slice_header_events(M, R)
    e_cap = 9 + M * He.SLOTS_MB + 2
    ref, rrec = He.h264_encode_yuv(yf, uf, vf, qp, jnp.asarray(pay),
                                   jnp.asarray(nb), e_cap, 2048,
                                   want_recon=True)
    out, orec = Hp.h264_encode_yuv(yf, uf, vf, qp, jnp.asarray(pay),
                                   jnp.asarray(nb), e_cap, 2048,
                                   want_recon=True)
    _assert_same(ref, out, R)
    for pr, po in zip(rrec, orec):
        assert np.array_equal(np.asarray(pr), np.asarray(po))


def test_i_path_per_row_qp_and_idr():
    rng = np.random.default_rng(7)
    H, W = 48, 64
    R, M = H // 16, W // 16
    yf, uf, vf = _mkyuv(rng, H, W)
    pay, nb = hc.slice_header_events(M, R)
    e_cap = 9 + M * He.SLOTS_MB + 2
    qp_rows = jnp.asarray([20, 31, 45], jnp.int32)
    idr_rows = jnp.asarray([0, 1, 1], jnp.int32)
    ref = He.h264_encode_yuv(yf, uf, vf, qp_rows, jnp.asarray(pay),
                             jnp.asarray(nb), e_cap, 2048,
                             idr_pic_id=idr_rows)
    out = Hp.h264_encode_yuv(yf, uf, vf, qp_rows, jnp.asarray(pay),
                             jnp.asarray(nb), e_cap, 2048,
                             idr_pic_id=idr_rows)
    _assert_same(ref, out, R)


@pytest.mark.parametrize("shift,qp", [(0, 26), (2, 18), (5, 38)])
def test_p_path_bit_identical(shift, qp):
    rng = np.random.default_rng(shift * 10 + qp)
    H, W = 64, 96
    R, M = H // 16, W // 16
    yf, uf, vf = _mkyuv(rng, H, W)
    ry = jnp.asarray(np.clip(
        np.roll(np.asarray(yf), shift, 0)
        + rng.integers(-2, 3, (H, W)), 0, 255).astype(np.uint8))
    ru = jnp.asarray(np.asarray(uf).astype(np.uint8))
    rv = jnp.asarray(np.asarray(vf).astype(np.uint8))
    pay, nb = hc.p_slice_header_events(M, R)
    e_cap = 9 + M * He.P_SLOTS_MB + 2
    cands = He.scroll_candidates(4, 2)
    ref, rrec = He.h264_encode_p_yuv(
        yf, uf, vf, ry, ru, rv, qp, jnp.asarray(pay), jnp.asarray(nb),
        3, e_cap, 4096, candidates=cands, stripe_rows=2)
    out, orec = Hp.h264_encode_p_yuv(
        yf, uf, vf, ry, ru, rv, qp, jnp.asarray(pay), jnp.asarray(nb),
        3, e_cap, 4096, candidates=cands, stripe_rows=2)
    _assert_same(ref, out, R)
    for pr, po in zip(rrec, orec):
        assert np.array_equal(np.asarray(pr), np.asarray(po))


def test_p_path_all_skip():
    """Encoding against one's own recon must produce all-skip rows in both
    implementations."""
    rng = np.random.default_rng(3)
    H, W = 32, 48
    R, M = H // 16, W // 16
    yf, uf, vf = _mkyuv(rng, H, W)
    pay_i, nb_i = hc.slice_header_events(M, R)
    e_cap_i = 9 + M * He.SLOTS_MB + 2
    _, rec = Hp.h264_encode_yuv(yf, uf, vf, 26, jnp.asarray(pay_i),
                                jnp.asarray(nb_i), e_cap_i, 2048,
                                want_recon=True)
    pay, nb = hc.p_slice_header_events(M, R)
    e_cap = 9 + M * He.P_SLOTS_MB + 2
    args = (yf, uf, vf, rec[0], rec[1], rec[2], 26, jnp.asarray(pay),
            jnp.asarray(nb), 1, e_cap, 4096)
    ref, _ = He.h264_encode_p_yuv(*args, candidates=((0, 0),))
    out, _ = Hp.h264_encode_p_yuv(*args, candidates=((0, 0),))
    _assert_same(ref, out, R)
