"""Input handler unit tests: key lifecycle, auto-repeat vs heartbeat,
stale-key sweep, clipboard multipart, gamepad state.

Deterministic time is injected via the handler's ``now`` parameter (the
testability seam the reference documents at selkies.py:1694-1696).
"""

import asyncio

from selkies_tpu.input.backends import NullBackend
from selkies_tpu.input.handler import (REPEAT_DELAY_S, STALE_KEY_S,
                                       InputHandler)


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make_handler():
    clock = Clock()
    backend = NullBackend()
    return InputHandler(backend=backend, now=clock), backend, clock


def run(coro):
    return asyncio.run(coro)


def test_key_down_up_roundtrip():
    h, b, _ = make_handler()
    run(h.on_message("kd,65"))
    run(h.on_message("ku,65"))
    assert b.events == [("key", 65, True), ("key", 65, False)]
    assert h.pressed == {}


def test_heartbeat_does_not_reset_repeat_delay():
    """A client heartbeating faster than REPEAT_DELAY_S must not suppress
    auto-repeat (round-1 advisor finding: press time and heartbeat time
    were conflated)."""
    h, b, clock = make_handler()
    run(h.on_message("kd,65"))
    # heartbeat every 0.2 s well past the repeat delay
    for i in range(1, 5):
        clock.t = i * 0.2
        run(h.on_message("kh,65"))
    clock.t = REPEAT_DELAY_S + 0.2
    assert h.repeat_once() == [65]
    assert b.events.count(("key", 65, True)) >= 2


def test_repeat_not_before_delay_and_not_for_modifiers():
    h, b, clock = make_handler()
    run(h.on_message("kd,65"))        # 'A'
    run(h.on_message("kd,65505"))     # Shift_L (modifier)
    clock.t = REPEAT_DELAY_S / 2
    assert h.repeat_once() == []
    clock.t = REPEAT_DELAY_S + 0.1
    assert h.repeat_once() == [65]    # modifier never repeats


def test_stale_sweep_uses_heartbeat_time():
    h, b, clock = make_handler()
    run(h.on_message("kd,65"))
    clock.t = 1.0
    run(h.on_message("kh,65"))        # heartbeat keeps it alive
    clock.t = 1.0 + STALE_KEY_S - 0.1
    assert h.sweep_stale_once() == []
    clock.t = 1.0 + STALE_KEY_S + 0.1
    assert h.sweep_stale_once() == [65]
    assert ("key", 65, False) in b.events
    assert h.pressed == {}


def test_kr_releases_everything():
    h, b, _ = make_handler()
    run(h.on_message("kd,65"))
    run(h.on_message("kd,66"))
    run(h.on_message("kr,"))
    assert h.pressed == {}
    assert ("key", 65, False) in b.events and ("key", 66, False) in b.events


def test_multipart_clipboard_respects_cap():
    h, b, _ = make_handler()
    h.clipboard_max = 16
    run(h.on_message("cws,"))
    run(h.on_message("cwd,QUFBQUFBQUFBQUFBQUFBQQ=="))  # 16 bytes of 'A'
    run(h.on_message("cwd,QkJCQg=="))                  # 4 more -> over cap
    run(h.on_message("cwe,"))
    assert b.clipboard[0] == b""                       # dropped, not partial


def test_multipart_clipboard_assembles():
    h, b, _ = make_handler()
    run(h.on_message("cws,"))
    run(h.on_message("cwd,aGVsbG8g"))   # "hello "
    run(h.on_message("cwd,d29ybGQ="))   # "world"
    run(h.on_message("cwe,"))
    assert b.clipboard == (b"hello world", "text/plain")


def test_gamepad_config_and_events():
    h, b, _ = make_handler()
    seen = []
    run(h.on_message("js,c,0,Xbox Pad"))
    h.gamepads[0].listeners.append(lambda k, n, v: seen.append((k, n, v)))
    run(h.on_message("js,b,0,3,1"))
    run(h.on_message("js,a,0,1,-0.5"))
    gp = h.gamepads[0]
    assert gp.connected and gp.name == "Xbox Pad"
    assert gp.buttons[3] == 1.0 and gp.axes[1] == -0.5
    assert seen == [("b", 3, 1.0), ("a", 1, -0.5)]
