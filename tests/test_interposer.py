"""End-to-end LD_PRELOAD interposer test: a real subprocess opens
/dev/input/js0 through the compiled .so and receives events served by
GamepadSocketServer — the full game-side data path without a kernel
device."""

import asyncio
import os
import pathlib
import shutil
import struct
import subprocess
import sys

import pytest

from selkies_tpu.input.gamepad import GamepadSocketServer

ADDON = pathlib.Path(__file__).resolve().parent.parent / "addons" / "js-interposer"
SO = ADDON / "selkies_joystick_interposer.so"

CLIENT_SCRIPT = r"""
import fcntl, os, struct, sys
fd = os.open("/dev/input/js0", os.O_RDONLY)
# JSIOCGAXES / JSIOCGBUTTONS / JSIOCGNAME
buf = bytearray(1)
fcntl.ioctl(fd, 0x80016a11, buf); axes = buf[0]
buf = bytearray(1)
fcntl.ioctl(fd, 0x80016a12, buf); btns = buf[0]
name = bytearray(128)
fcntl.ioctl(fd, 0x80006a13 | (128 << 16), name)
print(f"CFG axes={axes} btns={btns} name={name.split(b'\x00')[0].decode()}",
      flush=True)
ev = os.read(fd, 8)
t, val, typ, num = struct.unpack("<IhBB", ev)
print(f"EVENT val={val} type={typ} num={num}", flush=True)
os.close(fd)
"""


@pytest.mark.skipif(shutil.which("gcc") is None, reason="no gcc")
def test_interposer_end_to_end(tmp_path):
    if not SO.exists() or SO.stat().st_mtime < (ADDON / "selkies_joystick_interposer.c").stat().st_mtime:
        subprocess.run(["make", "-C", str(ADDON)], check=True,
                       capture_output=True)

    async def run():
        srv = GamepadSocketServer(0, str(tmp_path))
        await srv.start()
        env = dict(os.environ,
                   LD_PRELOAD=str(SO),
                   SELKIES_JS_SOCKET_PATH=str(tmp_path))
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-c", CLIENT_SCRIPT, env=env,
            stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE)

        cfg_line = await asyncio.wait_for(proc.stdout.readline(), 15)
        assert b"CFG axes=8 btns=11" in cfg_line, cfg_line
        assert b"Microsoft X-Box 360 pad" in cfg_line

        # wait for the client to appear, then press W3C button A
        for _ in range(100):
            if srv._js_clients:
                break
            await asyncio.sleep(0.05)
        assert srv._js_clients
        srv.report_button(0, 1.0)
        ev_line = await asyncio.wait_for(proc.stdout.readline(), 10)
        assert b"EVENT val=1 type=1 num=0" in ev_line, ev_line

        await asyncio.wait_for(proc.wait(), 10)
        stderr = await proc.stderr.read()
        assert proc.returncode == 0, stderr.decode()
        await srv.stop()

    asyncio.run(run())
