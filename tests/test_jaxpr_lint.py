"""graftlint v3 (--jaxpr): per-rule firing + non-firing fixtures over
hand-built TracedStep/SignatureTrace records (pure logic, no jax),
trace_step fidelity on tiny real jits, the budgets table round-trip,
and the slow full-surface ratchet: current findings ⊆ the checked-in
tools/jaxpr_baseline.json with every registered step actually traced."""
import json
import os
from pathlib import Path

import pytest

from selkies_tpu.analysis.core import Severity, load_baseline, new_findings
from selkies_tpu.analysis.jaxpr_lint import (DTYPE_DRIFT_FACTOR,
                                             TEMP_HEADROOM, JAXPR_RULES,
                                             lint_report, load_budgets,
                                             make_jaxpr_baseline)
from selkies_tpu.analysis.surface import (SignatureTrace, SurfaceReport,
                                          TracedStep)

REPO = Path(__file__).resolve().parent.parent
BASELINE = REPO / "tools" / "jaxpr_baseline.json"


def _step(**kw) -> TracedStep:
    base = dict(name="fix.step", program_key="pk", n_eqns=3,
                donated=(), aliased=(), forwarded=(), dropped=(),
                callbacks=(), float_temps=(), has_f64=False,
                int_plane=True, max_input_bytes=1024, arg_bytes=4096,
                temp_bytes=100)
    base.update(kw)
    return TracedStep(**base)


def _sig(**kw) -> SignatureTrace:
    base = dict(program_key="pk", predicted=("a", "b"), built=("a", "b"),
                lattice_key="pk", unreachable=None)
    base.update(kw)
    return SignatureTrace(**base)


def _report(*steps, signatures=(), errors=()) -> SurfaceReport:
    return SurfaceReport(steps=list(steps), signatures=list(signatures),
                         errors=list(errors))


def _by_rule(findings, rule):
    return [f for f in findings if f.rule_id == rule]


BUDGET = {"fix.step": 100}


# -- JAXPR-DONATION-ALIAS ----------------------------------------------------

def test_donated_not_aliased_fires():
    fs = lint_report(_report(_step(donated=(False, True), aliased=())),
                     BUDGET)
    f, = _by_rule(fs, "JAXPR-DONATION-ALIAS")
    assert f.source == "arg1 donated but not aliased"
    assert f.severity == Severity.ERROR
    assert f.path == "jaxpr://fix.step"


def test_donated_and_aliased_is_clean():
    fs = lint_report(_report(_step(donated=(False, True), aliased=(1,))),
                     BUDGET)
    assert not _by_rule(fs, "JAXPR-DONATION-ALIAS")


def test_forwarded_donation_fires_even_when_aliased():
    # XLA lists a forwarded param in the alias map, but returning the
    # very buffer the runtime marked consumed is the PR-10 hazard
    fs = lint_report(_report(_step(donated=(True,), aliased=(0,),
                                   forwarded=(0,))), BUDGET)
    f, = _by_rule(fs, "JAXPR-DONATION-ALIAS")
    assert f.source == "arg0 donated but forwarded"


def test_dropped_donation_fires():
    fs = lint_report(_report(_step(donated=(True, False), dropped=(0,))),
                     BUDGET)
    f, = _by_rule(fs, "JAXPR-DONATION-ALIAS")
    assert f.source == "arg0 donated but unused"


def test_dropped_non_donated_arg_is_clean():
    fs = lint_report(_report(_step(donated=(False, True), aliased=(1,),
                                   dropped=(0,))), BUDGET)
    assert not _by_rule(fs, "JAXPR-DONATION-ALIAS")


# -- JAXPR-HOST-CALLBACK -----------------------------------------------------

def test_callback_fires_per_primitive():
    fs = lint_report(_report(_step(callbacks=("debug_print",
                                              "pure_callback"))), BUDGET)
    srcs = {f.source for f in _by_rule(fs, "JAXPR-HOST-CALLBACK")}
    assert srcs == {"callback debug_print", "callback pure_callback"}


def test_no_callbacks_is_clean():
    assert not _by_rule(lint_report(_report(_step()), BUDGET),
                        "JAXPR-HOST-CALLBACK")


# -- JAXPR-DTYPE-DRIFT -------------------------------------------------------

def test_f64_always_fires_as_error():
    fs = lint_report(_report(_step(
        has_f64=True,
        float_temps=((8192, "float64", "32x32", "convert_element_type"),)
    )), BUDGET)
    f, = _by_rule(fs, "JAXPR-DTYPE-DRIFT")
    assert f.source == "f64 intermediate"
    assert f.severity == Severity.ERROR


def test_f32_blowup_fires_only_past_factor():
    big = int(DTYPE_DRIFT_FACTOR * 1024) + 4
    fs = lint_report(_report(_step(
        float_temps=((big, "float32", "64x64x32", "mul"),))), BUDGET)
    f, = _by_rule(fs, "JAXPR-DTYPE-DRIFT")
    assert f.source == "float32[64x64x32] mul"
    assert f.severity == Severity.WARNING
    # the legitimate CSC path (~4x the input plane) stays silent
    fs = lint_report(_report(_step(
        float_temps=((4 * 1024, "float32", "32x32x4", "mul"),))), BUDGET)
    assert not _by_rule(fs, "JAXPR-DTYPE-DRIFT")


def test_f32_blowup_silent_on_float_pipeline():
    big = int(DTYPE_DRIFT_FACTOR * 1024) + 4
    fs = lint_report(_report(_step(
        int_plane=False,
        float_temps=((big, "float32", "64x64x32", "mul"),))), BUDGET)
    assert not _by_rule(fs, "JAXPR-DTYPE-DRIFT")


def test_f32_blowup_one_finding_per_step():
    big = int(DTYPE_DRIFT_FACTOR * 1024)
    fs = lint_report(_report(_step(
        float_temps=((big + 8, "float32", "a", "mul"),
                     (big + 4, "float32", "b", "add")))), BUDGET)
    assert len(_by_rule(fs, "JAXPR-DTYPE-DRIFT")) == 1


# -- JAXPR-TEMP-BYTES --------------------------------------------------------

def test_unbudgeted_step_fires():
    fs = lint_report(_report(_step()), {})
    f, = _by_rule(fs, "JAXPR-TEMP-BYTES")
    assert f.source == "unbudgeted step"


def test_over_budget_fires_within_headroom_is_clean():
    at_headroom = int(100 * TEMP_HEADROOM)
    fs = lint_report(_report(_step(temp_bytes=at_headroom)), BUDGET)
    assert not _by_rule(fs, "JAXPR-TEMP-BYTES")
    fs = lint_report(_report(_step(temp_bytes=at_headroom + 1)), BUDGET)
    f, = _by_rule(fs, "JAXPR-TEMP-BYTES")
    assert f.source == "temp bytes over budget"


# -- LATTICE-COMPLETENESS ----------------------------------------------------

def test_unpredicted_and_ghost_programs_fire():
    fs = lint_report(_report(signatures=[
        _sig(predicted=("a", "ghost"), built=("a", "surprise"))]), {})
    srcs = {f.source for f in _by_rule(fs, "LATTICE-COMPLETENESS")}
    assert srcs == {"unpredicted program surprise",
                    "ghost program ghost"}
    assert all(f.path == "lattice://pk"
               for f in _by_rule(fs, "LATTICE-COMPLETENESS"))


def test_lattice_roundtrip_mismatch_fires():
    fs = lint_report(_report(signatures=[_sig(lattice_key="other")]), {})
    f, = _by_rule(fs, "LATTICE-COMPLETENESS")
    assert f.source == "lattice round-trip mismatch"


def test_matching_signature_is_clean():
    assert not lint_report(_report(signatures=[_sig()]), {})


def test_unknown_roundtrip_key_does_not_fire():
    # lattice_from_settings failing is reported as a trace error by the
    # CLI, not double-counted as a completeness finding
    assert not lint_report(_report(signatures=[_sig(lattice_key=None)]),
                           {})


# -- report-level contract ---------------------------------------------------

def test_disabled_rule_and_severity_override():
    rep = _report(_step(donated=(True,), dropped=(0,)))
    assert not lint_report(rep, BUDGET,
                           disabled=["jaxpr-donation-alias"])
    fs = lint_report(
        rep, BUDGET,
        severity_overrides={"JAXPR-DONATION-ALIAS": Severity.INFO})
    f, = _by_rule(fs, "JAXPR-DONATION-ALIAS")
    assert f.severity == Severity.INFO


def test_findings_sorted_and_stable():
    rep = _report(_step(name="z.step", callbacks=("debug_print",)),
                  _step(name="a.step", callbacks=("debug_print",)))
    fs = lint_report(rep, {"z.step": 100, "a.step": 100})
    assert [f.path for f in fs] == ["jaxpr://a.step", "jaxpr://z.step"]


def test_baseline_budgets_roundtrip():
    rep = _report(_step(name="s1", temp_bytes=123),
                  _step(name="s2", temp_bytes=456,
                        callbacks=("debug_print",)))
    fs = lint_report(rep, {"s1": 123, "s2": 456})
    doc = make_jaxpr_baseline(fs, rep)
    assert doc["budgets"] == {"s1": 123, "s2": 456}
    assert load_budgets(doc) == {"s1": 123, "s2": 456}
    assert load_budgets(None) == {}
    assert load_budgets({"budgets": "garbage"}) == {}
    # baseline identity is (path, rule, source): the same finding is
    # recognised across recompiles that shuffle byte counts
    again = lint_report(rep, {"s1": 123, "s2": 456})
    assert not new_findings(again, doc)


# -- trace_step fidelity on real (tiny) jits ---------------------------------

def test_trace_step_maps_alias_params_through_pruned_args():
    """jit prunes unused args, shifting compiled param numbering; the
    analyzer must report flat-arg indices, not compiled-param ones."""
    jax = pytest.importorskip("jax")
    import functools

    import jax.numpy as jnp

    from selkies_tpu.analysis.surface import trace_step

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def f(a, b, c):     # b pruned: donated+dropped; c aliases
        return a + c, jnp.bitwise_xor(c, jnp.uint8(1))

    aval = jax.ShapeDtypeStruct((32,), jnp.uint8)
    st = trace_step(f, (aval, aval, aval), name="fix.pruned")
    assert st.dropped == (1,)
    assert st.donated == (False, True, True)
    assert 2 in st.aliased          # arg index, not shifted param index
    fs = lint_report(_report(st), {"fix.pruned": st.temp_bytes})
    f, = _by_rule(fs, "JAXPR-DONATION-ALIAS")
    assert f.source == "arg1 donated but unused"


def test_trace_step_flags_forwarded_donation():
    jax = pytest.importorskip("jax")
    import functools

    import jax.numpy as jnp

    from selkies_tpu.analysis.surface import trace_step

    @functools.partial(jax.jit, donate_argnums=(0,))
    def f(state, delta):
        return state, jnp.bitwise_xor(delta, jnp.uint8(1))

    aval = jax.ShapeDtypeStruct((32,), jnp.uint8)
    st = trace_step(f, (aval, aval), name="fix.fwd")
    assert st.forwarded == (0,)


# -- CLI contract (faked surface: no tracing) --------------------------------

class _Args:
    baseline = None
    write_baseline = None
    severity_map = None
    jaxpr_disable = None
    fmt = "text"


def _fake_cli(monkeypatch, report, **kw):
    """run_cli against a canned SurfaceReport. ensure_analysis_env
    mutates os.environ; registering the keys with monkeypatch FIRST
    makes teardown restore them (donation forced on cpu must not leak
    into later engine tests)."""
    import selkies_tpu.analysis.surface as surface
    from selkies_tpu.analysis.jaxpr_lint import run_cli

    monkeypatch.setenv("SELKIES_FORCE_DONATION", "1")
    monkeypatch.setenv("XLA_FLAGS", os.environ.get("XLA_FLAGS", ""))
    monkeypatch.setattr(surface, "trace_surface", lambda: report)
    args = _Args()
    for k, v in kw.items():
        setattr(args, k, v)
    return run_cli(args)


def test_cli_exit_codes(monkeypatch, tmp_path, capsys):
    clean = _report(_step(), signatures=[_sig()])
    # unbudgeted step with no baseline -> gating finding -> exit 1
    assert _fake_cli(monkeypatch, clean) == 1
    # write-baseline pins budgets -> always clean -> exit 0
    bl = tmp_path / "jaxpr_baseline.json"
    assert _fake_cli(monkeypatch, clean, write_baseline=str(bl)) == 0
    doc = json.loads(bl.read_text())
    assert doc["budgets"] == {"fix.step": 100}
    # gated against the fresh baseline -> exit 0
    capsys.readouterr()
    assert _fake_cli(monkeypatch, clean, baseline=str(bl)) == 0
    assert "0 new, 0 gating" in capsys.readouterr().out
    # trace errors -> internal error -> exit 2, never 0 or 1
    broken = _report(errors=["boom"])
    assert _fake_cli(monkeypatch, broken, baseline=str(bl)) == 2


def test_cli_sarif_and_json_output(monkeypatch, capsys):
    rep = _report(_step(callbacks=("debug_print",)), signatures=[_sig()])
    assert _fake_cli(monkeypatch, rep, fmt="sarif") == 1
    sarif = json.loads(capsys.readouterr().out)
    results = sarif["runs"][0]["results"]
    rules = {r["ruleId"] for r in results}
    assert "JAXPR-HOST-CALLBACK" in rules
    assert _fake_cli(monkeypatch, rep, fmt="json") == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["traced_steps"] == ["fix.step"]
    assert doc["summary"]["gating"] >= 1


# -- the ratchet: repo surface ⊆ committed baseline --------------------------

def test_committed_baseline_shape():
    doc = load_baseline(BASELINE)
    budgets = load_budgets(doc)
    assert budgets, "tools/jaxpr_baseline.json must carry budgets"
    assert all(isinstance(v, int) and v >= 0 for v in budgets.values())
    # every registered rule referenced by an entry must exist
    known = {r.rule_id for r in JAXPR_RULES}
    for e in doc["entries"]:
        assert e["rule"] in known


@pytest.mark.slow
def test_full_surface_within_ratchet(monkeypatch):
    """Trace every registered step factory and require findings ⊆ the
    committed baseline — the same gate CI's jaxpr-lint job applies.
    (Needs a jax backend that has not initialised yet: the analysis env
    forces an 8-device host platform for the seats/stripes meshes.)"""
    from selkies_tpu.analysis import surface
    monkeypatch.setenv("SELKIES_FORCE_DONATION", "1")
    monkeypatch.setenv("XLA_FLAGS", os.environ.get("XLA_FLAGS", ""))
    surface.ensure_analysis_env()
    report = surface.trace_surface()
    assert not report.errors, report.errors
    doc = load_baseline(BASELINE)
    findings = lint_report(report, load_budgets(doc))
    fresh = new_findings(findings, doc)
    assert not fresh, [f.render() for f in fresh]
    # the budgets table must cover exactly the traced surface
    assert set(load_budgets(doc)) == set(report.step_names())
