import io

import numpy as np
import pytest
from PIL import Image

from selkies_tpu.codecs import jpeg as J
from selkies_tpu.ops import colorspace as C
from selkies_tpu.ops import dct as D
from selkies_tpu.ops.jpeg_pipeline import jpeg_forward_420, jpeg_forward_444


def _psnr(a, b):
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    mse = np.mean((a - b) ** 2)
    return 99.0 if mse == 0 else 10 * np.log10(255.0 ** 2 / mse)


def _test_image(h, w, seed=0):
    """Smooth gradient + blocks + text-like edges — desktop-ish content."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    r = (xx * 255 / w).astype(np.uint8)
    g = (yy * 255 / h).astype(np.uint8)
    b = ((xx + yy) % 256).astype(np.uint8)
    img = np.stack([r, g, b], axis=-1)
    # hard-edged rectangles
    for _ in range(6):
        y0, x0 = rng.integers(0, h - 16), rng.integers(0, w - 16)
        img[y0:y0 + 12, x0:x0 + 14] = rng.integers(0, 255, 3)
    return img


def test_dct_matrix_orthonormal():
    d = D.dct8_matrix()
    np.testing.assert_allclose(d @ d.T, np.eye(8), atol=1e-6)


def test_dct_roundtrip():
    rng = np.random.default_rng(1)
    blocks = rng.uniform(-128, 127, (10, 8, 8)).astype(np.float32)
    rec = np.asarray(D.idct2d(D.dct2d(blocks)))
    np.testing.assert_allclose(rec, blocks, atol=1e-3)


def test_zigzag_order_is_permutation():
    zz = D.zigzag_order()
    assert sorted(zz) == list(range(64))
    # first entries of the canonical JPEG zigzag
    assert list(zz[:10]) == [0, 1, 8, 16, 9, 2, 3, 10, 17, 24]


def test_blocks_roundtrip():
    rng = np.random.default_rng(2)
    plane = rng.uniform(0, 255, (32, 48)).astype(np.float32)
    import jax.numpy as jnp
    rec = D.from_blocks(D.to_blocks(jnp.asarray(plane)), 32, 48)
    np.testing.assert_allclose(np.asarray(rec), plane)


def test_csc_roundtrip():
    rng = np.random.default_rng(3)
    import jax.numpy as jnp
    rgb = jnp.asarray(rng.integers(0, 255, (16, 16, 3)), dtype=jnp.float32)
    for std in ("bt601-full", "bt709-limited"):
        rec = C.ycbcr_to_rgb(C.rgb_to_ycbcr(rgb, std), std)
        np.testing.assert_allclose(np.asarray(rec), np.asarray(rgb), atol=1e-2)


def test_csc_known_values():
    import jax.numpy as jnp
    # white and black in BT.601 full range
    white = C.rgb_to_ycbcr(jnp.full((1, 1, 3), 255.0), "bt601-full")
    np.testing.assert_allclose(np.asarray(white)[0, 0], [255, 128, 128], atol=0.01)
    black = C.rgb_to_ycbcr(jnp.zeros((1, 1, 3)), "bt601-full")
    np.testing.assert_allclose(np.asarray(black)[0, 0], [0, 128, 128], atol=0.01)


@pytest.mark.parametrize("quality", [90, 60])
def test_jpeg_pil_decodes_420(quality):
    """Self-calibrating oracle: our TPU-pipeline JPEG must land within 1 dB
    of PIL's own libjpeg encoder at the same quality on the same image."""
    h, w = 64, 96
    img = _test_image(h, w)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, "JPEG", quality=quality)
    pil_psnr = _psnr(np.asarray(Image.open(buf).convert("RGB")), img)

    qy = J.scale_qtable(J.STD_LUMA_QUANT, quality)
    qc = J.scale_qtable(J.STD_CHROMA_QUANT, quality)
    import jax.numpy as jnp
    y, cb, cr = jpeg_forward_420(jnp.asarray(img), jnp.asarray(qy), jnp.asarray(qc))
    jfif = J.encode_coeffs_to_jfif(np.asarray(y), np.asarray(cb), np.asarray(cr),
                                   h, w, qy, qc, "420")
    decoded = Image.open(io.BytesIO(jfif))
    decoded.load()  # force full decode; raises on malformed streams
    assert decoded.size == (w, h)
    psnr = _psnr(np.asarray(decoded.convert("RGB")), img)
    assert psnr > pil_psnr - 1.0, f"psnr {psnr:.1f} vs PIL {pil_psnr:.1f} at q{quality}"
    # and our stream must not be grossly larger than libjpeg's
    assert len(jfif) < buf.tell() * 1.2


def test_jpeg_pil_decodes_444():
    h, w = 40, 56
    img = _test_image(h, w, seed=7)
    qy = J.scale_qtable(J.STD_LUMA_QUANT, 85)
    qc = J.scale_qtable(J.STD_CHROMA_QUANT, 85)
    import jax.numpy as jnp
    y, cb, cr = jpeg_forward_444(jnp.asarray(img), jnp.asarray(qy), jnp.asarray(qc))
    jfif = J.encode_coeffs_to_jfif(np.asarray(y), np.asarray(cb), np.asarray(cr),
                                   h, w, qy, qc, "444")
    decoded = Image.open(io.BytesIO(jfif))
    decoded.load()
    psnr = _psnr(np.asarray(decoded.convert("RGB")), img)
    assert psnr > 33


def test_jpeg_flat_image_tiny():
    """All-DC image: exercises EOB-only blocks and DC prediction chain."""
    h, w = 32, 32
    img = np.full((h, w, 3), 77, dtype=np.uint8)
    qy = J.scale_qtable(J.STD_LUMA_QUANT, 75)
    qc = J.scale_qtable(J.STD_CHROMA_QUANT, 75)
    import jax.numpy as jnp
    y, cb, cr = jpeg_forward_420(jnp.asarray(img), jnp.asarray(qy), jnp.asarray(qc))
    jfif = J.encode_coeffs_to_jfif(np.asarray(y), np.asarray(cb), np.asarray(cr),
                                   h, w, qy, qc, "420")
    decoded = np.asarray(Image.open(io.BytesIO(jfif)).convert("RGB"))
    assert np.abs(decoded.astype(int) - 77).max() <= 3
    # flat image must compress tiny (headers dominate)
    assert len(jfif) < 1200


def test_jpeg_noise_stress():
    """Worst-case content: every AC coefficient populated, ZRL paths hit."""
    rng = np.random.default_rng(11)
    h, w = 32, 48
    img = rng.integers(0, 255, (h, w, 3)).astype(np.uint8)
    qy = J.scale_qtable(J.STD_LUMA_QUANT, 95)
    qc = J.scale_qtable(J.STD_CHROMA_QUANT, 95)
    import jax.numpy as jnp
    y, cb, cr = jpeg_forward_420(jnp.asarray(img), jnp.asarray(qy), jnp.asarray(qc))
    jfif = J.encode_coeffs_to_jfif(np.asarray(y), np.asarray(cb), np.asarray(cr),
                                   h, w, qy, qc, "420")
    Image.open(io.BytesIO(jfif)).load()  # must parse cleanly


def test_quality_scaling_monotonic():
    t50 = J.scale_qtable(J.STD_LUMA_QUANT, 50)
    np.testing.assert_array_equal(t50, J.STD_LUMA_QUANT)
    t90 = J.scale_qtable(J.STD_LUMA_QUANT, 90)
    t10 = J.scale_qtable(J.STD_LUMA_QUANT, 10)
    assert (t90 <= t50).all() and (t10 >= t50).all()
    assert J.scale_qtable(J.STD_LUMA_QUANT, 100).min() == 1


def test_plane_layout_forward_coefficient_exact():
    """The TPU plane-layout transform (ops/jpeg_planes, PERF.md lever 3)
    must produce coefficient-exact output vs the block-layout reference
    path (ops/jpeg_pipeline.jpeg_forward_*) — the plane rewrite is a pure
    layout change, like h264_planes vs h264_encode."""
    import jax.numpy as jnp

    from selkies_tpu.ops import jpeg_pipeline as blk
    from selkies_tpu.ops import jpeg_planes as pl

    rng = np.random.default_rng(7)
    rgb = jnp.asarray(rng.integers(0, 256, (48, 64, 3), np.uint8))
    qy = jnp.asarray(J.scale_qtable(J.STD_LUMA_QUANT, 60))
    qc = jnp.asarray(J.scale_qtable(J.STD_CHROMA_QUANT, 60))
    for old_fn, new_fn in ((blk.jpeg_forward_420, pl.jpeg_forward_420),
                           (blk.jpeg_forward_444, pl.jpeg_forward_444)):
        for a, b in zip(old_fn(rgb, qy, qc), new_fn(rgb, qy, qc)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
