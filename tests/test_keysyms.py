"""Server keysym map: rule-based keysym<->Unicode translation
(the functional core of the reference's generated server_keysym_map.py)."""

from selkies_tpu.input.keysyms import (char_to_keysym, is_modifier,
                                       keysym_to_char, normalize)


def test_latin1_identity():
    for ch in "aZ0 ~é½ÿ":
        ks = char_to_keysym(ch)
        assert ks == ord(ch)
        assert keysym_to_char(ks) == ch


def test_unicode_rule_roundtrip():
    for ch in "→中文🎮ßčşёλ€":
        ks = char_to_keysym(ch)
        assert keysym_to_char(ks) == ch


def test_legacy_keysyms_translate():
    assert keysym_to_char(0x01E8) == "č"        # Latin-2 ccaron
    assert keysym_to_char(0x07E9) == "ι"        # Greek iota
    assert keysym_to_char(0x06D7) == "в"        # Cyrillic ve
    assert keysym_to_char(0x20AC) == "€"
    # canonical reverse prefers the legacy page over the Unicode rule
    assert char_to_keysym("č") == 0x01E8
    assert char_to_keysym("ι") == 0x07E9


def test_normalize_collapses_layout_aliases():
    # a Czech layout's legacy keysym and the Unicode keysym for the same
    # character normalise to the same canonical value
    assert normalize(0x01E8) == normalize(0x01000000 | ord("č"))
    # keypad '7' normalises to the character it types
    assert normalize(0xFFB7) == ord("7")
    # non-printing keys pass through untouched
    assert normalize(0xFF1B) == 0xFF1B          # Escape
    assert normalize(0xFFE1) == 0xFFE1          # Shift_L


def test_nonprinting_have_no_char():
    for ks in (0xFF1B, 0xFFE1, 0xFF51, 0xFFC8):   # Esc, Shift, Left, F11
        assert keysym_to_char(ks) is None
    assert is_modifier(0xFFE1) and not is_modifier(0x61)
