"""Server keysym map: rule-based keysym<->Unicode translation
(the functional core of the reference's generated server_keysym_map.py)."""

from selkies_tpu.input.keysyms import (char_to_keysym, is_modifier,
                                       keysym_to_char, normalize)


def test_latin1_identity():
    for ch in "aZ0 ~é½ÿ":
        ks = char_to_keysym(ch)
        assert ks == ord(ch)
        assert keysym_to_char(ks) == ch


def test_unicode_rule_roundtrip():
    for ch in "→中文🎮ßčşёλ€":
        ks = char_to_keysym(ch)
        assert keysym_to_char(ks) == ch


def test_legacy_keysyms_translate():
    assert keysym_to_char(0x01E8) == "č"        # Latin-2 ccaron
    assert keysym_to_char(0x07E9) == "ι"        # Greek iota
    assert keysym_to_char(0x06D7) == "в"        # Cyrillic ve
    assert keysym_to_char(0x20AC) == "€"
    # canonical reverse prefers the legacy page over the Unicode rule
    assert char_to_keysym("č") == 0x01E8
    assert char_to_keysym("ι") == 0x07E9


def test_normalize_collapses_layout_aliases():
    # a Czech layout's legacy keysym and the Unicode keysym for the same
    # character normalise to the same canonical value
    assert normalize(0x01E8) == normalize(0x01000000 | ord("č"))
    # keypad '7' normalises to the character it types
    assert normalize(0xFFB7) == ord("7")
    # non-printing keys pass through untouched
    assert normalize(0xFF1B) == 0xFF1B          # Escape
    assert normalize(0xFFE1) == 0xFFE1          # Shift_L


def test_nonprinting_have_no_char():
    for ks in (0xFF1B, 0xFFE1, 0xFF51, 0xFFC8):   # Esc, Shift, Left, F11
        assert keysym_to_char(ks) is None
    assert is_modifier(0xFFE1) and not is_modifier(0x61)


def test_cyrillic_case_pairs_generated():
    # uppercase page is generated from lowercase: both halves agree
    assert keysym_to_char(0x06C1) == "а" and keysym_to_char(0x06E1) == "А"
    assert char_to_keysym("А") == 0x06E1
    # Serbian/Ukrainian extensions incl. the irregular ghe_with_upturn
    assert keysym_to_char(0x06A1) == "ђ" and keysym_to_char(0x06B1) == "Ђ"
    assert keysym_to_char(0x06AD) == "ґ" and keysym_to_char(0x06BD) == "Ґ"
    assert keysym_to_char(0x06B0) == "№"


def test_affine_pages_roundtrip():
    # Arabic / Hebrew / Thai pages are affine (keysymdef.h is laid out
    # in Unicode order); spot-check both directions incl. Thai digits
    for ks, ch in ((0x05D4, "ش"), (0x0CE0, "א"),
                   (0x0DA1, "ก"), (0x0DF5, "๕")):
        assert keysym_to_char(ks) == ch
        assert char_to_keysym(ch) == ks
    # normalize collapses the legacy page onto the same canonical keysym
    # as the Unicode-rule form a modern client would send
    assert normalize(0x01000000 | ord("ش")) == 0x05D4
