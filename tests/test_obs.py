"""Device telemetry + health-verdict plane (selkies_tpu/obs, ISSUE 3):
engine transitions, liveness/readiness split, flight recorder, the
compile/HBM monitor against synthetic jax.monitoring events and fake
devices, and the HTTP surface (/api/health?verbose=1, /api/profile,
device-lane trace overlay)."""

import asyncio
import json

import pytest

from selkies_tpu.obs import (DEGRADED, FAILED, OK, DeviceMonitor,
                             FlightRecorder, HealthEngine, degraded,
                             failed, ok)
from selkies_tpu.obs import health as health_mod
from tests.test_server import make_app


# ------------------------------------------------------------------ engine
def test_health_check_transitions():
    eng = HealthEngine()
    state = {"v": ok("fine")}
    eng.register("x", lambda: state["v"])
    assert eng.run()["x"].status == OK
    state["v"] = degraded("slow")
    assert eng.run()["x"].status == DEGRADED
    state["v"] = failed("dead")
    v = eng.run()["x"]
    assert v.status == FAILED and v.reason == "dead"


def test_health_crashing_check_is_failed_not_500():
    eng = HealthEngine()
    eng.register("boom", lambda: 1 / 0)
    v = eng.run()["boom"]
    assert v.status == FAILED and "ZeroDivisionError" in v.reason


def test_health_non_verdict_return_is_failed():
    eng = HealthEngine()
    eng.register("wrong", lambda: "ok")
    assert eng.run()["wrong"].status == FAILED


def test_health_liveness_readiness_split():
    """A readiness-scope failure (dead relay) must NOT fail liveness —
    k8s would otherwise crash-loop the pod against an external fault."""
    eng = HealthEngine()
    eng.register("service", lambda: ok("up"), liveness=True)
    eng.register("relay", lambda: failed("all relays dead"))
    rep = eng.report()
    assert rep["live"] is True
    assert rep["ready"] is False and rep["ok"] is False
    assert rep["status"] == FAILED and rep["failing"] == ["relay"]
    # liveness-scope failure fails both probes
    eng.register("service", lambda: failed("supervisor dead"),
                 liveness=True)
    rep = eng.report()
    assert rep["live"] is False and rep["ready"] is False


def test_health_degraded_keeps_ready():
    eng = HealthEngine()
    eng.register("fps", lambda: degraded("20 fps vs 60"))
    rep = eng.report()
    assert rep["ready"] is True and rep["status"] == DEGRADED


def test_health_verbose_payload_shape():
    eng = HealthEngine()
    eng.register("a", lambda: ok("fine", n=3))
    eng.recorder.record("relay_death", display=":0")
    rep = eng.report(verbose=True)
    assert rep["checks"]["a"] == {"status": "ok", "reason": "fine",
                                 "data": {"n": 3}}
    assert rep["incidents"][0]["kind"] == "relay_death"
    assert rep["incidents_total"] == 1
    json.dumps(rep)                       # must be JSON-serializable
    # non-verbose: no check bodies, no incident ring
    rep = eng.report()
    assert "checks" not in rep and "incidents" not in rep


def test_health_reregister_replaces_and_unregister():
    eng = HealthEngine()
    eng.register("x", lambda: failed("old"))
    eng.register("x", lambda: ok("new"))
    assert eng.run()["x"].status == OK
    eng.unregister("x")
    assert eng.run() == {}


# ---------------------------------------------------------- flight recorder
def test_flight_recorder_bounded_with_drop_accounting():
    rec = FlightRecorder(capacity=8)
    for i in range(11):
        rec.record("k", i=i)
    snap = rec.snapshot()
    assert len(snap) == 8 and snap[0]["i"] == 3 and snap[-1]["i"] == 10
    assert rec.dropped == 3 and rec.total == 11
    for line in rec.dump_text().splitlines():
        json.loads(line)


def test_relay_death_lands_in_flight_recorder():
    from selkies_tpu import protocol as P
    from selkies_tpu.server.relay import VideoRelay

    async def run():
        rec = health_mod.engine.recorder
        before = rec.total

        async def _failing_send(data):
            raise ConnectionError("gone")

        relay = VideoRelay(_failing_send, display=":7")
        relay.start()
        relay.offer(P.pack_jpeg_stripe(1, 0, b"\xff\xd8x\xff\xd9"))
        for _ in range(50):
            await asyncio.sleep(0.01)
            if relay.dead:
                break
        assert relay.dead
        incidents = [e for e in rec.snapshot()
                     if e["kind"] == "relay_death" and e["display"] == ":7"]
        assert rec.total == before + 1 and incidents
        await relay.close()
    asyncio.run(run())


# ----------------------------------------------------------- device monitor
def test_monitor_compile_accounting_synthetic_events():
    mon = DeviceMonitor(recorder=FlightRecorder())
    mon.on_event("/jax/compilation_cache/cache_hits")
    mon.on_event("/jax/compilation_cache/cache_hits")
    mon.on_event("/jax/compilation_cache/cache_misses")
    mon.on_event_duration(
        "/jax/core/compile/backend_compile_duration_sec", 2.0)
    mon.on_event_duration(
        "/jax/core/compile/backend_compile_duration_sec", 0.25)
    # the cache's own retrieval timer must NOT count as a compile
    mon.on_event_duration(
        "/jax/compilation_cache/cache_retrieval_time_sec", 9.0)
    cs = mon.compile_stats()
    assert cs["count"] == 2
    assert abs(cs["total_s"] - 2.25) < 1e-6
    assert cs["cache_hits"] == 2 and cs["cache_misses"] == 1


def test_monitor_prefers_backend_compile_timer():
    """Session- and backend-level timers for the same compile must not
    double-count."""
    mon = DeviceMonitor(recorder=FlightRecorder())
    for _ in range(3):
        mon.on_event_duration("/jax/compile/session_duration_sec", 5.0)
        mon.on_event_duration(
            "/jax/core/compile/backend_compile_duration_sec", 4.0)
    cs = mon.compile_stats()
    assert cs["count"] == 3 and abs(cs["total_s"] - 12.0) < 1e-6


def test_monitor_trace_overlay_events():
    mon = DeviceMonitor(recorder=FlightRecorder())
    mon.on_event_duration(
        "/jax/core/compile/backend_compile_duration_sec", 1.0)
    ev = mon.trace_events()
    assert ev[0]["ph"] == "M" and ev[0]["args"]["name"] == "device"
    span = ev[1]
    assert span["ph"] == "X" and span["dur"] >= 1e6 / 1e3  # >= 1s in µs
    assert span["name"].startswith("compile:")


def test_monitor_compile_storm_incident():
    from selkies_tpu.obs import device_monitor as dm
    rec = FlightRecorder()
    mon = DeviceMonitor(recorder=rec)
    mon._started_at -= dm.WARMUP_GRACE_S + 1   # past the cold-start grace
    for _ in range(dm.STORM_THRESHOLD):
        mon.on_event_duration(
            "/jax/core/compile/backend_compile_duration_sec", 0.5)
    storms = [e for e in rec.snapshot() if e["kind"] == "compile_storm"]
    assert len(storms) == 1                    # rate-limited per window
    assert storms[0]["count"] >= dm.STORM_THRESHOLD


def test_monitor_no_storm_during_warmup():
    from selkies_tpu.obs import device_monitor as dm
    rec = FlightRecorder()
    mon = DeviceMonitor(recorder=rec)       # fresh: inside warmup grace
    for _ in range(dm.STORM_THRESHOLD * 2):
        mon.on_event_duration(
            "/jax/core/compile/backend_compile_duration_sec", 0.5)
    assert not [e for e in rec.snapshot() if e["kind"] == "compile_storm"]


class _FakeDevice:
    def __init__(self, id, platform="tpu", stats=None):
        self.id = id
        self.platform = platform
        self.device_kind = "FakeTPU v9"
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_monitor_samples_fake_devices(monkeypatch):
    import jax
    gib = 1024 ** 3
    monkeypatch.setattr(jax, "local_devices", lambda: [
        _FakeDevice(0, stats={"bytes_in_use": 2 * gib,
                              "peak_bytes_in_use": 3 * gib,
                              "bytes_limit": 16 * gib}),
        _FakeDevice(1, stats={"bytes_in_use": 15 * gib,
                              "peak_bytes_in_use": 15 * gib,
                              "bytes_limit": 16 * gib}),
    ])
    mon = DeviceMonitor(recorder=FlightRecorder())
    out = mon.sample(force=True)
    assert [d["hbm_in_use"] for d in out] == [2 * gib, 15 * gib]
    assert out[0]["hbm_pct"] == 12.5
    assert mon.hbm_peak_mb() == 15 * 1024.0
    # exported gauges
    from selkies_tpu.server import metrics
    text = metrics.render_prometheus()
    assert 'selkies_device_hbm_bytes{device="0",platform="tpu"}' in text
    # headroom verdicts: device 1 at 93.8% -> degraded
    v = mon.hbm_verdict()
    assert v.status == DEGRADED and "device 1" in v.reason
    monkeypatch.setattr(jax, "local_devices", lambda: [
        _FakeDevice(0, stats={"bytes_in_use": 159 * gib // 10,
                              "bytes_limit": 16 * gib})])
    mon.sample(force=True)
    assert mon.hbm_verdict().status == FAILED


def test_monitor_hbm_verdict_honest_without_data():
    mon = DeviceMonitor(recorder=FlightRecorder())
    v = mon.hbm_verdict()
    assert v.status == OK and "no device memory telemetry" in v.reason


def test_monitor_sampling_policy(monkeypatch):
    import jax
    calls = []

    class _CountingDevice(_FakeDevice):
        def memory_stats(self):
            calls.append(self.id)
            return {"bytes_in_use": 1}

    monkeypatch.setattr(jax, "local_devices",
                        lambda: [_CountingDevice(0, platform="tpu")])
    monkeypatch.delenv("SELKIES_DEVICE_MEMSTATS", raising=False)
    mon = DeviceMonitor(recorder=FlightRecorder())
    mon.sampling = "auto"
    mon.sample()                       # tpu + auto + no env: RPC skipped
    assert calls == []
    mon.sampling = "on"
    mon.sample()
    assert calls == [0]
    mon.sampling = "off"
    mon.sample(force=True)             # force overrides even 'off'
    assert calls == [0, 0]


def test_backend_verdict_modes(monkeypatch):
    mon = DeviceMonitor(recorder=FlightRecorder())
    monkeypatch.delenv("BENCH_CPU_REASON", raising=False)
    monkeypatch.delenv("SELKIES_CPU_FALLBACK_REASON", raising=False)
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    mon.platform = "cpu"
    assert mon.backend_verdict().status == OK          # explicit cpu
    mon.platform = "tpu"
    assert mon.backend_verdict().status == OK          # real device
    # intended accelerator, got cpu: the r04/r05 silent-failure mode
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    mon.platform = "cpu"
    assert mon.backend_verdict().status == FAILED
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    assert mon.backend_verdict().status == FAILED
    # an explicit fallback reason always fails, whatever the platform
    monkeypatch.setenv("BENCH_CPU_REASON", "relay-dead")
    mon.platform = "tpu"
    v = mon.backend_verdict()
    assert v.status == FAILED and "relay-dead" in v.reason


# ------------------------------------------------------------- HTTP surface
async def test_health_endpoint_basic_and_verbose(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    r = await c.get("/api/health")
    body = await r.json()
    assert r.status == 200
    assert body["ok"] is True and body["mode"] == "websockets"
    assert body["status"] in ("ok", "degraded")
    assert body["live"] is True and body["ready"] is True
    assert "checks" not in body
    r = await c.get("/api/health?verbose=1")
    body = await r.json()
    for name in ("service", "stage_latency", "relay", "capture_fps",
                 "audio"):
        assert name in body["checks"], name
    assert body["checks"]["service"]["status"] == "ok"
    assert "incidents" in body


async def test_health_probe_split_over_http(client_factory):
    """Dead relays fail readiness but not liveness at the HTTP layer."""
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("START_VIDEO")
    await asyncio.sleep(0.1)
    for cl in svc.clients.values():
        for relay in cl.relays.values():
            relay.mark_dead()
    r = await c.get("/api/health")
    body = await r.json()
    assert r.status == 503 and body["ready"] is False
    assert "relay" in body["failing"]
    r = await c.get("/api/health?probe=live")
    assert r.status == 200 and (await r.json())["live"] is True
    await ws.close()


async def test_capture_fps_check_degrades(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("START_VIDEO")
    await asyncio.sleep(0.1)
    assert svc._check_capture_fps().status == OK      # 42 vs 60 * 0.5
    fake.encoded_fps = 10.0                           # below 30 -> degraded
    v = svc._check_capture_fps()
    assert v.status == DEGRADED and "10.0 fps" in v.reason
    await ws.close()


async def test_audio_check_reports_missing_pipeline(client_factory):
    server, svc, fake, _ = make_app()          # make_app passes no audio
    c = await client_factory(server)
    v = svc._check_audio()
    assert v.status == DEGRADED and "pipeline failed to start" in v.reason
    server2, svc2, *_ = make_app(enable_audio=False,
                                 enable_microphone=False)
    await client_factory(server2)
    assert svc2._check_audio().status == OK


async def test_profile_endpoint_role_gated_and_status(client_factory):
    import base64
    server, svc, fake, _ = make_app(
        enable_basic_auth=True, basic_auth_user="u",
        basic_auth_password="pw", viewonly_password="vo")
    c = await client_factory(server)
    vo = {"Authorization": "Basic " + base64.b64encode(b"u:vo").decode()}
    full = {"Authorization": "Basic " + base64.b64encode(b"u:pw").decode()}
    r = await c.post("/api/profile", json={"action": "status"}, headers=vo)
    assert r.status == 403
    r = await c.post("/api/profile", json={"action": "status"},
                     headers=full)
    body = await r.json()
    assert r.status == 200 and body["active"] is False
    r = await c.post("/api/profile", json={"action": "nope"}, headers=full)
    assert r.status == 400
    # stop without start: structured 409, not a 500
    r = await c.post("/api/profile", json={"action": "stop"}, headers=full)
    assert r.status == 409 and "no capture" in (await r.json())["error"]


@pytest.mark.slow
async def test_profile_capture_roundtrip(client_factory, tmp_path):
    """Full start->stop cycle writes a jax.profiler trace dir.

    Slow-marked (ISSUE 14 budget pass): the CPU jax.profiler capture
    costs ~49 s of the 870 s tier-1 budget; the endpoint's
    control-flow contracts (role gate, double-start 409, stop-without-
    start 409) stay tier-1 in the tests above, and bench --profile
    exercises the capture end-to-end on the perf rounds."""
    server, *_ = make_app()
    c = await client_factory(server)
    target = str(tmp_path / "cap")
    r = await c.post("/api/profile",
                     json={"action": "start", "dir": target})
    body = await r.json()
    assert r.status == 200 and body["ok"] is True, body
    # double-start is refused while active
    r = await c.post("/api/profile", json={"action": "start"})
    assert r.status == 409
    import jax
    import jax.numpy as jnp
    jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    r = await c.post("/api/profile", json={"action": "stop"})
    body = await r.json()
    assert r.status == 200 and body["trace_dir"] == target, body
    assert (tmp_path / "cap").is_dir()


async def test_trace_endpoint_carries_device_lane(client_factory):
    from selkies_tpu.obs import monitor as global_monitor
    server, *_ = make_app()
    c = await client_factory(server)
    global_monitor.on_event_duration(
        "/jax/core/compile/backend_compile_duration_sec", 0.75)
    try:
        r = await c.get("/api/trace")
        doc = await r.json()
        lanes = [e for e in doc["traceEvents"]
                 if e.get("ph") == "M"
                 and e["args"].get("name") == "device"]
        spans = [e for e in doc["traceEvents"]
                 if str(e.get("name", "")).startswith("compile:")]
        assert lanes and spans
        assert doc["otherData"]["compile"]["count"] >= 1
    finally:
        global_monitor._compile_ring.clear()


def test_obs_selftest_cli():
    """The CI lint smoke: must pass in-process too."""
    from selkies_tpu.obs.__main__ import main
    assert main(["selftest"]) == 0


def test_monitor_cached_sample_avoids_second_rpc_pass(monkeypatch):
    """While the background sampler owns the cadence, device_stats()
    callers must get the cached sample — a second memory_stats pass
    would double the encode-thread RPC contention the gating exists to
    avoid."""
    import jax
    calls = []

    class _Dev(_FakeDevice):
        def memory_stats(self):
            calls.append(1)
            return {"bytes_in_use": 7}

    monkeypatch.setattr(jax, "local_devices",
                        lambda: [_Dev(0, platform="cpu")])
    mon = DeviceMonitor(recorder=FlightRecorder())
    mon.interval_s = 60.0                  # thread sleeps; we drive it
    mon.start()
    try:
        mon.sample()                       # the sampler's own pass
        assert calls == [1]
        assert mon.cached_sample()[0]["hbm_in_use"] == 7
        assert calls == [1]                # served from cache, no RPC
    finally:
        mon.stop()
    mon2 = DeviceMonitor(recorder=FlightRecorder())
    assert mon2.cached_sample()[0]["hbm_in_use"] == 7
    assert len(calls) == 2                 # no thread: inline sample


def test_liveness_probe_runs_only_liveness_checks():
    """The liveness path must not EVALUATE readiness closures — a
    wedged one would time the probe out and crash-loop the pod."""
    eng = HealthEngine()
    ran = []
    eng.register("service", lambda: (ran.append("live"), ok("up"))[1],
                 liveness=True)
    eng.register("relay", lambda: (ran.append("ready"), failed("dead"))[1])
    out = eng.liveness()
    assert out["ok"] is True and out["live"] is True
    assert ran == ["live"]          # the readiness closure never ran


def test_unregister_is_owner_matched():
    eng = HealthEngine()

    def old():
        return failed("old instance")

    def new():
        return ok("new instance")

    eng.register("service", old)
    eng.register("service", new)      # newer instance replaces
    eng.unregister("service", old)    # stale teardown: must be a no-op
    assert eng.run()["service"].status == OK
    eng.unregister("service", new)
    assert eng.run() == {}


async def test_audio_check_degrades_on_failed_mic_provision(
        client_factory):
    """Satellite (ADVICE r5): a mic that silently cannot work must
    show up as a degraded verdict, not a green health endpoint."""

    class _FakeAudio:
        mic_only = True
        mic_ok = False
        alive = False

    server, svc, fake, _ = make_app(enable_audio=False,
                                    enable_microphone=True)
    await client_factory(server)
    svc.audio = _FakeAudio()
    v = svc._check_audio()
    assert v.status == DEGRADED and "mic provisioning failed" in v.reason
    svc.audio.mic_ok = True
    assert svc._check_audio().status == OK
