"""Multi-seat mesh sharding tests on the virtual 8-device CPU mesh
(conftest forces ``xla_force_host_platform_device_count=8``)."""

import io

import jax
import numpy as np
import pytest
from PIL import Image

from selkies_tpu.engine.encoder import JpegEncoderSession
from selkies_tpu.engine.types import CaptureSettings
from selkies_tpu.parallel import (MultiSeatEncoder, seat_mesh,
                                  synthetic_seat_frames)

SMALL = dict(capture_width=64, capture_height=64, stripe_height=32,
             jpeg_quality=70)


def test_seat_mesh_divides_devices():
    assert seat_mesh(8).devices.size == 8
    assert seat_mesh(4).devices.size == 4
    assert seat_mesh(3).devices.size == 3
    assert seat_mesh(5).devices.size == 5
    assert seat_mesh(16).devices.size == 8  # 2 seats per device


def test_multiseat_outputs_match_single_seat():
    """Every seat's sharded bitstream must be byte-identical to what the
    single-seat session produces for the same frame."""
    n = 4
    s = CaptureSettings(**SMALL)
    enc = MultiSeatEncoder(s, n_seats=n)
    frames = synthetic_seat_frames(enc, tick=0)
    per_seat = enc.finalize(enc.encode(frames), force_all=True)

    host_frames = np.asarray(frames)
    for seat in range(n):
        ref_sess = JpegEncoderSession(CaptureSettings(**SMALL))
        ref = ref_sess.finalize(
            ref_sess.encode(jax.numpy.asarray(host_frames[seat])),
            force_all=True)
        assert [c.payload for c in per_seat[seat]] == \
            [c.payload for c in ref]


def test_multiseat_seats_are_distinct_and_decodable():
    enc = MultiSeatEncoder(CaptureSettings(**SMALL), n_seats=8)
    frames = synthetic_seat_frames(enc, tick=5)
    per_seat = enc.finalize(enc.encode(frames), force_all=True)
    blobs = set()
    for seat, chunks in enumerate(per_seat):
        assert len(chunks) == enc.grid.n_stripes
        for c in chunks:
            Image.open(io.BytesIO(c.payload)).load()
            assert c.seat_index == seat and c.display_id == f"seat{seat}"
        blobs.add(b"".join(c.payload for c in chunks))
    assert len(blobs) == 8


def test_multiseat_damage_gating_is_per_seat():
    """Static seats stay silent while animated seats keep sending."""
    n = 4
    enc = MultiSeatEncoder(CaptureSettings(**SMALL), n_seats=n)
    f0 = synthetic_seat_frames(enc, tick=0)
    enc.finalize(enc.encode(f0), force_all=True)

    # next frame: seats 0,1 unchanged; seats 2,3 animated
    f1 = synthetic_seat_frames(enc, tick=1)
    mixed = np.asarray(f0).copy()
    mixed[2:] = np.asarray(f1)[2:]
    mixed_dev = jax.device_put(mixed, enc.input_sharding)
    per_seat = enc.finalize(enc.encode(mixed_dev))
    assert len(per_seat[0]) == 0 and len(per_seat[1]) == 0
    assert len(per_seat[2]) > 0 and len(per_seat[3]) > 0


def test_multiseat_two_seats_per_device():
    enc = MultiSeatEncoder(CaptureSettings(**SMALL), n_seats=16)
    assert enc.mesh.devices.size == 8
    frames = synthetic_seat_frames(enc, tick=2)
    per_seat = enc.finalize(enc.encode(frames), force_all=True)
    assert len(per_seat) == 16
    assert all(len(c) == enc.grid.n_stripes for c in per_seat)


@pytest.mark.slow
def test_dryrun_multichip_entrypoint():
    # slow-marked (ISSUE 14 budget pass, the PR-12 precedent): the
    # 8-device XLA build costs ~86 s of the 870 s tier-1 budget, and
    # the driver invokes __graft_entry__ itself every round
    # (MULTICHIP_r*.json), so tier-1 is not the only proof
    import importlib.util
    import pathlib
    path = pathlib.Path(__file__).resolve().parent.parent / "__graft_entry__.py"
    spec = importlib.util.spec_from_file_location("_graft", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
    fn, args = mod.entry()
    out = fn(*args)
    # h264 I-step: (data, row_lens, send, is_paint, age, sent, fnum,
    #               recon_y, recon_u, recon_v, prev_out, overflow)
    assert len(out) == 12


def test_multiseat_capture_thread_serves_all_seats():
    """The server-facing capture facade: one sharded encode loop emits
    decodable chunks for every seat display."""
    import time

    from PIL import Image

    from selkies_tpu.parallel.capture import MultiSeatCapture

    got = []
    cap = MultiSeatCapture(4)
    cap.start_capture(
        got.append,
        CaptureSettings(capture_width=64, capture_height=64,
                        stripe_height=32, target_fps=60.0))
    deadline = time.time() + 60
    while time.time() < deadline and \
            len({c.display_id for c in got}) < 4:
        time.sleep(0.1)
    cap.stop_capture()
    seats = {c.display_id for c in got}
    assert seats == {"seat0", "seat1", "seat2", "seat3"}
    for c in got[:4]:
        Image.open(io.BytesIO(c.payload)).load()


# (the former test_stripe_sharded_h264_bit_identical lives on, grown,
# as tests/test_stripes.py::test_i_frame_sharded_byte_identity[1/2/4] —
# same geometry and mesh plus the P/halo/444/session layers around it)


def test_multiseat_h264_bitexact_vs_single_seat():
    """Seat-sharded adaptive I/P H.264: every seat's payload bytes must
    equal an independent single-seat session encoding the same frames —
    the sharding must be a pure distribution axis, no value change."""
    import jax

    from selkies_tpu.engine.h264_encoder import H264EncoderSession
    from selkies_tpu.parallel import MultiSeatH264Encoder
    from selkies_tpu.parallel.seats import synthetic_seat_frames

    n = 4
    s = CaptureSettings(capture_width=48, capture_height=32,
                        stripe_height=16, output_mode="h264",
                        video_crf=28, use_paint_over=False,
                        h264_motion_vrange=2, h264_motion_hrange=1)
    enc = MultiSeatH264Encoder(s, n_seats=n, devices=jax.devices()[:n])
    assert enc.mesh.devices.size == n

    def flat(per_seat):
        return [[(c.stripe_y, c.is_idr, c.payload) for c in chunks]
                for chunks in per_seat]

    f0 = synthetic_seat_frames(enc, tick=0)
    f1 = synthetic_seat_frames(enc, tick=1)
    got0 = flat(enc.finalize(enc.encode(f0)))          # IDR batch
    got1 = flat(enc.finalize(enc.encode(f1)))          # P batch
    assert all(chunks for chunks in got0)
    assert any(chunks for chunks in got1)

    f0h, f1h = np.asarray(f0), np.asarray(f1)
    for seat in range(n):
        sess = H264EncoderSession(s)
        ref0 = [(c.stripe_y, c.is_idr, c.payload) for c in
                sess.finalize(sess.encode(jax.numpy.asarray(f0h[seat])))]
        ref1 = [(c.stripe_y, c.is_idr, c.payload) for c in
                sess.finalize(sess.encode(jax.numpy.asarray(f1h[seat])))]
        assert got0[seat] == ref0, f"seat {seat} IDR mismatch"
        assert got1[seat] == ref1, f"seat {seat} P mismatch"
    # distinct seats must carry distinct content
    assert len({tuple(p for _, _, p in chunks) for chunks in got0}) == n


@pytest.mark.slow
def test_multiseat_capture_h264_mode():
    # slow-marked (ISSUE 14 budget pass): ~44 s of XLA build; h264
    # multiseat correctness stays tier-1 via the bitexact test and the
    # capture facade via the jpeg-mode thread test
    """The server-facing facade honors output_mode=h264 end-to-end."""
    import time

    from selkies_tpu.codecs import h264_ref_decoder as refdec
    from selkies_tpu.parallel.capture import MultiSeatCapture

    got = []
    cap = MultiSeatCapture(n_seats=2)
    s = CaptureSettings(capture_width=48, capture_height=32,
                        stripe_height=16, output_mode="h264",
                        video_crf=28, use_paint_over=False,
                        h264_motion_vrange=2, h264_motion_hrange=1,
                        target_fps=30.0)
    cap.start_capture(got.append, s)
    # two-phase deadline: the first chunk pays jit compile (minutes under
    # a loaded full-suite run), the rest must then flow at frame rate
    first_by = time.time() + 420
    while time.time() < first_by and not got:
        time.sleep(0.1)
    deadline = time.time() + 90
    while time.time() < deadline and len(got) < 8:
        time.sleep(0.1)
    cap.stop_capture()
    assert len(got) >= 8
    assert all(c.output_mode == "h264" for c in got)
    seats = {c.seat_index for c in got}
    assert seats == {0, 1}
    idr = next(c for c in got if c.is_idr and c.seat_index == 0)
    y, _, _ = refdec.Decoder().decode(idr.payload)
    assert y.shape[1] == 48
