"""Performance observability plane (ISSUE 6): occupancy/critical-path
math on synthetic timelines, the perf ledger's record/check round-trip
(incl. CPU-vs-TPU key isolation and noise-band edges), the static cost
registry + roofline math, the profiler-capture parser, and the
AOT-instrumented step wrapper.
"""

import gzip
import json
import os
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from selkies_tpu.obs import perf as perf_mod  # noqa: E402
from selkies_tpu.trace.export import (timelines_from_events,  # noqa: E402
                                      to_trace_events)
from selkies_tpu.trace.summary import (BUBBLE,  # noqa: E402
                                       frame_critical_path, lane_occupancy,
                                       occupancy_report, render_occupancy)
from tools import perf_ledger  # noqa: E402

MS = 1_000_000  # ns


def _tl(frame_id, t0, t1, spans, display="d0"):
    return {"display_id": display, "frame_id": frame_id,
            "t0_ns": t0, "t1_ns": t1,
            "spans": [{"name": n, "lane": la, "t0_ns": s0, "dur_ns": d}
                      for n, la, s0, d in spans]}


# ------------------------------------------------------- occupancy math
def test_serial_pipeline_zero_overlap_critical_path_equals_stage_sum():
    """Fully-serial pipeline: overlap fraction == 0 and the critical
    path IS the stage sum (each stage's charge == its duration)."""
    tl = _tl(1, 0, 30 * MS, [
        ("capture", "cap", 0, 10 * MS),
        ("encode.dispatch", "main", 10 * MS, 12 * MS),
        ("packetize", "main", 22 * MS, 8 * MS),
    ])
    cp = frame_critical_path(tl)
    assert cp["overlap_fraction"] == 0.0
    assert cp["bubble_ms"] == 0.0
    assert cp["stages"] == {"capture": 10.0, "encode.dispatch": 12.0,
                            "packetize": 8.0}
    assert cp["e2e_ms"] == cp["stage_sum_ms"] == 30.0


def test_serial_pipeline_gap_becomes_bubble():
    tl = _tl(1, 0, 30 * MS, [
        ("capture", "cap", 0, 10 * MS),
        # 5 ms of nothing: host stall the spans never covered
        ("packetize", "main", 15 * MS, 15 * MS),
    ])
    cp = frame_critical_path(tl)
    assert cp["bubble_ms"] == 5.0
    assert cp["stages"]["capture"] == 10.0
    assert cp["stages"]["packetize"] == 15.0
    # accounting is exact: stages + bubble == e2e
    assert sum(cp["stages"].values()) + cp["bubble_ms"] == cp["e2e_ms"]


def test_overlapped_timeline_attributes_gating_stage():
    """Constructed overlap: a=[0,10], b=[2,12] in a 12 ms frame. The
    shared [2,10] window is charged to b (it ends later — it is what
    gates completion), so a keeps only its solo [0,2]."""
    tl = _tl(1, 0, 12 * MS, [
        ("a", "l1", 0, 10 * MS),
        ("b", "l2", 2 * MS, 10 * MS),
    ])
    cp = frame_critical_path(tl)
    assert cp["stages"] == {"a": 2.0, "b": 10.0}
    # union 12 of 20 summed span-ms -> 40% overlap
    assert cp["overlap_fraction"] == pytest.approx(0.4)
    assert cp["bubble_ms"] == 0.0
    assert sum(cp["stages"].values()) == cp["e2e_ms"]


def test_open_or_empty_frames_are_skipped():
    assert frame_critical_path(
        _tl(1, 0, None, [("a", "l", 0, MS)])) is None
    assert frame_critical_path(_tl(1, 0, 10 * MS, [])) is None
    rep = occupancy_report([_tl(1, 0, None, [("a", "l", 0, MS)])])
    assert rep["frames"] == 0


def test_occupancy_report_aggregates_and_renders():
    tls = [
        _tl(1, 0, 10 * MS, [("capture", "cap", 0, 4 * MS),
                            ("encode.dispatch", "main", 4 * MS, 6 * MS)]),
        _tl(2, 20 * MS, 32 * MS, [
            ("capture", "cap", 20 * MS, 4 * MS),
            ("encode.dispatch", "main", 24 * MS, 8 * MS)]),
    ]
    rep = occupancy_report(tls)
    assert rep["frames"] == 2
    assert rep["overlap_fraction"] == 0.0
    # capture: 8 of 22 total e2e ms; dispatch: 14 of 22
    assert rep["critical_path"]["encode.dispatch"]["share"] == \
        pytest.approx(14 / 22, abs=1e-4)
    assert rep["critical_path"]["capture"]["share"] == \
        pytest.approx(8 / 22, abs=1e-4)
    assert rep["e2e_ms"]["p50"] in (10.0, 12.0)
    text = render_occupancy(rep)
    assert "encode.dispatch" in text and "overlap=0.0%" in text


def test_lane_occupancy_detects_bubbles():
    """Two frames pipelined on two lanes: the cap lane works [0,4] and
    [10,14] inside a [0,20] window -> 40% occupancy, 6 ms worst gap."""
    tls = [
        _tl(1, 0, 12 * MS, [("capture", "cap", 0, 4 * MS),
                            ("encode", "dev", 4 * MS, 8 * MS)]),
        _tl(2, 10 * MS, 20 * MS, [("capture", "cap", 10 * MS, 4 * MS),
                                  ("encode", "dev", 14 * MS, 6 * MS)]),
    ]
    lanes = lane_occupancy(tls)
    assert lanes["cap"]["busy_ms"] == 8.0
    assert lanes["cap"]["window_ms"] == 20.0
    assert lanes["cap"]["occupancy"] == pytest.approx(0.4)
    assert lanes["cap"]["largest_gap_ms"] == 6.0
    # the dev lane is busy [4,12]+[14,20]: 14/20, worst gap 4 (start)
    assert lanes["dev"]["occupancy"] == pytest.approx(0.7)
    assert lanes["dev"]["largest_gap_ms"] == 4.0


def test_lane_occupancy_clips_spans_to_window():
    """A span adopted by frame-id that outlives its frame envelope (the
    relay ws.send pattern) is clipped to the window: busy can never
    exceed the denominator, occupancy never reads > 100%."""
    tls = [
        _tl(1, 0, 10 * MS, [
            ("encode", "dev", 0, 10 * MS),
            # ws.send attached to frame 1 but running [5, 25] — 15 ms
            # of it lies beyond the frame window's w1 of 10 ms
            ("ws.send", "relay", 5 * MS, 20 * MS),
        ]),
    ]
    lanes = lane_occupancy(tls)
    assert lanes["relay"]["busy_ms"] == 5.0
    assert lanes["relay"]["window_ms"] == 10.0
    assert lanes["relay"]["occupancy"] == pytest.approx(0.5)
    for lane in lanes.values():
        assert lane["busy_ms"] <= lane["window_ms"]
        assert lane["occupancy"] <= 1.0


def test_occupancy_survives_export_roundtrip():
    """A saved /api/trace snapshot must occupancy-analyze identically
    to the live ring (timelines_from_events inverts to_trace_events)."""
    tls = [_tl(7, 0, 12 * MS, [("a", "l1", 0, 10 * MS),
                               ("b", "l2", 2 * MS, 10 * MS)])]
    doc = to_trace_events(tls)
    back = timelines_from_events(doc["traceEvents"])
    assert len(back) == 1
    assert back[0]["frame_id"] == 7
    direct = occupancy_report(tls)
    via_export = occupancy_report(back)
    assert via_export["overlap_fraction"] == \
        pytest.approx(direct["overlap_fraction"])
    assert via_export["critical_path"].keys() == \
        direct["critical_path"].keys()


# ------------------------------------------------------------ perf ledger
def _bench_doc(fps=10.0, p99=80.0, backend="cpu", status="ok",
               metric="encode_fps_256x128_h264_tpu"):
    return {"metric": metric, "value": fps, "unit": "fps",
            "vs_baseline": round(fps / 60.0, 3),
            "latency_p50_ms": p99 * 0.6, "latency_p99_ms": p99,
            "backend": backend,
            "backend_health": {"status": status, "reason": "test"},
            "stages_ms": {"encode.dispatch": 9.0, "packetize": 1.0}}


def _record(ledger, doc, host=None):
    entry = perf_ledger.entry_from_bench(doc, host=host)
    perf_ledger.append_entry(str(ledger), entry)
    return entry


def test_ledger_record_check_roundtrip(tmp_path):
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc())
    _record(led, _bench_doc(fps=9.8, p99=82.0))
    entries = perf_ledger.read_ledger(str(led))
    assert len(entries) == 2
    assert all(e["baseline_eligible"] for e in entries)
    assert entries[0]["resolution"] == "256x128"
    assert entries[0]["codec"] == "h264"
    # within-band drift: check passes
    assert perf_ledger.main(["--ledger", str(led), "check"]) == 0


def test_ledger_check_fails_on_seeded_regression(tmp_path):
    """The ISSUE acceptance fixture: record a healthy baseline, then a
    seeded regression — check must fail (and pass with --warn-only)."""
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc(fps=10.0, p99=80.0))
    _record(led, _bench_doc(fps=6.0, p99=200.0))
    assert perf_ledger.main(["--ledger", str(led), "check"]) == 1
    assert perf_ledger.main(
        ["--ledger", str(led), "check", "--warn-only"]) == 0


def test_ledger_noise_band_edges(tmp_path):
    led = tmp_path / "ledger.jsonl"
    base = perf_ledger.entry_from_bench(_bench_doc(fps=10.0, p99=100.0))
    # exactly on the band edge: NOT a regression (band is exclusive)
    at_edge = perf_ledger.entry_from_bench(_bench_doc(fps=8.5, p99=115.0))
    assert perf_ledger.compare(at_edge, base, band=0.15) == []
    beyond_fps = perf_ledger.entry_from_bench(
        _bench_doc(fps=8.49, p99=100.0))
    assert len(perf_ledger.compare(beyond_fps, base, band=0.15)) == 1
    beyond_p99 = perf_ledger.entry_from_bench(
        _bench_doc(fps=10.0, p99=115.1))
    assert len(perf_ledger.compare(beyond_p99, base, band=0.15)) == 1
    # a tighter band flags the edge case too
    assert len(perf_ledger.compare(at_edge, base, band=0.10)) == 2


def test_ledger_cpu_fallback_never_compares_against_tpu(tmp_path):
    """The r4/r5 rule, structurally: a cpu-fallback candidate has
    backend class 'cpu' so no TPU baseline can ever match its key, AND
    its failed health verdict skips gating entirely."""
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc(fps=50.0, p99=20.0, backend="tpu"))
    fallback = perf_ledger.entry_from_bench(
        _bench_doc(fps=0.3, p99=900.0, backend="cpu-fallback-relay-dead",
                   status="failed"))
    assert fallback["baseline_eligible"] is False
    assert fallback["backend_class"] == "cpu"
    entries = perf_ledger.read_ledger(str(led))
    assert perf_ledger.find_baseline(entries, fallback) is None
    perf_ledger.append_entry(str(led), fallback)
    # a failed-health run is reported, never compared — rc 3 ("no
    # gateable number") so a hard-fail gate can't be bypassed by a
    # regression that also breaks health; --warn-only stays green
    assert perf_ledger.main(["--ledger", str(led), "check"]) == 3
    assert perf_ledger.main(
        ["--ledger", str(led), "check", "--warn-only"]) == 0
    # and an HONEST cpu run still never matches the tpu baseline
    honest_cpu = perf_ledger.entry_from_bench(
        _bench_doc(fps=1.0, p99=500.0, backend="cpu"))
    assert perf_ledger.find_baseline(entries, honest_cpu) is None


def test_ledger_degraded_health_never_exits_green(tmp_path):
    """A degraded (not just failed) candidate is equally non-gateable:
    rc 3 without --warn-only, so perf regressions that co-occur with a
    health degradation can't pass a hard-fail gate."""
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc(fps=10.0, p99=80.0))
    _record(led, _bench_doc(fps=6.0, p99=200.0, status="degraded"))
    assert perf_ledger.main(["--ledger", str(led), "check"]) == 3
    assert perf_ledger.main(
        ["--ledger", str(led), "check", "--warn-only"]) == 0


def test_ledger_fallback_entry_is_never_a_baseline(tmp_path):
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc(fps=5.0, p99=300.0,
                            backend="cpu-fallback-relay-dead",
                            status="failed"))
    cand = perf_ledger.entry_from_bench(_bench_doc(fps=1.0, p99=900.0))
    assert perf_ledger.find_baseline(
        perf_ledger.read_ledger(str(led)), cand) is None


def test_ledger_host_isolation_and_ignore_host(tmp_path):
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc(fps=10.0), host="host-a")
    cand = perf_ledger.entry_from_bench(_bench_doc(fps=5.0),
                                        host="host-b")
    entries = perf_ledger.read_ledger(str(led))
    assert perf_ledger.find_baseline(entries, cand) is None
    assert perf_ledger.find_baseline(entries, cand,
                                     ignore_host=True) is not None


def test_ledger_check_candidate_file_and_report(tmp_path, capsys):
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc(fps=10.0, p99=80.0))
    cand_file = tmp_path / "cand.json"
    cand_file.write_text(json.dumps(_bench_doc(fps=4.0, p99=400.0)))
    assert perf_ledger.main(
        ["--ledger", str(led), "check", "--candidate", str(cand_file),
         "--ignore-host"]) == 1
    assert perf_ledger.main(["--ledger", str(led), "report"]) == 0
    out = capsys.readouterr().out
    assert "encode.dispatch" in out        # top-stage column rendered
    assert "256x128" in out


def test_ledger_check_candidate_not_compared_to_its_own_copy(tmp_path):
    """bench auto-appends every run, so `check --candidate out.json`
    must not match the candidate against its own ledger copy (same rev,
    same numbers) — that would make the gate always pass."""
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc(fps=10.0, p99=80.0))       # the real baseline
    reg_doc = _bench_doc(fps=6.0, p99=200.0)           # a regression run
    _record(led, reg_doc)                              # ...auto-appended
    cand = tmp_path / "out.json"
    cand.write_text(json.dumps(reg_doc))
    assert perf_ledger.main(
        ["--ledger", str(led), "check", "--candidate", str(cand)]) == 1


def test_ledger_check_unknown_health_fails_loudly(tmp_path):
    """Schema drift / wrong file must not silently disable the gate:
    a candidate without a recognisable backend_health errors out."""
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc(fps=10.0, p99=80.0))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"metric": "encode_fps_256x128_h264_tpu",
                               "value": 9.0}))
    assert perf_ledger.main(
        ["--ledger", str(led), "check", "--candidate", str(bad)]) == 2
    assert perf_ledger.main(
        ["--ledger", str(led), "check", "--candidate", str(bad),
         "--warn-only"]) == 0


def test_ledger_chaos_runs_are_ignored_by_check(tmp_path):
    led = tmp_path / "ledger.jsonl"
    _record(led, _bench_doc(fps=1.0, p99=100.0, metric="chaos_recovery"))
    # no encode_fps entry at all -> no candidate; warn-only passes
    assert perf_ledger.main(
        ["--ledger", str(led), "check", "--warn-only"]) == 0


# -------------------------------------------------- cost registry / parser
def test_registry_roofline_and_normalisation():
    reg = perf_mod.PerfRegistry()
    e = reg.record_analysis(
        "step", cost=[{"flops": 2e9, "bytes accessed": 1.6e9}],
        memory={"argument_size_in_bytes": 10, "output_size_in_bytes": 20,
                "temp_size_in_bytes": 30}, backend="tpu", compile_s=2.0)
    assert e["roofline_ms"] == pytest.approx(2.0)   # 1.6e9 B @ 800 GB/s
    assert e["peak_bytes"] == 60
    rep = reg.report()
    assert rep["count"] == 1 and rep["hbm_gbps"] == 800.0
    json.dumps(rep)                                 # API-serialisable
    # overwrite (recompile after buffer growth) replaces, not duplicates
    reg.record_analysis("step", cost={"flops": 1.0})
    assert reg.report()["count"] == 1


def test_parse_profile_dir(tmp_path):
    run = tmp_path / "plugins" / "profile" / "r1"
    run.mkdir(parents=True)
    events = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 4000.0,
         "name": "jit_h264_p_step"},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 0.0, "dur": 2500.0,
         "name": "fusion.42"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 9999.0,
         "name": "jit_h264_p_step"},     # host copy: must not count
    ]
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    table = perf_mod.parse_profile_dir(
        str(tmp_path), step_names=["h264.p_step[1920x1088]"])
    assert table["device"] is True
    assert table["steps"]["h264.p_step[1920x1088]"]["total_ms"] == \
        pytest.approx(4.0)
    assert table["total_ms"] == pytest.approx(6.5)
    assert table["top_ops"][0]["name"] == "jit_h264_p_step"


def _write_capture(tmp_path, events):
    run = tmp_path / "plugins" / "profile" / "r1"
    run.mkdir(parents=True)
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_parse_profile_dir_same_stem_steps_do_not_double_count(tmp_path):
    """Two geometries of one program share a stem ('jpeg_step'): the
    capture's events must be claimed once across the table, never
    summed into both rows."""
    _write_capture(tmp_path, [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 4000.0,
         "name": "jit_jpeg_step"},
    ])
    table = perf_mod.parse_profile_dir(
        str(tmp_path), step_names=["jpeg.step[1920x1080@420]",
                                   "jpeg.step[1280x720@420]"])
    total = sum(s["total_ms"] for s in table["steps"].values())
    assert total == pytest.approx(4.0)
    assert len(table["steps"]) == 1
    # and the time is NOT silently credited to whichever geometry sorts
    # first: the row is merged and names both claimants
    row = table["steps"]["jpeg.step[*]"]
    assert row["ambiguous"] == ["jpeg.step[1280x720@420]",
                                "jpeg.step[1920x1080@420]"]


def test_parse_profile_dir_seats_stem_is_distinct_from_single_seat(
        tmp_path):
    """Multi-seat modules compile as jit_h264_seatsN_{mode}_step: their
    device time must land on the seats row, and the single-seat stem
    must not claim it."""
    _write_capture(tmp_path, [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 6000.0,
         "name": "jit_h264_seats2_i_step"},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 1000.0,
         "name": "jit_h264_i_step"},
    ])
    table = perf_mod.parse_profile_dir(
        str(tmp_path), step_names=["h264.i_step[256x128]",
                                   "h264.seats2_i_step[256x128]"])
    assert table["steps"]["h264.seats2_i_step[256x128]"]["total_ms"] == \
        pytest.approx(6.0)
    assert table["steps"]["h264.i_step[256x128]"]["total_ms"] == \
        pytest.approx(1.0)


def test_parse_profile_dir_host_fallback_and_empty(tmp_path):
    assert perf_mod.parse_profile_dir(
        str(tmp_path), step_names=[])["trace_files"] == 0
    run = tmp_path / "p"
    run.mkdir()
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 1000.0,
         "name": "jit_jpeg_step"},
    ]
    (run / "h.trace.json").write_text(json.dumps({"traceEvents": events}))
    table = perf_mod.parse_profile_dir(
        str(tmp_path), step_names=["jpeg.step[64x64@420]"])
    assert table["device"] is False      # cpu capture: host lane counts
    assert table["steps"]["jpeg.step[64x64@420]"]["total_ms"] == \
        pytest.approx(1.0)


# --------------------------------------------------------- wrap_step (jax)
def test_wrap_step_records_analysis_and_matches_jit():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    reg = perf_mod.PerfRegistry()
    jitted = jax.jit(lambda x: (x.astype(jnp.float32) ** 2).sum())
    wrapped = perf_mod._WrappedStep("test.step", jitted, reg)
    x = jnp.arange(64, dtype=jnp.int32)
    assert float(wrapped(x)) == float(jitted(x))
    rep = reg.report()
    assert rep["count"] == 1
    entry = rep["steps"][0]
    assert entry["name"] == "test.step" and entry["error"] is None
    assert entry["signature"] == "(int32[64])"
    assert entry["compile_s"] is not None
    # second call reuses the AOT executable; no new entries
    assert float(wrapped(x + 1)) == float(jitted(x + 1))
    assert reg.report()["count"] == 1


def test_record_analysis_keeps_zero_compile_s():
    """compile_s=0.0 is a measurement (instant/cached compile), not
    'never measured': it must survive as 0.0, not collapse to null."""
    reg = perf_mod.PerfRegistry()
    e = reg.record_analysis("zero.step", compile_s=0.0)
    assert e["compile_s"] == 0.0
    assert reg.record_analysis("unmeasured.step")["compile_s"] is None


class _FakeJit:
    """A 'jitted' callable whose AOT path is broken: wrap_step must
    fall back to plain dispatch and record the failure. No jax needed —
    numpy arrays carry shape/dtype for the signature."""

    def __init__(self):
        self.calls = 0
        self.lowers = 0

    def __call__(self, x):
        self.calls += 1
        return x + 1

    def lower(self, *args):
        self.lowers += 1
        raise RuntimeError("no AOT for you")


def test_wrap_step_falls_back_when_analysis_breaks():
    import numpy as np
    reg = perf_mod.PerfRegistry()
    fake = _FakeJit()
    wrapped = perf_mod._WrappedStep("broken.step", fake, reg)
    x = np.arange(8)
    # the step still runs (plain dispatch) and the failure is visible
    assert list(wrapped(x)) == list(x + 1)
    entry = reg.report()["steps"][0]
    assert entry["error"] is not None and "no AOT" in entry["error"]
    # the fallback is sticky: no second lowering attempt
    assert list(wrapped(x)) == list(x + 1)
    assert fake.lowers == 1 and fake.calls == 2


def test_note_fallback_counts_and_surfaces_in_report():
    reg = perf_mod.PerfRegistry()
    e = reg.note_fallback("enc.step", "execute_failed", "(uint8[4])")
    assert e["count"] == 1
    reg.note_fallback("enc.step", "execute_failed", "(uint8[4])")
    reg.note_fallback("other.step", "compile_failed")
    rep = reg.report()
    assert [x["step"] for x in rep["fallbacks"]] == \
        ["enc.step", "other.step"]          # most occurrences first
    assert rep["fallbacks"][0]["count"] == 2
    assert rep["fallbacks"][1]["reason"] == "compile_failed"
    reg.clear()
    assert reg.report()["fallbacks"] == []


def test_note_fallback_table_is_bounded():
    reg = perf_mod.PerfRegistry(max_steps=4)
    for i in range(10):
        reg.note_fallback(f"s{i}", "execute_failed")
    assert len(reg.report()["fallbacks"]) == 4


def test_wrap_step_compile_failure_notes_fallback():
    import numpy as np
    reg = perf_mod.PerfRegistry()
    wrapped = perf_mod._WrappedStep("broken.step", _FakeJit(), reg)
    wrapped(np.arange(8))
    fb, = reg.report()["fallbacks"]
    assert fb["step"] == "broken.step"
    assert fb["reason"] == "compile_failed"


def test_wrap_step_execute_failure_notes_fallback_and_incident():
    import numpy as np

    from selkies_tpu.obs.health import engine as health_engine

    class _Compiled:
        def cost_analysis(self):
            return {"flops": 1.0}

        def memory_analysis(self):
            return None

        def __call__(self, x):
            raise RuntimeError("exec boom")

    class _Lowered:
        def cost_analysis(self):
            return {"flops": 1.0}

        def compile(self):
            return _Compiled()

    class _Jit:
        def __call__(self, x):
            return "jit-result"

        def lower(self, *a):
            return _Lowered()

    reg = perf_mod.PerfRegistry()
    wrapped = perf_mod._WrappedStep("exec.step", _Jit(), reg)
    assert wrapped(np.arange(4, dtype=np.int32)) == "jit-result"
    fb, = reg.report()["fallbacks"]
    assert fb["reason"] == "execute_failed"
    assert "int32" in fb["signature"]
    # the permanent fallback is an operator-visible incident
    kinds = [e for e in health_engine.recorder.snapshot()
             if e["kind"] == "wrapped_step_fallback"]
    assert kinds and kinds[-1]["step"] == "exec.step"


def test_kill_switch_fallback_is_not_counted(monkeypatch):
    """SELKIES_PERF_ANALYSIS=0 is a deliberate operator choice, not a
    defect — it must not pollute the fallback incident surface."""
    import numpy as np
    monkeypatch.setenv("SELKIES_PERF_ANALYSIS", "0")
    reg = perf_mod.PerfRegistry()
    fake = _FakeJit()
    wrapped = perf_mod._WrappedStep("ks.step", fake, reg)
    assert list(wrapped(np.arange(8))) == list(np.arange(8) + 1)
    assert reg.report()["fallbacks"] == []
    assert fake.lowers == 0


def test_wrap_step_no_retry_after_donated_input_consumed():
    """A Compiled that dies mid-execution AFTER consuming a donated
    input (reference planes, age counters) must re-raise the real
    device error: retrying plain jit against deleted buffers would mask
    it with 'Array has been deleted'. Fresh inputs still take the
    sticky jit fallback."""
    class _Arg:
        shape = (4,)
        dtype = "int32"
        weak_type = False

        def __init__(self):
            self.deleted = False

        def is_deleted(self):
            return self.deleted

    class _Compiled:
        def cost_analysis(self):
            return {"flops": 1.0}

        def memory_analysis(self):
            return None

        def __call__(self, x):
            x.deleted = True           # donation consumed the buffer
            raise RuntimeError("device boom")

    class _Lowered:
        def cost_analysis(self):
            return {"flops": 1.0}

        def compile(self):
            return _Compiled()

    class _Jit:
        def __init__(self):
            self.calls = 0

        def __call__(self, x):
            self.calls += 1
            return "jit-result"

        def lower(self, *a):
            return _Lowered()

    reg = perf_mod.PerfRegistry()
    jit = _Jit()
    wrapped = perf_mod._WrappedStep("donate.step", jit, reg)
    with pytest.raises(RuntimeError, match="device boom"):
        wrapped(_Arg())
    assert jit.calls == 0              # no masking retry
    # a later call with live inputs uses the sticky jit fallback
    assert wrapped(_Arg()) == "jit-result"
    assert jit.calls == 1


def test_wrap_step_env_kill_switch(monkeypatch):
    import numpy as np
    monkeypatch.setenv("SELKIES_PERF_ANALYSIS", "0")
    reg = perf_mod.PerfRegistry()
    fake = _FakeJit()
    wrapped = perf_mod._WrappedStep("off.step", fake, reg)
    assert list(wrapped(np.arange(4))) == [1, 2, 3, 4]
    assert fake.lowers == 0
    assert reg.report()["count"] == 0


# ------------------------------------------------- profile_h264 increments
def test_profile_writer_incremental_partial_results(tmp_path):
    """The r3 failure mode: a profile killed mid-run must keep every
    completed stage on disk, marked incomplete."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "profile_writer_host", ROOT / "tools" / "profile_h264.py")
    src = (ROOT / "tools" / "profile_h264.py").read_text()
    # lift just the writer class: importing the module pulls in jax and
    # configures the compile cache, which a unit test must not do
    ns: dict = {}
    class_src = src[src.index("class ProfileWriter"):
                    src.index("def t(")]
    exec(compile("import json, os\n" + class_src,  # noqa: S102
                 str(spec.origin), "exec"), ns)
    out = tmp_path / "prof.json"
    w = ns["ProfileWriter"](str(out), meta={"backend": "cpu"})
    w.add("csc", 0.123)
    # simulate the relay dying here: the file already carries stage 1
    doc = json.loads(out.read_text())
    assert doc["complete"] is False
    assert doc["stages"]["csc"]["ms"] == 0.123
    assert doc["backend"] == "cpu"
    w.add("full_i", 88.0, motion_k=9)
    w.finish()
    doc = json.loads(out.read_text())
    assert doc["complete"] is True
    assert set(doc["stages"]) == {"csc", "full_i"}
    assert doc["stages"]["full_i"]["motion_k"] == 9
