"""Deep-pipeline invariants (ROADMAP 2 / ISSUE 10).

The depth-N rework must never be OBSERVABLE in the byte stream: in-order
per-seat delivery, byte-identical output vs serial mode for both codecs
(donation must not alias a slot still being read back), bounded depth
under backpressure, and a mid-pipeline finalize death that drains — not
wedges — the ring. All on CPU at tiny geometry.
"""

import threading
import time

import numpy as np
import pytest

from selkies_tpu.engine import CaptureSettings, ScreenCapture
from selkies_tpu.engine.pipeline import PipelineError, PipelineRing
from selkies_tpu.resilience import faults as _faults

SMALL = dict(capture_width=64, capture_height=64, stripe_height=32,
             target_fps=240.0, jpeg_quality=75)


# ------------------------------------------------------------------ ring unit

def test_ring_delivers_in_submission_order():
    got = []
    ring = PipelineRing(lambda out: got.append(out["n"]), depth=3)
    for n in range(24):
        ring.submit({"n": n})
    ring.close(drain=True)
    assert got == list(range(24))


def test_ring_slot_indices_cycle_the_depth():
    slots = []
    ring = PipelineRing(lambda out: slots.append(out["slot"]), depth=3)
    for n in range(9):
        ring.submit({"n": n})
    ring.close(drain=True)
    assert slots == [0, 1, 2] * 3


def test_ring_submit_blocks_at_depth_and_resumes():
    """The ring IS the engine's backpressure: with `depth` frames in
    flight, submit() parks the producer until a slot drains."""
    gate = threading.Event()
    done = []

    def fin(out):
        gate.wait(5.0)
        done.append(out["n"])

    ring = PipelineRing(fin, depth=2)
    ring.submit({"n": 0})
    ring.submit({"n": 1})       # depth reached; finalizer holds slot 0
    blocked = threading.Event()
    submitted = threading.Event()

    def third():
        blocked.set()
        ring.submit({"n": 2})
        submitted.set()

    t = threading.Thread(target=third, daemon=True)
    t.start()
    assert blocked.wait(2.0)
    assert not submitted.wait(0.3), "submit must block at depth"
    gate.set()                   # finalizer drains
    assert submitted.wait(5.0)
    ring.close(drain=True)
    assert done == [0, 1, 2]


def test_ring_set_depth_shrinks_live():
    gate = threading.Event()
    ring = PipelineRing(lambda out: gate.wait(5.0), depth=4)
    ring.submit({})
    ring.submit({})
    ring.set_depth(1)
    t0 = time.monotonic()
    ok = []

    def try_submit():
        try:
            ring.submit({})
            ok.append(time.monotonic() - t0)
        except PipelineError:
            pass

    t = threading.Thread(target=try_submit, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not ok, "shrunk depth must gate new submissions"
    gate.set()
    t.join(5.0)
    ring.close(drain=True)
    assert ok, "gate must lift once in-flight drains below the new depth"


def test_ring_finalize_death_drains_never_wedges():
    """A mid-pipeline finalize death parks the ring failed: queued slots
    are DISCARDED, blocked producers wake, and the next submit raises on
    the producer thread (-> capture_death -> supervised restart)."""
    def fin(out):
        if out["n"] == 1:
            raise RuntimeError("injected readback death")

    ring = PipelineRing(fin, depth=2)
    ring.submit({"n": 0})
    ring.submit({"n": 1})
    with pytest.raises(PipelineError) as ei:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            ring.submit({"n": 99})
            time.sleep(0.01)
    assert "injected readback death" in str(ei.value)
    assert ring.failed
    ring.close(drain=False)      # returns promptly: nothing wedged


# ------------------------------------------------- byte-identity vs serial

def _frames(src_cls, n, w, h):
    """Animated frames with a static tail — exercises damage gating and
    the donation path across slot reuse."""
    src = src_cls(w, h)
    return [src.get_frame(t if t < n - 3 else n - 4) for t in range(n)]


def _run_serial(sess, frames, force_first=True):
    got = []
    for t, f in enumerate(frames):
        out = sess.encode(f, force=(force_first and t == 0))
        out["slot"] = 0
        got.extend(sess.finalize(out, force_all=(force_first and t == 0)))
    return [(c.frame_id, c.stripe_y, c.payload) for c in got]


def _run_pipelined(sess, frames, depth, stream, force_first=True):
    got = []

    def fin(out):
        force_all = out.pop("force_all")
        if stream:
            got.extend(sess.finalize_stream(out, force_all=force_all))
        else:
            got.extend(sess.finalize(out, force_all=force_all))

    ring = PipelineRing(fin, depth=depth)
    for t, f in enumerate(frames):
        out = sess.encode(f, force=(force_first and t == 0))
        out["force_all"] = force_first and t == 0
        ring.submit(out)
    ring.close(drain=True)
    return [(c.frame_id, c.stripe_y, c.payload) for c in got]


@pytest.mark.parametrize("stream", [False, True],
                         ids=["batch", "stripe-streaming"])
def test_jpeg_pipelined_byte_identical_to_serial(stream):
    from selkies_tpu.engine.encoder import JpegEncoderSession
    from selkies_tpu.engine.sources import SyntheticSource
    s1, s2 = CaptureSettings(**SMALL), CaptureSettings(**SMALL)
    frames = _frames(SyntheticSource, 10, s1.capture_width,
                     s1.capture_height)
    serial = _run_serial(JpegEncoderSession(s1), frames)
    piped = _run_pipelined(JpegEncoderSession(s2), frames, depth=3,
                           stream=stream)
    assert serial == piped


@pytest.mark.parametrize("stream", [False, True],
                         ids=["batch", "stripe-streaming"])
def test_h264_pipelined_byte_identical_to_serial(stream):
    """Depth-3 in flight with donated prev/age/refs: donation must not
    alias a slot still being read back — any aliasing shows up as a
    byte diff in the P-frame residuals here."""
    from selkies_tpu.engine.h264_encoder import H264EncoderSession
    from selkies_tpu.engine.sources import SyntheticSource
    cfg = dict(SMALL, output_mode="h264", video_crf=28)
    s1, s2 = CaptureSettings(**cfg), CaptureSettings(**cfg)
    frames = _frames(SyntheticSource, 10, s1.capture_width,
                     s1.capture_height)
    serial = _run_serial(H264EncoderSession(s1), frames)
    piped = _run_pipelined(H264EncoderSession(s2), frames, depth=3,
                           stream=stream)
    assert serial == piped


def test_sessions_tolerate_caller_reusing_frame_arrays():
    """Donation discipline: the step donates only session-owned state,
    never the caller's frame — a source handing back the SAME device
    array every tick (static X11 grab) must keep working."""
    from selkies_tpu.engine.encoder import JpegEncoderSession
    sess = JpegEncoderSession(CaptureSettings(**SMALL))
    import jax.numpy as jnp
    frame = jnp.zeros((sess.grid.height, sess.grid.width, 3), jnp.uint8)
    for _ in range(4):
        sess.finalize(sess.encode(frame))      # same array object each time
    assert int(sess.frame_id) == 4


# --------------------------------------------------------- capture loop

def _collect_chunks(depth, n_want=12, **over):
    cfg = dict(SMALL, pipeline_depth=depth, **over)
    got = []
    cap = ScreenCapture("synthetic")
    cap.start_capture(got.append, CaptureSettings(**cfg))
    deadline = time.monotonic() + 30
    while len(got) < n_want and time.monotonic() < deadline:
        time.sleep(0.01)
    cap.stop_capture()
    return got, cap


@pytest.mark.parametrize("depth", [2, 3])
def test_capture_loop_pipelined_delivery_in_order(depth):
    got, _ = _collect_chunks(depth, n_want=16)
    assert len(got) >= 16
    fids = [c.frame_id for c in got]
    # frame ids non-decreasing: pipelining must never reorder delivery
    assert fids == sorted(fids)


def test_capture_depth_clamp_and_effective_depth():
    cap = ScreenCapture("synthetic")
    cap._settings = CaptureSettings(**dict(SMALL, pipeline_depth=3))
    assert cap.effective_pipeline_depth() == 3
    cap.set_pipeline_clamp(1)        # relay backpressure window
    assert cap.effective_pipeline_depth() == 1
    cap.set_pipeline_clamp(None)
    assert cap.effective_pipeline_depth() == 3
    cap._settings.pipeline_depth = 1
    cap.set_pipeline_clamp(4)        # clamp never RAISES the depth
    assert cap.effective_pipeline_depth() == 1


def test_capture_loop_depth_clamp_under_injected_backpressure():
    """Clamping to 1 mid-run drops the loop to serial (ring closed,
    drained) without losing or reordering frames."""
    got, cap = [], ScreenCapture("synthetic")
    cap.start_capture(got.append,
                      CaptureSettings(**dict(SMALL, pipeline_depth=3)))
    deadline = time.monotonic() + 30
    while len(got) < 6 and time.monotonic() < deadline:
        time.sleep(0.01)
    cap.set_pipeline_clamp(1)        # what a paused client does
    n_at_clamp = len(got)
    while len(got) < n_at_clamp + 6 and time.monotonic() < deadline:
        time.sleep(0.01)
    cap.stop_capture()
    fids = [c.frame_id for c in got]
    assert fids == sorted(fids)
    assert len(got) >= n_at_clamp + 6, "loop must keep delivering at depth 1"


def test_readback_fetch_death_recovers_via_supervised_restart():
    """Mid-pipeline readback death (fault readback.fetch:error): the
    ring drains, the loop dies through on_death, and a restart delivers
    fresh frames — in-flight slots never wedge the stop/restart path."""
    died = threading.Event()
    got = []
    cap = ScreenCapture("synthetic")
    cap.on_death = lambda exc: died.set()
    _faults.registry.disarm()
    _faults.registry.arm("readback.fetch:error:after=6,count=1")
    try:
        cap.start_capture(got.append,
                          CaptureSettings(**dict(SMALL, pipeline_depth=2)))
        assert died.wait(30), "injected readback death must reach on_death"
        cap.restart()                # what the supervisor does
        n0 = len(got)
        deadline = time.monotonic() + 30
        while len(got) < n0 + 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(got) >= n0 + 4, "restarted loop must deliver again"
    finally:
        _faults.registry.disarm()
        cap.stop_capture()


# ------------------------------------------------------------ relay reorder

def test_relay_stripe_reorder_fault_swaps_queue_and_asks_idr():
    from selkies_tpu import protocol as P
    from selkies_tpu.server.relay import VideoRelay
    idr = []

    async def send(_b):
        pass

    _faults.registry.disarm()
    # the injection site only CONSUMES a clause when the queue can
    # actually be reordered (>= 2 queued), so hits count from the
    # second offer on
    _faults.registry.arm("relay.stripe:reorder:after=1,count=1")
    try:
        r = VideoRelay(send, request_idr=lambda: idr.append(1),
                       display="d0")
        frames = [P.pack_h264_stripe(fid, 0, 64, 32, b"x" * 8, idr=True)
                  for fid in range(3)]
        r.offer(frames[0])       # q=1: cannot reorder, clause untouched
        assert _faults.registry.remaining() == 1
        r.offer(frames[1])       # hit 1: skipped by after=1
        r.offer(frames[2])       # hit 2: fires
        q = list(r._q)
        assert q == [frames[0], frames[2], frames[1]]   # newest two swapped
        assert idr, "an out-of-order h264 stripe must request a resync"
    finally:
        _faults.registry.disarm()


# ----------------------------------------------------- occupancy window view

def test_window_overlap_zero_for_serial_frames():
    from selkies_tpu.trace.summary import window_overlap_fraction
    MS = 1_000_000
    dicts = [
        {"t0_ns": 0, "t1_ns": 10 * MS, "spans": [
            {"name": "encode.dispatch", "lane": "cap", "t0_ns": 0,
             "dur_ns": 10 * MS}]},
        {"t0_ns": 10 * MS, "t1_ns": 20 * MS, "spans": [
            {"name": "encode.dispatch", "lane": "cap", "t0_ns": 10 * MS,
             "dur_ns": 10 * MS}]},
    ]
    assert window_overlap_fraction(dicts) == 0.0


def test_window_overlap_sees_cross_frame_concurrency():
    """Frame N+1's dispatch under frame N's readback: invisible to the
    per-frame view (stages of ONE frame are still sequential), captured
    by the window view — the deep-pipeline acceptance number."""
    from selkies_tpu.trace.summary import (occupancy_report,
                                           window_overlap_fraction)
    MS = 1_000_000
    dicts = [
        {"t0_ns": 0, "t1_ns": 20 * MS, "spans": [
            {"name": "encode.dispatch", "lane": "cap", "t0_ns": 0,
             "dur_ns": 10 * MS},
            {"name": "encode.readback", "lane": "slot0", "t0_ns": 10 * MS,
             "dur_ns": 10 * MS}]},
        {"t0_ns": 10 * MS, "t1_ns": 30 * MS, "spans": [
            {"name": "encode.dispatch", "lane": "cap", "t0_ns": 10 * MS,
             "dur_ns": 10 * MS},
            {"name": "encode.readback", "lane": "slot1", "t0_ns": 20 * MS,
             "dur_ns": 10 * MS}]},
    ]
    # union [0,30] = 30ms of 40ms span time -> 25% overlap
    assert window_overlap_fraction(dicts) == pytest.approx(0.25)
    rep = occupancy_report(dicts)
    assert rep["overlap_fraction"] == pytest.approx(0.25)
    # per-frame identity still exact: shares + bubble account for e2e
    assert rep["bubble_share"] == 0.0


# ----------------------------------------------------------- ladder rung 0

def test_ladder_default_steps_open_with_pipeline_rung():
    from selkies_tpu.resilience.ladder import DEFAULT_STEPS
    assert DEFAULT_STEPS[0] == "pipeline"
