"""Compile-plane tests (ISSUE 8): lattice enumeration, the pre-warm
worker, the ladder's deferred-transition gate, warm-cache artifacts,
and the cross-process cache-hit acceptance bar.

Fast paths are stdlib-only (fake compilers, injected clocks, tmp
artifact dirs). The one real-jax test — pack on "host A", refuse a
mismatched fingerprint, matched unpack makes the first session build
cache-hit — runs tiny-geometry subprocesses so the persistent-cache
counters (PR 3) are observed from a COLD process, the way a new fleet
host would see them.
"""

import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

import pytest

from selkies_tpu.obs.health import FAILED, OK, HealthEngine
from selkies_tpu.prewarm import artifact as art
from selkies_tpu.prewarm.lattice import (Signature, downscale_factor,
                                         enumerate_lattice,
                                         lattice_from_settings)
from selkies_tpu.prewarm.worker import PrewarmGate, PrewarmWorker
from selkies_tpu.resilience.ladder import DegradationLadder

ROOT = Path(__file__).resolve().parent.parent


class _NS:
    def __init__(self, **kw):
        self.__dict__.update(kw)


# ----------------------------------------------------------------- lattice

def test_lattice_dedups_quality_tier_onto_one_program():
    plan = lattice_from_settings(_NS(encoder="h264-tpu-striped",
                                     initial_width=1920,
                                     initial_height=1080))
    # fps + quality rungs share the base program; only downscale mints
    # a new compile identity
    assert len(plan.signatures) == 2
    assert plan.signatures[0] is plan.base
    assert (plan.signatures[1].width, plan.signatures[1].height) \
        == (960, 540)
    assert plan.signatures[1].quality_tier == "degraded"
    base = Signature(1920, 1080, "h264")
    degraded = Signature(1920, 1080, "h264", quality_tier="degraded")
    assert base.program_key == degraded.program_key


def test_lattice_rung_targets_point_at_programs():
    plan = lattice_from_settings(_NS(encoder="jpeg-tpu",
                                     initial_width=1280,
                                     initial_height=720))
    assert plan.rung_targets["fps"] == {"down": [], "up": []}
    assert plan.rung_targets["quality"] == {"down": [], "up": []}
    down = plan.rung_targets["downscale"]["down"]
    up = plan.rung_targets["downscale"]["up"]
    assert down == [plan.signatures[1].program_key]
    assert up == [plan.base.program_key]


def test_lattice_downscale_floor_and_stacking():
    multi = enumerate_lattice(Signature(1024, 768, "jpeg"),
                              steps=("downscale", "downscale4"))
    assert [(s.width, s.height) for s in multi.signatures] \
        == [(1024, 768), (512, 384), (128, 96)]
    # at the floor the rung is a no-op, not a duplicate program
    tiny = enumerate_lattice(Signature(64, 64, "jpeg"),
                             steps=("downscale",))
    assert len(tiny.signatures) == 1
    assert tiny.rung_targets["downscale"] == {"down": [], "up": []}
    assert downscale_factor("downscale") == 2
    assert downscale_factor("downscale4") == 4
    assert downscale_factor("quality") is None
    assert downscale_factor("downscaleX") is None


def test_lattice_seat_count_variants_are_distinct_programs():
    one = lattice_from_settings(_NS(encoder="jpeg-tpu",
                                    initial_width=640,
                                    initial_height=480, tpu_seats=1))
    four = lattice_from_settings(_NS(encoder="jpeg-tpu",
                                     initial_width=640,
                                     initial_height=480, tpu_seats=4))
    assert all(s.seats == 4 for s in four.signatures)
    assert one.base.program_key != four.base.program_key


def test_lattice_respects_session_knobs_in_program_key():
    a = Signature(640, 480, "jpeg")
    assert a.program_key != Signature(640, 480, "jpeg",
                                      fullcolor=True).program_key
    assert a.program_key != Signature(640, 480, "jpeg",
                                      stripe_height=32).program_key
    assert a.program_key != Signature(
        640, 480, "jpeg", use_damage_gating=False).program_key
    h = Signature(640, 480, "h264")
    assert h.program_key != Signature(
        640, 480, "h264", h264_motion_vrange=0).program_key


# ------------------------------------------------------------------ worker

def _fake_compiler(log):
    def compiler(sig):
        log.append(sig.program_key)
        if sig.width == 13:
            raise RuntimeError("synthetic compile failure")
        return {"programs": [f"fake[{sig.width}x{sig.height}]"]}
    return compiler


def test_worker_compiles_operating_point_first_then_rung_order():
    plan = enumerate_lattice(Signature(1024, 768, "jpeg"),
                             steps=("downscale", "downscale4"))
    log = []
    w = PrewarmWorker(plan, compiler=_fake_compiler(log))
    w.note_operating_point(512, 384)
    w.run_pending_sync()
    assert log == [plan.signatures[1].program_key,
                   plan.signatures[0].program_key,
                   plan.signatures[2].program_key]
    assert w.query(plan.program_keys) == "warm"
    assert w.counts()["warmed"] == 3


def test_worker_request_promotes_and_query_cold_for_unknown():
    plan = enumerate_lattice(Signature(1024, 768, "jpeg"),
                             steps=("downscale", "downscale4"))
    log = []
    w = PrewarmWorker(plan, compiler=_fake_compiler(log))
    target = plan.signatures[2].program_key
    assert w.query([target]) == "cold"
    assert w.query(["never-heard-of-it"]) == "cold"
    w.request([target])
    w._compile_one(w._order[0])
    assert log == [target]
    assert w.query([target]) == "warm"


def test_worker_failure_fails_health_and_records_incident():
    eng = HealthEngine()
    log = []
    w = PrewarmWorker(compiler=_fake_compiler(log), recorder=eng.recorder)
    good = w.ensure(Signature(640, 480, "jpeg"))
    bad = w.ensure(Signature(13, 13, "jpeg"))
    assert w.health_check().status == OK     # cold-but-warming is ok
    w.run_pending_sync()
    assert w.states() == {good: "warm", bad: "failed"}
    v = w.health_check()
    assert v.status == FAILED and "failed to warm" in v.reason
    kinds = [e["kind"] for e in eng.recorder.snapshot()]
    assert "prewarm_compiled" in kinds and "prewarm_failed" in kinds


def test_worker_unreachable_is_distinct_from_failure():
    """A lattice point the host cannot realise (stripe mesh degraded
    to one device) is neither warm-as-requested nor failed: it reports
    its own state, stays green, and answers the gate warm (the runtime
    would dispatch the same degraded program)."""
    eng = HealthEngine()

    def compiler(sig):
        if sig.width == 999:
            return {"programs": ["fake[999x480]"],
                    "unreachable": "stripe_devices=2 resolves to 1 "
                                   "on this host"}
        return {"programs": [f"fake[{sig.width}x{sig.height}]"]}

    w = PrewarmWorker(compiler=compiler, recorder=eng.recorder)
    good = w.ensure(Signature(640, 480, "jpeg"))
    unr = w.ensure(Signature(999, 480, "jpeg"))
    w.run_pending_sync()
    assert w.states() == {good: "warm", unr: "unreachable"}
    c = w.counts()
    assert c["unreachable"] == 1 and c["failed"] == 0
    assert w.query([unr]) == "warm"
    v = w.health_check()
    assert v.status == OK and "unreachable" in v.reason
    kinds = [e["kind"] for e in eng.recorder.snapshot()]
    assert "prewarm_unreachable" in kinds
    assert "prewarm_failed" not in kinds


def test_unreachable_point_not_advertised_in_warm_geometries():
    """An @sN entry for a mesh that degraded away must neither appear
    as schedulable capacity nor block the single-device geometry."""
    def compiler(sig):
        if getattr(sig, "stripe_devices", 1) > 1:
            return {"programs": [], "unreachable": "1 device host"}
        return {"programs": [f"fake[{sig.width}x{sig.height}]"]}

    w = PrewarmWorker(compiler=compiler)
    w.ensure(Signature(640, 480, "h264"))
    w.ensure(Signature(640, 480, "h264", stripe_devices=2))
    w.run_pending_sync()
    assert w.warm_geometries() == ["640x480"]


def test_worker_thread_pauses_on_storm_and_resumes():
    import threading
    storm = {"on": True}
    gate_open = threading.Event()
    compiled = threading.Event()

    def compiler(sig):
        compiled.set()
        return {"programs": ["p"]}

    w = PrewarmWorker(compiler=compiler, storm_check=lambda: storm["on"],
                      poll_s=0.02)
    w.ensure(Signature(640, 480, "jpeg"))
    w.start()
    try:
        assert not compiled.wait(0.3)     # held by the storm
        assert w.paused
        storm["on"] = False
        assert compiled.wait(2.0)         # resumes once the storm clears
        deadline = 50
        while w.counts()["warmed"] != 1 and deadline:
            deadline -= 1
            import time
            time.sleep(0.02)
        assert w.counts()["warmed"] == 1
    finally:
        w.stop()
    del gate_open


def test_worker_restart_requeues_interrupted_compile():
    plan = enumerate_lattice(Signature(640, 480, "jpeg"),
                             steps=("downscale",))
    log = []
    w = PrewarmWorker(plan, compiler=_fake_compiler(log))
    key = plan.base.program_key
    with w._lock:
        w._entries[key]["state"] = "compiling"   # died mid-compile
        w._order.remove(key)
    w.restart()
    try:
        import time
        for _ in range(100):
            if w.counts()["warmed"] == len(plan.signatures):
                break
            time.sleep(0.02)
        assert w.counts()["warmed"] == len(plan.signatures)
    finally:
        w.stop()


def test_worker_mark_warm_from_names_adopts_registry_programs():
    plan = enumerate_lattice(Signature(640, 480, "jpeg"),
                             steps=("downscale",))
    w = PrewarmWorker(plan, compiler=_fake_compiler([]))
    names_fn = lambda sig: [f"n[{sig.width}]"]     # noqa: E731
    assert w.mark_warm_from_names({"n[640]"}, names_fn) == 1
    assert w.states()[plan.base.program_key] == "warm"
    assert w.counts()["pending"] == 1              # the downscale target


# ----------------------------------------------------- ladder gate deferral

class _FakeGate:
    def __init__(self, state):
        self.state = dict(state)
        self.requests = []

    def query(self, step, direction):
        return self.state.get(step, "warm")

    def request(self, step, direction):
        self.requests.append((step, "down" if direction > 0 else "up"))


def test_ladder_defers_cold_rung_with_incident_and_request():
    eng = HealthEngine()
    gate = _FakeGate({"downscale": "cold"})
    lad = DegradationLadder(steps=("downscale",), down_after_s=1.0,
                            hold_s=1.0, ok_window_s=10.0, gate=gate,
                            defer_deadline_s=30.0, recorder=eng.recorder)
    bad = {"qoe": FAILED}
    lad.observe(bad, now=0.0)
    lad.observe(bad, now=1.5)
    assert lad.level == 0
    assert lad.deferred_transitions == 1
    assert gate.requests == [("downscale", "down")]
    kinds = [e["kind"] for e in eng.recorder.snapshot()]
    assert kinds == ["transition_deferred"]
    snap = lad.snapshot()
    assert snap["deferred"]["step"] == "downscale"
    assert snap["deferred"]["direction"] == "down"
    # deferral episode does not re-record every tick
    lad.observe(bad, now=2.0)
    assert lad.deferred_transitions == 1
    # program warms -> the held shift lands on the next tick
    gate.state["downscale"] = "warm"
    lad.observe(bad, now=3.0)
    assert lad.level == 1
    assert lad.snapshot()["deferred"] is None


def test_ladder_deadline_forces_nearest_warm_rung():
    eng = HealthEngine()
    gate = _FakeGate({"downscale": "cold", "downscale4": "warm"})
    lad = DegradationLadder(steps=("downscale", "downscale4"),
                            down_after_s=1.0, hold_s=1.0,
                            ok_window_s=10.0, gate=gate,
                            defer_deadline_s=3.0, recorder=eng.recorder)
    bad = {"qoe": FAILED}
    lad.observe(bad, now=0.0)
    lad.observe(bad, now=1.5)       # defers
    lad.observe(bad, now=2.0)       # still deferred
    assert lad.level == 0
    lad.observe(bad, now=5.0)       # deadline passed -> force /4
    assert lad.level == 2           # jumped past the cold rung
    step = [e for e in eng.recorder.snapshot()
            if e["kind"] == "degradation_step"][-1]
    assert step["step"] == "downscale4"
    assert step["skipped"] == ["downscale"]


def test_ladder_holds_when_nothing_is_warm_and_renews_deadline():
    gate = _FakeGate({"downscale": "cold"})
    lad = DegradationLadder(steps=("downscale",), down_after_s=1.0,
                            hold_s=1.0, ok_window_s=10.0, gate=gate,
                            defer_deadline_s=2.0,
                            recorder=HealthEngine().recorder)
    bad = {"qoe": FAILED}
    lad.observe(bad, now=0.0)
    lad.observe(bad, now=1.5)       # defer (deadline 3.5)
    lad.observe(bad, now=4.0)       # deadline passed, nothing warm
    assert lad.level == 0
    assert lad.snapshot()["deferred"]["deadline"] == 6.0   # renewed
    assert len(gate.requests) == 2  # re-requested at renewal


def test_ladder_recovery_cancels_down_deferral():
    gate = _FakeGate({"downscale": "cold"})
    lad = DegradationLadder(steps=("downscale",), down_after_s=1.0,
                            hold_s=1.0, ok_window_s=5.0, gate=gate,
                            defer_deadline_s=30.0,
                            recorder=HealthEngine().recorder)
    lad.observe({"qoe": FAILED}, now=0.0)
    lad.observe({"qoe": FAILED}, now=1.5)
    assert lad.snapshot()["deferred"] is not None
    lad.observe({"qoe": OK}, now=2.0)
    assert lad.snapshot()["deferred"] is None
    assert lad.level == 0


def test_ladder_gate_failures_fail_open():
    class _Boom:
        def query(self, step, direction):
            raise RuntimeError("gate crashed")

        def request(self, step, direction):
            raise RuntimeError("gate crashed")

    lad = DegradationLadder(steps=("downscale",), down_after_s=1.0,
                            hold_s=1.0, ok_window_s=10.0, gate=_Boom(),
                            recorder=HealthEngine().recorder)
    lad.observe({"qoe": FAILED}, now=0.0)
    lad.observe({"qoe": FAILED}, now=1.5)
    assert lad.level == 1           # shedding must not be blocked


def test_prewarm_gate_over_worker():
    plan = enumerate_lattice(Signature(1024, 768, "jpeg"),
                             steps=("downscale",))
    w = PrewarmWorker(plan, compiler=_fake_compiler([]))
    gate = PrewarmGate(w, plan.rung_targets)
    assert gate.query("fps", +1) == "warm"       # compile-free rung
    assert gate.query("downscale", +1) == "cold"
    gate.request("downscale", +1)
    assert w._order[0] == plan.signatures[1].program_key
    w.run_pending_sync()
    assert gate.query("downscale", +1) == "warm"
    assert gate.query("downscale", -1) == "warm"


# ---------------------------------------------------------------- artifact

def _make_cache(tmp_path) -> str:
    cache = tmp_path / "cache"
    (cache / "sub").mkdir(parents=True)
    (cache / "a.bin").write_bytes(b"xla" * 100)
    (cache / "sub" / "b.bin").write_bytes(b"exe" * 50)
    return str(cache)


def test_artifact_roundtrip_and_fingerprint_refusal(tmp_path):
    cache = _make_cache(tmp_path)
    out = str(tmp_path / "warm.tgz")
    manifest = art.pack(out, cache_dir=cache, fingerprint="fpA",
                        jax_ver="1.2.3")
    assert manifest["files"] == 2 and manifest["fingerprint"] == "fpA"
    v = art.verify(out, fingerprint="fpA", jax_ver="1.2.3")
    assert v["verified"]["files"] == 2
    with pytest.raises(art.FingerprintMismatch) as ei:
        art.unpack(out, root=str(tmp_path / "o"), fingerprint="fpB",
                   jax_ver="1.2.3")
    assert ei.value.field == "fingerprint"
    res = art.unpack(out, root=str(tmp_path / "o"), fingerprint="fpA",
                     jax_ver="1.2.3")
    assert res["files"] == 2
    assert (Path(res["dir"]) / "sub" / "b.bin").read_bytes() \
        == b"exe" * 50


def test_artifact_jax_version_mismatch_refused_unless_forced(tmp_path):
    out = str(tmp_path / "warm.tgz")
    art.pack(out, cache_dir=_make_cache(tmp_path), fingerprint="fpA",
             jax_ver="9.9.9")
    with pytest.raises(art.FingerprintMismatch) as ei:
        art.unpack(out, root=str(tmp_path / "o"), fingerprint="fpA",
                   jax_ver="1.0.0")
    assert ei.value.field == "jax_version"
    res = art.unpack(out, root=str(tmp_path / "o"), fingerprint="fpA",
                     jax_ver="1.0.0", force_version=True)
    assert res["files"] == 2
    # force NEVER overrides the fingerprint (the SIGILL hazard)
    with pytest.raises(art.FingerprintMismatch):
        art.unpack(out, root=str(tmp_path / "o2"), fingerprint="fpB",
                   jax_ver="9.9.9", force_version=True)


def test_artifact_tamper_and_traversal_rejected(tmp_path):
    import tarfile
    out = str(tmp_path / "warm.tgz")
    art.pack(out, cache_dir=_make_cache(tmp_path), fingerprint="fpA",
             jax_ver="1")
    # corrupt a member: sha mismatch must fail verify
    evil = str(tmp_path / "evil.tgz")
    with tarfile.open(out, "r:gz") as src, \
            tarfile.open(evil, "w:gz") as dst:
        for m in src.getmembers():
            data = src.extractfile(m).read()
            if m.name.endswith("a.bin"):
                data = b"tampered" + data[8:]
            import io
            mi = tarfile.TarInfo(m.name)
            mi.size = len(data)
            dst.addfile(mi, io.BytesIO(data))
    with pytest.raises(art.ArtifactError, match="sha256"):
        art.verify(evil, fingerprint="fpA", jax_ver="1")
    for name in ("/abs", "../up", "cache/../../x"):
        with pytest.raises(art.ArtifactError):
            art._safe_member(name)
    with pytest.raises(art.ArtifactError):
        art.read_manifest(str(tmp_path / "nope.tgz"))


def test_artifact_unpack_if_configured_statuses(tmp_path):
    eng = HealthEngine()
    assert art.unpack_if_configured(_NS(warm_cache_artifact="")) is None
    missing = art.unpack_if_configured(
        _NS(warm_cache_artifact=str(tmp_path / "nope.tgz")),
        recorder=eng.recorder)
    assert missing["status"] == "missing"
    out = str(tmp_path / "warm.tgz")
    art.pack(out, cache_dir=_make_cache(tmp_path), fingerprint="other",
             jax_ver="1")
    refused = art.unpack_if_configured(
        _NS(warm_cache_artifact=out), recorder=eng.recorder)
    assert refused["status"] == "refused"
    kinds = [e["kind"] for e in eng.recorder.snapshot()]
    assert "warm_cache_refused" in kinds


def test_warm_cache_cli_exit_codes(tmp_path):
    """pack -> verify ok; mismatched unpack exits the DISTINCT code 4."""
    cache = _make_cache(tmp_path)
    out = str(tmp_path / "cli.tgz")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def run(*args):
        return subprocess.run(
            [sys.executable, str(ROOT / "tools" / "warm_cache.py"),
             *args], capture_output=True, text=True, cwd=ROOT, env=env,
            timeout=120)

    r = run("pack", "--cache-dir", cache, "--out", out, "--json")
    assert r.returncode == 0, r.stderr[-500:]
    doc = json.loads(r.stdout)
    assert doc["ok"] and doc["manifest"]["files"] == 2
    assert run("verify", out).returncode == 0
    # rewrite the manifest fingerprint so THIS host mismatches
    foreign = str(tmp_path / "foreign.tgz")
    art.pack(foreign, cache_dir=cache, fingerprint="some-other-host",
             jax_ver=doc["manifest"]["jax_version"])
    r = run("unpack", foreign, "--root", str(tmp_path / "o"), "--json")
    assert r.returncode == 4, (r.returncode, r.stderr[-500:])
    assert json.loads(r.stdout)["refused"]
    r = run("verify", foreign)
    assert r.returncode == 4
    # malformed artifact: a distinct (non-refusal) failure code
    bad = tmp_path / "bad.tgz"
    bad.write_bytes(b"not a tarball")
    assert run("verify", str(bad)).returncode == 3


# ------------------------------------------------- perf warm() unit seams

def test_wrap_step_warm_with_avals_then_real_call():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from selkies_tpu.obs import perf as perf_mod
    reg = perf_mod.PerfRegistry()
    jitted = jax.jit(lambda x: (x.astype(jnp.float32) * 2).sum())
    wrapped = perf_mod._WrappedStep("warm.step", jitted, reg)
    aval = jax.ShapeDtypeStruct((16,), jnp.int32)
    assert wrapped.warm((aval,)) is True
    assert reg.report()["count"] == 1
    x = jnp.arange(16, dtype=jnp.int32)
    assert float(wrapped(x)) == float(jitted(x))
    # the real call hit the warmed executable: still ONE analysis
    assert reg.report()["count"] == 1
    assert wrapped.warm((aval,)) is True      # idempotent


def test_wrap_step_signature_cache_is_bounded_lru():
    import numpy as np

    from selkies_tpu.obs import perf as perf_mod

    class _Jit:
        def __call__(self, x):
            return x

        def lower(self, *a):
            raise RuntimeError("force fallback entries")

    wrapped = perf_mod._WrappedStep("lru.step", _Jit(),
                                    perf_mod.PerfRegistry())
    for n in range(perf_mod._WrappedStep._CACHE_CAP + 4):
        wrapped(np.zeros((n + 1,)))
    assert len(wrapped._cache) == perf_mod._WrappedStep._CACHE_CAP


def test_perf_registry_is_bounded():
    from selkies_tpu.obs import perf as perf_mod
    reg = perf_mod.PerfRegistry(max_steps=5)
    for n in range(12):
        reg.record_analysis(f"step{n}")
    rep = reg.report()
    assert rep["count"] == 5
    names = {e["name"] for e in rep["steps"]}
    assert "step11" in names and "step0" not in names


def test_encoder_compile_fault_point_parses():
    from selkies_tpu.resilience.faults import FaultRegistry, parse_spec
    specs = parse_spec("encoder.compile:slow:delay_s=0.01")
    assert specs[0].point == "encoder.compile"
    reg = FaultRegistry()
    reg.arm(specs)
    reg.perturb("encoder.compile")     # sleeping mode: must not raise
    assert reg.fired_log


# -------------------------------------------- acceptance: cross-host cache

_WARM_SNIPPET = """
import json, sys, time
import jax
from selkies_tpu.compile_cache import enable, host_fingerprint
cache_dir = enable(jax)
from selkies_tpu.obs import monitor
monitor.attach_jax(jax)
from selkies_tpu.prewarm.lattice import Signature
from selkies_tpu.prewarm import plan
sig = Signature(48, 32, "jpeg", stripe_height=16, use_paint_over=False)
t0 = time.monotonic()
plan.warm_signature(sig)
print(json.dumps({
    "cache_dir": cache_dir, "fingerprint": host_fingerprint(),
    "seconds": round(time.monotonic() - t0, 2),
    "cache_hits": monitor.cache_hits,
    "cache_misses": monitor.cache_misses,
}))
"""


def _run_warm_subprocess(cache_root: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               JAX_CACHE_DIR=cache_root)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", _WARM_SNIPPET],
                       capture_output=True, text=True, timeout=600,
                       cwd=ROOT, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout.splitlines()[-1])


def test_warm_cache_artifact_makes_first_build_cache_hit():
    """The ISSUE 8 acceptance bar: pack on host A -> matched-fingerprint
    unpack on a fresh cache root -> the first session build in a COLD
    process is a persistent-cache hit (selkies_compile_cache_* counters
    via the PR-3 monitor), while a mismatched fingerprint is refused
    with the distinct exit code (covered in
    test_warm_cache_cli_exit_codes)."""
    with tempfile.TemporaryDirectory() as tmp:
        root_a = os.path.join(tmp, "hostA")
        root_b = os.path.join(tmp, "hostB")
        # host A pays the cold compile and populates its cache
        a = _run_warm_subprocess(root_a)
        assert os.path.isdir(a["cache_dir"])
        assert os.listdir(a["cache_dir"]), "cold warm wrote no cache"
        # pack A's cache, unpack into B's EMPTY root (same fingerprint:
        # same machine — the mismatch path is refused in the CLI test)
        artifact_path = os.path.join(tmp, "warm.tgz")
        art.pack(artifact_path, cache_dir=a["cache_dir"])
        res = art.unpack(artifact_path, root=root_b)
        assert res["files"] >= 1
        # a cold process on "host B" builds the same program: cache HIT
        b = _run_warm_subprocess(root_b)
        assert b["cache_hits"] >= 1, b
        assert b["seconds"] < max(5.0, a["seconds"] / 3), (a, b)


def test_perf_kill_switch_skips_instead_of_failing(monkeypatch):
    """SELKIES_PERF_ANALYSIS=0 disables the AOT path entirely: the
    worker must mark programs skipped (gate fails OPEN, /api/health
    stays ok) — never failed."""
    monkeypatch.setenv("SELKIES_PERF_ANALYSIS", "0")
    from selkies_tpu.prewarm import plan as _plan
    p = enumerate_lattice(Signature(640, 480, "jpeg"),
                          steps=("downscale",))
    w = PrewarmWorker(p, compiler=_plan.warm_signature)
    w.run_pending_sync()
    c = w.counts()
    assert c["skipped"] == c["lattice_size"] and c["failed"] == 0
    assert w.health_check().status == OK
    gate = PrewarmGate(w, p.rung_targets)
    assert gate.query("downscale", +1) == "warm"   # fail open


def test_artifact_garbage_tarballs_stay_in_contract(tmp_path):
    """Any unreadable/alien tarball must surface as ArtifactError (the
    boot hook's 'cold boot, not no boot' contract) — not KeyError or
    TarError leaking out of verify/unpack."""
    import tarfile as _tar
    # a valid tar that simply is not an artifact (no manifest)
    alien = tmp_path / "alien.tgz"
    (tmp_path / "x.txt").write_text("hi")
    with _tar.open(alien, "w:gz") as t:
        t.add(tmp_path / "x.txt", arcname="x.txt")
    for fn in (art.read_manifest, art.verify, art.unpack):
        with pytest.raises(art.ArtifactError):
            fn(str(alien))
    status = art.unpack_if_configured(
        _NS(warm_cache_artifact=str(alien)))
    assert status["status"] == "error"
