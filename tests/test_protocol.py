import pytest

from selkies_tpu import protocol as P


def test_h264_roundtrip():
    payload = b"\x00\x00\x00\x01\x65rest"
    buf = P.pack_h264_stripe(70000, 256, 1920, 64, payload, idr=True)
    assert buf[0] == P.OP_H264
    ftype, fid, y, w, h = P.unpack_h264_header(buf)
    assert ftype == P.FRAME_TYPE_IDR
    assert fid == 70000 % 65536  # wraps into u16 space
    assert (y, w, h) == (256, 1920, 64)
    assert buf[10:] == payload
    # matches the byte offsets the reference server itself relies on
    # (selkies.py:604-621): frame_type at byte 1, y_start at bytes 4:6.
    assert buf[1] == 0x01
    assert int.from_bytes(buf[4:6], "big") == 256


def test_jpeg_roundtrip():
    buf = P.pack_jpeg_stripe(5, 128, b"\xff\xd8jpeg")
    flags, fid, y = P.unpack_jpeg_header(buf)
    assert (flags, fid, y) == (0, 5, 128)
    assert buf[6:] == b"\xff\xd8jpeg"


def test_frame_id_distance_wraps():
    assert P.frame_id_distance(5, 65534) == 7
    assert P.frame_id_distance(100, 90) == 10
    assert P.frame_id_distance(90, 100) == 65526  # stale ack reads as huge


def test_audio_framing():
    assert P.pack_audio(b"opus", 0)[:2] == bytes((0x01, 0))
    red = P.pack_red_payload(90000, b"PRIMARY", [(1920, b"OLD")])
    framed = P.pack_audio(red, 1)
    assert framed[1] == 1
    # u32 pts, one 4-byte block header, 1-byte primary header, then blocks
    assert framed[2:6] == (90000).to_bytes(4, "big")
    hdr = int.from_bytes(framed[6:10], "big")
    assert hdr >> 31 == 1            # F bit
    assert (hdr >> 24) & 0x7F == 111  # PT
    assert (hdr >> 10) & 0x3FFF == 1920
    assert hdr & 0x3FF == 3
    assert framed[10] == 111          # primary header F=0
    assert framed[11:14] == b"OLD" and framed[14:] == b"PRIMARY"


def test_control_compression_threshold():
    small = "pong"
    assert P.maybe_compress_text(small) == "pong"
    big = "SETTINGS," + "x" * 4096
    out = P.maybe_compress_text(big)
    assert isinstance(out, bytes) and out[0] == P.OP_GZ_CONTROL
    assert P.decompress_control(out) == big


def test_bounded_gzip_inflation():
    import gzip
    bomb = gzip.compress(b"\0" * (2 * 1024 * 1024))
    assert P.inflate_gz_bounded(bomb, limit=4 * 1024 * 1024)
    with pytest.raises(ValueError):
        P.inflate_gz_bounded(bomb, limit=1024)
    with pytest.raises(ValueError):
        P.inflate_gz_bounded(bomb[:10])  # truncated
    with pytest.raises(ValueError):
        P.inflate_gz_bounded(gzip.compress(b"ok") + b"junk")  # trailing garbage


def test_malformed_headers_raise_valueerror():
    with pytest.raises(ValueError):
        P.unpack_h264_header(b"\x04\x01")
    with pytest.raises(ValueError):
        P.unpack_jpeg_header(b"\x03")
    with pytest.raises(ValueError):
        P.pack_red_payload(0, b"p", [(1 << 14, b"x")])  # ts offset overflow


def test_parse_verbs():
    v = P.parse_verb("kd,65")
    assert v.name == "kd" and v.args == "65"
    v = P.parse_verb("CLIENT_FRAME_ACK 123")
    assert v.name == "CLIENT_FRAME_ACK" and v.args == "123"
    v = P.parse_verb("SETTINGS,{\"a\": 1}")
    assert v.name == "SETTINGS" and v.args.startswith("{")
    v = P.parse_verb("START_VIDEO")
    assert v.name == "START_VIDEO" and v.args == ""
    v = P.parse_verb("m,100,200,1,0")
    assert v.arg_list == ["100", "200", "1", "0"]
