"""Session QoE plane (ISSUE 4): ACK-RTT estimator (injected clock),
the documented score formula, registry verdicts + qoe_collapse edge
triggering, bounded-cardinality metrics export, per-metric histogram
bucket overrides, the qoe trace lane, and log correlation."""

import json
import logging

import pytest

from selkies_tpu.obs import health as H
from selkies_tpu.obs import logctx, qoe
from selkies_tpu.server import metrics


# --------------------------------------------------------------- estimator
def test_ack_rtt_estimator_injected_clock():
    est = qoe.AckRttEstimator()
    t = 1000.0
    for fid in range(10):
        est.note_sent(fid, t + fid * 0.016)
    # ack frame 9 at +20ms: matched RTT, and every OLDER outstanding
    # entry retires with it (the client acks the latest displayed frame;
    # relay-dropped frames never ack and must not read as a stall)
    rtt = est.note_ack(9, t + 9 * 0.016 + 0.020)
    assert abs(rtt - 20.0) < 1e-6
    assert est.pending == 0
    assert est.oldest_pending_ms(t + 10) == 0.0
    assert abs(est.ewma_ms - 20.0) < 1e-6
    # EWMA converges toward the new level at alpha=1/8
    est.note_sent(20, t + 1.0)
    est.note_ack(20, t + 1.0 + 0.100)
    assert 20.0 < est.ewma_ms < 100.0
    p = est.percentiles()
    # nearest-rank (bench.py's convention): n=2 puts p50 on the 2nd value
    assert p["n"] == 2 and p["p50_ms"] == 100.0 and p["p99_ms"] == 100.0
    # unmatched ack: ignored
    assert est.note_ack(555, t + 2.0) is None


def test_ack_rtt_stall_floors_effective_rtt():
    est = qoe.AckRttEstimator()
    t = 0.0
    est.note_sent(1, t)
    est.note_ack(1, t + 0.005)
    est.note_sent(2, t + 0.01)
    # 4 s later frame 2 still unACKed: the EWMA says 5ms, the queue
    # says stall — effective RTT must follow the queue
    assert est.effective_rtt_ms(t + 4.01) >= 4000.0


def test_ack_rtt_ring_bounded():
    est = qoe.AckRttEstimator(ring=16)
    for fid in range(100):
        est.note_sent(fid, float(fid))
    assert est.pending == 16


def test_frame_id_wraps_uint16():
    est = qoe.AckRttEstimator()
    est.note_sent(0x1FFFF, 1.0)           # wraps to 0xFFFF
    assert est.note_ack(0xFFFF, 1.010) is not None


# ------------------------------------------------------------------- score
def test_qoe_score_formula():
    # perfect session
    assert qoe.qoe_score(60.0, 60.0, 0.0, 0.0) == 100.0
    # documented terms: fps shortfall x rtt x drops
    assert qoe.qoe_score(30.0, 60.0, 0.0, 0.0) == 50.0
    assert qoe.qoe_score(60.0, 60.0, 250.0, 0.0) == 50.0
    assert qoe.qoe_score(60.0, 60.0, 0.0, 0.5) == 50.0
    # unknown fps scores as on-target, never as zero
    assert qoe.qoe_score(None, 60.0, 0.0, 0.0) == 100.0
    # 4s ACK stall alone is a failed session
    assert qoe.qoe_score(60.0, 60.0, 4000.0, 0.0) < qoe.FAILED_SCORE


# ---------------------------------------------------------------- sessions
def _healthy_session(reg, now=0.0):
    st = reg.register("ws", "seat0", 1, raddr="10.0.0.9", now=now)
    st.video_active = True
    st.target_fps = lambda: 60.0
    st.reported_fps = 60.0
    st.relay_provider = lambda: {"sent_bytes": 1_000_000,
                                 "dropped_frames": 0,
                                 "queue_depth": 0, "queued_bytes": 0,
                                 "relays": 1, "dead": 0}
    for fid in range(30):
        st.note_sent(fid, now + fid * 0.016)
        st.note_ack(fid, now + fid * 0.016 + 0.008)
    return st


def test_session_snapshot_and_report_roundtrip():
    reg = qoe.QoERegistry()
    st = _healthy_session(reg)
    doc = reg.report(verbose=True, now=0.6)
    json.loads(json.dumps(doc))            # /api/sessions JSON contract
    assert doc["count"] == 1
    s = doc["sessions"][0]
    assert s["sid"] == 1 and s["kind"] == "ws" and s["seat"] == "seat0"
    assert s["client_fps"] == 60.0
    assert 7.0 < s["ack_rtt_ms"] < 9.0
    assert s["qoe_score"] > 90
    assert s["ack"]["n"] == 30 and 7.0 < s["ack"]["p50_ms"] < 9.0
    assert s["raddr"] == "10.0.0.9"
    # summary omits the verbose detail
    s2 = reg.report(now=0.6)["sessions"][0]
    assert "ack" not in s2 and "raddr" not in s2
    assert doc["worst_score"] == s["qoe_score"]
    reg.unregister(st)
    assert reg.report()["count"] == 0


def test_drop_rate_from_relay_counters():
    reg = qoe.QoERegistry()
    st = _healthy_session(reg)
    st.relay_provider = lambda: {"sent_bytes": 1, "dropped_frames": 15,
                                 "queue_depth": 3, "queued_bytes": 9}
    assert abs(st.drop_rate() - 0.5) < 1e-9          # 15/30 offered
    assert st.score(0.6) < 60


def test_striped_frames_count_once():
    """note_sent runs per chunk; a striped frame's chunks share one
    frame_id and must count as ONE frame (drop rate stays in chunk
    units to match the relay's per-item dropped counter)."""
    reg = qoe.QoERegistry()
    st = reg.register("ws", "seat0", 3)
    st.video_active = True
    for fid in (7, 7, 7, 8, 8, 8):         # two frames x three stripes
        st.note_sent(fid, 0.1)
    assert st.frames_sent == 2
    assert st.chunks_sent == 6
    st.relay_provider = lambda: {"dropped_frames": 3}
    assert abs(st.drop_rate() - 0.5) < 1e-9


def test_inactive_session_has_no_score():
    reg = qoe.QoERegistry()
    st = reg.register("ws", "seat0", 2)
    assert st.score(1.0) is None
    assert reg.health_check().status == H.OK


def test_qoe_health_check_degrades_fails_and_records_collapse():
    reg = qoe.QoERegistry()
    rec = H.FlightRecorder()
    reg.recorder = rec
    st = _healthy_session(reg)
    assert reg.health_check().status == H.OK
    # moderate drop rate -> degraded
    st.relay_provider = lambda: {"dropped_frames": 18, "sent_bytes": 1}
    v = reg.health_check()
    assert v.status == H.DEGRADED and "seat0#1" in v.reason
    assert not rec.snapshot()
    # heavy drops -> failed + ONE qoe_collapse incident (edge-triggered)
    st.relay_provider = lambda: {"dropped_frames": 27, "sent_bytes": 1}
    assert reg.health_check().status == H.FAILED
    assert reg.health_check().status == H.FAILED
    kinds = [e["kind"] for e in rec.snapshot()]
    assert kinds == ["qoe_collapse"]
    inc = rec.snapshot()[0]
    assert inc["seat"] == "seat0" and inc["transport"] == "ws"
    # recovery re-arms the edge
    st.relay_provider = lambda: {"dropped_frames": 0, "sent_bytes": 1}
    assert reg.health_check().status == H.OK
    st.relay_provider = lambda: {"dropped_frames": 27, "sent_bytes": 1}
    reg.health_check()
    assert [e["kind"] for e in rec.snapshot()] == ["qoe_collapse"] * 2


def test_webrtc_session_scores_from_cc_stats():
    reg = qoe.QoERegistry()
    st = reg.register("webrtc", "primary", "peer-1")
    cc = {"target_bps": 2e6, "acked_bps": 1.5e6,
          "detector_state": "normal", "loss_fraction": 0.0,
          "rtt_ms": 12.0, "in_flight": 4}
    st.cc_provider = lambda: cc
    st.target_fps = lambda: 60.0
    assert st.score(1.0) > 90
    cc = dict(cc, loss_fraction=0.5, rtt_ms=400.0)
    assert st.score(1.0) < qoe.DEGRADED_SCORE
    snap = st.snapshot(now=1.0)
    assert snap["cc"]["detector_state"] == "normal"
    assert snap["drop_rate"] == 0.5


# ------------------------------------------------------------ backpressure
def test_backpressure_windows_and_trace_lane():
    reg = qoe.QoERegistry()
    st = _healthy_session(reg)
    st.backpressure_begin(10.0)
    st.backpressure_begin(10.5)            # idempotent while open
    assert st.bp_windows == 1
    dur = st.backpressure_end(12.0)
    assert abs(dur - 2.0) < 1e-9
    assert st.backpressure_end(13.0) is None
    assert abs(st.bp_total_s - 2.0) < 1e-9
    ev = reg.trace_events()
    assert ev[0]["ph"] == "M" and ev[0]["args"]["name"] == "qoe"
    assert len(ev) == 2 and ev[1]["ph"] == "X"
    assert ev[1]["name"] == "backpressure seat0#1"
    snap = st.snapshot(now=13.0, verbose=True)
    assert snap["backpressure"]["windows"] == 1
    assert snap["backpressure"]["total_s"] == 2.0


# ----------------------------------------------------------------- metrics
def test_metrics_export_bounded_cardinality():
    metrics.clear()
    # detach the process singleton's scrape collector (hooked by any
    # earlier server test): it would clear-and-re-export the same
    # metric names at render time, wiping this registry's series
    was_hooked = qoe.registry._collector_hooked
    metrics.unregister_collector(qoe.registry._export_metrics)
    reg = qoe.QoERegistry()
    reg.configure(seat_label_cap=2)
    for i in range(4):
        st = reg.register("ws", f"seat{i}", i)
        st.video_active = True
        st.target_fps = lambda: 60.0
        st.note_sent(1, 0.0)
        st.note_ack(1, 0.010)
        st.relay_provider = lambda i=i: {"sent_bytes": 100 * (i + 1),
                                         "dropped_frames": i}
    reg._export_metrics()
    text = metrics.render_prometheus()
    # first cap sessions keep their own series...
    assert 'selkies_session_qoe_score{seat="seat0",sid="0"}' in text
    assert 'selkies_session_qoe_score{seat="seat1",sid="1"}' in text
    # ...the rest roll up into the overflow aggregate, never their own
    assert 'seat="seat2"' not in text and 'seat="seat3"' not in text
    assert ('selkies_session_sent_bytes_total{seat="_overflow",sid="_"} '
            '700.0') in text
    assert 'selkies_sessions{kind="ws"} 4.0' in text
    assert "selkies_qoe_worst_score" in text
    # departed sessions vanish on the next export (no flat-lining)
    for st in reg.sessions():
        reg.unregister(st)
    reg._export_metrics()
    text = metrics.render_prometheus()
    assert "selkies_session_qoe_score{" not in text
    if was_hooked:
        metrics.register_collector(qoe.registry._export_metrics)
    metrics.clear()


def test_histogram_bucket_override_via_describe():
    metrics.clear()
    metrics.describe("qoe_test_rtt_ms", "test rtt",
                     buckets=(0.5, 5, 500))
    metrics.observe_hist("qoe_test_rtt_ms", 0.3)
    metrics.observe_hist("qoe_test_rtt_ms", 42.0)
    text = metrics.render_prometheus()
    assert 'qoe_test_rtt_ms_bucket{le="0.5"} 1' in text
    assert 'qoe_test_rtt_ms_bucket{le="5"} 1' in text
    assert 'qoe_test_rtt_ms_bucket{le="500"} 2' in text
    assert 'qoe_test_rtt_ms_bucket{le="+Inf"} 2' in text
    assert "qoe_test_rtt_ms_count 2" in text
    # the default ladder still renders for undescribed histograms
    metrics.observe_hist("qoe_test_default_hist", 3.0)
    text = metrics.render_prometheus()
    assert 'qoe_test_default_hist_bucket{le="1"} 0' in text
    assert 'qoe_test_default_hist_bucket{le="240"} 1' in text
    metrics.clear()


def test_ack_rtt_histogram_uses_wide_ladder():
    metrics.clear()
    reg = qoe.QoERegistry()
    st = reg.register("ws", "seat0", 7)
    st.note_sent(1, 0.0)
    st.note_ack(1, 0.0008)                 # 0.8 ms
    text = metrics.render_prometheus()
    assert 'selkies_session_ack_rtt_ms_bucket{le="0.5"} 0' in text
    assert 'selkies_session_ack_rtt_ms_bucket{le="1"} 1' in text
    assert 'selkies_session_ack_rtt_ms_bucket{le="5000"} 1' in text
    metrics.clear()


def test_render_prometheus_survives_crashing_collector():
    calls = []

    def bad():
        calls.append(1)
        raise RuntimeError("boom")

    metrics.register_collector(bad)
    try:
        text = metrics.render_prometheus()
        assert calls and isinstance(text, str)
    finally:
        metrics.unregister_collector(bad)


# ------------------------------------------------------------------- logs
def test_logctx_filter_and_json_formatter():
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    log = logging.getLogger("selkies_tpu.test.qoe")
    log.propagate = False
    h = Sink()
    h.addFilter(logctx.SessionContextFilter())
    h.setFormatter(logctx.JsonFormatter())
    log.addHandler(h)
    try:
        tok = logctx.bind(7, "seat1")
        log.warning("client %d backpressured", 7)
        logctx.clear(tok)
        log.warning("no session here")
    finally:
        log.removeHandler(h)
        log.propagate = True
    doc = json.loads(records[0])
    assert doc["session"] == "7" and doc["seat"] == "seat1"
    assert doc["msg"] == "client 7 backpressured"
    assert doc["level"] == "WARNING"
    doc2 = json.loads(records[1])
    assert "session" not in doc2


def test_logctx_plain_session_tag():
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    log = logging.getLogger("selkies_tpu.test.qoe2")
    log.propagate = False
    log.setLevel(logging.INFO)
    h = Sink()
    h.addFilter(logctx.SessionContextFilter())
    h.setFormatter(logging.Formatter("%(levelname)s:%(session_tag)s "
                                     "%(message)s"))
    log.addHandler(h)
    try:
        tok = logctx.bind(3, ":0")
        log.info("hello")
        logctx.clear(tok)
        log.info("bye")
    finally:
        log.removeHandler(h)
        log.propagate = True
    assert records[0] == "INFO: [:0#3] hello"
    assert records[1] == "INFO: bye"


# ---------------------------------------------------------------- g2g plane
def _synced_session(offset_ms=500.0):
    """Session whose clock estimator learned `client = server + offset`
    from injected exchanges (server instants are plain floats here —
    nothing reads the wall clock)."""
    st = qoe.SessionStats(1, "ws", "seat0", now=0.0)
    for i in range(5):
        s = 1000.0 + i * 500.0
        st.clock.add_sample(s + offset_ms, s + 1.0, s + 1.1,
                            s + offset_ms + 2.1)
    return st


def test_note_frame_timing_requires_sync():
    st = qoe.SessionStats(1, "ws", "seat0", now=0.0)
    assert st.note_frame_timing(1, 10.0, 11.0, 12.0) is None
    assert st.g2g_percentiles()["n"] == 0


def test_note_frame_timing_maps_and_builds_g2g(monkeypatch):
    import time as _time
    st = _synced_session(offset_ms=500.0)
    # pin the send-side perf_counter read so g2g is exact
    monkeypatch.setattr(_time, "perf_counter_ns",
                        lambda: int(5000.0 * 1e6))
    st.note_sent(7, 123.0)                  # records send at 5000.0 ms
    # client saw the frame at server 5010/5012/5016 (client = s + 500)
    m = st.note_frame_timing(7, 5510.0, 5512.0, 5516.0)
    assert m is not None
    assert m["send_ms"] == 5000.0
    assert m["recv_ms"] == pytest.approx(5010.0, abs=1.0)
    assert m["present_ms"] == pytest.approx(5016.0, abs=1.0)
    assert m["g2g_ms"] == pytest.approx(16.0, abs=1.0)
    p = st.g2g_percentiles()
    assert p["n"] == 1 and p["p99_ms"] == pytest.approx(16.0, abs=1.0)
    snap = st.snapshot(now=1.0, verbose=True)
    assert snap["g2g_p99_ms"] == p["p99_ms"]
    assert snap["g2g"]["frames_timed"] == 1
    assert snap["clock"]["synced"] is True


def test_note_frame_timing_unknown_fid_has_no_g2g(monkeypatch):
    import time as _time
    st = _synced_session(offset_ms=500.0)
    monkeypatch.setattr(_time, "perf_counter_ns",
                        lambda: int(6010.0 * 1e6))   # plausibility anchor
    m = st.note_frame_timing(999, 6500.0, 6501.0, 6502.0)
    assert m is not None and m["send_ms"] is None and m["g2g_ms"] is None
    assert st.g2g_percentiles()["n"] == 0
    assert st.frames_timed == 1


def test_note_frame_timing_clamps_monotone(monkeypatch):
    """Mapping jitter must never produce a negative decode/present
    span: out-of-order client stamps clamp to monotone."""
    import time as _time
    st = _synced_session(offset_ms=500.0)
    monkeypatch.setattr(_time, "perf_counter_ns",
                        lambda: int(5520.0 * 1e6))
    m = st.note_frame_timing(1, 6010.0, 6005.0, 6000.0)
    assert m["recv_ms"] <= m["decode_ms"] <= m["present_ms"]


def test_client_stats_sanitised():
    st = qoe.SessionStats(1, "ws", "seat0", now=0.0)
    st.note_client_stats({"decode_queue": 3, "dropped_decodes": 1.0,
                          "draw_fps": 59.94, "evil": {"a": 1},
                          "huge": 1e300})
    assert st.client_stats == {"decode_queue": 3.0, "dropped_decodes": 1.0,
                               "draw_fps": 59.94}
    st.note_client_stats({"nothing": "useful"})
    assert st.client_stats["decode_queue"] == 3.0   # last good kept


def test_note_frame_timing_counts_present_before_send(monkeypatch):
    """Clock-sync bias (up to rtt/2) can map a fast frame's present
    BEFORE its send anchor. The drop must be counted, not silent —
    selectively losing the fastest frames biases p50 upward with
    nothing in /api/sessions explaining why."""
    import time as _time
    st = _synced_session(offset_ms=500.0)
    monkeypatch.setattr(_time, "perf_counter_ns",
                        lambda: int(5000.0 * 1e6))
    st.note_sent(7, 123.0)                  # send anchor at 5000.0 ms
    # client claims present at server 4995 ms — 5 ms before the send
    m = st.note_frame_timing(7, 5493.0, 5494.0, 5495.0)
    assert m is not None and m["g2g_ms"] is None
    assert st.g2g_percentiles()["n"] == 0
    assert st.timing_rejected == 1
    assert st.frames_timed == 1


def test_note_frame_timing_rejects_implausible_timestamps(monkeypatch):
    """A finite-but-absurd client timestamp passes the parser; the
    plausibility gate must drop it before it poisons percentiles, the
    shared histogram, the g2g SLO, or the trace envelope."""
    import time as _time
    st = _synced_session(offset_ms=0.0)
    now_ms = 10_000.0
    monkeypatch.setattr(_time, "perf_counter_ns",
                        lambda: int(now_ms * 1e6))
    st.note_sent(7, 0.0)
    # presented "years in the future"
    assert st.note_frame_timing(7, 9_000.0, 9_001.0, 1e11) is None
    # ...and in the distant past
    assert st.note_frame_timing(7, -1e11, -1e11, -1e11) is None
    assert st.timing_rejected == 2
    assert st.g2g_percentiles()["n"] == 0
    snap = st.snapshot(now=1.0, verbose=True)
    assert snap["g2g"]["rejected"] == 2
    # a plausible report for the same fid still lands
    m = st.note_frame_timing(7, 9_990.0, 9_995.0, 10_000.0)
    assert m is not None and st.g2g_percentiles()["n"] == 1
