"""Minimal-readback fetch (engine/readback.py, PERF.md lever 4) and the
stripe-granular fetch path (deep pipeline, ROADMAP 2)."""

import numpy as np

from selkies_tpu.engine.readback import (MIN_BUCKET, MIN_STRIPE_BUCKET,
                                         bucket_for, fetch_stream_bytes,
                                         fetch_stripe_bytes)


def test_bucket_ladder():
    assert bucket_for(0) == MIN_BUCKET
    assert bucket_for(1) == MIN_BUCKET
    assert bucket_for(MIN_BUCKET) == MIN_BUCKET
    assert bucket_for(MIN_BUCKET + 1) == 2 * MIN_BUCKET
    assert bucket_for(100_000) == 131072


def test_fetch_prefix_is_byte_identical():
    import jax.numpy as jnp
    rng = np.random.default_rng(1)
    full = rng.integers(0, 256, (4 * MIN_BUCKET,), dtype=np.uint8)
    dev = jnp.asarray(full)
    for total in (0, 1, 1000, MIN_BUCKET, MIN_BUCKET + 7,
                  3 * MIN_BUCKET, 4 * MIN_BUCKET):
        got = fetch_stream_bytes(dev, total)
        assert len(got) >= total
        assert np.array_equal(got[:total], full[:total]), total


def test_small_buffer_fetch_covers_request():
    import jax.numpy as jnp
    full = np.arange(100, dtype=np.uint8)
    got = fetch_stream_bytes(jnp.asarray(full), 50)
    # contract: AT LEAST the requested prefix, byte-identical (the host
    # path returns exactly the prefix; the device path rounds up)
    assert len(got) >= 50
    assert np.array_equal(got[:50], full[:50])


def test_fetch_stripe_arbitrary_ranges_byte_identical():
    """The stripe-streaming fetch: any (start, length) range equals the
    same slice of the full buffer — including ranges that straddle the
    bucketed device slice's clamp at the buffer tail."""
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    n = 4 * MIN_STRIPE_BUCKET
    full = rng.integers(0, 256, (n,), dtype=np.uint8)
    dev = jnp.asarray(full)
    cases = [(0, 0), (0, 1), (0, MIN_STRIPE_BUCKET), (17, 1000),
             (MIN_STRIPE_BUCKET - 1, 2), (n - 100, 100),
             (n - 1, 1), (n - MIN_STRIPE_BUCKET - 3, MIN_STRIPE_BUCKET),
             (1000, 3 * MIN_STRIPE_BUCKET)]
    for start, length in cases:
        got = fetch_stripe_bytes(dev, start, length)
        assert np.array_equal(got, full[start:start + length]), \
            (start, length)


def test_fetch_stripe_clamps_overlong_range():
    import jax.numpy as jnp
    full = np.arange(256, dtype=np.uint8)
    got = fetch_stripe_bytes(jnp.asarray(full), 200, 1000)
    assert np.array_equal(got, full[200:])


def test_fetch_stripe_seat_axis_preserved():
    """Multi-seat (S, out_cap) buffers slice along the minor axis."""
    import jax.numpy as jnp
    rng = np.random.default_rng(3)
    full = rng.integers(0, 256, (2, 2 * MIN_STRIPE_BUCKET), dtype=np.uint8)
    got = fetch_stripe_bytes(jnp.asarray(full), 123, 456)
    assert np.array_equal(got, full[:, 123:123 + 456])
