"""Minimal-readback fetch (engine/readback.py, PERF.md lever 4)."""

import numpy as np

from selkies_tpu.engine.readback import (MIN_BUCKET, bucket_for,
                                         fetch_stream_bytes)


def test_bucket_ladder():
    assert bucket_for(0) == MIN_BUCKET
    assert bucket_for(1) == MIN_BUCKET
    assert bucket_for(MIN_BUCKET) == MIN_BUCKET
    assert bucket_for(MIN_BUCKET + 1) == 2 * MIN_BUCKET
    assert bucket_for(100_000) == 131072


def test_fetch_prefix_is_byte_identical():
    import jax.numpy as jnp
    rng = np.random.default_rng(1)
    full = rng.integers(0, 256, (4 * MIN_BUCKET,), dtype=np.uint8)
    dev = jnp.asarray(full)
    for total in (0, 1, 1000, MIN_BUCKET, MIN_BUCKET + 7,
                  3 * MIN_BUCKET, 4 * MIN_BUCKET):
        got = fetch_stream_bytes(dev, total)
        assert len(got) >= total
        assert np.array_equal(got[:total], full[:total]), total


def test_small_buffer_fetches_whole():
    import jax.numpy as jnp
    full = np.arange(100, dtype=np.uint8)
    got = fetch_stream_bytes(jnp.asarray(full), 50)
    assert np.array_equal(got, full)     # buffer smaller than a bucket
