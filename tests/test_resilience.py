"""Resilience plane tests (ISSUE 5): restart-policy math, supervisor
scheduling, degradation-ladder hysteresis, fault-spec grammar, and —
through the real injection points — every fault-driven recovery path:
relay death -> supervised re-offer, capture-source raise -> supervised
restart, encoder device-error, ws-accept rejection, and the qoe-failed
-> downshift -> sustained-ok -> step-up ladder walk.

Deterministic by construction: policies and ladders take injected
clocks, the supervisor takes a manual scheduler, and the asyncio-level
recovery tests poll bounded *conditions* (never fixed wall-clock
sleeps) with millisecond backoffs configured through settings.
"""

import asyncio
import threading
import time

import pytest

from selkies_tpu import protocol as P
from selkies_tpu.obs import health as _health
from selkies_tpu.resilience import faults as _faults
from selkies_tpu.resilience.ladder import DegradationLadder
from selkies_tpu.resilience.supervisor import (BACKING_OFF, FAILED,
                                               RestartPolicy, Supervisor)
from tests.test_server import FakeCapture, make_app


@pytest.fixture(autouse=True)
def _clean_faults():
    """The process-wide fault registry must never leak between tests."""
    _faults.registry.disarm()
    old_sleep = _faults.registry.sleep
    old_sleep_async = _faults.registry.sleep_async
    yield
    _faults.registry.disarm()
    _faults.registry.sleep = old_sleep
    _faults.registry.sleep_async = old_sleep_async


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class ManualSched:
    """Deterministic supervisor scheduler: collect, fire by hand."""

    class H:
        def __init__(self, sched, entry):
            self.sched, self.entry = sched, entry

        def cancel(self):
            if self.entry in self.sched.pending:
                self.sched.pending.remove(self.entry)

    def __init__(self):
        self.pending = []

    def __call__(self, delay, cb):
        entry = (delay, cb)
        self.pending.append(entry)
        return self.H(self, entry)

    def fire(self):
        pending, self.pending = self.pending, []
        for _, cb in pending:
            cb()


async def _until(cond, timeout=10.0, interval=0.02):
    """Await a condition with a hard bound (the no-wall-clock-sleeps
    discipline: waits END as soon as the condition holds)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return cond()


# ---------------------------------------------------------------- policy

def test_backoff_ramps_and_caps():
    clk = Clock()
    p = RestartPolicy(base_backoff_s=1.0, max_backoff_s=4.0, jitter=0.0,
                      min_uptime_s=5.0, max_restarts=100, clock=clk)
    p.record_started()
    seq = []
    for i in range(4):
        clk.t += 0.1                       # consecutive fast deaths
        seq.append(p.next_backoff())
        p.record_started()
    assert seq == [1.0, 2.0, 4.0, 4.0]     # 2^n ramp, capped


def test_healthy_uptime_resets_ramp():
    clk = Clock()
    p = RestartPolicy(base_backoff_s=1.0, jitter=0.0, min_uptime_s=5.0,
                      max_restarts=100, clock=clk)
    p.record_started()
    clk.t = 0.1
    assert p.next_backoff() == 1.0
    p.record_started()
    clk.t = 0.2
    assert p.next_backoff() == 2.0
    p.record_started()
    clk.t = 20.0                           # ran healthy for ~20 s
    assert p.next_backoff() == 1.0         # ramp reset to base
    assert not p.crash_looping


def test_crash_loop_flag_and_budget():
    clk = Clock()
    p = RestartPolicy(base_backoff_s=1.0, jitter=0.0, min_uptime_s=5.0,
                      max_restarts=3, window_s=100.0, clock=clk)
    p.record_started()
    for _ in range(2):
        clk.t += 0.1
        assert p.next_backoff() is not None
        p.record_started()
    assert not p.crash_looping
    clk.t += 0.1
    assert p.next_backoff() is not None    # 3rd restart: budget edge
    assert p.crash_looping
    p.record_started()
    clk.t += 0.1
    assert p.next_backoff() is None        # 4th in window: exhausted


def test_budget_window_slides():
    clk = Clock()
    p = RestartPolicy(base_backoff_s=1.0, jitter=0.0, min_uptime_s=0.0,
                      max_restarts=2, window_s=10.0, clock=clk)
    p.record_started()
    for t in (1.0, 2.0):
        clk.t = t
        assert p.next_backoff() is not None
        p.record_started()
    clk.t = 20.0                           # old deaths aged out
    assert p.next_backoff() is not None


def test_jitter_is_seeded_and_additive():
    def run(seed):
        clk = Clock()
        p = RestartPolicy(base_backoff_s=1.0, jitter=0.5, seed=seed,
                          min_uptime_s=0.0, max_restarts=100, clock=clk)
        p.record_started()
        return [p.next_backoff() for _ in range(4)]

    a, b = run(7), run(7)
    assert a == b                          # deterministic replay
    assert all(x >= 1.0 for x in a[:1])    # jitter only adds
    assert run(7) != run(8)                # and actually varies by seed


# ------------------------------------------------------------- supervisor

def test_supervisor_restart_coalesce_giveup():
    eng = _health.HealthEngine()
    sched = ManualSched()
    clk = Clock()
    calls = {"restarts": 0, "gave_up": False}
    sup = Supervisor(recorder=eng.recorder, schedule=sched,
                     policy_factory=lambda: RestartPolicy(
                         max_restarts=1, jitter=0.0, min_uptime_s=0.0,
                         clock=clk))
    sup.adopt("c", lambda: calls.__setitem__("restarts",
                                             calls["restarts"] + 1),
              on_give_up=lambda: calls.__setitem__("gave_up", True))
    assert sup.health_check().status == _health.OK
    sup.report_death("c", "boom")
    assert sup.get("c").state == BACKING_OFF
    assert sup.health_check().status == _health.DEGRADED
    sup.report_death("c", "dup")           # pending restart: coalesced
    assert len(sched.pending) == 1
    sched.fire()
    assert calls["restarts"] == 1
    assert sup.health_check().status == _health.OK
    sup.report_death("c", "boom2")         # budget 1: exhausted
    assert calls["gave_up"]
    assert sup.get("c").state == FAILED
    assert sup.health_check().status == _health.FAILED
    kinds = [e["kind"] for e in eng.recorder.snapshot()]
    assert kinds.count("supervisor_restart") == 1
    assert "crash_loop" in kinds


def test_supervisor_drop_cancels_pending_restart():
    sched = ManualSched()
    eng = _health.HealthEngine()
    fired = []
    sup = Supervisor(recorder=eng.recorder, schedule=sched,
                     policy_factory=lambda: RestartPolicy(jitter=0.0))
    sup.adopt("gone", lambda: fired.append(1))
    sup.report_death("gone", "x")
    sup.drop("gone")
    sched.fire()
    assert not fired and sup.get("gone") is None


def test_adopt_unparks_failed_component():
    """Re-adoption (a deliberate restart: operator switch, START_VIDEO)
    must un-park a FAILED component so its next death is supervised
    again — while the sliding-window death history keeps an immediate
    re-crash from burning fresh budget."""
    clk = Clock()
    sched = ManualSched()
    eng = _health.HealthEngine()
    sup = Supervisor(recorder=eng.recorder, schedule=sched,
                     policy_factory=lambda: RestartPolicy(
                         max_restarts=1, jitter=0.0, min_uptime_s=0.0,
                         window_s=10.0, clock=clk))
    sup.adopt("svc", lambda: None)
    sup.report_death("svc", "boom")
    sched.fire()                           # one restart: budget spent
    clk.t = 1.0
    sup.report_death("svc", "boom again")  # 2nd in window: parks
    assert sup.get("svc").state == FAILED
    sup.report_death("svc", "parked: ignored")
    assert sup.get("svc").state == FAILED
    sup.adopt("svc", lambda: None)         # deliberate re-start
    assert sup.get("svc").state == "running"
    clk.t = 50.0                           # old deaths aged out
    sup.report_death("svc", "supervised again")
    assert sup.get("svc").state == BACKING_OFF


async def test_supervisor_inflight_async_restart_not_clobbered():
    """A death reported while an async restart is still in flight must
    coalesce — not drop the task's strong ref or run a second restart
    concurrently; the task's own failure callback feeds the policy."""
    sched = ManualSched()
    eng = _health.HealthEngine()
    fut = asyncio.get_running_loop().create_future()
    restarts = []

    def restart_fn():
        restarts.append(1)
        return fut

    sup = Supervisor(recorder=eng.recorder, schedule=sched,
                     policy_factory=lambda: RestartPolicy(
                         max_restarts=10, jitter=0.0, min_uptime_s=0.0))
    sup.adopt("c", restart_fn)
    sup.report_death("c", "one")
    sched.fire()
    await asyncio.sleep(0)
    comp = sup.get("c")
    assert comp._task is not None and len(restarts) == 1
    sup.report_death("c", "while restart in flight")   # coalesced
    assert not sched.pending
    fut.set_exception(RuntimeError("restart failed"))
    await asyncio.sleep(0)
    await asyncio.sleep(0)
    assert comp._task is None
    assert comp.state == BACKING_OFF       # failure fed back via callback
    assert len(sched.pending) == 1
    sup.close()


async def test_death_during_successful_restart_is_replayed():
    """A death reported while an in-flight restart is about to SUCCEED
    must be queued and replayed at completion — not swallowed (which
    would abandon a fast-crashing component with supervision ok)."""
    sched = ManualSched()
    eng = _health.HealthEngine()
    fut = asyncio.get_running_loop().create_future()
    sup = Supervisor(recorder=eng.recorder, schedule=sched,
                     policy_factory=lambda: RestartPolicy(
                         max_restarts=10, jitter=0.0, min_uptime_s=0.0))
    sup.adopt("c", lambda: fut)
    sup.report_death("c", "first")
    sched.fire()
    await asyncio.sleep(0)
    comp = sup.get("c")
    assert comp._task is not None
    # the restarted instance crashes BEFORE the restart future resolves
    sup.report_death("c", "crashed during restart")
    assert comp._pending_death == "crashed during restart"
    fut.set_result(None)                   # ...and the restart succeeds
    await asyncio.sleep(0)
    await asyncio.sleep(0)
    assert comp.state == BACKING_OFF       # queued death replayed
    assert comp._pending_death is None
    assert len(sched.pending) == 1
    sup.close()


def test_supervisor_failing_restart_feeds_policy():
    sched = ManualSched()
    eng = _health.HealthEngine()
    clk = Clock()
    sup = Supervisor(recorder=eng.recorder, schedule=sched,
                     policy_factory=lambda: RestartPolicy(
                         max_restarts=5, jitter=0.0, min_uptime_s=0.0,
                         clock=clk))

    def bad_restart():
        raise RuntimeError("still broken")

    sup.adopt("flappy", bad_restart)
    sup.report_death("flappy", "first")
    sched.fire()                           # restart raises -> new death
    assert sup.get("flappy").state == BACKING_OFF
    assert sup.get("flappy").restarts == 2
    assert "restart failed" in sup.get("flappy").last_error


# ----------------------------------------------------------------- ladder

def test_ladder_full_walk_with_injected_clock():
    eng = _health.HealthEngine()
    calls = []
    lad = DegradationLadder(down_after_s=4.0, hold_s=10.0, ok_window_s=30.0,
                            recorder=eng.recorder)
    lad.bind_controls({
        "pipeline": (lambda: calls.append("p-"),
                     lambda: calls.append("p+")),
        "fps": (lambda: calls.append("fps-"), lambda: calls.append("fps+")),
        "quality": (lambda: calls.append("q-"), lambda: calls.append("q+")),
        "downscale": (lambda: calls.append("s-"),
                      lambda: calls.append("s+")),
    })
    bad = {"qoe": _health.failed("stall")}
    ok = {"qoe": _health.ok()}
    lad.observe(bad, now=0.0)
    assert lad.level == 0                  # hysteresis: not yet
    lad.observe(bad, now=4.0)
    # rung 0 of the deep pipeline era: depth -> 1 before fidelity is cut
    assert lad.level == 1 and calls == ["p-"]
    lad.observe(bad, now=5.0)
    assert lad.level == 1                  # hold_s blocks
    lad.observe(bad, now=15.0)
    assert lad.level == 2 and calls[-1] == "fps-"
    lad.observe(bad, now=26.0)
    assert lad.level == 3 and calls[-1] == "q-"
    lad.observe(bad, now=40.0)
    assert lad.level == 4 and calls[-1] == "s-"
    lad.observe(bad, now=55.0)
    assert lad.level == 4                  # bottom rung holds
    # recovery: sustained-ok window then one rung per hold
    lad.observe(ok, now=56.0)
    lad.observe(ok, now=75.0)
    assert lad.level == 4                  # 19 s ok < 30 s window
    lad.observe(ok, now=86.5)
    assert lad.level == 3 and calls[-1] == "s+"
    lad.observe(ok, now=116.5)
    assert lad.level == 2 and calls[-1] == "q+"
    kinds = [e["kind"] for e in eng.recorder.snapshot()]
    assert kinds.count("degradation_step") == 4
    assert kinds.count("degradation_recover") == 2
    ev = lad.trace_events()
    assert ev[0]["args"]["name"] == "resilience"
    assert len(ev) == 1 + lad.transitions


def test_ladder_energy_mode_picks_efficient_warm_slo_rung():
    """ISSUE 14 contract: under an injected power budget (injected
    watts feed + clocks), the downshift lands on the HIGHEST-EFFICIENCY
    warm rung that meets the SLO — skipping both a cheaper-but-
    SLO-violating rung and a cold one — and the two-sided hysteresis
    (down_after_s / hold_s / ok_window_s) governs power-driven shifts
    exactly like verdict-driven ones."""
    from selkies_tpu.obs.energy import EnergyBudgetPolicy
    eng = _health.HealthEngine()
    watts_box = [120.0]
    policy = EnergyBudgetPolicy(100.0, lambda: watts_box[0], rung_table={
        "pipeline": {"fps_per_w": 0.2},
        "fps": {"fps_per_w": 1.0},
        # the CHEAPEST rung — but its SLO predicate says no: must skip
        "quality": {"fps_per_w": 5.0, "meets_slo": False},
        # more efficient than fps, but cold: the gate excludes it
        "downscale": {"fps_per_w": 3.0},
    })

    class Gate:
        queried = []

        def query(self, step, direction):
            self.queried.append((step, direction))
            return "cold" if step == "downscale" else "warm"

        def request(self, step, direction):
            pass

    lad = DegradationLadder(down_after_s=4.0, hold_s=10.0,
                            ok_window_s=30.0, gate=Gate(),
                            energy_policy=policy,
                            recorder=eng.recorder)
    calls = []
    lad.bind_controls({
        "pipeline": (lambda: calls.append("p-"),
                     lambda: calls.append("p+")),
        "fps": (lambda: calls.append("fps-"),
                lambda: calls.append("fps+")),
        "quality": (lambda: calls.append("q-"),
                    lambda: calls.append("q+")),
        "downscale": (lambda: calls.append("s-"),
                      lambda: calls.append("s+")),
    })
    ok = {"qoe": _health.ok()}
    lad.observe(ok, now=0.0)
    assert lad.level == 0                  # hysteresis: not yet
    lad.observe(ok, now=4.0)
    # the pick: quality (eff 5.0) violates SLO, downscale (3.0) is
    # cold, fps (1.0) beats pipeline (0.2) -> land on fps, skipping
    # the pipeline rung
    assert lad.level == 2 and calls == ["fps-"]
    steps = [e for e in eng.recorder.snapshot()
             if e["kind"] == "degradation_step"]
    assert steps[-1]["step"] == "fps"
    assert steps[-1]["skipped"] == ["pipeline"]
    assert "power=over_budget" in steps[-1]["reasons"]
    assert any(r.startswith("energy-efficient:fps")
               for r in steps[-1]["reasons"])
    lad.observe(ok, now=5.0)
    assert lad.level == 2                  # hold_s blocks further shed
    # budget clears: the sustained-ok window governs the walk back up,
    # one rung per hold — unchanged two-sided hysteresis
    watts_box[0] = 50.0
    lad.observe(ok, now=20.0)
    lad.observe(ok, now=49.0)
    assert lad.level == 2                  # 29 s ok < 30 s window
    lad.observe(ok, now=51.0)
    assert lad.level == 1 and calls[-1] == "fps+"
    lad.observe(ok, now=62.0)
    assert lad.level == 0 and calls[-1] == "p+"
    assert lad.snapshot()["energy_mode"] is True
    assert lad.snapshot()["energy"]["budget_w"] == 100.0


def test_ladder_energy_mode_inert_without_policy_or_under_budget():
    """Default ladder (no policy) and an under-budget policy both keep
    the stock nearest-rung walk — the energy seam adds no behaviour
    until the budget is actually exceeded."""
    from selkies_tpu.obs.energy import EnergyBudgetPolicy
    policy = EnergyBudgetPolicy(100.0, lambda: 10.0, rung_table={
        "downscale": {"fps_per_w": 99.0},
    })
    lad = DegradationLadder(down_after_s=0.0, hold_s=0.0,
                            energy_policy=policy,
                            recorder=_health.HealthEngine().recorder)
    bad = {"qoe": _health.failed("stall")}
    lad.observe(bad, now=0.0)
    # a verdict-driven shift with the budget NOT exceeded: nearest
    # rung (pipeline), never the policy's favourite
    assert lad.level == 1
    assert lad.snapshot()["step"] == "pipeline"


def test_ladder_ignores_qoe_degraded():
    # degraded qoe is what shedding CAUSES; only failed triggers
    lad = DegradationLadder(down_after_s=0.0, hold_s=0.0,
                            recorder=_health.HealthEngine().recorder)
    lad.observe({"qoe": _health.degraded("meh")}, now=0.0)
    lad.observe({"qoe": _health.degraded("meh")}, now=10.0)
    assert lad.level == 0
    lad.observe({"hbm_headroom": _health.degraded("hot")}, now=20.0)
    assert lad.level == 1                  # hbm degraded DOES trigger


# ----------------------------------------------------------------- faults

def test_fault_spec_grammar_round_trip():
    text = ("relay.send:stall:delay_s=0.25;capture.source:raise:"
            "after=3,count=2;ws.accept:close;"
            "encoder.dispatch:device_error:prob=0.5")
    specs = _faults.parse_spec(text)
    again = _faults.parse_spec(";".join(s.to_spec() for s in specs))
    assert [s.to_dict() for s in specs] == [s.to_dict() for s in again]
    for bad in ("bogus:raise", "relay.send:nope", "relay.send",
                "relay.send:error:count=-1", "relay.send:error:k=v"):
        with pytest.raises(ValueError):
            _faults.parse_spec(bad)


def test_fault_schedule_is_exact_and_seeded():
    reg = _faults.FaultRegistry(seed=5)
    reg.arm("encoder.dispatch:device_error:after=2,count=2")
    assert reg.pull("relay.send") is None          # other points untouched
    assert reg.pull("encoder.dispatch") is None    # hit 1: skipped
    assert reg.pull("encoder.dispatch") is None    # hit 2: skipped
    with pytest.raises(_faults.FaultError) as ei:
        reg.perturb("encoder.dispatch")            # hit 3: fires
    assert (ei.value.point, ei.value.mode) == ("encoder.dispatch",
                                               "device_error")
    with pytest.raises(_faults.FaultError):
        reg.perturb("encoder.dispatch")            # hit 4: fires (count 2)
    reg.perturb("encoder.dispatch")                # exhausted: no-op
    assert reg.remaining() == 0 and len(reg.fired_log) == 2

    draws = []
    for _ in range(2):
        r = _faults.FaultRegistry(seed=99)
        r.arm("relay.send:error:prob=0.5,count=50")
        draws.append([r.pull("relay.send") is not None for _ in range(16)])
    assert draws[0] == draws[1]


async def test_sleeping_fault_modes_use_injected_sleep():
    reg = _faults.FaultRegistry()
    slept = []
    reg.sleep = slept.append
    reg.arm("encoder.dispatch:slow:delay_s=0.25;"
            "capture.source:freeze:delay_s=1.5")
    reg.perturb("encoder.dispatch")
    reg.perturb("capture.source")
    assert slept == [0.25, 1.5]
    async_sleeps = []

    async def fake_sleep(d):
        async_sleeps.append(d)

    reg2 = _faults.FaultRegistry()
    reg2.sleep_async = fake_sleep
    reg2.arm("relay.send:stall:delay_s=0.4")
    await reg2.perturb_async("relay.send")
    assert async_sleeps == [0.4]


async def test_relay_stall_trips_send_bound_and_marks_dead():
    """The stall mode sleeps past the (injectable) send bound, so the
    relay hits exactly the wedged-TCP timeout path and dies."""
    from selkies_tpu.server.relay import VideoRelay
    sent = []

    async def send(item):
        sent.append(item)

    relay = VideoRelay(send, send_timeout_s=0.05, display="d0")
    relay.start()
    _faults.registry.arm("relay.send:stall:delay_s=30,count=1")
    relay.offer(P.pack_jpeg_stripe(1, 0, b"\xff\xd8xx\xff\xd9"))
    assert await _until(lambda: relay.dead, timeout=5.0)
    assert not sent                        # the stalled send never landed
    await relay.close()


# ------------------------------------------------- recovery: relay re-offer

async def test_relay_fault_supervised_reoffer(client_factory):
    """Injected relay send error -> relay dead -> supervisor re-offers a
    fresh relay (+ IDR) and the restarts metric increments."""
    server, svc, fake, _ = make_app(
        supervisor_backoff_base_s=0.01, supervisor_backoff_max_s=0.05)
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str()                         # MODE
    await ws.receive_str()                         # server_settings
    await ws.send_str("START_VIDEO")
    assert await _until(lambda: svc.clients
                        and next(iter(svc.clients.values())).relays)
    client = next(iter(svc.clients.values()))
    first_relay = client.relays[client.display]

    _faults.registry.arm("relay.send:error:count=1")
    fake.emit()                                    # next send dies
    assert await _until(lambda: first_relay.dead)
    # supervised re-offer: a FRESH relay object replaces the dead one
    assert await _until(
        lambda: client.relays.get(client.display) is not None
        and client.relays[client.display] is not first_relay
        and not client.relays[client.display].dead)
    comp = f"relay:{client.id}:{client.display}"
    assert server.supervisor.get(comp).restarts >= 1
    idr_before = fake.idr_requests
    assert idr_before >= 1                         # re-offer asked for IDR
    # the new relay actually carries media again
    fake.emit()
    got = False
    for _ in range(20):
        msg = await ws.receive(timeout=5)
        if msg.type.name == "BINARY" and msg.data[0] == P.OP_JPEG:
            got = True
            break
    assert got
    r = await c.get("/api/metrics")
    text = await r.text()
    assert "selkies_supervisor_restarts_total" in text
    assert f'component="{comp}"' in text
    # incident trail: relay_death AND supervisor_restart both present
    r = await c.get("/api/health?verbose=1")
    incidents = (await r.json())["incidents"]
    kinds = [e["kind"] for e in incidents]
    assert "relay_death" in kinds and "supervisor_restart" in kinds
    await ws.close()


# --------------------------------------------- recovery: capture restart

class SupervisedFakeCapture(FakeCapture):
    """FakeCapture + the restart/on_death surface ScreenCapture grew."""

    def __init__(self):
        super().__init__()
        self.on_death = None
        self.restarts = 0

    def restart(self, settings=None):
        self.restarts += 1
        self._capturing = True
        self.emit()

    def die(self, exc):
        self._capturing = False
        hook = self.on_death
        if hook is not None:
            hook(exc)


async def test_capture_death_supervised_restart_in_health(client_factory):
    server, svc, fake, _ = make_app(
        capture_cls=SupervisedFakeCapture,
        supervisor_backoff_base_s=0.01, supervisor_backoff_max_s=0.05)
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str()
    await ws.receive_str()
    await ws.send_str("START_VIDEO")
    assert await _until(lambda: fake.is_capturing())
    fake.die(RuntimeError("injected source death"))
    assert await _until(lambda: fake.restarts >= 1)
    assert fake.is_capturing()
    comp = f"capture:{svc._default_display()}"
    assert server.supervisor.get(comp).restarts >= 1
    r = await c.get("/api/health?verbose=1")
    body = await r.json()
    assert body["checks"]["supervision"]["status"] == "ok"
    restart_incidents = [e for e in body["incidents"]
                         if e["kind"] == "supervisor_restart"
                         and e.get("component") == comp]
    assert restart_incidents
    await ws.close()


# ------------------------------------------------- recovery: ws.accept

async def test_ws_accept_fault_rejects_then_recovers(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    _faults.registry.arm("ws.accept:close:count=1")
    ws = await c.ws_connect("/api/websockets")
    msg = await ws.receive(timeout=5)
    assert msg.type.name in ("CLOSE", "CLOSING", "CLOSED")
    assert not svc.clients                          # never admitted
    await ws.close()
    ws2 = await c.ws_connect("/api/websockets")     # fault exhausted
    assert (await ws2.receive_str()) == "MODE websockets"
    await ws2.close()


# --------------------------------------------------- engine-level faults

def _tiny_settings():
    from selkies_tpu.engine.types import CaptureSettings
    return CaptureSettings(capture_width=64, capture_height=64,
                           output_mode="jpeg", jpeg_quality=40,
                           target_fps=60.0, display_id=":t",
                           stripe_height=64, use_damage_gating=True,
                           use_paint_over=False)


def test_encoder_dispatch_fault_raises_before_device_work():
    from selkies_tpu.engine.encoder import JpegEncoderSession
    sess = JpegEncoderSession(_tiny_settings())
    _faults.registry.arm("encoder.dispatch:device_error:count=1")
    with pytest.raises(_faults.FaultError):
        sess.encode(None)          # fires before the frame is touched
    _faults.registry.disarm()


def test_capture_source_fault_kills_loop_and_restart_recovers():
    """The real injection point: capture.source:raise kills the real
    capture thread, on_death fires, the incident lands, and restart()
    brings frames back."""
    from selkies_tpu.engine.capture import ScreenCapture
    died = threading.Event()
    chunks = []
    cap = ScreenCapture("synthetic")
    cap.on_death = lambda exc: died.set()
    _health.engine.recorder.clear()
    _faults.registry.arm("capture.source:raise:after=1,count=1")
    cap.start_capture(chunks.append, _tiny_settings())
    # bound covers the first-frame XLA compile on a loaded 1-core box
    assert died.wait(120.0)
    # loop dead, thread exits; deliberate-stop path was NOT taken
    deadline = time.monotonic() + 10.0
    while cap.is_capturing() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not cap.is_capturing()
    kinds = [e["kind"] for e in _health.engine.recorder.snapshot()]
    assert "capture_death" in kinds and "fault_injected" in kinds
    # supervised-restart contract: restart() (the supervisor's target)
    # rebuilds the session and frames flow again
    n0 = len(chunks)
    cap.restart()
    deadline = time.monotonic() + 120.0
    while len(chunks) <= n0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert len(chunks) > n0
    cap.stop_capture()


def test_restart_after_death_closes_source_and_uses_fresh_flag(monkeypatch):
    """The supervised-restart path must not leak the dead loop's source
    (it was left open when the thread died) and must hand the new thread
    its OWN run flag (a shared Event could resurrect an abandoned one)."""
    from selkies_tpu.engine import capture as capture_mod
    sources = []

    class DyingSource:
        width = height = 64

        def __init__(self):
            self.closed = False
            sources.append(self)

        def get_frame(self, tick):
            raise RuntimeError("dead source")

        def close(self):
            self.closed = True

    monkeypatch.setattr(capture_mod, "make_source",
                        lambda *a, **k: DyingSource())
    died = threading.Event()
    cap = capture_mod.ScreenCapture("synthetic")
    cap.on_death = lambda exc: died.set()
    cap.start_capture(lambda c: None, _tiny_settings())
    flag1 = cap._running
    assert died.wait(10.0)
    died.clear()
    cap.restart()                          # the supervisor's target
    assert cap._running is not flag1       # fresh per-run flag
    assert sources[0].closed               # dead loop's source closed
    assert died.wait(10.0)                 # new loop ran (and died too)
    cap.stop_capture()
    assert sources[1].closed


def test_stop_capture_bounded_join_escalates(monkeypatch):
    """A wedged source must not hang stop/restart forever: the join
    times out, escalates (log + incident + abandoned accounting), and
    the capture object stays restartable."""
    from selkies_tpu.engine import capture as capture_mod
    gate = threading.Event()
    entered = threading.Event()

    class WedgeSource:
        width = height = 64

        def get_frame(self, tick):
            entered.set()
            gate.wait(30.0)
            raise RuntimeError("released")   # die fast once released

        def close(self):
            pass

    monkeypatch.setattr(capture_mod, "make_source",
                        lambda *a, **k: WedgeSource())
    _health.engine.recorder.clear()
    cap = capture_mod.ScreenCapture("synthetic")
    cap.join_timeout_s = 0.2
    cap.start_capture(lambda c: None, _tiny_settings())
    assert entered.wait(10.0)
    t0 = time.monotonic()
    cap.stop_capture()                       # wedged: bounded join
    assert time.monotonic() - t0 < 5.0
    assert cap.abandoned_threads == 1
    assert not cap.is_capturing()
    kinds = [e["kind"] for e in _health.engine.recorder.snapshot()]
    assert "capture_thread_wedged" in kinds
    gate.set()                               # let the leaked thread exit


# ------------------------------------------------------- switch_to_mode

async def test_overlapping_switches_serialize():
    from selkies_tpu.server.core import (BaseStreamingService,
                                         CentralizedStreamServer)
    from selkies_tpu.settings import AppSettings

    events = []

    class SlowService(BaseStreamingService):
        def __init__(self, name):
            self.name = name

        async def start(self):
            events.append(f"start:{self.name}")
            await asyncio.sleep(3600)        # long-lived service task

        async def stop(self):
            events.append(f"stop-begin:{self.name}")
            await asyncio.sleep(0)           # yield: invite interleaving
            events.append(f"stop-end:{self.name}")

    s = AppSettings.parse([], {})
    s.set_server("enable_dual_mode", True)
    server = CentralizedStreamServer(s)
    server.register_service("a", SlowService("a"))
    server.register_service("b", SlowService("b"))
    await server.switch_to_mode("a")
    await asyncio.sleep(0)
    # two overlapping switches: without the lock these interleave the
    # stop/start pairs and can strand a service
    await asyncio.gather(server.switch_to_mode("b"),
                         server.switch_to_mode("a"))
    await asyncio.sleep(0)
    assert server.active_mode in server.services
    # every stop ran to completion before the next start began
    for i, e in enumerate(events):
        if e.startswith("stop-begin:"):
            name = e.split(":")[1]
            assert events[i + 1] == f"stop-end:{name}"
    assert events[-1].startswith("start:")
    await server.shutdown()


async def test_service_death_is_supervised():
    from selkies_tpu.server.core import (BaseStreamingService,
                                         CentralizedStreamServer)
    from selkies_tpu.settings import AppSettings

    class DyingService(BaseStreamingService):
        name = "dying"

        def __init__(self):
            self.starts = 0

        async def start(self):
            self.starts += 1
            if self.starts == 1:
                raise RuntimeError("first boot dies")
            await asyncio.sleep(3600)

        async def stop(self):
            pass

    s = AppSettings.parse([], {})
    s.set_server("supervisor_backoff_base_s", 0.01)
    s.set_server("supervisor_backoff_max_s", 0.05)
    server = CentralizedStreamServer(s)
    svc = DyingService()
    server.register_service("dying", svc)
    await server.switch_to_mode("dying")
    assert await _until(lambda: svc.starts >= 2)
    assert server.active_mode == "dying"     # recovered, not cleared
    assert server.supervisor.get("service:dying").restarts == 1
    await server.shutdown()


# --------------------------------------------------------- HTTP surface

async def test_faults_api_arm_fire_disarm(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    r = await c.post("/api/faults", json={
        "action": "arm", "spec": "ws.accept:close:count=1", "seed": 3})
    assert r.status == 200
    body = await (await c.get("/api/faults")).json()
    assert body["remaining"] == 1 and body["seed"] == 3
    assert body["active"][0]["point"] == "ws.accept"
    r = await c.post("/api/faults", json={"action": "arm", "spec": "x:y"})
    assert r.status == 400
    r = await c.post("/api/faults", json={"action": "disarm"})
    assert (await r.json())["removed"] == 1
    assert (await (await c.get("/api/faults")).json())["active"] == []


async def test_faults_api_view_only_forbidden(client_factory):
    import base64
    server, svc, fake, _ = make_app(
        enable_basic_auth=True, basic_auth_user="u",
        basic_auth_password="pw", viewonly_password="vo")
    c = await client_factory(server)
    hdr = {"Authorization": "Basic " + base64.b64encode(b"u:vo").decode()}
    assert (await c.get("/api/faults", headers=hdr)).status == 403
    assert (await c.post("/api/faults", headers=hdr,
                         json={"spec": "ws.accept:close"})).status == 403
    assert (await c.get("/api/resilience", headers=hdr)).status == 403


async def test_resilience_endpoint_snapshot(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    body = await (await c.get("/api/resilience")).json()
    assert "components" in body["supervisor"]
    assert body["ladder"]["level"] == 0
    assert body["ladder"]["controls_bound"]    # ws service bound its rungs
    assert body["faults"]["active"] == []


# ------------------------------------------------------ ladder wiring

async def test_ladder_downshift_and_stepup_through_ws_controls(
        client_factory):
    """qoe-failed verdicts walk the REAL ws controls down (pipeline to
    serial first, then fps halves, then quality/bitrate shed) and a
    sustained-ok window walks them back up — driven through injected
    `now`, no wall clock."""
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ladder = server.ladder
    assert ladder is not None
    s = svc.settings
    fps0, q0, kbps0 = s.framerate, s.jpeg_quality, s.video_bitrate_kbps
    pd0 = int(s.pipeline_depth)
    assert pd0 >= 2
    bad = {"qoe": _health.failed("ack stall")}
    ok = {"qoe": _health.ok()}
    ladder.observe(bad, now=0.0)
    ladder.observe(bad, now=4.0)
    # rung 0 of the deep-pipeline era: depth drops to serial, fidelity
    # untouched
    assert ladder.level == 1 and int(s.pipeline_depth) == 1
    assert s.framerate == fps0
    ladder.observe(bad, now=15.0)
    assert ladder.level == 2 and s.framerate == fps0 // 2
    ladder.observe(bad, now=26.0)
    assert ladder.level == 3
    assert s.jpeg_quality < q0 and s.video_bitrate_kbps == kbps0 // 2
    ladder.observe(ok, now=27.0)
    ladder.observe(ok, now=57.5)
    assert ladder.level == 2 and s.jpeg_quality == q0 \
        and s.video_bitrate_kbps == kbps0
    ladder.observe(ok, now=91.0)
    assert ladder.level == 1 and s.framerate == fps0
    ladder.observe(ok, now=125.0)
    assert ladder.level == 0 and int(s.pipeline_depth) == pd0
    kinds = [e["kind"] for e in _health.engine.recorder.snapshot()]
    assert "degradation_step" in kinds and "degradation_recover" in kinds


async def test_ladder_stepup_respects_operator_changes(client_factory):
    """A setting the operator changed while degraded must NOT be
    clobbered by the ladder's step-up restore."""
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    s = svc.settings
    fps0 = int(s.framerate)
    svc._ladder_fps_down()
    assert int(s.framerate) == fps0 // 2
    s.set_server("framerate", 24)          # operator takes over
    assert svc._ladder_fps_up() is False   # restore declined
    assert int(s.framerate) == 24
    # untouched values DO restore
    q0 = int(s.jpeg_quality)
    svc._ladder_quality_down()
    svc._ladder_quality_up()
    assert int(s.jpeg_quality) == q0


async def test_ladder_fps_floor_reports_not_applied(client_factory):
    """At the fps floor the rung has nothing to shed: the transition
    still happens but the incident must record applied=False."""
    server, svc, fake, _ = make_app(framerate=15)
    c = await client_factory(server)
    assert svc._ladder_fps_down() is False
    assert svc.settings.framerate == 15    # unchanged
    ladder = server.ladder
    bad = {"qoe": _health.failed("x")}
    ladder.observe(bad, now=0.0)
    ladder.observe(bad, now=4.0)       # rung 0: pipeline (applies)
    ladder.observe(bad, now=15.0)      # rung 1: fps — at the floor
    steps = [e for e in _health.engine.recorder.snapshot()
             if e["kind"] == "degradation_step"]
    assert steps and steps[-1]["step"] == "fps"
    assert steps[-1]["applied"] is False


# --------------------------------------------------------------- taskutil

async def test_spawn_retained_logs_uncaught_exceptions(caplog):
    import logging

    from selkies_tpu.taskutil import spawn_retained

    async def boom():
        raise ValueError("kaput")

    tasks: set = set()
    with caplog.at_level(logging.ERROR, logger="selkies_tpu.taskutil"):
        t = spawn_retained(tasks, boom(), component="test-component")
        await asyncio.gather(t, return_exceptions=True)
        await asyncio.sleep(0)             # let the done-callback run
    assert not tasks
    msgs = [r.getMessage() for r in caplog.records]
    assert any("test-component" in m and "kaput" in m for m in msgs)
