"""HTTP core + WS service tests with a fake capture (protocol-level client
simulators — the test strategy SURVEY.md §4 says the reference lacks)."""

import asyncio
import base64
import json

from aiohttp import WSMsgType

from selkies_tpu import protocol as P
from selkies_tpu.engine.types import CaptureSettings, EncodedChunk
from selkies_tpu.input.backends import NullBackend
from selkies_tpu.input.handler import InputHandler
from selkies_tpu.server.core import CentralizedStreamServer
from selkies_tpu.server.ws_service import WebSocketsService
from selkies_tpu.settings import AppSettings


class FakeCapture:
    """Emits one JPEG-ish chunk per start/idr; no TPU, no threads."""

    def __init__(self):
        self._cb = None
        self._settings = None
        self._capturing = False
        self.fid = 0
        self.idr_requests = 0
        self.encoded_fps = 42.0
        self._callback = None

    def start_capture(self, cb, settings):
        self._cb = self._callback = cb
        self._settings = settings
        self._capturing = True
        self.emit()

    def stop_capture(self):
        self._capturing = False

    def is_capturing(self):
        return self._capturing

    def request_idr_frame(self):
        self.idr_requests += 1
        if self._capturing:
            self.emit()

    def update_framerate(self, fps): ...
    def update_video_bitrate(self, kbps): ...
    def update_tunables(self, **kw): ...
    def update_capture_region(self, x, y, w, h): ...
    def set_cursor_callback(self, cb): self.cursor_cb = cb

    def emit(self, n=1):
        did = self._settings.display_id if self._settings else ":0"
        for _ in range(n):
            self._cb(EncodedChunk(
                payload=b"\xff\xd8FAKEJPEG\xff\xd9", frame_id=self.fid,
                stripe_y=0, width=64, height=64, is_idr=True,
                output_mode="jpeg", display_id=did))
            self.fid += 1


def make_app(env=None, capture_cls=FakeCapture, **fields):
    s = AppSettings.parse([], env or {})
    for k, v in fields.items():
        s.set_server(k, v)
    fake = capture_cls()
    handler = InputHandler(backend=NullBackend())
    svc = WebSocketsService(s, input_handler=handler,
                            capture_factory=lambda: fake)
    server = CentralizedStreamServer(s)
    server.register_service("websockets", svc)
    return server, svc, fake, handler


async def test_status_and_health(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    r = await c.get("/api/status")
    body = await r.json()
    assert r.status == 200 and body["mode"] == "websockets"
    r = await c.get("/api/health")
    assert (await r.json())["ok"] is True


async def test_basic_auth_and_viewonly(client_factory):
    server, svc, fake, _ = make_app(
        enable_basic_auth=True, basic_auth_user="u",
        basic_auth_password="pw", viewonly_password="vo")
    c = await client_factory(server)
    assert (await c.get("/api/status")).status == 401
    hdr = {"Authorization": "Basic " + base64.b64encode(b"u:pw").decode()}
    r = await c.get("/api/status", headers=hdr)
    assert r.status == 200 and (await r.json())["role"] == "full"
    hdr = {"Authorization": "Basic " + base64.b64encode(b"u:vo").decode()}
    r = await c.get("/api/status", headers=hdr)
    assert (await r.json())["role"] == "viewonly"
    hdr = {"Authorization": "Basic " + base64.b64encode(b"u:nope").decode()}
    assert (await c.get("/api/status", headers=hdr)).status == 401


async def test_master_token_bearer(client_factory):
    server, *_ = make_app(enable_basic_auth=True, basic_auth_user="u",
                          basic_auth_password="pw", master_token="tok123")
    c = await client_factory(server)
    r = await c.get("/api/status",
                    headers={"Authorization": "Bearer tok123"})
    assert r.status == 200 and (await r.json())["role"] == "full"
    assert (await c.get(
        "/api/status", headers={"Authorization": "Bearer bad"})).status == 401


async def test_ws_handshake_and_video(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    assert (await ws.receive_str()) == "MODE websockets"
    settings_msg = await ws.receive_str()
    assert settings_msg.startswith("server_settings ")
    payload = json.loads(settings_msg.split(" ", 1)[1])
    assert payload["settings"]["framerate"]["value"] == 60
    assert payload["features"]["resize"] is True

    await ws.send_str("START_VIDEO")
    got_binary = None
    for _ in range(10):
        msg = await ws.receive(timeout=5)
        if msg.type == WSMsgType.BINARY and msg.data[0] == P.OP_JPEG:
            got_binary = msg.data
            break
        if msg.type == WSMsgType.TEXT:
            continue
    assert got_binary is not None
    flags, fid, y = P.unpack_jpeg_header(got_binary)
    assert got_binary[6:8] == b"\xff\xd8"
    await ws.close()


async def test_keyframe_request_reaches_capture(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("START_VIDEO")
    await asyncio.sleep(0.1)
    before = fake.idr_requests
    await ws.send_str("REQUEST_KEYFRAME")
    await asyncio.sleep(0.1)
    assert fake.idr_requests > before
    await ws.close()


async def test_input_verbs_reach_backend(client_factory):
    server, svc, fake, handler = make_app()
    backend = handler.backend
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("kd,65")
    await ws.send_str("m,100,200")
    await ws.send_str("mb,1,1")
    await ws.send_str("ku,65")
    await asyncio.sleep(0.2)
    assert ("key", 65, True) in backend.events
    assert ("motion", 100, 200) in backend.events
    assert ("button", 1, True) in backend.events
    assert ("key", 65, False) in backend.events
    await ws.close()


async def test_viewonly_client_cannot_inject(client_factory):
    server, svc, fake, handler = make_app(
        enable_basic_auth=True, basic_auth_user="u",
        basic_auth_password="pw", viewonly_password="vo")
    backend = handler.backend
    c = await client_factory(server)
    hdr = {"Authorization": "Basic " + base64.b64encode(b"u:vo").decode()}
    ws = await c.ws_connect("/api/websockets", headers=hdr)
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("kd,65")
    await ws.send_str("REQUEST_KEYFRAME")   # allowed for viewers
    await asyncio.sleep(0.2)
    assert ("key", 65, True) not in backend.events
    await ws.close()


async def test_second_full_client_demoted_without_collab(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws1 = await c.ws_connect("/api/websockets")
    await ws1.receive_str(); await ws1.receive_str()
    await asyncio.sleep(0.6)  # reconnect debounce window
    ws2 = await c.ws_connect("/api/websockets")
    await ws2.receive_str(); await ws2.receive_str()
    roles = sorted(cl.role for cl in svc.clients.values())
    assert roles == ["full", "viewonly"]
    await ws1.close(); await ws2.close()


async def test_settings_verb_applies_and_rejects(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str('SETTINGS,{"framerate": 30, "master_token": "evil", "video_crf": 999}')
    msg = await ws.receive_str()
    assert msg.startswith("settings_applied ")
    applied = json.loads(msg.split(" ", 1)[1])
    assert applied == {"framerate": 30}
    assert svc.settings.framerate == 30
    assert svc.settings.master_token == ""
    await ws.close()


async def test_resize_updates_geometry(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("r,2560x1440")
    msg = await ws.receive_str()
    payload = json.loads(msg.split(" ", 1)[1])
    assert payload["displays"][0]["width"] == 2560
    assert svc.display_geometry[":0"] == (2560, 1440)
    await ws.close()


async def test_upload_and_download(tmp_path, client_factory):
    server, svc, fake, _ = make_app(file_transfer_dir=str(tmp_path))
    c = await client_factory(server)
    data1, data2 = b"A" * 1000, b"B" * 500
    r = await c.post("/api/upload", data=data1, headers={
        "X-Upload-Name": "test.bin", "X-Upload-Offset": "0",
        "X-Upload-Total": str(len(data1) + len(data2))})
    assert (await r.json())["complete"] is False
    r = await c.post("/api/upload", data=data2, headers={
        "X-Upload-Name": "test.bin", "X-Upload-Offset": str(len(data1)),
        "X-Upload-Total": str(len(data1) + len(data2))})
    assert (await r.json())["complete"] is True
    assert (tmp_path / "test.bin").read_bytes() == data1 + data2
    r = await c.get("/api/files")
    assert (await r.json())["files"][0]["name"] == "test.bin"
    r = await c.get("/api/files/test.bin")
    assert await r.read() == data1 + data2


async def test_upload_unicode_filename_percent_encoded(tmp_path,
                                                       client_factory):
    """The JS client percent-encodes X-Upload-Name (headers are Latin-1
    only); the server must decode it back to the real filename."""
    import urllib.parse
    server, svc, fake, _ = make_app(file_transfer_dir=str(tmp_path))
    c = await client_factory(server)
    name = "r\u00e9sum\u00e9 \u4e2d\u6587.pdf"
    r = await c.post("/api/upload", data=b"hello", headers={
        "X-Upload-Name": urllib.parse.quote(name),
        "X-Upload-Offset": "0", "X-Upload-Total": "5"})
    assert r.status == 200, await r.text()
    assert (tmp_path / name).read_bytes() == b"hello"


async def test_upload_path_traversal_rejected(tmp_path, client_factory):
    server, *_ = make_app(file_transfer_dir=str(tmp_path))
    c = await client_factory(server)
    r = await c.post("/api/upload", data=b"x", headers={
        "X-Upload-Name": "../../etc/passwd", "X-Upload-Offset": "0"})
    assert r.status == 400


async def test_metrics_endpoint(client_factory):
    server, *_ = make_app()
    c = await client_factory(server)
    r = await c.get("/api/metrics")
    text = await r.text()
    assert r.status == 200 and "# TYPE" in text


async def test_gzip_control_roundtrip(client_factory):
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("_gz,1")
    big = {"framerate": 24, "pad": "x" * 2000}
    framed = P.maybe_compress_text("SETTINGS," + json.dumps(big))
    assert isinstance(framed, bytes)
    await ws.send_bytes(framed)
    msg = await ws.receive()
    # reply may itself be gzip'd now that the client negotiated _gz
    text = (P.decompress_control(msg.data)
            if msg.type == WSMsgType.BINARY else msg.data)
    assert "framerate" in text and svc.settings.framerate == 24
    await ws.close()


async def test_viewonly_settings_do_not_mutate_server(client_factory):
    """A view-only client sending SETTINGS must not steer the shared stream
    (round-1 verdict: viewer-authority hole)."""
    server, svc, fake, _ = make_app(
        enable_basic_auth=True, basic_auth_user="u",
        basic_auth_password="pw", viewonly_password="vo")
    c = await client_factory(server)
    hdr = {"Authorization": "Basic " + base64.b64encode(b"u:vo").decode()}
    ws = await c.ws_connect("/api/websockets", headers=hdr)
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str('SETTINGS,{"framerate": 30}')
    msg = await ws.receive_str()
    assert json.loads(msg.split(" ", 1)[1]) == {}
    assert svc.settings.framerate == 60
    await ws.close()


async def test_malformed_input_verbs_do_not_disconnect(client_factory):
    """Garbage verb args must be tolerated, not tear down the WS
    (round-1 advisor finding)."""
    server, svc, fake, handler = make_app()
    backend = handler.backend
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("kd,notanumber")
    await ws.send_str("js,b,0")          # missing fields
    await ws.send_str("m,")              # empty args
    await ws.send_str("kd,65")           # connection still alive and working
    await asyncio.sleep(0.2)
    assert ("key", 65, True) in backend.events
    assert not ws.closed
    await ws.close()


async def test_static_web_client_served(client_factory):
    server, *_ = make_app()
    server.register_static()
    c = await client_factory(server)
    r = await c.get("/")
    body = await r.text()
    assert r.status == 200 and "selkies-client.js" in body
    r = await c.get("/selkies-client.js")
    assert r.status == 200 and "SelkiesClient" in await r.text()
    # addon surfaces (reference addons/selkies-dashboard + touch gamepad)
    r = await c.get("/dashboard/")
    assert r.status == 200 and "postMessage" in await r.text()
    r = await c.get("/touch-gamepad/universalTouchGamepad.js")
    assert r.status == 200 and "getGamepads" in await r.text()


async def test_cursor_broadcast_and_late_joiner(client_factory):
    """XFixes cursor updates broadcast as cursor,{json}; late joiners get
    the current cursor at handshake."""
    import numpy as np
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    rgba = np.zeros((8, 8, 4), np.uint8); rgba[..., 3] = 255
    svc._on_cursor({"rgba": rgba, "xhot": 2, "yhot": 3, "serial": 9})
    msg = await ws.receive_str()
    assert msg.startswith("cursor,")
    body = json.loads(msg.split(",", 1)[1])
    assert body["xhot"] == 2 and body["png_b64"]
    # second client sees the cursor right after server_settings
    await asyncio.sleep(0.6)  # reconnect debounce
    ws2 = await c.ws_connect("/api/websockets")
    await ws2.receive_str(); await ws2.receive_str()
    msg2 = await ws2.receive_str()
    assert msg2.startswith("cursor,")
    await ws.close(); await ws2.close()


async def test_secure_token_mode(client_factory):
    """secure_api: WS requires a minted token; /api/tokens mints them
    (reference /api/tokens + secure-mode gate, selkies.py:4516-4550)."""
    server, svc, fake, _ = make_app(
        secure_api=True, enable_basic_auth=True,
        basic_auth_user="u", basic_auth_password="pw")
    c = await client_factory(server)
    hdr = {"Authorization": "Basic " + base64.b64encode(b"u:pw").decode()}
    # no token -> connection refused with 4401
    ws = await c.ws_connect("/api/websockets", headers=hdr)
    await ws.receive()
    assert ws.close_code == 4401
    # mint a viewonly token and use it
    r = await c.post("/api/tokens", json={"role": "viewonly"}, headers=hdr)
    assert r.status == 200
    tok = (await r.json())["token"]
    await asyncio.sleep(0.6)   # reconnect debounce
    ws = await c.ws_connect(f"/api/websockets?token={tok}", headers=hdr)
    assert (await ws.receive_str()) == "MODE websockets"
    assert [cl.role for cl in svc.clients.values()] == ["viewonly"]
    # token list is redacted
    r = await c.get("/api/tokens", headers=hdr)
    body = await r.json()
    assert body["tokens"][0]["token"].endswith("…")
    await ws.close()


async def test_stats_include_device_telemetry(client_factory):
    server, svc, fake, _ = make_app(stats_interval_s=0.2)
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    import gzip as _gz
    for _ in range(20):
        msg = await asyncio.wait_for(ws.receive(), 5)
        text = msg.data
        if msg.type == WSMsgType.BINARY and msg.data[0] == P.OP_GZ_CONTROL:
            text = _gz.decompress(msg.data[1:]).decode()
        if isinstance(text, str) and text.startswith("system_stats"):
            stats = json.loads(text.split(" ", 1)[1])
            assert "devices" in stats
            assert stats["devices"][0]["platform"] == "cpu"
            break
    else:
        raise AssertionError("no system_stats seen")
    await ws.close()


async def test_multiseat_displays_route_per_client(client_factory):
    """tpu_seats>1: displays seat0..N-1 are advertised; each client views
    its ?display= pin and receives only that seat's chunks."""
    server, svc, fake, _ = make_app(tpu_seats=2)
    c = await client_factory(server)
    ws0 = await c.ws_connect("/api/websockets?display=seat0")
    await ws0.receive_str()
    payload = json.loads((await ws0.receive_str()).split(" ", 1)[1])
    assert [d["id"] for d in payload["displays"]] == ["seat0", "seat1"]
    await asyncio.sleep(0.6)
    ws1 = await c.ws_connect("/api/websockets?display=seat1")
    await ws1.receive_str(); await ws1.receive_str()
    await ws0.send_str("START_VIDEO")
    await ws1.send_str("START_VIDEO")
    await asyncio.sleep(0.1)
    # the custom factory stands in for the sharded capture; emit per-seat
    for seat in (0, 1):
        fake._cb(EncodedChunk(
            payload=b"\xff\xd8SEAT%d\xff\xd9" % seat, frame_id=seat,
            stripe_y=0, width=64, height=64, is_idr=True,
            output_mode="jpeg", display_id=f"seat{seat}"))
    got0 = got1 = None
    for _ in range(12):
        m = await asyncio.wait_for(ws0.receive(), 3)
        if m.type == WSMsgType.BINARY and m.data[0] == P.OP_JPEG:
            got0 = m.data; break
    for _ in range(12):
        m = await asyncio.wait_for(ws1.receive(), 3)
        if m.type == WSMsgType.BINARY and m.data[0] == P.OP_JPEG:
            got1 = m.data; break
    assert got0 and b"SEAT0" in got0
    assert got1 and b"SEAT1" in got1
    await ws0.close(); await ws1.close()


async def test_lifecycle_hooks_fire(client_factory, tmp_path):
    marker = tmp_path / "connected"
    marker2 = tmp_path / "disconnected"
    server, svc, fake, _ = make_app(
        run_after_connect=f"touch {marker}",
        run_after_disconnect=f"touch {marker2}")
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    for _ in range(40):
        if marker.exists():
            break
        await asyncio.sleep(0.05)
    assert marker.exists()
    await ws.close()
    for _ in range(40):
        if marker2.exists():
            break
        await asyncio.sleep(0.05)
    assert marker2.exists()


async def test_request_clipboard_pushes_to_clients(client_factory):
    server, svc, fake, handler = make_app()
    handler.backend.clipboard = (b"remote text", "text/plain")
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("REQUEST_CLIPBOARD")
    for _ in range(10):
        msg = await asyncio.wait_for(ws.receive_str(), 5)
        if msg.startswith("clipboard,"):
            assert base64.b64decode(msg.split(",", 1)[1]) == b"remote text"
            break
    else:
        raise AssertionError("no clipboard push")
    await ws.close()


async def test_recording_tap_and_stats_csv(client_factory, tmp_path):
    rec = tmp_path / "rec.mjpeg"
    csvp = tmp_path / "stats.csv"
    server, svc, fake, _ = make_app(
        recording_path=str(rec), stats_csv_path=str(csvp),
        stats_interval_s=0.2)
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("START_VIDEO")
    await asyncio.sleep(0.3)
    fake.emit(3)
    await asyncio.sleep(0.5)
    assert rec.exists() and rec.read_bytes().startswith(b"\xff\xd8")
    assert csvp.exists()
    lines = csvp.read_text().splitlines()
    assert lines[0].startswith("ts,cpu_percent")
    assert len(lines) >= 2
    await ws.close()


async def test_computer_use_api(client_factory):
    server, svc, fake, handler = make_app(enable_computer_use=True)
    backend = handler.backend
    c = await client_factory(server)
    r = await c.post("/api/computer_use",
                     json={"action": "click", "x": 10, "y": 20, "button": 1})
    assert (await r.json())["ok"] is True
    assert ("motion", 10, 20) in backend.events
    assert ("button", 1, True) in backend.events
    r = await c.post("/api/computer_use", json={"action": "type", "text": "hi"})
    assert r.status == 200
    assert ("key", ord("h"), True) in backend.events
    r = await c.post("/api/computer_use", json={"action": "nope"})
    assert r.status == 400
    # screenshot requires an active capture with frames; FakeCapture has no
    # screenshot() -> 503 is the honest degraded answer
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("START_VIDEO")
    await asyncio.sleep(0.2)
    r = await c.get("/api/screenshot")
    assert r.status == 503
    await ws.close()


import pytest as _pytest

_JS_FILES = (
    ("selkies_tpu", "web", "selkies-client.js"),
    ("selkies_tpu", "web", "lib", "protocol.js"),
    ("selkies_tpu", "web", "lib", "keysyms.js"),
    ("selkies_tpu", "web", "lib", "audio.js"),
    ("selkies_tpu", "web", "lib", "input.js"),
    ("selkies_tpu", "web", "lib", "upload.js"),
    ("selkies_tpu", "web", "lib", "video.js"),
    ("selkies_tpu", "web", "lib", "video-worker.js"),
    ("selkies_tpu", "web", "lib", "stripe-core.js"),
    ("addons", "universal-touch-gamepad", "universalTouchGamepad.js"),
    ("addons", "selkies-dashboard", "index.html"),
)


@_pytest.mark.parametrize("parts", _JS_FILES,
                          ids=[p[-1] for p in _JS_FILES])
def test_client_js_delimiters_balanced(parts):
    """No JS engine exists in this image, so guard the shipped client and
    JS addons against gross syntax damage: with strings/comments stripped,
    every bracket must balance and nest correctly."""
    import pathlib

    path = pathlib.Path(__file__).parent.parent.joinpath(*parts)
    raw = path.read_text()
    if path.suffix == ".html":      # check only the inline script body
        raw = "".join(raw.split("<script>")[1:]).split("</script>")[0]

    # state machine: comments, '…'/"…" strings, template literals with
    # nested ${ code } (a regex can't do this — `//` inside a template
    # URL must NOT count as a comment)
    out = []
    mode = [["code", 0]]               # stack of [kind, brace_depth]
    i, n = 0, len(raw)
    while i < n:
        kind = mode[-1][0]
        c = raw[i]
        nxt = raw[i + 1] if i + 1 < n else ""
        if kind == "code":
            if c == "/" and nxt == "/":
                j = raw.find("\n", i)
                i = n if j < 0 else j
                continue
            if c == "/" and nxt == "*":
                j = raw.find("*/", i + 2)
                i = n if j < 0 else j + 2
                continue
            if c in "'\"`":
                mode.append([c, 0])
                i += 1
                continue
            if c == "{":
                mode[-1][1] += 1
            elif c == "}":
                if mode[-1][1] == 0 and len(mode) > 1:
                    mode.pop()         # end of a template ${ }
                    i += 1
                    continue
                mode[-1][1] -= 1
            out.append(c)
            i += 1
        else:                          # inside a string/template
            if c == "\\":
                i += 2
                continue
            if c == kind:
                mode.pop()
                i += 1
                continue
            if kind == "`" and c == "$" and nxt == "{":
                mode.append(["code", 0])
                i += 2
                continue
            i += 1
    src = "".join(out)

    pairs = {")": "(", "]": "[", "}": "{"}
    stack = []
    for i, ch in enumerate(src):
        if ch in "([{":
            stack.append((ch, i))
        elif ch in pairs:
            assert stack, f"unmatched {ch!r} at offset {i}"
            top, _ = stack.pop()
            assert top == pairs[ch], \
                f"mismatched {ch!r} at offset {i} (open {top!r})"
    assert not stack, f"unclosed {stack[-1]!r}"
    if parts[-1] != "selkies-client.js":
        return
    # the client features must be present somewhere in the module graph
    # (entry + lib/ modules; test_web_client.py checks the graph itself)
    web = pathlib.Path(__file__).parent.parent / "selkies_tpu" / "web"
    corpus = "".join(p.read_text() for p in sorted(web.rglob("*.js")))
    for needle in ("js,c,", "js,b,", "js,a,", "getGamepads",
                   "X-Upload-Name", "touchstart",
                   # RTC transport path (server ICE-lite offer -> answer)
                   "RTCPeerConnection", "HELLO client", "SESSION server",
                   "createDataChannel", "setRemoteDescription",
                   # worker-decode / track-generator rendering path
                   "MediaStreamTrackGenerator", "VideoTrackGenerator",
                   "transferControlToOffscreen"):
        assert needle in corpus, needle


def test_gpu_stats_drm_sysfs_chain(tmp_path):
    """The DRM sysfs backfill reports AMD gauges and skips devices the
    NVML/nvidia-smi stages already covered (reference gpu_stats.py
    chain; neither NVIDIA path exists in this image, so sysfs is the
    live stage)."""
    from selkies_tpu.server import gpu_stats as G

    # fake /sys/class/drm with one amdgpu card and one intel card
    card0 = tmp_path / "card0" / "device"
    card0.mkdir(parents=True)
    (card0 / "vendor").write_text("0x1002\n")
    (card0 / "gpu_busy_percent").write_text("37\n")
    (card0 / "mem_info_vram_used").write_text(str(512 * 2**20))
    (card0 / "mem_info_vram_total").write_text(str(8192 * 2**20))
    card1 = tmp_path / "card1" / "device"
    card1.mkdir(parents=True)
    (card1 / "vendor").write_text("0x8086\n")
    # connector nodes (card0-DP-1) must be ignored
    (tmp_path / "card0-DP-1").mkdir()

    gpus = G.get_gpus(drm_root=str(tmp_path))
    assert len(gpus) == 2
    amd = next(g for g in gpus if g.vendor == "amd")
    assert amd.load_percent == 37.0
    assert amd.memory_used_mb == 512.0
    assert amd.memory_total_mb == 8192.0
    assert amd.source == "drm-sysfs"
    intel = next(g for g in gpus if g.vendor == "intel")
    assert intel.load_percent is None
    payload = G.gpu_stats_payload(drm_root=str(tmp_path))
    assert isinstance(payload, list) and payload[0]["vendor"] in ("amd",
                                                                  "intel")


async def test_file_transfer_role_and_direction_gating(tmp_path,
                                                       client_factory):
    """VERDICT r3 weak 7: downloads must be role-gated like uploads, and
    the reference's file_transfers direction list must be honoured
    (reference stream_server.py:980,1171)."""
    (tmp_path / "f.bin").write_bytes(b"secret")
    server, *_ = make_app(
        enable_basic_auth=True, basic_auth_user="u",
        basic_auth_password="pw", viewonly_password="vo",
        file_transfer_dir=str(tmp_path))
    c = await client_factory(server)
    full = {"Authorization": "Basic " + base64.b64encode(b"u:pw").decode()}
    vo = {"Authorization": "Basic " + base64.b64encode(b"u:vo").decode()}
    # full role: default directions allow both
    assert (await c.get("/api/files", headers=full)).status == 200
    assert (await c.get("/api/files/f.bin", headers=full)).status == 200
    # view-only: 403 on index, download AND upload by default
    assert (await c.get("/api/files", headers=vo)).status == 403
    assert (await c.get("/api/files/f.bin", headers=vo)).status == 403
    r = await c.post("/api/upload", data=b"x", headers={
        **vo, "X-Upload-Name": "x.bin", "X-Upload-Offset": "0",
        "X-Upload-Total": "1"})
    assert r.status == 403
    # ...unless explicitly opened to the view-only role
    server2, *_ = make_app(
        enable_basic_auth=True, basic_auth_user="u",
        basic_auth_password="pw", viewonly_password="vo",
        file_transfer_dir=str(tmp_path), viewonly_file_transfers="download")
    c2 = await client_factory(server2)
    assert (await c2.get("/api/files/f.bin", headers=vo)).status == 200
    # direction list: upload-only server denies downloads for everyone
    server3, *_ = make_app(file_transfer_dir=str(tmp_path),
                           file_transfers="upload")
    c3 = await client_factory(server3)
    assert (await c3.get("/api/files", )).status == 403
    assert (await c3.get("/api/files/f.bin")).status == 403


async def test_keyframe_targets_requesting_display_only(client_factory):
    """REQUEST_KEYFRAME (and the fresh-join IDR) must hit only the
    requesting client's display, not storm every capture (VERDICT r3
    weak 7)."""
    s = AppSettings.parse([], {})
    fakes = []

    def factory():
        f = FakeCapture()
        fakes.append(f)
        return f

    handler = InputHandler(backend=NullBackend())
    svc = WebSocketsService(s, input_handler=handler,
                            capture_factory=factory)
    server = CentralizedStreamServer(s)
    server.register_service("websockets", svc)
    c = await client_factory(server)

    ws1 = await c.ws_connect("/api/websockets")
    await ws1.receive_str(); await ws1.receive_str()
    await ws1.send_str("START_VIDEO")
    await asyncio.sleep(0.1)
    ws2 = await c.ws_connect("/api/websockets?display=display2")
    await ws2.receive_str(); await ws2.receive_str()
    await ws2.send_str("START_VIDEO")
    await asyncio.sleep(0.2)
    assert len(fakes) == 2
    base = [f.idr_requests for f in fakes]
    await ws2.send_str("REQUEST_KEYFRAME")
    await asyncio.sleep(0.2)
    assert fakes[1].idr_requests > base[1], "target display must IDR"
    assert fakes[0].idr_requests == base[0], \
        "other display must NOT be IDR-stormed"
    await ws1.close()
    await ws2.close()


async def test_mic_disabled_notice_once(client_factory):
    """0x02 frames with the mic disabled get ONE MICROPHONE_DISABLED
    (reference parity) so the client UI can stop capturing."""
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive()                      # MODE
    await ws.receive()                      # server_settings
    await ws.send_bytes(b"\x02" + b"\x00" * 32)
    await ws.send_bytes(b"\x02" + b"\x00" * 32)
    got = []
    try:
        while True:
            msg = await asyncio.wait_for(ws.receive(), timeout=1.5)
            if msg.type == WSMsgType.TEXT and "MICROPHONE" in msg.data:
                got.append(msg.data)
    except asyncio.TimeoutError:
        pass
    assert got == ["MICROPHONE_DISABLED"]


async def test_window_manager_swap_safelisted(client_factory, tmp_path,
                                              monkeypatch):
    """SETTINGS window_manager execs only safelisted WMs (a client-
    writable exec must never run arbitrary binaries)."""
    import os as _os
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    log = tmp_path / "wm.log"
    s = bin_dir / "openbox"
    s.write_text(f"#!/bin/sh\necho \"$@\" > {log}\n")
    s.chmod(0o755)
    evil = bin_dir / "evilbin"
    evil.write_text(f"#!/bin/sh\necho evil > {log}.evil\n")
    evil.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bin_dir}:{_os.environ['PATH']}")

    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive(); await ws.receive()
    await ws.send_str('SETTINGS,{"window_manager": "evilbin"}')
    await ws.send_str('SETTINGS,{"window_manager": "openbox"}')
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline and not log.exists():
        await asyncio.sleep(0.05)
    assert log.exists() and "--replace" in log.read_text()
    assert not (tmp_path / "wm.log.evil").exists()


async def test_rtc_config_file_pushes_to_clients(client_factory, tmp_path):
    """rtc_config_file edits reach connected clients as an rtc_config
    push (reference RTCConfigFileMonitor end-to-end)."""
    import os as _os
    path = tmp_path / "rtc.json"
    server, svc, fake, _ = make_app(rtc_config_file=str(path))
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive(); await ws.receive()
    # monitor polls at 1 s; write after connect so the push targets us
    path.write_text(json.dumps({"iceServers": [{"urls": ["stun:x"]}]}))
    _os.chmod(path, 0o600)
    got = None
    deadline = asyncio.get_event_loop().time() + 6
    while asyncio.get_event_loop().time() < deadline:
        try:
            msg = await asyncio.wait_for(ws.receive(), timeout=2)
        except asyncio.TimeoutError:
            continue
        if msg.type == WSMsgType.TEXT and msg.data.startswith("rtc_config"):
            got = msg.data
            break
    assert got is not None
    cfg = json.loads(got.split(",", 1)[1])
    assert cfg["iceServers"][0]["urls"] == ["stun:x"]
    await ws.close()


async def test_cold_start_system_msg(client_factory):
    """Starting a capture pushes a 'preparing encoder' system_msg so a
    minutes-long first compile isn't a silent black screen (VERDICT r3
    weak 4)."""
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive(); await ws.receive()
    await ws.send_str("START_VIDEO")
    got = None
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline and got is None:
        try:
            msg = await asyncio.wait_for(ws.receive(), timeout=2)
        except asyncio.TimeoutError:
            continue
        if msg.type == WSMsgType.TEXT and msg.data.startswith("system_msg"):
            got = msg.data
    assert got is not None and "preparing encoder" in got
    await ws.close()


def test_prometheus_label_escaping():
    """Satellite (ISSUE 2): '"' and '\\' (and newlines) in label values
    must be escaped per the Prometheus text exposition spec, or the
    /api/metrics output is unparseable."""
    from selkies_tpu.server import metrics
    metrics.clear()
    metrics.set_gauge("esc_test_gauge", 1.0,
                      {"path": 'C:\\tmp "quoted"\nnext'})
    text = metrics.render_prometheus()
    assert ('esc_test_gauge{path="C:\\\\tmp \\"quoted\\"\\nnext"} 1.0'
            in text)
    # escaped output stays one physical line per sample
    sample = [ln for ln in text.splitlines() if "esc_test_gauge{" in ln]
    assert len(sample) == 1
    metrics.clear()


async def test_relay_death_metrics():
    """Satellite (ISSUE 2): relay death must be visible at /api/metrics
    (counter + alive gauge), not only as a bench fallback string."""
    from selkies_tpu.server import metrics
    from selkies_tpu.server.relay import VideoRelay

    def _gauge(text, name):
        for ln in text.splitlines():
            if ln.startswith(name + " ") or ln.startswith(name + "{"):
                return float(ln.rsplit(" ", 1)[1])
        return None

    def _counter(text):
        return _gauge(text, "selkies_relay_deaths_total") or 0.0

    async def _failing_send(data):
        raise ConnectionError("peer gone")

    deaths_before = _counter(metrics.render_prometheus())
    relay = VideoRelay(_failing_send, display=":0")
    relay.start()
    alive_started = _gauge(metrics.render_prometheus(),
                           "selkies_relay_alive")
    relay.offer(P.pack_jpeg_stripe(1, 0, b"\xff\xd8payload\xff\xd9"))
    for _ in range(50):
        await asyncio.sleep(0.01)
        if relay.dead:
            break
    assert relay.dead
    text = metrics.render_prometheus()
    assert _counter(text) == deaths_before + 1
    assert _gauge(text, "selkies_relay_alive") == alive_started - 1
    # a second death verdict on the same relay (control path + sender
    # task can both conclude it) must not double-count
    relay.mark_dead()
    assert _counter(metrics.render_prometheus()) == deaths_before + 1
    # close() of an already-dead relay must not double-release
    await relay.close()
    assert _gauge(metrics.render_prometheus(),
                  "selkies_relay_alive") == alive_started - 1


async def test_relay_clean_close_is_not_a_death():
    from selkies_tpu.server import metrics
    from selkies_tpu.server.relay import VideoRelay

    def _counter(text):
        for ln in text.splitlines():
            if ln.startswith("selkies_relay_deaths_total "):
                return float(ln.rsplit(" ", 1)[1])
        return 0.0

    sent = []

    async def _send(data):
        sent.append(data)

    before = _counter(metrics.render_prometheus())
    relay = VideoRelay(_send, display=":0")
    relay.start()
    relay.offer(P.pack_jpeg_stripe(2, 0, b"\xff\xd8ok\xff\xd9"))
    await asyncio.sleep(0.05)
    await relay.close()
    assert sent
    assert _counter(metrics.render_prometheus()) == before


async def test_sessions_endpoint_live_stats(client_factory):
    """ISSUE 4 acceptance: GET /api/sessions returns live per-session
    ACK RTT, client fps, and drop counts for a streaming WS client."""
    import time as _time

    from selkies_tpu.obs import qoe as _qoe
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("START_VIDEO")
    got = None
    for _ in range(10):
        msg = await asyncio.wait_for(ws.receive(), 5)
        if msg.type == WSMsgType.BINARY and msg.data[0] == P.OP_JPEG:
            got = msg.data
            break
    assert got is not None
    _, fid, _ = P.unpack_jpeg_header(got)
    await ws.send_str(f"CLIENT_FRAME_ACK,{fid}")
    await ws.send_str("_f,58.5")
    await asyncio.sleep(0.2)

    r = await c.get("/api/sessions")
    assert r.status == 200
    doc = await r.json()
    assert doc["count"] == 1
    s = doc["sessions"][0]
    assert s["kind"] == "ws" and s["seat"] == ":0"
    assert s["video_active"] is True
    assert s["frames_sent"] >= 1
    assert s["client_fps"] == 58.5
    assert s["ack_rtt_ms"] >= 0.0
    assert s["dropped_frames"] == 0 and s["drop_rate"] == 0.0
    assert s["qoe_score"] is not None and s["qoe_score"] > 50

    r = await c.get("/api/sessions?verbose=1")
    v = (await r.json())["sessions"][0]
    assert v["ack"]["acked"] >= 1 and v["ack"]["p50_ms"] is not None
    assert v["relay"]["sent_bytes"] > 0
    assert "backpressure" in v and v["raddr"]

    # the session disappears from the registry on disconnect
    await ws.close()
    await asyncio.sleep(0.2)
    assert all(st.kind != "ws"
               for st in _qoe.registry.sessions()), "session leaked"
    _ = _time  # silence unused in case of skip paths


async def test_sessions_endpoint_role_gated(client_factory):
    server, *_ = make_app(
        enable_basic_auth=True, basic_auth_user="u",
        basic_auth_password="pw", viewonly_password="vo")
    c = await client_factory(server)
    vo = {"Authorization": "Basic " + base64.b64encode(b"u:vo").decode()}
    assert (await c.get("/api/sessions", headers=vo)).status == 403
    full = {"Authorization": "Basic " + base64.b64encode(b"u:pw").decode()}
    assert (await c.get("/api/sessions", headers=full)).status == 200


async def test_stalled_client_fails_qoe_check_and_records_collapse(
        client_factory):
    """ISSUE 4 acceptance: a stalled client (frames sent, never ACKed)
    drives the qoe health check to failed and a qoe_collapse incident
    into the flight recorder."""
    import time as _time

    from selkies_tpu.obs import health as _health
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("START_VIDEO")
    await asyncio.sleep(0.2)
    client = next(iter(svc.clients.values()))
    assert client.qoe is not None and client.qoe.frames_sent >= 1
    # simulate the stall: a frame sent 10 s ago with no ACK since
    client.qoe.note_sent(4242, _time.monotonic() - 10.0)
    before = [e["kind"] for e in _health.engine.recorder.snapshot()]
    r = await c.get("/api/health?verbose=1")
    body = await r.json()
    assert body["checks"]["qoe"]["status"] == "failed"
    assert "qoe" in body["failing"] and body["ready"] is False
    kinds = [e["kind"] for e in body["incidents"]]
    assert kinds.count("qoe_collapse") == before.count("qoe_collapse") + 1
    await ws.close()


async def test_relay_sent_and_dropped_metrics_per_display():
    """Satellite (ISSUE 4): FrameRelay sent_bytes/dropped_frames reach
    /api/metrics as per-display counters, not just the debug
    snapshot."""
    from selkies_tpu.server import metrics
    from selkies_tpu.server.relay import VideoRelay

    def _counter(text, name, display):
        needle = f'{name}{{display="{display}"}} '
        for ln in text.splitlines():
            if ln.startswith(needle):
                return float(ln.rsplit(" ", 1)[1])
        return 0.0

    gate = asyncio.Event()

    async def _send(data):
        await gate.wait()

    text0 = metrics.render_prometheus()
    sent0 = _counter(text0, "selkies_relay_sent_bytes_total", ":qoet")
    drop0 = _counter(text0, "selkies_relay_dropped_frames_total", ":qoet")
    relay = VideoRelay(_send, display=":qoet")
    relay.start()
    big = P.pack_jpeg_stripe(1, 0, b"\xff\xd8" + b"x" * (3 << 20))
    relay.offer(big)                      # picked up by the blocked sender
    await asyncio.sleep(0.05)
    relay.offer(big)                      # queued: 3 MiB
    relay.offer(big)                      # 6 MiB > 4 MiB floor -> drop
    assert relay.dropped_frames == 1
    gate.set()
    for _ in range(100):
        await asyncio.sleep(0.01)
        if relay.sent_bytes >= 2 * len(big):
            break
    await relay.close()
    text = metrics.render_prometheus()
    assert _counter(text, "selkies_relay_sent_bytes_total", ":qoet") \
        == sent0 + 2 * len(big)
    assert _counter(text, "selkies_relay_dropped_frames_total", ":qoet") \
        == drop0 + 1


async def test_trace_endpoint_carries_qoe_lane(client_factory):
    """Backpressure windows overlay the /api/trace timeline as a qoe
    lane (the PR-2 Perfetto view shows WHEN a seat was paused)."""
    from selkies_tpu.obs import qoe as _qoe
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("START_VIDEO")
    await asyncio.sleep(0.2)
    client = next(iter(svc.clients.values()))
    import time as _time
    client.qoe.backpressure_begin(_time.monotonic() - 0.5)
    client.qoe.backpressure_end(_time.monotonic())
    r = await c.get("/api/trace")
    doc = await r.json()
    lanes = [e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "thread_name"]
    assert "qoe" in lanes
    assert any(e.get("ph") == "X"
               and str(e.get("name", "")).startswith("backpressure")
               for e in doc["traceEvents"])
    _ = _qoe
    await ws.close()


async def test_perf_endpoint_reports_steps_and_occupancy(client_factory):
    """GET /api/perf (ISSUE 6): static step cost table + occupancy over
    the live trace ring, JSON-round-trippable; ?profile=1 is full-role
    gated and answers null with no capture on disk."""
    from selkies_tpu.obs import perf as _perf
    from selkies_tpu.trace import tracer
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    _perf.registry.record_analysis(
        "h264.i_step[srvtest]",
        cost=[{"flops": 1e6, "bytes accessed": 8e6}],
        memory={"argument_size_in_bytes": 1, "output_size_in_bytes": 2,
                "temp_size_in_bytes": 3}, backend="cpu")
    tracer.enable(capacity=16)
    try:
        tl = tracer.frame_begin(":perft")
        tracer.bind(tl, 5)
        with tracer.span("packetize", tl):
            await asyncio.sleep(0.002)
        tracer.frame_end(":perft", 5)
        r = await c.get("/api/perf")
        assert r.status == 200
        doc = await r.json()
        names = [s["name"] for s in doc["perf"]["steps"]]
        assert "h264.i_step[srvtest]" in names
        step = doc["perf"]["steps"][names.index("h264.i_step[srvtest]")]
        assert step["roofline_ms"] == 0.01          # 8e6 B @ 800 GB/s
        assert doc["occupancy"]["frames"] >= 1
        assert "packetize" in doc["occupancy"]["critical_path"]
        assert doc["tracing"] is True
        # an earlier test's jax.profiler capture (test_obs' on-demand
        # profile round-trip) leaves the module-global last_trace_dir
        # set — this assertion is about the NO-capture answer, so
        # isolate it from suite ordering
        from selkies_tpu.obs.profiler import profiler as _prof_session
        saved_dir, _prof_session.last_trace_dir = \
            _prof_session.last_trace_dir, None
        try:
            r = await c.get("/api/perf?profile=1")
            assert r.status == 200
            assert (await r.json())["profile"] is None  # no capture yet
        finally:
            _prof_session.last_trace_dir = saved_dir
    finally:
        tracer.disable()
        tracer.clear()
        _perf.registry.clear()


async def test_relay_send_span_attaches_to_frame_timeline():
    """The ws.send stage lands on the frame's trace timeline by id."""
    from selkies_tpu.server.relay import VideoRelay
    from selkies_tpu.trace import tracer

    async def _send(data):
        await asyncio.sleep(0)

    tracer.enable(capacity=16)
    try:
        tl = tracer.frame_begin(":0")
        tracer.bind(tl, 42)
        relay = VideoRelay(_send, display=":0")
        relay.start()
        relay.offer(P.pack_jpeg_stripe(42, 0, b"\xff\xd8x\xff\xd9"))
        for _ in range(50):
            await asyncio.sleep(0.01)
            if any(s[0] == "ws.send" for s in tl.spans):
                break
        await relay.close()
        assert any(s[0] == "ws.send" for s in tl.spans)
    finally:
        tracer.disable()
        tracer.clear()


# --------------------------------------------------------------------------
# Glass-to-glass plane (ISSUE 7): clock exchange, frame-timing join, SLO
# surface, and the malformed-command hardening.
# --------------------------------------------------------------------------

def _pc_ms():
    import time as _time
    return _time.perf_counter_ns() / 1e6


async def _sync_clock(ws, n=3):
    """Run n CLIENT_CLOCK exchanges; the test process IS the client, so
    its 'client clock' is the server's perf_counter (offset ~0) and
    mapped timestamps can be asserted against perf_counter directly."""
    for i in range(n):
        await ws.send_str(f"CLIENT_CLOCK ping,{i},{_pc_ms():.3f}")
        reply = await asyncio.wait_for(ws.receive_str(), 5)
        assert reply.startswith("server_clock ")
        await ws.send_str(
            f"CLIENT_CLOCK sample,{reply.split(' ', 1)[1]},{_pc_ms():.3f}")
    await asyncio.sleep(0.05)


async def test_clock_sync_exchange_and_sessions_export(client_factory):
    """CLIENT_CLOCK ping -> server_clock reply -> sample feeds the
    session's estimator; quality lands in /api/sessions?verbose=1."""
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await _sync_clock(ws)
    r = await c.get("/api/sessions?verbose=1")
    v = (await r.json())["sessions"][0]
    assert v["clock"]["synced"] is True
    assert v["clock"]["samples"] == 3
    # same process, same physical clock: offset must read ~0
    assert abs(v["clock"]["offset_ms"]) < 50.0
    assert v["clock"]["rtt_min_ms"] is not None
    await ws.close()


async def test_frame_timing_joins_g2g_trace_and_slo(client_factory):
    """The tentpole round-trip: a timed frame becomes a per-session g2g
    sample, client-lane spans on /api/trace (with the frame envelope
    extended past ws.send), and a g2g SLO event."""
    from selkies_tpu.obs import slo as _slo
    from selkies_tpu.trace import tracer
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await _sync_clock(ws)
    g2g_before = _slo.engine.get("g2g").good_total

    tracer.enable(capacity=32)
    try:
        await ws.send_str("START_VIDEO")
        got = None
        for _ in range(10):
            msg = await asyncio.wait_for(ws.receive(), 5)
            if msg.type == WSMsgType.BINARY and msg.data[0] == P.OP_JPEG:
                got = msg.data
                break
        assert got is not None
        _, fid, _ = P.unpack_jpeg_header(got)
        recv = _pc_ms()
        # the fake capture emitted before tracing was on for this frame;
        # give the frame a closed timeline the client spans can join
        tl = tracer.frame_begin(":0")
        tracer.bind(tl, fid)
        tracer.frame_end(":0", fid)
        t1_closed = tl.t1_ns
        await ws.send_str(
            f"CLIENT_FRAME_TIMING {fid}:{recv:.2f}:{recv + 2.5:.2f}:"
            f"{recv + 4.0:.2f}")
        await asyncio.sleep(0.1)

        # g2g sample in the session snapshot
        r = await c.get("/api/sessions?verbose=1")
        v = (await r.json())["sessions"][0]
        assert v["g2g"]["n"] == 1 and v["g2g"]["p99_ms"] > 0
        assert v["g2g_p99_ms"] == v["g2g"]["p99_ms"]

        # client lane on the trace doc, envelope extended to present
        r = await c.get("/api/trace")
        doc = await r.json()
        lanes = [e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "thread_name"]
        assert "client" in lanes
        names = {e["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        assert {"net", "client.decode", "client.present"} <= names
        assert tl.t1_ns > t1_closed, "frame envelope must extend to present"

        # one g2g SLO event recorded (well under the 250 ms budget)
        assert _slo.engine.get("g2g").good_total == g2g_before + 1
        r = await c.get("/api/slo")
        slo_doc = await r.json()
        assert slo_doc["status"] == "ok"
        assert {d["name"] for d in slo_doc["slos"]} == {"fps", "g2g", "qoe"}
    finally:
        tracer.disable()
        tracer.clear()
    await ws.close()


async def test_slo_feed_skips_idle_sessions(client_factory):
    """Damage gating means a static desktop legitimately delivers no
    frames; an fps/qoe bad event per tick for such a session would burn
    the error budget — and page — on a perfectly healthy system."""
    import time as _time

    from selkies_tpu.obs import slo as _slo
    server, svc, fake, _ = make_app(stats_interval_s=0.1)
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str("START_VIDEO")
    for _ in range(10):
        msg = await asyncio.wait_for(ws.receive(), 5)
        if msg.type == WSMsgType.BINARY and msg.data[0] == P.OP_JPEG:
            break
    await ws.send_str("_f,1.0")            # a terrible client fps
    await asyncio.sleep(0.05)
    cc = next(iter(svc.clients.values()))
    fps_slo = _slo.engine.get("fps")
    # age the delivery stamp past the idle horizon: nobody is painting
    cc.qoe.last_send_mono = _time.monotonic() - 10.0
    before = (fps_slo.good_total, fps_slo.bad_total)
    await asyncio.sleep(0.35)              # >= 2 stats ticks
    assert (fps_slo.good_total, fps_slo.bad_total) == before
    # fresh delivery re-enables the feed (and records the bad fps)
    fake.emit()
    await asyncio.sleep(0.35)
    assert fps_slo.bad_total > before[1]
    await ws.close()


async def test_api_slo_flips_under_g2g_regression(client_factory):
    """ISSUE 7 acceptance: the burn-rate verdict flips failed under an
    injected g2g regression — injected event stamps, zero sleeps."""
    import time as _time

    from selkies_tpu.obs import slo as _slo
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    r = await c.get("/api/slo")
    doc = await r.json()
    assert doc["status"] == "ok"
    g2g = next(d for d in doc["slos"] if d["name"] == "g2g")
    assert g2g["burn_fast"] is None        # no events yet

    now = _time.monotonic()
    _slo.engine.record("g2g", good=True, n=50, now=now - 10.0)
    _slo.engine.record("g2g", good=False, n=450, now=now)
    r = await c.get("/api/slo")
    doc = await r.json()
    assert doc["status"] == "failed"
    g2g = next(d for d in doc["slos"] if d["name"] == "g2g")
    assert g2g["status"] == "failed"
    assert g2g["burn_fast"] > g2g["burn_threshold"]
    assert g2g["budget_remaining"] == 0.0
    # the slo health check carries the verdict + a slo_burn incident
    r = await c.get("/api/health?verbose=1")
    body = await r.json()
    assert body["checks"]["slo"]["status"] == "failed"
    assert "g2g" in body["checks"]["slo"]["reason"]
    assert any(e["kind"] == "slo_burn" for e in body["incidents"])


async def test_malformed_protocol_messages_counted_and_dropped(
        client_factory):
    """ISSUE 7 satellite: any malformed ACK/timing/clock/stats token
    increments selkies_protocol_errors_total{kind} and is dropped; the
    receive loop survives and keeps answering."""
    from selkies_tpu.server import metrics
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()

    cases = [
        ("CLIENT_FRAME_ACK,notanint", "client_frame_ack"),
        ("CLIENT_FRAME_TIMING abc:1:2:3", "client_frame_timing"),
        ("CLIENT_FRAME_TIMING 1:2:3", "client_frame_timing"),
        ("CLIENT_FRAME_TIMING ", "client_frame_timing"),
        ("CLIENT_FRAME_TIMING 1:nan:2:3", "client_frame_timing"),
        ("CLIENT_FRAME_TIMING 7:1:2:3;8:9", "client_frame_timing"),
        ("CLIENT_CLOCK ping,1", "client_clock"),
        ("CLIENT_CLOCK sample,1,2,3", "client_clock"),
        ("CLIENT_CLOCK bogus,1,2,3", "client_clock"),
        ("CLIENT_CLOCK ping,1,inf", "client_clock"),
        ("CLIENT_STATS notjson", "client_stats"),
        ("CLIENT_STATS [1,2]", "client_stats"),
        # deep nesting raises RecursionError, not ValueError — it must
        # be counted+dropped, not tear down the receive loop
        ("CLIENT_STATS " + "[" * 100_000, "client_stats"),
        # a well-formed sample that echoes no outstanding ping: the
        # estimator must not trust client-fabricated server stamps
        ("CLIENT_CLOCK sample,77,1.0,2.0,3.0,4.0", "client_clock"),
    ]
    before = {k: metrics.counter_value("selkies_protocol_errors_total",
                                       {"kind": k})
              for _, k in cases}
    for text, _kind in cases:
        await ws.send_str(text)
    # a valid exchange after the garbage proves the loop survived
    await ws.send_str(f"CLIENT_CLOCK ping,99,{_pc_ms():.3f}")
    reply = await asyncio.wait_for(ws.receive_str(), 5)
    assert reply.startswith("server_clock 99,")

    from collections import Counter
    want = Counter(k for _, k in cases)
    for kind, n in want.items():
        got = metrics.counter_value("selkies_protocol_errors_total",
                                    {"kind": kind})
        assert got == before[kind] + n, (kind, got, before[kind], n)
    await ws.close()


async def test_client_stats_surface_in_sessions(client_factory):
    """CLIENT_STATS (decoder queue depth, dropped decodes) lands in the
    verbose session snapshot — and hostile fields do not."""
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    await ws.receive_str(); await ws.receive_str()
    await ws.send_str('CLIENT_STATS {"decode_queue": 7, '
                      '"dropped_decodes": 3, "draw_fps": 58.5, '
                      '"evil": "x", "huge": 1e300}')
    await asyncio.sleep(0.1)
    r = await c.get("/api/sessions?verbose=1")
    v = (await r.json())["sessions"][0]
    assert v["client"] == {"decode_queue": 7.0, "dropped_decodes": 3.0,
                           "draw_fps": 58.5}
    await ws.close()


# --------------------------------------------------------- compile plane

async def test_prewarm_endpoint_reports_lattice_and_gate(client_factory):
    """GET /api/prewarm (ISSUE 8): the worker's lattice snapshot, the
    ladder's deferral state, and the artifact outcome in one panel —
    and the ladder is actually gated on the worker."""
    server, svc, fake, _ = make_app()
    c = await client_factory(server)
    r = await c.get("/api/prewarm")
    body = await r.json()
    assert r.status == 200 and body["enabled"] is True
    w = body["worker"]
    assert w["lattice_size"] >= 2          # base + downscale target
    assert w["pending"] + w["warmed"] == w["lattice_size"]
    geoms = {e["geometry"] for e in w["entries"]}
    assert "1920x1080" in geoms and "960x540" in geoms
    assert body["ladder"] == {"deferred": None,
                              "deferred_transitions": 0,
                              "gated": True, "level": 0}
    # the gate is the worker's: a cold downscale rung defers
    assert server.ladder.gate.query("downscale", +1) == "cold"
    assert server.ladder.gate.query("fps", +1) == "warm"
    # prewarm health check registered (ok while warming)
    r = await c.get("/api/health?verbose=1")
    checks = (await r.json())["checks"]
    assert checks["prewarm"]["status"] == "ok"


async def test_prewarm_disabled_by_setting(client_factory):
    server, svc, fake, _ = make_app(enable_prewarm=False)
    c = await client_factory(server)
    body = await (await c.get("/api/prewarm")).json()
    assert body["enabled"] is False and body["worker"] is None
    assert server.ladder.gate is None
