import json

import pytest

from selkies_tpu import settings as S


def load(argv=(), env=None):
    return S.AppSettings.parse(argv=list(argv), env=env or {})


def test_defaults():
    s = load()
    assert s.mode == "websockets"
    assert s.port == 8080
    assert s.framerate == 60
    assert s.encoder == "jpeg-tpu"
    assert s.audio_red_distance == 2


def test_precedence_cli_over_env():
    s = load(["--framerate", "30"], {"SELKIES_FRAMERATE": "120"})
    assert s.framerate == 30


def test_env_applies():
    s = load([], {"SELKIES_PORT": "9000", "SELKIES_ENABLE_AUDIO": "false"})
    assert s.port == 9000
    assert s.enable_audio is False


def test_cli_equals_form_and_bare_bool():
    s = load(["--debug", "--port=9001"])
    assert s.debug is True and s.port == 9001


def test_unknown_flag_rejected():
    with pytest.raises(S.SettingsError):
        load(["--no-such-flag", "1"])


def test_enum_validation():
    with pytest.raises(S.SettingsError):
        load(["--mode", "carrier-pigeon"])


def test_range_clamp_rejected():
    with pytest.raises(S.SettingsError):
        load(["--framerate", "1000"])


def test_locked_suffix():
    s = load([], {"SELKIES_FRAMERATE": "60|locked"})
    assert s.framerate == 60
    assert s.is_locked("framerate")
    with pytest.raises(S.SettingsError):
        s.apply_client_setting("framerate", 30)


def test_range_lock_pins_value():
    # reference settings.py:12-27 — "60-60" pins a range setting
    s = load([], {"SELKIES_FRAMERATE": "60-60"})
    assert s.framerate == 60 and s.is_locked("framerate")


def test_range_restriction():
    s = load([], {"SELKIES_VIDEO_BITRATE_KBPS": "4000-20000"})
    assert s.video_bitrate_kbps == 8000  # default inside range
    assert s.apply_client_setting("video_bitrate_kbps", 20000) == 20000
    with pytest.raises(S.SettingsError):
        s.apply_client_setting("video_bitrate_kbps", 30000)


def test_client_payload_shape():
    s = load()
    p = s.build_client_settings_payload()
    assert p["framerate"]["value"] == 60
    assert p["framerate"]["min"] == 8 and p["framerate"]["max"] == 240
    assert "basic_auth_password" not in p  # non-client settings absent
    assert p["encoder"]["choices"]
    json.dumps(p)  # serialisable


def test_sanitize_rejects_non_client():
    s = load()
    with pytest.raises(S.SettingsError):
        s.sanitize_client_setting("master_token", "x")


def test_sensitive_redaction():
    s = load(["--basic_auth_password", "hunter2"])
    d = s.dump()
    assert d["basic_auth_password"] == "<redacted>"
    assert "hunter2" not in s.to_json()


def test_list_setting():
    s = load([], {"SELKIES_ALLOWED_WS_ORIGINS": "https://a.example, https://b.example"})
    assert s.allowed_ws_origins == ("https://a.example", "https://b.example")


def test_negative_env_value_is_not_a_range():
    # "-5-10" must fail as a bad scalar, not crash range parsing
    with pytest.raises(S.SettingsError):
        load([], {"SELKIES_FRAMERATE": "-5-10"})


def test_missing_value_for_non_bool_flag():
    with pytest.raises(S.SettingsError):
        load(["--app_name"])


def test_keyframe_not_redacted_but_keys_are():
    s = load()
    d = s.dump()
    assert d["keyframe_interval_s"] == 10.0
    assert S.is_sensitive("https_key") and not S.is_sensitive("keyframe_interval_s")
