"""SLO burn-rate engine (obs.slo): multi-window math, budget
exhaustion, recovery, incident edges — all on injected clocks (every
``record``/``evaluate`` takes ``now=``), zero sleeps."""

import json

import pytest

from selkies_tpu.obs import health as _health
from selkies_tpu.obs.slo import Slo, SloEngine

T0 = 100_000.0


def mk(objective=0.99, burn_threshold=10.0, **kw):
    return Slo("g2g", "test objective", objective=objective,
               burn_threshold=burn_threshold, **kw)


def test_objective_validation():
    with pytest.raises(ValueError):
        Slo("x", objective=0.0)
    with pytest.raises(ValueError):
        Slo("x", objective=1.0)


def test_burn_rate_math_exact():
    slo = mk(objective=0.99)
    slo.record(True, n=90, now=T0)
    slo.record(False, n=10, now=T0)
    # 10% bad vs 1% budget = burn 10x, both windows see the same events
    assert slo.burn_rate(slo.fast_window_s, now=T0 + 1) \
        == pytest.approx(10.0)
    assert slo.burn_rate(slo.slow_window_s, now=T0 + 1) \
        == pytest.approx(10.0)
    assert slo.budget_remaining(now=T0 + 1) == 0.0


def test_no_events_is_ok_not_unknown_failure():
    slo = mk()
    doc = slo.evaluate(now=T0)
    assert doc["status"] == _health.OK
    assert doc["burn_fast"] is None and doc["burn_slow"] is None


def test_fast_window_alone_degrades():
    """A regression the slow window has not confirmed yet warns; only a
    double-window burn pages."""
    slo = mk(objective=0.9, burn_threshold=2.0)
    # one clean hour fills the slow window with good events
    for i in range(360):
        slo.record(True, n=10, now=T0 + i * 10.0)
    t1 = T0 + 3600.0
    # then a bad burst entirely inside the fast window: fast burn is
    # large (160 bad vs ~500 events in 5m), slow burn stays diluted
    # below threshold (160 bad vs ~3800 events in 1h)
    slo.record(False, n=160, now=t1)
    slo.record(True, n=40, now=t1)
    doc = slo.evaluate(now=t1 + 1.0)
    assert doc["burn_fast"] > 2.0
    assert doc["burn_slow"] < 2.0
    assert doc["status"] == _health.DEGRADED


def test_double_window_burn_fails():
    slo = mk(objective=0.9, burn_threshold=2.0)
    slo.record(False, n=50, now=T0)
    slo.record(True, n=50, now=T0)
    doc = slo.evaluate(now=T0 + 1.0)
    assert doc["status"] == _health.FAILED
    assert doc["burn_fast"] > 2.0 and doc["burn_slow"] > 2.0


def test_budget_exhaustion_fails_even_on_slow_leak():
    """A slow leak that ate the whole budget is an incident even when
    the slow-window burn never crossed the page threshold."""
    slo = mk(objective=0.9, burn_threshold=100.0)   # threshold very high
    # 20% bad: slow burn 2x << 100x threshold, but budget_remaining == 0
    slo.record(False, n=20, now=T0)
    slo.record(True, n=80, now=T0)
    doc = slo.evaluate(now=T0 + 1.0)
    assert doc["budget_remaining"] == 0.0
    # fast window is not burning past 100x either -> only degraded/ok?
    # burn 2x < 100x threshold: not fast_burning, so status stays ok —
    # exhaustion alone fails only WITH a burning fast window:
    assert doc["status"] == _health.OK
    slo2 = mk(objective=0.99, burn_threshold=10.0,
              fast_window_s=60.0, slow_window_s=3600.0)
    # old bad events exhaust the slow budget...
    slo2.record(False, n=50, now=T0)
    slo2.record(True, n=50, now=T0)
    # ...and a fresh fast burst is still arriving an hour minus a bit in
    t1 = T0 + 3000.0
    slo2.record(False, n=20, now=t1)
    slo2.record(True, n=80, now=t1)
    doc2 = slo2.evaluate(now=t1 + 1.0)
    assert doc2["budget_remaining"] == 0.0
    assert doc2["status"] == _health.FAILED


def test_recovery_after_windows_drain():
    slo = mk(objective=0.9, burn_threshold=2.0)
    slo.record(False, n=100, now=T0)
    assert slo.evaluate(now=T0 + 1.0)["status"] == _health.FAILED
    # both windows drain past the events: verdict returns to ok
    t_later = T0 + slo.slow_window_s + 60.0
    assert slo.evaluate(now=t_later)["status"] == _health.OK
    # and fresh clean traffic keeps it there
    slo.record(True, n=100, now=t_later)
    assert slo.evaluate(now=t_later + 1.0)["status"] == _health.OK


def test_bucket_ring_is_bounded():
    slo = mk(bucket_s=10.0, slow_window_s=3600.0)
    for i in range(10_000):
        slo.record(True, now=T0 + i * 10.0)
    # ring bounded by the slow window: 360 buckets + gc slack
    assert len(slo._buckets) <= 365
    assert slo.good_total == 10_000


def test_engine_report_and_worst_status():
    eng = SloEngine()
    eng.recorder = _health.FlightRecorder()
    eng.register(mk(burn_threshold=2.0, objective=0.9))
    eng.register(Slo("fps", objective=0.9, burn_threshold=2.0))
    eng.get("fps").record(True, n=100, now=T0)
    eng.get("g2g").record(False, n=100, now=T0)
    rep = eng.report(now=T0 + 1.0)
    assert rep["status"] == _health.FAILED
    by_name = {d["name"]: d for d in rep["slos"]}
    assert by_name["fps"]["status"] == _health.OK
    assert by_name["g2g"]["status"] == _health.FAILED
    json.loads(json.dumps(rep))


def test_engine_health_check_names_the_burning_objective():
    import time
    eng = SloEngine()
    eng.recorder = _health.FlightRecorder()
    eng.register(mk(burn_threshold=2.0, objective=0.9))
    # health_check() reads its own clock, so the events use real-
    # monotonic-relative stamps (still no sleeps)
    eng.get("g2g").record(False, n=100, now=time.monotonic())
    v = eng.health_check()
    assert v.status == _health.FAILED
    assert "g2g" in v.reason
    assert v.data["slo"] == "g2g"


def test_slo_burn_incident_edge_triggered():
    eng = SloEngine()
    rec = eng.recorder = _health.FlightRecorder()
    eng.register(mk(burn_threshold=2.0, objective=0.9))
    slo = eng.get("g2g")
    slo.record(False, n=100, now=T0)
    eng.report(now=T0 + 1.0)
    eng.report(now=T0 + 2.0)

    def burns():
        return [e for e in rec.snapshot() if e["kind"] == "slo_burn"]

    assert len(burns()) == 1, "one incident per excursion, not per report"
    # recovery re-arms the edge; the next excursion records again
    eng.report(now=T0 + slo.slow_window_s + 60.0)
    slo.record(False, n=100, now=T0 + slo.slow_window_s + 120.0)
    eng.report(now=T0 + slo.slow_window_s + 121.0)
    assert len(burns()) == 2


def test_record_against_unknown_objective_drops():
    eng = SloEngine()
    assert eng.record("nope", True) is False
    eng.register(mk())
    assert eng.record("g2g", True, now=T0) is True


def test_configure_defaults_declares_stock_objectives():
    eng = SloEngine()

    class S:
        slo_g2g_ms = 100.0
        slo_objective = 0.95
        slo_burn_threshold = 5.0
        slo_fast_window_s = 60.0
        slo_slow_window_s = 600.0

    eng.configure_defaults(S())
    assert eng.names() == ["fps", "g2g", "qoe"]
    g2g = eng.get("g2g")
    assert g2g.objective == 0.95
    assert g2g.burn_threshold == 5.0
    assert g2g.fast_window_s == 60.0 and g2g.slow_window_s == 600.0
    assert "100" in g2g.description
    # reconfigure replaces the definitions (fresh windows, no stale data)
    g2g.record(False, n=10, now=T0)
    eng.configure_defaults(S())
    assert eng.get("g2g").bad_total == 0
