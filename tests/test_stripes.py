"""Split-frame device parallelism (ROADMAP 2 / ISSUE 12): one session's
frame sharded across the virtual 8-device CPU mesh, merged by the
hierarchical bit-merge packer.

Covers the three layers the tentpole touches:

- ops/bitpack: the bit-merge packer's equivalence with the scatter (and
  gather) formulations on randomized event stacks;
- parallel/stripes: sharded-vs-unsharded BYTE identity for I and P
  frames (incl. the 4:4:4 path) across 1/2/4 devices, the
  halo-correctness fixture with motion AT a shard boundary, mesh
  degradation (logged, gauged, never silent), and the
  ValueError/padding contract;
- engine: the StripeShardedH264Session emits byte-identical chunks on
  both finalize paths, and the fleet heartbeat advertises stripe-sharded
  warm geometries.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from selkies_tpu.codecs import h264 as H
from selkies_tpu.ops import h264_planes as PL
from selkies_tpu.ops.bitpack import (pack_slot_events,
                                     pack_slot_events_bitmerge,
                                     pack_slot_events_scatter,
                                     words_to_bytes)
from selkies_tpu.ops.h264_encode import (P_SLOTS_MB, SLOTS_MB,
                                         scroll_candidates)
from selkies_tpu.parallel.stripes import (h264_encode_p_sharded,
                                          h264_encode_sharded,
                                          resolved_stripe_devices,
                                          stripe_mesh)


# ---------------------------------------------------------------------------
# hierarchical bit-merge packer vs scatter/gather
# ---------------------------------------------------------------------------

def _random_events(rng, m, s, max_bits=28, sparsity=0.4):
    nb = rng.integers(0, max_bits + 1, (m, s)).astype(np.int32)
    nb[rng.random((m, s)) < sparsity] = 0
    pay = np.zeros((m, s), np.uint32)
    mask = nb > 0
    vals = rng.integers(0, 1 << 30, int(mask.sum())).astype(np.uint32)
    pay[mask] = vals & ((np.uint32(1) << nb[mask].astype(np.uint32))
                        - np.uint32(1))
    return pay, nb


@pytest.mark.parametrize("m,s", [(1, 7), (3, 33), (8, 61), (120, 16)])
def test_bitmerge_packer_equals_scatter_and_gather(m, s):
    rng = np.random.default_rng(m * 1000 + s)
    pay, nb = _random_events(rng, m, s)
    e_cap = m * s + 4
    w_cap = max(8, (int(nb.sum()) + 31) // 32 + 2)
    outs = [f(jnp.asarray(pay), jnp.asarray(nb), e_cap, w_cap, 33)
            for f in (pack_slot_events_scatter, pack_slot_events_bitmerge,
                      pack_slot_events)]
    ref = outs[0]
    for o in outs[1:]:
        assert int(o.total_bits) == int(ref.total_bits)
        assert int(o.n_events) == int(ref.n_events)
        assert bool(o.overflow) == bool(ref.overflow)
        assert np.array_equal(np.asarray(o.words), np.asarray(ref.words))


def test_bitmerge_packer_one_bit_codes_and_overflow():
    # all-ones 1-bit events: the worst straddle density; then a word cap
    # too small must flag overflow on both formulations identically
    m, s = 2, 70
    pay = np.ones((m, s), np.uint32)
    nb = np.ones((m, s), np.int32)
    a = pack_slot_events_scatter(jnp.asarray(pay), jnp.asarray(nb),
                                 m * s, 8, 33)
    b = pack_slot_events_bitmerge(jnp.asarray(pay), jnp.asarray(nb),
                                  m * s, 8, 33)
    assert not bool(a.overflow) and not bool(b.overflow)
    assert np.array_equal(np.asarray(a.words), np.asarray(b.words))
    a2 = pack_slot_events_scatter(jnp.asarray(pay), jnp.asarray(nb),
                                  m * s, 2, 33)
    b2 = pack_slot_events_bitmerge(jnp.asarray(pay), jnp.asarray(nb),
                                   m * s, 2, 33)
    assert bool(a2.overflow) and bool(b2.overflow)


def _sink_strategy_frames(monkeypatch, with_p: bool):
    """Encode the same content under both sink strategies; -> list of
    (bitmerge, scatter) H264FrameOut pairs."""
    rng = np.random.default_rng(21)
    h, w = 32, 32
    R, M = h // 16, w // 16
    y = rng.integers(0, 256, (h, w)).astype(np.int32)
    u = rng.integers(0, 256, (h // 2, w // 2)).astype(np.int32)
    v = rng.integers(0, 256, (h // 2, w // 2)).astype(np.int32)
    pay, nb = H.slice_header_events(M, R)
    ppay, pnb = H.p_slice_header_events(M, R)
    e_cap = 9 + M * max(SLOTS_MB, P_SLOTS_MB) + 2
    w_cap = 4096

    def run():
        out, rec = PL.h264_encode_yuv(
            jnp.asarray(y), jnp.asarray(u), jnp.asarray(v), 26,
            jnp.asarray(pay), jnp.asarray(nb), e_cap, w_cap,
            want_recon=True)
        if not with_p:
            return [out]
        y1 = np.roll(y, 2, axis=0)
        pout, _ = PL.h264_encode_p_yuv(
            jnp.asarray(y1), jnp.asarray(u), jnp.asarray(v),
            rec[0], rec[1], rec[2], 26, jnp.asarray(ppay),
            jnp.asarray(pnb), 1, e_cap, w_cap,
            candidates=((0, 0), (2, 0)), stripe_rows=1)
        return [out, pout]

    monkeypatch.setenv("SELKIES_PACKER", "bitmerge")
    bm = run()
    monkeypatch.setenv("SELKIES_PACKER", "scatter")
    sc = run()
    return list(zip(bm, sc))


def _assert_same_out(pairs):
    for a, b in pairs:
        assert np.array_equal(np.asarray(a.total_bits),
                              np.asarray(b.total_bits))
        assert np.array_equal(np.asarray(a.words), np.asarray(b.words))


def test_event_sink_bitmerge_strategy_bit_identical_i(monkeypatch):
    """The production event sink's bitmerge strategy (per-MB stacks,
    log2(M) merges) must produce the scatter strategy's exact words."""
    _assert_same_out(_sink_strategy_frames(monkeypatch, with_p=False))


@pytest.mark.slow
def test_event_sink_bitmerge_strategy_bit_identical_p(monkeypatch):
    """P variant (tail events: trailing skip run + stop bit)."""
    _assert_same_out(_sink_strategy_frames(monkeypatch, with_p=True)[1:])


# ---------------------------------------------------------------------------
# sharded-vs-unsharded byte identity (ops layer)
# ---------------------------------------------------------------------------

def _rows_bytes(out):
    w = np.asarray(out.words)
    b = np.asarray(out.total_bits)
    return [words_to_bytes(w[r], int(b[r]), pad_ones=False)
            for r in range(w.shape[0])]


def _yuv420(rng, h, w):
    return (rng.integers(0, 256, (h, w)).astype(np.int32),
            rng.integers(0, 256, (h // 2, w // 2)).astype(np.int32),
            rng.integers(0, 256, (h // 2, w // 2)).astype(np.int32))


@pytest.fixture(scope="module")
def i_fixture():
    """Shared eager (un-jitted) I reference for the 1/2/4-device
    parametrization — computed once."""
    rng = np.random.default_rng(11)
    h, w = 64, 48
    R, M = h // 16, w // 16
    y, u, v = _yuv420(rng, h, w)
    pay, nb = H.slice_header_events(M, R)
    e_cap = 7 + M * SLOTS_MB + 1
    w_cap = 4096
    ref = PL.h264_encode_yuv(jnp.asarray(y), jnp.asarray(u),
                             jnp.asarray(v), 26, jnp.asarray(pay),
                             jnp.asarray(nb), e_cap, w_cap)
    return dict(R=R, y=y, u=u, v=v, pay=pay, nb=nb, e_cap=e_cap,
                w_cap=w_cap, ref_bytes=_rows_bytes(ref),
                ref_bits=np.asarray(ref.total_bits))


@pytest.mark.parametrize(
    "ndev", [pytest.param(1, marks=pytest.mark.slow), 2, 4])
def test_i_frame_sharded_byte_identity(i_fixture, ndev):
    fx = i_fixture
    mesh = stripe_mesh(fx["R"], devices=jax.devices()[:ndev])
    assert mesh.devices.size == ndev
    out = h264_encode_sharded(jnp.asarray(fx["y"]), jnp.asarray(fx["u"]),
                              jnp.asarray(fx["v"]), 26, fx["pay"],
                              fx["nb"], fx["e_cap"], fx["w_cap"], mesh)
    assert np.array_equal(fx["ref_bits"], np.asarray(out.total_bits))
    assert fx["ref_bytes"] == _rows_bytes(out)


@pytest.fixture(scope="module")
def p_fixture():
    """Shared I-frame recon + scrolled next frame for the P tests; the
    scroll amount (3 px) crosses the 2-shard boundary of a 4-row frame,
    so motion at the boundary only resolves through halo rows."""
    rng = np.random.default_rng(7)
    h, w = 64, 48
    R, M = h // 16, w // 16
    y0, u0, v0 = _yuv420(rng, h, w)
    pay, nb = H.slice_header_events(M, R)
    ppay, pnb = H.p_slice_header_events(M, R)
    e_cap = 9 + M * max(SLOTS_MB, P_SLOTS_MB) + 2
    w_cap = 4096
    _, rec = PL.h264_encode_yuv(jnp.asarray(y0), jnp.asarray(u0),
                                jnp.asarray(v0), 26, jnp.asarray(pay),
                                jnp.asarray(nb), e_cap, w_cap,
                                want_recon=True)
    y1 = np.roll(y0, 3, axis=0)
    u1 = np.roll(u0, 1, axis=0)
    v1 = np.roll(v0, 1, axis=0)
    # dy=3 matches the roll AND reaches across the 2-shard boundary;
    # kept small — candidate count scales the unrolled motion graph
    cands = ((0, 0), (3, 0), (-1, 0), (0, 1))
    return dict(R=R, M=M, rec=rec, y1=y1, u1=u1, v1=v1, ppay=ppay,
                pnb=pnb, e_cap=e_cap, w_cap=w_cap, cands=cands)


def _p_ref(fx, stripe_rows):
    out, rec = PL.h264_encode_p_yuv(
        jnp.asarray(fx["y1"]), jnp.asarray(fx["u1"]),
        jnp.asarray(fx["v1"]), fx["rec"][0], fx["rec"][1], fx["rec"][2],
        26, jnp.asarray(fx["ppay"]), jnp.asarray(fx["pnb"]), 1,
        fx["e_cap"], fx["w_cap"], candidates=fx["cands"],
        stripe_rows=stripe_rows)
    return out, rec


def test_p_frame_sharded_aligned_byte_identity(p_fixture):
    """Whole motion windows per shard: collective-free, no halo."""
    fx = p_fixture
    ref, ref_rec = _p_ref(fx, stripe_rows=2)
    mesh = stripe_mesh(fx["R"], devices=jax.devices()[:2])
    out, rec = h264_encode_p_sharded(
        jnp.asarray(fx["y1"]), jnp.asarray(fx["u1"]),
        jnp.asarray(fx["v1"]), fx["rec"][0], fx["rec"][1], fx["rec"][2],
        26, fx["ppay"], fx["pnb"], 1, fx["e_cap"], fx["w_cap"], mesh,
        candidates=fx["cands"], stripe_rows=2)
    assert _rows_bytes(ref) == _rows_bytes(out)
    for a, b in zip(ref_rec, rec):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_p_frame_sharded_halo_byte_identity(p_fixture):
    """Halo-correctness: the motion window is the WHOLE frame
    (stripe_rows=4), so the 2-shard boundary cuts the window and the
    vertical scroll's best candidate reaches across it — resolvable
    only through the exchanged halo rows. Output must still be
    byte-identical to the unsharded search."""
    fx = p_fixture
    ref, _ = _p_ref(fx, stripe_rows=4)
    mesh = stripe_mesh(fx["R"], devices=jax.devices()[:2])
    out, _ = h264_encode_p_sharded(
        jnp.asarray(fx["y1"]), jnp.asarray(fx["u1"]),
        jnp.asarray(fx["v1"]), fx["rec"][0], fx["rec"][1], fx["rec"][2],
        26, fx["ppay"], fx["pnb"], 1, fx["e_cap"], fx["w_cap"], mesh,
        candidates=fx["cands"], stripe_rows=4)
    assert np.array_equal(np.asarray(ref.total_bits),
                          np.asarray(out.total_bits))
    assert _rows_bytes(ref) == _rows_bytes(out)
    # the halo actually mattered: without motion candidates the same
    # frame costs far more bits (the scroll is only cheap via MVs,
    # whose search reaches across the shard boundary) — eager unsharded
    # run, no extra compile
    no_mv, _ = PL.h264_encode_p_yuv(
        jnp.asarray(fx["y1"]), jnp.asarray(fx["u1"]),
        jnp.asarray(fx["v1"]), fx["rec"][0], fx["rec"][1], fx["rec"][2],
        26, jnp.asarray(fx["ppay"]), jnp.asarray(fx["pnb"]), 1,
        fx["e_cap"], fx["w_cap"], candidates=((0, 0),))
    assert int(np.asarray(out.total_bits).sum()) < \
        int(np.asarray(no_mv.total_bits).sum())


@pytest.mark.slow
def test_444_sharded_i_and_p_byte_identity():
    from selkies_tpu.ops.h264_planes444 import (P_SLOTS_MB_444,
                                                SLOTS_MB_444,
                                                h264_encode_p_yuv444,
                                                h264_encode_yuv444)
    rng = np.random.default_rng(9)
    h, w = 64, 32
    R, M = h // 16, w // 16
    y = rng.integers(0, 256, (h, w)).astype(np.int32)
    u = rng.integers(0, 256, (h, w)).astype(np.int32)
    v = rng.integers(0, 256, (h, w)).astype(np.int32)
    pay, nb = H.slice_header_events(M, R)
    ppay, pnb = H.p_slice_header_events(M, R)
    e_cap = 9 + M * max(SLOTS_MB_444, P_SLOTS_MB_444) + 2
    w_cap = 6144
    ref, rec = h264_encode_yuv444(
        jnp.asarray(y), jnp.asarray(u), jnp.asarray(v), 26,
        jnp.asarray(pay), jnp.asarray(nb), e_cap, w_cap, want_recon=True)
    mesh = stripe_mesh(R, devices=jax.devices()[:4])
    out, rec_sh = h264_encode_sharded(
        jnp.asarray(y), jnp.asarray(u), jnp.asarray(v), 26, pay, nb,
        e_cap, w_cap, mesh, fullcolor=True, want_recon=True)
    assert _rows_bytes(ref) == _rows_bytes(out)
    for a, b in zip(rec, rec_sh):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # P with whole-frame window over 4 shards: the 4:4:4 halo path
    y1 = np.roll(y, 2, axis=0)
    u1 = np.roll(u, 2, axis=0)
    v1 = np.roll(v, 2, axis=0)
    cands = ((0, 0), (2, 0), (0, 1))
    p_ref, _ = h264_encode_p_yuv444(
        jnp.asarray(y1), jnp.asarray(u1), jnp.asarray(v1),
        rec[0], rec[1], rec[2], 26, jnp.asarray(ppay), jnp.asarray(pnb),
        1, e_cap, w_cap, candidates=cands, stripe_rows=4)
    p_sh, _ = h264_encode_p_sharded(
        jnp.asarray(y1), jnp.asarray(u1), jnp.asarray(v1),
        rec[0], rec[1], rec[2], 26, ppay, pnb, 1, e_cap, w_cap, mesh,
        candidates=cands, stripe_rows=4, fullcolor=True)
    assert _rows_bytes(p_ref) == _rows_bytes(p_sh)


# ---------------------------------------------------------------------------
# mesh degradation / ValueError / padding
# ---------------------------------------------------------------------------

def test_stripe_mesh_degrades_loudly(caplog):
    import logging
    from selkies_tpu.server import metrics
    with caplog.at_level(logging.WARNING,
                         logger="selkies_tpu.parallel.stripes"):
        mesh = stripe_mesh(5, requested=4)     # 5 rows: only 1 divides
    assert mesh.devices.size == 1
    assert any("degraded" in r.message for r in caplog.records)
    # the chosen count is a gauge, never only a log line
    assert metrics._gauges.get(("selkies_stripe_devices", ())) == 1.0
    assert resolved_stripe_devices(5, 4) == 1
    assert resolved_stripe_devices(6, 4) == 3
    assert resolved_stripe_devices(8, 4) == 4


def test_sharded_geometry_value_errors():
    rng = np.random.default_rng(0)
    mesh = stripe_mesh(4, devices=jax.devices()[:2])
    y = rng.integers(0, 256, (40, 48)).astype(np.int32)   # not MB-aligned
    u = rng.integers(0, 256, (20, 24)).astype(np.int32)
    with pytest.raises(ValueError, match="macroblock"):
        h264_encode_sharded(jnp.asarray(y), jnp.asarray(u),
                            jnp.asarray(u), 26, np.zeros((2, 2)),
                            np.zeros((2, 2)), 64, 64, mesh)
    y4, u4, v4 = _yuv420(rng, 64, 48)
    bad_hdr = np.zeros((2, 2), np.uint32)     # 4 rows need 4 header rows
    with pytest.raises(ValueError, match="header"):
        h264_encode_sharded(jnp.asarray(y4), jnp.asarray(u4),
                            jnp.asarray(v4), 26, bad_hdr, bad_hdr,
                            64, 64, mesh)
    mesh8 = stripe_mesh(1)
    y1, u1, v1 = _yuv420(rng, 16, 16)
    from jax.sharding import Mesh
    too_many = Mesh(np.array(jax.devices()[:2]), ("stripe",))
    with pytest.raises(ValueError, match="more shards than rows"):
        h264_encode_sharded(jnp.asarray(y1), jnp.asarray(u1),
                            jnp.asarray(v1), 26, np.zeros((1, 2)),
                            np.zeros((1, 2)), 64, 64, too_many)
    del mesh8


@pytest.mark.slow
def test_sharded_pads_non_dividing_rows():
    """3 MB rows over 2 devices: padded to 4, output trimmed, bytes
    identical to the unsharded encode. (The pad-count math and the
    ValueError surface stay in the fast suite —
    test_sharded_geometry_value_errors; this compiles the padded
    program end to end.)"""
    rng = np.random.default_rng(13)
    h, w = 48, 32
    R, M = h // 16, w // 16
    y, u, v = _yuv420(rng, h, w)
    pay, nb = H.slice_header_events(M, R)
    e_cap = 9 + M * SLOTS_MB + 2
    ref = PL.h264_encode_yuv(jnp.asarray(y), jnp.asarray(u),
                             jnp.asarray(v), 26, jnp.asarray(pay),
                             jnp.asarray(nb), e_cap, 4096)
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:2]), ("stripe",))
    out = h264_encode_sharded(jnp.asarray(y), jnp.asarray(u),
                              jnp.asarray(v), 26, pay, nb, e_cap, 4096,
                              mesh)
    assert out.words.shape[0] == R
    assert _rows_bytes(ref) == _rows_bytes(out)


# ---------------------------------------------------------------------------
# engine session
# ---------------------------------------------------------------------------

def _session_frames(n, w, h, seed=0):
    rng = np.random.default_rng(seed)
    f0 = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
    out = [f0]
    for _ in range(1, n):
        f = np.roll(out[-1], 5, axis=0)
        f[:5] = rng.integers(0, 256, (5, w, 3), dtype=np.uint8)
        out.append(f)
    return out


@pytest.mark.slow
def test_stripe_sharded_session_byte_identity():
    """The serving path: sharded session == plain session, chunk for
    chunk, on an IDR + P sequence with damage, on BOTH finalize paths
    (the stripe-streaming one composes with the PR-10 pipeline).

    ``slow`` (4 session-scale XLA builds): the stripe-bench CI job runs
    this same session-level byte-identity contract at 1 vs 4 shards on
    every push via ``bench.py --stripes``."""
    from selkies_tpu.engine.h264_encoder import (H264EncoderSession,
                                                 StripeShardedH264Session)
    from selkies_tpu.engine.types import CaptureSettings
    kw = dict(capture_width=64, capture_height=64, stripe_height=16,
              output_mode="h264", video_crf=28, use_paint_over=False,
              h264_motion_vrange=2, h264_motion_hrange=1)
    ref = H264EncoderSession(CaptureSettings(**kw))
    sh = StripeShardedH264Session(
        CaptureSettings(**kw, stripe_devices=4))
    assert sh.stripe_devices == 4
    for t, f in enumerate(_session_frames(3, 64, 64)):
        a = ref.finalize(ref.encode(jnp.asarray(f)))
        b = list(sh.finalize_stream(sh.encode(jnp.asarray(f))))
        assert [(c.stripe_y, c.is_idr, c.payload) for c in a] == \
            [(c.stripe_y, c.is_idr, c.payload) for c in b], f"frame {t}"


def test_stripe_sharded_session_degrades_to_dividing_count():
    from selkies_tpu.engine.h264_encoder import StripeShardedH264Session
    from selkies_tpu.engine.types import CaptureSettings
    # 96 px / 32 px stripes = 3 stripes: requested 4 -> chosen 3
    sess = StripeShardedH264Session(CaptureSettings(
        capture_width=48, capture_height=96, stripe_height=32,
        output_mode="h264", video_crf=28, use_paint_over=False,
        stripe_devices=4))
    assert sess.stripe_devices == 3


# ---------------------------------------------------------------------------
# fleet / prewarm surface
# ---------------------------------------------------------------------------

def test_warm_geometry_stripe_suffix_roundtrip():
    import json
    from selkies_tpu.fleet.protocol import (FleetProtocolError,
                                            parse_heartbeat)
    hb = {"v": 1, "kind": "heartbeat", "host_id": "h1", "url": "u",
          "fingerprint": "f", "seq": 1, "ts": 1.0, "started_at": 1.0,
          "ready": True, "draining": False, "health": "ok",
          "slo": {"status": "ok", "fast_burn": None}, "devices": [],
          "sessions": [],
          "warm_geometries": ["1920x1080", "1920x1080@s4"]}
    p = parse_heartbeat(json.dumps(hb))
    assert p.warm_geometries == ["1920x1080", "1920x1080@s4"]
    for bad in ("1920x1080@sx", "1920x1080@4", "1920x1080@s0"):
        hb["warm_geometries"] = [bad]
        with pytest.raises(FleetProtocolError):
            parse_heartbeat(json.dumps(hb))


def test_lattice_stripe_axis_and_program_names():
    import types
    from selkies_tpu.prewarm.lattice import lattice_from_settings
    from selkies_tpu.prewarm.plan import program_names
    lat = lattice_from_settings(types.SimpleNamespace(
        encoder="h264-tpu-striped", initial_width=128, initial_height=128,
        tpu_seats=1, tpu_stripe_devices=4, fullcolor=False,
        stripe_height=32, use_damage_gating=True, use_paint_over=False))
    assert all("stripes4" in s.program_key for s in lat.signatures)
    names = program_names(lat.base)
    # no band programs: sharded sessions gate the partial path off
    # (PR 15), so a sharded signature's compile surface is exactly the
    # device-parallel step pair
    assert names == ["h264.stripes4.i_step[128x128]",
                     "h264.stripes4.p_step[128x128]"]


def test_worker_warm_geometries_advertise_stripe_points():
    from selkies_tpu.prewarm.lattice import Signature, enumerate_lattice
    from selkies_tpu.prewarm.worker import PrewarmWorker
    plan = enumerate_lattice(Signature(width=128, height=128,
                                       codec="h264", stripe_devices=4),
                             steps=("fps",))
    w = PrewarmWorker(plan)
    for e in w._entries.values():
        e["state"] = "warm"
    assert w.warm_geometries() == ["128x128@s4"]
