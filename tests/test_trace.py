"""selkies_tpu/trace tests: span nesting/ordering, ring eviction,
disabled-mode overhead, trace-event JSON round-trip, /api/trace endpoint
contract, summarizer percentiles, CLI — plus the compile-cache host
fingerprint satellite (ISSUE 2)."""

import json
import time
import tracemalloc
import types

from selkies_tpu import compile_cache
from selkies_tpu.trace import STAGES
from selkies_tpu.trace import tracer as global_tracer
from selkies_tpu.trace.__main__ import main as trace_cli
from selkies_tpu.trace.core import _NULL_SPAN, FrameTracer
from selkies_tpu.trace.export import events_from_document, to_trace_events
from selkies_tpu.trace.summary import (frame_latency_ms, render_table,
                                       summarize_durations,
                                       summarize_events,
                                       summarize_timelines)


# -- core ---------------------------------------------------------------------

def test_span_nesting_and_ordering():
    tr = FrameTracer()
    tr.enable()
    tl = tr.frame_begin(":0")
    tr.bind(tl, 1)
    with tr.span("outer", tl):
        time.sleep(0.002)
        with tr.span("inner", tl):
            time.sleep(0.001)
    tr.frame_end(":0", 1)
    assert tl.done and tl.frame_id == 1
    names = [s[0] for s in tl.spans]
    assert names == ["inner", "outer"]      # exit order: inner closes first
    spans = {n: (t0, dur) for n, _lane, t0, dur in tl.spans}
    o_t0, o_dur = spans["outer"]
    i_t0, i_dur = spans["inner"]
    assert o_t0 <= i_t0 and i_t0 + i_dur <= o_t0 + o_dur   # containment
    assert i_dur >= 1_000_000 and o_dur >= 3_000_000
    assert tl.wall_ms() >= 3.0


def test_current_context_spans_without_explicit_timeline():
    tr = FrameTracer()
    tr.enable()
    tl = tr.frame_begin(":0")
    with tr.span("capture"):            # resolves via contextvar
        pass
    tr.bind(tl, 9)
    assert [s[0] for s in tl.spans] == ["capture"]
    # explicit None target (evicted frame) must NOT fall back to current
    with tr.span("stray", None):
        pass
    assert len(tl.spans) == 1


def test_ring_buffer_eviction():
    tr = FrameTracer(capacity=4)
    tr.enable()
    for fid in range(10):
        tl = tr.frame_begin(":0")
        tr.bind(tl, fid)
        tr.frame_end(":0", fid)
    snap = tr.snapshot()
    assert [t.frame_id for t in snap] == [6, 7, 8, 9]
    assert tr.lookup(":0", 0) is None
    assert not tr.attach_span(":0", 0, "ws.send", 0, 1000)
    assert tr.stats()["dropped"] == 6
    tr.clear()
    assert tr.snapshot() == [] and tr.stats()["frames"] == 0


def test_disabled_mode_no_allocation_beyond_flag_check():
    tr = FrameTracer()
    assert not tr.enabled
    # the disabled span is one shared singleton — identity proves no
    # per-call allocation
    assert tr.span("a") is tr.span("b") is _NULL_SPAN
    assert tr.frame_begin(":0") is None
    tr.bind(None, 1)
    tr.frame_end(":0", 1)
    assert not tr.attach_span(":0", 1, "x", 0, 1)
    # a full per-frame call sequence retains nothing
    tracemalloc.start()
    for _ in range(1000):
        with tr.span("x"):
            pass
        t = tr.frame_begin(":0")
        tr.bind(t, 0)
        tr.frame_end(":0", 0)
    current, _peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert current < 2048, f"disabled tracer retained {current} bytes"


def test_bind_aliases_route_multi_seat_attach():
    tr = FrameTracer()
    tr.enable()
    tl = tr.frame_begin("__seats__")
    tr.bind(tl, 3, aliases=("seat0", "seat1"))
    assert tr.lookup("seat0", 3) is tl
    assert tr.attach_span("seat1", 3, "ws.send", 0, 2_000_000, lane="ws")
    assert tr.instant("seat0", 3, "ack")
    assert len(tr.snapshot()) == 1          # aliases dedupe in snapshot
    names = [s[0] for s in tl.spans]
    assert names == ["ws.send", "ack"]


def test_enable_mid_stream_and_reenable():
    tr = FrameTracer()
    assert tr.frame_begin(":0") is None
    tr.enable(capacity=8)
    tl = tr.frame_begin(":0")
    tr.bind(tl, 1)
    tr.disable()
    # post-disable calls are no-ops, ring keeps what it had
    assert tr.frame_begin(":0") is None
    assert tr.lookup(":0", 1) is None       # lookups gate on enabled
    assert len(tr.snapshot()) == 1          # but the data survives


# -- export / summarize -------------------------------------------------------

def _built_tracer():
    tr = FrameTracer()
    tr.enable()
    tl = tr.frame_begin(":0")
    tr.bind(tl, 1)
    tr.attach_span(":0", 1, "capture", 1_000, 2_000_000)
    tr.attach_span(":0", 1, "encode.dispatch", 2_001_000, 5_000_000)
    tr.attach_span(":0", 1, "packetize", 7_001_000, 500_000,
                   lane="seat0")
    tr.instant(":0", 1, "ack", lane="ws")
    tr.frame_end(":0", 1)
    return tr


def test_trace_event_json_schema_roundtrip():
    tr = _built_tracer()
    doc = to_trace_events(tr.snapshot())
    assert doc["displayTimeUnit"] == "ms"
    loaded = json.loads(json.dumps(doc))    # the wire round-trip
    events = events_from_document(loaded)
    assert events, "no events survived"
    lanes = set()
    for e in events:
        assert e["ph"] in ("X", "M", "i")
        assert e["pid"] == 1 and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float)) and e["dur"] >= 0
        if e["ph"] == "i":
            assert e["s"] == "t"
        if e["ph"] == "M" and e["name"] == "thread_name":
            lanes.add(e["args"]["name"])
    assert {"frames", "seat0", "ws"} <= lanes
    # the per-frame envelope rides the frames track
    assert any(e["ph"] == "X" and e["name"] == "frame 1" for e in events)
    # summarizing the export matches summarizing the live timelines
    assert summarize_events(events) == summarize_timelines(tr.snapshot())
    # bare-array form is accepted too
    assert events_from_document(loaded["traceEvents"]) == events


def test_summarizer_percentiles_hand_built():
    durs = [float(v) for v in range(1, 101)]     # 1..100 ms
    s = summarize_durations({"stage": durs})["stage"]
    assert s["count"] == 100
    assert s["p50_ms"] == 51.0              # nearest-rank, bench convention
    assert s["p99_ms"] == 100.0
    assert s["mean_ms"] == 50.5
    assert s["total_ms"] == 5050.0
    # sorted by total descending
    two = summarize_durations({"small": [1.0], "big": [500.0]})
    assert list(two) == ["big", "small"]
    assert "stage" in render_table(s and {"stage": s})


def test_frame_latency_and_instants_excluded():
    tr = _built_tracer()
    lats = frame_latency_ms(tr.snapshot())
    assert len(lats) == 1 and lats[0] > 0
    summ = summarize_timelines(tr.snapshot())
    assert "ack" not in summ                 # zero-duration markers excluded
    assert summ["encode.dispatch"]["p50_ms"] == 5.0
    assert summ["capture"]["p50_ms"] == 2.0


def test_stage_sink_feeds_metrics_histogram():
    from selkies_tpu.server import metrics
    metrics.clear()
    tr = FrameTracer()
    tr.enable()
    assert tr.stage_sink is not None
    tl = tr.frame_begin(":0")
    tr.bind(tl, 1)
    tr.attach_span(":0", 1, "encode.readback", 0, 5_000_000)   # 5 ms
    text = metrics.render_prometheus()
    assert 'selkies_stage_ms_bucket{stage="encode.readback",le="5"} 1' \
        in text
    assert 'selkies_stage_ms_count{stage="encode.readback"} 1' in text


# -- CLI ----------------------------------------------------------------------

def test_cli_summarize(tmp_path, capsys):
    doc = to_trace_events(_built_tracer().snapshot())
    p = tmp_path / "t.json"
    p.write_text(json.dumps(doc))
    assert trace_cli(["summarize", str(p), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["version"] == 1
    assert out["stages"]["encode.dispatch"]["count"] == 1
    assert trace_cli(["summarize", str(p)]) == 0
    assert "encode.dispatch" in capsys.readouterr().out
    assert trace_cli(["summarize", str(tmp_path / "missing.json")]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert trace_cli(["summarize", str(bad)]) == 2


def test_cli_selftest_roundtrip(tmp_path):
    out = tmp_path / "selftest.json"
    assert trace_cli(["selftest", str(out)]) == 0
    events = events_from_document(json.loads(out.read_text()))
    summ = summarize_events(events)
    for stage in STAGES:
        assert stage in summ


# -- engine integration: the session spans land on the frame timeline --------

def test_jpeg_session_records_stage_spans():
    from selkies_tpu.engine.encoder import JpegEncoderSession
    from selkies_tpu.engine.types import CaptureSettings

    import jax.numpy as jnp
    s = CaptureSettings(capture_width=64, capture_height=32,
                        stripe_height=16, use_damage_gating=True)
    sess = JpegEncoderSession(s)
    g = sess.grid
    frame = jnp.zeros((g.height, g.width, 3), jnp.uint8) + 7
    global_tracer.enable(capacity=32)
    try:
        tl = global_tracer.frame_begin(s.display_id)
        out = sess.encode(frame)
        global_tracer.bind(tl, out["frame_id"])
        chunks = sess.finalize(out, force_all=True)
        global_tracer.frame_end(s.display_id, out["frame_id"])
        assert chunks
        names = [sp[0] for sp in tl.spans]
        assert {"encode.dispatch", "encode.readback", "packetize"} \
            <= set(names)
        # exactly ONE span per stage per frame — fragments would double
        # the count and skew the stage percentiles
        for stage in ("encode.dispatch", "encode.readback", "packetize"):
            assert names.count(stage) == 1, names
        summ = summarize_timelines([tl])
        assert summ["encode.dispatch"]["count"] == 1
    finally:
        global_tracer.disable()
        global_tracer.clear()


# -- /api/trace endpoint contract ---------------------------------------------

async def test_api_trace_endpoint(client_factory):
    from test_server import make_app
    server, _svc, _fake, _ = make_app()
    c = await client_factory(server)
    try:
        global_tracer.disable()
        global_tracer.clear()
        r = await c.post("/api/trace", json={"action": "start",
                                             "capacity": 64})
        body = await r.json()
        assert r.status == 200 and body["enabled"] is True \
            and body["capacity"] == 64
        tl = global_tracer.frame_begin(":0")
        global_tracer.bind(tl, 7)
        global_tracer.attach_span(":0", 7, "capture", 0, 1_000_000)
        global_tracer.frame_end(":0", 7)
        r = await c.get("/api/trace")
        assert r.status == 200
        doc = await r.json()
        events = events_from_document(doc)
        names = [e.get("name") for e in events]
        assert "capture" in names and "frame 7" in names
        assert doc["otherData"]["frames"] == 1
        r = await c.post("/api/trace", json={"action": "clear"})
        assert (await r.json())["frames"] == 0
        r = await c.post("/api/trace", json={"action": "stop"})
        assert (await r.json())["enabled"] is False
        r = await c.post("/api/trace", json={"action": "bogus"})
        assert r.status == 400
        r = await c.post("/api/trace")                  # no body
        assert r.status == 400
        r = await c.post("/api/trace", json=["start"])  # non-object body
        assert r.status == 400
        for bad_cap in ("abc", 0, -3):
            r = await c.post("/api/trace",
                             json={"action": "start", "capacity": bad_cap})
            assert r.status == 400, bad_cap
        assert global_tracer.enabled is False           # none took effect
    finally:
        global_tracer.disable()
        global_tracer.clear()


async def test_api_trace_post_needs_full_role(client_factory):
    import base64
    from test_server import make_app
    server, *_ = make_app(enable_basic_auth=True, basic_auth_user="u",
                          basic_auth_password="pw", viewonly_password="vo")
    c = await client_factory(server)
    vo = {"Authorization": "Basic " + base64.b64encode(b"u:vo").decode()}
    r = await c.post("/api/trace", json={"action": "start"}, headers=vo)
    assert r.status == 403
    r = await c.get("/api/trace", headers=vo)     # snapshots are readable
    assert r.status == 200


# -- compile-cache host fingerprint (satellite) -------------------------------

def test_host_fingerprint_stable_and_sanitized():
    fp = compile_cache.host_fingerprint()
    assert fp == compile_cache.host_fingerprint()       # stable in-process
    assert fp and "/" not in fp and " " not in fp
    fp2 = compile_cache.host_fingerprint("TPU v5e/lite pod")
    assert fp2.startswith(fp) and "/" not in fp2 and " " not in fp2
    assert fp2 != fp


def test_compile_cache_dir_keyed_by_fingerprint(tmp_path, monkeypatch):
    monkeypatch.setenv("JAX_CACHE_DIR", str(tmp_path))

    class _Cfg:
        def __init__(self):
            self.updates = {}

        def update(self, k, v):
            self.updates[k] = v

    fake_jax = types.SimpleNamespace(config=_Cfg())
    d = compile_cache.enable(fake_jax)
    assert d == str(tmp_path / compile_cache.host_fingerprint())
    assert fake_jax.config.updates["jax_compilation_cache_dir"] == d
    # a different device kind gets its own subtree
    fake2 = types.SimpleNamespace(config=_Cfg())
    d2 = compile_cache.enable(fake2, device_kind="TPU v5e")
    assert d2 != d and d2.startswith(str(tmp_path))
