"""Wayland plane tests: wire codec, screencopy capture, virtual input.

A fake wlroots-style compositor (server side of the same wire protocol,
built on the package's own codec) listens on a real unix socket; the
client under test connects exactly as it would to labwc/sway. This is
the same strategy the interposer/fake-udev C addons are tested with:
drive the real wire contract, no mocks inside the client.
"""

from __future__ import annotations

import mmap
import os
import socket
import struct
import threading

import numpy as np
import pytest

from selkies_tpu.wayland import DynamicKeymap, WaylandClient, WireError
from selkies_tpu.wayland.client import FMT_XRGB8888
from selkies_tpu.wayland.wire import (ArgReader, WaylandConnection, arg_i32,
                                      arg_string, arg_u32)

W, H = 64, 32
STRIDE = W * 4


class FakeCompositor(threading.Thread):
    """Minimal compositor: registry, shm, one output, screencopy v3,
    virtual keyboard + pointer. Records everything it is sent."""

    GLOBALS = [
        (1, "wl_shm", 1),
        (2, "wl_seat", 7),
        (3, "wl_output", 2),
        (4, "zwlr_screencopy_manager_v1", 3),
        (5, "zwp_virtual_keyboard_manager_v1", 1),
        (6, "zwlr_virtual_pointer_manager_v1", 2),
    ]

    def __init__(self, sock_path: str):
        super().__init__(daemon=True)
        self.path = sock_path
        self.listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.listener.bind(sock_path)
        self.listener.listen(4)
        self.keymaps: list[str] = []
        self.key_events: list[tuple[int, int]] = []      # (evdev key, state)
        self.modifier_events: list[tuple[int, int, int, int]] = []
        self.pointer_events: list[tuple] = []
        self.capture_count = 0
        self.fail_next_capture = False
        # per-connection object state (ids are a per-connection namespace);
        # a live server opens SEPARATE connections for capture and input
        self.ifaces: dict[tuple[int, int], str] = {}
        self.pools: dict[tuple[int, int], mmap.mmap] = {}
        self.buffers: dict[tuple[int, int], tuple[int, int]] = {}
        self.conns: dict[int, WaylandConnection] = {}
        self._stop = threading.Event()

    def run(self) -> None:
        cn = 0
        while not self._stop.is_set():
            try:
                s, _ = self.listener.accept()
            except OSError:
                return
            cn += 1
            threading.Thread(target=self._serve, args=(s, cn),
                             daemon=True).start()

    def _serve(self, s: socket.socket, cn: int) -> None:
        conn = WaylandConnection(s)
        self.conns[cn] = conn
        self.conn = conn                   # latest, for single-conn tests
        conn.handlers[1] = self._make_handler(cn, 1, "wl_display")
        try:
            while not self._stop.is_set():
                conn.dispatch(timeout=0.2)
        except (WireError, OSError):
            pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self.listener.close()
        except OSError:
            pass

    # -- request dispatch ---------------------------------------------------
    def _make_handler(self, cn: int, oid: int, iface: str):
        def h(opcode: int, r: ArgReader) -> None:
            self._request(cn, oid, iface, opcode, r)
        return h

    def _register(self, cn: int, oid: int, iface: str) -> None:
        self.ifaces[cn, oid] = iface
        self.conns[cn].handlers[oid] = self._make_handler(cn, oid, iface)

    def _request(self, cn: int, oid: int, iface: str, op: int,
                 r: ArgReader) -> None:
        c = self.conns[cn]
        if iface == "wl_display":
            if op == 0:                                   # sync
                cb = r.u32()
                c.send(cb, 0, arg_u32(1))                 # callback.done
                c.send(1, 1, arg_u32(cb))                 # delete_id
            elif op == 1:                                 # get_registry
                reg = r.u32()
                self._register(cn, reg, "wl_registry")
                for name, g_iface, ver in self.GLOBALS:
                    c.send(reg, 0, arg_u32(name) + arg_string(g_iface)
                           + arg_u32(ver))
        elif iface == "wl_registry" and op == 0:          # bind
            name = r.u32()
            b_iface, _ver, nid = r.string(), r.u32(), r.u32()
            self._register(cn, nid, b_iface)
            if b_iface == "wl_output":
                # current mode + done
                c.send(nid, 1, arg_u32(1) + arg_i32(W) + arg_i32(H)
                       + arg_i32(60000))
                c.send(nid, 2)
            elif b_iface == "wl_seat":
                c.send(nid, 0, arg_u32(3))                # caps kbd|ptr
        elif iface == "wl_shm" and op == 0:               # create_pool
            nid, fd, size = r.u32(), r.fd(), r.i32()
            self._register(cn, nid, "wl_shm_pool")
            self.pools[cn, nid] = mmap.mmap(fd, size)
            os.close(fd)
        elif iface == "wl_shm_pool":
            if op == 0:                                   # create_buffer
                nid, off = r.u32(), r.i32()
                self._register(cn, nid, "wl_buffer")
                self.buffers[cn, nid] = (oid, off)
        elif iface == "zwlr_screencopy_manager_v1" and op == 0:
            nid = r.u32()
            r.i32()                                       # overlay_cursor
            r.u32()                                       # output
            self._register(cn, nid, "zwlr_screencopy_frame_v1")
            if self.fail_next_capture:
                self.fail_next_capture = False
                c.send(nid, 3)                            # failed
                return
            c.send(nid, 0, arg_u32(FMT_XRGB8888) + arg_u32(W) + arg_u32(H)
                   + arg_u32(STRIDE))                     # buffer
            c.send(nid, 6)                                # buffer_done
        elif iface == "zwlr_screencopy_frame_v1":
            if op == 0:                                   # copy(buffer)
                buf_id = r.u32()
                pool_id, off = self.buffers[cn, buf_id]
                m = self.pools[cn, pool_id]
                # pattern: x in B, y in G, 0xAA in R (XRGB little-endian
                # memory order B,G,R,X)
                px = np.zeros((H, W, 4), np.uint8)
                px[..., 0] = np.arange(W)[None, :] % 256
                px[..., 1] = np.arange(H)[:, None] % 256
                px[..., 2] = 0xAA
                m.seek(off)
                m.write(px.tobytes())
                self.capture_count += 1
                c.send(oid, 1, arg_u32(0))                # flags
                c.send(oid, 2, arg_u32(0) + arg_u32(0) + arg_u32(0))  # ready
        elif iface == "zwp_virtual_keyboard_manager_v1" and op == 0:
            r.u32()                                       # seat
            nid = r.u32()
            self._register(cn, nid, "zwp_virtual_keyboard_v1")
        elif iface == "zwp_virtual_keyboard_v1":
            if op == 0:                                   # keymap
                fmt, fd, size = r.u32(), r.fd(), r.u32()
                assert fmt == 1                           # xkb_v1
                with mmap.mmap(fd, size, prot=mmap.PROT_READ) as m:
                    self.keymaps.append(
                        m.read(size).split(b"\x00")[0].decode())
                os.close(fd)
            elif op == 1:                                 # key
                r.u32()
                self.key_events.append((r.u32(), r.u32()))
            elif op == 2:                                 # modifiers
                self.modifier_events.append(
                    (r.u32(), r.u32(), r.u32(), r.u32()))
        elif iface == "zwlr_virtual_pointer_manager_v1" and op == 0:
            r.u32()
            nid = r.u32()
            self._register(cn, nid, "zwlr_virtual_pointer_v1")
        elif iface == "zwlr_virtual_pointer_v1":
            if op == 0:                                   # motion (rel)
                r.u32()
                self.pointer_events.append(("rel", r.fixed(), r.fixed()))
            elif op == 1:                                 # motion_absolute
                r.u32()
                self.pointer_events.append(
                    ("abs", r.u32(), r.u32(), r.u32(), r.u32()))
            elif op == 2:                                 # button
                r.u32()
                self.pointer_events.append(("btn", r.u32(), r.u32()))
            elif op == 3:                                 # axis
                r.u32()
                self.pointer_events.append(("axis", r.u32(), r.fixed()))
            elif op == 4:                                 # frame
                self.pointer_events.append(("frame",))


@pytest.fixture()
def compositor(tmp_path):
    path = str(tmp_path / "wayland-9")
    comp = FakeCompositor(path)
    comp.start()
    yield comp
    comp.stop()


@pytest.fixture()
def client(compositor):
    cl = WaylandClient(display=compositor.path)
    yield cl
    cl.close()


def test_registry_and_output(client, compositor):
    assert client.can_capture and client.can_input
    assert client.output_size() == (W, H)
    assert set(client.globals) == {g[1] for g in FakeCompositor.GLOBALS}


def test_screencopy_capture_pattern(client, compositor):
    frame = client.capture_frame()
    assert frame.shape == (H, W, 3) and frame.dtype == np.uint8
    # XRGB memory (B,G,R,X) -> RGB: R=0xAA, G=y, B=x
    assert (frame[..., 0] == 0xAA).all()
    assert (frame[:, :, 1] == np.arange(H)[:, None] % 256).all()
    assert (frame[:, :, 2] == np.arange(W)[None, :] % 256).all()
    # second capture reuses the same shm pool/buffer
    f2 = client.capture_frame()
    assert compositor.capture_count == 2
    assert (f2 == frame).all()
    assert len(compositor.pools) == 1


def test_screencopy_failure_returns_none(client, compositor):
    compositor.fail_next_capture = True
    assert client.capture_frame() is None
    assert client.capture_frame() is not None     # next one recovers


def test_virtual_keyboard_keymap_and_keys(client, compositor):
    km = DynamicKeymap()
    kc, changed = km.keycode_for(0x61)            # 'a'
    assert changed
    assert client.ensure_virtual_keyboard(km.text())
    client.keyboard_key(kc - 8, True)
    client.keyboard_key(kc - 8, False)
    client.conn.roundtrip()
    import time
    deadline = time.monotonic() + 3
    while (not compositor.key_events or not compositor.keymaps) \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert compositor.keymaps and "0x61" in compositor.keymaps[0]
    assert compositor.key_events == [(kc - 8, 1), (kc - 8, 0)]


def test_virtual_pointer_motion_button_axis(client, compositor):
    client.pointer_motion_abs(10, 20, W, H)
    client.pointer_button(0x110, True)            # BTN_LEFT
    client.pointer_button(0x110, False)
    client.pointer_axis(0, 15.0)
    client.pointer_motion_rel(3.5, -2.25)
    client.conn.roundtrip()
    import time
    deadline = time.monotonic() + 3
    while len(compositor.pointer_events) < 10 \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    ev = compositor.pointer_events
    assert ("abs", 10, 20, W, H) in ev
    assert ("btn", 0x110, 1) in ev and ("btn", 0x110, 0) in ev
    assert ("axis", 0, 15.0) in ev
    assert ("rel", 3.5, -2.25) in ev
    assert ("frame",) in ev


def test_dynamic_keymap_reuse_and_lru():
    km = DynamicKeymap()
    kc_a, ch1 = km.keycode_for(0x61)
    kc_a2, ch2 = km.keycode_for(0x61)
    assert kc_a == kc_a2 and ch1 and not ch2      # stable, no re-upload
    kc_b, ch3 = km.keycode_for(0x62)
    assert ch3 and kc_b != kc_a
    # exhaust the keycode space: the LRU keysym is evicted
    for i in range(300):
        km.keycode_for(0x1000000 + i)
    kc_new, _ = km.keycode_for(0x63)
    assert 9 <= kc_new <= 255
    text = km.text()
    assert "xkb_keymap" in text and f"<K{kc_new}>" in text


def test_keymap_text_is_wellformed():
    km = DynamicKeymap()
    km.keycode_for(0xFF0D)                        # Enter
    km.keycode_for(0x100263A)                     # Unicode smiley keysym
    t = km.text()
    assert t.count("{") == t.count("}")
    assert "0xff0d" in t and "0x100263a" in t
    for section in ("xkb_keycodes", "xkb_types", "xkb_compatibility",
                    "xkb_symbols"):
        assert section in t


# ------------------------------------------------------ engine integration


def test_wayland_source_through_engine(compositor):
    """make_source('wayland') -> WaylandSource: device frames with the
    static-scene upload skip."""
    from selkies_tpu.engine.sources import make_source

    src = make_source("wayland", W, H, display=compositor.path)
    try:
        f0 = src.get_frame(0)
        assert f0.shape == (H, W, 3)
        assert int(np.asarray(f0)[0, 5, 2]) == 5        # B channel = x
        f1 = src.get_frame(1)
        assert f1 is f0          # identical grab -> cached device array
    finally:
        src.close()


def test_wayland_backend_through_input_handler(compositor):
    """The full input path: text verbs -> InputHandler -> WaylandBackend
    -> virtual-input protocol events at the compositor."""
    import asyncio

    from selkies_tpu.input.backends import WaylandBackend
    from selkies_tpu.input.handler import InputHandler

    backend = WaylandBackend(compositor.path, screen_size=(W, H))
    h = InputHandler(backend=backend)

    async def drive():
        await h.on_message("kd,97")          # 'a'
        await h.on_message("ku,97")
        await h.on_message("m,10,20")
        await h.on_message("mb,1,1")
        await h.on_message("mb,1,0")
        await h.on_message("ms,0,1")

    asyncio.run(drive())
    backend._wl.conn.roundtrip()
    import time
    deadline = time.monotonic() + 3
    while (len(compositor.key_events) < 2
           or len(compositor.pointer_events) < 4) \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    backend.close()
    assert compositor.keymaps and "0x61" in compositor.keymaps[-1]
    assert compositor.key_events[:2] == [(1, 1), (1, 0)]  # keycode 9 - 8
    ev = compositor.pointer_events
    assert ("abs", 10, 20, W, H) in ev
    assert ("btn", 0x110, 1) in ev and ("btn", 0x110, 0) in ev
    assert any(e[0] == "axis" for e in ev)


# --------------------------------------------------------- own-compositor
def _fake_compositor_script(tmp_path, name="labwc", rc=0, delay=0.0):
    """A scripted 'compositor': creates the Wayland socket its env names
    and sleeps (or exits rc immediately when asked)."""
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir(exist_ok=True)
    script = bin_dir / name
    script.write_text(f"""#!/bin/sh
sleep {delay}
if [ {rc} -ne 0 ]; then exit {rc}; fi
python3 - <<'PY'
import os, socket, signal, sys
path = os.path.join(os.environ["XDG_RUNTIME_DIR"],
                    os.environ["WAYLAND_DISPLAY"])
s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
s.bind(path)
s.listen(1)
signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
while True:
    try:
        c, _ = s.accept()
        c.close()
    except OSError:
        break
PY
""")
    script.chmod(0o755)
    return bin_dir


async def test_own_compositor_spawns_and_stops(tmp_path, monkeypatch):
    """ensure_wayland_display (reference stream_server.py:420-447): with
    no external socket alive, the supervisor spawns the first candidate
    on PATH, waits for ITS socket, and teardown kills it."""
    from selkies_tpu.settings import AppSettings
    from selkies_tpu.wayland import compositor as C

    bin_dir = _fake_compositor_script(tmp_path)
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    monkeypatch.setenv("XDG_RUNTIME_DIR", str(tmp_path / "run"))
    (tmp_path / "run").mkdir()
    monkeypatch.delenv("WAYLAND_DISPLAY", raising=False)

    s = AppSettings.parse([], {})
    display, owned = await C.ensure_wayland_display(s)
    try:
        assert display == "selkies-wl-0"
        assert owned is not None
        assert C.socket_alive(display)
        assert owned.proc is not None and owned.proc.returncode is None
    finally:
        if owned:
            await owned.stop()
    assert owned.proc.returncode is not None


async def test_external_socket_preferred(tmp_path, monkeypatch):
    """A live wayland_host_display socket wins: no process is spawned."""
    import socket as _socket
    from selkies_tpu.settings import AppSettings
    from selkies_tpu.wayland import compositor as C

    run = tmp_path / "run"
    run.mkdir()
    monkeypatch.setenv("XDG_RUNTIME_DIR", str(run))
    srv = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    srv.bind(str(run / "external-wl"))
    srv.listen(1)
    try:
        s = AppSettings.parse([], {})
        s.set_server("wayland_host_display", "external-wl")
        display, owned = await C.ensure_wayland_display(s)
        assert display == "external-wl"
        assert owned is None
    finally:
        srv.close()


async def test_own_compositor_unavailable_degrades(tmp_path, monkeypatch):
    """No candidate on PATH -> (None, None), never an exception (the
    server keeps running with capture degraded)."""
    from selkies_tpu.settings import AppSettings
    from selkies_tpu.wayland import compositor as C

    monkeypatch.setenv("PATH", str(tmp_path / "empty"))
    monkeypatch.setenv("XDG_RUNTIME_DIR", str(tmp_path / "run2"))
    (tmp_path / "run2").mkdir()
    monkeypatch.delenv("WAYLAND_DISPLAY", raising=False)
    s = AppSettings.parse([], {})
    display, owned = await C.ensure_wayland_display(s)
    assert display is None and owned is None
