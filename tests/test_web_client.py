"""Web client static checks.

No JS engine ships in this image, so these tests guard the ES-module
client at the import-graph level (a typo'd module path or export name is
a blank screen in production): every ``import { X } from "./mod.js"``
must resolve to an existing file that actually exports ``X``, and the
HTTP server must serve the module files (reference web client:
addons/selkies-web-core; SURVEY.md §2.3).
"""

import re
from pathlib import Path

from selkies_tpu.input.backends import NullBackend
from selkies_tpu.input.handler import InputHandler
from selkies_tpu.server.core import CentralizedStreamServer
from selkies_tpu.server.ws_service import WebSocketsService
from selkies_tpu.settings import AppSettings

WEB = Path(__file__).resolve().parent.parent / "selkies_tpu" / "web"

IMPORT_RE = re.compile(
    r'import\s*(?:\{([^}]*)\})?\s*(?:from\s*)?["\'](\./[^"\']+)["\']')
EXPORT_RE = re.compile(
    r'export\s+(?:async\s+)?(?:class|function|const|let|var)\s+'
    r'([A-Za-z_$][\w$]*)')


def _imports(path: Path):
    for m in IMPORT_RE.finditer(path.read_text()):
        names = [n.strip().split(" as ")[0]
                 for n in (m.group(1) or "").split(",") if n.strip()]
        yield m.group(2), names


def _exports(path: Path):
    return set(EXPORT_RE.findall(path.read_text()))


def test_entry_module_graph_resolves():
    entry = WEB / "selkies-client.js"
    seen = set()
    stack = [entry]
    checked_any = False
    while stack:
        mod = stack.pop()
        if mod in seen:
            continue
        seen.add(mod)
        for rel, names in _imports(mod):
            target = (mod.parent / rel).resolve()
            assert target.is_file(), f"{mod.name}: import {rel} missing"
            exported = _exports(target)
            for n in names:
                assert n in exported, (
                    f"{mod.name}: imports {{{n}}} from {rel}, "
                    f"but {target.name} exports {sorted(exported)}")
                checked_any = True
            stack.append(target)
    assert checked_any, "no named imports found — regex rot?"


def test_index_loads_client_as_module():
    html = (WEB / "index.html").read_text()
    assert 'type="module"' in html and "selkies-client.js" in html


def test_worker_is_classic_with_shared_core():
    # lib/video-worker.js is a CLASSIC worker (loads where module workers
    # don't): ES import statements would break it at runtime; the shared
    # decode core arrives via importScripts instead
    text = (WEB / "lib" / "video-worker.js").read_text()
    assert not re.search(r"^\s*import\s", text, re.M)
    assert 'importScripts("stripe-core.js")' in text
    assert "SelkiesStripeCore.makeStripeDecoder" in text
    # the sink must spawn it by the path the server serves
    video = (WEB / "lib" / "video.js").read_text()
    assert 'new Worker("lib/video-worker.js")' in video
    # the main-thread fallback shares the SAME core, loaded by the page
    assert "window.SelkiesStripeCore.makeStripeDecoder" in video
    html = (WEB / "index.html").read_text()
    assert '<script src="lib/stripe-core.js">' in html


def test_js_braces_balanced():
    # crude parse check: balanced braces/parens/brackets outside strings
    # and comments catches truncated writes and merge damage
    for path in sorted(WEB.rglob("*.js")):
        text = re.sub(r"//[^\n]*|/\*.*?\*/", "",
                      path.read_text(), flags=re.S)
        text = re.sub(r'"(?:\\.|[^"\\\n])*"'
                      r"|'(?:\\.|[^'\\\n])*'"
                      r"|`(?:\\.|[^`\\])*`", '""', text)
        for o, c in ("{}", "()", "[]"):
            assert text.count(o) == text.count(c), (
                f"{path.name}: unbalanced {o}{c} "
                f"({text.count(o)} vs {text.count(c)})")


def test_timing_batch_format_round_trips():
    """ISSUE 7: the batch format the JS emits
    (``fid:recv:decode:present;...``, toFixed(2) floats) parses through
    protocol.parse_frame_timing — built here exactly as the client
    builds it, so a format drift on either side breaks this test."""
    from selkies_tpu import protocol as P

    # mirror selkies-client.js _noteFramePresented: per-entry template
    # `${fid}:${recv.toFixed(2)}:${decode.toFixed(2)}:${present.toFixed(2)}`
    entries = [(17, 1001.5, 1003.25, 1011.0),
               (18, 1017.33, 1018.0, 1019.99)]
    batch = ";".join(f"{fid}:{r:.2f}:{d:.2f}:{p:.2f}"
                     for fid, r, d, p in entries)
    parsed = P.parse_frame_timing(batch)
    assert parsed == [(17, 1001.5, 1003.25, 1011.0),
                      (18, 1017.33, 1018.0, 1019.99)]
    # and the JS really does emit that shape
    js = (WEB / "selkies-client.js").read_text()
    assert "CLIENT_FRAME_TIMING ${this._timingQueue.join(\";\")}" in js
    assert re.search(
        r"\$\{fid\}:\$\{e\.recv\.toFixed\(2\)\}", js), \
        "timing entry template drifted from fid:recv:decode:present"


def test_timing_parser_rejects_malformed_batches():
    import pytest

    from selkies_tpu import protocol as P
    for bad in ("", "abc:1:2:3", "1:2:3", "1:nan:2:3", "1:inf:2:3",
                "5:1:2:3;6:7", ";".join("1:2:3:4" for _ in range(65))):
        with pytest.raises(ValueError):
            P.parse_frame_timing(bad)


def test_client_speaks_the_glass_to_glass_protocol():
    """Static wiring checks: clock ping loop, server_clock echo, frame
    receive/decode/present capture, CLIENT_STATS from the sink."""
    js = (WEB / "selkies-client.js").read_text()
    assert "CLIENT_CLOCK ping," in js
    assert "CLIENT_CLOCK sample," in js
    assert '"server_clock"' in js
    assert "requestVideoFrameCallback" in js
    assert "CLIENT_STATS" in js and "clientStats" in js
    # the decoder-load counters the stats ride on
    core = (WEB / "lib" / "stripe-core.js").read_text()
    assert "droppedDecodes" in core and "function stats()" in core
    worker = (WEB / "lib" / "video-worker.js").read_text()
    assert '"cstats"' in worker
    video = (WEB / "lib" / "video.js").read_text()
    assert video.count("clientStats()") >= 2   # worker sink + fallback


def test_migrate_command_contract():
    """ISSUE 11 remaining item: the client handles ``migrate,{json}``.
    Built here exactly as ws_service.announce_migration builds it
    (fleet/protocol.migrate_command), then statically checked against
    the JS handler — a drift on either side of the verb breaks this
    test, like the timing-batch contract above."""
    import json as _json

    from selkies_tpu.fleet.protocol import migrate_command

    cmd = migrate_command("https://host2.example:8443", "sid-42",
                          resync=True)
    verb, payload = cmd.split(",", 1)
    assert verb == "migrate"
    body = _json.loads(payload)
    assert set(body) == {"url", "sid", "resync"}
    assert body["url"] == "https://host2.example:8443"
    assert body["sid"] == "sid-42" and body["resync"] is True

    js = (WEB / "selkies-client.js").read_text()
    # verb dispatch + handler consume every field the server sends
    assert 'case "migrate": this._onMigrate(rest); break;' in js
    for field in ("m.url", "m.sid", "m.resync"):
        assert field in js, f"migrate handler ignores {field}"
    # the reconnect carries the gateway's affinity key on the WS path
    assert 'u.searchParams.set("fleet_sid", String(m.sid))' in js
    assert '"/api/websockets"' in js
    assert "this._migrateUrl" in js
    # resync requests a keyframe once reconnected
    assert "_migrateResync" in js and "REQUEST_KEYFRAME" in js


async def test_server_serves_module_assets(client_factory):
    s = AppSettings.parse([], {})
    svc = WebSocketsService(s, input_handler=InputHandler(
        backend=NullBackend()), capture_factory=lambda: None)
    server = CentralizedStreamServer(s)
    server.register_service("websockets", svc)
    server.register_static()     # run() does this on the real path
    client = await client_factory(server)
    for path in ("/lib/video.js", "/lib/video-worker.js",
                 "/lib/stripe-core.js", "/lib/input.js", "/lib/audio.js",
                 "/lib/keysyms.js", "/lib/protocol.js", "/lib/upload.js",
                 "/selkies-client.js"):
        r = await client.get(path)
        assert r.status == 200, path
        body = await r.text()
        assert body.strip(), path
