"""WebRTC signaling + TURN chain tests (protocol-level WS simulators,
no aiortc required)."""

import asyncio
import base64
import hashlib
import hmac as hmac_mod
import json
import os

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from selkies_tpu.server.signaling import SignalingServer
from selkies_tpu.server.turn import (get_rtc_configuration,
                                     hmac_turn_credential,
                                     load_rtc_config_file)
from selkies_tpu.settings import AppSettings


def _settings(**kw):
    s = AppSettings.parse([], {})
    for k, v in kw.items():
        s.set_server(k, v)
    return s


def test_hmac_turn_credential_matches_coturn_scheme():
    user, cred = hmac_turn_credential("s3cret", "alice", ttl_s=600,
                                      now=1_000_000)
    assert user == "1000600:alice"
    expect = base64.b64encode(
        hmac_mod.new(b"s3cret", user.encode(), hashlib.sha1).digest()
    ).decode()
    assert cred == expect


def test_rtc_config_chain_legacy_and_hmac():
    async def run():
        cfg = await get_rtc_configuration(_settings(
            turn_host="turn.example", turn_port=3478,
            turn_username="u", turn_password="pw"))
        srv = cfg["iceServers"][0]
        assert srv["username"] == "u" and srv["credential"] == "pw"
        assert "turn:turn.example:3478?transport=udp" in srv["urls"]

        cfg = await get_rtc_configuration(_settings(
            turn_host="turn.example", turn_shared_secret="sec"))
        srv = cfg["iceServers"][0]
        assert ":" in srv["username"]          # expiry:user form

        cfg = await get_rtc_configuration(_settings())
        assert any("stun:" in u for s in cfg["iceServers"]
                   for u in s["urls"])
    asyncio.run(run())


def test_rtc_config_file_refuses_world_writable(tmp_path):
    p = tmp_path / "rtc.json"
    p.write_text(json.dumps({"iceServers": [{"urls": ["stun:x:1"]}]}))
    os.chmod(p, 0o646)
    assert load_rtc_config_file(str(p)) is None
    os.chmod(p, 0o600)
    assert load_rtc_config_file(str(p))["iceServers"][0]["urls"] == ["stun:x:1"]


async def _ws_app(sig):
    app = web.Application()
    app.router.add_get("/api/signaling", sig.handler)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def test_signaling_session_relay():
    async def run():
        sig = SignalingServer()
        c = await _ws_app(sig)
        # the streaming server's own peer
        srv = await c.ws_connect("/api/signaling")
        await srv.send_str("HELLO server")
        assert (await srv.receive_str()) == "HELLO"
        # a browser peer
        br = await c.ws_connect("/api/signaling")
        await br.send_str('HELLO client {"client_type": "controller", '
                          '"display_id": "primary"}')
        assert (await br.receive_str()) == "HELLO"
        await br.send_str("SESSION server")
        ok = await br.receive_str()
        assert ok.startswith("SESSION_OK ")
        start = await srv.receive_str()
        assert start.startswith("SESSION_START ")
        caller_uid = start.split()[1]
        assert "controller" in start and "primary" in start
        # browser -> server: raw SDP json arrives wrapped MSG <uid> <json>
        sdp = json.dumps({"sdp": {"type": "offer", "sdp": "v=0..."}})
        await br.send_str(sdp)
        relay = await srv.receive_str()
        assert relay == f"MSG {caller_uid} {sdp}"
        # server -> that browser peer: answer addressed by uid
        answer = json.dumps({"sdp": {"type": "answer", "sdp": "v=0..."}})
        await srv.send_str(f"MSG {caller_uid} {answer}")
        assert (await br.receive_str()) == answer
        # teardown notifies the partner
        await br.send_str("SESSION_END")
        end = await srv.receive_str()
        assert end.startswith("SESSION_END ")
        await br.close(); await srv.close(); await c.close()
    asyncio.run(run())


def test_signaling_server_peer_superseded():
    async def run():
        sig = SignalingServer()
        c = await _ws_app(sig)
        old = await c.ws_connect("/api/signaling")
        await old.send_str("HELLO server")
        await old.receive_str()
        new = await c.ws_connect("/api/signaling")
        await new.send_str("HELLO server")
        await new.receive_str()
        msg = await old.receive()          # evicted with close 4001
        assert old.close_code == 4001
        assert len([p for p in sig.peers.values()
                    if p.peer_type == "server"]) == 1
        await new.close(); await c.close()
    asyncio.run(run())


def test_turn_endpoint_through_webrtc_service():
    async def run():
        from selkies_tpu.server.webrtc_service import WebRTCService
        svc = WebRTCService(_settings(turn_host="t.example",
                                      turn_shared_secret="k"))
        app = web.Application()
        svc.register_routes(app)
        client = TestClient(TestServer(app))
        await client.start_server()
        r = await client.get("/api/turn")
        cfg = await r.json()
        assert cfg["iceServers"][0]["urls"][0].startswith("turn:t.example")
        await client.close()
    asyncio.run(run())


def test_turn_rest_addon_app():
    """addons/turn-rest mints coturn-compatible HMAC credentials through
    the same scheme the server's resolution chain consumes."""
    async def run():
        import importlib.util
        import pathlib
        path = (pathlib.Path(__file__).parent.parent / "addons"
                / "turn-rest" / "app.py")
        spec = importlib.util.spec_from_file_location("turn_rest_app", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.SECRET = "s3cret"
        mod.TURN_HOST = "turn.example"
        app = mod.make_app()
        client = TestClient(TestServer(app))
        await client.start_server()
        r = await client.get("/?service=turn&username=alice")
        cfg = await r.json()
        assert r.status == 200
        turn = cfg["iceServers"][1]
        assert turn["urls"][0].startswith("turn:turn.example:3478")
        user, cred = turn["username"], turn["credential"]
        assert user.endswith(":alice")
        expect = base64.b64encode(hmac_mod.new(
            b"s3cret", user.encode(), hashlib.sha1).digest()).decode()
        assert cred == expect
        r = await client.get("/?service=smtp")
        assert r.status == 400
        await client.close()
    asyncio.run(run())


async def test_cloudflare_turn_resolver():
    """Cloudflare Calls credentials (reference webrtc_utils.py:298-352):
    POST bearer-auth'd key endpoint -> iceServers; exercised against an
    in-test API double, including the single-object response shape."""
    from aiohttp import web as _web
    from selkies_tpu.server.turn import fetch_cloudflare

    seen = {}

    async def handler(request):
        seen["auth"] = request.headers.get("Authorization")
        seen["body"] = await request.json()
        return _web.json_response({"iceServers": {
            "urls": ["turn:turn.cloudflare.com:3478?transport=udp"],
            "username": "u1", "credential": "c1"}}, status=201)

    app = _web.Application()
    app.router.add_post("/gen", handler)
    runner = _web.AppRunner(app)
    await runner.setup()
    site = _web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    try:
        cfg = await fetch_cloudflare(
            "kid", "tok", ttl_s=120,
            api_url=f"http://127.0.0.1:{port}/gen")
    finally:
        await runner.cleanup()
    assert seen["auth"] == "Bearer tok"
    assert seen["body"] == {"ttl": 120}
    assert cfg["iceServers"][0]["username"] == "u1"
    assert cfg["lifetimeDuration"] == "120s"


async def test_rtc_config_monitor_pushes_changes(tmp_path):
    """The watched rtc_config_file fires on appearance and on change,
    and refuses a world-writable replacement (reference
    RTCConfigFileMonitor, webrtc_utils.py:354-460)."""
    import asyncio as _asyncio
    from selkies_tpu.server.turn import RtcConfigMonitor

    path = tmp_path / "rtc.json"
    got = []
    mon = RtcConfigMonitor(str(path), got.append, poll_s=0.05)
    mon.start()
    try:
        await _asyncio.sleep(0.12)
        assert got == []                      # no file yet
        path.write_text(json.dumps({"iceServers": [{"urls": ["stun:a"]}]}))
        path.chmod(0o600)
        await _asyncio.sleep(0.2)
        assert len(got) == 1
        path.write_text(json.dumps({"iceServers": [{"urls": ["stun:b"]}]}))
        await _asyncio.sleep(0.2)
        assert len(got) == 2
        assert got[1]["iceServers"][0]["urls"] == ["stun:b"]
        path.chmod(0o666)                     # now tainted: no more fires
        path.write_text(json.dumps({"iceServers": [{"urls": ["stun:c"]}]}))
        await _asyncio.sleep(0.2)
        assert len(got) == 2
    finally:
        await mon.stop()


def test_display_rect_honours_display2_position():
    """Satellite (ISSUE 3 / ADVICE r5): secondary captures must follow
    display2_position instead of being pinned to (initial_width, 0) —
    and left/above layouts move the PRIMARY's origin too."""
    from selkies_tpu.server.webrtc_service import WebRTCService
    w, h = 1920, 1080
    for pos, o1, o2 in (
            ("right", (0, 0), (w, 0)),
            ("left", (w, 0), (0, 0)),
            ("above", (0, h), (0, 0)),
            ("below", (0, 0), (0, h))):
        svc = WebRTCService(_settings(display2_position=pos))
        assert svc._display_rect("primary") == o1, pos
        assert svc._display_rect(":0") == o1, pos      # x-display alias
        assert svc._display_rect("display2") == o2, pos


def test_webrtc_resize_retargets_all_live_captures():
    """Satellite: a resize must push update_capture_region to EVERY live
    capture — with left/above layouts the other display's origin shifts
    when the geometry changes."""
    from selkies_tpu.server.webrtc_service import WebRTCService

    class _Cap:
        def __init__(self):
            self.regions = []

        def is_capturing(self):
            return True

        def update_capture_region(self, x, y, w, h):
            self.regions.append((x, y, w, h))

    async def run():
        svc = WebRTCService(_settings(display2_position="left"))
        svc._loop = asyncio.get_running_loop()
        svc._captures = {"primary": _Cap(), "display2": _Cap()}
        await svc._resize(1280, 720, "primary")
        # both captures retargeted; primary's origin follows the NEW
        # width of the left-placed secondary
        assert svc._captures["primary"].regions == [(1280, 0, 1280, 720)]
        assert svc._captures["display2"].regions == [(0, 0, 1280, 720)]
    asyncio.run(run())
