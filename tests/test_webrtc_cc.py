"""GCC send-side congestion control: TWCC wire format round-trips, the
trendline detector's three states, AIMD behavior, and the reference loss
policy (x0.7 backoff / x1.15 recovery, webrtc_mode.py:1652-1716)."""

import struct

import pytest

# the webrtc package binds OpenSSL at import time; boxes whose
# libssl/libcrypto lack the DTLS-SRTP surface must SKIP these tests,
# not error collection (dtls converts missing symbols to ImportError)
pytest.importorskip("selkies_tpu.webrtc.dtls",
                    reason="usable OpenSSL (DTLS-SRTP surface) required",
                    exc_type=ImportError)

from selkies_tpu.webrtc.cc import (AckedBitrate, AimdRateControl,
                                   LossController,
                                   SendSideCongestionController,
                                   TrendlineEstimator, TWCC_EXT_ID,
                                   build_rtcp_twcc, parse_rtcp_twcc)
from selkies_tpu.webrtc.rtp import H264Packetizer, RtpPacket


def test_twcc_feedback_roundtrip():
    times = [1_000_000 + i * 2_000 for i in range(10)]
    times[3] = None                       # lost
    times[7] = None
    pkt = build_rtcp_twcc(1, 2, base_seq=100, rx_times_us=times)
    fbs = parse_rtcp_twcc(pkt)
    assert len(fbs) == 1
    fb = fbs[0]
    assert fb.base_seq == 100
    assert len(fb.packets) == 10
    got = {seq: t for seq, t in fb.packets}
    assert got[103] is None and got[107] is None
    # delta quantisation is 250 us — times must round-trip exactly here
    assert got[100] == 1_000_000
    assert got[109] == 1_018_000


def test_twcc_large_negative_delta():
    times = [64_000 * 10, 64_000 * 10 - 30_000]      # re-ordered arrival
    pkt = build_rtcp_twcc(1, 2, base_seq=5, rx_times_us=times)
    fb = parse_rtcp_twcc(pkt)[0]
    assert fb.packets[1][1] == 64_000 * 10 - 30_000


def test_rtp_extension_roundtrip():
    cc = SendSideCongestionController()
    pk = H264Packetizer(twcc_alloc=cc.alloc_seq)
    pkts = pk.packetize(b"\x00\x00\x00\x01\x65" + b"x" * 50, 1234)
    assert len(pkts) == 1
    p = pkts[0]
    assert p.twcc_seq == 0
    wire = p.to_bytes()
    assert wire[0] & 0x10                            # X bit set
    assert struct.unpack_from("!H", wire, 12)[0] == 0xBEDE
    # parse() must skip the extension and recover the payload
    back = RtpPacket.parse(wire)
    assert back.payload == p.payload


def test_trendline_normal_and_overuse():
    t = TrendlineEstimator()
    # constant delay: send every 10ms, arrive 5ms later -> normal
    for i in range(30):
        t.add_packet(i * 10_000, i * 10_000 + 5_000)
    t.flush()
    assert t.state == "normal"
    # growing queue: arrival delta exceeds send delta consistently
    t2 = TrendlineEstimator()
    for i in range(60):
        t2.add_packet(i * 10_000, i * 10_000 + 5_000 + i * 3_000)
    t2.flush()
    assert t2.state == "overuse"


def test_aimd_decrease_on_overuse_and_recovery():
    a = AimdRateControl(start_bps=4_000_000)
    r1 = a.update("overuse", 3_000_000.0, 1_000_000)
    assert r1 == 0.85 * 3_000_000.0
    # normal periods recover (hold -> increase)
    r2 = a.update("normal", 3_000_000.0, 2_000_000)
    r3 = a.update("normal", 3_000_000.0, 3_000_000)
    assert r3 >= r2 >= r1


def test_acked_bitrate_window():
    ab = AckedBitrate(window_us=1_000_000)
    for i in range(11):
        ab.add(i * 100_000, 12_500)      # 12.5 kB / 100 ms = 1 Mbps
    bps = ab.bps()
    assert bps is not None and 0.8e6 < bps < 1.2e6


def test_loss_controller_reference_policy():
    lc = LossController(ceiling_bps=10_000_000, backoff_interval_us=0)
    c1 = lc.update(0.2, 1_000_000)
    assert c1 == 10_000_000 * 0.7
    c2 = lc.update(0.2, 2_000_000)
    assert c2 == c1 * 0.7
    c3 = lc.update(0.0, 3_000_000)
    assert c3 == min(10_000_000, c2 * 1.15)
    # mid-range loss holds
    assert lc.update(0.05, 4_000_000) == c3


def test_controller_end_to_end_backoff():
    """Sustained queue growth reported via TWCC must pull the target
    below its start value; clean feedback must let it climb again."""
    cc = SendSideCongestionController(start_bps=4_000_000.0)
    start = cc.target_bps
    now = 0

    def feed(n, queue_per_pkt_us, lost_every=0):
        nonlocal now
        seqs, times = [], []
        for i in range(n):
            s = cc.alloc_seq()
            cc.on_packet_sent(s, 1200, now)
            lost = lost_every and (i % lost_every == 0)
            times.append(None if lost
                         else now + 5_000 + i * queue_per_pkt_us)
            seqs.append(s)
            now += 10_000
        fb = build_rtcp_twcc(1, 2, seqs[0], times)
        for f in parse_rtcp_twcc(fb):
            cc.on_feedback(f, now)

    for _ in range(6):
        feed(20, 4_000)                  # 4ms of queue per packet
    assert cc.target_bps < start
    low = cc.target_bps
    for _ in range(30):
        feed(20, 0)
    assert cc.target_bps > low


def test_sdp_offers_transport_cc():
    from selkies_tpu.webrtc.sdp import build_offer
    sdp = build_offer("127.0.0.1", 5000, "u", "p", "AA:BB")
    assert "transport-cc" in sdp
    assert f"a=extmap:{TWCC_EXT_ID} " in sdp


def _feedback(cc, seqs, times, now):
    fb = build_rtcp_twcc(1, 2, seqs[0], times)
    for f in parse_rtcp_twcc(fb):
        cc.on_feedback(f, now)


def test_missing_then_received_is_not_loss():
    """TWCC routinely reports a packet 'not received' and re-reports it
    received in the next feedback (reordering / delayed delivery). The
    grace window must keep such packets out of the loss fraction."""
    cc = SendSideCongestionController(start_bps=4_000_000.0)
    now = 0
    seqs = []
    for i in range(10):
        s = cc.alloc_seq()
        cc.on_packet_sent(s, 1200, now)
        seqs.append(s)
        now += 10_000
    # first feedback: seq 5 missing
    times = [now + i * 1_000 if i != 5 else None for i in range(10)]
    _feedback(cc, seqs, times, now)
    assert cc.last_loss_fraction == 0.0
    assert 5 in cc._missing
    # second feedback (within grace): seq 5 arrived after all
    now += 50_000
    _feedback(cc, [seqs[5]], [now], now)
    assert 5 not in cc._missing
    # grace expiry with nothing outstanding: still no loss
    now += SendSideCongestionController.LOSS_GRACE_US + 1
    s = cc.alloc_seq()
    cc.on_packet_sent(s, 1200, now)
    _feedback(cc, [s], [now + 1_000], now)
    assert cc.last_loss_fraction == 0.0


def test_loss_finalised_after_grace_window():
    """A packet never re-reported received must count as lost once the
    grace window expires — weighed against the receives of the whole
    sliding window, not just the finalising feedback."""
    cc = SendSideCongestionController(start_bps=4_000_000.0)
    now = 0
    seqs = []
    for i in range(20):
        s = cc.alloc_seq()
        cc.on_packet_sent(s, 1200, now)
        seqs.append(s)
        now += 10_000
    times = [now + i * 1_000 if i >= 4 else None for i in range(20)]
    _feedback(cc, seqs, times, now)
    assert cc.last_loss_fraction == 0.0          # still provisional
    # grace expires; the finalising feedback acks just 2 new packets
    now += SendSideCongestionController.LOSS_GRACE_US + 1_000
    extra = []
    for i in range(2):
        s = cc.alloc_seq()
        cc.on_packet_sent(s, 1200, now)
        extra.append(s)
    _feedback(cc, extra, [now + 1_000, now + 2_000], now)
    # 4 lost vs 16+2 received over the window -> ~18%, NOT 4/(4+2)=67%
    assert abs(cc.last_loss_fraction - 4 / 22) < 1e-9


def test_cc_stats_snapshot_coherent_mid_stream():
    """ISSUE 4 satellite: stats() is coherent after synthetic TWCC
    feedback — acked bps > 0, detector state is a valid state, the
    AIMD/loss internals mirror the live controller, and the snapshot is
    JSON-serialisable for /api/sessions."""
    import json as _json

    cc = SendSideCongestionController(start_bps=4_000_000.0)
    now = 0
    for _ in range(5):
        seqs, times = [], []
        for i in range(20):
            s = cc.alloc_seq()
            cc.on_packet_sent(s, 1200, now)
            times.append(now + 5_000)
            seqs.append(s)
            now += 10_000
        _feedback(cc, seqs, times, now)
    st = cc.stats()
    assert st["acked_bps"] is not None and st["acked_bps"] > 0
    assert st["detector_state"] in ("normal", "overuse", "underuse")
    assert st["aimd_state"] in ("increase", "hold")
    assert st["target_bps"] == round(cc.target_bps, 1)
    assert st["loss_fraction"] == 0.0
    assert st["loss_cap_bps"] > 0
    assert st["trend_threshold"] >= 6.0
    assert st["in_flight"] == len(cc._sent)
    assert st["provisional_missing"] == 0
    _json.loads(_json.dumps(st))


def test_cc_stats_loss_fraction_roundtrips_from_rtcp():
    """The loss fraction surfaced by stats() equals what the RTCP
    feedback (grace-finalised) actually reported."""
    cc = SendSideCongestionController(start_bps=4_000_000.0)
    now = 0
    seqs = []
    for i in range(20):
        s = cc.alloc_seq()
        cc.on_packet_sent(s, 1200, now)
        seqs.append(s)
        now += 10_000
    times = [now + i * 1_000 if i >= 4 else None for i in range(20)]
    _feedback(cc, seqs, times, now)
    now += SendSideCongestionController.LOSS_GRACE_US + 1_000
    extra = []
    for i in range(2):
        s = cc.alloc_seq()
        cc.on_packet_sent(s, 1200, now)
        extra.append(s)
    _feedback(cc, extra, [now + 1_000, now + 2_000], now)
    st = cc.stats()
    assert abs(st["loss_fraction"] - round(4 / 22, 4)) < 1e-9
    assert st["loss_fraction"] == round(cc.last_loss_fraction, 4)


def test_cc_rtt_from_twcc_feedback_timing():
    """TWCC RTT: feedback arrival minus the newest acked packet's send
    time, EWMA-smoothed into srtt_ms."""
    cc = SendSideCongestionController()
    s0 = cc.alloc_seq()
    cc.on_packet_sent(s0, 1200, 0)
    _feedback(cc, [s0], [10_000], 50_000)      # feedback 50ms after send
    assert abs(cc.last_rtt_ms - 50.0) < 1e-6
    assert abs(cc.srtt_ms - 50.0) < 1e-6
    s1 = cc.alloc_seq()
    cc.on_packet_sent(s1, 1200, 100_000)
    _feedback(cc, [s1], [110_000], 100_000 + 90_000)   # 90ms
    assert abs(cc.last_rtt_ms - 90.0) < 1e-6
    assert 50.0 < cc.srtt_ms < 90.0                    # 1/8 EWMA
    assert cc.stats()["rtt_ms"] == round(cc.srtt_ms, 3)
    assert cc.stats()["last_rtt_ms"] == 90.0


def test_packetizer_counters_for_qoe():
    cc = SendSideCongestionController()
    pk = H264Packetizer(twcc_alloc=cc.alloc_seq)
    pk.packetize(b"\x00\x00\x00\x01\x65" + b"x" * 50, 1234)
    st = pk.stats()
    assert st["packets"] == 1 and st["octets"] > 50
    from selkies_tpu.webrtc.rtp import OpusPacketizer
    op = OpusPacketizer(twcc_alloc=cc.alloc_seq)
    op.packetize(b"opus-frame", 960)
    assert op.stats() == {"packets": 1, "octets": 10}


def test_late_received_packet_does_not_poison_trendline():
    """A packet reported missing then received later must not be grouped
    behind newer packets — its stale send time would inject a spurious
    delay-delta and flip the detector to overuse on a healthy link."""
    cc = SendSideCongestionController(start_bps=4_000_000.0)
    now = 0
    seqs = []
    for i in range(40):
        s = cc.alloc_seq()
        cc.on_packet_sent(s, 1200, now)
        seqs.append(s)
        now += 10_000
    # fb1: constant 5ms delay, seq 2 missing
    times = [i * 10_000 + 5_000 if i != 2 else None for i in range(40)]
    _feedback(cc, seqs, times, now)
    assert cc._trend.state == "normal"
    # fb2: seq 2 finally arrives (re-reported received) + fresh packets
    late = [seqs[2]]
    late_times = [now + 1_000]
    for i in range(20):
        s = cc.alloc_seq()
        cc.on_packet_sent(s, 1200, now)
        late.append(s)
        late_times.append(now + 5_000 + i * 10_000)
        now += 10_000
    _feedback(cc, late, late_times, now)
    # healthy link: the late packet must not fabricate queue growth
    assert cc._trend.state == "normal"
    assert cc.last_loss_fraction == 0.0
