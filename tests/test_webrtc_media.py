"""WebRTC media plane: unit tests for each protocol layer plus a full
protocol-level loopback — a fake browser completes ICE + DTLS against
RTCPeer, receives SRTP, depacketizes RFC 6184, and byte-exact-decodes an
IDR with the spec decoder (VERDICT round-2 item 4's 'done' bar)."""

import asyncio
import json
import secrets
import struct

import numpy as np
import pytest

# the webrtc DTLS layer binds OpenSSL at import time; boxes whose
# libssl/libcrypto lack the DTLS-SRTP surface must SKIP these tests,
# not error collection (dtls converts missing symbols to ImportError)
pytest.importorskip("selkies_tpu.webrtc.dtls",
                    reason="usable OpenSSL (DTLS-SRTP surface) required",
                    exc_type=ImportError)

from selkies_tpu.codecs import h264 as H
from selkies_tpu.codecs import h264_ref_decoder as refdec
from selkies_tpu.webrtc.dtls import DtlsEndpoint
from selkies_tpu.webrtc.peer import RTCPeer
from selkies_tpu.webrtc.rtp import (H264Packetizer, RtpPacket,
                                    depacketize_h264, parse_rtcp_pli,
                                    split_annexb)
from selkies_tpu.webrtc.sdp import build_offer, parse_answer
from selkies_tpu.webrtc.srtp import SrtpContext, SrtpError
from selkies_tpu.webrtc.stun import (BINDING_REQUEST, BINDING_RESPONSE,
                                     IceLiteResponder, StunMessage, is_stun,
                                     make_ice_credentials)


# --------------------------------------------------------------- STUN


def test_stun_roundtrip_and_integrity():
    ufrag, pwd = make_ice_credentials()
    req = StunMessage(BINDING_REQUEST)
    req.add(0x0006, f"srv:{ufrag}".encode())
    wire = req.to_bytes(integrity_key=pwd.encode())
    assert is_stun(wire)
    parsed = StunMessage.parse(wire)
    assert parsed.type == BINDING_REQUEST
    assert parsed.txid == req.txid
    assert parsed.check_integrity(pwd.encode())
    assert not parsed.check_integrity(b"wrong-password")


def test_ice_lite_responder_flow():
    ufrag, pwd = make_ice_credentials()
    srv = IceLiteResponder(ufrag, pwd)
    cli = IceLiteResponder(*make_ice_credentials())
    cli.set_remote(ufrag, pwd)
    req = cli.binding_request()
    resp = srv.handle(req, ("192.0.2.7", 4242))
    assert resp is not None
    msg = StunMessage.parse(resp)
    assert msg.type == BINDING_RESPONSE
    assert msg.check_integrity(pwd.encode())
    assert msg.xor_mapped_address() == ("192.0.2.7", 4242)
    assert srv.nominated_addr == ("192.0.2.7", 4242)
    # unauthenticated request -> 401, no nomination change
    bad = StunMessage(BINDING_REQUEST).to_bytes()
    err = srv.handle(bad, ("203.0.113.9", 1))
    assert StunMessage.parse(err).type == 0x0111
    assert srv.nominated_addr == ("192.0.2.7", 4242)


# --------------------------------------------------------------- SRTP


def _dtls_loopback():
    srv = DtlsEndpoint(server=True)
    cli = DtlsEndpoint(server=False)
    cli.handshake()
    for _ in range(10):
        if srv.handshake_complete and cli.handshake_complete:
            break
        d = cli.take_outgoing()
        if d:
            srv.feed(d)
        d = srv.take_outgoing()
        if d:
            cli.feed(d)
    assert srv.handshake_complete and cli.handshake_complete
    return srv, cli


def test_dtls_handshake_and_key_export():
    srv, cli = _dtls_loopback()
    assert srv.export_srtp_keys() == cli.export_srtp_keys()
    ck, sk = srv.export_srtp_keys()
    assert len(ck) == 30 and len(sk) == 30 and ck != sk
    assert srv.verify_peer_fingerprint(cli.peer_fingerprint()
                                       ) or srv.peer_fingerprint()
    srv.close()
    cli.close()


def test_srtp_kdf_rfc3711_vectors():
    """RFC 3711 Appendix B.3 key-derivation test vectors — the one bug
    class a loopback test can never catch (both ends sharing a wrong KDF
    still interoperate with each other, just not with libsrtp)."""
    from selkies_tpu.webrtc.srtp import _kdf
    mk = bytes.fromhex("E1F97A0D3E018BE0D64FA32C06DE4139")
    ms = bytes.fromhex("0EC675AD498AFEEBB6960B3AABE6")
    assert _kdf(mk, ms, 0, 16) == \
        bytes.fromhex("C61E7A93744F39EE10734AFE3FF7A087")
    assert _kdf(mk, ms, 2, 14) == \
        bytes.fromhex("30CBBC08863D8C85D49DB34A9AE1")
    assert _kdf(mk, ms, 1, 20) == \
        bytes.fromhex("CEBE321F6FF7716B6FD4AB49AF256A156D38BAA4")


def test_srtp_rtp_and_rtcp_roundtrip():
    ck, sk = secrets.token_bytes(30), secrets.token_bytes(30)
    sender = SrtpContext(ck, sk, is_client=False)     # protects w/ server
    receiver = SrtpContext(ck, sk, is_client=True)    # expects server
    pkt = RtpPacket(102, 7, 1234, 0xDEADBEEF, True, b"payload" * 20)
    wire = sender.protect_rtp(pkt.to_bytes())
    assert wire != pkt.to_bytes()
    back = receiver.unprotect_rtp(wire)
    assert back == pkt.to_bytes()
    # replay must be rejected
    try:
        receiver.unprotect_rtp(wire)
        raised = False
    except SrtpError:
        raised = True
    assert raised
    # tampered tag must fail
    try:
        receiver.unprotect_rtp(wire[:-1] + bytes((wire[-1] ^ 1,)))
        raised = False
    except SrtpError:
        raised = True
    assert raised
    rtcp = struct.pack("!BBHI", 0x80, 200, 1, 0xDEADBEEF) + b"x" * 20
    assert receiver.unprotect_rtcp(sender.protect_rtcp(rtcp)) == rtcp


# ---------------------------------------------------------------- RTP


def _small_idr():
    rng = np.random.default_rng(2)
    h, w = 32, 48
    y = rng.integers(0, 256, (h, w), dtype=np.uint8)
    u = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)
    v = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)
    enc = H.I16Encoder(w, h, 30)
    annexb = enc.headers() + enc.encode_frame(y, u, v)
    return annexb, enc


def test_h264_packetize_depacketize_with_fua():
    annexb, _ = _small_idr()
    pk = H264Packetizer(mtu=100)          # force FU-A fragmentation
    pkts = pk.packetize(annexb, 90000)
    assert any(p.payload[0] & 0x1F == 28 for p in pkts), "no FU-A made"
    assert pkts[-1].marker and not pkts[0].marker
    rebuilt = depacketize_h264(pkts)
    assert [n[0] & 0x1F for n in split_annexb(rebuilt)] == \
        [n[0] & 0x1F for n in split_annexb(annexb)]
    assert b"".join(split_annexb(rebuilt)) == b"".join(split_annexb(annexb))


def test_rtcp_pli_parse():
    pli = struct.pack("!BBHII", 0x81, 206, 2, 1, 0xCAFEBABE)
    assert parse_rtcp_pli(pli) == [0xCAFEBABE]
    sr = struct.pack("!BBHIIIIII", 0x80, 200, 6, 1, 0, 0, 0, 0, 0)
    assert parse_rtcp_pli(sr) == []


# ------------------------------------------------- full loopback peer


class _Browser(asyncio.DatagramProtocol):
    """The fake browser: collects datagrams, demuxes SRTP vs rest."""

    def __init__(self):
        self.queue = asyncio.Queue()
        self.transport = None

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        self.queue.put_nowait(data)


async def _drain(q, timeout=2.0):
    out = []
    try:
        while True:
            out.append(await asyncio.wait_for(q.get(), timeout))
            timeout = 0.25
    except asyncio.TimeoutError:
        return out


async def test_full_loopback_browser_decodes_idr():
    keyframe_requests = []
    peer = RTCPeer(on_request_keyframe=lambda: keyframe_requests.append(1))
    port = await peer.listen()
    offer = peer.create_offer()
    assert "a=ice-lite" in offer and "a=setup:actpass" in offer

    # the browser side: parse the offer like a real client would
    remote = parse_answer(offer)          # same grammar both ways
    assert remote.ice_pwd == peer.pwd
    cli_ice = IceLiteResponder(*make_ice_credentials())
    cli_ice.set_remote(remote.ice_ufrag, remote.ice_pwd)
    cli_dtls = DtlsEndpoint(server=False)

    # answer SDP back to the server (fingerprint of the shared test cert)
    answer = build_offer("127.0.0.1", 0, cli_ice.ufrag, cli_ice.pwd,
                         remote.fingerprint).replace(
        "a=setup:actpass", "a=setup:active")
    peer.set_remote_answer(answer)

    loop = asyncio.get_running_loop()
    browser = _Browser()
    transport, _ = await loop.create_datagram_endpoint(
        lambda: browser, remote_addr=("127.0.0.1", port))

    # ICE: authenticated binding request -> response
    transport.sendto(cli_ice.binding_request())
    resp = await asyncio.wait_for(browser.queue.get(), 2)
    assert is_stun(resp)
    assert StunMessage.parse(resp).type == BINDING_RESPONSE

    # DTLS handshake (client drives)
    cli_dtls.handshake()
    transport.sendto(cli_dtls.take_outgoing())
    for _ in range(10):
        if cli_dtls.handshake_complete and peer.srtp is not None:
            break
        try:
            d = await asyncio.wait_for(browser.queue.get(), 2)
        except asyncio.TimeoutError:
            d = b""
        if d and 20 <= d[0] <= 63:
            cli_dtls.feed(d)
            out = cli_dtls.take_outgoing()
            if out:
                transport.sendto(out)
    assert cli_dtls.handshake_complete
    await asyncio.wait_for(peer.connected.wait(), 2)

    ck, sk = cli_dtls.export_srtp_keys()
    cli_srtp = SrtpContext(ck, sk, is_client=True)

    # server streams a REAL IDR access unit (golden encoder output)
    annexb, enc = _small_idr()
    sent = peer.send_video_au(annexb)
    assert sent > 0

    datagrams = await _drain(browser.queue)
    rtp_pkts = []
    for d in datagrams:
        if not d or not (128 <= d[0] <= 191):
            continue
        pt = d[1] & 0x7F
        if 64 <= pt <= 95:
            cli_srtp.unprotect_rtcp(d)    # SR must authenticate
            continue
        rtp_pkts.append(RtpPacket.parse(cli_srtp.unprotect_rtp(d)))
    assert rtp_pkts, "no media arrived"
    rebuilt = depacketize_h264(rtp_pkts)
    my, mu, mv = refdec.Decoder().decode(rebuilt)
    assert np.array_equal(my, enc.recon_y)
    assert np.array_equal(mu, enc.recon_u)
    assert np.array_equal(mv, enc.recon_v)

    # browser asks for a keyframe: PLI through SRTCP
    pli = struct.pack("!BBHII", 0x81, 206, 2,
                      0xAABBCCDD, peer.video.ssrc)
    transport.sendto(cli_srtp.protect_rtcp(pli))
    await asyncio.sleep(0.2)
    assert keyframe_requests, "PLI did not reach the keyframe callback"

    transport.close()
    peer.close()


# ----------------------------------------- service end-to-end session


async def test_webrtc_service_builds_real_sessions(client_factory):
    """Browser simulator end-to-end THROUGH the service: signaling WS ->
    offer -> answer -> ICE -> DTLS -> live SRTP video from the synthetic
    TPU capture, decoded with the spec decoder."""
    import aiohttp

    from selkies_tpu.engine.capture import ScreenCapture
    from selkies_tpu.server.core import CentralizedStreamServer
    from selkies_tpu.settings import AppSettings

    s = AppSettings.parse([], {})
    s.set_server("mode", "webrtc")
    s.set_server("initial_width", 64)
    s.set_server("initial_height", 64)
    s.set_server("webrtc_media_ip", "127.0.0.1")
    s.set_server("h264_motion_vrange", 2)   # small jit for test speed
    s.set_server("h264_motion_hrange", 1)
    from selkies_tpu.server.webrtc_service import WebRTCService
    server = CentralizedStreamServer(s)
    svc = WebRTCService(
        s, capture_factory=lambda: ScreenCapture(source_kind="synthetic"))
    server.register_service("webrtc", svc)
    client = await client_factory(server, "webrtc")

    ws = await client.ws_connect("/api/signaling")
    await ws.send_str("HELLO client {}")
    assert (await ws.receive_str()) == "HELLO"
    await ws.send_str("SESSION server")
    ok = await ws.receive_str()
    assert ok.startswith("SESSION_OK")

    offer_msg = json.loads(await asyncio.wait_for(ws.receive_str(), 5))
    offer = offer_msg["sdp"]["sdp"]
    assert offer_msg["sdp"]["type"] == "offer"
    remote = parse_answer(offer)
    # media port from the offer's candidate line
    port = int(remote.candidates[0].split()[5])

    cli_ice = IceLiteResponder(*make_ice_credentials())
    cli_ice.set_remote(remote.ice_ufrag, remote.ice_pwd)
    cli_dtls = DtlsEndpoint(server=False)
    answer = build_offer("127.0.0.1", 0, cli_ice.ufrag, cli_ice.pwd,
                         remote.fingerprint).replace(
        "a=setup:actpass", "a=setup:active")
    await ws.send_str(json.dumps(
        {"sdp": {"type": "answer", "sdp": answer}}))

    loop = asyncio.get_running_loop()
    browser = _Browser()
    transport, _ = await loop.create_datagram_endpoint(
        lambda: browser, remote_addr=("127.0.0.1", port))
    transport.sendto(cli_ice.binding_request())
    resp = await asyncio.wait_for(browser.queue.get(), 3)
    assert is_stun(resp)

    cli_dtls.handshake()
    transport.sendto(cli_dtls.take_outgoing())
    while not cli_dtls.handshake_complete:
        d = await asyncio.wait_for(browser.queue.get(), 3)
        if 20 <= d[0] <= 63:
            cli_dtls.feed(d)
            out = cli_dtls.take_outgoing()
            if out:
                transport.sendto(out)
    ck, sk = cli_dtls.export_srtp_keys()
    cli_srtp = SrtpContext(ck, sk, is_client=True)

    # live capture -> SRTP media; collect one decodable access unit.
    # The first IDR may have flown before SRTP was up (drop-don't-block),
    # so do what a real client does: ask for a keyframe via PLI.
    by_ts = {}
    decoded = None
    deadline = loop.time() + 150            # first jit compile dominates
    last_pli = 0.0
    media_ssrc = 0
    while decoded is None and loop.time() < deadline:
        if loop.time() - last_pli > 2.0:
            last_pli = loop.time()
            pli = struct.pack("!BBHII", 0x81, 206, 2, 0xAABBCCDD,
                              media_ssrc)
            transport.sendto(cli_srtp.protect_rtcp(pli))
        try:
            d = await asyncio.wait_for(browser.queue.get(), 2)
        except asyncio.TimeoutError:
            continue
        if not d or not (128 <= d[0] <= 191):
            continue
        if 64 <= (d[1] & 0x7F) <= 95:
            continue
        try:
            pkt = RtpPacket.parse(cli_srtp.unprotect_rtp(d))
        except SrtpError:
            continue
        media_ssrc = pkt.ssrc
        by_ts.setdefault(pkt.timestamp, []).append(pkt)
        if pkt.marker:
            annexb = depacketize_h264(by_ts.pop(pkt.timestamp))
            kinds = [n[0] & 0x1F for n in split_annexb(annexb)]
            if 7 in kinds and 5 in kinds:       # a full IDR AU
                y, u, v = refdec.Decoder().decode(annexb)
                decoded = y
    assert decoded is not None, "no decodable IDR arrived from the service"
    assert decoded.shape == (64, 64)

    transport.close()
    await ws.close()


# ---------------------------------------------------------------- SCTP


def test_crc32c_vectors():
    from selkies_tpu.webrtc.sctp import crc32c
    # RFC 3720 appendix B.4 vectors
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"\xff" * 32) == 0x62A8AB43
    assert crc32c(bytes(range(32))) == 0x46DD794E


def _sctp_pair(drop_first_data=False):
    from selkies_tpu.webrtc.sctp import SctpAssociation

    wires = {"a": [], "b": []}
    dropped = {"n": 0}

    def to_b(pkt):
        if drop_first_data and pkt[12] == 0 and dropped["n"] == 0:
            dropped["n"] += 1
            return                      # lose the first DATA packet
        wires["b"].append(pkt)

    clock = {"t": 0.0}
    a = SctpAssociation(lambda p: wires["a"].append(p), server=True,
                        now=lambda: clock["t"])
    b = SctpAssociation(to_b, server=False, now=lambda: clock["t"])

    def pump(rounds=8):
        for _ in range(rounds):
            for pkt in wires["b"]:
                a.receive(pkt)
            wires["b"].clear()
            for pkt in wires["a"]:
                b.receive(pkt)
            wires["a"].clear()

    return a, b, pump, clock


def test_sctp_handshake_channels_and_messages():
    a, b, pump, _ = _sctp_pair()
    b.connect()
    pump()
    assert a.state == b.state == "ESTABLISHED"

    opened = []
    got_a, got_b = [], []
    a.on_channel = opened.append
    a.on_message = lambda ch, d, p: got_a.append((ch.label, d))
    b.on_message = lambda ch, d, p: got_b.append((ch.label, d))

    b.open_channel(1, "input")
    pump()
    assert [c.label for c in opened] == ["input"]

    b.send(1, b"kd,65")
    b.send(1, b"ku,65")
    pump()
    assert got_a == [("input", b"kd,65"), ("input", b"ku,65")]

    a.send(1, b"cursor,{}")              # server -> browser direction
    pump()
    assert got_b == [("input", b"cursor,{}")]

    big = bytes(range(256)) * 20         # 5120 B: must fragment
    b.send(1, big)
    pump()
    assert got_a[-1] == ("input", big)


def test_sctp_retransmission_recovers_loss():
    a, b, pump, clock = _sctp_pair(drop_first_data=True)
    b.connect()
    pump()
    got = []
    a.on_message = lambda ch, d, p: got.append(d)
    b.open_channel(1, "input")
    pump()
    b.send(1, b"first")                  # this DATA packet is dropped
    b.send(1, b"second")
    pump()
    assert got == []                     # 'second' parked out of order
    clock["t"] += 2.0                    # T3 expires
    b.poll_timers()
    pump()
    assert got == [b"first", b"second"]


async def test_full_loopback_datachannel_input():
    """Browser sim opens a data channel through the REAL peer (DTLS app
    records -> SCTP) and sends input verbs; the peer surfaces them."""
    from selkies_tpu.webrtc.sctp import SctpAssociation

    verbs = []
    peer = RTCPeer(on_datachannel_message=lambda lbl, t: verbs.append(t))
    port = await peer.listen()
    remote = parse_answer(peer.create_offer())
    assert "webrtc-datachannel" in peer.create_offer()
    cli_ice = IceLiteResponder(*make_ice_credentials())
    cli_ice.set_remote(remote.ice_ufrag, remote.ice_pwd)
    cli_dtls = DtlsEndpoint(server=False)
    peer.set_remote_answer(build_offer(
        "127.0.0.1", 0, cli_ice.ufrag, cli_ice.pwd,
        remote.fingerprint).replace("a=setup:actpass", "a=setup:active"))

    loop = asyncio.get_running_loop()
    browser = _Browser()
    transport, _ = await loop.create_datagram_endpoint(
        lambda: browser, remote_addr=("127.0.0.1", port))
    transport.sendto(cli_ice.binding_request())
    await asyncio.wait_for(browser.queue.get(), 2)
    cli_dtls.handshake()
    transport.sendto(cli_dtls.take_outgoing())

    app_records = []
    async def pump_browser(timeout=0.5):
        try:
            while True:
                d = await asyncio.wait_for(browser.queue.get(), timeout)
                if d and 20 <= d[0] <= 63:
                    app_records.extend(cli_dtls.feed(d))
                    out = cli_dtls.take_outgoing()
                    if out:
                        transport.sendto(out)
        except asyncio.TimeoutError:
            return

    for _ in range(10):
        if cli_dtls.handshake_complete:
            break
        await pump_browser(1.0)
    assert cli_dtls.handshake_complete
    await asyncio.wait_for(peer.connected.wait(), 2)

    def ship(pkt):
        cli_dtls.send_app(pkt)
        out = cli_dtls.take_outgoing()
        if out:
            transport.sendto(out)

    sctp = SctpAssociation(ship, server=False)
    sctp.connect()
    for _ in range(10):
        await pump_browser(0.3)
        while app_records:
            sctp.receive(app_records.pop(0))
        if sctp.state == "ESTABLISHED":
            break
    assert sctp.state == "ESTABLISHED"

    sctp.open_channel(1, "input")
    sctp.send(1, b"kd,65")
    sctp.send(1, b"m,10,20")
    for _ in range(10):
        await pump_browser(0.3)
        while app_records:
            sctp.receive(app_records.pop(0))
        if len(verbs) >= 2:
            break
    assert verbs == ["kd,65", "m,10,20"]
    transport.close()
    peer.close()


def test_rtcp_remb_parse():
    from selkies_tpu.webrtc.rtp import parse_rtcp_remb
    # REMB for 1.2 Mbps: mantissa/exp encoding
    target = 1_200_000
    exp = 0
    mantissa = target
    while mantissa >= (1 << 18):
        mantissa >>= 1
        exp += 1
    pkt = struct.pack("!BBHII", 0x8F, 206, 5, 1, 0) + b"REMB" + \
        struct.pack("!I", (1 << 24) | (exp << 18) | mantissa) + \
        struct.pack("!I", 0xCAFE)
    got = parse_rtcp_remb(pkt)
    assert got is not None and abs(got - target) / target < 0.01
    assert parse_rtcp_remb(struct.pack("!BBHII", 0x81, 206, 2, 1, 2)) is None


# ------------------------------------------------- data-channel control verbs


async def test_datachannel_control_verbs():
    """REQUEST_KEYFRAME / vb / r are service-level controls (the WS
    transport's _h_keyframe/_h_video_bitrate/_h_resize equivalents); input
    verbs forward to the shared input handler."""
    from selkies_tpu.server.webrtc_service import WebRTCService
    from selkies_tpu.settings import AppSettings

    s = AppSettings.parse([], {})
    s.set_server("video_bitrate_kbps", 8000)

    class FakeCapture:
        def __init__(self):
            self.idr_requests = 0
            self.bitrates = []
            self.regions = []

        def is_capturing(self):
            return True

        def request_idr_frame(self):
            self.idr_requests += 1

        def update_video_bitrate(self, kbps):
            self.bitrates.append(kbps)

        def update_capture_region(self, x, y, w, h):
            self.regions.append((x, y, w, h))

    class FakeInput:
        def __init__(self):
            self.msgs = []

        async def on_message(self, text):
            self.msgs.append(text)

    svc = WebRTCService(s, input_handler=FakeInput())
    svc._loop = asyncio.get_running_loop()
    cap = FakeCapture()
    svc._captures = {"primary": cap}

    svc._on_input_verb("input", "REQUEST_KEYFRAME")
    svc._on_input_verb("input", "vb,3000")
    svc._on_input_verb("input", "vb,999999")     # ceiling-capped
    svc._on_input_verb("input", "vb,junk")       # ignored
    svc._on_input_verb("input", "r,800x600")
    svc._on_input_verb("input", "r,nonsense")    # ignored
    svc._on_input_verb("input", "kd,65")
    for _ in range(5):
        await asyncio.sleep(0.05)
    assert cap.idr_requests == 1
    assert cap.bitrates == [3000, 8000]
    assert cap.regions == [(0, 0, 800, 600)]
    assert (s.initial_width, s.initial_height) == (800, 600)
    assert svc.input_handler.msgs == ["kd,65"]


# -------------------------------------------------- mic receive (rtc)
def test_offer_audio_direction_follows_mic():
    from selkies_tpu.webrtc.sdp import build_offer
    base = dict(host="1.2.3.4", port=5, ufrag="u", pwd="p",
                fingerprint="AA:BB")
    sdp = build_offer(**base, with_mic=True)
    audio = sdp.split("m=audio", 1)[1].split("m=application")[0]
    assert "a=sendrecv" in audio
    video = sdp.split("m=video", 1)[1].split("m=audio")[0]
    assert "a=sendonly" in video and "a=sendrecv" not in video
    sdp2 = build_offer(**base)
    audio2 = sdp2.split("m=audio", 1)[1].split("m=application")[0]
    assert "a=sendonly" in audio2


def test_peer_mic_reorder_buffer():
    """Out-of-order mic RTP re-sequences; a real gap is skipped after
    the 8-deep buffer fills instead of damming the stream."""
    from selkies_tpu.webrtc.peer import RTCPeer
    from selkies_tpu.webrtc.rtp import RtpPacket

    got = []
    peer = RTCPeer(with_mic=True,
                   on_audio_packet=lambda pl, seq, ts: got.append(seq))

    def pkt(seq):
        return RtpPacket(111, seq, seq * 480, 0x1234, False,
                         bytes([seq & 0xFF]))

    for seq in (10, 12, 11, 13):         # simple swap: resequenced
        peer._deliver_mic(pkt(seq))
    assert got == [10, 11, 12, 13]
    got.clear()
    peer._deliver_mic(pkt(14))
    # seq 15 lost: 16..24 buffer up, then the stream jumps the gap
    for seq in range(16, 26):
        peer._deliver_mic(pkt(seq))
    assert got[0] == 14 and 16 in got and got == sorted(got)
    # duplicates / stale arrivals are dropped
    n = len(got)
    peer._deliver_mic(pkt(14))
    assert len(got) == n


def test_service_mic_packet_feeds_virtual_mic_path():
    """An Opus browser-mic packet decodes and lands on play_mic_pcm as
    24 kHz mono s16 (half the 48 kHz decode length); each session keeps
    ITS OWN stateful decoder so two peers can't garble each other."""
    from selkies_tpu.audio import opus
    if not opus.available():
        pytest.skip("libopus missing")
    from selkies_tpu.server.webrtc_service import WebRTCService, _Session
    from selkies_tpu.settings import AppSettings

    s = AppSettings.parse([], {})
    svc = WebRTCService(s)

    class FakeAudio:
        def __init__(self):
            self.chunks = []

        def play_mic_pcm(self, pcm):
            self.chunks.append(pcm)

    svc.audio = FakeAudio()
    svc._sessions = {"a": _Session("a", object(), "primary"),
                     "b": _Session("b", object(), "primary")}
    enc = opus.Encoder(48000, 1, 64000)
    t = np.arange(960) / 48000.0
    pcm = (np.sin(2 * np.pi * 440 * t) * 12000).astype(np.int16)
    svc._on_mic_packet("a", enc.encode(pcm))
    svc._on_mic_packet("a", enc.encode(pcm))
    svc._on_mic_packet("b", opus.Encoder(48000, 1, 64000).encode(pcm))
    assert len(svc.audio.chunks) == 3
    # 20 ms at 48k mono decodes to 960 samples -> 480 samples at 24k
    assert len(svc.audio.chunks[1]) == 480 * 2
    # stateful decode is per-session, never shared
    assert svc._sessions["a"].mic_decoder is not None
    assert svc._sessions["b"].mic_decoder is not None
    assert svc._sessions["a"].mic_decoder is not \
        svc._sessions["b"].mic_decoder
    # unknown session: dropped, no decoder allocated
    svc._on_mic_packet("ghost", enc.encode(pcm))
    assert len(svc.audio.chunks) == 3


async def test_per_display_fanout_routing():
    """Two sessions on two displays: chunks route by chunk.display_id
    (reference webrtc_mode.py:1193-1406 per-display media graphs)."""
    from selkies_tpu.server.webrtc_service import WebRTCService, _Session
    from selkies_tpu.settings import AppSettings

    s = AppSettings.parse([], {})
    svc = WebRTCService(s)
    svc._captures = {"primary": object(), "second": object()}

    class FakePeer:
        def __init__(self):
            self.sent = []

        def send_video_au(self, payload):
            self.sent.append(payload)

    p1, p2 = FakePeer(), FakePeer()
    svc._sessions = {
        "a": _Session("a", p1, "primary"),
        "b": _Session("b", p2, "second"),
    }

    class Chunk:
        def __init__(self, did, payload):
            self.display_id = did
            self.payload = payload

    svc._fanout(Chunk("primary", b"P"))
    svc._fanout(Chunk("second", b"S"))
    assert p1.sent == [b"P"] and p2.sent == [b"S"]
    # a chunk from a display nobody tracks still reaches everyone
    # (single-capture factories whose chunks carry e.g. ':0')
    svc._fanout(Chunk(":0", b"X"))
    assert p1.sent[-1] == b"X" and p2.sent[-1] == b"X"


def test_offer_multiopus_surround():
    """>2ch audio advertises Chrome's multiopus with the encoder's
    stream layout in the fmtp (reference webrtc_mode.py:252-254)."""
    from selkies_tpu.webrtc.sdp import build_offer
    sdp = build_offer("1.2.3.4", 5, "u", "p", "AA:BB",
                      audio_params={"channels": 6, "num_streams": 4,
                                    "coupled_streams": 2,
                                    "channel_mapping": [0, 4, 1, 2, 3, 5]})
    audio = sdp.split("m=audio", 1)[1].split("m=application")[0]
    assert "multiopus/48000/6" in audio
    assert "channel_mapping=0,4,1,2,3,5" in audio
    assert "num_streams=4" in audio and "coupled_streams=2" in audio
    # stereo keeps plain opus
    sdp2 = build_offer("1.2.3.4", 5, "u", "p", "AA:BB")
    assert "multiopus" not in sdp2 and "opus/48000/2" in sdp2


def test_offer_mic_only_emits_recvonly_audio_mline():
    """Satellite (ADVICE r5): enable_microphone without enable_audio
    must still produce an audio m-line (recvonly) or the browser has
    nowhere to attach its mic track."""
    from selkies_tpu.webrtc.sdp import build_offer
    o = build_offer("1.2.3.4", 9, "uf", "pw", "FP",
                    with_audio=False, with_mic=True)
    assert "m=audio" in o and "a=recvonly" in o
    assert o.count("a=sendonly") == 1          # the video m-line only
    assert "a=group:BUNDLE 0 1 2" in o         # audio keeps its mid
    # sendrecv when BOTH directions are on; sendonly when mic is off
    o = build_offer("1.2.3.4", 9, "uf", "pw", "FP",
                    with_audio=True, with_mic=True)
    assert "a=sendrecv" in o and "a=recvonly" not in o
    o = build_offer("1.2.3.4", 9, "uf", "pw", "FP",
                    with_audio=True, with_mic=False)
    assert o.count("a=sendonly") == 2 and "a=sendrecv" not in o
    # no audio at all: no m-line, bundle shrinks
    o = build_offer("1.2.3.4", 9, "uf", "pw", "FP",
                    with_audio=False, with_mic=False)
    assert "m=audio" not in o and "a=group:BUNDLE 0 1\r\n" in o
